"""Analytic per-tier cost model for the kernel grid + roofline accounting.

ROADMAP item 3's hardware-free half: before cutting serial DP steps we
need to *predict* where the cycles go — per POA bucket (DEPTH_BUCKETS x
128-lane window class, tier ls/v2/xla) and per aligner bucket — and
check those predictions against what `--trace` actually measured.  The
vocabulary is the one AnySeq/GPU and gpuPairHMM use to justify DP
optimizations: cell updates per second against a machine roofline.

Three layers:

* **CostEstimate** — closed-form FLOPs / HBM bytes / serial DP steps per
  window (POA) or per job (aligner), parameterized by bucket shape.
  Where a lowered kernel is on hand, `lowered_cost()` asks
  ``jax.stages.Lowered.cost_analysis()`` instead and falls back to the
  closed forms (the XLA estimate has no notion of our serial rank loop,
  so serial steps always come from the closed form).
* **MachineProfile** — peak FLOP/s, HBM bandwidth, serial-step latency,
  host engine cell rates, and the prediction-error bound the profile
  *declares* it can hold.  ``cpu-host`` (this repo's CI box class) and
  ``tpu-v4-lite`` (anchored to the dp_cost_probe measurements in
  docs/benchmarks.md) ship built in.
* **Roofline verdict** — predicted wall = max(compute, bandwidth,
  serial-step term); whichever term wins classifies the bucket as
  compute-bound / bandwidth-bound / serial-step-bound.  The measured
  0.188x story is the serial-step term winning by ~40x, which is why
  the serial-step cut landed: the Pallas POA tiers now divide their
  step count by POA_COLSTEP_PACK (column-compressed rank pairing,
  ops/colstep.py) and the packed Hirschberg kernels divide theirs by
  ALIGN_ROW_PACK (ops/encoding.PACK rows per iteration).

Everything here is stdlib-only (the obs package contract): the kernel
grid constants are mirrored from ``racon_tpu.ops`` and pinned equal by
tests/test_costmodel.py, so this module stays importable without jax.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

# -- kernel grid constants (mirrored from racon_tpu.ops; parity-tested) ----

#: poa_driver.DEPTH_BUCKETS — layer-count buckets windows batch into.
DEPTH_BUCKETS = (8, 32, 200)
#: align.BUCKETS — (max length, band) buckets for the xla aligner.
ALIGN_BUCKETS = ((1024, 256), (2048, 512), (4096, 1024), (8192, 2048))
#: poa_pallas_ls.G — windows per lane-lockstep program (amortizes the
#: serial rank loop across G windows).
LS_GROUP = 8
#: poa_driver.AUDIT_WINDOW_LENGTHS — the window lengths the grid is
#: audited (and documented) at.
AUDIT_WINDOW_LENGTHS = (500, 1000)

POA_TIERS = ("ls", "v2", "xla")

#: Graph ranks per backbone position: POA graphs grow past the backbone
#: as divergent layer bases fork nodes.  λ at ~30x measured ~2x
#: (docs/benchmarks.md: ~1000 ranks over a 500-base backbone).
NODE_GROWTH = 2.0

#: ops.colstep.PACK — ranks retired per serial iteration by the
#: column-compressed Pallas loops (v2 pairs adjacent same-column
#: siblings, ls retires unconditional rank pairs).  At NODE_GROWTH=2.0
#: the average column multiplicity is 2, so the greedy pairer runs at
#: its ceiling and the serial-step divisor is the full pack factor.
#: Applies to the v2 and ls tiers only; the XLA twin keeps the
#: one-rank-per-step scan.
POA_COLSTEP_PACK = 2.0

#: ops.encoding.PACK — query bases packed per int32 word by the packed
#: Hirschberg kernels; each serial loop iteration scores PACK adjacent
#: DP rows, dividing the row-scan trip count.
ALIGN_ROW_PACK = 4.0

#: ops.band.BAND_BUCKETS — the verify-and-widen ladder's compiled band
#: rungs (RACON_TPU_BAND); the top rungs coincide with the flat
#: aligner's BANDS, so the ladder's ceiling is the flat kernel.
BAND_BUCKETS = (128, 256, 512, 1024, 2048)
#: config default for RACON_TPU_BAND_SLACK — the half-band margin added
#: to the length delta when planning w0.
BAND_SLACK = 32

#: Vector ops per DP cell (sub/ins/del merge, weight add, move select,
#: cummax contribution) — same math in all three tiers.
POA_FLOPS_PER_CELL = 14.0
#: HBM bytes per admitted layer base (u8 code + i32 weight streamed in).
POA_LAYER_BYTES = 5.0
#: Aligner DP: add/min/select + move byte per cell.
ALIGN_FLOPS_PER_CELL = 10.0
ALIGN_BYTES_PER_CELL = 2.0   # move byte written + amortized re-read


def window_class(bb_len: int) -> int:
    """128-lane geometry class (mirror of poa_driver.window_class)."""
    return max(128, (bb_len + 127) // 128 * 128)


def band_need(n: int, m: int) -> int:
    """Band the aligner actually needs for an (n, m) pair — the 10%%
    auto-band rule (mirror of align_pallas.band_for's `need`)."""
    return abs(m - n) + max(n, m) // 10 + 2


class CostEstimate(NamedTuple):
    """Predicted work for one unit (window / align job / batch)."""

    flops: float          # vector FLOPs (or int-ops; the VPU doesn't care)
    hbm_bytes: float      # bytes that must cross HBM
    serial_steps: float   # latency-chained DP steps (rank loop / row scan)

    def scaled(self, k: float) -> "CostEstimate":
        return CostEstimate(self.flops * k, self.hbm_bytes * k,
                            self.serial_steps * k)

    def plus(self, other: "CostEstimate") -> "CostEstimate":
        return CostEstimate(self.flops + other.flops,
                            self.hbm_bytes + other.hbm_bytes,
                            self.serial_steps + other.serial_steps)


ZERO = CostEstimate(0.0, 0.0, 0.0)


@dataclass(frozen=True)
class MachineProfile:
    """What the machine can do — the denominator under every estimate.

    ``error_bound_ratio`` is the bound the profile *declares*: `obs
    validate` fails (exit 3) when max(pred/meas, meas/pred) on a modeled
    phase exceeds it.  The CPU profile's bound is deliberately loose
    (XLA-on-CPU throughput varies ~4x across host classes); the TPU
    profile is the calibration target and declares a tight one.
    """

    name: str
    description: str
    clock_hz: float              # core clock (cycles tables only)
    peak_flops: float            # sustained vector FLOP/s for one program
    hbm_bytes_per_s: float       # sustained HBM bandwidth
    serial_step_s: float         # latency per serial DP step
    host_poa_cells_per_s: float  # host SIMD POA engine
    host_align_cells_per_s: float  # host Myers aligner
    error_bound_ratio: float     # declared validate bound (>= 1)


PROFILES: Dict[str, MachineProfile] = {p.name: p for p in (
    MachineProfile(
        name="cpu-host",
        description="1-core x86 host running the XLA twin kernels "
                    "(the CI traced-bench configuration); host engines "
                    "are the native SIMD paths",
        clock_hz=3.0e9,
        # XLA CPU executes the scan-based DP kernels essentially
        # scalar + dispatch-bound; calibrated against traced runs of
        # the v2 XLA twin on this repo's dev box.
        peak_flops=2.0e9,
        hbm_bytes_per_s=1.0e10,
        # One serial DP step on this profile is one XLA while-loop
        # iteration over the whole window batch — dispatch-dominated on
        # CPU, measured at ~2.6 ms/step on the 1-core dev box (traced
        # 0.002 Mbp forced-device bench: 28.7k steps -> 73.6 s poa
        # phase). This is what makes the forced-device dry run hundreds
        # of times slower than the host SIMD path, and it is why the
        # error bound below is wide: runner-class machines differ in
        # dispatch overhead far more than in FLOP rate.
        serial_step_s=2.5e-3,
        host_poa_cells_per_s=1.2e9,    # 1.57 Gcells/s AVX-512 measured,
                                       # derated for short-window overhead
        host_align_cells_per_s=6.0e8,  # banded block-Myers, measured class
        error_bound_ratio=8.0,
    ),
    MachineProfile(
        name="tpu-v4-lite",
        description="single TPU chip of the v4-lite/v5e class; "
                    "serial_step_s anchored to the dp_cost_probe "
                    "measurement (~2.7 us/rank at production geometry, "
                    "docs/benchmarks.md)",
        clock_hz=9.4e8,
        peak_flops=2.0e12,           # VPU f32/i32 class, one core
        hbm_bytes_per_s=4.0e11,
        serial_step_s=2.7e-6,        # measured: latency-bound rank loop
        host_poa_cells_per_s=1.5e9,  # host VM SIMD engines
        host_align_cells_per_s=1.0e9,
        error_bound_ratio=2.5,
    ),
)}


def profile(name: str) -> MachineProfile:
    """Look up a machine profile; raises KeyError with the valid names."""
    try:
        return PROFILES[name]
    except KeyError:
        raise KeyError(f"unknown machine profile {name!r}; "
                       f"available: {sorted(PROFILES)}") from None


def resolve_profile(name: str, platform: Optional[str] = None
                    ) -> MachineProfile:
    """'auto' picks by backend platform (tpu -> tpu-v4-lite, else
    cpu-host); anything else must be a registered profile name."""
    if name in ("", "auto", None):
        return PROFILES["tpu-v4-lite" if platform == "tpu" else "cpu-host"]
    return profile(name)


# -- closed-form estimates -------------------------------------------------

def poa_window_cost(depth: int, wl_class: int, tier: str) -> CostEstimate:
    """Predicted work for ONE window of `depth` admitted layers in a
    `wl_class` geometry class served by `tier`.

    The DP: each layer aligns against the window graph — ranks x layer
    length cells, with the rank loop latency-chained (each rank's row
    depends on its predecessors' rows).  Graph update + consensus ride
    inside the same rank-step constants.
    """
    ranks = NODE_GROWTH * wl_class
    cells = depth * ranks * wl_class
    flops = cells * POA_FLOPS_PER_CELL
    # HBM traffic: layer bases/weights streamed in, consensus out; the H
    # matrix lives in VMEM (v2 ring / ls ring), so it does not cross HBM.
    hbm = depth * wl_class * POA_LAYER_BYTES + 2 * wl_class * 5
    steps = depth * ranks
    if tier in ("v2", "ls"):
        # Column-compressed stepping (ops/colstep.py): the Pallas loops
        # retire rank pairs per serial iteration.
        steps /= POA_COLSTEP_PACK
    if tier == "ls":
        # G windows share one program's rank loop: the serial term
        # amortizes per window, the cell work does not.
        steps /= LS_GROUP
    return CostEstimate(flops, hbm, steps)


def align_job_cost(cap: int, band: int, tier: str = "xla") -> CostEstimate:
    """Predicted work for ONE aligner job in a (cap, band) bucket.

    xla: full cap x band moves-matrix DP (scan over cap rows, then a
    2*cap traceback while-loop).  hirschberg: fwd+bwd distance passes
    over the recursion tree ~ 2x the base DP, no stored matrix.
    """
    cells = float(cap) * band
    if tier == "hirschberg":
        cells *= 2.0
        # Row scans across recursion levels; the packed kernels score
        # ALIGN_ROW_PACK adjacent rows per serial iteration.
        steps = 4.0 * cap / ALIGN_ROW_PACK
        hbm = cap * 2.0            # sequences only; no moves matrix
    else:
        steps = 3.0 * cap          # row scan + traceback chain
        hbm = cells * ALIGN_BYTES_PER_CELL
    return CostEstimate(cells * ALIGN_FLOPS_PER_CELL, hbm, steps)


def banded_align_job_cost(cap: int, k: int) -> CostEstimate:
    """Predicted work for ONE Hirschberg job served on band rung `k`
    (RACON_TPU_BAND): the fwd+bwd distance passes iterate ``2*cap*k``
    cells instead of ``2*cap*band_for(cap)`` — the in-loop cell bill
    divides by the band ratio.  The serial row scan is UNCHANGED: the
    band narrows each row's live lanes, it does not shorten the
    latency chain (same rows, fewer columns per row)."""
    cells = 2.0 * float(cap) * k
    steps = 4.0 * cap / ALIGN_ROW_PACK
    return CostEstimate(cells * ALIGN_FLOPS_PER_CELL, cap * 2.0, steps)


def banded_poa_window_cost(depth: int, wl_class: int, w: int,
                           tier: str) -> CostEstimate:
    """Predicted work for ONE banded POA window at runtime half-band
    `w` (wband): each rank's row keeps ``2*w + 1`` live columns around
    its backbone offset instead of the full class width, so the cell
    (and FLOP) bill scales by ``(2w+1)/wl_class``.  Rank-loop length —
    the serial term — is unchanged; HBM traffic still streams every
    admitted layer base once."""
    ranks = NODE_GROWTH * wl_class
    width = min(float(wl_class), 2.0 * w + 1.0)
    cells = depth * ranks * width
    flops = cells * POA_FLOPS_PER_CELL
    hbm = depth * wl_class * POA_LAYER_BYTES + 2 * wl_class * 5
    steps = depth * ranks
    if tier in ("v2", "ls"):
        steps /= POA_COLSTEP_PACK
    if tier == "ls":
        steps /= LS_GROUP
    return CostEstimate(flops, hbm, steps)


def banded_cell_ratio(kind: str, *, cap: int = 0, band: int = 0, k: int = 0,
                      wl_class: int = 0, w: int = 0) -> float:
    """Predicted flat/banded in-loop cell ratio for one unit — the
    quantity dp_cost_probe's ``--gate`` measures on silicon and
    docs/benchmarks.md tabulates.  kind 'align': flat band `band` vs
    rung `k`; kind 'poa': class width `wl_class` vs half-band `w`."""
    if kind == "align":
        return float(band) / max(1, k)
    return float(wl_class) / max(1.0, min(float(wl_class), 2.0 * w + 1.0))


def roofline(est: CostEstimate, prof: MachineProfile):
    """(seconds, verdict): predicted wall is the max of the three
    roofline terms; the winning term names the bound."""
    terms = {
        "compute-bound": est.flops / prof.peak_flops,
        "bandwidth-bound": est.hbm_bytes / prof.hbm_bytes_per_s,
        "serial-step-bound": est.serial_steps * prof.serial_step_s,
    }
    verdict = max(terms, key=lambda k: terms[k])
    return terms[verdict], verdict


def host_poa_seconds(cells: float, prof: MachineProfile) -> float:
    return cells / prof.host_poa_cells_per_s


def host_align_seconds(cells: float, prof: MachineProfile) -> float:
    return cells / prof.host_align_cells_per_s


# -- optional jax.stages.Lowered.cost_analysis ----------------------------

def lowered_cost(lowered) -> Optional[CostEstimate]:
    """FLOPs/bytes from a ``jax.stages.Lowered`` (or anything exposing
    ``cost_analysis()``), serial steps left 0 — XLA's estimate has no
    notion of the rank loop's latency chain, so callers must merge this
    with a closed form for the serial term.  Returns None when the
    backend provides no cost analysis (CPU often returns {} or raises).
    """
    try:
        ca = lowered.cost_analysis()
    except Exception:  # noqa: BLE001 — optional-path probe
        return None
    if isinstance(ca, (list, tuple)):   # older jax: one dict per device
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    flops = float(ca.get("flops", 0.0))
    byt = float(ca.get("bytes accessed", 0.0))
    if flops <= 0.0 and byt <= 0.0:
        return None
    return CostEstimate(flops, byt, 0.0)


def lowered_poa_cost(depth: int, wl_class: int, tier: str
                     ) -> Optional[CostEstimate]:
    """Best-effort: lower the real POA kernel for this bucket and read
    XLA's own FLOPs/bytes, keeping the closed-form serial term.  Imports
    jax and traces the kernel — minutes-cheap on CPU for the xla tier,
    potentially slow for pallas tiers; callers gate it (``obs model
    --lowered``).  Any failure returns None (closed form stands)."""
    try:
        import jax
        import numpy as np

        from ..ops import poa as poa_mod
        from ..ops import poa_driver

        cfg = poa_driver.make_config(wl_class, depth, 5, -4, -8)
        if tier != "xla":
            return None   # pallas lowerings carry no useful cost_analysis
        kernel = poa_mod.build_poa_kernel(cfg)
        B = 1
        args = (
            np.zeros((B, cfg.max_backbone), np.uint8),
            np.zeros((B, cfg.max_backbone), np.int32),
            np.ones(B, np.int32),
            np.zeros(B, np.int32),
            np.zeros((B, cfg.depth, cfg.max_len), np.uint8),
            np.zeros((B, cfg.depth, cfg.max_len), np.int32),
            np.zeros((B, cfg.depth), np.int32),
            np.zeros((B, cfg.depth), np.int32),
            np.zeros((B, cfg.depth), np.int32),
        )
        est = lowered_cost(jax.jit(kernel).lower(*args))
        del jax
        if est is None:
            return None
        closed = poa_window_cost(depth, wl_class, tier)
        return CostEstimate(est.flops, est.hbm_bytes or closed.hbm_bytes,
                            closed.serial_steps)
    except Exception:  # noqa: BLE001 — optional-path probe
        return None


# -- the predicted grid (obs model) ----------------------------------------

def model_rows(prof: MachineProfile,
               window_lengths=AUDIT_WINDOW_LENGTHS,
               tiers=POA_TIERS, depth: Optional[int] = None,
               lowered: bool = False) -> List[dict]:
    """One row per (tier, depth bucket, window class) plus one per
    aligner bucket: predicted FLOPs / HBM bytes / serial steps /
    wall+cycles per unit, and the roofline verdict."""
    rows = []
    classes = sorted({window_class(w) for w in window_lengths})
    for tier in tiers:
        for d in DEPTH_BUCKETS if depth is None else (depth,):
            for c in classes:
                est = None
                if lowered:
                    est = lowered_poa_cost(d, c, tier)
                if est is None:
                    est = poa_window_cost(d, c, tier)
                s, verdict = roofline(est, prof)
                rows.append({
                    "kind": "poa", "tier": tier, "depth": d, "class": c,
                    "flops": est.flops, "hbm_bytes": est.hbm_bytes,
                    "serial_steps": est.serial_steps,
                    "predicted_s": s,
                    "predicted_cycles": s * prof.clock_hz,
                    "verdict": verdict,
                })
    for cap, band in ALIGN_BUCKETS:
        est = align_job_cost(cap, band, "xla")
        s, verdict = roofline(est, prof)
        rows.append({
            "kind": "align", "tier": "xla", "cap": cap, "band": band,
            "flops": est.flops, "hbm_bytes": est.hbm_bytes,
            "serial_steps": est.serial_steps,
            "predicted_s": s, "predicted_cycles": s * prof.clock_hz,
            "verdict": verdict,
        })
    return rows


# -- validation against a measured trace ----------------------------------

_POA_CELLS = re.compile(r"^poa\.cells\.d(\d+)\.c(\d+)$")
_POA_WINDOWS = re.compile(r"^poa\.windows\.d(\d+)\.c(\d+)$")
_ALIGN_CELLS = re.compile(r"^align\.cells\.c(\d+)$")
_SHARD_ROWS = re.compile(r"^shard\.rows\.d(\d+)$")


def infer_n_devices(counters: Dict[str, int]) -> int:
    """Device count from the per-device shard-row counters the executor
    emits on every sharded dispatch (`shard.rows.d<i>`); 1 when the run
    never sharded."""
    n = 0
    for k in counters:
        m = _SHARD_ROWS.match(k)
        if m:
            n = max(n, int(m.group(1)) + 1)
    return max(1, n)


def _over_devices(est: CostEstimate, n: int) -> CostEstimate:
    """Spread a device-side estimate over n mesh shards: FLOPs and HBM
    traffic divide (data-parallel rows), the latency-chained serial
    steps do NOT — every shard runs the same lockstep DP loop on its
    slice, concurrently."""
    if n <= 1:
        return est
    return CostEstimate(est.flops / n, est.hbm_bytes / n,
                        est.serial_steps)

#: Trace phase span name -> run-report phase name (bench.py's
#: `phase_wall` keys use the report names).
PHASE_ALIASES = {"align": "alignment", "poa": "consensus"}


def _err_pct(pred: float, meas: float) -> Optional[float]:
    if meas <= 0.0:
        return None
    return 100.0 * (pred - meas) / meas


def _ratio(pred: float, meas: float) -> Optional[float]:
    if pred <= 0.0 or meas <= 0.0:
        return None
    return max(pred / meas, meas / pred)


def _dominant_tier(counters: Dict[str, int], phase: str,
                   candidates) -> Optional[str]:
    best, best_n = None, 0
    for t in candidates:
        n = counters.get(f"served.{phase}.{t}", 0)
        if n > best_n:
            best, best_n = t, n
    return best


def predict_from_counters(counters: Dict[str, int],
                          prof: MachineProfile,
                          n_devices: Optional[int] = None) -> dict:
    """Turn the measured-cell counters (the drivers count them per
    bucket, see docs/observability.md) into predicted per-phase walls
    plus a per-bucket table.

    POA: `poa.cells.d<D>.c<C>` = sum over the bucket's windows of
    (admitted depth x class C) — the serial-step count at graph growth 1.
    Aligner: `align.cells.c<CAP>` = padded cap x band DP cells per xla
    bucket, `align.cells.hirschberg` likewise, `align.cells.total` the
    need-band cells over ALL phase-1 jobs (host share included).

    `n_devices` divides the device-side FLOP/byte bill (data-parallel
    mesh sharding; serial steps are NOT divided — shards run their DP
    loops concurrently).  None = infer from the `shard.rows.d<i>`
    counters, EXCEPT on the cpu-host profile, where forced-host virtual
    devices share the same cores and sharding adds no real throughput
    (the CI `obs validate` bound must not assume an 8x that can't
    exist); an explicit count always wins.
    """
    if n_devices is None:
        n_devices = (1 if prof.name == "cpu-host"
                     else infer_n_devices(counters))
    n_devices = max(1, int(n_devices))
    # ---- consensus / POA
    tier = _dominant_tier(counters, "consensus", POA_TIERS) or "v2"
    total_served = sum(v for k, v in counters.items()
                       if k.startswith("served.consensus."))
    host_served = counters.get("served.consensus.host", 0)
    host_frac = host_served / total_served if total_served else 0.0
    buckets = []
    poa_est = ZERO
    poa_host_cells = 0.0
    for name, raw in sorted(counters.items()):
        m = _POA_CELLS.match(name)
        if not m:
            continue
        d, c = int(m.group(1)), int(m.group(2))
        steps1 = float(raw)                      # sum(depth_i) * C
        ranks_steps = steps1 * NODE_GROWTH       # rank-loop steps
        cells = ranks_steps * c                  # DP cells
        step_div = {"ls": LS_GROUP * POA_COLSTEP_PACK,
                    "v2": POA_COLSTEP_PACK}.get(tier, 1.0)
        est = CostEstimate(cells * POA_FLOPS_PER_CELL,
                           steps1 * POA_LAYER_BYTES,
                           ranks_steps / step_div)
        dev_share = 1.0 - host_frac
        dev_est = _over_devices(est.scaled(dev_share), n_devices)
        sec, verdict = roofline(dev_est, prof)
        sec += host_poa_seconds(cells * host_frac, prof)
        windows = counters.get(f"poa.windows.d{d}.c{c}")
        buckets.append({"kind": "poa", "tier": tier, "depth": d,
                        "class": c, "windows": windows,
                        "cells": cells, "serial_steps": est.serial_steps,
                        "predicted_s": sec, "verdict": verdict})
        poa_est = poa_est.plus(dev_est)
        poa_host_cells += cells * host_frac
    poa_s, poa_verdict = roofline(poa_est, prof)
    poa_s += host_poa_seconds(poa_host_cells, prof)

    # ---- alignment
    a_est = ZERO
    dev_cells = 0.0
    for name, raw in sorted(counters.items()):
        m = _ALIGN_CELLS.match(name)
        if m:
            cap = int(m.group(1))
            band = dict(ALIGN_BUCKETS).get(cap, cap // 4)
            jobs = max(1, raw // (cap * band))
            est = _over_devices(
                align_job_cost(cap, band, "xla").scaled(jobs), n_devices)
            a_est = a_est.plus(est)
            dev_cells += float(raw)
            sec, verdict = roofline(est, prof)
            buckets.append({"kind": "align", "tier": "xla", "cap": cap,
                            "band": band, "cells": float(raw),
                            "predicted_s": sec, "verdict": verdict})
    hs_cells = counters.get("align.cells.hirschberg", 0)
    if hs_cells:
        est = _over_devices(
            CostEstimate(hs_cells * ALIGN_FLOPS_PER_CELL,
                         hs_cells * 0.1,
                         hs_cells * (4.0 / ALIGN_ROW_PACK) / 256.0),
            n_devices)
        a_est = a_est.plus(est)
        dev_cells += float(hs_cells)
        sec, verdict = roofline(est, prof)
        buckets.append({"kind": "align", "tier": "hirschberg",
                        "cells": float(hs_cells), "predicted_s": sec,
                        "verdict": verdict})
    # banded-DP info rows (RACON_TPU_BAND): the actually-iterated cells
    # of banded jobs/windows.  Informational only — the flat-equivalent
    # bill is already inside the hirschberg / poa bucket estimates
    # above, so these are NOT added to the phase totals (no double
    # count); the flat-vs-banded cell ratio is the measured saving.
    for phase, cname, fpc in (
            ("align", "align.cells.banded", ALIGN_FLOPS_PER_CELL),
            ("poa", "poa.cells.banded", POA_FLOPS_PER_CELL)):
        bnd = counters.get(cname, 0)
        if bnd:
            best = _over_devices(
                CostEstimate(bnd * fpc, bnd * 0.1, 0.0), n_devices)
            sec, verdict = roofline(best, prof)
            buckets.append({"kind": "banded", "phase": phase,
                            "tier": "banded", "cells": float(bnd),
                            "predicted_s": sec, "verdict": verdict})
    align_s, align_verdict = roofline(a_est, prof)
    # the host aligner serves whatever the device buckets did not cover
    total_cells = counters.get("align.cells.total", 0)
    host_cells = max(0.0, float(total_cells) - dev_cells)
    align_s += host_align_seconds(host_cells, prof)
    if host_cells and host_cells >= dev_cells:
        align_verdict = "host-served"

    return {
        "buckets": buckets,
        "n_devices": n_devices,
        "phases": {
            "poa": {"predicted_s": poa_s, "verdict": poa_verdict,
                    "tier": tier,
                    "serial_steps": poa_est.serial_steps},
            "align": {"predicted_s": align_s, "verdict": align_verdict,
                      "serial_steps": a_est.serial_steps},
        },
    }


# -- span-interval math (phase pipelining makes spans overlap) -------------

def span_intervals(doc: dict, name: str) -> List[tuple]:
    """Sorted [(start_us, end_us)] of every complete event named `name`
    (exact match) in the trace."""
    out = []
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict) and ev.get("ph") == "X" \
                and ev.get("name") == name:
            ts = float(ev.get("ts", 0))
            out.append((ts, ts + float(ev.get("dur", 0))))
    return sorted(out)


def union_intervals(intervals) -> List[tuple]:
    """Merge possibly-overlapping intervals into disjoint ones."""
    merged: List[list] = []
    for s, e in sorted(intervals):
        if merged and s <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([s, e])
    return [tuple(iv) for iv in merged]


def overlap_us(doc: dict, name_a: str, name_b: str) -> float:
    """Total wall (µs) during which a span named `name_a` and one named
    `name_b` were simultaneously open — the phase-pipelining evidence
    (`align.cohort` vs `poa.bucket`: nonzero iff alignment cohorts were
    in flight while POA buckets dispatched)."""
    a = union_intervals(span_intervals(doc, name_a))
    b = union_intervals(span_intervals(doc, name_b))
    total = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            total += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def phase_overlaps_us(doc: dict) -> Dict[str, float]:
    """Nonzero pairwise overlaps between ``phase.*`` span families,
    keyed ``"a+b"``.  Sequential runs return {} (disjoint phase walls);
    pipelined runs show ``align+poa`` > 0."""
    names = sorted({ev["name"] for ev in doc.get("traceEvents", [])
                    if isinstance(ev, dict) and ev.get("ph") == "X"
                    and isinstance(ev.get("name"), str)
                    and ev["name"].startswith("phase.")})
    out: Dict[str, float] = {}
    for i, na in enumerate(names):
        for nb in names[i + 1:]:
            ov = overlap_us(doc, na, nb)
            if ov > 0:
                out[f"{na[len('phase.'):]}+{nb[len('phase.'):]}"] = ov
    return out


def _bucket_walls_us(doc: dict) -> Dict[tuple, float]:
    """Measured submit-side wall per (kind, key) from the bucket/cohort
    spans.  Pipelined drains can land inside a neighboring bucket's span
    (documented in docs/observability.md), so these are first-order."""
    walls: Dict[tuple, float] = {}
    for ev in doc.get("traceEvents", []):
        if not (isinstance(ev, dict) and ev.get("ph") == "X"):
            continue
        args = ev.get("args") or {}
        if ev.get("name") == "poa.bucket":
            key = ("poa", int(args.get("depth", -1)),
                   int(args.get("wl_class", -1)))
        elif ev.get("name") == "align.cohort":
            key = ("align", args.get("tier", "?"),
                   int(args.get("cap", 0) or 0))
        else:
            continue
        walls[key] = walls.get(key, 0.0) + float(ev.get("dur", 0))
    return walls


def validate_trace(doc: dict, prof: MachineProfile) -> dict:
    """Join predictions against a measured trace.

    Returns {profile, phases: {name: {predicted_s, measured_s,
    error_pct, ratio, within_bound}}, buckets: [...], dropped_events,
    ok}.  Only the modeled phases (align, poa) gate `ok`; a phase with
    no measured wall or no counted cells is reported but not gated.
    """
    metrics = (doc.get("racon_tpu") or {}).get("metrics") or {}
    counters = metrics.get("counters") or {}
    pred = predict_from_counters(counters, prof)

    measured: Dict[str, float] = {}
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict) and ev.get("ph") == "X" \
                and isinstance(ev.get("name"), str) \
                and ev["name"].startswith("phase."):
            p = ev["name"][len("phase."):]
            measured[p] = measured.get(p, 0.0) + ev.get("dur", 0) / 1e6

    phases = {}
    ok = True
    for name, row in pred["phases"].items():
        meas = measured.get(name)
        p_s = row["predicted_s"]
        entry = dict(row, measured_s=meas)
        if meas is not None and p_s > 0.0:
            entry["error_pct"] = _err_pct(p_s, meas)
            r = _ratio(p_s, meas)
            entry["ratio"] = r
            within = r is not None and r <= prof.error_bound_ratio
            entry["within_bound"] = within
            ok = ok and within
        else:
            entry["within_bound"] = None   # nothing to gate on
        phases[name] = entry

    # join per-bucket predictions against the bucket/cohort span walls
    bwalls = _bucket_walls_us(doc)
    for b in pred["buckets"]:
        if b["kind"] == "poa":
            key = ("poa", b["depth"], b["class"])
        else:
            key = ("align", b["tier"], b.get("cap", 0))
        us = bwalls.get(key)
        if us is not None:
            b["measured_s"] = us / 1e6
            b["error_pct"] = _err_pct(b["predicted_s"], us / 1e6)

    dropped = (doc.get("otherData") or {}).get("dropped_events", 0)
    # Pipelined runs overlap phase.align / phase.poa in wall time; the
    # per-phase measured walls above are summed span durations (work
    # time), so the prediction join stays valid — the overlap is surfaced
    # so a reader knows the phases did not execute back to back.
    overlaps = {k: round(v / 1e6, 6)
                for k, v in phase_overlaps_us(doc).items()}
    return {
        "profile": prof.name,
        "error_bound_ratio": prof.error_bound_ratio,
        "phases": phases,
        "buckets": pred["buckets"],
        **({"phase_overlap_s": overlaps} if overlaps else {}),
        "dropped_events": dropped,
        "ok": ok,
    }


# -- bench.py integration --------------------------------------------------

def bench_cost_model(snapshot: Optional[dict], phase_wall: Dict[str, float],
                     profile_name: str = "auto",
                     platform: Optional[str] = None,
                     n_devices: Optional[int] = None) -> Optional[dict]:
    """The `cost_model` stamp for a bench JSON entry: predicted vs
    measured per modeled phase, error %%, and the profile used.  Returns
    None when the run collected no metrics (cost model disarmed).
    `n_devices` threads through to predict_from_counters (None = infer
    from shard counters on device profiles)."""
    if not snapshot or not isinstance(snapshot.get("counters"), dict):
        return None
    prof = resolve_profile(profile_name, platform)
    pred = predict_from_counters(snapshot["counters"], prof,
                                 n_devices=n_devices)
    out = {"profile": prof.name, "n_devices": pred["n_devices"],
           "phases": {}}
    ok = True
    for span_name, row in pred["phases"].items():
        report_name = PHASE_ALIASES.get(span_name, span_name)
        meas = phase_wall.get(report_name)
        p_s = row["predicted_s"]
        entry = {"predicted_s": round(p_s, 4),
                 "measured_s": meas,
                 "serial_steps": round(row.get("serial_steps", 0.0), 1),
                 "verdict": row["verdict"]}
        if meas and p_s > 0.0:
            entry["error_pct"] = round(_err_pct(p_s, meas), 1)
            r = _ratio(p_s, meas)
            entry["within_bound"] = (r is not None
                                     and r <= prof.error_bound_ratio)
            ok = ok and entry["within_bound"]
        out["phases"][report_name] = entry
    out["ok"] = ok
    return out


# -- rendering -------------------------------------------------------------

def _fmt_si(v: Optional[float]) -> str:
    if v is None:
        return "-"
    if v == 0:
        return "0"
    mag = int(math.floor(math.log10(abs(v)) / 3)) if v else 0
    mag = max(0, min(mag, 4))
    return f"{v / 1000 ** mag:.3g}{('', 'k', 'M', 'G', 'T')[mag]}"


def render_model(rows: List[dict], prof: MachineProfile) -> str:
    lines = [f"machine profile: {prof.name} "
             f"(clock {prof.clock_hz / 1e9:.2f} GHz, "
             f"peak {_fmt_si(prof.peak_flops)}FLOP/s, "
             f"HBM {_fmt_si(prof.hbm_bytes_per_s)}B/s, "
             f"serial step {prof.serial_step_s * 1e6:.2f} us)",
             f"{'kernel':<22s} {'flops':>8s} {'bytes':>8s} "
             f"{'steps':>8s} {'wall':>10s} {'cycles':>9s}  verdict"]
    for r in rows:
        if r["kind"] == "poa":
            name = f"poa.{r['tier']} d{r['depth']} c{r['class']}"
        else:
            name = f"align.{r['tier']} c{r['cap']} b{r['band']}"
        lines.append(
            f"{name:<22s} {_fmt_si(r['flops']):>8s} "
            f"{_fmt_si(r['hbm_bytes']):>8s} "
            f"{_fmt_si(r['serial_steps']):>8s} "
            f"{r['predicted_s'] * 1e3:>8.3f}ms "
            f"{_fmt_si(r['predicted_cycles']):>9s}  {r['verdict']}")
    return "\n".join(lines)


def render_validation(v: dict) -> str:
    lines = [f"cost-model validation (profile {v['profile']}, "
             f"declared bound {v['error_bound_ratio']:.1f}x)"]
    if v["dropped_events"]:
        lines.append(f"WARNING: trace dropped {v['dropped_events']} "
                     f"span(s) past the bounded buffer — measured walls "
                     f"below may be incomplete")
    lines.append("-- phases " + "-" * 48)
    for name, row in sorted(v["phases"].items()):
        meas = row.get("measured_s")
        err = row.get("error_pct")
        gate = row.get("within_bound")
        mark = ("ok" if gate else "PAST BOUND") if gate is not None \
            else "not gated"
        lines.append(
            f"  phase.{name:<10s} predicted {row['predicted_s']:>9.3f}s  "
            f"measured {'-' if meas is None else f'{meas:9.3f}s'}  "
            f"err {'-' if err is None else f'{err:+7.1f}%'}  "
            f"[{row['verdict']}] {mark}")
    if v.get("phase_overlap_s"):
        lines.append("-- phase overlap (pipelined run) " + "-" * 25)
        for k, s in sorted(v["phase_overlap_s"].items()):
            lines.append(f"  {k:<18s} {s:9.3f}s concurrent")
    if v["buckets"]:
        lines.append("-- buckets " + "-" * 47)
        for b in v["buckets"]:
            if b["kind"] == "poa":
                name = f"poa d{b['depth']} c{b['class']}"
                extra = f" x{b['windows']}" if b.get("windows") else ""
            elif b["kind"] == "banded":
                name = f"banded {b['phase']}"
                extra = ""
            else:
                name = f"align {b['tier']}" + (
                    f" c{b['cap']}" if b.get("cap") else "")
                extra = ""
            meas = b.get("measured_s")
            err = b.get("error_pct")
            lines.append(
                f"  {name:<18s}{extra:<6s} cells {_fmt_si(b['cells']):>7s} "
                f"pred {b['predicted_s'] * 1e3:>9.2f}ms "
                f"meas {'-' if meas is None else f'{meas * 1e3:9.2f}ms'} "
                f"err {'-' if err is None else f'{err:+6.0f}%'} "
                f"[{b['verdict']}]")
    verdict = "OK" if v["ok"] else "PREDICTION ERROR PAST DECLARED BOUND"
    lines.append(f"verdict: {verdict}")
    return "\n".join(lines)
