"""Critical-path attribution over a merged fleet trace.

``obs merge`` folds the plane/coordinator trace and the per-chunk
worker traces onto one monotonic timeline; this module walks the
``distrib.dispatch`` -> ``distrib.chunk`` span parenting that
``obs fleet`` validates and answers the question the aggregate p99
cannot: *where did a slow job's wall time go?*

Per chunk, the decomposition is interval accounting inside the chunk
span (queue wait from the dispatch event to the span start, a
``setup`` prefix before the first ``phase.*`` span, the phase spans
themselves, a ``teardown`` suffix, and an explicit ``gap`` remainder —
never hidden).  ``journal.replay`` / ``kernel.build`` spans overlap
the phases they run inside, so they are reported as informational
sub-attribution, not added to the sum.

Per job, the **critical path** ends at the job's last-finishing chunk:
control-plane lead-in (submit -> that chunk's dispatch, from the
scheduler's ``serve.job.submit`` events when present), the chunk's own
decomposition, and the gather tail (chunk end -> ``serve.job.done``).
Stage contributions therefore sum to the job wall by construction,
with the residue reported as ``unattributed`` — the exit-3 gate.

The compute stages are cross-checked against the analytic cost model
(``costmodel.predict_from_counters`` over the counters ``obs merge``
aggregates from the input traces); the cross-check is informational
here — ``obs validate`` owns that gate.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional

from . import PHASES
from . import costmodel

#: Stage order of the per-job decomposition (control -> compute -> tail).
JOB_STAGES = ("admit_queue", "queue", "setup", "parse", "align",
              "window_assign", "poa", "stitch", "teardown", "gap",
              "gather")

#: Informational overlapping sub-stages (not part of the additive sum).
OVERLAY_STAGES = ("journal_replay", "kernel_build")

_OVERLAY_SPANS = {"journal.replay": "journal_replay",
                  "kernel.build": "kernel_build"}


def percentile(values: List[float], q: float) -> Optional[float]:
    """Linearly interpolated percentile (same estimator family as the
    interpolated ``hist_quantile``), ``q`` in [0, 1]."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    pos = q * (len(vs) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (pos - lo) * (vs[hi] - vs[lo])


def _events(doc: dict):
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict):
            yield ev


def _args(ev: dict) -> dict:
    a = ev.get("args")
    return a if isinstance(a, dict) else {}


def _chunk_decomposition(chunk: dict, inner: List[dict],
                         dispatch_ts: Optional[float]) -> Dict[str, float]:
    """Interval accounting (µs) inside one ``distrib.chunk`` span."""
    ts = float(chunk.get("ts", 0))
    dur = float(chunk.get("dur", 0))
    end = ts + dur
    out: Dict[str, float] = {}
    if dispatch_ts is not None:
        out["queue"] = max(0.0, ts - dispatch_ts)
    phase_ivs = []
    for ev in inner:
        name = ev.get("name", "")
        ev_ts = float(ev.get("ts", 0))
        ev_dur = float(ev.get("dur", 0))
        if name.startswith("phase."):
            stage = name[len("phase."):]
            if stage in PHASES:
                out[stage] = out.get(stage, 0.0) + ev_dur
                phase_ivs.append((ev_ts, ev_ts + ev_dur))
        elif name in _OVERLAY_SPANS:
            stage = _OVERLAY_SPANS[name]
            out[stage] = out.get(stage, 0.0) + ev_dur
    if phase_ivs:
        union = costmodel.union_intervals(phase_ivs)
        first = min(s for s, _ in union)
        last = max(e for _, e in union)
        covered = sum(e - s for s, e in union)
        out["setup"] = max(0.0, first - ts)
        out["teardown"] = max(0.0, end - last)
        out["gap"] = max(0.0, (last - first) - covered)
    else:
        # a replayed/cached chunk may run no phases at all: its whole
        # span is setup+teardown-free compute we cannot split further
        out["gap"] = dur
    return out


def analyze(doc: dict, profile: str = "auto") -> dict:
    """The machine-readable critical-path report for a merged trace."""
    dispatches = {}           # span_id -> dispatch event
    job_marks: Dict[str, dict] = {}   # job -> {"submit": ts, "done": ts, ...}
    chunks = []
    spans_by_pid: Dict[int, List[dict]] = {}
    for ev in _events(doc):
        name = ev.get("name", "")
        ph = ev.get("ph")
        a = _args(ev)
        if ph in ("i", "I"):
            if name == "distrib.dispatch" and a.get("span_id"):
                dispatches[a["span_id"]] = ev
            elif name in ("serve.job.submit", "serve.job.done"):
                job = str(a.get("job"))
                m = job_marks.setdefault(job, {})
                key = name.rsplit(".", 1)[1]
                m[key] = float(ev.get("ts", 0))
                if a.get("tenant") is not None:
                    m["tenant"] = a.get("tenant")
        elif ph == "X":
            if name == "distrib.chunk":
                chunks.append(ev)
            elif isinstance(ev.get("pid"), int):
                spans_by_pid.setdefault(ev["pid"], []).append(ev)

    per_chunk = []
    for chunk in chunks:
        a = _args(chunk)
        parent = a.get("parent")
        disp = dispatches.get(parent)
        disp_args = _args(disp) if disp else {}
        ts = float(chunk.get("ts", 0))
        end = ts + float(chunk.get("dur", 0))
        inner = [ev for ev in spans_by_pid.get(chunk.get("pid"), [])
                 if ts <= float(ev.get("ts", 0))
                 and float(ev.get("ts", 0)) + float(ev.get("dur", 0))
                 <= end + 1]
        stages = _chunk_decomposition(
            chunk, inner,
            float(disp["ts"]) if disp is not None else None)
        per_chunk.append({
            "chunk": a.get("chunk"),
            "job": disp_args.get("job"),
            "worker": disp_args.get("worker"),
            "dispatch_ts": float(disp["ts"]) if disp is not None else None,
            "ts": ts, "end": end,
            "stages_us": stages,
        })

    # ---- per-job critical paths
    jobs = {}
    for c in per_chunk:
        key = str(c["job"]) if c["job"] is not None else "?"
        jobs.setdefault(key, []).append(c)
    per_job = []
    for job, job_chunks in sorted(jobs.items()):
        crit = max(job_chunks, key=lambda c: c["end"])
        marks = job_marks.get(job, {})
        start = marks.get("submit")
        done = marks.get("done")
        path: Dict[str, float] = {}
        t0 = crit["dispatch_ts"] if crit["dispatch_ts"] is not None \
            else crit["ts"]
        if start is not None:
            path["admit_queue"] = max(0.0, t0 - start)
        else:
            start = min(c["dispatch_ts"] if c["dispatch_ts"] is not None
                        else c["ts"] for c in job_chunks)
            path["admit_queue"] = max(0.0, t0 - start)
        for stage, us in crit["stages_us"].items():
            if stage in OVERLAY_STAGES:
                continue
            path[stage] = path.get(stage, 0.0) + us
        t_end = done if done is not None else max(c["end"]
                                                  for c in job_chunks)
        path["gather"] = max(0.0, t_end - crit["end"])
        wall = max(0.0, t_end - start)
        attributed = sum(path.values())
        unattributed = max(0.0, wall - attributed)
        overlay = {s: sum(c["stages_us"].get(s, 0.0) for c in job_chunks)
                   for s in OVERLAY_STAGES}
        per_job.append({
            "job": job,
            "tenant": marks.get("tenant"),
            "chunks": len(job_chunks),
            "critical_chunk": crit["chunk"],
            "wall_us": wall,
            "path_us": {k: round(v, 1) for k, v in path.items()},
            "overlay_us": {k: round(v, 1) for k, v in overlay.items()
                           if v},
            "attributed_us": round(attributed, 1),
            "unattributed_us": round(unattributed, 1),
            "unattributed_frac": round(unattributed / wall, 4)
            if wall > 0 else 0.0,
        })

    # ---- loadtest-level aggregation: per-stage p50/p99 contributions
    stage_pcts = {}
    walls = [j["wall_us"] for j in per_job if j["wall_us"] > 0]
    for stage in JOB_STAGES:
        vals = [j["path_us"].get(stage, 0.0) for j in per_job]
        if not any(vals):
            continue
        stage_pcts[stage] = {
            "p50_us": round(percentile(vals, 0.50) or 0.0, 1),
            "p99_us": round(percentile(vals, 0.99) or 0.0, 1),
            "total_us": round(sum(vals), 1),
        }
    # ---- cost-model cross-check over the merged counters
    crosscheck = None
    metrics = doc.get("racon_tpu")
    counters = None
    if isinstance(metrics, dict):
        m = metrics.get("metrics")
        if isinstance(m, dict) and isinstance(m.get("counters"), dict):
            counters = m["counters"]
    if counters:
        od = doc.get("otherData")
        platform = od.get("platform") if isinstance(od, dict) else None
        prof = costmodel.resolve_profile(profile, platform)
        pred = costmodel.predict_from_counters(counters, prof)
        crosscheck = {"profile": prof.name, "phases": {}}
        for stage, alias in (("align", "align"), ("poa", "poa")):
            measured_s = sum(c["stages_us"].get(stage, 0.0)
                             for c in per_chunk) / 1e6
            p = pred["phases"].get(alias, {})
            predicted_s = p.get("predicted_s", 0.0)
            crosscheck["phases"][stage] = {
                "predicted_s": round(predicted_s, 6),
                "measured_s": round(measured_s, 6),
                "ratio": round(costmodel._ratio(predicted_s, measured_s)
                               or 0.0, 2),
                "within_bound": (costmodel._ratio(predicted_s, measured_s)
                                 or 0.0) <= prof.error_bound_ratio
                if predicted_s and measured_s else None,
                "verdict": p.get("verdict"),
            }

    return {
        "jobs": per_job,
        "chunks": len(per_chunk),
        "stages": stage_pcts,
        "wall_p50_us": round(percentile(walls, 0.50) or 0.0, 1),
        "wall_p99_us": round(percentile(walls, 0.99) or 0.0, 1),
        "costmodel": crosscheck,
        "max_unattributed_frac": round(
            max((j["unattributed_frac"] for j in per_job), default=0.0), 4),
    }


def render(result: dict, path: str, threshold: float) -> str:
    lines = [f"critical path: {path}"]
    if not result["jobs"]:
        lines.append("  (no distrib.dispatch -> distrib.chunk pairs; "
                     "nothing to attribute)")
        return "\n".join(lines)
    lines.append(f"  jobs={len(result['jobs'])} chunks={result['chunks']} "
                 f"wall p50={result['wall_p50_us'] / 1e3:.2f} ms "
                 f"p99={result['wall_p99_us'] / 1e3:.2f} ms")
    lines.append("-- per-stage contribution to the job critical path " +
                 "-" * 5)
    for stage, s in result["stages"].items():
        lines.append(f"  {stage:<14s} p50={s['p50_us'] / 1e3:>9.2f} ms  "
                     f"p99={s['p99_us'] / 1e3:>9.2f} ms  "
                     f"total={s['total_us'] / 1e3:>9.2f} ms")
    lines.append("-- per-job attribution " + "-" * 22)
    for j in result["jobs"]:
        flag = " OVER" if j["unattributed_frac"] > threshold else ""
        lines.append(
            f"  job {j['job']:<10s} chunks={j['chunks']:<2d} "
            f"wall={j['wall_us'] / 1e3:>9.2f} ms  "
            f"unattributed={j['unattributed_us'] / 1e3:>8.2f} ms "
            f"({100 * j['unattributed_frac']:.1f}%){flag}")
    cc = result.get("costmodel")
    if cc:
        lines.append(f"-- cost-model cross-check ({cc['profile']}) " +
                     "-" * 10)
        for stage, p in cc["phases"].items():
            ok = ("n/a" if p["within_bound"] is None
                  else "ok" if p["within_bound"] else "OFF-MODEL")
            lines.append(f"  {stage:<8s} predicted={p['predicted_s']:.3f} s "
                         f"measured={p['measured_s']:.3f} s "
                         f"ratio={p['ratio']:.2f} [{ok}]")
    return "\n".join(lines)
