"""``python -m racon_tpu.obs`` — read a trace written via ``--trace`` /
``RACON_TPU_TRACE``: validate the Chrome-trace schema, render a
phase/tier breakdown, diff two runs, or run the cost-model tooling.

Legacy flag form (kept stable for CI and tests)::

    python -m racon_tpu.obs run.json              # breakdown
    python -m racon_tpu.obs --validate run.json   # schema check
    python -m racon_tpu.obs --diff old.json new.json

Subcommands (the cost-model surface, same exit-code contract)::

    python -m racon_tpu.obs model [--profile P] [--lowered]
    python -m racon_tpu.obs validate run.json [--profile P]
    python -m racon_tpu.obs bench [extra.json ...] [--threshold T]
    python -m racon_tpu.obs merge --out MERGED.json T1.json T2.json ...
    python -m racon_tpu.obs fleet MERGED.json [--json]
    python -m racon_tpu.obs critpath MERGED.json [--json]

Exit codes (CI keys off these):

* 0 — trace valid / prediction within the profile's declared bound /
  no bench regression
* 1 — schema violation(s) in an otherwise readable trace, or a
  ``fleet`` trace-context violation (dangling parent / mixed trace ids)
* 2 — file unreadable / not JSON / not a trace object / bad arguments
* 3 — regression: ``--diff`` phase regression past ``--threshold``,
  ``validate`` prediction error past the machine profile's declared
  bound, ``bench`` history regression, or ``critpath`` unattributed
  wall time past ``--max-unattributed``
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

from . import PHASES
from . import bench_track, costmodel, critpath
from .metrics import hist_quantile

_VALID_PH = {"X", "B", "E", "i", "I", "M", "C"}


def load_trace(path: str) -> Tuple[dict, List[str]]:
    """Read + structurally validate one trace file.  Returns the parsed
    document and a list of schema-violation strings (empty = valid).
    Raises OSError/ValueError for exit-code-2 conditions."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("not a Chrome-trace object (no 'traceEvents' key)")
    errors: List[str] = []
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return doc, ["'traceEvents' is not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            errors.append(f"{where}: bad or missing 'ph' {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: bad or missing 'name'")
        if not isinstance(ev.get("pid"), int) \
                or not isinstance(ev.get("tid"), int):
            errors.append(f"{where}: bad or missing 'pid'/'tid'")
        if ph == "M":
            continue  # metadata events carry no timestamp
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: bad or missing 'ts' {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: complete event with bad "
                              f"'dur' {dur!r}")
        if len(errors) >= 50:
            errors.append("... (further violations suppressed)")
            break
    return doc, errors


def phase_walls_us(doc: dict) -> Dict[str, int]:
    """Total duration per ``phase.*`` span, µs."""
    walls: Dict[str, int] = {}
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict) and ev.get("ph") == "X" \
                and isinstance(ev.get("name"), str) \
                and ev["name"].startswith("phase."):
            name = ev["name"][len("phase."):]
            walls[name] = walls.get(name, 0) + int(ev.get("dur", 0))
    return walls


def _metrics_doc(doc: dict) -> dict:
    m = doc.get("racon_tpu")
    if isinstance(m, dict):
        m = m.get("metrics")
    return m if isinstance(m, dict) else {}


def _counters(doc: dict) -> Dict[str, int]:
    c = _metrics_doc(doc).get("counters")
    return c if isinstance(c, dict) else {}


def span_quantiles(doc: dict) -> Dict[str, dict]:
    """Per-span-name p50/p99 (µs) from the ``span_us.*`` log2 histograms
    the armed tracer feeds into the metrics registry.  Quantiles are
    bucket upper bounds — right to within the log2 bucket width."""
    out: Dict[str, dict] = {}
    hists = _metrics_doc(doc).get("histograms")
    if not isinstance(hists, dict):
        return out
    for name, h in sorted(hists.items()):
        if not name.startswith("span_us.") or not isinstance(h, dict):
            continue
        p50 = hist_quantile(h, 0.50)
        p99 = hist_quantile(h, 0.99)
        if p50 is None:
            continue
        out[name[len("span_us."):]] = {
            "count": h.get("count", 0), "p50_us": p50, "p99_us": p99,
            "max_us": h.get("max"),
        }
    return out


def dropped_events(doc: dict) -> int:
    od = doc.get("otherData")
    if isinstance(od, dict):
        try:
            return int(od.get("dropped_events", 0))
        except (TypeError, ValueError):
            return 0
    return 0


def breakdown(doc: dict) -> dict:
    """Phase walls, per-tier served counters, span-duration quantiles,
    and event counts — the machine-readable form behind the rendered
    table."""
    walls = phase_walls_us(doc)
    counters = _counters(doc)
    served: Dict[str, Dict[str, int]] = {}
    for name, v in counters.items():
        parts = name.split(".")
        if len(parts) == 3 and parts[0] == "served":
            served.setdefault(parts[1], {})[parts[2]] = v
    events: Dict[str, int] = {}
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict) and ev.get("ph") == "i":
            events[ev.get("name", "?")] = events.get(ev.get("name", "?"),
                                                     0) + 1
    return {"phase_us": walls, "served": served, "events": events,
            "counters": counters, "span_quantiles": span_quantiles(doc),
            # pipelined runs overlap phase spans in wall time; phase_us
            # above sums work time, this records the concurrency
            "phase_overlap_us": costmodel.phase_overlaps_us(doc),
            "dropped_events": dropped_events(doc)}


def render(doc: dict, path: str) -> str:
    b = breakdown(doc)
    lines = [f"trace: {path}"]
    if b["dropped_events"]:
        lines.append(f"  WARNING: {b['dropped_events']} event(s) dropped "
                     f"past the bounded buffer — totals are lower bounds")
    total = sum(b["phase_us"].values())
    lines.append("-- phases " + "-" * 34)
    order = [p for p in PHASES if p in b["phase_us"]]
    order += sorted(set(b["phase_us"]) - set(order))
    for p in order:
        us = b["phase_us"][p]
        pct = (100.0 * us / total) if total else 0.0
        lines.append(f"  {p:<16s} {us / 1e3:>10.2f} ms {pct:>5.1f}%")
    if not order:
        lines.append("  (no phase.* spans)")
    if b["phase_overlap_us"]:
        # sum(phase_us) counts concurrent time twice; the union wall is
        # what the clock saw
        ivs = []
        for ev in doc.get("traceEvents", []):
            if isinstance(ev, dict) and ev.get("ph") == "X" \
                    and isinstance(ev.get("name"), str) \
                    and ev["name"].startswith("phase."):
                ts = float(ev.get("ts", 0))
                ivs.append((ts, ts + float(ev.get("dur", 0))))
        union = sum(e - s for s, e in costmodel.union_intervals(ivs))
        lines.append("-- phase overlap (pipelined) " + "-" * 15)
        for pair, us in sorted(b["phase_overlap_us"].items()):
            lines.append(f"  {pair:<16s} {us / 1e3:>10.2f} ms concurrent")
        lines.append(f"  {'union wall':<16s} {union / 1e3:>10.2f} ms "
                     f"(vs {total / 1e3:.2f} ms summed)")
    if b["served"]:
        lines.append("-- served (windows/jobs per tier) " + "-" * 10)
        for phase, tiers in sorted(b["served"].items()):
            mix = "  ".join(f"{t}={n}" for t, n in sorted(tiers.items()))
            lines.append(f"  {phase:<16s} {mix}  (sum="
                         f"{sum(tiers.values())})")
    if b["span_quantiles"]:
        lines.append("-- span durations (p50/p99 from log2 histograms) --")
        for name, q in b["span_quantiles"].items():
            lines.append(f"  {name:<24s} n={q['count']:<6d} "
                         f"p50<={q['p50_us'] / 1e3:>9.2f} ms  "
                         f"p99<={q['p99_us'] / 1e3:>9.2f} ms")
    if b["events"]:
        lines.append("-- events " + "-" * 34)
        for name, n in sorted(b["events"].items()):
            lines.append(f"  {name:<28s} x{n}")
    return "\n".join(lines)


def diff(old: dict, new: dict, threshold: float,
         min_delta_us: int) -> Tuple[List[str], List[str]]:
    """Phase-wall regressions plus one-sided-phase flags.

    A phase present on only one side is *flagged* (``only-in-old`` /
    ``only-in-new``) with the missing side treated as 0 — a resumed run
    that replayed align from the journal legitimately has no
    ``phase.align`` span, and that must read as a structural difference,
    not a crash or an infinite-percent regression.  Regressions keep the
    exit-3 contract: new > old*(1+threshold) and absolute growth past
    ``min_delta_us``."""
    ow, nw = phase_walls_us(old), phase_walls_us(new)
    regressions, flags = [], []
    for phase in sorted(set(ow) | set(nw)):
        o, n = ow.get(phase, 0), nw.get(phase, 0)
        if phase not in ow or phase not in nw:
            side = "new" if phase not in ow else "old"
            us = n if side == "new" else o
            flags.append(f"phase.{phase}: only-in-{side} "
                         f"({us / 1e3:.2f} ms; missing side counted as 0)")
        if n > o * (1.0 + threshold) and (n - o) > min_delta_us:
            pct = f"+{100.0 * (n - o) / o:.0f}%" if o else "only-in-new"
            regressions.append(
                f"phase.{phase}: {o / 1e3:.2f} ms -> {n / 1e3:.2f} ms "
                f"({pct}, threshold {threshold * 100:.0f}%)")
    return regressions, flags


# -- subcommands -----------------------------------------------------------

def _profile_for(doc: dict, name: str) -> costmodel.MachineProfile:
    """'auto' resolves from the platform stamped into the trace at write
    time (falls back to cpu-host when absent)."""
    platform = None
    od = doc.get("otherData")
    if isinstance(od, dict):
        platform = od.get("platform")
    return costmodel.resolve_profile(name, platform)


def cmd_model(args) -> int:
    try:
        prof = costmodel.profile(args.profile if args.profile != "auto"
                                 else "cpu-host")
    except KeyError as e:
        print(f"[obs] {e}", file=sys.stderr)
        return 2
    rows = costmodel.model_rows(
        prof, window_lengths=args.window_length or
        costmodel.AUDIT_WINDOW_LENGTHS, lowered=args.lowered)
    if args.as_json:
        print(json.dumps({"profile": prof.name, "rows": rows}, indent=2))
    else:
        print(costmodel.render_model(rows, prof))
    return 0


def cmd_validate(args) -> int:
    try:
        doc, errors = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"[obs] cannot read trace {args.trace}: {e}", file=sys.stderr)
        return 2
    if errors:
        for err in errors:
            print(f"[obs] {args.trace}: {err}", file=sys.stderr)
        return 1
    try:
        prof = _profile_for(doc, args.profile)
    except KeyError as e:
        print(f"[obs] {e}", file=sys.stderr)
        return 2
    v = costmodel.validate_trace(doc, prof)
    if args.as_json:
        print(json.dumps(v, indent=2))
    else:
        print(costmodel.render_validation(v))
    return 0 if v["ok"] else 3


def cmd_bench(args) -> int:
    entries, problems = bench_track.load_history(
        root=args.root, extra_paths=args.extra)
    for p in problems:
        print(f"[obs] bench history problem: {p}", file=sys.stderr)
    if problems:
        return 2
    if not entries:
        print("[obs] no bench history found", file=sys.stderr)
        return 2
    result = bench_track.trend(entries, threshold=args.threshold,
                               min_delta_s=args.min_delta_s)
    if args.as_json:
        print(json.dumps(result, indent=2))
    else:
        print(bench_track.render(result))
    return 3 if result["regressions"] else 0


def _doc_t0_ns(doc: dict):
    od = doc.get("otherData")
    if isinstance(od, dict):
        t0 = od.get("t0_monotonic_ns")
        if isinstance(t0, int):
            return t0
    return None


def merge_traces(docs: List[dict], paths: List[str]) -> dict:
    """Fold per-process trace documents into one multi-track timeline.

    Same-host traces share the monotonic clock, so each document's
    events shift by the µs offset of its ``t0_monotonic_ns`` epoch from
    the earliest one — dispatch spans in the coordinator then line up
    against the worker chunk spans they caused.  Documents without an
    epoch stamp (older traces) keep their own timebase.  pid/tid stamps
    are preserved: one Perfetto track group per process, named by the
    ``process_name`` metadata each document already carries."""
    t0s = [_doc_t0_ns(d) for d in docs]
    known = [t for t in t0s if t is not None]
    base = min(known) if known else None
    events: List[dict] = []
    processes: List[dict] = []
    counters: Dict[str, int] = {}
    platform = None
    dropped = 0
    for doc, path, t0 in zip(docs, paths, t0s):
        dt_us = ((t0 - base) // 1000) if (t0 is not None
                                          and base is not None) else 0
        for ev in doc.get("traceEvents", []):
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            if ev.get("ph") != "M" and isinstance(ev.get("ts"),
                                                  (int, float)):
                ev["ts"] = max(0, int(ev["ts"]) + dt_us)
            events.append(ev)
        dropped += dropped_events(doc)
        # counters are exact and additive, so the merged document can
        # carry the fleet-wide sums (critpath's cost-model cross-check
        # reads them); histograms don't merge losslessly and are left out
        for name, v in _counters(doc).items():
            try:
                counters[name] = counters.get(name, 0) + int(v)
            except (TypeError, ValueError):
                continue
        od = doc.get("otherData") if isinstance(doc.get("otherData"),
                                                dict) else {}
        platform = platform or od.get("platform")
        processes.append({
            "path": path, "pid": od.get("pid"), "role": od.get("role"),
            "trace_id": od.get("trace_id"), "t0_monotonic_ns": t0,
            "offset_us": dt_us, "events": len(doc.get("traceEvents", [])),
        })
    other = {"tool": "racon_tpu.obs", "clock": "monotonic",
             "dropped_events": dropped, "merged_from": list(paths)}
    if platform:
        other["platform"] = platform
    merged = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": other,
        "racon_tpu": {"processes": processes},
    }
    if counters:
        merged["racon_tpu"]["metrics"] = {
            "counters": dict(sorted(counters.items()))}
    return merged


def cmd_merge(args) -> int:
    docs = []
    for path in args.traces:
        try:
            doc, errors = load_trace(path)
        except (OSError, ValueError) as e:
            print(f"[obs] cannot read trace {path}: {e}", file=sys.stderr)
            return 2
        if errors:
            for err in errors:
                print(f"[obs] {path}: {err}", file=sys.stderr)
            return 1
        docs.append(doc)
    merged = merge_traces(docs, args.traces)
    try:
        with open(args.out, "w") as f:
            json.dump(merged, f)
            f.write("\n")
    except OSError as e:
        print(f"[obs] cannot write {args.out}: {e}", file=sys.stderr)
        return 2
    procs = merged["racon_tpu"]["processes"]
    print(f"[obs] merged {len(docs)} trace(s), "
          f"{len(merged['traceEvents'])} events, "
          f"{len(procs)} process entr{'y' if len(procs) == 1 else 'ies'} "
          f"-> {args.out}")
    return 0


def fleet_breakdown(doc: dict) -> dict:
    """Per-process accounting over a merged fleet trace, plus the
    trace-context invariants the merge exists to make checkable:

    * every ``distrib.chunk`` span naming a parent must name the
      ``span_id`` of some coordinator ``distrib.dispatch`` event
      (dangling parent = causality lost in the merge);
    * every ``trace_id`` stamped on chunks/dispatches must match — one
      fleet run is one trace.
    """
    roles: Dict[int, str] = {}
    per: Dict[int, dict] = {}
    dispatch_ids = set()
    trace_ids = set()
    violations: List[str] = []
    # elastic-fleet control-plane events (fleet/plane.py + pool.py):
    # pool resizes, cross-job steals, admission sheds
    elastic = {"scale_ups": 0, "scale_downs": 0, "steals": 0, "sheds": 0}
    _ELASTIC_NAMES = {"fleet.scale_up": "scale_ups",
                      "fleet.scale_down": "scale_downs",
                      "fleet.steal": "steals",
                      "serve.shed": "sheds"}
    for ev in doc.get("traceEvents", []):
        if not isinstance(ev, dict):
            continue
        pid = ev.get("pid")
        if not isinstance(pid, int):
            continue
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            name = (ev.get("args") or {}).get("name")
            if isinstance(name, str):
                roles[pid] = name
            continue
        p = per.setdefault(pid, {"spans": 0, "events": 0, "chunks": 0,
                                 "dispatches": 0, "chunk_wall_us": 0,
                                 "kernel_wall_us": 0, "peak_rss_mb": 0.0})
        args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
        name = ev.get("name", "")
        if ev.get("ph") == "X":
            p["spans"] += 1
            dur = int(ev.get("dur", 0))
            if name == "distrib.chunk":
                p["chunks"] += 1
                p["chunk_wall_us"] += dur
                if args.get("trace_id"):
                    trace_ids.add(args["trace_id"])
            elif name in ("phase.align", "phase.poa"):
                # the two hot-kernel phases (obs.PHASES naming)
                p["kernel_wall_us"] += dur
        elif ev.get("ph") in ("i", "I"):
            p["events"] += 1
            if name == "distrib.dispatch":
                p["dispatches"] += 1
                if args.get("span_id"):
                    dispatch_ids.add(args["span_id"])
                if args.get("trace_id"):
                    trace_ids.add(args["trace_id"])
            elif name in _ELASTIC_NAMES:
                elastic[_ELASTIC_NAMES[name]] += 1
            elif name == "mem.rss":
                # per-worker peak RSS (distrib/worker.py stamps one
                # instant per chunk) — the memory column of `obs fleet`
                try:
                    p["peak_rss_mb"] = max(p["peak_rss_mb"],
                                           float(args.get("rss_mb") or 0.0))
                except (TypeError, ValueError):
                    pass
    # second pass: parenting — a chunk span's parent must be a dispatch
    for ev in doc.get("traceEvents", []):
        if not (isinstance(ev, dict) and ev.get("ph") == "X"
                and ev.get("name") == "distrib.chunk"):
            continue
        args = ev.get("args") if isinstance(ev.get("args"), dict) else {}
        parent = args.get("parent")
        if parent and parent not in dispatch_ids:
            violations.append(
                f"distrib.chunk (pid {ev.get('pid')}, chunk "
                f"{args.get('chunk')}) names parent {parent!r} but no "
                f"distrib.dispatch event carries that span_id")
    if len(trace_ids) > 1:
        violations.append(f"multiple trace ids in one fleet trace: "
                          f"{sorted(trace_ids)}")
    return {
        "processes": {str(pid): {"role": roles.get(pid), **stats}
                      for pid, stats in sorted(per.items())},
        "dispatch_span_ids": len(dispatch_ids),
        "trace_ids": sorted(trace_ids),
        "elastic": elastic,
        "violations": violations,
    }


def cmd_fleet(args) -> int:
    try:
        doc, errors = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"[obs] cannot read trace {args.trace}: {e}", file=sys.stderr)
        return 2
    if errors:
        for err in errors:
            print(f"[obs] {args.trace}: {err}", file=sys.stderr)
        return 1
    b = fleet_breakdown(doc)
    if args.as_json:
        print(json.dumps(b, indent=2))
    else:
        print(f"fleet trace: {args.trace}")
        print("-- processes " + "-" * 31)
        for pid, p in b["processes"].items():
            print(f"  pid {pid:<8s} {p['role'] or '?':<14s} "
                  f"chunks={p['chunks']:<3d} "
                  f"dispatches={p['dispatches']:<3d} "
                  f"chunk={p['chunk_wall_us'] / 1e3:>9.2f} ms  "
                  f"kernel={p['kernel_wall_us'] / 1e3:>9.2f} ms  "
                  f"peak_rss={p['peak_rss_mb']:>7.1f} MiB")
        if b["trace_ids"]:
            print(f"  trace id: {', '.join(b['trace_ids'])} "
                  f"({b['dispatch_span_ids']} dispatch span ids)")
        e = b["elastic"]
        if any(e.values()):
            print(f"  elastic: scale_ups={e['scale_ups']} "
                  f"scale_downs={e['scale_downs']} steals={e['steals']} "
                  f"sheds={e['sheds']}")
        for v in b["violations"]:
            print(f"[obs] VIOLATION: {v}", file=sys.stderr)
        if not b["violations"]:
            print("[obs] OK: trace-context parenting holds")
    return 1 if b["violations"] else 0


def cmd_critpath(args) -> int:
    try:
        doc, errors = load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"[obs] cannot read trace {args.trace}: {e}", file=sys.stderr)
        return 2
    if errors:
        for err in errors:
            print(f"[obs] {args.trace}: {err}", file=sys.stderr)
        return 1
    try:
        result = critpath.analyze(doc, profile=args.profile)
    except KeyError as e:
        print(f"[obs] {e}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps(result, indent=2))
    else:
        print(critpath.render(result, args.trace, args.max_unattributed))
    over = [j for j in result["jobs"]
            if j["unattributed_frac"] > args.max_unattributed]
    if over:
        for j in over:
            print(f"[obs] UNATTRIBUTED: job {j['job']}: "
                  f"{100 * j['unattributed_frac']:.1f}% of "
                  f"{j['wall_us'] / 1e3:.2f} ms wall unexplained "
                  f"(threshold {100 * args.max_unattributed:.0f}%)",
                  file=sys.stderr)
        return 3
    if result["jobs"] and not args.as_json:
        print(f"[obs] OK: every job attributed to within "
              f"{100 * args.max_unattributed:.0f}% of its wall")
    return 0


def _sub_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m racon_tpu.obs",
        description="cost-model tooling over racon_tpu traces and bench "
                    "history (see docs/benchmarks.md)")
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("model", help="print the predicted cost grid")
    m.add_argument("--profile", default="cpu-host",
                   help="machine profile (%s)" % ", ".join(
                       sorted(costmodel.PROFILES)))
    m.add_argument("--window-length", type=int, action="append",
                   help="window length(s) to tabulate (repeatable; "
                        "default: the audit lengths)")
    m.add_argument("--lowered", action="store_true",
                   help="refine FLOPs/bytes via jax Lowered.cost_analysis "
                        "where available (imports jax; slower)")
    m.add_argument("--json", action="store_true", dest="as_json")
    m.set_defaults(fn=cmd_model)

    v = sub.add_parser("validate",
                       help="join predictions against a measured trace; "
                            "exit 3 when error exceeds the profile's "
                            "declared bound")
    v.add_argument("trace")
    v.add_argument("--profile", default="auto",
                   help="machine profile, or 'auto' to pick from the "
                        "platform stamped in the trace (default)")
    v.add_argument("--json", action="store_true", dest="as_json")
    v.set_defaults(fn=cmd_validate)

    b = sub.add_parser("bench",
                       help="trend + regression gate over BENCH_r*.json "
                            "and docs/device_bench_log.jsonl")
    b.add_argument("extra", nargs="*",
                   help="extra bench-entry JSON file(s) appended to the "
                        "history (newest last) — CI injects a synthetic "
                        "regression here as a self-test")
    b.add_argument("--root", default=bench_track._REPO_ROOT,
                   help="repo root holding BENCH_r*.json (default: this "
                        "checkout)")
    b.add_argument("--threshold", type=float, default=0.25,
                   help="relative drop/growth gated per series "
                        "(default 0.25)")
    b.add_argument("--min-delta-s", type=float, default=0.05,
                   help="ignore phase-wall growth smaller than this many "
                        "seconds (default 0.05)")
    b.add_argument("--json", action="store_true", dest="as_json")
    b.set_defaults(fn=cmd_bench)

    mg = sub.add_parser("merge",
                        help="fold per-process traces (coordinator + "
                             "workers) into one multi-track timeline, "
                             "re-based onto the earliest monotonic epoch")
    mg.add_argument("traces", nargs="+",
                    help="trace files to merge (any order)")
    mg.add_argument("--out", required=True,
                    help="path for the merged Chrome-trace JSON")
    mg.set_defaults(fn=cmd_merge)

    fl = sub.add_parser("fleet",
                        help="per-process breakdown of a merged fleet "
                             "trace + trace-context parenting check; "
                             "exit 1 on a dangling parent or mixed "
                             "trace ids")
    fl.add_argument("trace")
    fl.add_argument("--json", action="store_true", dest="as_json")
    fl.set_defaults(fn=cmd_fleet)

    cp = sub.add_parser("critpath",
                        help="critical-path attribution over a merged "
                             "fleet trace: per-job/per-stage latency "
                             "decomposition via the dispatch->chunk "
                             "parenting; exit 3 when unattributed wall "
                             "exceeds --max-unattributed")
    cp.add_argument("trace")
    cp.add_argument("--profile", default="auto",
                    help="machine profile for the cost-model "
                         "cross-check (default: auto from the trace)")
    cp.add_argument("--max-unattributed", type=float, default=0.10,
                    help="tolerated unattributed fraction of each "
                         "job's wall (default 0.10)")
    cp.add_argument("--json", action="store_true", dest="as_json")
    cp.set_defaults(fn=cmd_critpath)
    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] in ("model", "validate", "bench", "merge", "fleet",
                            "critpath"):
        try:
            args = _sub_parser().parse_args(argv)
        except SystemExit as e:
            return 2 if e.code not in (0, None) else 0
        return args.fn(args)

    p = argparse.ArgumentParser(
        prog="python -m racon_tpu.obs",
        description="validate / summarize / diff racon_tpu trace files "
                    "(Chrome-trace JSON from --trace / RACON_TPU_TRACE); "
                    "subcommands model/validate/bench run the cost-model "
                    "tooling")
    p.add_argument("trace", nargs="+",
                   help="trace file (two files with --diff: OLD NEW)")
    p.add_argument("--validate", action="store_true",
                   help="schema validation only, no breakdown")
    p.add_argument("--diff", action="store_true",
                   help="compare two traces; exit 3 on phase regression")
    p.add_argument("--overlap", metavar="NAME_A:NAME_B",
                   help="assert the two span families overlap in time "
                        "(e.g. align.cohort:poa.bucket for a pipelined "
                        "polish); exit 3 when the overlap is zero")
    p.add_argument("--threshold", type=float, default=0.25,
                   help="--diff: relative slowdown tolerated per phase "
                        "(default 0.25 = 25%%)")
    p.add_argument("--min-delta-us", type=int, default=1000,
                   help="--diff: ignore regressions smaller than this "
                        "many µs (default 1000)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(argv)

    if args.diff and len(args.trace) != 2:
        print("[obs] --diff needs exactly two trace files", file=sys.stderr)
        return 2
    if not args.diff and len(args.trace) != 1:
        print("[obs] expected one trace file (or two with --diff)",
              file=sys.stderr)
        return 2

    docs = []
    for path in args.trace:
        try:
            doc, errors = load_trace(path)
        except (OSError, ValueError) as e:
            print(f"[obs] cannot read trace {path}: {e}", file=sys.stderr)
            return 2
        if errors:
            for err in errors:
                print(f"[obs] {path}: {err}", file=sys.stderr)
            print(f"[obs] SCHEMA FAIL: {path}: {len(errors)} violation(s)",
                  file=sys.stderr)
            return 1
        docs.append(doc)

    if args.diff:
        regressions, flags = diff(docs[0], docs[1], args.threshold,
                                  args.min_delta_us)
        if args.as_json:
            print(json.dumps({"regressions": regressions,
                              "only_in": flags}, indent=2))
        else:
            for fl in flags:
                print(f"[obs] NOTE: {fl}")
            for r in regressions:
                print(f"[obs] REGRESSION: {r}")
            if not regressions:
                print(f"[obs] OK: no phase regression past "
                      f"{args.threshold * 100:.0f}%")
        return 3 if regressions else 0

    doc = docs[0]
    if args.overlap:
        if ":" not in args.overlap:
            print("[obs] --overlap expects NAME_A:NAME_B", file=sys.stderr)
            return 2
        name_a, name_b = args.overlap.split(":", 1)
        ov_us = costmodel.overlap_us(doc, name_a, name_b)
        n_a = len(costmodel.span_intervals(doc, name_a))
        n_b = len(costmodel.span_intervals(doc, name_b))
        if args.as_json:
            print(json.dumps({"a": name_a, "b": name_b, "spans_a": n_a,
                              "spans_b": n_b, "overlap_us": ov_us}))
        elif ov_us > 0:
            print(f"[obs] OK: {name_a} ({n_a} spans) and {name_b} "
                  f"({n_b} spans) overlap for {ov_us / 1e3:.2f} ms")
        else:
            print(f"[obs] NO OVERLAP: {name_a} ({n_a} spans) and "
                  f"{name_b} ({n_b} spans) never ran concurrently",
                  file=sys.stderr)
        return 0 if ov_us > 0 else 3
    if args.validate:
        dropped = dropped_events(doc)
        if not args.as_json:
            print(f"[obs] OK: {args.trace[0]} is valid Chrome-trace JSON "
                  f"({len(doc['traceEvents'])} events)")
            if dropped:
                print(f"[obs] WARNING: {dropped} event(s) were dropped "
                      f"past the tracer's bounded buffer — the trace is "
                      f"truncated, not complete")
        else:
            print(json.dumps({"valid": True,
                              "events": len(doc["traceEvents"]),
                              "dropped_events": dropped}))
        return 0
    if args.as_json:
        print(json.dumps(breakdown(doc), indent=2))
    else:
        print(render(doc, args.trace[0]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
