"""``python -m racon_tpu.obs`` — read a trace written via ``--trace`` /
``RACON_TPU_TRACE``: validate the Chrome-trace schema, render a
phase/tier breakdown, or diff two runs.

Exit codes (CI keys off these):

* 0 — trace valid (and, in ``--diff`` mode, no regression)
* 1 — schema violation(s) in an otherwise readable trace
* 2 — file unreadable / not JSON / not a trace object
* 3 — ``--diff`` found a phase regression past ``--threshold``
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Tuple

from . import PHASES

_VALID_PH = {"X", "B", "E", "i", "I", "M", "C"}


def load_trace(path: str) -> Tuple[dict, List[str]]:
    """Read + structurally validate one trace file.  Returns the parsed
    document and a list of schema-violation strings (empty = valid).
    Raises OSError/ValueError for exit-code-2 conditions."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError("not a Chrome-trace object (no 'traceEvents' key)")
    errors: List[str] = []
    events = doc["traceEvents"]
    if not isinstance(events, list):
        return doc, ["'traceEvents' is not a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _VALID_PH:
            errors.append(f"{where}: bad or missing 'ph' {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: bad or missing 'name'")
        if not isinstance(ev.get("pid"), int) \
                or not isinstance(ev.get("tid"), int):
            errors.append(f"{where}: bad or missing 'pid'/'tid'")
        if ph == "M":
            continue  # metadata events carry no timestamp
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"{where}: bad or missing 'ts' {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: complete event with bad "
                              f"'dur' {dur!r}")
        if len(errors) >= 50:
            errors.append("... (further violations suppressed)")
            break
    return doc, errors


def phase_walls_us(doc: dict) -> Dict[str, int]:
    """Total duration per ``phase.*`` span, µs."""
    walls: Dict[str, int] = {}
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict) and ev.get("ph") == "X" \
                and isinstance(ev.get("name"), str) \
                and ev["name"].startswith("phase."):
            name = ev["name"][len("phase."):]
            walls[name] = walls.get(name, 0) + int(ev.get("dur", 0))
    return walls


def _counters(doc: dict) -> Dict[str, int]:
    m = doc.get("racon_tpu")
    if isinstance(m, dict):
        m = m.get("metrics")
    if isinstance(m, dict):
        c = m.get("counters")
        if isinstance(c, dict):
            return c
    return {}


def breakdown(doc: dict) -> dict:
    """Phase walls, per-tier served counters, and event counts — the
    machine-readable form behind the rendered table."""
    walls = phase_walls_us(doc)
    counters = _counters(doc)
    served: Dict[str, Dict[str, int]] = {}
    for name, v in counters.items():
        parts = name.split(".")
        if len(parts) == 3 and parts[0] == "served":
            served.setdefault(parts[1], {})[parts[2]] = v
    events: Dict[str, int] = {}
    for ev in doc.get("traceEvents", []):
        if isinstance(ev, dict) and ev.get("ph") == "i":
            events[ev.get("name", "?")] = events.get(ev.get("name", "?"),
                                                     0) + 1
    return {"phase_us": walls, "served": served, "events": events,
            "counters": counters}


def render(doc: dict, path: str) -> str:
    b = breakdown(doc)
    lines = [f"trace: {path}"]
    total = sum(b["phase_us"].values())
    lines.append("-- phases " + "-" * 34)
    order = [p for p in PHASES if p in b["phase_us"]]
    order += sorted(set(b["phase_us"]) - set(order))
    for p in order:
        us = b["phase_us"][p]
        pct = (100.0 * us / total) if total else 0.0
        lines.append(f"  {p:<16s} {us / 1e3:>10.2f} ms {pct:>5.1f}%")
    if not order:
        lines.append("  (no phase.* spans)")
    if b["served"]:
        lines.append("-- served (windows/jobs per tier) " + "-" * 10)
        for phase, tiers in sorted(b["served"].items()):
            mix = "  ".join(f"{t}={n}" for t, n in sorted(tiers.items()))
            lines.append(f"  {phase:<16s} {mix}  (sum="
                         f"{sum(tiers.values())})")
    if b["events"]:
        lines.append("-- events " + "-" * 34)
        for name, n in sorted(b["events"].items()):
            lines.append(f"  {name:<28s} x{n}")
    return "\n".join(lines)


def diff(old: dict, new: dict, threshold: float,
         min_delta_us: int) -> List[str]:
    """Phase-wall regressions: new > old*(1+threshold) and the absolute
    growth exceeds ``min_delta_us`` (filters noise on tiny runs)."""
    ow, nw = phase_walls_us(old), phase_walls_us(new)
    regressions = []
    for phase in sorted(set(ow) | set(nw)):
        o, n = ow.get(phase, 0), nw.get(phase, 0)
        if n > o * (1.0 + threshold) and (n - o) > min_delta_us:
            pct = (100.0 * (n - o) / o) if o else float("inf")
            regressions.append(
                f"phase.{phase}: {o / 1e3:.2f} ms -> {n / 1e3:.2f} ms "
                f"(+{pct:.0f}%, threshold {threshold * 100:.0f}%)")
    return regressions


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m racon_tpu.obs",
        description="validate / summarize / diff racon_tpu trace files "
                    "(Chrome-trace JSON from --trace / RACON_TPU_TRACE)")
    p.add_argument("trace", nargs="+",
                   help="trace file (two files with --diff: OLD NEW)")
    p.add_argument("--validate", action="store_true",
                   help="schema validation only, no breakdown")
    p.add_argument("--diff", action="store_true",
                   help="compare two traces; exit 3 on phase regression")
    p.add_argument("--threshold", type=float, default=0.25,
                   help="--diff: relative slowdown tolerated per phase "
                        "(default 0.25 = 25%%)")
    p.add_argument("--min-delta-us", type=int, default=1000,
                   help="--diff: ignore regressions smaller than this "
                        "many µs (default 1000)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    args = p.parse_args(argv)

    if args.diff and len(args.trace) != 2:
        print("[obs] --diff needs exactly two trace files", file=sys.stderr)
        return 2
    if not args.diff and len(args.trace) != 1:
        print("[obs] expected one trace file (or two with --diff)",
              file=sys.stderr)
        return 2

    docs = []
    for path in args.trace:
        try:
            doc, errors = load_trace(path)
        except (OSError, ValueError) as e:
            print(f"[obs] cannot read trace {path}: {e}", file=sys.stderr)
            return 2
        if errors:
            for err in errors:
                print(f"[obs] {path}: {err}", file=sys.stderr)
            print(f"[obs] SCHEMA FAIL: {path}: {len(errors)} violation(s)",
                  file=sys.stderr)
            return 1
        docs.append(doc)

    if args.diff:
        regressions = diff(docs[0], docs[1], args.threshold,
                           args.min_delta_us)
        if args.as_json:
            print(json.dumps({"regressions": regressions}, indent=2))
        else:
            for r in regressions:
                print(f"[obs] REGRESSION: {r}")
            if not regressions:
                print(f"[obs] OK: no phase regression past "
                      f"{args.threshold * 100:.0f}%")
        return 3 if regressions else 0

    doc = docs[0]
    if args.validate:
        if not args.as_json:
            print(f"[obs] OK: {args.trace[0]} is valid Chrome-trace JSON "
                  f"({len(doc['traceEvents'])} events)")
        else:
            print(json.dumps({"valid": True,
                              "events": len(doc["traceEvents"])}))
        return 0
    if args.as_json:
        print(json.dumps(breakdown(doc), indent=2))
    else:
        print(render(doc, args.trace[0]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
