"""Per-tenant SLO objects + multi-window burn-rate engine over the
job-latency ledger stream.

An **objective** is a latency target (seconds, per tenant with a
``default`` fallback) plus an availability fraction.  Every finished
serve job is one observation: *bad* when it failed or overran its
tenant's latency target.  The classic error budget follows: with
availability ``a``, the budget is ``1 - a`` bad-fraction; the **burn
rate** over a window is ``bad_fraction / (1 - a)`` — burn 1.0 spends
the budget exactly at the sustainable rate, burn 10 spends it 10x too
fast.

Alerting is multi-window (the SRE-workbook shape): an alert requires
*both* the fast window (reactive, noisy) and the slow window
(confirming, stable) to burn past ``RACON_TPU_SLO_BURN_ALERT``, so a
single slow job cannot page and a sustained regression cannot hide.
The alert state is a first-class control signal: the fleet plane's
autoscaler grows the pool on it (cause ``slo_burn``) and the
scheduler's admission ladder sheds above ``RACON_TPU_SLO_SHED_BURN``.

Everything here is control-plane metadata — monotonic clocks only (the
``wall-clock`` lint scopes this package) and no dataflow into polished
bytes.  The engine is process-global (scheduler, plane, and the
metrics exposition all read the same one); disarmed (no knobs set) it
costs one deque append per finished job and never alerts.

Fault point ``slo.burn``: an armed raise is absorbed as a *forced*
burn — both windows report at least the alert threshold for one fast
window — the deterministic injected-slowdown drill CI uses to prove
the alert -> scale-up path without a real regression.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from .. import config
from ..resilience import faults

#: Observations kept per engine — bounds memory under sustained load
#: (the slow window trims by time; this caps a burst inside it).
_MAX_EVENTS = 8192


def parse_targets(text: str) -> Dict[str, float]:
    """Parse ``RACON_TPU_SLO_LATENCY_S``: a bare float is the default
    target; ``key=value`` pairs (comma-separated) set per-tenant /
    per-profile targets, e.g. ``"default=2.5,tenant-a=1.0"``.
    Malformed fragments are skipped (a typo'd target must not take the
    daemon down)."""
    out: Dict[str, float] = {}
    for part in filter(None, (p.strip() for p in (text or "").split(","))):
        key, sep, val = part.partition("=")
        if not sep:
            key, val = "default", key
        try:
            t = float(val)
        except ValueError:
            continue
        if t > 0:
            out[key.strip()] = t
    return out


class SLOEngine:
    """Burn-rate accounting over (tenant, latency, ok) completions."""

    def __init__(self):
        self._lock = threading.Lock()
        self.targets = parse_targets(
            config.get_str("RACON_TPU_SLO_LATENCY_S"))
        self.availability = min(
            0.999999, max(0.0, config.get_float("RACON_TPU_SLO_AVAILABILITY")))
        self.fast_window_s = max(
            1.0, config.get_float("RACON_TPU_SLO_FAST_WINDOW_S"))
        self.slow_window_s = max(
            self.fast_window_s, config.get_float("RACON_TPU_SLO_SLOW_WINDOW_S"))
        self.burn_alert = max(
            0.0, config.get_float("RACON_TPU_SLO_BURN_ALERT"))
        self.shed_burn = max(0.0, config.get_float("RACON_TPU_SLO_SHED_BURN"))
        # (t_mono_s, tenant, bad) observations, newest right
        self._events = deque(maxlen=_MAX_EVENTS)
        self._alerting: Dict[str, bool] = {}
        self._counters = {"observed": 0, "bad": 0, "alerts": 0,
                          "shed": 0, "burn_faults": 0}
        self._forced_until = 0.0

    # -- ingest ------------------------------------------------------------

    def target_for(self, tenant: str) -> Optional[float]:
        t = self.targets.get(tenant or "")
        return t if t is not None else self.targets.get("default")

    def record(self, tenant: str, latency_s: float, ok: bool = True,
               now: Optional[float] = None) -> None:
        """Ingest one finished job.  ``bad`` = failed, or overran the
        tenant's latency target (jobs with no target are bad only on
        failure — availability still applies)."""
        t = time.monotonic() if now is None else now
        target = self.target_for(tenant)
        bad = (not ok) or (target is not None and latency_s > target)
        with self._lock:
            self._events.append((t, tenant or "", bool(bad)))
            self._counters["observed"] += 1
            if bad:
                self._counters["bad"] += 1
        self._check_fault(t)
        self._evaluate(tenant or "", now=t)

    def _check_fault(self, now: float) -> None:
        """The ``slo.burn`` injection point: a raise is absorbed as a
        forced burn for one fast window (counted, never propagated)."""
        try:
            faults.check("slo.burn")
        except Exception:  # noqa: BLE001 — absorbed: an injected burn
            # forces the alert threshold, never propagates
            with self._lock:
                self._counters["burn_faults"] += 1
                self._forced_until = max(self._forced_until,
                                         now + self.fast_window_s)

    # -- burn math ---------------------------------------------------------

    def _window_burn(self, tenant: str, window_s: float,
                     now: float) -> float:
        lo = now - window_s
        total = bad = 0
        with self._lock:
            for t, ten, b in self._events:
                if t < lo or (tenant and ten != tenant):
                    continue
                total += 1
                bad += 1 if b else 0
            forced = now < self._forced_until
        budget = 1.0 - self.availability
        burn = (bad / total) / budget if total and budget > 0 else 0.0
        if forced:
            burn = max(burn, self.burn_alert if self.burn_alert > 0
                       else 1.0)
        return burn

    def burn_rates(self, tenant: str = "",
                   now: Optional[float] = None) -> Dict[str, float]:
        """Fast/slow-window burn rates for one tenant ('' = all
        traffic)."""
        t = time.monotonic() if now is None else now
        return {"fast": round(self._window_burn(tenant, self.fast_window_s,
                                                t), 4),
                "slow": round(self._window_burn(tenant, self.slow_window_s,
                                                t), 4)}

    def alerting(self, tenant: str = "",
                 now: Optional[float] = None) -> bool:
        """Multi-window alert: both windows burning past the threshold.
        Called from the autoscaler loop, so it also drives the fault
        drill and the alert-transition event."""
        t = time.monotonic() if now is None else now
        self._check_fault(t)
        return self._evaluate(tenant, now=t)

    def _evaluate(self, tenant: str, now: float) -> bool:
        if self.burn_alert <= 0:
            return False
        rates = self.burn_rates(tenant, now=now)
        alert = (rates["fast"] >= self.burn_alert
                 and rates["slow"] >= self.burn_alert)
        with self._lock:
            was = self._alerting.get(tenant, False)
            self._alerting[tenant] = alert
            if alert and not was:
                self._counters["alerts"] += 1
        if alert and not was:
            # lazily: obs may be disarmed (no-op) or armed into the
            # plane's fleet trace — the alert is then merge-visible
            from . import count, event
            event("slo.alert", tenant=tenant, fast=rates["fast"],
                  slow=rates["slow"])
            count("slo.alerts")
        return alert

    def should_shed(self, tenant: str = "",
                    now: Optional[float] = None) -> bool:
        """Admission-ladder signal: shed new non-urgent work while the
        burn exceeds ``RACON_TPU_SLO_SHED_BURN`` on both windows (0 =
        shedding disabled)."""
        if self.shed_burn <= 0:
            return False
        t = time.monotonic() if now is None else now
        rates = self.burn_rates(tenant, now=t)
        shed = (rates["fast"] >= self.shed_burn
                and rates["slow"] >= self.shed_burn)
        if shed:
            with self._lock:
                self._counters["shed"] += 1
        return shed

    # -- export ------------------------------------------------------------

    def tenants(self):
        with self._lock:
            return sorted({ten for _, ten, _ in self._events})

    def snapshot(self, now: Optional[float] = None) -> dict:
        """JSON-ready engine state for the ``metrics`` wire op, the
        Prometheus exposition, stats, and bench stamps."""
        t = time.monotonic() if now is None else now
        per = {}
        for ten in self.tenants():
            rates = self.burn_rates(ten, now=t)
            per[ten] = {"burn": rates,
                        "target_s": self.target_for(ten),
                        "alerting": self._alerting.get(ten, False)}
        with self._lock:
            counters = dict(self._counters)
            forced = t < self._forced_until
        return {
            "objectives": {"availability": self.availability,
                           "latency_s": dict(sorted(self.targets.items()))},
            "windows_s": {"fast": self.fast_window_s,
                          "slow": self.slow_window_s},
            "burn_alert": self.burn_alert,
            "shed_burn": self.shed_burn,
            "overall": {"burn": self.burn_rates("", now=t),
                        "alerting": self._alerting.get("", False)},
            "tenants": per,
            "counters": counters,
            "forced": forced,
        }


# -- process-global engine --------------------------------------------------
# One engine per process: the scheduler feeds it, the plane's autoscaler
# and the admission ladder read it, the metrics op exports it.

_lock = threading.Lock()
_engine: Optional[SLOEngine] = None


def engine() -> SLOEngine:
    global _engine
    with _lock:
        if _engine is None:
            _engine = SLOEngine()
        return _engine


def reset() -> None:
    """Drop the process engine (tests; knobs are re-read on next use)."""
    global _engine
    with _lock:
        _engine = None
