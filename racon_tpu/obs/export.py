"""Prometheus text-format exposition of the obs metrics registry and
the SLO engine.

Pure formatting over snapshots — no sockets, no clocks, stdlib only —
so it is trivially testable and shared by the two scrape surfaces: the
daemon's ``metrics`` wire op and the optional ``--metrics-port`` HTTP
endpoint (``GET /metrics``).

Mapping (exposition format 0.0.4):

* counter ``served.poa.fleet`` -> ``racon_tpu_served_poa_fleet_total``
* log2 histogram ``span_us.phase.poa`` ->
  ``racon_tpu_span_us_phase_poa_bucket{le="..."}`` (cumulative, with a
  closing ``+Inf``), ``_sum`` and ``_count``
* SLO engine -> ``racon_tpu_slo_burn_rate{tenant="...",window="fast"}``
  gauges, ``racon_tpu_slo_alerting{tenant="..."}`` 0/1, and the
  engine's own counters (``racon_tpu_slo_alerts_total`` etc.)
* extra gauges (queue depth, live workers, ...) ->
  ``racon_tpu_<name>`` gauges
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[\\\"\n]")


def _san(name: str) -> str:
    """A metric-name-safe identifier: dots (our namespace separator)
    and anything else illegal become underscores."""
    return _NAME_RE.sub("_", str(name))


def _label(value) -> str:
    return _LABEL_RE.sub("_", str(value))


def _fmt(v) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


def _histogram_lines(name: str, hist: dict) -> List[str]:
    metric = f"racon_tpu_{_san(name)}"
    lines = [f"# TYPE {metric} histogram"]
    buckets = hist.get("buckets")
    cum = 0
    if isinstance(buckets, dict):
        for bound in sorted(buckets, key=float):
            try:
                cum += int(buckets[bound])
            except (TypeError, ValueError):
                continue
            lines.append(f'{metric}_bucket{{le="{_label(bound)}"}} {cum}')
    count = hist.get("count", cum)
    lines.append(f'{metric}_bucket{{le="+Inf"}} {_fmt(count)}')
    lines.append(f"{metric}_sum {_fmt(hist.get('sum', 0.0))}")
    lines.append(f"{metric}_count {_fmt(count)}")
    return lines


def prometheus_text(metrics: Optional[dict] = None,
                    slo: Optional[dict] = None,
                    gauges: Optional[Dict[str, float]] = None) -> str:
    """Render one scrape: ``metrics`` is an ``obs.snapshot()`` dict (or
    None when the registry is disarmed), ``slo`` an
    ``SLOEngine.snapshot()`` dict, ``gauges`` extra instantaneous
    values.  Always ends with a newline (the format requires it)."""
    lines: List[str] = []
    counters = (metrics or {}).get("counters")
    if isinstance(counters, dict):
        for name in sorted(counters):
            metric = f"racon_tpu_{_san(name)}"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric}_total {_fmt(counters[name])}")
    hists = (metrics or {}).get("histograms")
    if isinstance(hists, dict):
        for name in sorted(hists):
            h = hists[name]
            if isinstance(h, dict):
                lines.extend(_histogram_lines(name, h))
    if isinstance(gauges, dict):
        for name in sorted(gauges):
            v = gauges[name]
            if v is None:
                continue
            metric = f"racon_tpu_{_san(name)}"
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {_fmt(v)}")
    if isinstance(slo, dict):
        lines.extend(_slo_lines(slo))
    return "\n".join(lines) + "\n"


def _slo_lines(slo: dict) -> List[str]:
    lines = ["# TYPE racon_tpu_slo_burn_rate gauge"]
    scopes = [("", slo.get("overall") or {})]
    tenants = slo.get("tenants")
    if isinstance(tenants, dict):
        scopes.extend(sorted(tenants.items()))
    for tenant, state in scopes:
        burn = state.get("burn") if isinstance(state, dict) else None
        if not isinstance(burn, dict):
            continue
        for window in ("fast", "slow"):
            lines.append(
                f'racon_tpu_slo_burn_rate{{tenant="{_label(tenant)}",'
                f'window="{window}"}} {_fmt(burn.get(window, 0.0))}')
    lines.append("# TYPE racon_tpu_slo_alerting gauge")
    for tenant, state in scopes:
        if isinstance(state, dict):
            lines.append(
                f'racon_tpu_slo_alerting{{tenant="{_label(tenant)}"}} '
                f'{1 if state.get("alerting") else 0}')
    objectives = slo.get("objectives")
    if isinstance(objectives, dict) \
            and objectives.get("availability") is not None:
        lines.append("# TYPE racon_tpu_slo_availability_objective gauge")
        lines.append(f"racon_tpu_slo_availability_objective "
                     f"{_fmt(objectives['availability'])}")
    counters = slo.get("counters")
    if isinstance(counters, dict):
        for name in sorted(counters):
            metric = f"racon_tpu_slo_{_san(name)}"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric}_total {_fmt(counters[name])}")
    return lines
