"""In-process metrics registry: named counters + log2-bucket histograms.

Names are dotted, lowest-cardinality-first (``served.consensus.ls``,
``poa.windows.d8.c512``) so prefix sums give per-phase / per-tier
rollups without a query language.  Everything is integer-or-float plain
data; ``snapshot()`` is JSON-ready for embedding in ``RunReport["obs"]``
and in the trace file.
"""

from __future__ import annotations

import math
import threading
from typing import Dict


class Histogram:
    """Count/sum/min/max plus log2 buckets keyed by upper bound.

    Log2 bucketing keeps the bucket count tiny over the value ranges we
    observe (window counts 1..10^5, walls 10µs..10^3s) while still
    separating "one straggler cohort" from "everything is slow"."""

    __slots__ = ("count", "sum", "min", "max", "buckets")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        self.buckets: Dict[str, int] = {}

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if v <= 0:
            key = "0"
        else:
            key = f"{2 ** max(0, math.ceil(math.log2(v))):g}"
        self.buckets[key] = self.buckets.get(key, 0) + 1

    def as_dict(self) -> dict:
        return {"count": self.count, "sum": round(self.sum, 6),
                "min": self.min, "max": self.max,
                "buckets": dict(self.buckets)}


def hist_quantile(hist: dict, q: float):
    """Quantile estimate from a snapshotted log2 histogram dict
    (``Histogram.as_dict()`` shape), with linear interpolation inside
    the winning bucket.

    The cumulative count crosses ``q`` somewhere inside one log2 bucket
    ``(lo, hi]`` (``lo = hi/2`` for ``hi >= 2``; the "1" bucket covers
    ``(0, 1]``).  The old estimator returned ``hi``, so a p99 gate
    jumped in 2x steps; interpolating the crossing fraction into the
    bucket keeps the estimate inside the same bucket (so the error is
    still bounded by the bucket width) while moving smoothly with the
    data.  The result is clamped to the observed ``[min, max]``.
    Returns None for an empty/malformed histogram."""
    try:
        total = int(hist["count"])
        buckets = hist["buckets"]
    except (KeyError, TypeError, ValueError):
        return None
    if total <= 0 or not isinstance(buckets, dict) or not buckets:
        return None
    need = max(1, math.ceil(q * total))
    seen = 0
    for bound in sorted(buckets, key=float):
        n = int(buckets[bound])
        if seen + n >= need:
            hi = float(bound)
            if not hi:
                return 0.0          # the "0" bucket holds only <=0 values
            lo = hi / 2.0 if hi >= 2.0 else 0.0
            frac = (need - seen) / n
            v = lo + frac * (hi - lo)
            hmin, hmax = hist.get("min"), hist.get("max")
            if hmax is not None:
                v = min(v, float(hmax))
            if hmin is not None:
                v = max(v, float(hmin))
            return v
        seen += n
    return hist.get("max")


class Metrics:
    """Thread-safe registry.  Counter and histogram namespaces are
    disjoint by convention (a name is one or the other)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._hists: Dict[str, Histogram] = {}

    def count(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def prefix_sum(self, prefix: str) -> int:
        """Sum of every counter whose name starts with ``prefix`` —
        the rollup behind the served-sum invariant."""
        with self._lock:
            return sum(v for k, v in self._counters.items()
                       if k.startswith(prefix))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(sorted(self._counters.items())),
                "histograms": {k: h.as_dict()
                               for k, h in sorted(self._hists.items())},
            }
