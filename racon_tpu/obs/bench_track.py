"""Bench-history tracker: trend deltas + regression gate over the
committed benchmark trajectory.

Two durable series exist in the repo:

* ``BENCH_r<N>.json`` — one wrapper per PR round ({n, cmd, rc, tail,
  parsed}); ``parsed`` holds the bench.py one-line JSON entry.
* ``docs/device_bench_log.jsonl`` — one bench/golden entry per line,
  appended by ``bench.py log_device_measurement`` on healthy-device runs.

Every entry passes through ``bench.normalize_entry`` (the reader-side
honesty backfill) so pre-observability generations parse identically:
old ``vs_baseline: 0.0`` dead-tunnel lines become ``null`` +
``device_status: "unreachable"``, ``phase_wall`` is derived from the
embedded report when the explicit stamp is missing, and ``cost_model``
backfills ``null``.  Entries are then grouped into comparable series
(same workload shape + device status + kernel tier — a host-only round
is never compared against a device measurement), and the newest entry
in each series is gated against its predecessor:

* headline throughput (``value``) dropping more than ``threshold``;
* ``vs_baseline`` dropping more than ``threshold``;
* any per-phase wall (``phase_wall``) growing more than ``threshold``
  (and more than ``min_delta_s``, to filter noise on tiny runs).

Exit codes mirror the trace-diff CLI: 0 clean, 2 unreadable history,
3 regression.  Stdlib-only except for the ``bench`` import, which is
optional (a vendored fallback keeps the module usable when the repo-root
script is absent, e.g. installed layouts).
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_BENCH_ROUND = re.compile(r"BENCH_r(\d+)\.json$")


def _normalize(e: dict) -> dict:
    """bench.normalize_entry when the repo-root script is importable,
    else a minimal vendored equivalent (same semantics for the fields
    this tracker reads)."""
    try:
        if _REPO_ROOT not in sys.path:
            sys.path.insert(0, _REPO_ROOT)
        import bench
        return bench.normalize_entry(e)
    except Exception:  # noqa: BLE001 — installed layout without bench.py
        if not isinstance(e, dict):
            return e
        if (e.get("device_status") == "unreachable"
                or "TPU UNREACHABLE" in str(e.get("metric", ""))):
            e = dict(e, device_status="unreachable")
            if e.get("vs_baseline") == 0.0:
                e["vs_baseline"] = None
        if "cost_model" not in e:
            e = dict(e, cost_model=None)
        if "serial_steps" not in e:
            cm = e.get("cost_model")
            ss = ({ph: row["serial_steps"]
                   for ph, row in cm.get("phases", {}).items()
                   if isinstance(row, dict) and "serial_steps" in row}
                  if isinstance(cm, dict) else None)
            e = dict(e, serial_steps=ss or None)
        return e


def load_history(root: str = _REPO_ROOT,
                 extra_paths: Optional[List[str]] = None
                 ) -> Tuple[List[dict], List[str]]:
    """All throughput entries, oldest first, normalized.  Returns
    (entries, problems); a malformed committed file is a *problem*
    (exit-2 material), a malformed hand-edited log *line* just skips —
    same tolerance bench.py itself applies to the log."""
    entries: List[dict] = []
    problems: List[str] = []

    rounds = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")),
                    key=lambda p: int(_BENCH_ROUND.search(p).group(1))
                    if _BENCH_ROUND.search(p) else 0)
    for path in rounds:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f"{path}: {e}")
            continue
        parsed = doc.get("parsed") if isinstance(doc, dict) else None
        if isinstance(parsed, dict) and "value" in parsed:
            entries.append(dict(_normalize(parsed),
                                _source=os.path.basename(path)))

    log = os.path.join(root, "docs", "device_bench_log.jsonl")
    if os.path.exists(log):
        try:
            with open(log) as f:
                for i, line in enumerate(f, 1):
                    if not line.strip():
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        continue  # hand-editable log: skip, don't hide
                    if isinstance(e, dict) and "value" in e \
                            and not e.get("forced"):
                        entries.append(dict(_normalize(e),
                                            _source=f"device_log:{i}"))
        except OSError as e:
            problems.append(f"{log}: {e}")

    for path in extra_paths or []:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            problems.append(f"{path}: {e}")
            continue
        if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
            doc = doc["parsed"]   # BENCH_r-style wrapper accepted too
        if isinstance(doc, dict) and "value" in doc:
            entries.append(dict(_normalize(doc),
                                _source=os.path.basename(path)))
        else:
            problems.append(f"{path}: no 'value' field — not a bench entry")
    return entries, problems


def series_key(e: dict) -> str:
    """Comparable-series key: workload shape + how it was served.  A
    host-only (dead tunnel) round and a device measurement are different
    experiments — the gate must never diff one against the other."""
    status = e.get("device_status") or "device"
    return "|".join(str(e.get(k, "?")) for k in
                    ("unit", "mbp", "input", "profile")) + \
        f"|{status}|{e.get('kernel', '?')}" + \
        ("|sanitize" if e.get("sanitize") else "")


def _pct(new: float, old: float) -> float:
    return 100.0 * (new - old) / old if old else float("inf")


def trend(entries: List[dict], threshold: float = 0.25,
          min_delta_s: float = 0.05) -> dict:
    """Group into series, compute consecutive deltas, gate the newest
    entry of each series against its predecessor."""
    series: Dict[str, List[dict]] = {}
    for e in entries:
        series.setdefault(series_key(e), []).append(e)

    out = {"series": [], "regressions": []}
    for key, ents in series.items():
        deltas = []
        for prev, cur in zip(ents, ents[1:]):
            d = {"from": prev.get("_source"), "to": cur.get("_source"),
                 "value": [prev.get("value"), cur.get("value")],
                 "value_pct": None, "phase_pct": {}}
            pv, cv = prev.get("value"), cur.get("value")
            if isinstance(pv, (int, float)) and pv \
                    and isinstance(cv, (int, float)):
                d["value_pct"] = round(_pct(cv, pv), 1)
            ppw, cpw = prev.get("phase_wall"), cur.get("phase_wall")
            if isinstance(ppw, dict) and isinstance(cpw, dict):
                for phase in sorted(set(ppw) | set(cpw)):
                    o, n = ppw.get(phase), cpw.get(phase)
                    if isinstance(o, (int, float)) and o \
                            and isinstance(n, (int, float)):
                        d["phase_pct"][phase] = round(_pct(n, o), 1)
            deltas.append(d)
        out["series"].append({"key": key, "n": len(ents),
                              "sources": [e.get("_source") for e in ents],
                              "values": [e.get("value") for e in ents],
                              "deltas": deltas})
        if len(ents) < 2:
            continue
        prev, cur = ents[-2], ents[-1]
        src = f"{prev.get('_source')} -> {cur.get('_source')}"
        pv, cv = prev.get("value"), cur.get("value")
        if isinstance(pv, (int, float)) and pv > 0 \
                and isinstance(cv, (int, float)) \
                and cv < pv * (1.0 - threshold):
            out["regressions"].append(
                f"[{key}] value: {pv} -> {cv} Mbp/s "
                f"({_pct(cv, pv):+.0f}%, threshold "
                f"-{threshold * 100:.0f}%) {src}")
        pb, cb = prev.get("vs_baseline"), cur.get("vs_baseline")
        if isinstance(pb, (int, float)) and pb > 0 \
                and isinstance(cb, (int, float)) \
                and cb < pb * (1.0 - threshold):
            out["regressions"].append(
                f"[{key}] vs_baseline: {pb} -> {cb} "
                f"({_pct(cb, pb):+.0f}%) {src}")
        ppw, cpw = prev.get("phase_wall"), cur.get("phase_wall")
        if isinstance(ppw, dict) and isinstance(cpw, dict):
            for phase in sorted(set(ppw) & set(cpw)):
                o, n = ppw[phase], cpw[phase]
                if isinstance(o, (int, float)) and o > 0 \
                        and isinstance(n, (int, float)) \
                        and n > o * (1.0 + threshold) \
                        and (n - o) > min_delta_s:
                    out["regressions"].append(
                        f"[{key}] phase_wall.{phase}: {o}s -> {n}s "
                        f"({_pct(n, o):+.0f}%) {src}")
    return out


def render(result: dict) -> str:
    lines = []
    for s in result["series"]:
        vals = " -> ".join("?" if v is None else f"{v:g}"
                           for v in s["values"])
        lines.append(f"series [{s['key']}]  n={s['n']}")
        lines.append(f"  value: {vals}")
        for d in s["deltas"]:
            pcts = "" if d["value_pct"] is None else f"{d['value_pct']:+g}%"
            ph = "  ".join(f"{k}:{v:+g}%" for k, v in d["phase_pct"].items())
            lines.append(f"    {d['from']} -> {d['to']}: {pcts}"
                         f"{('  phases: ' + ph) if ph else ''}")
    if result["regressions"]:
        for r in result["regressions"]:
            lines.append(f"REGRESSION: {r}")
    else:
        lines.append("no regression in any series")
    return "\n".join(lines)
