"""Thread-safe span tracer emitting Chrome-trace ("Trace Event Format")
JSON, loadable in Perfetto / chrome://tracing.

Design constraints (mirrored by tests/test_obs.py):

* **Monotonic clock only.**  Span math uses ``time.monotonic_ns()``;
  a wall-clock (``time.time``) span goes negative across an NTP step.
  The ``wall-clock`` lint rule (analysis/rules/clock.py) scopes this
  package, so a regression is a lint failure, not a code review hope.
* **Bounded memory.**  The event buffer is capped; past the cap events
  are counted as dropped (surfaced in the written trace) instead of
  growing without bound on pathological runs.
* **No data dependence.**  The tracer observes timing only — it never
  touches sequences, CIGARs, or consensus bytes, which is what makes
  the armed-vs-disarmed byte-identity guarantee trivial to keep.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import List, Optional


class Span:
    """One timed region, used as a context manager.

    Records a Chrome-trace complete ("ph":"X") event on exit; ``set()``
    attaches key/value args that show up in the Perfetto detail pane.
    An exception escaping the body is recorded as an ``error`` arg so a
    trace of a degraded run shows *where* the lattice demoted."""

    __slots__ = ("_tracer", "name", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args: dict):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = 0

    def set(self, **attrs) -> "Span":
        self.args.update(attrs)
        return self

    def __enter__(self) -> "Span":
        self._t0 = time.monotonic_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self._tracer.add_complete(self.name, self._t0, time.monotonic_ns(),
                                  **self.args)
        return False


class _NullSpan:
    """The disarmed span: a shared, allocation-free no-op so tracing-off
    call sites cost one attribute load + identity return."""

    __slots__ = ()

    def set(self, **attrs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


#: Singleton handed out by ``obs.span()`` when tracing is disarmed.
NULL_SPAN = _NullSpan()


class Tracer:
    """In-memory trace-event buffer.  All mutation happens under one
    lock, so spans opened from watchdog threads, the native callback
    thread, or test thread pools interleave safely."""

    def __init__(self, max_events: int = 200_000):
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._thread_names = {}   # tid -> python thread name ("M" events)
        self.dropped = 0
        self._max = max_events
        #: Optional ``(name, dur_us) -> None`` callback fired for every
        #: complete event — even past the buffer cap, so the span_us.*
        #: duration histograms stay exact when the timeline is truncated.
        self.on_complete = None
        # Event timestamps are offsets from tracer creation so traces
        # start near ts=0 regardless of the monotonic clock's epoch.
        self._t0 = time.monotonic_ns()
        self.pid = os.getpid()
        #: Cross-process provenance, stamped by ``obs.configure`` from
        #: ``obs.context`` / ``obs.set_role``.  ``role`` names this
        #: process's track in a merged timeline ("coordinator",
        #: "worker0", …); trace_id/parent_span tie its spans to the
        #: fleet-wide trace context.
        self.role: Optional[str] = None
        self.trace_id: Optional[str] = None
        self.parent_span: Optional[str] = None
        # Events absorbed from other processes' shipments (already
        # re-based onto this tracer's clock) + their metadata events.
        self._foreign: List[dict] = []
        self._foreign_meta: List[dict] = []

    @property
    def t0_ns(self) -> int:
        """Monotonic epoch of this tracer's ts=0 — CLOCK_MONOTONIC is
        system-wide on Linux, so two same-host tracers re-base each
        other's events via the difference of their epochs."""
        return self._t0

    def _ts_us(self, t_ns: int) -> int:
        # Clamp at the epoch: a span on a concurrent thread (e.g. an rpc
        # handler) may have *started* before this tracer was re-armed for
        # the current trace file, so its start predates t0.  Pinning it
        # to ts=0 keeps every emitted event schema-valid (ts >= 0).
        return max(0, (t_ns - self._t0) // 1000)

    def _append(self, ev: dict) -> None:
        tid = threading.get_ident()
        ev["pid"] = self.pid
        ev["tid"] = tid
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            if len(self._events) >= self._max:
                self.dropped += 1
                return
            self._events.append(ev)

    def add_complete(self, name: str, t0_ns: int, t1_ns: int,
                     cat: str = "span", **args) -> None:
        """Record a finished region [t0_ns, t1_ns] (monotonic_ns stamps).
        Exposed directly (not only via Span) so call sites that detect an
        interesting region *after the fact* — e.g. a kernel-cache miss —
        can stamp it retroactively."""
        dur = max(0, (t1_ns - t0_ns) // 1000)
        self._append({"name": name, "cat": cat, "ph": "X",
                      "ts": self._ts_us(t0_ns), "dur": dur,
                      "args": args})
        cb = self.on_complete
        if cb is not None:
            cb(name, dur)

    def add_instant(self, name: str, cat: str = "event", **args) -> None:
        """Record a point event (lattice demotion, watchdog timeout, …)."""
        self._append({"name": name, "cat": cat, "ph": "i", "s": "t",
                      "ts": self._ts_us(time.monotonic_ns()),
                      "args": args})

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    # -- cross-process shipping -------------------------------------------
    def export(self, max_events: Optional[int] = None,
               metrics: Optional[dict] = None) -> dict:
        """A JSON-ready shipment of this process's span buffer: the last
        ``max_events`` events (newest win — the tail is where the crash
        or the result lives), thread names, and the clock epoch a peer
        needs to re-base them.  Bounded so a shipment always fits the
        wire's one-line message limit."""
        with self._lock:
            events = list(self._events)
            names = dict(self._thread_names)
            dropped = self.dropped
        if max_events is not None and len(events) > max_events:
            dropped += len(events) - max_events
            events = events[-max_events:]
        ship = {
            "pid": self.pid,
            "t0_mono_ns": self._t0,
            "role": self.role,
            "trace_id": self.trace_id,
            "dropped": dropped,
            "thread_names": {str(t): n for t, n in names.items()},
            "events": events,
        }
        if metrics is not None:
            ship["metrics"] = metrics
        return ship

    def ingest(self, ship: dict) -> int:
        """Absorb a peer process's ``export()``: re-base its timestamps
        onto this tracer's clock (same-host monotonic epochs) and keep
        its pid/tid stamps so the merged file renders one track per
        process.  Malformed shipments are dropped whole — a worker's
        trace must never corrupt the coordinator's.  Returns the number
        of events absorbed."""
        if not isinstance(ship, dict):
            return 0
        events = ship.get("events")
        if not isinstance(events, list):
            return 0
        try:
            dt_us = (int(ship["t0_mono_ns"]) - self._t0) // 1000
            pid = int(ship["pid"])
        except (KeyError, TypeError, ValueError):
            return 0
        absorbed = []
        for ev in events:
            if not isinstance(ev, dict) or "ts" not in ev:
                continue
            ev = dict(ev)
            try:
                ev["ts"] = max(0, int(ev["ts"]) + dt_us)
                ev["pid"] = int(ev.get("pid", pid))
                ev["tid"] = int(ev.get("tid", 0))
            except (TypeError, ValueError):
                continue
            absorbed.append(ev)
        meta = []
        role = ship.get("role")
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "tid": 0, "args": {"name": role or f"pid{pid}"}})
        tnames = ship.get("thread_names")
        if isinstance(tnames, dict):
            for t, n in sorted(tnames.items()):
                try:
                    meta.append({"name": "thread_name", "ph": "M",
                                 "pid": pid, "tid": int(t),
                                 "args": {"name": str(n)}})
                except (TypeError, ValueError):
                    continue
        try:
            foreign_dropped = int(ship.get("dropped", 0))
        except (TypeError, ValueError):
            foreign_dropped = 0
        with self._lock:
            self._foreign.extend(absorbed)
            self._foreign_meta.extend(meta)
            self.dropped += foreign_dropped
        return len(absorbed)

    def to_dict(self, metrics: Optional[dict] = None,
                platform: Optional[str] = None) -> dict:
        """The full Chrome-trace JSON object.  Extra top-level keys are
        ignored by Perfetto, so the metrics snapshot and provenance ride
        along in the same file the timeline lives in."""
        with self._lock:
            events = list(self._events) + list(self._foreign)
            names = dict(self._thread_names)
            meta = list(self._foreign_meta)
            dropped = self.dropped
        events.append({"name": "process_name", "ph": "M", "pid": self.pid,
                       "tid": 0,
                       "args": {"name": self.role or "racon-tpu"}})
        for tid, tname in sorted(names.items()):
            events.append({"name": "thread_name", "ph": "M", "pid": self.pid,
                           "tid": tid, "args": {"name": tname}})
        events.extend(meta)
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"tool": "racon_tpu.obs", "clock": "monotonic",
                          "dropped_events": dropped,
                          "pid": self.pid,
                          "t0_monotonic_ns": self._t0},
        }
        if self.role:
            doc["otherData"]["role"] = self.role
        if self.trace_id:
            doc["otherData"]["trace_id"] = self.trace_id
            if self.parent_span:
                doc["otherData"]["parent_span"] = self.parent_span
        if platform:
            # lets `obs validate --profile auto` pick the right machine
            # profile without re-importing the backend
            doc["otherData"]["platform"] = platform
        if metrics is not None:
            doc["racon_tpu"] = {"metrics": metrics}
        return doc

    def write(self, path: str, metrics: Optional[dict] = None,
              platform: Optional[str] = None) -> None:
        tmp = f"{path}.tmp.{self.pid}"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(metrics, platform=platform), f)
            f.write("\n")
        os.replace(tmp, path)
