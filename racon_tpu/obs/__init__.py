"""Unified observability: span tracing + metrics for the polish pipeline.

One module-level armed/disarmed switch feeds two sinks:

* a **span tracer** (tracer.Tracer) producing Chrome-trace/Perfetto JSON
  — nested phase spans, per-bucket POA batches, align cohorts, journal
  replays, kernel builds, plus instant events for lattice retries /
  demotions / quarantines and watchdog timeouts;
* a **metrics registry** (metrics.Metrics) — counters and histograms
  keyed by phase, serving tier, and bucket class.  ``served.*`` counters
  are incremented inside ``PhaseReport.record_served`` itself, so the
  served-sum invariant between the metrics and the run report is checked
  (``served_sum_check``), not assumed.

Arming: ``obs.configure(trace_path=...)`` (the polisher constructors call
it after ``obs.reset()``), the CLI ``--trace`` flag, or the
``RACON_TPU_TRACE`` / ``RACON_TPU_METRICS`` knobs.  Disarmed, every hook
is a no-op: ``span()`` returns a shared null singleton and ``count()`` /
``event()`` are a None-check — polish output stays byte-identical and no
trace file is written (regression-tested in tests/test_obs.py).

Imports stay stdlib + config so this module is loadable from anywhere in
the stack (kernel_cache, resilience, tools) without cycles or a jax
dependency; the optional ``jax.profiler`` device capture imports jax
lazily and only when armed on a TPU backend.
"""

from __future__ import annotations

import collections
import sys
import threading
import time
from typing import Optional

from .. import config
from . import context, flight
from .metrics import Metrics
from .tracer import NULL_SPAN, Span, Tracer

ENV_TRACE = "RACON_TPU_TRACE"
ENV_METRICS = "RACON_TPU_METRICS"
ENV_TRACE_DEVICE = "RACON_TPU_TRACE_DEVICE"
ENV_SHIP_EVENTS = "RACON_TPU_OBS_SHIP_EVENTS"
ENV_TELEMETRY_RING = "RACON_TPU_TELEMETRY_RING"

#: The five pipeline phases every polish decomposes into, in execution
#: order.  Span names are ``phase.<name>``; the CLI breakdown and the
#: CI trace validation key off this tuple.
PHASES = ("parse", "align", "window_assign", "poa", "stitch")

_lock = threading.Lock()
_tracer: Optional[Tracer] = None
_metrics: Optional[Metrics] = None
_trace_path: Optional[str] = None
_device_tracing = False

# Process role ("coordinator", "worker0", "serve", …) for the merged
# fleet timeline.  Survives reset() on purpose: a process keeps its
# identity across every run it hosts, exactly like its pid.
_role: Optional[str] = None

# Live-telemetry ring: periodic gauge snapshots (queue depth, in-flight
# leases, …) scraped through the serve/distrib 'stats' wire verb.
# Survives reset() — it is per-process history, not per-run state.
_telemetry_lock = threading.Lock()
_telemetry = None


# -- arming ----------------------------------------------------------------

def reset() -> None:
    """Disarm and drop all collected state (called per run by the
    polisher constructors, before ``configure``).  A device trace left
    running by a crashed run is stopped first.  The flight recorder,
    process role, trace context, and telemetry ring survive — they are
    process identity/history, not per-run trace state."""
    global _tracer, _metrics, _trace_path
    maybe_stop_device_trace()
    with _lock:
        _tracer = None
        _metrics = None
        _trace_path = None


def configure(trace_path: Optional[str] = None,
              metrics: Optional[bool] = None) -> None:
    """Arm for one run.  Explicit arguments (the CLI flags) win; ``None``
    falls back to the ``RACON_TPU_TRACE`` / ``RACON_TPU_METRICS`` knobs.
    Tracing implies metrics (the snapshot rides inside the trace file);
    ``RACON_TPU_METRICS=1`` alone collects spans + counters in memory for
    the ``RunReport["obs"]`` snapshot without writing a trace file.

    Idempotent per destination: re-arming with the trace path already
    armed keeps the collected spans (the serve session re-enters
    ``reset``/``configure`` per job; the distrib coordinator arms once
    per ``run()``).  Arming a *different* path swaps in a fresh tracer,
    so a second in-process run can never append spans into the previous
    run's file — the scoped teardown (``release()``) plus this check is
    the regression surface tests/test_obs.py pins."""
    global _tracer, _metrics, _trace_path
    if trace_path is None:
        trace_path = config.get_str(ENV_TRACE) or None
    if metrics is None:
        metrics = config.get_bool(ENV_METRICS)
    if not trace_path and not metrics:
        return
    with _lock:
        if _tracer is not None and _trace_path == trace_path:
            return
        _trace_path = trace_path
        _tracer = Tracer()
        _metrics = Metrics()
        _tracer.role = _role
        ctx = context.current()
        if ctx is not None:
            _tracer.trace_id = ctx.get("trace_id")
            _tracer.parent_span = ctx.get("parent")
        # every finished span also lands in a span_us.<name> log2
        # histogram, so the CLI breakdown gets p50/p99 per span name
        # even when the bounded event buffer truncated the timeline —
        # and in the flight-recorder ring, so a crash dump carries the
        # span tail too
        m = _metrics
        fl = flight.recorder()
        def _on_complete(name, dur_us, _m=m, _fl=fl):
            _m.observe(f"span_us.{name}", dur_us)
            _fl.span(name, dur_us)
        _tracer.on_complete = _on_complete


def release(write: bool = True) -> Optional[str]:
    """Scoped teardown of one armed run: optionally write the trace,
    then disarm.  The multi-run surfaces (distrib coordinator, serve
    scheduler) call this in a ``finally`` so the process-global tracer
    never outlives the run that armed it."""
    path = write_trace() if write else None
    reset()
    return path


def set_role(role: Optional[str]) -> None:
    """Name this process's track in merged fleet timelines and flight
    dumps ("coordinator", "worker0", "serve", …).  Sticky across
    ``reset()``."""
    global _role
    _role = role
    flight.set_role(role)
    t = _tracer
    if t is not None:
        t.role = role


def role() -> Optional[str]:
    return _role


def enabled() -> bool:
    return _tracer is not None


def tracer() -> Optional[Tracer]:
    """The armed tracer, or None — read-only introspection for tests
    and tools; mutation goes through the hooks below."""
    return _tracer


def trace_path() -> Optional[str]:
    return _trace_path


# -- recording hooks (each a cheap no-op when disarmed) --------------------

def span(name: str, **args):
    """Context manager timing a region; returns the shared null span
    when disarmed so the call site costs one identity return."""
    t = _tracer
    if t is None:
        return NULL_SPAN
    return Span(t, name, args)


def event(name: str, **args) -> None:
    """Instant event (lattice demotion, watchdog timeout, …).  Always
    breadcrumbed into the flight recorder — instant events are exactly
    the rare, high-signal moments a post-mortem needs — and additionally
    recorded on the tracer timeline when armed."""
    flight.record(name, **args)
    t = _tracer
    if t is not None:
        t.add_instant(name, **args)


def add_complete(name: str, t0_ns: int, t1_ns: int, **args) -> None:
    """Retroactive span from raw monotonic_ns stamps (kernel-cache miss
    detection times the call first, then learns it was a compile)."""
    t = _tracer
    if t is not None:
        t.add_complete(name, t0_ns, t1_ns, **args)


def count(name: str, n: int = 1) -> None:
    m = _metrics
    if m is not None:
        m.count(name, n)


def observe(name: str, value: float) -> None:
    m = _metrics
    if m is not None:
        m.observe(name, value)


# -- cross-process span shipping -------------------------------------------

def shipment(max_events: Optional[int] = None) -> Optional[dict]:
    """Bounded, JSON-ready export of this process's span buffer +
    metrics snapshot, shipped with a distrib chunk / serve job result so
    the coordinator can fold it into the merged fleet trace.  None when
    disarmed — a disarmed worker ships nothing and the wire field stays
    absent."""
    t = _tracer
    if t is None:
        return None
    if max_events is None:
        max_events = max(1, config.get_int(ENV_SHIP_EVENTS))
    return t.export(max_events=max_events, metrics=snapshot())


def absorb(ship) -> int:
    """Fold a peer process's ``shipment()`` into this process's armed
    tracer (timestamps re-based, pid tracks preserved).  No-op when
    disarmed or the shipment is absent/malformed; returns the number of
    events absorbed."""
    t = _tracer
    if t is None or not isinstance(ship, dict):
        return 0
    return t.ingest(ship)


# -- live telemetry ----------------------------------------------------------

def telemetry_tick(**gauges) -> dict:
    """Append one gauge snapshot (queue depth, in-flight leases, …) to
    the process's bounded telemetry ring and return it.  Armed or not —
    telemetry is scrape-state for the 'stats' wire verb, not trace
    output — but when metrics are armed the per-phase served totals ride
    along so a poller watches serving progress live."""
    global _telemetry
    entry = {"t_mono_ns": time.monotonic_ns()}
    entry.update(gauges)
    # every tick carries the process RSS: memory is the gauge that
    # matters when the budget watchdog (resilience/budget.py) is the
    # thing a poller wants to see approaching its watermarks
    from ..resilience import budget as _budget
    entry["mem.rss_mb"] = round(_budget.rss_mb(), 1)
    m = _metrics
    if m is not None:
        entry["served_total"] = m.prefix_sum("served.")
    with _telemetry_lock:
        if _telemetry is None:
            _telemetry = collections.deque(
                maxlen=max(1, config.get_int(ENV_TELEMETRY_RING)))
        _telemetry.append(entry)
    return entry


def telemetry(last: Optional[int] = None) -> list:
    """The telemetry ring, oldest first (optionally just the last N)."""
    with _telemetry_lock:
        items = [] if _telemetry is None else list(_telemetry)
    return items[-last:] if last else items


# -- snapshots & invariants ------------------------------------------------

def snapshot() -> Optional[dict]:
    """JSON-ready metrics snapshot, or None when disarmed."""
    m = _metrics
    return None if m is None else m.snapshot()


def counter_total(prefix: str) -> int:
    """Sum of every counter whose name starts with ``prefix`` (0 when
    metrics are disarmed).  The serve session reads
    ``counter_total("kernel.builds.")`` after each job to prove the
    hot-kernel invariant: jobs 2..N on a resident process build nothing."""
    m = _metrics
    return 0 if m is None else m.prefix_sum(prefix)


def served_sum_check(phases) -> dict:
    """Cross-check the ``served.<phase>.<tier>`` counters against each
    ``PhaseReport``'s served totals.  The counters are fed from
    ``record_served`` itself, so a mismatch means some code path served
    work while bypassing the report (or vice versa) — exactly the drift
    this layer exists to catch.

    ``phases`` is the ``RunReport.phases`` mapping; returns
    ``{phase: {"report": n, "metrics": n, "ok": bool}}``."""
    m = _metrics
    if m is None:
        return {}
    out = {}
    for name, rep in phases.items():
        counted = m.prefix_sum(f"served.{name}.")
        total = rep.served_total()
        out[name] = {"report": total, "metrics": counted,
                     "ok": counted == total}
    return out


# -- export ----------------------------------------------------------------

def _platform() -> Optional[str]:
    """Backend platform for the trace provenance stamp.  Reads jax only
    when the run already imported it (a traced polish always has) — this
    module must stay importable, and write_trace callable, without a jax
    dependency."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 — provenance only, never fail a write
        return None


def write_trace() -> Optional[str]:
    """Write the Chrome-trace JSON (metrics snapshot embedded) to the
    configured path.  Returns the path written, or None when tracing is
    disarmed or armed metrics-only.  A write failure warns — a full disk
    must not fail the polish that just finished."""
    t, path = _tracer, _trace_path
    if t is None or not path:
        return None
    try:
        t.write(path, metrics=snapshot(), platform=_platform())
    except OSError as e:
        print(f"[racon_tpu::obs] WARNING: cannot write trace {path}: {e}",
              file=sys.stderr)
        return None
    return path


# -- optional jax.profiler device capture ----------------------------------

def maybe_start_device_trace() -> bool:
    """Best-effort ``jax.profiler`` device trace next to the host trace
    (``<trace_path>.device/``), gated on ``RACON_TPU_TRACE_DEVICE=1`` and
    an actual TPU backend — on CPU/GPU the host spans already tell the
    whole story.  Any failure degrades to host-only tracing."""
    global _device_tracing
    if _trace_path is None or _device_tracing:
        return False
    if not config.get_bool(ENV_TRACE_DEVICE):
        return False
    try:
        import jax

        if jax.devices()[0].platform != "tpu":
            return False
        jax.profiler.start_trace(f"{_trace_path}.device")
    except Exception as e:  # noqa: BLE001 — never fail a polish for this
        print(f"[racon_tpu::obs] WARNING: device trace unavailable "
              f"({type(e).__name__}: {e}); continuing host-only",
              file=sys.stderr)
        return False
    _device_tracing = True
    return True


def maybe_stop_device_trace() -> None:
    global _device_tracing
    if not _device_tracing:
        return
    _device_tracing = False
    try:
        import jax

        jax.profiler.stop_trace()
    except Exception as e:  # noqa: BLE001
        print(f"[racon_tpu::obs] WARNING: device trace stop failed "
              f"({type(e).__name__}: {e})", file=sys.stderr)
