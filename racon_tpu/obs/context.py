"""Trace-context propagation for the multi-process surfaces.

A *trace context* is two hex tokens — a fleet-wide ``trace_id`` minted
once per coordinator/daemon run, and a ``parent`` span id minted per
dispatch — that ride the serve/distrib newline-JSON wire (the
``trace`` payload field of ``distrib.fetch`` / ``serve.submit``) so a
worker's spans can be causally parented under the coordinator's
dispatch event in the merged timeline.

The current context is process-global and deliberately lives *outside*
``obs`` arming state: ``obs.reset()`` (called by every polisher
constructor via ``reset_run_state``) must not clear it, because a
distrib worker activates the context *before* building the per-chunk
polisher.  ``obs.configure`` reads ``current()`` and stamps the ids
onto the tracer, which embeds them in every exported event's args and
in the trace file's provenance block.

Ids are random (``os.urandom``), not time-derived, so two processes
started in the same tick cannot collide and replaying a journal cannot
alias an old trace.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

_lock = threading.Lock()
_current: Optional[dict] = None


def mint_trace_id() -> str:
    """64-bit random hex — one per fleet run."""
    return os.urandom(8).hex()


def mint_span_id() -> str:
    """32-bit random hex — one per dispatch/submit span."""
    return os.urandom(4).hex()


def fresh() -> dict:
    """A new root context (coordinator/daemon side)."""
    return {"trace_id": mint_trace_id(), "parent": None}


def child(ctx: Optional[dict]) -> Optional[dict]:
    """Derive the context shipped with one dispatch: same trace id, a
    fresh parent span id naming the dispatch event.  None stays None so
    disarmed runs ship no context at all."""
    if not ctx or not ctx.get("trace_id"):
        return None
    return {"trace_id": ctx["trace_id"], "parent": mint_span_id()}


def activate(ctx: Optional[dict]) -> None:
    """Install ``ctx`` as this process's current trace context (worker
    side, from the wire; coordinator side, from ``fresh()``).  Passing a
    malformed dict deactivates instead of half-installing."""
    global _current
    ok = (isinstance(ctx, dict)
          and isinstance(ctx.get("trace_id"), str) and ctx["trace_id"])
    with _lock:
        _current = ({"trace_id": ctx["trace_id"],
                     "parent": ctx.get("parent")} if ok else None)


def clear() -> None:
    global _current
    with _lock:
        _current = None


def current() -> Optional[dict]:
    with _lock:
        return dict(_current) if _current else None
