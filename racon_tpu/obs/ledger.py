"""Per-job latency ledger: stage-level accounting of where a serve job's
wall time went.

Every job the scheduler admits gets one :class:`JobLedger`.  The
control-plane side records **monotonic stage stamps** (submit, admit,
dispatch, finish, result-ship) as the job moves through the scheduler
and (when the fleet plane is attached) the dispatch machinery; the
compute side — the in-process session or a distrib worker — reports
**per-stage durations** (parse/align/window_assign/poa/stitch plus
journal replay and kernel builds) derived from its run report, shipped
back over the existing ``stats`` field of the result wire message.

The two sides compose without clock negotiation: stamps are
``time.monotonic_ns()`` and CLOCK_MONOTONIC is system-wide on Linux, so
cross-process stamps share an epoch — the same property ``obs merge``
and ``Tracer.ingest`` re-base on.  Worker durations are *relative*
(seconds), so they need no re-basing at all.

The finalized ledger is a plain JSON-ready dict persisted into the
job's ``result.json``, surfaced in ``RunReport["ledger"]``, and fed to
the per-tenant SLO engine (``obs/slo.py``).  Like the tracer, the
ledger observes timing only — it never touches sequences or consensus
bytes, so polished output is byte-identical with or without it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

#: Canonical stage order of the ledger's ``stage_s`` decomposition.
#: ``queue``/``dispatch``/``result_ship`` are derived from the
#: control-plane stamps; the rest are compute durations reported by the
#: session/worker.  ``journal_replay``/``kernel_build`` overlap the
#: compute phases they occur inside (replay substitutes for align/poa
#: work; builds happen within align/poa batches), so sums over STAGES
#: must exclude them — ``attributed_s`` below does.
STAGES = ("queue", "dispatch", "journal_replay", "kernel_build",
          "parse", "align", "window_assign", "poa", "stitch",
          "result_ship")

#: Stages whose durations are additive pieces of the job wall.
_ADDITIVE = ("queue", "dispatch", "parse", "align", "window_assign",
             "poa", "stitch", "result_ship")

#: run-report phase name -> ledger stage name (the report uses racon's
#: phase vocabulary; the ledger uses obs.PHASES vocabulary).
_REPORT_STAGES = {"parse": "parse", "alignment": "align",
                  "window_assign": "window_assign", "consensus": "poa",
                  "stitch": "stitch"}


def stage_seconds(summary: dict) -> Dict[str, float]:
    """Ledger ``stage_s`` fragment from a ``RunReport.summary()`` dict:
    per-phase wall seconds mapped onto the canonical stage names.
    Unknown/malformed entries are skipped — a ledger is advisory."""
    out: Dict[str, float] = {}
    if not isinstance(summary, dict):
        return out
    for phase, rep in summary.items():
        stage = _REPORT_STAGES.get(phase)
        if stage is None or not isinstance(rep, dict):
            continue
        # per-phase wall is a tier -> seconds split (xla/v2/journal/...):
        # the ledger wants the phase total, whichever tiers served it
        walls = rep.get("wall_s")
        if isinstance(walls, dict):
            total = 0.0
            for s in walls.values():
                try:
                    total += float(s)
                except (TypeError, ValueError):
                    continue
            out[stage] = round(total, 6)
        else:
            try:
                out[stage] = round(float(walls or 0.0), 6)
            except (TypeError, ValueError):
                continue
    return out


#: metrics-histogram name -> overlay stage: builds/replays happen
#: *inside* the compute phases, so these land in the non-additive
#: overlay stages of STAGES.
_OVERLAY_HISTS = {"span_us.kernel.build": "kernel_build",
                  "span_us.journal.replay": "journal_replay"}


def overlay_seconds(snapshot: Optional[dict]) -> Dict[str, float]:
    """Overlay-stage seconds (kernel builds, journal replay) from an
    ``obs.snapshot()`` metrics dict — the span_us histogram sums carry
    the totals.  Empty when disarmed or the spans never fired."""
    out: Dict[str, float] = {}
    hists = (snapshot or {}).get("histograms")
    if not isinstance(hists, dict):
        return out
    for hname, stage in _OVERLAY_HISTS.items():
        h = hists.get(hname)
        if not isinstance(h, dict):
            continue
        try:
            total = float(h.get("sum") or 0.0)
        except (TypeError, ValueError):
            continue
        if total > 0:
            out[stage] = round(total / 1e6, 6)
    return out


class JobLedger:
    """Stage stamps + per-stage durations for one job.  Thread-safe:
    the scheduler stamps from the submit connection thread, the worker
    loop, and the plane's ``on_done`` callback."""

    def __init__(self, job_id: str, tenant: str = ""):
        self.job_id = job_id
        self.tenant = tenant
        self._lock = threading.Lock()
        self._marks: Dict[str, int] = {}       # stage -> monotonic_ns
        self._stage_s: Dict[str, float] = {}   # stage -> seconds
        self.mark("submit")

    def mark(self, stage: str, t_ns: Optional[int] = None) -> None:
        """Record the first time ``stage`` is reached (idempotent, so a
        retried dispatch keeps the original stamp)."""
        with self._lock:
            self._marks.setdefault(
                stage, time.monotonic_ns() if t_ns is None else int(t_ns))

    def add_stage(self, stage: str, seconds: float) -> None:
        """Accumulate a compute-stage duration (chunked jobs report one
        fragment per chunk)."""
        try:
            s = float(seconds)
        except (TypeError, ValueError):
            return
        if s < 0:
            return
        with self._lock:
            self._stage_s[stage] = self._stage_s.get(stage, 0.0) + s

    def merge_stage_s(self, stage_s: dict) -> None:
        """Absorb a worker/session ``stage_s`` fragment (the shape
        :func:`stage_seconds` returns; rides the result wire message)."""
        if not isinstance(stage_s, dict):
            return
        for stage, s in stage_s.items():
            if isinstance(stage, str):
                self.add_stage(stage, s)

    def as_dict(self) -> dict:
        """The finalized JSON-ready ledger.  ``marks`` are seconds
        relative to submit; interval stages (queue/dispatch/result_ship)
        are derived from the stamps; ``unattributed_s`` is the part of
        the wall the additive stages do not explain — reported, never
        hidden."""
        with self._lock:
            marks = dict(self._marks)
            stage_s = dict(self._stage_s)
        t0 = marks.get("submit", 0)

        def rel(stage: str) -> Optional[float]:
            t = marks.get(stage)
            return None if t is None else round((t - t0) / 1e9, 6)

        def between(a: str, b: str) -> Optional[float]:
            ta, tb = marks.get(a), marks.get(b)
            if ta is None or tb is None:
                return None
            return max(0.0, (tb - ta) / 1e9)

        queue = between("admit", "dispatch")
        if queue is not None:
            stage_s["queue"] = round(
                stage_s.get("queue", 0.0) + queue, 6)
        ship = between("finish", "result_ship")
        if ship is not None:
            stage_s["result_ship"] = round(
                stage_s.get("result_ship", 0.0) + ship, 6)
        wall = between("submit", "result_ship")
        if wall is None:
            wall = between("submit", "finish")
        attributed = sum(stage_s.get(k, 0.0) for k in _ADDITIVE)
        doc = {
            "job": self.job_id,
            "tenant": self.tenant,
            "marks": {k: rel(k) for k in sorted(marks)},
            "stage_s": {k: round(stage_s[k], 6)
                        for k in STAGES if k in stage_s},
            "wall_s": None if wall is None else round(wall, 6),
        }
        if wall is not None:
            doc["attributed_s"] = round(attributed, 6)
            doc["unattributed_s"] = round(max(0.0, wall - attributed), 6)
        return doc


def summarize(ledgers) -> Optional[dict]:
    """Aggregate finalized ledger dicts (one per job) into the compact
    per-stage summary bench.py stamps: total seconds per stage, job
    count, and the total/unattributed walls.  Returns None when there
    is nothing to aggregate."""
    totals: Dict[str, float] = {}
    wall = unattributed = 0.0
    n = 0
    for led in ledgers or ():
        if not isinstance(led, dict):
            continue
        stage_s = led.get("stage_s")
        if not isinstance(stage_s, dict):
            continue
        n += 1
        for stage, s in stage_s.items():
            try:
                totals[stage] = totals.get(stage, 0.0) + float(s)
            except (TypeError, ValueError):
                continue
        try:
            wall += float(led.get("wall_s") or 0.0)
            unattributed += float(led.get("unattributed_s") or 0.0)
        except (TypeError, ValueError):
            continue
    if not n:
        return None
    return {"jobs": n,
            "stage_s": {k: round(totals[k], 6) for k in sorted(totals)},
            "wall_s": round(wall, 6),
            "unattributed_s": round(unattributed, 6)}
