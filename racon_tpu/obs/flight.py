"""Crash flight recorder: an always-on ring of the last N spans/events.

Unlike the tracer (armed per run, bounded-but-large, written once at
the end), the flight recorder is *always* collecting — a fixed-size
``deque`` of the most recent instant events, completed spans, and
explicit breadcrumbs — and is dumped on the paths where a process dies
with its trace unwritten: fault injection (including ``kill=1``, which
SIGKILLs mid-run), ``TierDead``/``TierWedged``, a worker chunk
exception, or SIGTERM in a process entrypoint.  The dump is a small
JSON file (``flight.<pid>.json``) in the current job/chunk directory;
the distrib coordinator folds any dumps it finds into
``RunReport["flight"]`` so the chaos tests get a post-mortem artifact
instead of a bare exit code.

Overhead discipline: ``record`` is a dict build + deque append under a
lock, gated on one env-knob read — no I/O, no formatting.  Disarmed
tracing does not disable the recorder (that is the point); setting
``RACON_TPU_FLIGHT=0`` does.  Timestamps are ``monotonic_ns`` like the
tracer's, so a dump's events line up with a trace from the same
process.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

from .. import config

ENV_FLIGHT = "RACON_TPU_FLIGHT"
ENV_FLIGHT_EVENTS = "RACON_TPU_FLIGHT_EVENTS"


class FlightRecorder:
    """Bounded ring of breadcrumbs + a one-shot JSON dumper."""

    def __init__(self, max_events: Optional[int] = None):
        if max_events is None:
            max_events = max(16, config.get_int(ENV_FLIGHT_EVENTS))
        self._lock = threading.Lock()
        self._ring = collections.deque(maxlen=max_events)
        self._dir: Optional[str] = None
        self._role: Optional[str] = None

    # -- recording (hot-path safe) ----------------------------------------
    def enabled(self) -> bool:
        return config.get_bool(ENV_FLIGHT)

    def record(self, name: str, kind: str = "event", **args) -> None:
        if not self.enabled():
            return
        ev = {"t_mono_ns": time.monotonic_ns(), "name": name, "kind": kind}
        if args:
            ev["args"] = args
        with self._lock:
            self._ring.append(ev)

    def span(self, name: str, dur_us: int) -> None:
        """Completed-span breadcrumb (chained off the tracer's
        ``on_complete``, so armed runs log their span tail here too)."""
        self.record(name, kind="span", dur_us=int(dur_us))

    # -- placement ---------------------------------------------------------
    def set_dir(self, path: Optional[str]) -> None:
        """Where a dump lands: the current chunk/job directory.  The
        worker re-points this per chunk; None disables dumping until the
        next ``set_dir``."""
        with self._lock:
            self._dir = path

    def set_role(self, role: Optional[str]) -> None:
        with self._lock:
            self._role = role

    # -- dumping -----------------------------------------------------------
    def dump(self, reason: str, dir_path: Optional[str] = None,
             **detail) -> Optional[str]:
        """Write the ring to ``<dir>/flight.<pid>.json`` (tmp+replace so
        a dump interrupted by the impending SIGKILL never leaves a torn
        file).  Returns the path, or None when disabled / no directory
        is set / the write fails — a post-mortem must never mask the
        crash it documents."""
        if not self.enabled():
            return None
        with self._lock:
            target = dir_path or self._dir
            events = list(self._ring)
            role = self._role
        if not target:
            return None
        from . import context
        doc = {
            "tool": "racon_tpu.obs.flight",
            "clock": "monotonic",
            "pid": os.getpid(),
            "role": role,
            "reason": reason,
            "t_dump_mono_ns": time.monotonic_ns(),
            "trace_context": context.current(),
            "events": events,
        }
        if detail:
            doc["detail"] = detail
        path = os.path.join(target, f"flight.{os.getpid()}.json")
        tmp = f"{path}.tmp"
        try:
            os.makedirs(target, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f)
                f.write("\n")
            os.replace(tmp, path)
        except OSError:
            return None
        return path


#: Process-wide recorder.  Deliberately NOT cleared by ``obs.reset()``:
#: the breadcrumbs from run setup are exactly what a crash early in the
#: next run needs.
_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def record(name: str, kind: str = "event", **args) -> None:
    _recorder.record(name, kind=kind, **args)


def set_dir(path: Optional[str]) -> None:
    _recorder.set_dir(path)


def set_role(role: Optional[str]) -> None:
    _recorder.set_role(role)


def dump(reason: str, dir_path: Optional[str] = None,
         **detail) -> Optional[str]:
    return _recorder.dump(reason, dir_path=dir_path, **detail)


def scan(dir_path: str) -> list:
    """Load every parseable ``flight.*.json`` under ``dir_path``
    (recursively — dumps land in nested chunk/job directories) — the
    coordinator's end-of-run sweep for worker post-mortems.  Unreadable
    or torn files are skipped; the sweep is reporting, not recovery."""
    out = []
    for root, dirs, names in os.walk(dir_path):
        dirs.sort()
        for name in sorted(names):
            if not (name.startswith("flight.") and name.endswith(".json")):
                continue
            path = os.path.join(root, name)
            try:
                with open(path) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(doc, dict):
                doc["path"] = path
                out.append(doc)
    return out
