"""AST lint engine: walks the repo's Python sources, runs every rule in
`rules/`, honors inline suppressions and a violation baseline.

A rule sees one parsed file at a time (`FileContext`) or the whole repo
once (`check_project`, for registry-vs-docs style checks).  Violations
are stable, fingerprintable records so a baseline file can distinguish
pre-existing debt from new regressions.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

from . import astcache

#: Inline suppression: `# lint: disable=rule-id[,rule-id]` on the
#: offending line silences those rules for that line only.
_SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=([\w,-]+)")


@dataclass(frozen=True)
class Violation:
    rule: str      # rule id (kebab-case)
    path: str      # repo-relative posix path
    line: int      # 1-based
    message: str

    def fingerprint(self) -> str:
        """Line-number-free identity: survives unrelated edits above the
        violation, so a baseline doesn't churn on every refactor."""
        return f"{self.rule}:{self.path}:{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class FileContext:
    """One parsed source file, as rules see it."""

    def __init__(self, repo_root: str, relpath: str, source: str,
                 tree: ast.AST):
        self.repo_root = repo_root
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # parent links let rules walk ancestor chains (ast has none)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)

    def in_package(self, *parts: str) -> bool:
        """Whether this file lives under racon_tpu/<parts...>."""
        prefix = "/".join(("racon_tpu",) + parts)
        return self.relpath == prefix or self.relpath.startswith(prefix + "/")


class ProjectContext:
    """Repo-level view for rules that check cross-file invariants."""

    def __init__(self, repo_root: str, files: Sequence[FileContext]):
        self.repo_root = repo_root
        self.files = files

    def read_text(self, relpath: str) -> Optional[str]:
        path = os.path.join(self.repo_root, relpath)
        try:
            with open(path) as f:
                return f.read()
        except OSError:
            return None


#: Source files the lint covers: the package itself plus the repo-level
#: entry points.  Tests and fixtures are deliberately out of scope (they
#: monkeypatch environments and write intentional violations).
_EXTRA_FILES = ("bench.py", "__graft_entry__.py")
_EXCLUDE_DIRS = {"__pycache__", "build"}


def repo_root_for(start: Optional[str] = None) -> str:
    """The repo root: the directory holding the racon_tpu package."""
    here = start or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # analysis/ -> racon_tpu/ -> repo root
    return os.path.dirname(here) if os.path.basename(here) == "racon_tpu" \
        else here


def iter_source_files(repo_root: str) -> List[str]:
    """Repo-relative paths of every linted source file, sorted."""
    out = []
    pkg = os.path.join(repo_root, "racon_tpu")
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rel = os.path.relpath(os.path.join(dirpath, fn), repo_root)
                out.append(rel.replace(os.sep, "/"))
    for fn in _EXTRA_FILES:
        if os.path.exists(os.path.join(repo_root, fn)):
            out.append(fn)
    return sorted(out)


def _suppressed(lines: Sequence[str], line_no: int, rule_id: str) -> bool:
    if not 1 <= line_no <= len(lines):
        return False
    m = _SUPPRESS_RE.search(lines[line_no - 1])
    return bool(m) and rule_id in m.group(1).split(",")


def run_lint(repo_root: Optional[str] = None,
             paths: Optional[Sequence[str]] = None,
             rules=None) -> List[Violation]:
    """Run every (or the given) lint rule over the repo's sources.

    paths — repo-relative file list override (fixture tests point this
    at a single snippet); default: `iter_source_files`.
    Returns inline-suppression-filtered violations, sorted by location.
    Baseline filtering is the CLI's job (`__main__.py`).
    """
    from .rules import ALL_RULES

    root = repo_root or repo_root_for()
    active = list(rules) if rules is not None else list(ALL_RULES)
    contexts: List[FileContext] = []
    violations: List[Violation] = []
    for rel in (paths if paths is not None else iter_source_files(root)):
        parsed = astcache.load(root, rel)
        if parsed.tree is None:
            violations.append(Violation(
                "parse-error", rel, parsed.error_line, parsed.error or ""))
            continue
        contexts.append(FileContext(root, rel, parsed.source, parsed.tree))

    for ctx in contexts:
        for rule in active:
            for v in rule.check(ctx):
                if not _suppressed(ctx.lines, v.line, v.rule):
                    violations.append(v)
    project = ProjectContext(root, contexts)
    for rule in active:
        check_project = getattr(rule, "check_project", None)
        if check_project is not None:
            violations.extend(check_project(project))
    return sorted(violations, key=lambda v: (v.path, v.line, v.rule))


# --------------------------------------------------------------------------
# baseline: accepted pre-existing violations (fingerprint set)
# --------------------------------------------------------------------------

def load_baseline(path: str) -> set:
    """Fingerprints accepted by the suppression baseline file."""
    try:
        with open(path) as f:
            data = json.load(f)
    except OSError:
        return set()
    return set(data.get("accepted", []))


def write_baseline(path: str, violations: Iterable[Violation]) -> None:
    data = {
        "comment": "accepted pre-existing violations; regenerate with "
                   "python -m racon_tpu.analysis --write-baseline",
        "accepted": sorted({v.fingerprint() for v in violations}),
    }
    with open(path, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def filter_baselined(violations: Sequence[Violation],
                     baseline: set) -> List[Violation]:
    """Violations NOT covered by the baseline (i.e. the new ones)."""
    return [v for v in violations if v.fingerprint() not in baseline]
