"""Interprocedural explicit-flow taint analysis for Engine 5.

Sources are knob reads (``config.get_*("RACON_TPU_X")``); sinks are the
byte-install seams every polished byte passes through —
``pipeline.set_consensus(i, payload, ...)`` (poa_driver._install, the
CPU polisher, journal replay) and ``pipeline.set_job_cigar(job, cigar)``
(align.run_jobs / align_pallas, CigarTap).  A knob whose *value* can
reach a sink payload is output-affecting; a knob that cannot is
cost-only under the model below.

Modeling rules (deliberate, documented, and what makes the byte-identity
contract statically checkable at all):

* **explicit flows only** — a knob choosing a branch, a tier, or a
  kernel variant is control flow, and the repo contract is precisely
  that all such paths produce identical bytes; only *data* flow into a
  payload is a leak.  Concretely: ``if`` / ``while`` tests and the
  test of a conditional expression never propagate taint.
* **index barrier** — ``seq[i]`` / ``seq[a:b]`` never taints the loaded
  value with the *index* taint (the container's own taint propagates).
  This is the paper's windows-are-independent decomposition as an
  analysis rule: batch/chunk knobs decide *which* units are grouped
  together, never what any unit's bytes are.
* **callee barrier** — calling a tainted *callable* contributes only
  the argument taints to the result.  Knobs select which built kernel
  runs; the contract says every kernel computes the same bytes.
* **shape barrier** — array allocators (``zeros``/``empty``/...) do not
  propagate taint from their shape arguments into the array values.
* everything else is conservative: unknown calls union their argument
  (and receiver) taints, containers carry element taint, attributes
  are tracked per ``(class, attr)`` plus object-level for dataclasses.

Waiver: a ``# determinism: <reason>`` comment on the flagged line (or
on a comment line directly above it) waives a source or a sink —
intentional flows like journal replay, which installs previously-
journaled bytes that the journal fingerprint already proves belong to
this exact run.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..concurrency.model import _MUTATORS, Model
from . import knobs as knobs_mod

#: Sink methods: name -> 0-based payload argument index.
SINKS = {
    "set_consensus": 1,    # pipeline.set_consensus(i, payload, polished)
    "set_job_cigar": 1,    # pipeline.set_job_cigar(job, cigar)
}

#: Calls whose result carries no taint (counts/sizes/allocations).
BARRIERS = frozenset({
    "len", "range", "id", "zeros", "ones", "empty", "full",
    "zeros_like", "ones_like", "empty_like", "full_like", "arange",
    "eye", "iota",
})

_WAIVER_RE = re.compile(r"#\s*determinism:\s*(\S[^#]*)")


def waiver_reason(model: Model, rel: str, line: int) -> Optional[str]:
    """The ``# determinism:`` waiver covering this line: on the line
    itself, or on a run of pure comment lines directly above it."""
    lines = model.lines.get(rel, [])
    if not 1 <= line <= len(lines):
        return None
    m = _WAIVER_RE.search(lines[line - 1])
    if m:
        return m.group(1).strip()
    i = line - 2
    while i >= 0 and lines[i].lstrip().startswith("#"):
        m = _WAIVER_RE.search(lines[i])
        if m:
            return m.group(1).strip()
        i -= 1
    return None


@dataclass(frozen=True)
class SinkHit:
    """One knob reaching one install seam."""

    knob: str
    relpath: str
    line: int
    seam: str                  # sink method name
    func: str                  # enclosing function qname
    waived: Optional[str]      # waiver reason, if any


class State:
    """The monotone interprocedural facts of one fixpoint run."""

    def __init__(self) -> None:
        self.param: Dict[Tuple[str, str], Set[str]] = {}
        self.ret: Dict[str, Set[str]] = {}
        self.attr: Dict[Tuple[str, str], Set[str]] = {}
        self.glob: Dict[Tuple[str, str], Set[str]] = {}
        self.hits: Dict[Tuple[str, str, int], SinkHit] = {}
        self.reads: Dict[Tuple[str, str, int], knobs_mod.KnobRead] = {}
        self.changed = False
        self.iterations = 0

    def add(self, table: Dict, key, taints: Set[str]) -> None:
        if not taints:
            return
        cur = table.setdefault(key, set())
        if not taints <= cur:
            cur |= taints
            self.changed = True


def analyze(model: Model) -> State:
    """Run the taint fixpoint over every function in the model."""
    state = State()
    by_rel: Dict[str, List[str]] = {}
    for q, fn in model.functions.items():
        by_rel.setdefault(fn.relpath, []).append(q)
    for i in range(25):
        state.changed = False
        state.iterations = i + 1
        for rel, tree in sorted(model.trees.items()):
            w = _TaintWalker(model, state, rel)
            w.walk_module_level(tree)
            for q in by_rel.get(rel, ()):
                fn = model.functions[q]
                if fn.name == "<module>":
                    continue
                node = model.def_node(q)
                if node is not None:
                    w.walk_function(q, node, fn.cls)
        if not state.changed:
            break
    return state


class _TaintWalker:
    """Walks one file's functions, evaluating expression taint."""

    def __init__(self, model: Model, state: State, rel: str):
        self.m = model
        self.s = state
        self.rel = rel
        self.q = f"{rel}::<module>"
        self.cls: Optional[str] = None
        self.env: Dict[str, Set[str]] = {}
        self.types: Dict[str, Tuple] = {}
        self.globals_decl: Set[str] = set()
        self.module_level = False

    # -- walking -----------------------------------------------------------

    def walk_module_level(self, tree: ast.Module) -> None:
        self.q = f"{self.rel}::<module>"
        self.cls = None
        self.env = {}
        self.types = {}
        self.globals_decl = set()
        self.module_level = True
        body = [n for n in tree.body
                if not isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef))]
        for _ in range(2):
            for stmt in body:
                self._stmt(stmt)

    def walk_function(self, q: str, node, cls: Optional[str]) -> None:
        self.q = q
        self.cls = cls
        self.module_level = False
        self.globals_decl = {
            name for sub in ast.walk(node)
            if isinstance(sub, ast.Global) for name in sub.names}
        self.env = {}
        self.types = {}
        args = list(getattr(node.args, "posonlyargs", [])) \
            + list(node.args.args) + list(node.args.kwonlyargs)
        for a in args:
            self.env[a.arg] = set(self.s.param.get((q, a.arg), ()))
            if a.arg == "self" and cls:
                self.types["self"] = ("class", cls)
            elif a.annotation is not None:
                tag = self._annotation_tag(a.annotation)
                if tag:
                    self.types[a.arg] = tag
        for _ in range(3):
            before = {k: set(v) for k, v in self.env.items()}
            for stmt in node.body:
                self._stmt(stmt)
            if self.env == before:
                break

    # -- statements --------------------------------------------------------

    def _stmt(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return                       # walked as their own functions
        if isinstance(node, ast.Assign):
            t = self._eval(node.value)
            tag = self._type_of(node.value)
            for tgt in node.targets:
                self._assign(tgt, t, tag)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                self._assign(node.target, self._eval(node.value),
                             self._type_of(node.value))
        elif isinstance(node, ast.AugAssign):
            t = self._eval(node.value) | self._eval(
                _as_load(node.target))
            self._assign(node.target, t, None)
        elif isinstance(node, ast.Return):
            if node.value is not None:
                self.s.add(self.s.ret, self.q, self._eval(node.value))
        elif isinstance(node, ast.For):
            t = self._eval(node.iter)
            self._assign(node.target, t, None)
            for sub in node.body + node.orelse:
                self._stmt(sub)
        elif isinstance(node, ast.While):
            self._eval(node.test)        # calls inside tests still count
            for sub in node.body + node.orelse:
                self._stmt(sub)
        elif isinstance(node, ast.If):
            self._eval(node.test)
            for sub in node.body + node.orelse:
                self._stmt(sub)
        elif isinstance(node, ast.With):
            for item in node.items:
                t = self._eval(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, t,
                                 self._type_of(item.context_expr))
            for sub in node.body:
                self._stmt(sub)
        elif isinstance(node, ast.Try):
            for sub in (node.body + node.orelse + node.finalbody):
                self._stmt(sub)
            for h in node.handlers:
                for sub in h.body:
                    self._stmt(sub)
        elif isinstance(node, ast.Expr):
            self._eval(node.value)
        elif isinstance(node, (ast.Raise, ast.Assert)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self._eval(sub)
        elif isinstance(node, ast.Global):
            self.globals_decl.update(node.names)

    def _assign(self, target, taints: Set[str],
                tag: Optional[Tuple]) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._assign(el, taints, None)
            return
        if isinstance(target, ast.Starred):
            self._assign(target.value, taints, None)
            return
        if isinstance(target, ast.Name):
            name = target.id
            if tag is not None:
                self.types[name] = tag
            if name in self.globals_decl or (
                    self.module_level
                    and self.m.is_module_global(self.rel, name)):
                self.s.add(self.s.glob, (self.rel, name), taints)
            cur = self.env.setdefault(name, set())
            cur |= taints
            return
        if isinstance(target, ast.Attribute):
            cls = self._class_of(target.value)
            if cls is not None:
                self.s.add(self.s.attr, (cls, target.attr), taints)
            if isinstance(target.value, ast.Name):
                # object-level: a tainted field taints the object
                self.env.setdefault(target.value.id, set()).update(taints)
            return
        if isinstance(target, ast.Subscript):
            # container store: taint the container, drop the index
            self._assign(target.value, taints, None)

    # -- expressions -------------------------------------------------------

    def _eval(self, node) -> Set[str]:
        if node is None or isinstance(node, ast.Constant):
            return set()
        if isinstance(node, ast.Name):
            out = set(self.env.get(node.id, ()))
            if node.id not in self.env \
                    and self.m.is_module_global(self.rel, node.id):
                out |= self.s.glob.get((self.rel, node.id), set())
            return out
        if isinstance(node, ast.Attribute):
            out = self._eval(node.value)
            cls = self._class_of(node.value)
            if cls is not None:
                out |= self.s.attr.get((cls, node.attr), set())
            return out
        if isinstance(node, ast.Subscript):
            self._eval(node.slice)       # still visit calls in the index
            return self._eval(node.value)
        if isinstance(node, ast.Call):
            return self._eval_call(node)
        if isinstance(node, ast.IfExp):
            self._eval(node.test)        # control: test taint dropped
            return self._eval(node.body) | self._eval(node.orelse)
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            if node.value is not None:
                self.s.add(self.s.ret, self.q, self._eval(node.value))
            return set()
        if isinstance(node, ast.NamedExpr):
            t = self._eval(node.value)
            self._assign(node.target, t, self._type_of(node.value))
            return t
        if isinstance(node, ast.Lambda):
            return set()
        # everything else (BinOp, BoolOp, Compare, JoinedStr,
        # comprehensions, Tuple/List/Set/Dict, Starred, Slice, Await):
        # the union of every sub-expression
        out: Set[str] = set()
        for sub in ast.iter_child_nodes(node):
            if isinstance(sub, ast.expr):
                out |= self._eval(sub)
            elif isinstance(sub, ast.comprehension):
                it = self._eval(sub.iter)
                self._assign(sub.target, it, None)
                out |= it
                for cond in sub.ifs:
                    self._eval(cond)
        return out

    def _eval_call(self, node: ast.Call) -> Set[str]:
        knob = knobs_mod.knob_of_call(self.m, self.rel, node)
        if knob is not None:
            waived = waiver_reason(self.m, self.rel, node.lineno)
            key = (knob, self.rel, node.lineno)
            if key not in self.s.reads:
                self.s.reads[key] = knobs_mod.KnobRead(
                    knob, self.rel, node.lineno, self.q, waived)
                self.s.changed = True
            return set() if waived else {knob}

        arg_taints = [self._eval(a.value if isinstance(a, ast.Starred)
                                 else a) for a in node.args]
        kw_taints = {kw.arg: self._eval(kw.value) for kw in node.keywords}
        all_args: Set[str] = set().union(*arg_taints) if arg_taints \
            else set()
        for t in kw_taints.values():
            all_args |= t

        func = node.func
        attr = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")

        # sink check: tainted payload into an install seam
        if attr in SINKS:
            idx = SINKS[attr]
            payload = arg_taints[idx] if idx < len(arg_taints) else set()
            for t in kw_taints.values():
                payload = payload | t
            if payload:
                waived = waiver_reason(self.m, self.rel, node.lineno)
                for k in sorted(payload):
                    key = (k, self.rel, node.lineno)
                    if key not in self.s.hits:
                        self.s.hits[key] = SinkHit(
                            k, self.rel, node.lineno, attr, self.q,
                            waived)
                        self.s.changed = True

        if attr in BARRIERS:
            return set()

        # in-place mutators taint their receiver container
        if isinstance(func, ast.Attribute) and attr in _MUTATORS \
                and all_args:
            self._assign(func.value, all_args, None)

        callee = self._resolve_callee(node)
        if callee is not None and callee[0] == "func":
            fq = callee[1]
            self._bind_args(fq, node, arg_taints, kw_taints,
                            callee[2])
            return set(self.s.ret.get(fq, ()))
        if callee is not None and callee[0] == "class":
            cq = callee[1]
            init_q = f"{cq}.__init__"
            if init_q in self.m.functions:
                self._bind_args(init_q, node, arg_taints, kw_taints,
                                None)
                return set(self.s.ret.get(init_q, ()))
            # dataclass-style: the object carries its field taints
            return all_args

        # unknown callee: union of args + receiver
        out = all_args
        if isinstance(func, ast.Attribute):
            out = out | self._eval(func.value)
        return out

    def _bind_args(self, fq: str, node: ast.Call,
                   arg_taints: List[Set[str]],
                   kw_taints: Dict[Optional[str], Set[str]],
                   receiver) -> None:
        """Flow call-site taints into the callee's parameters."""
        def_node = self.m.def_node(fq)
        if def_node is None:
            return
        params = [a.arg for a in
                  list(getattr(def_node.args, "posonlyargs", []))
                  + list(def_node.args.args)]
        kwonly = {a.arg for a in def_node.args.kwonlyargs}
        if params and params[0] == "self":
            if receiver is not None:
                self.s.add(self.s.param, (fq, "self"),
                           self._eval(receiver))
            params = params[1:]
        for i, t in enumerate(arg_taints):
            if i < len(params):
                self.s.add(self.s.param, (fq, params[i]), t)
            elif def_node.args.vararg is not None:
                self.s.add(self.s.param,
                           (fq, def_node.args.vararg.arg), t)
        for name, t in kw_taints.items():
            if name is None:             # **kwargs expansion
                if def_node.args.kwarg is not None:
                    self.s.add(self.s.param,
                               (fq, def_node.args.kwarg.arg), t)
                continue
            if name in params or name in kwonly:
                self.s.add(self.s.param, (fq, name), t)
            elif def_node.args.kwarg is not None:
                self.s.add(self.s.param,
                           (fq, def_node.args.kwarg.arg), t)

    # -- resolution --------------------------------------------------------

    def _resolve_callee(self, node: ast.Call):
        """("func", qname, receiver_expr|None) / ("class", qname) /
        None.  Mirrors the concurrency model's resolution with this
        walker's local type environment for method receivers."""
        func = node.func
        if isinstance(func, ast.Name):
            scope: Optional[str] = self.q
            while scope is not None:
                found = self.m._funcs_by_parent.get(scope, {}).get(func.id)
                if found:
                    return ("func", found, None)
                if ".<locals>." in scope:
                    scope = scope.rsplit(".<locals>.", 1)[0]
                elif scope != self.rel:
                    scope = self.rel
                else:
                    scope = None
            sym = self.m.resolve_symbol(self.rel, func)
            if sym and sym[0] == "func":
                return ("func", sym[1], None)
            if sym and sym[0] == "class":
                return ("class", sym[1])
            return None
        if isinstance(func, ast.Attribute):
            base = self._class_of(func.value)
            if base is not None:
                q = f"{base}.{func.attr}"
                if q in self.m.functions:
                    return ("func", q, func.value)
                return None
            sym = self.m.resolve_symbol(self.rel, func)
            if sym and sym[0] == "func":
                return ("func", sym[1], None)
            if sym and sym[0] == "class":
                return ("class", sym[1])
        return None

    def _class_of(self, expr) -> Optional[str]:
        tag = self._type_of(expr)
        if tag and tag[0] == "class":
            return tag[1]
        return None

    def _type_of(self, expr) -> Optional[Tuple]:
        if isinstance(expr, ast.Name):
            tag = self.types.get(expr.id)
            if tag is not None:
                return tag
            sym = self.m.resolve_symbol(self.rel, expr)
            if sym and sym[0] == "class":
                return None              # the class object, not an instance
            return None
        if isinstance(expr, ast.Attribute):
            base = self._class_of(expr.value)
            if base is not None:
                info = self.m.classes.get(base)
                if info is not None:
                    tag = info.attr_tags.get(expr.attr)
                    if tag and tag[0] == "class":
                        return tag
            return None
        if isinstance(expr, ast.Call):
            sym = self.m.resolve_symbol(self.rel, expr.func) \
                if isinstance(expr.func, (ast.Name, ast.Attribute)) \
                else None
            if sym and sym[0] == "class":
                return ("class", sym[1])
            return None
        return None

    def _annotation_tag(self, ann) -> Optional[Tuple]:
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):   # Optional[X] / List[X]
            return self._annotation_tag(ann.slice)
        sym = self.m.resolve_symbol(self.rel, ann) \
            if isinstance(ann, (ast.Name, ast.Attribute)) else None
        if sym and sym[0] == "class":
            return ("class", sym[1])
        return None


def _as_load(node):
    """AugAssign targets double as reads; ``_eval`` ignores ctx, so the
    Store-context node is usable as-is."""
    return node
