"""Engine 5: determinism taint auditor.

Statically proves the repo's byte-identity contract: every knob in the
``config.py`` registry is classified **output-affecting** or
**cost-only** by propagating explicit dataflow taint from its read
sites through the interprocedural call graph to the consensus/CIGAR
install seams (``pipeline.set_consensus`` / ``pipeline.set_job_cigar``
— ``poa_driver._install``, ``align.run_jobs``, the CPU polisher stitch
and journal replay).  The verdicts are then cross-checked against the
fingerprint compositions declared in ``racon_tpu/fingerprint.py``:

* ``determinism-leak`` — a cost-only knob's value reaches an install
  seam (the contract broken in code);
* ``fingerprint-gap`` — an output-affecting source missing from a
  composition declared complete (a cache could serve stale bytes);
* ``fingerprint-overkey`` (warning) — a component keyed only on
  cost-only, taint-clean knobs (needless cache misses).

Violations are ordinary ``lint.Violation`` objects, so the baseline /
suppression / CLI plumbing applies unchanged; intentional flows carry a
``# determinism: <reason>`` waiver on (or directly above) the flagged
line.  ``--emit-manifest`` writes the full knob/site classification as
``determinism.json``.
"""

from __future__ import annotations

import os
import shutil
import tempfile
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..lint import Violation, repo_root_for
from .rules import WARNING_RULES

__all__ = [
    "AuditResult", "MUTANTS", "WARNING_RULES", "build_audit",
    "run_determinism", "run_mutant",
]


@dataclass
class AuditResult:
    """One Engine 5 run: hard violations, warnings, and the manifest."""

    violations: List[Violation] = field(default_factory=list)
    warnings: List[Violation] = field(default_factory=list)
    manifest: Dict = field(default_factory=dict)


def build_audit(repo_root: Optional[str] = None,
                paths: Optional[Sequence[str]] = None) -> AuditResult:
    """Run the full audit over one repo tree.

    paths — repo-relative file subset: the taint model is built from
    just these files (flows through unlisted code are invisible by
    design, like ``--concurrency``); the knob and fingerprint
    registries are always read from their canonical root files so the
    fingerprint rules judge the real contract either way.
    """
    from ..concurrency.model import Model
    from . import fingerprints, knobs, manifest, rules, taint
    root = repo_root or repo_root_for()
    model = Model.build(root, list(paths) if paths is not None else None)
    state = taint.analyze(model)
    decls = knobs.extract_registry(root) or {}
    fp_reg = fingerprints.extract_registry(root)
    viols = rules.evaluate(state, decls, fp_reg)
    return AuditResult(
        violations=[v for v in viols if v.rule not in WARNING_RULES],
        warnings=[v for v in viols if v.rule in WARNING_RULES],
        manifest=manifest.build(state, decls, fp_reg, viols))


def run_determinism(repo_root: Optional[str] = None,
                    paths: Optional[Sequence[str]] = None
                    ) -> List[Violation]:
    """The hard (non-warning) violations of one audit — the shape every
    other engine's ``run_*`` entry point returns."""
    return build_audit(repo_root, paths).violations


# --------------------------------------------------------------------------
# seeded mutants: prove the auditor catches what it claims to catch
# --------------------------------------------------------------------------

#: (name, doc, expected-rule, patches) — each patch is a
#: (relpath, old-text, new-text) exact-match textual substitution
#: applied to a scratch copy of the tree.  ``--det-mutate NAME`` (or
#: index) must then report the expected rule, else the self-test
#: failed.  CI runs every entry and requires a non-zero (caught) exit.
MUTANTS = [
    ("drop-input-bytes",
     "remove the input_bytes component from the journal fingerprint "
     "composition: the declared-complete site no longer covers the "
     "problem's input bytes",
     "fingerprint-gap",
     [("racon_tpu/fingerprint.py",
       '            "params": ("input:params",),\n'
       '            "input_bytes": ("input:sequences", "input:overlaps",\n'
       '                            "input:target"),\n',
       '            "params": ("input:params",),\n')]),
    ("leak-pipeline-depth",
     "route the RACON_TPU_PIPELINE_DEPTH value into the device "
     "consensus payload installed by poa_driver._install",
     "determinism-leak",
     [("racon_tpu/ops/poa_driver.py",
       "        payload = decode(kept_codes)\n",
       "        payload = decode(kept_codes) + str(\n"
       "            config.get_int(\"RACON_TPU_PIPELINE_DEPTH\"))"
       ".encode()\n")]),
    ("overkey-tier",
     "key the journal fingerprint on the POA kernel tier knob: a "
     "cost-only, taint-clean knob would force fingerprint misses "
     "between byte-identical runs",
     "fingerprint-overkey",
     [("racon_tpu/fingerprint.py",
       '            "backend": ("input:backend",),\n'
       '            "params": ("input:params",),\n',
       '            "backend": ("input:backend",),\n'
       '            "tier": ("knob:RACON_TPU_POA_KERNEL",),\n'
       '            "params": ("input:params",),\n')]),
    ("drop-journal-waiver",
     "strip the documented waiver from the journal window-replay "
     "install: the intentional journal-bytes flow must resurface as a "
     "determinism-leak",
     "determinism-leak",
     [("racon_tpu/resilience/journal.py",
       "            # determinism: replayed bytes are journal records\n",
       "            # (waiver stripped by the seeded mutant)\n")]),
]


def run_mutant(repo_root: Optional[str], which: str) -> tuple:
    """Apply one seeded mutant to a scratch copy of the tree and audit
    it.  Returns ``(mutant, AuditResult, caught)``."""
    from ..lint import _EXTRA_FILES
    root = repo_root or repo_root_for()
    by_name = {m[0]: m for m in MUTANTS}
    if which in by_name:
        mutant = by_name[which]
    else:
        try:
            mutant = MUTANTS[int(which)]
        except (ValueError, IndexError):
            raise ValueError(
                f"unknown determinism mutant {which!r}; see "
                f"--list-det-mutations") from None
    tmp = tempfile.mkdtemp(prefix="racon-det-mutant-")
    try:
        shutil.copytree(os.path.join(root, "racon_tpu"),
                        os.path.join(tmp, "racon_tpu"))
        for extra in _EXTRA_FILES:
            src = os.path.join(root, extra)
            if os.path.exists(src):
                shutil.copy(src, os.path.join(tmp, extra))
        for rel, old, new in mutant[3]:
            path = os.path.join(tmp, rel)
            with open(path) as f:
                text = f.read()
            if old not in text:
                raise RuntimeError(
                    f"determinism mutant {mutant[0]}: patch anchor not "
                    f"found in {rel} (tree drifted; update MUTANTS)")
            with open(path, "w") as f:
                f.write(text.replace(old, new, 1))
        audit = build_audit(tmp)
        caught = any(v.rule == mutant[2]
                     for v in audit.violations + audit.warnings)
        return mutant, audit, caught
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
