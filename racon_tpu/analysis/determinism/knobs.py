"""Knob-registry extraction + read-site discovery for Engine 5.

The registry side parses the audited tree's ``racon_tpu/config.py``
*literally* (every ``_k(...)`` call), so the engine audits what the
file declares, not what an imported module computed — fixture
mini-trees carry their own tiny config.py the same way the protocol
conformance pass carries its own TRANSITIONS.

The read side finds every ``config.get_*("RACON_TPU_X") / is_set``
call in the model's files; the env-registry lint rule already forces
every knob read through those accessors, so this enumeration is
complete by construction.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import astcache

CONFIG_REL = "racon_tpu/config.py"

#: The sanctioned accessor names (config.py's public readers).
GETTERS = frozenset({
    "get_raw", "get_str", "get_int", "get_float", "get_bool", "is_set",
})


@dataclass
class KnobDecl:
    """One registered knob, as declared (not imported) in config.py."""

    name: str
    kind: str = "str"
    scope: str = "runtime"
    affects_output: bool = False
    line: int = 0
    reads: List["KnobRead"] = field(default_factory=list)


@dataclass(frozen=True)
class KnobRead:
    """One ``config.get_*("KNOB")`` call site."""

    knob: str
    relpath: str
    line: int
    func: str          # qname of the enclosing model function
    waived: Optional[str]   # `# determinism: <reason>` text, if any


def extract_registry(repo_root: str) -> Optional[Dict[str, KnobDecl]]:
    """The ``_k(...)`` declarations of ``<root>/racon_tpu/config.py``,
    or None when the tree has no registry (knob rules are skipped)."""
    parsed = astcache.load(repo_root, CONFIG_REL)
    if parsed.tree is None:
        return None
    out: Dict[str, KnobDecl] = {}
    for node in ast.walk(parsed.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "_k"):
            continue
        lits: List[object] = []
        for a in node.args:
            lits.append(a.value if isinstance(a, ast.Constant) else None)
        if not lits or not isinstance(lits[0], str):
            continue
        kw = {k.arg: k.value.value for k in node.keywords
              if k.arg and isinstance(k.value, ast.Constant)}
        decl = KnobDecl(
            name=lits[0],
            kind=str(lits[2]) if len(lits) > 2 and lits[2] else "str",
            scope=str(kw.get("scope",
                             lits[4] if len(lits) > 4 and lits[4]
                             else "runtime")),
            affects_output=bool(kw.get("affects_output", False)),
            line=node.lineno)
        out[decl.name] = decl
    return out


def knob_of_call(model, rel: str, node: ast.Call) -> Optional[str]:
    """The literal knob name a call reads, or None when the call is not
    a registry accessor.  Resolution goes through the model's namespace
    so both ``config.get_int(...)`` and an imported alias match."""
    dotted = model.dotted_in_ns(rel, node.func)
    if not dotted:
        return None
    head, _, attr = dotted.rpartition(".")
    if attr not in GETTERS or not head.endswith("config"):
        return None
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None
