"""Engine 5 rule evaluation.

Three rules over the taint state plus the two literal registries
(config.py knob declarations, fingerprint.py site compositions):

* ``determinism-leak`` — a knob declared cost-only (or not declared at
  all) whose value reaches an install-seam payload.  Anchored at the
  sink call.
* ``fingerprint-gap`` — a fingerprint site declared ``complete`` whose
  expanded composition misses a token from the required domain (every
  ``OUTPUT_SOURCES`` entry plus ``knob:<NAME>`` for every runtime knob
  declared ``affects_output=True``).  Anchored at the site's line in
  fingerprint.py.
* ``fingerprint-overkey`` (warning) — a site component whose sources
  are all cost-only, taint-clean knobs: equal-output runs would get
  needless fingerprint misses.  Anchored at the component line.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..lint import Violation
from . import fingerprints, knobs, taint

#: Rules that report but never fail the run (or CI).
WARNING_RULES = frozenset({"fingerprint-overkey"})


def required_domain(fp_reg: Optional[fingerprints.Registry],
                    decls: Dict[str, knobs.KnobDecl]) -> Set[str]:
    """Every token a complete fingerprint composition must cover."""
    domain: Set[str] = set(fp_reg.output_sources) if fp_reg else set()
    for d in decls.values():
        if d.affects_output and d.scope == "runtime":
            domain.add(f"knob:{d.name}")
    return domain


def leak_violations(state: taint.State,
                    decls: Dict[str, knobs.KnobDecl]) -> List[Violation]:
    out = []
    for hit in state.hits.values():
        if hit.waived is not None:
            continue
        decl = decls.get(hit.knob)
        if decl is not None and decl.affects_output:
            continue                    # declared output-affecting: fine
        status = ("declared cost-only" if decl is not None
                  else "not in the config registry")
        out.append(Violation(
            "determinism-leak", hit.relpath, hit.line,
            f"knob {hit.knob} ({status}) flows into the "
            f"{hit.seam} payload in {hit.func}: output bytes may "
            f"depend on it; declare affects_output=True and add "
            f"knob:{hit.knob} to the fingerprint domain, or cut the "
            f"flow (`# determinism: <reason>` if intentional)"))
    return out


def gap_violations(fp_reg: Optional[fingerprints.Registry],
                   decls: Dict[str, knobs.KnobDecl]) -> List[Violation]:
    if fp_reg is None:
        return []
    domain = required_domain(fp_reg, decls)
    out = []
    for name in sorted(fp_reg.sites):
        site = fp_reg.sites[name]
        if not site.complete:
            continue
        covered = fp_reg.expanded_coverage(name)
        for token in sorted(domain - covered):
            out.append(Violation(
                "fingerprint-gap", fp_reg.relpath, site.line,
                f"site `{name}` is declared complete but its "
                f"composition misses required token `{token}`: two "
                f"runs differing on it would collide to one "
                f"fingerprint"))
    return out


def overkey_violations(fp_reg: Optional[fingerprints.Registry],
                       decls: Dict[str, knobs.KnobDecl],
                       state: taint.State) -> List[Violation]:
    if fp_reg is None:
        return []
    flowed = {hit.knob for hit in state.hits.values()}
    out = []
    for name in sorted(fp_reg.sites):
        site = fp_reg.sites[name]
        for comp in sorted(site.components):
            sources = site.components[comp]
            knob_names = [t[5:] for t in sources
                          if t.startswith("knob:")]
            if not sources or len(knob_names) != len(sources):
                continue                # any non-knob token earns its keep
            if any(k in flowed
                   or decls.get(k) is None
                   or decls[k].affects_output
                   for k in knob_names):
                continue
            out.append(Violation(
                "fingerprint-overkey", fp_reg.relpath,
                site.component_lines.get(comp, site.line),
                f"site `{name}` component `{comp}` keys only on "
                f"cost-only, taint-clean knob(s) "
                f"{', '.join(sorted(knob_names))}: equal-output runs "
                f"get needless fingerprint misses"))
    return out


def evaluate(state: taint.State,
             decls: Dict[str, knobs.KnobDecl],
             fp_reg: Optional[fingerprints.Registry]) -> List[Violation]:
    """Every Engine 5 violation (warnings included) of one audit."""
    return (leak_violations(state, decls)
            + gap_violations(fp_reg, decls)
            + overkey_violations(fp_reg, decls, state))
