"""The machine-readable ``determinism.json`` manifest.

One document per audit: every knob with its declared/analyzed
output-affecting verdict and every fingerprint site with its component
set and expanded coverage.  Downstream consumers (the planned
device-kernel result cache of ROADMAP open item 5, CI artifacts,
humans debugging a fingerprint miss) read this instead of re-deriving
the contract from the source.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from . import fingerprints, knobs, rules, taint

VERSION = 1


def build(state: taint.State,
          decls: Dict[str, knobs.KnobDecl],
          fp_reg: Optional[fingerprints.Registry],
          violations: List) -> dict:
    knob_entries: Dict[str, dict] = {}
    names = set(decls) | {r.knob for r in state.reads.values()}
    for name in sorted(names):
        decl = decls.get(name)
        reads = sorted((r for r in state.reads.values()
                        if r.knob == name),
                       key=lambda r: (r.relpath, r.line))
        flows = sorted((h for h in state.hits.values()
                        if h.knob == name),
                       key=lambda h: (h.relpath, h.line))
        declared = bool(decl.affects_output) if decl else False
        leaks = [h for h in flows if h.waived is None]
        knob_entries[name] = {
            "registered": decl is not None,
            "kind": decl.kind if decl else None,
            "scope": decl.scope if decl else None,
            "declared_affects_output": declared,
            "affects_output": declared or bool(leaks),
            "verdict": ("output-affecting" if declared or leaks
                        else "cost-only"),
            "reads": [{"path": r.relpath, "line": r.line,
                       "func": r.func,
                       **({"waived": r.waived} if r.waived else {})}
                      for r in reads],
            "sink_flows": [{"path": h.relpath, "line": h.line,
                            "seam": h.seam, "func": h.func,
                            **({"waived": h.waived} if h.waived
                               else {})}
                           for h in flows],
        }

    site_entries: Dict[str, dict] = {}
    if fp_reg is not None:
        for name in sorted(fp_reg.sites):
            site = fp_reg.sites[name]
            site_entries[name] = {
                "helper": site.helper,
                "complete": site.complete,
                "line": site.line,
                "components": {c: list(site.components[c])
                               for c in sorted(site.components)},
                "expanded_coverage":
                    sorted(fp_reg.expanded_coverage(name)),
            }

    errors = [v for v in violations if v.rule not in rules.WARNING_RULES]
    warnings = [v for v in violations if v.rule in rules.WARNING_RULES]
    return {
        "version": VERSION,
        "engine": "racon_tpu.analysis.determinism",
        "taint_iterations": state.iterations,
        "required_domain": sorted(rules.required_domain(fp_reg, decls)),
        "knobs": knob_entries,
        "sites": site_entries,
        "violations": {
            "errors": [vars(v) for v in errors],
            "warnings": [vars(v) for v in warnings],
        },
    }
