"""Fingerprint-registry extraction for Engine 5.

Parses the audited tree's ``racon_tpu/fingerprint.py`` literally: the
``SITES`` dict (composition per fingerprint site) and the
``OUTPUT_SOURCES`` tuple (the input tokens every complete composition
must cover).  Literal parsing — not import — keeps fixture mini-trees
self-contained and guarantees the audit anchors on exactly what the
file says.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .. import astcache

FINGERPRINT_REL = "racon_tpu/fingerprint.py"


@dataclass
class Site:
    """One fingerprint composition, as declared in SITES."""

    name: str
    helper: str
    complete: bool
    components: Dict[str, Tuple[str, ...]]
    line: int = 0
    component_lines: Dict[str, int] = field(default_factory=dict)


@dataclass
class Registry:
    relpath: str
    output_sources: Tuple[str, ...]
    sites: Dict[str, Site]

    def expanded_coverage(self, site_name: str,
                          _seen: Optional[Set[str]] = None) -> Set[str]:
        """Every source token a site covers, with ``site:<name>``
        references expanded transitively (cycle-safe)."""
        seen = _seen if _seen is not None else set()
        if site_name in seen or site_name not in self.sites:
            return set()
        seen.add(site_name)
        out: Set[str] = set()
        for sources in self.sites[site_name].components.values():
            for token in sources:
                if token.startswith("site:"):
                    out |= self.expanded_coverage(token[5:], seen)
                else:
                    out.add(token)
        return out


def _literal(node) -> object:
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None


def extract_registry(repo_root: str) -> Optional[Registry]:
    """The SITES/OUTPUT_SOURCES literals of the tree's fingerprint.py,
    or None when the tree has no fingerprint registry (the fingerprint
    rules are then skipped — a taint-only audit is still sound)."""
    parsed = astcache.load(repo_root, FINGERPRINT_REL)
    if parsed.tree is None:
        return None
    sources: Tuple[str, ...] = ()
    sites: Dict[str, Site] = {}
    for node in parsed.tree.body:
        targets: List[ast.expr] = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "OUTPUT_SOURCES" in names:
            lit = _literal(value)
            if isinstance(lit, (tuple, list)):
                sources = tuple(str(s) for s in lit)
        elif "SITES" in names and isinstance(value, ast.Dict):
            sites = _parse_sites(value)
    if not sites:
        return None
    return Registry(FINGERPRINT_REL, sources, sites)


def _parse_sites(node: ast.Dict) -> Dict[str, Site]:
    out: Dict[str, Site] = {}
    for key, val in zip(node.keys, node.values):
        if not (isinstance(key, ast.Constant) and isinstance(key.value, str)
                and isinstance(val, ast.Dict)):
            continue
        entry = _literal(val)
        if not isinstance(entry, dict):
            continue
        comps_node = next(
            (v for k, v in zip(val.keys, val.values)
             if isinstance(k, ast.Constant) and k.value == "components"
             and isinstance(v, ast.Dict)), None)
        comp_lines: Dict[str, int] = {}
        if comps_node is not None:
            for ck, cv in zip(comps_node.keys, comps_node.values):
                if isinstance(ck, ast.Constant):
                    comp_lines[str(ck.value)] = cv.lineno
        raw = entry.get("components") or {}
        comps = {str(c): tuple(str(s) for s in srcs)
                 for c, srcs in raw.items()
                 if isinstance(srcs, (tuple, list))}
        out[key.value] = Site(
            name=key.value,
            helper=str(entry.get("helper", "")),
            complete=bool(entry.get("complete", False)),
            components=comps,
            line=key.lineno,
            component_lines=comp_lines)
    return out
