"""Lock-discipline findings over the repo model.

Two rules:

* ``unguarded-mutation`` — a shared location (attribute of a shared
  class, or a module global) is mutated by two or more thread roles
  without one lock held at **every** mutation site.  Fix by guarding
  every site with the same lock, switching to a sanctioned lock-free
  type, or waiving the site with ``# concurrency: <reason>``.
* ``lock-order-cycle`` — the repo-wide lock-acquisition-order digraph
  (edge ``A -> B`` whenever B is acquired while A is held) contains a
  cycle: two paths can acquire the same locks in opposite orders, the
  classic deadlock.  Self-edges are ignored (Condition wraps an RLock).
"""

from __future__ import annotations

import os
from typing import Dict, List, Tuple

from ..lint import Violation
from .model import Model, _cls_base

UNGUARDED = "unguarded-mutation"
LOCK_ORDER = "lock-order-cycle"


def audit(repo_root: str, model: Model = None) -> List[Violation]:
    m = model or Model.build(repo_root)
    out = _unguarded_mutations(m)
    out.extend(_lock_order_cycles(m))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule))


def _location_name(owner: Tuple) -> str:
    if owner[0] == "attr":
        return f"{_cls_base(owner[1])}.{owner[2]}"
    return f"{os.path.basename(owner[1])} global {owner[2]}"


def _short_fn(qname: str) -> str:
    return qname.split("::", 1)[-1]


def _unguarded_mutations(m: Model) -> List[Violation]:
    by_loc: Dict[Tuple, List] = {}
    for mut in m.mutations:
        if mut.owner[0] == "attr" and mut.owner[1] not in m.shared_classes:
            continue  # instance never crosses threads
        by_loc.setdefault(mut.owner, []).append(mut)

    out: List[Violation] = []
    for owner, muts in sorted(by_loc.items(),
                              key=lambda kv: str(kv[0])):
        live = [x for x in muts if not x.waived]
        if not live:
            continue
        if all(x.const_flag for x in live):
            continue  # atomic flag: only constant rebinds
        roles = set()
        for x in live:
            roles |= m.roles_of(x.func)
        roles.discard("")
        if len(roles) < 2:
            continue
        guard = m.effective_held(live[0])
        for x in live[1:]:
            guard &= m.effective_held(x)
        if guard:
            continue
        live.sort(key=lambda x: (x.relpath, x.line))
        fns = sorted({_short_fn(x.func) for x in live})
        anchor = live[0]
        out.append(Violation(
            UNGUARDED, anchor.relpath, anchor.line,
            f"{_location_name(owner)} is mutated from roles "
            f"{{{', '.join(sorted(roles))}}} with no lock held at every "
            f"site (mutators: {', '.join(fns)}); guard every site with "
            f"one lock, use a sanctioned lock-free type, or waive with "
            f"'# concurrency: <reason>'"))
    return out


def _lock_order_cycles(m: Model) -> List[Violation]:
    edges: Dict[str, Dict[str, Tuple[str, int]]] = {}
    for acq in m.acquires:
        fn = m.functions.get(acq.func)
        entry = fn.entry_locks if fn and fn.entry_locks else frozenset()
        for held in acq.held_before | entry:
            if held == acq.lock:
                continue  # reentrant re-acquire (RLock/Condition)
            edges.setdefault(held, {}).setdefault(
                acq.lock, (acq.relpath, acq.line))

    # Tarjan SCC over the lock digraph
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on_stack = set()
    sccs: List[List[str]] = []
    counter = [0]
    nodes = sorted(set(edges) | {t for d in edges.values() for t in d})

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(edges.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                comp.append(w)
                if w == v:
                    break
            sccs.append(comp)

    for v in nodes:
        if v not in index:
            strongconnect(v)

    out: List[Violation] = []
    for comp in sccs:
        if len(comp) < 2:
            continue
        comp = sorted(comp)
        # witness: the first edge inside the component
        witness = None
        for a in comp:
            for b, site in sorted(edges.get(a, {}).items()):
                if b in comp:
                    witness = site
                    break
            if witness:
                break
        rel, line = witness if witness else ("", 0)
        out.append(Violation(
            LOCK_ORDER, rel, line,
            f"lock-order cycle between {{{', '.join(comp)}}}: these "
            f"locks are acquired while holding each other in opposite "
            f"orders (deadlock potential); pick one global acquisition "
            f"order"))
    return out
