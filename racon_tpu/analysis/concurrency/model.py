"""The repo model the concurrency auditor reasons over.

One pass over every linted source file builds:

* a function table (module functions, methods, nested defs, plus a
  ``<module>`` pseudo-function per file for module-level code);
* per-class attribute *tags* — which attrs hold locks, sanctioned
  lock-free types (queues/events/GuardedStats), or instances of other
  repo classes (from ``self.x = Expr`` with constructor-call and
  parameter-annotation typing);
* every shared-state **mutation** (attr rebind/augment, container
  store, mutating method call) with the lexically-held lock set;
* every lock **acquisition** (``with <lock>:``) with what was already
  held — the edges of the lock-order digraph;
* the intra-repo **call graph** with per-site held-lock sets;
* **thread entries**: ``threading.Thread(target=..., name=...)`` sites,
  the resolved target function and the patternized role name.

Then three fixpoints:

* *shared classes* — classes whose instances cross threads: seeds are
  classes owning a lock attr, classes stored into module globals, and
  classes whose bound methods are thread targets; the closure follows
  stores into shared attrs/containers and constructor-argument flow
  (``Job(spec=spec)`` shares JobSpec once Job is shared);
* *roles* — each thread entry seeds its role on the target function;
  module-level code and uncalled functions seed ``main``; roles flow
  caller -> callee to a fixpoint;
* *entry locks* — locks provably held on **every** path into a
  function (intersection over call sites of caller-entry + site-held),
  so ``_helper_locked``-style callees are credited with the guard.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .. import astcache
from ..lint import iter_source_files
from . import roles as roles_mod

#: Method names that mutate their receiver in place.
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popitem", "clear",
    "update", "add", "discard", "setdefault", "__setitem__",
})

_MAIN = "main"


@dataclass
class Mutation:
    owner: Tuple          # ("attr", cls_q, attr) | ("global", relpath, name)
    relpath: str
    line: int
    func: str             # qname of the enclosing function
    held: frozenset       # lexically-held locks at the site
    waived: bool
    const_flag: bool      # plain rebind to a constant (atomic flag write)


@dataclass
class Acquire:
    lock: str
    relpath: str
    line: int
    func: str
    held_before: frozenset


@dataclass
class CallSite:
    caller: str
    callee: str
    relpath: str
    line: int
    held: frozenset


@dataclass
class ThreadEntry:
    target: Optional[str]  # qname of the resolved target function
    role: str
    relpath: str
    line: int
    creator: str


@dataclass
class FunctionInfo:
    qname: str
    relpath: str
    name: str
    line: int
    cls: Optional[str]                     # owning class qname
    waived: bool = False                   # `# concurrency:` on def line
    roles: Set[str] = field(default_factory=set)
    entry_locks: Optional[frozenset] = None  # None = not yet known


@dataclass
class ClassInfo:
    qname: str
    relpath: str
    name: str
    line: int
    waived: bool = False                   # `# concurrency:` on class line
    attr_tags: Dict[str, Tuple] = field(default_factory=dict)


def _attr_chain(node) -> Optional[List[str]]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def _module_dotted(relpath: str) -> str:
    rel = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _cls_base(cls_qname: str) -> str:
    return cls_qname.rsplit("::", 1)[-1]


class Model:
    def __init__(self, repo_root: str):
        self.repo_root = repo_root
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.mutations: List[Mutation] = []
        self.acquires: List[Acquire] = []
        self.calls: List[CallSite] = []
        self.thread_entries: List[ThreadEntry] = []
        self.shared_classes: Set[str] = set()
        self.lines: Dict[str, List[str]] = {}     # relpath -> source lines
        self.trees: Dict[str, ast.Module] = {}
        # sharedness flow edges, resolved during the closure
        self._global_stored: Set[str] = set()     # class qnames
        self._attr_flows: List[Tuple[str, str]] = []  # (owner_cls, stored)
        self._ctor_flows: List[Tuple[str, str]] = []  # (ctor_cls, arg_cls)
        # resolution tables
        self._mod_by_dotted: Dict[str, str] = {}  # dotted -> relpath
        self._ns: Dict[str, Dict[str, Tuple]] = {}  # relpath -> name -> sym
        self._funcs_by_parent: Dict[str, Dict[str, str]] = {}
        self._callers: Dict[str, List[CallSite]] = {}
        self._def_nodes: Dict[Tuple[str, int, str], ast.AST] = {}
        self._module_globals: Dict[str, Set[str]] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, repo_root: str,
              paths: Optional[List[str]] = None) -> "Model":
        m = cls(repo_root)
        rels = []
        for rel in (paths if paths is not None else
                    iter_source_files(repo_root)):
            parsed = astcache.load(repo_root, rel)
            if parsed.tree is None:
                continue  # run_lint already reports parse errors
            source, tree = parsed.source, parsed.tree
            m.lines[rel] = source.splitlines()
            m.trees[rel] = tree
            m._mod_by_dotted[_module_dotted(rel)] = rel
            rels.append(rel)
        for rel in rels:
            m._index_file(rel, m.trees[rel])
        for rel in rels:
            m._build_namespace(rel, m.trees[rel])
        for rel in rels:
            m._tag_classes(rel, m.trees[rel])
        for rel in rels:
            _FileWalker(m, rel).walk_module(m.trees[rel])
        m._resolve_shared_classes()
        m._resolve_roles()
        m._resolve_entry_locks()
        return m

    def waived_line(self, relpath: str, line: int) -> bool:
        lines = self.lines.get(relpath, [])
        if 1 <= line <= len(lines):
            return roles_mod.waiver_reason(lines[line - 1]) is not None
        return False

    def _index_file(self, rel: str, tree: ast.Module) -> None:
        """First pass: register every class/function qname in the file."""
        mod_fn = f"{rel}::<module>"
        self.functions[mod_fn] = FunctionInfo(mod_fn, rel, "<module>", 0,
                                              None)
        self._funcs_by_parent.setdefault(rel, {})
        self._module_globals[rel] = {
            t.id for stmt in tree.body
            if isinstance(stmt, (ast.Assign, ast.AnnAssign))
            for t in (stmt.targets if isinstance(stmt, ast.Assign)
                      else [stmt.target])
            if isinstance(t, ast.Name)}

        def visit(node, parent_q: str, cls_q: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    if cls_q and parent_q == cls_q:
                        q = f"{cls_q}.{child.name}"
                    elif parent_q == rel:
                        q = f"{rel}::{child.name}"
                    else:
                        q = f"{parent_q}.<locals>.{child.name}"
                    self.functions[q] = FunctionInfo(
                        q, rel, child.name, child.lineno, cls_q,
                        waived=self.waived_line(rel, child.lineno))
                    self._funcs_by_parent.setdefault(parent_q, {})[
                        child.name] = q
                    self._def_nodes[(rel, child.lineno, child.name)] = child
                    visit(child, q, cls_q)
                elif isinstance(child, ast.ClassDef):
                    cq = f"{rel}::{child.name}"
                    self.classes[cq] = ClassInfo(
                        cq, rel, child.name, child.lineno,
                        waived=self.waived_line(rel, child.lineno))
                    visit(child, cq, cq)

        visit(tree, rel, None)

    def _build_namespace(self, rel: str, tree: ast.Module) -> None:
        """Imports + module-level defs -> a per-file symbol table."""
        ns: Dict[str, Tuple] = {}
        pkg_parts = _module_dotted(rel).split(".")
        if not rel.endswith("/__init__.py") and rel != "__init__.py":
            pkg_parts = pkg_parts[:-1]
        for node in tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        ns[alias.asname] = ("mod", alias.name)
                    else:
                        head = alias.name.split(".")[0]
                        ns[head] = ("mod", head)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    keep = len(pkg_parts) - (node.level - 1)
                    base = pkg_parts[:keep] if keep > 0 else []
                    src = ".".join(base + ([node.module]
                                           if node.module else []))
                else:
                    src = node.module or ""
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    ns[alias.asname or alias.name] = ("sym", src,
                                                      alias.name)
            elif isinstance(node, ast.ClassDef):
                ns[node.name] = ("class", f"{rel}::{node.name}")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ns[node.name] = ("func", f"{rel}::{node.name}")
        self._ns[rel] = ns
        # module-level lock globals (`_lock = threading.Lock()`)
        for node in tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                dotted = self._expand_dotted(ns, _raw_dotted(
                    node.value.func))
                if dotted and roles_mod.lock_call(dotted):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            ns[t.id] = ("lock", f"{rel}::{t.id}")

    def _tag_classes(self, rel: str, tree: ast.Module) -> None:
        """Attribute tags from ``self.x = ...`` in every method."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            cq = f"{rel}::{node.name}"
            info = self.classes.get(cq)
            if info is None:
                continue
            for stmt in ast.walk(node):
                tgt = val = None
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                    tgt, val = stmt.targets[0], stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    tgt, val = stmt.target, stmt.value
                else:
                    continue
                chain = _attr_chain(tgt)
                if not chain or len(chain) != 2 or chain[0] != "self":
                    continue
                attr = chain[1]
                tag = self._value_tag(rel, cq, attr, val)
                if tag and (attr not in info.attr_tags
                            or info.attr_tags[attr][0] == "class"):
                    info.attr_tags[attr] = tag

    def _value_tag(self, rel: str, cq: str, attr: str,
                   val) -> Optional[Tuple]:
        if not isinstance(val, ast.Call):
            return None
        dotted = self.dotted_in_ns(rel, val.func)
        if dotted:
            if roles_mod.lock_call(dotted):
                return ("lock", f"{_cls_base(cq)}.{attr}")
            if roles_mod.sanctioned_call(dotted):
                return ("sanct",)
        sym = self.resolve_symbol(rel, val.func)
        if sym and sym[0] == "class":
            return ("class", sym[1])
        return None

    def _expand_dotted(self, ns: Dict[str, Tuple], dotted: str) -> str:
        """Expand the leading name of a dotted chain through imports."""
        if not dotted:
            return ""
        head, _, rest = dotted.partition(".")
        sym = ns.get(head)
        if sym and sym[0] == "mod":
            return sym[1] + ("." + rest if rest else "")
        if sym and sym[0] == "sym":
            full = (sym[1] + "." + sym[2]) if sym[1] else sym[2]
            return full + ("." + rest if rest else "")
        return dotted

    def dotted_in_ns(self, rel: str, node) -> str:
        """Import-resolved dotted name of a call target, '' if opaque."""
        return self._expand_dotted(self._ns.get(rel, {}),
                                   _raw_dotted(node))

    def resolve_symbol(self, rel: str, node) -> Optional[Tuple]:
        """Resolve a Name/Attribute to ("func", q) / ("class", q) /
        ("lock", id) across modules, following one import hop."""
        chain = _attr_chain(node)
        if not chain:
            return None
        ns = self._ns.get(rel, {})
        sym = ns.get(chain[0])
        if sym is None:
            return None
        if sym[0] in ("func", "class", "lock"):
            if len(chain) == 1:
                return sym
            if sym[0] == "class" and len(chain) == 2:
                q = f"{sym[1]}.{chain[1]}"
                return ("func", q) if q in self.functions else None
            return None
        if sym[0] == "sym":
            target_rel = self._mod_by_dotted.get(sym[1])
            if target_rel is not None:
                res = self._member(target_rel, sym[2], chain[1:])
                if res is not None:
                    return res
            # `from pkg import submodule` — sym names a module
            sub_rel = self._mod_by_dotted.get(
                (sym[1] + "." if sym[1] else "") + sym[2])
            if sub_rel is not None and len(chain) >= 2:
                return self._member(sub_rel, chain[1], chain[2:])
            return None
        if sym[0] == "mod":
            target_rel = self._mod_by_dotted.get(sym[1])
            if target_rel is not None and len(chain) >= 2:
                return self._member(target_rel, chain[1], chain[2:])
        return None

    def _member(self, target_rel: str, name: str, rest: List[str],
                _seen=None) -> Optional[Tuple]:
        seen = _seen or set()
        if (target_rel, name) in seen:
            return None  # re-export cycle (pkg __init__ loops)
        seen.add((target_rel, name))
        sym = self._ns.get(target_rel, {}).get(name)
        if sym is None:
            return None
        if sym[0] == "class" and rest:
            q = f"{sym[1]}.{rest[0]}"
            return ("func", q) if q in self.functions else None
        if sym[0] in ("func", "class", "lock") and not rest:
            return sym
        if sym[0] == "sym":  # re-export chain (one more hop)
            target2 = self._mod_by_dotted.get(sym[1])
            if target2 is not None:
                return self._member(target2, sym[2], rest, seen)
        return None

    def def_node(self, qname: str):
        fn = self.functions.get(qname)
        if fn is None:
            return None
        return self._def_nodes.get((fn.relpath, fn.line, fn.name))

    def is_module_global(self, rel: str, name: str) -> bool:
        return name in self._module_globals.get(rel, ())

    # -- fixpoints ---------------------------------------------------------

    def _resolve_shared_classes(self) -> None:
        shared = set(self._global_stored)
        for cq, info in self.classes.items():
            if any(t[0] == "lock" for t in info.attr_tags.values()):
                shared.add(cq)
        for te in self.thread_entries:
            if te.target and te.target in self.functions:
                cls = self.functions[te.target].cls
                if cls:
                    shared.add(cls)
        changed = True
        while changed:
            changed = False
            for owner, stored in self._attr_flows:
                if owner in shared and stored not in shared:
                    shared.add(stored)
                    changed = True
            for ctor, arg in self._ctor_flows:
                if ctor in shared and arg not in shared:
                    shared.add(arg)
                    changed = True
        self.shared_classes = shared

    def _resolve_roles(self) -> None:
        targets = {te.target for te in self.thread_entries if te.target}
        self._callers = {}
        for cs in self.calls:
            self._callers.setdefault(cs.callee, []).append(cs)
        for q, fn in self.functions.items():
            if fn.name == "<module>":
                fn.roles.add(_MAIN)
            elif q not in targets and q not in self._callers:
                fn.roles.add(_MAIN)
        for te in self.thread_entries:
            if te.target and te.target in self.functions:
                self.functions[te.target].roles.add(te.role)
        changed = True
        while changed:
            changed = False
            for cs in self.calls:
                src = self.functions.get(cs.caller)
                dst = self.functions.get(cs.callee)
                if src is None or dst is None:
                    continue
                if not src.roles <= dst.roles:
                    dst.roles |= src.roles
                    changed = True

    def _resolve_entry_locks(self) -> None:
        targets = {te.target for te in self.thread_entries if te.target}
        forced = set(targets)
        for q, fn in self.functions.items():
            if fn.name == "<module>" or q not in self._callers:
                forced.add(q)
        for _ in range(50):
            changed = False
            for q, fn in self.functions.items():
                contribs = [frozenset()] if q in forced else []
                for cs in self._callers.get(q, ()):
                    caller = self.functions.get(cs.caller)
                    if caller is None or caller.entry_locks is None:
                        continue  # unknown caller entry = universe; skip
                    contribs.append(caller.entry_locks | cs.held)
                if not contribs:
                    continue
                new = contribs[0]
                for c in contribs[1:]:
                    new = new & c
                if new != fn.entry_locks:
                    fn.entry_locks = new
                    changed = True
            if not changed:
                break
        for fn in self.functions.values():
            if fn.entry_locks is None:
                fn.entry_locks = frozenset()

    # -- queries -----------------------------------------------------------

    def effective_held(self, mut: Mutation) -> frozenset:
        fn = self.functions.get(mut.func)
        entry = fn.entry_locks if fn and fn.entry_locks else frozenset()
        return mut.held | entry

    def roles_of(self, qname: str) -> Set[str]:
        fn = self.functions.get(qname)
        return fn.roles if fn else set()


def _raw_dotted(node) -> str:
    chain = _attr_chain(node)
    return ".".join(chain) if chain else ""


def _local_names(node) -> Set[str]:
    """Names bound locally in a function body (not through nested defs)."""
    out: Set[str] = set()

    def visit(n):
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                out.add(child.id)
            visit(child)

    if hasattr(node, "body"):
        for stmt in node.body:
            visit(stmt)
            if isinstance(stmt, ast.Name) and isinstance(
                    stmt.ctx, (ast.Store, ast.Del)):
                out.add(stmt.id)
    return out


class _FileWalker:
    """Walks one file's functions, recording mutations / acquires /
    calls / thread entries with the lexical held-lock stack."""

    def __init__(self, model: Model, rel: str):
        self.m = model
        self.rel = rel
        self.ns = model._ns.get(rel, {})
        self.q = f"{rel}::<module>"
        self.cls: Optional[str] = None
        self.env: Dict[str, Tuple] = {}
        self.held: List[str] = []
        self.globals_decl: Set[str] = set()
        self.locals: Set[str] = set()
        self.module_level = True
        self.is_init = False

    def walk_module(self, tree: ast.Module) -> None:
        self._walk_function(f"{self.rel}::<module>", tree, None, {},
                            module_level=True)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = self._qname_of_def(node)
                if q is None:
                    continue
                cls = self.m.functions[q].cls
                env = self._param_env(node, cls)
                self._walk_function(q, node, cls, env)

    def _qname_of_def(self, node) -> Optional[str]:
        for (rel, line, name), n in self.m._def_nodes.items():
            if n is node:
                fn = self.m.functions
                for q, info in fn.items():
                    if info.relpath == rel and info.line == line \
                            and info.name == name:
                        return q
        return None

    def _param_env(self, node, cls: Optional[str]) -> Dict[str, Tuple]:
        env: Dict[str, Tuple] = {}
        args = list(getattr(node.args, "posonlyargs", [])) \
            + list(node.args.args) + list(node.args.kwonlyargs)
        for a in args:
            if a.arg == "self" and cls:
                env["self"] = ("class", cls)
            elif a.annotation is not None:
                tag = self._annotation_tag(a.annotation)
                if tag:
                    env[a.arg] = tag
        return env

    def _annotation_tag(self, ann) -> Optional[Tuple]:
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            try:
                ann = ast.parse(ann.value, mode="eval").body
            except SyntaxError:
                return None
        if isinstance(ann, ast.Subscript):  # Optional[X] / List[X]
            return self._annotation_tag(ann.slice)
        sym = self.m.resolve_symbol(self.rel, ann)
        if sym and sym[0] == "class":
            return ("class", sym[1])
        return None

    # -- per-function walk -------------------------------------------------

    def _walk_function(self, q: str, node, cls: Optional[str],
                       env: Dict[str, Tuple],
                       module_level: bool = False) -> None:
        self.q = q
        self.cls = cls
        self.env = dict(env)
        self.held = []
        self.globals_decl = set()
        self.locals = _local_names(node)
        self.module_level = module_level
        self.is_init = bool(cls) and q.endswith((".__init__",
                                                 ".__post_init__"))
        for stmt in getattr(node, "body", []):
            self._visit(stmt)

    def _visit(self, node) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # walked separately
        if isinstance(node, ast.Global):
            self.globals_decl.update(node.names)
            return
        if isinstance(node, ast.With):
            self._visit_with(node)
            return
        if isinstance(node, ast.Assign):
            self._record_assign(node.targets, node.value,
                                aug=False, line=node.lineno)
        elif isinstance(node, ast.AugAssign):
            self._record_assign([node.target], node.value,
                                aug=True, line=node.lineno)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            self._record_assign([node.target], node.value,
                                aug=False, line=node.lineno)
        if isinstance(node, ast.Call):
            self._visit_call(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    def _visit_with(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            self._visit(item.context_expr)
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                self.m.acquires.append(Acquire(
                    lock, self.rel, node.lineno, self.q,
                    frozenset(self.held)))
                self.held.append(lock)
                pushed += 1
        for stmt in node.body:
            self._visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def _lock_of(self, expr) -> Optional[str]:
        tag = self._type_of(expr)
        if tag and tag[0] == "lock":
            return tag[1]
        return None

    def _type_of(self, expr) -> Optional[Tuple]:
        if isinstance(expr, ast.Name):
            if expr.id in self.env:
                return self.env[expr.id]
            sym = self.ns.get(expr.id)
            if sym and sym[0] in ("lock", "class"):
                return sym
            return None
        if isinstance(expr, ast.Attribute):
            base = self._type_of(expr.value)
            if base and base[0] == "class":
                info = self.m.classes.get(base[1])
                if info:
                    return info.attr_tags.get(expr.attr)
            sym = self.m.resolve_symbol(self.rel, expr)
            if sym and sym[0] == "lock":
                return sym
            return None
        if isinstance(expr, ast.Call):
            dotted = self.m.dotted_in_ns(self.rel, expr.func)
            if dotted and roles_mod.lock_call(dotted):
                return ("lock", f"{self.rel}:{expr.lineno}")
            if dotted and roles_mod.sanctioned_call(dotted):
                return ("sanct",)
            sym = self.m.resolve_symbol(self.rel, expr.func)
            if sym and sym[0] == "class":
                return ("class", sym[1])
            if sym and sym[0] == "func":
                fn_node = self.m.def_node(sym[1])
                if fn_node is not None and fn_node.returns is not None:
                    other = _FileWalker(self.m,
                                        self.m.functions[sym[1]].relpath)
                    return other._annotation_tag(fn_node.returns)
            return None
        return None

    # -- mutations ---------------------------------------------------------

    def _global_owner(self, name: str) -> Optional[Tuple]:
        if name in self.globals_decl:
            return ("global", self.rel, name)
        if name not in self.locals and name not in self.env \
                and self.m.is_module_global(self.rel, name):
            return ("global", self.rel, name)
        return None

    def _owner_of(self, target) -> Optional[Tuple]:
        """Shared-state owner of a store target, None if local."""
        if isinstance(target, ast.Subscript):
            return self._owner_of_expr(target.value)
        if isinstance(target, ast.Name):
            if self.module_level:
                return None  # module-level assignment = initialization
            return ("global", self.rel, target.id) \
                if target.id in self.globals_decl else None
        if isinstance(target, ast.Attribute):
            base = self._type_of(target.value)
            if base and base[0] == "class":
                return ("attr", base[1], target.attr)
            return None
        return None

    def _owner_of_expr(self, expr) -> Optional[Tuple]:
        """Owner of a read expression mutated through
        (``self._queues[lane].append(x)`` -> (Scheduler, _queues))."""
        if isinstance(expr, ast.Subscript):
            return self._owner_of_expr(expr.value)
        if isinstance(expr, ast.Attribute):
            base = self._type_of(expr.value)
            if base and base[0] == "class":
                return ("attr", base[1], expr.attr)
            return None
        if isinstance(expr, ast.Name):
            return self._global_owner(expr.id)
        return None

    def _record_mutation(self, owner: Tuple, line: int,
                         const_flag: bool) -> None:
        if self.module_level:
            return  # module-level code is single-threaded initialization
        if owner[0] == "attr":
            if self.is_init and self.cls == owner[1]:
                return  # constructing your own instance
            info = self.m.classes.get(owner[1])
            if info is not None:
                tag = info.attr_tags.get(owner[2])
                if tag and tag[0] in ("lock", "sanct"):
                    return
                if info.waived:
                    return
        fn = self.m.functions.get(self.q)
        waived = self.m.waived_line(self.rel, line) \
            or bool(fn and fn.waived)
        self.m.mutations.append(Mutation(
            owner, self.rel, line, self.q, frozenset(self.held),
            waived, const_flag))

    def _record_assign(self, targets, value, aug: bool, line: int) -> None:
        vtag = self._type_of(value)
        const_flag = (not aug and isinstance(value, ast.Constant)
                      and value.value in (True, False, None))
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                for el in t.elts:
                    self._record_assign([el], value, aug, line)
                continue
            owner = self._owner_of(t)
            if owner is None and isinstance(t, ast.Name) \
                    and not self.module_level:
                owner = None if t.id in self.locals \
                    or not self.m.is_module_global(self.rel, t.id) \
                    else ("global", self.rel, t.id)
            if owner is None:
                if isinstance(t, ast.Name) and not aug:
                    if vtag is not None:
                        self.env[t.id] = vtag
                    else:
                        self.env.pop(t.id, None)
                continue
            self._record_mutation(owner, line, const_flag)
            self._record_flows(owner, value, vtag)
        if self.module_level:
            # still track sharedness: `_TRACKER = WedgeTracker()`
            for t in targets:
                if isinstance(t, ast.Name):
                    self._record_flows(("global", self.rel, t.id),
                                       value, vtag)

    def _record_flows(self, owner: Tuple, value, vtag) -> None:
        """Sharedness flow: storing a repo-class instance into a global
        or into another class's attr/container."""
        stored: Set[str] = set()
        if vtag and vtag[0] == "class":
            stored.add(vtag[1])
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                sym = self.m.resolve_symbol(self.rel, sub.func)
                if sym and sym[0] == "class":
                    stored.add(sym[1])
        for cq in stored:
            if owner[0] == "global":
                self.m._global_stored.add(cq)
            else:
                self.m._attr_flows.append((owner[1], cq))

    # -- calls -------------------------------------------------------------

    def _visit_call(self, node: ast.Call) -> None:
        dotted = self.m.dotted_in_ns(self.rel, node.func)
        if dotted == "threading.Thread":
            self._record_thread(node)
            return
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            owner = self._owner_of_expr(node.func.value)
            if owner is not None:
                if not (owner[0] == "attr"
                        and self._tag_is_safe(owner)):
                    self._record_mutation(owner, node.lineno, False)
                for arg in node.args:
                    self._record_flows(owner, arg, self._type_of(arg))
        callee = self._resolve_callee(node)
        if callee is not None:
            if callee[0] == "func":
                self.m.calls.append(CallSite(
                    self.q, callee[1], self.rel, node.lineno,
                    frozenset(self.held)))
            elif callee[0] == "class":
                # constructor-argument flow: Job(spec=spec, ...)
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]:
                    t = self._type_of(arg)
                    if t and t[0] == "class":
                        self.m._ctor_flows.append((callee[1], t[1]))

    def _tag_is_safe(self, owner: Tuple) -> bool:
        info = self.m.classes.get(owner[1])
        tag = info.attr_tags.get(owner[2]) if info else None
        return bool(tag and tag[0] in ("lock", "sanct"))

    def _resolve_callee(self, node: ast.Call) -> Optional[Tuple]:
        func = node.func
        if isinstance(func, ast.Name):
            # lexical scope chain: nested defs, then enclosing, then
            # module functions, then imports
            scope: Optional[str] = self.q
            while scope is not None:
                found = self.m._funcs_by_parent.get(scope, {}).get(func.id)
                if found:
                    return ("func", found)
                if ".<locals>." in scope:
                    scope = scope.rsplit(".<locals>.", 1)[0]
                elif scope != self.rel:
                    scope = self.rel
                else:
                    scope = None
            sym = self.m.resolve_symbol(self.rel, func)
            return sym if sym and sym[0] in ("func", "class") else None
        if isinstance(func, ast.Attribute):
            base = self._type_of(func.value)
            if base and base[0] == "class":
                q = f"{base[1]}.{func.attr}"
                return ("func", q) if q in self.m.functions else None
            sym = self.m.resolve_symbol(self.rel, func)
            return sym if sym and sym[0] in ("func", "class") else None
        return None

    def _record_thread(self, node: ast.Call) -> None:
        target_q = None
        role = None
        for kw in node.keywords:
            if kw.arg == "target":
                texpr = kw.value
                if isinstance(texpr, ast.Call) and texpr.args:
                    texpr = texpr.args[0]  # functools.partial(f, ...)
                if isinstance(texpr, (ast.Name, ast.Attribute)):
                    callee = self._resolve_callee(
                        ast.Call(func=texpr, args=[], keywords=[]))
                    if callee and callee[0] == "func":
                        target_q = callee[1]
            elif kw.arg == "name":
                role = _patternized_name(kw.value)
        if role is None:
            role = f"unnamed@{self.rel}:{node.lineno}"
        self.m.thread_entries.append(ThreadEntry(
            target_q, role, self.rel, node.lineno, self.q))


def _patternized_name(expr) -> Optional[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return expr.value
    if isinstance(expr, ast.JoinedStr):
        out = []
        for part in expr.values:
            if isinstance(part, ast.Constant):
                out.append(str(part.value))
            else:
                out.append("*")
        return "".join(out)
    return None
