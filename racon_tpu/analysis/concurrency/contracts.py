"""Contract extraction & cross-checking (Part B of the auditor).

Three contracts, all extracted statically from the analyzed tree:

* **Lattice edges** — the degradation-lattice edge set is derived from
  ``resilience/lattice.py``: the ``CONSENSUS_TIERS`` chain, the
  ``ALIGN_TIERS`` star-to-floor edges, every literal
  ``record_degrade("a", "b")`` call site repo-wide, and the parametric
  ``banded``/``sharded`` edges when ``record_band_fallback`` /
  ``record_shard_demotion`` are defined.  Every edge must have a test
  drill (a file under ``tests/`` mentioning both tiers plus a
  degradation keyword) and a failure-modes docs row (a ``|`` table row
  in ``docs/`` mentioning both tiers).
* **Fault points** — every name in ``faults.KNOWN_POINTS`` must appear
  in a test under ``tests/`` and in a docs table row; the fleet-scoped
  ones (``worker.*``/``pool.*``/``lease.*``) must additionally be
  claimed by a protocol-model transition (``fault-model``), so no
  control-plane injection point escapes the model checker.
* **Wire protocol** — producers/consumers in ``serve/server.py``,
  ``serve/client.py``, ``distrib/coordinator.py`` and
  ``distrib/worker.py`` are cross-checked field-for-field against the
  declared ``PROTOCOL`` / ``PAYLOADS`` literals in
  ``serve/protocol.py``.

Contracts degrade gracefully: a tree without ``lattice.py`` (or without
a declared ``PROTOCOL``) simply skips that section, so fixture
mini-trees exercise one contract at a time.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import astcache
from ..lint import Violation, iter_source_files

LATTICE_DRILL = "lattice-drill"
LATTICE_DOCS = "lattice-docs"
FAULT_DRILL = "fault-drill"
FAULT_DOCS = "fault-docs"
FAULT_MODEL = "fault-model"
PROTOCOL_RULE = "protocol-mismatch"

_LATTICE_REL = "racon_tpu/resilience/lattice.py"
_FAULTS_REL = "racon_tpu/resilience/faults.py"
_PROTOCOL_REL = "racon_tpu/serve/protocol.py"

#: The four wire surfaces: (surface, consumer file, producer file).
_SURFACES = (
    ("serve", "racon_tpu/serve/server.py", "racon_tpu/serve/client.py"),
    ("distrib", "racon_tpu/distrib/coordinator.py",
     "racon_tpu/distrib/worker.py"),
)

#: A test file only counts as a lattice-edge drill when it also talks
#: about degradation, not merely mentions two tier names.
_DEGRADE_RE = re.compile(r"degrad|demot|fallback|lattice|bisect", re.I)


def audit(repo_root: str) -> List[Violation]:
    tests = _test_texts(repo_root)
    rows = _doc_rows(repo_root)
    out: List[Violation] = []
    out.extend(_lattice_checks(repo_root, tests, rows))
    out.extend(_fault_checks(repo_root, tests, rows))
    out.extend(_protocol_checks(repo_root))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule, v.message))


# -- shared helpers ---------------------------------------------------------

def _parse(repo_root: str, rel: str) -> Optional[ast.Module]:
    return astcache.load(repo_root, rel).tree


def _test_texts(repo_root: str) -> List[Tuple[str, str]]:
    out = []
    tests_dir = os.path.join(repo_root, "tests")
    for dirpath, dirnames, filenames in os.walk(tests_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            try:
                with open(full) as f:
                    text = f.read()
            except OSError:
                continue
            rel = os.path.relpath(full, repo_root).replace(os.sep, "/")
            out.append((rel, text))
    return out


def _doc_rows(repo_root: str) -> List[str]:
    """Every markdown table row (``|``-prefixed line) under docs/."""
    rows: List[str] = []
    docs_dir = os.path.join(repo_root, "docs")
    for dirpath, _dirnames, filenames in os.walk(docs_dir):
        for fn in sorted(filenames):
            if not fn.endswith(".md"):
                continue
            try:
                with open(os.path.join(dirpath, fn)) as f:
                    for line in f:
                        if line.lstrip().startswith("|"):
                            rows.append(line)
            except OSError:
                continue
    return rows


def _token_re(token: str) -> "re.Pattern":
    return re.compile(r"(?<![A-Za-z0-9_.])" + re.escape(token)
                      + r"(?![A-Za-z0-9_])")


def _has_tokens(text: str, tokens: Sequence[str]) -> bool:
    return all(_token_re(t).search(text) for t in tokens)


# -- lattice edges ----------------------------------------------------------

def _tuple_of_strs(node) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for el in node.elts:
            if not (isinstance(el, ast.Constant)
                    and isinstance(el.value, str)):
                return None
            vals.append(el.value)
        return tuple(vals)
    return None


def lattice_edges(repo_root: str) -> List[Tuple[Tuple[str, ...], int]]:
    """The extracted edge set: [(tokens, anchor_line)].  Two-token
    entries are ``from -> to`` tier edges; one-token entries are the
    parametric ``banded`` / ``sharded`` orthogonal edges."""
    tree = _parse(repo_root, _LATTICE_REL)
    if tree is None:
        return []
    edges: Dict[Tuple[str, ...], int] = {}

    def add(tokens: Tuple[str, ...], line: int) -> None:
        edges.setdefault(tokens, line)

    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            tiers = _tuple_of_strs(node.value)
            if not tiers:
                continue
            if name == "CONSENSUS_TIERS":
                for a, b in zip(tiers, tiers[1:]):
                    add((a, b), node.lineno)
            elif name == "ALIGN_TIERS":
                floor = tiers[-1]
                for a in tiers[:-1]:
                    add((a, floor), node.lineno)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name == "record_band_fallback":
                add(("banded",), node.lineno)
            elif node.name == "record_shard_demotion":
                add(("sharded",), node.lineno)

    # literal record_degrade("a", "b") call sites, repo-wide
    for rel in iter_source_files(repo_root):
        t = _parse(repo_root, rel)
        if t is None:
            continue
        for node in ast.walk(t):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "record_degrade"
                    and len(node.args) >= 2
                    and all(isinstance(a, ast.Constant)
                            and isinstance(a.value, str)
                            for a in node.args[:2])):
                add((node.args[0].value, node.args[1].value), node.lineno)
    return sorted(edges.items(), key=lambda kv: kv[0])


def _lattice_checks(repo_root: str, tests, rows) -> List[Violation]:
    out: List[Violation] = []
    for tokens, line in lattice_edges(repo_root):
        label = " -> ".join(tokens) if len(tokens) > 1 \
            else f"<tier>+{tokens[0]} -> <tier>"
        if not any(_has_tokens(text, tokens) and _DEGRADE_RE.search(text)
                   for _rel, text in tests):
            out.append(Violation(
                LATTICE_DRILL, _LATTICE_REL, line,
                f"lattice edge {label} has no test drill: no file under "
                f"tests/ mentions {_fmt_tokens(tokens)} together with a "
                f"degradation keyword"))
        if not any(_has_tokens(row, tokens) for row in rows):
            out.append(Violation(
                LATTICE_DOCS, _LATTICE_REL, line,
                f"lattice edge {label} has no failure-modes docs row: no "
                f"markdown table row under docs/ mentions "
                f"{_fmt_tokens(tokens)}"))
    return out


def _fmt_tokens(tokens: Sequence[str]) -> str:
    return " and ".join(f"'{t}'" for t in tokens)


# -- fault points -----------------------------------------------------------

def fault_points(repo_root: str) -> List[Tuple[str, int]]:
    tree = _parse(repo_root, _FAULTS_REL)
    if tree is None:
        return []
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "KNOWN_POINTS":
            val = node.value
            if isinstance(val, ast.Call) and val.args:
                val = val.args[0]
            if isinstance(val, ast.Set):
                return sorted(
                    (el.value, el.lineno) for el in val.elts
                    if isinstance(el, ast.Constant)
                    and isinstance(el.value, str))
    return []


def _fault_checks(repo_root: str, tests, rows) -> List[Violation]:
    out: List[Violation] = []
    for point, line in fault_points(repo_root):
        pat = _token_re(point)
        if not any(pat.search(text) for _rel, text in tests):
            out.append(Violation(
                FAULT_DRILL, _FAULTS_REL, line,
                f"fault point {point} has no test drill: no file under "
                f"tests/ mentions it"))
        if not any(pat.search(row) for row in rows):
            out.append(Violation(
                FAULT_DOCS, _FAULTS_REL, line,
                f"fault point {point} has no docs table row: no markdown "
                f"table row under docs/ mentions it"))
    out.extend(_fault_model_checks(repo_root))
    return out


def _fault_model_checks(repo_root: str) -> List[Violation]:
    """Every fleet-scoped KNOWN_POINTS entry must be claimed by a
    protocol-model transition — a fault point the model does not know
    about is a failure mode no interleaving ever exercises.  Skipped
    when the tree carries no protocol model (fixture mini-trees)."""
    from ..protocol import conformance      # local: avoids an import cycle
    entries, _ = conformance._transitions(repo_root)
    if entries is None:
        return []
    claimed = {e[3] for e in entries if e[3] is not None}
    out: List[Violation] = []
    for point, line in fault_points(repo_root):
        if (point.startswith(conformance.FLEET_PREFIXES)
                and point not in claimed):
            out.append(Violation(
                FAULT_MODEL, _FAULTS_REL, line,
                f"fleet fault point {point} is not claimed by any "
                f"protocol-model transition "
                f"(analysis/protocol/model.py TRANSITIONS)"))
    return out


# -- wire protocol ----------------------------------------------------------

def _declared_protocol(repo_root: str):
    tree = _parse(repo_root, _PROTOCOL_REL)
    if tree is None:
        return None
    spec = common = payloads = None
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            name = node.targets[0].id
            try:
                value = ast.literal_eval(node.value)
            except ValueError:
                continue
            if name == "PROTOCOL":
                spec = value
            elif name == "COMMON_RESP":
                common = value
            elif name == "PAYLOADS":
                payloads = value
    if spec is None:
        return None
    return spec, tuple(common or ("ok", "error")), dict(payloads or {})


class _Reads:
    def __init__(self):
        self.strict: Set[str] = set()
        self.opt: Set[str] = set()
        self.allowed: Optional[Set[str]] = None  # from_dict universe


def _index_classes(tree: ast.Module) -> Dict[str, ast.ClassDef]:
    return {n.name: n for n in ast.walk(tree)
            if isinstance(n, ast.ClassDef)}


def _index_functions(tree: ast.Module) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _methods(cls: Optional[ast.ClassDef]) -> Dict[str, ast.FunctionDef]:
    if cls is None:
        return {}
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _param_names(func) -> List[str]:
    names = [a.arg for a in func.args.args]
    return names[1:] if names and names[0] in ("self", "cls") else names


def _collect_dict_reads(nodes, var: str, cls: Optional[ast.ClassDef],
                        all_classes: Dict[str, ast.ClassDef],
                        reads: _Reads, depth: int = 3) -> None:
    """Strict (``d["k"]``) and optional (``d.get("k")``) reads of dict
    ``var`` in ``nodes``, recursing through same-class helper methods
    and ``X.from_dict({k: v for k, v in d.items() if ...})``."""
    methods = _methods(cls)
    for top in nodes:
        for node in ast.walk(top):
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id == var \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                reads.strict.add(node.slice.value)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == var and f.attr == "get" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    reads.opt.add(node.args[0].value)
                elif depth > 0 and isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self" and f.attr in methods:
                    # self._helper(req): recurse with the matched param
                    for i, arg in enumerate(node.args):
                        if isinstance(arg, ast.Name) and arg.id == var:
                            params = _param_names(methods[f.attr])
                            if i < len(params):
                                _collect_dict_reads(
                                    methods[f.attr].body, params[i], cls,
                                    all_classes, reads, depth - 1)
                elif depth > 0 and isinstance(f, ast.Attribute) \
                        and f.attr == "from_dict" \
                        and isinstance(f.value, ast.Name) \
                        and node.args \
                        and _comprehension_over(node.args[0], var):
                    target = all_classes.get(f.value.id)
                    fd = _methods(target).get("from_dict")
                    if fd is not None:
                        params = _param_names(fd)
                        if params:
                            _collect_dict_reads(fd.body, params[0],
                                                target, all_classes,
                                                reads, depth - 1)
                            allowed = _from_dict_universe(fd, params[0])
                            if allowed is not None:
                                reads.allowed = allowed


def _comprehension_over(node, var: str) -> bool:
    """`{k: v for k, v in var.items() ...}`"""
    if not isinstance(node, ast.DictComp) or not node.generators:
        return False
    it = node.generators[0].iter
    return (isinstance(it, ast.Call) and isinstance(it.func, ast.Attribute)
            and it.func.attr == "items"
            and isinstance(it.func.value, ast.Name)
            and it.func.value.id == var)


def _from_dict_universe(func, param: str) -> Optional[Set[str]]:
    """The allowed-field set from a ``set(d) - {...}`` guard."""
    for node in ast.walk(func):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub) \
                and isinstance(node.left, ast.Call) \
                and isinstance(node.left.func, ast.Name) \
                and node.left.func.id == "set" \
                and node.left.args \
                and isinstance(node.left.args[0], ast.Name) \
                and node.left.args[0].id == param \
                and isinstance(node.right, ast.Set):
            vals = set()
            for el in node.right.elts:
                if isinstance(el, ast.Constant) \
                        and isinstance(el.value, str):
                    vals.add(el.value)
                else:
                    return None
            return vals
    return None


def _collect_returns(func, cls: Optional[ast.ClassDef],
                     depth: int = 3) -> List[ast.Dict]:
    """Response dict literals returned by ``func``, following
    ``return self._helper(...)`` one class-local hop at a time."""
    methods = _methods(cls)
    out: List[ast.Dict] = []
    for node in ast.walk(func):
        if not isinstance(node, ast.Return) or node.value is None:
            continue
        v = node.value
        if isinstance(v, ast.Dict):
            out.append(v)
        elif depth > 0 and isinstance(v, ast.Call) \
                and isinstance(v.func, ast.Attribute) \
                and isinstance(v.func.value, ast.Name) \
                and v.func.value.id == "self" \
                and v.func.attr in methods:
            out.extend(_collect_returns(methods[v.func.attr], cls,
                                        depth - 1))
    return out


def _dict_fields(d: ast.Dict) -> Tuple[Set[str], bool]:
    """(literal string keys, has-spread)."""
    fields: Set[str] = set()
    open_dict = False
    for k in d.keys:
        if k is None:
            open_dict = True
        elif isinstance(k, ast.Constant) and isinstance(k.value, str):
            fields.add(k.value)
        else:
            open_dict = True
    return fields, open_dict


def _find_dispatch(tree: ast.Module):
    """(func, enclosing class, req param, op var) of the consumer's
    dispatch function: the one doing ``op = <req>.get("op")``."""
    for cls in [None] + [n for n in ast.walk(tree)
                         if isinstance(n, ast.ClassDef)]:
        body = tree.body if cls is None else cls.body
        for func in body:
            if not isinstance(func, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            params = set(_param_names(func))
            for node in ast.walk(func):
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1 \
                        and isinstance(node.targets[0], ast.Name) \
                        and isinstance(node.value, ast.Call) \
                        and isinstance(node.value.func, ast.Attribute) \
                        and node.value.func.attr == "get" \
                        and isinstance(node.value.func.value, ast.Name) \
                        and node.value.func.value.id in params \
                        and node.value.args \
                        and isinstance(node.value.args[0], ast.Constant) \
                        and node.value.args[0].value == "op":
                    return (func, cls, node.value.func.value.id,
                            node.targets[0].id)
    return None


def _branches(func, op_var: str) -> Dict[str, list]:
    out: Dict[str, list] = {}
    for node in ast.walk(func):
        if isinstance(node, ast.If) \
                and isinstance(node.test, ast.Compare) \
                and isinstance(node.test.left, ast.Name) \
                and node.test.left.id == op_var \
                and len(node.test.ops) == 1 \
                and isinstance(node.test.ops[0], ast.Eq) \
                and isinstance(node.test.comparators[0], ast.Constant) \
                and isinstance(node.test.comparators[0].value, str):
            out[node.test.comparators[0].value] = node.body
    return out


def _fmt(fields) -> str:
    return ", ".join(sorted(fields))


def _check_consumer(repo_root: str, surface: str, rel: str, spec: dict,
                    common: tuple, payloads: dict) -> List[Violation]:
    tree = _parse(repo_root, rel)
    if tree is None:
        return []
    found = _find_dispatch(tree)
    if found is None:
        return []
    func, cls, req_var, op_var = found
    all_classes = _index_classes(tree)
    # JobSpec.from_dict may live in a sibling module (serve/session.py)
    sdir = os.path.dirname(rel)
    for fn in sorted(os.listdir(os.path.join(repo_root, sdir))
                     if os.path.isdir(os.path.join(repo_root, sdir))
                     else []):
        if fn.endswith(".py"):
            t = _parse(repo_root, f"{sdir}/{fn}")
            if t is not None:
                for name, node in _index_classes(t).items():
                    all_classes.setdefault(name, node)
    branches = _branches(func, op_var)
    out: List[Violation] = []
    for op in sorted(set(branches) - set(spec)):
        out.append(Violation(
            PROTOCOL_RULE, rel, func.lineno,
            f"{surface}: consumer handles op '{op}' that the declared "
            f"PROTOCOL does not define"))
    for op in sorted(set(spec) - set(branches)):
        out.append(Violation(
            PROTOCOL_RULE, rel, func.lineno,
            f"{surface}: declared op '{op}' has no consumer branch"))
    for op, body in sorted(branches.items()):
        decl = spec.get(op)
        if decl is None:
            continue
        req = set(decl.get("req", ()))
        opt = set(decl.get("opt", ()))
        reads = _Reads()
        _collect_dict_reads(body, req_var, cls, all_classes, reads)
        reads.strict.discard("op")
        reads.opt.discard("op")
        bad_strict = reads.strict - req
        if bad_strict:
            out.append(Violation(
                PROTOCOL_RULE, rel, body[0].lineno,
                f"{surface}: op '{op}' consumer strictly reads "
                f"field(s) {_fmt(bad_strict)} not declared required "
                f"(KeyError on a spec-conforming request)"))
        bad_opt = reads.opt - req - opt
        if bad_opt:
            out.append(Violation(
                PROTOCOL_RULE, rel, body[0].lineno,
                f"{surface}: op '{op}' consumer reads undeclared "
                f"field(s) {_fmt(bad_opt)}"))
        if reads.allowed is not None and reads.allowed != req | opt:
            out.append(Violation(
                PROTOCOL_RULE, rel, body[0].lineno,
                f"{surface}: op '{op}' consumer accepts field universe "
                f"{{{_fmt(reads.allowed)}}} but the spec declares "
                f"{{{_fmt(req | opt)}}}"))
        # response side of each branch
        resp_ok = set(decl.get("resp", ())) | set(common)
        shell = ast.Module(body=body, type_ignores=[])
        shell_fn = ast.FunctionDef(
            name=f"_branch_{op}", args=func.args, body=body,
            decorator_list=[], returns=None)
        for d in _collect_returns(shell_fn, cls):
            fields, _open = _dict_fields(d)
            extra = fields - resp_ok
            if extra:
                out.append(Violation(
                    PROTOCOL_RULE, rel, d.lineno,
                    f"{surface}: op '{op}' response carries undeclared "
                    f"field(s) {_fmt(extra)}"))
            for k, v in zip(d.keys, d.values):
                if k is None or not isinstance(k, ast.Constant):
                    continue
                pkey = f"{surface}.{op}.{k.value}"
                if pkey in payloads and isinstance(v, ast.Dict):
                    want = set(payloads[pkey])
                    got, popen = _dict_fields(v)
                    if not popen and got != want:
                        out.append(Violation(
                            PROTOCOL_RULE, rel, v.lineno,
                            f"{surface}: payload '{pkey}' produced with "
                            f"fields {{{_fmt(got)}}} but PAYLOADS "
                            f"declares {{{_fmt(want)}}}"))
        del shell
    return out


def _rpc_call_fields(node: ast.Call):
    """(op, fields, open) of a producer rpc call, else None.

    Two producer shapes: ``self.rpc(op="x", k=v, ...)`` (serve client)
    and ``rpc(f, {"op": "x", "k": v, ...})`` (distrib worker)."""
    f = node.func
    is_rpc = (isinstance(f, ast.Attribute) and f.attr == "rpc") or \
        (isinstance(f, ast.Name) and f.id == "rpc")
    if not is_rpc:
        return None
    op = None
    fields: Set[str] = set()
    open_call = False
    for kw in node.keywords:
        if kw.arg is None:
            open_call = True
        elif kw.arg == "op":
            if isinstance(kw.value, ast.Constant):
                op = kw.value.value
        else:
            fields.add(kw.arg)
    if op is None:
        for arg in node.args:
            if isinstance(arg, ast.Dict):
                dfields, dopen = _dict_fields(arg)
                if "op" in dfields:
                    for k, v in zip(arg.keys, arg.values):
                        if isinstance(k, ast.Constant) \
                                and k.value == "op" \
                                and isinstance(v, ast.Constant):
                            op = v.value
                    fields = dfields - {"op"}
                    open_call = open_call or dopen
                    break
    if op is None:
        return None
    return op, fields, open_call


def _check_producer(repo_root: str, surface: str, rel: str, spec: dict,
                    common: tuple, payloads: dict) -> List[Violation]:
    tree = _parse(repo_root, rel)
    if tree is None:
        return []
    out: List[Violation] = []
    module_fns = _index_functions(tree)
    resp_fields = {op: set(decl.get("resp", ())) | set(common)
                   for op, decl in spec.items()}

    for func in ast.walk(tree):
        if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        rpc_vars: Dict[str, str] = {}       # var -> op
        payload_vars: Dict[str, str] = {}   # var -> payload key
        for node in ast.walk(func):
            if not isinstance(node, (ast.Assign, ast.Expr, ast.Return)):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            sent = _rpc_call_fields(value)
            if sent is None:
                continue
            op, fields, open_call = sent
            decl = spec.get(op)
            if decl is None:
                out.append(Violation(
                    PROTOCOL_RULE, rel, value.lineno,
                    f"{surface}: producer sends op '{op}' that the "
                    f"declared PROTOCOL does not define"))
                continue
            req = set(decl.get("req", ()))
            opt = set(decl.get("opt", ()))
            if not open_call:
                missing = req - fields
                if missing:
                    out.append(Violation(
                        PROTOCOL_RULE, rel, value.lineno,
                        f"{surface}: op '{op}' producer omits required "
                        f"field(s) {_fmt(missing)}"))
                extra = fields - req - opt
                if extra:
                    out.append(Violation(
                        PROTOCOL_RULE, rel, value.lineno,
                        f"{surface}: op '{op}' producer sends "
                        f"undeclared field(s) {_fmt(extra)}"))
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                rpc_vars[node.targets[0].id] = op

        # response reads on tracked rpc-result vars
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Subscript) \
                    and isinstance(node.value.value, ast.Name) \
                    and node.value.value.id in rpc_vars \
                    and isinstance(node.value.slice, ast.Constant) \
                    and isinstance(node.value.slice.value, str):
                op = rpc_vars[node.value.value.id]
                pkey = f"{surface}.{op}.{node.value.slice.value}"
                if pkey in payloads:
                    payload_vars[node.targets[0].id] = pkey
            field = line = None
            if isinstance(node, ast.Subscript) \
                    and isinstance(node.value, ast.Name) \
                    and isinstance(node.slice, ast.Constant) \
                    and isinstance(node.slice.value, str):
                var, field, line = (node.value.id, node.slice.value,
                                    node.lineno)
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "get" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                var, field, line = (node.func.value.id,
                                    node.args[0].value, node.lineno)
            if field is None:
                continue
            if var in rpc_vars:
                op = rpc_vars[var]
                if field not in resp_fields.get(op, set()):
                    out.append(Violation(
                        PROTOCOL_RULE, rel, line,
                        f"{surface}: op '{op}' client reads undeclared "
                        f"response field '{field}'"))
            elif var in payload_vars:
                pkey = payload_vars[var]
                if field not in payloads[pkey]:
                    out.append(Violation(
                        PROTOCOL_RULE, rel, line,
                        f"{surface}: payload '{pkey}' consumer reads "
                        f"undeclared field '{field}'"))

        # payload vars handed whole to module helpers: recurse one hop
        for node in ast.walk(func):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in module_fns:
                for i, arg in enumerate(node.args):
                    if isinstance(arg, ast.Name) \
                            and arg.id in payload_vars:
                        helper = module_fns[node.func.id]
                        params = _param_names(helper)
                        if i >= len(params):
                            continue
                        pkey = payload_vars[arg.id]
                        reads = _Reads()
                        _collect_dict_reads(helper.body, params[i],
                                            None, {}, reads)
                        bad = (reads.strict | reads.opt) \
                            - set(payloads[pkey])
                        if bad:
                            out.append(Violation(
                                PROTOCOL_RULE, rel, helper.lineno,
                                f"{surface}: payload '{pkey}' consumer "
                                f"({node.func.id}) reads undeclared "
                                f"field(s) {_fmt(bad)}"))
    return out


def _protocol_checks(repo_root: str) -> List[Violation]:
    declared = _declared_protocol(repo_root)
    if declared is None:
        return []
    protocol, common, payloads = declared
    out: List[Violation] = []
    for surface, consumer_rel, producer_rel in _SURFACES:
        spec = protocol.get(surface)
        if not spec:
            continue
        out.extend(_check_consumer(repo_root, surface, consumer_rel,
                                   spec, common, payloads))
        out.extend(_check_producer(repo_root, surface, producer_rel,
                                   spec, common, payloads))
    return out
