"""Registered thread roles, sanctioned types, and the waiver syntax.

A *role* is the identity of a thread as far as the auditor is
concerned: every ``threading.Thread(name=...)`` must carry a name whose
pattern is registered here (enforced by the ``thread-discipline`` lint
rule), and the lock-discipline audit reasons about which roles reach
which mutation sites.  The implicit ``main`` role covers everything
reachable from module level / uncalled public entry points.

Waiver syntax — a mutation the auditor flags can be waived with a
trailing comment on the mutation line, the enclosing ``def`` line, or
the owning ``class`` line:

    self.calls[point] = n + 1  # concurrency: guarded by caller's _cv
"""

from __future__ import annotations

import re
from fnmatch import fnmatchcase

#: Registered thread-role name patterns (fnmatch globs) -> description.
#: The lint rule requires every Thread name to match one of these; the
#: docs table in docs/static-analysis.md is generated from this dict.
THREAD_ROLE_PATTERNS = {
    "serve-accept": "serve daemon accept loop (serve/server.py)",
    "serve-conn": "serve per-connection request handler",
    "serve-*-lane": "scheduler lane worker (serve/scheduler.py)",
    "distrib-accept": "coordinator accept loop (distrib/coordinator.py)",
    "distrib-conn": "coordinator per-worker connection handler",
    "distrib-heartbeat": "worker lease-renewal loop (distrib/worker.py)",
    "fleet-accept": "fleet plane accept loop (fleet/plane.py)",
    "fleet-conn": "fleet plane per-worker connection handler",
    "fleet-monitor": "fleet plane autoscaler/lease monitor "
                     "(fleet/plane.py)",
    "mem-watchdog": "memory-budget RSS sampler (resilience/budget.py)",
    "poa-warm": "pipelined-phases consensus warm thread (polisher.py)",
    "align-worker": "pipelined-phases alignment feeder (polisher.py)",
    "racon-tpu-watchdog-call": "device-call watchdog runner",
    "serve-metrics-http": "Prometheus exposition HTTP listener "
                          "(serve/server.py)",
    "loadtest-c*": "serve load-test client thread (serve/loadtest.py)",
    "loadtest-stats": "load-test daemon telemetry poller "
                      "(serve/loadtest.py)",
    "sanitize-stats-probe": "sanitizer cross-thread stats probe",
}

#: Constructor names whose instances are sanctioned lock-free shared
#: state: internally synchronised or append-only-with-guard.
SANCTIONED_CONSTRUCTORS = frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "threading.Event", "GuardedStats",
    "guard_stats",
})

#: threading constructors that create a lock-like guard usable in a
#: ``with`` statement.  Condition wraps an RLock, so re-acquiring the
#: same condition reentrantly is legal (self-edges are ignored in the
#: lock-order digraph).
LOCK_CONSTRUCTORS = frozenset({
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
})

_WAIVER_RE = re.compile(r"#\s*concurrency:\s*(\S.*)")


def waiver_reason(line: str):
    """The ``# concurrency: <reason>`` waiver on a source line, or None."""
    m = _WAIVER_RE.search(line)
    return m.group(1).strip() if m else None


def role_is_registered(name: str) -> bool:
    """True when a thread-name pattern matches a registered role.

    ``name`` is the *patternized* thread name: f-string interpolations
    are replaced with ``*``, so both directions of the glob match are
    tried (``loadtest-c3`` vs registered ``loadtest-c*``, and the
    patternized ``loadtest-c*`` vs the same registration).
    """
    for pat in THREAD_ROLE_PATTERNS:
        if fnmatchcase(name, pat) or fnmatchcase(pat, name):
            return True
    return False


def sanctioned_call(dotted: str) -> bool:
    """True when a constructor call creates sanctioned shared state."""
    if dotted in SANCTIONED_CONSTRUCTORS:
        return True
    last = dotted.rsplit(".", 1)[-1]
    return last in {"GuardedStats", "guard_stats"} or (
        last in {"Queue", "SimpleQueue", "LifoQueue", "PriorityQueue"}
        and (dotted == last or dotted.startswith("queue.")))


def lock_call(dotted: str) -> bool:
    """True when a constructor call creates a lock-like guard."""
    last = dotted.rsplit(".", 1)[-1]
    return last in LOCK_CONSTRUCTORS and (
        dotted == last or dotted.startswith("threading."))
