"""Static concurrency & contract auditor.

Part A (``--concurrency``): lock discipline.  Discovers thread entry
points (``threading.Thread(target=...)``), assigns each a *role* from
the registered role patterns (roles.py), propagates roles through the
intra-repo call graph, infers which attributes/globals each role
mutates, and requires every multi-role-mutated location to be guarded by
a consistently-held lock, be a sanctioned lock-free type (queue.Queue,
threading.Event, GuardedStats), or carry an explicit
``# concurrency: <reason>`` waiver.  Also builds the repo-wide
lock-acquisition-order digraph and fails on cycles.

Part B (``--contracts``): contract extraction.  Statically extracts the
degradation-lattice edge set, the fault-point name set, and the
serve/distrib wire-protocol field sets, then cross-checks them against
the declared specs (serve/protocol.py), the test drills under tests/,
and the failure-modes rows in docs/.

Both emit ``lint.Violation`` objects so the existing baseline /
suppression / CLI plumbing applies unchanged.
"""

from __future__ import annotations

from typing import List, Optional

from ..lint import Violation, repo_root_for


def run_concurrency(repo_root: Optional[str] = None) -> List[Violation]:
    """Run the lock-discipline + lock-order audit over one repo tree."""
    from .locks import audit
    root = repo_root or repo_root_for()
    return audit(root)


def run_contracts(repo_root: Optional[str] = None) -> List[Violation]:
    """Run the lattice/fault/protocol contract cross-checks."""
    from .contracts import audit
    root = repo_root or repo_root_for()
    return audit(root)
