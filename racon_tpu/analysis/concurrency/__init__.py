"""Static concurrency & contract auditor.

Part A (``--concurrency``): lock discipline.  Discovers thread entry
points (``threading.Thread(target=...)``), assigns each a *role* from
the registered role patterns (roles.py), propagates roles through the
intra-repo call graph, infers which attributes/globals each role
mutates, and requires every multi-role-mutated location to be guarded by
a consistently-held lock, be a sanctioned lock-free type (queue.Queue,
threading.Event, GuardedStats), or carry an explicit
``# concurrency: <reason>`` waiver.  Also builds the repo-wide
lock-acquisition-order digraph and fails on cycles.

Part B (``--contracts``): contract extraction.  Statically extracts the
degradation-lattice edge set, the fault-point name set, and the
serve/distrib wire-protocol field sets, then cross-checks them against
the declared specs (serve/protocol.py), the test drills under tests/,
and the failure-modes rows in docs/.

Both emit ``lint.Violation`` objects so the existing baseline /
suppression / CLI plumbing applies unchanged.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..lint import Violation, repo_root_for


class UnsupportedScope(ValueError):
    """A --paths scope that cannot carry the requested audit."""


def run_concurrency(repo_root: Optional[str] = None,
                    paths: Optional[Sequence[str]] = None
                    ) -> List[Violation]:
    """Run the lock-discipline + lock-order audit over one repo tree.

    paths — repo-relative file subset: the model (thread roles, lock
    sets, call graph) is built from just these files, so cross-file
    edges to unlisted code are invisible by design.
    """
    from .locks import audit
    from .model import Model
    root = repo_root or repo_root_for()
    model = Model.build(root, list(paths)) if paths is not None else None
    return audit(root, model=model)


def run_contracts(repo_root: Optional[str] = None,
                  paths: Optional[Sequence[str]] = None
                  ) -> List[Violation]:
    """Run the lattice/fault/protocol contract cross-checks.

    paths — repo-relative scope: the audit still reads the whole tree
    (contracts cross-reference tests/ and docs/), but only violations
    anchored at the scoped files are returned.  At least one contract
    anchor (lattice.py / faults.py / serve/protocol.py or a wire
    surface) must be in scope — raises UnsupportedScope otherwise,
    because every contract check would be vacuously filtered away.
    """
    from . import contracts
    root = repo_root or repo_root_for()
    if paths is None:
        return contracts.audit(root)
    anchors = {contracts._LATTICE_REL, contracts._FAULTS_REL,
               contracts._PROTOCOL_REL}
    for _surface, consumer, producer in contracts._SURFACES:
        anchors.update((consumer, producer))
    scoped = set(paths)
    if not scoped & anchors:
        raise UnsupportedScope(
            "--contracts with --paths needs at least one contract "
            "anchor in scope (got none); anchors: "
            + ", ".join(sorted(anchors)))
    return [v for v in contracts.audit(root) if v.path in scoped]
