"""Shared parsed-AST cache for every analysis engine.

A full ``python -m racon_tpu.analysis`` run used to parse each source
file up to four times — once per engine (lint, concurrency model,
contracts, and now the protocol conformance pass).  This module gives
them one process-wide cache: the first engine to ask for a file pays
the ``ast.parse``, the rest get the same tree back.

Entries are validated against ``(mtime_ns, size, ctime_ns, inode)`` on
every lookup, so a long-lived process (the test suite, a REPL) that
rewrites a fixture between runs never sees a stale tree; within one CLI
run the stat is the only cost.  Size alone is not enough (a same-length
rewrite keeps it), and neither is mtime (``os.utime`` — or a filesystem
with coarse timestamps — can produce an mtime-equal rewrite): ctime
changes on *every* write and cannot be set from userspace, and the
inode catches atomic replace-by-rename, so a stale parse cannot be
served to any engine.  Failures are cached too — a file that does not
parse returns the same ``error`` to every engine instead of being
re-opened per engine.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class Parsed:
    """One cached parse: `tree` is None iff `error` is set."""

    relpath: str
    source: str
    tree: Optional[ast.Module]
    error: Optional[str]            # OSError/SyntaxError text
    error_line: int = 0             # SyntaxError line (0 when unknown)


_cache: Dict[str, Tuple[Tuple[int, int, int, int], Parsed]] = {}
_stats = {"parses": 0, "hits": 0, "failures": 0}


def load(repo_root: str, relpath: str) -> Parsed:
    """The parsed form of ``repo_root/relpath``, cached process-wide."""
    full = os.path.join(repo_root, relpath)
    try:
        st = os.stat(full)
        key = (st.st_mtime_ns, st.st_size, st.st_ctime_ns, st.st_ino)
    except OSError as e:
        _stats["failures"] += 1
        return Parsed(relpath, "", None, str(e))
    hit = _cache.get(full)
    if hit is not None and hit[0] == key:
        _stats["hits"] += 1
        # the same file may be asked for under a different repo_root
        # spelling; the relpath in the entry is from the first caller
        return hit[1]
    try:
        with open(full) as f:
            source = f.read()
        tree = ast.parse(source, filename=relpath)
        entry = Parsed(relpath, source, tree, None)
    except OSError as e:
        _stats["failures"] += 1
        return Parsed(relpath, "", None, str(e))
    except SyntaxError as e:
        entry = Parsed(relpath, source, None, str(e),
                       getattr(e, "lineno", 0) or 0)
    _stats["parses"] += 1
    _cache[full] = (key, entry)
    return entry


def stats() -> Dict[str, int]:
    return dict(_stats)


def clear() -> None:
    _cache.clear()
    for k in _stats:
        _stats[k] = 0
