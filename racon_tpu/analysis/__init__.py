"""First-party static analysis: TPU-invariant lint + jaxpr audit +
concurrency/contract audits + protocol model checker + determinism
taint auditor.

Five engines, one CLI (``python -m racon_tpu.analysis``) and one shared
parsed-AST cache (`astcache.py`).  The two founding engines:

* **AST lint** (`lint.py` + `rules/`): repo-specific rules over the
  Python sources — invariants that every round-5 advisor finding turned
  out to violate silently: tracer leaks inside jit/Pallas regions,
  kernel-builder caches not keyed on device topology, `RACON_TPU_*` env
  reads bypassing the central knob registry (racon_tpu/config.py),
  fault-point names unknown to the resilience registry, and broad
  excepts around device seams that don't document the degradation
  lattice boundary.

* **Jaxpr audit** (`jaxpr_audit.py`): abstractly traces the POA and
  alignment kernels over the bucket-config grid and statically rejects
  forbidden primitives (host callbacks, infeed/outfeed, float64) and
  recompile blow-ups (distinct jit signatures across the grid vs. the
  budgets declared in `ops/poa_driver.py` / `ops/align.py`).

The later engines live in their own subpackages: `concurrency/` (lock
discipline + contract cross-checks, ``--concurrency``/``--contracts``),
`protocol/` (explicit-state fleet-lifecycle model checker,
``--model-check``), and `determinism/` (knob-to-install-seam taint
audit of the byte-identity contract vs the fingerprint registry,
``--determinism``, on by default for full-tree runs).

Suppression: append ``# lint: disable=<rule-id>`` to the flagged line,
or record existing debt in a baseline file (``--write-baseline``) — the
CLI then fails only on NEW violations.  `docs/static-analysis.md` lists
every rule with rationale.
"""

from .lint import Violation, iter_source_files, run_lint  # noqa: F401
from .jaxpr_audit import run_audit  # noqa: F401

__all__ = ["Violation", "iter_source_files", "run_lint", "run_audit"]
