"""`python -m racon_tpu.analysis` — run the AST lint and the jaxpr
audit over the repo; exit non-zero on new (non-baselined) violations.

Wired into tier-1 via tests/test_analysis.py; run it locally before
sending a change that touches kernels, knobs, or the resilience layer.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from . import jaxpr_audit, lint


def _sanitize_report(path: str, as_json: bool) -> int:
    """Render the `sanitize` section of a run report; exit 1 on any
    recorded finding (the dynamic-analysis analogue of the lint gate)."""
    try:
        with open(path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        print(f"[analysis] cannot read run report {path}: {e}",
              file=sys.stderr)
        return 2
    section = report.get("sanitize")
    if not isinstance(section, dict):
        print(f"[analysis] {path}: no `sanitize` section (report predates "
              f"the runtime sanitizer?)", file=sys.stderr)
        return 2
    armed = bool(section.get("armed"))
    findings = section.get("findings") or []
    if as_json:
        print(json.dumps({"armed": armed, "findings": findings}, indent=2))
        return 1 if findings else 0
    for f in findings:
        times = f" x{f['count']}" if f.get("count", 1) > 1 else ""
        print(f"[sanitize] {f.get('kind', '?')} at {f.get('where', '?')}"
              f"{times}: {f.get('detail', '')}")
    if findings:
        print(f"[analysis] SANITIZE FAIL: {len(findings)} distinct "
              f"finding(s)")
        return 1
    state = "armed" if armed else "not armed (RACON_TPU_SANITIZE unset)"
    print(f"[analysis] SANITIZE OK: no findings; sanitizer {state}")
    return 0


_MODEL_REL = "racon_tpu/analysis/protocol/model.py"


def _mc_config(args):
    """A model Config from the --mc-* knobs (defaults from Config)."""
    from .protocol import Config
    kw = {}
    if args.mc_workers is not None:
        kw["workers"] = args.mc_workers
    if args.mc_chunks is not None:
        kw["chunks"] = tuple(args.mc_chunks.split(","))
    if args.mc_retry is not None:
        kw["retry"] = args.mc_retry
    if args.mc_faults is not None:
        kw["faults"] = args.mc_faults
    if args.mc_budget is not None:
        kw["budget"] = args.mc_budget
    if args.mc_submits is not None:
        kw["submit_ests"] = tuple(int(x) for x
                                  in args.mc_submits.split(","))
    return Config(**kw)


def _model_check(args):
    """Run the state exploration; counterexamples come back as ordinary
    Violations (rule `protocol-invariant`) so the baseline/waiver and
    exit-code plumbing apply unchanged."""
    from .protocol import check
    from .lint import Violation

    res = check(cfg=_mc_config(args), mutation=args.mutate,
                strategy=args.mc_strategy, max_states=args.mc_max_states,
                depth=args.mc_depth)
    violations = [Violation("protocol-invariant", _MODEL_REL, 1,
                            v.render())
                  for v in res.violations]
    if args.emit_schedule:
        _emit_schedule(args.emit_schedule, res)
    return res, violations


def _emit_schedule(dest: str, res) -> None:
    """Compile the first counterexample (or a clean worker-death
    witness run) into a replayable RACON_TPU_FAULT schedule JSON."""
    from .protocol import replay
    from .protocol.checker import _fmt_event

    payload = {}
    try:
        if res.violations:
            trace = res.violations[0].trace
            sched = replay.compile_trace(trace)
            payload["source"] = res.violations[0].invariant
        else:
            trace, sched = replay.witness_trace()
            payload["source"] = "witness"
        payload.update(spec=sched.spec, worker=sched.worker,
                       events=list(sched.events), env=sched.env(),
                       trace=[_fmt_event(e) for e in trace])
    except replay.Unreplayable as e:
        payload = {"error": str(e)}
    text = json.dumps(payload, indent=2) + "\n"
    if dest == "-":
        sys.stdout.write(text)
    else:
        with open(dest, "w") as f:
            f.write(text)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m racon_tpu.analysis",
        description="racon_tpu static analysis: repo-specific AST lint "
                    "+ abstract jaxpr audit of the device kernel grid")
    p.add_argument("--repo-root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="suppression baseline JSON; violations whose "
                        "fingerprints it accepts are not reported "
                        "(default: <repo>/tools/lint_baseline.json if "
                        "present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept every current violation into the "
                        "baseline file and exit 0")
    p.add_argument("--paths", nargs="+", default=None, metavar="REL",
                   help="analyze only these repo-relative files instead "
                        "of the whole tree (CI uses this to focus on the "
                        "modules a change touched); jaxpr audit is "
                        "skipped when --paths is given.  Default is "
                        "lint-only; an explicit --concurrency/"
                        "--contracts/--determinism runs that audit "
                        "scoped to the paths")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr audit (AST lint only; fast)")
    p.add_argument("--no-lint", action="store_true",
                   help="skip the AST lint (jaxpr audit only)")
    p.add_argument("--concurrency", action="store_true",
                   help="run only the lock-discipline audit "
                        "(unguarded-mutation / lock-order-cycle); may be "
                        "combined with --contracts")
    p.add_argument("--contracts", action="store_true",
                   help="run only the contract cross-checks (lattice "
                        "drills/docs, fault-point drills/docs, wire-"
                        "protocol field agreement); may be combined "
                        "with --concurrency")
    p.add_argument("--determinism", action="store_true",
                   help="run only the determinism taint audit "
                        "(determinism-leak / fingerprint-gap / "
                        "fingerprint-overkey): prove no cost-only knob "
                        "value can reach the consensus/CIGAR install "
                        "seams and that every fingerprint composition "
                        "covers the output-affecting domain; may be "
                        "combined with --concurrency/--contracts")
    p.add_argument("--emit-manifest", default=None, metavar="FILE",
                   help="with the determinism audit: write the "
                        "knob/fingerprint classification manifest "
                        "(determinism.json schema) to FILE ('-' = "
                        "stdout); implies --determinism")
    p.add_argument("--det-mutate", default=None, metavar="N|NAME",
                   help="determinism self-test: seed one contract bug "
                        "into a scratch copy of the tree (see "
                        "--list-det-mutations) and audit it; exit goes "
                        "non-zero when the expected rule catches it")
    p.add_argument("--list-det-mutations", action="store_true",
                   help="print every seeded determinism mutant + the "
                        "rule expected to catch it, and exit")
    p.add_argument("--model-check", action="store_true",
                   help="run the protocol model checker: exhaust the "
                        "bounded fleet-lifecycle state space, evaluate "
                        "the invariant library, print minimal "
                        "counterexample traces (plus the conformance "
                        "pass keeping the model honest)")
    p.add_argument("--mutate", default=None, metavar="N|NAME",
                   help="model-check self-test: flip one transition "
                        "guard (index or name, see --list-mutations); "
                        "the checker must find a violation, so the exit "
                        "code goes non-zero when the seeded bug is "
                        "caught (implies --model-check)")
    p.add_argument("--list-mutations", action="store_true",
                   help="print every seeded model mutation + the "
                        "invariant expected to catch it, and exit")
    p.add_argument("--emit-schedule", default=None, metavar="FILE",
                   help="with --model-check: compile the first "
                        "counterexample (or, when clean, a shortest "
                        "worker-death witness run) into a replayable "
                        "RACON_TPU_FAULT schedule JSON ('-' = stdout)")
    p.add_argument("--mc-workers", type=int, default=None,
                   help="model-check: pool slots (default 2)")
    p.add_argument("--mc-chunks", default=None, metavar="J,J,...",
                   help="model-check: job label per chunk, e.g. A,A,B "
                        "(default)")
    p.add_argument("--mc-retry", type=int, default=None,
                   help="model-check: per-chunk retry budget (default 1)")
    p.add_argument("--mc-faults", type=int, default=None,
                   help="model-check: injected-fault budget (default 1)")
    p.add_argument("--mc-budget", type=int, default=None,
                   help="model-check: window-budget capacity (default 3)")
    p.add_argument("--mc-submits", default=None, metavar="E,E,...",
                   help="model-check: window estimate per submitter, "
                        "e.g. 2,2 (default)")
    p.add_argument("--mc-strategy", choices=("bfs", "dfs"), default="bfs",
                   help="model-check: bfs exhausts with minimal traces "
                        "(default); dfs is the depth-bounded fallback "
                        "for oversized configs")
    p.add_argument("--mc-depth", type=int, default=40,
                   help="model-check: dfs depth bound (default 40)")
    p.add_argument("--mc-max-states", type=int, default=2_000_000,
                   help="model-check: state-count cap (default 2e6)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-rules", action="store_true",
                   help="print every rule id + summary and exit")
    p.add_argument("--sanitize-report", default=None, metavar="FILE",
                   help="render the runtime-sanitizer verdict from a run "
                        "report JSON (see RACON_TPU_REPORT / --report); "
                        "exit 1 when the run recorded sanitizer findings")
    args = p.parse_args(argv)

    if args.sanitize_report:
        return _sanitize_report(args.sanitize_report, args.as_json)

    if args.list_rules:
        from .rules import ALL_RULES
        for rule in ALL_RULES:
            print(f"{rule.id:18s} {rule.doc}")
        for rid, doc in (
            ("jaxpr-forbidden-primitive",
             "no host callbacks / infeed / implicit transfers in "
             "kernel jaxprs"),
            ("jaxpr-float64",
             "no float64 intermediates in kernel jaxprs"),
            ("recompile-budget",
             "distinct jit signatures across the kernel grid stay "
             "within the declared budget"),
            ("unguarded-mutation",
             "shared state mutated by >=2 thread roles without one "
             "lock held at every site"),
            ("lock-order-cycle",
             "lock-acquisition-order digraph must be acyclic"),
            ("lattice-drill",
             "every degradation-lattice edge needs a test drill"),
            ("lattice-docs",
             "every degradation-lattice edge needs a failure-modes "
             "docs row"),
            ("fault-drill",
             "every registered fault point needs a test drill"),
            ("fault-docs",
             "every registered fault point needs a docs table row"),
            ("protocol-mismatch",
             "wire-protocol producers/consumers must agree field-for-"
             "field with the declared spec"),
            ("fault-model",
             "every fleet-scoped fault point must be claimed by a "
             "protocol-model transition"),
            ("model-site",
             "every protocol-model transition must point at a live "
             "code site"),
            ("model-fault",
             "every protocol-model fault point must exist in "
             "faults.KNOWN_POINTS"),
            ("model-coverage",
             "every fleet-scoped faults.check() site must be claimed "
             "by a protocol-model transition"),
            ("protocol-invariant",
             "no bounded interleaving of the fleet lifecycle may "
             "violate the invariant library (--model-check)"),
            ("determinism-leak",
             "no cost-only knob's value may flow into the "
             "consensus/CIGAR install seams"),
            ("fingerprint-gap",
             "every complete fingerprint composition must cover the "
             "whole output-affecting domain"),
            ("fingerprint-overkey",
             "warning: fingerprint components keyed only on cost-only, "
             "taint-clean knobs cause needless misses"),
        ):
            print(f"{rid:18s} {doc}")
        return 0

    if args.list_mutations:
        from .protocol import MUTATIONS
        for i, (name, doc, expected, overrides) in enumerate(MUTATIONS):
            extra = f" [config: {overrides}]" if overrides else ""
            print(f"{i}: {name:28s} -> {expected}{extra}\n"
                  f"     {doc}")
        return 0

    if args.list_det_mutations:
        from .determinism import MUTANTS
        for i, (name, doc, expected, _patches) in enumerate(MUTANTS):
            print(f"{i}: {name:28s} -> {expected}\n"
                  f"     {doc}")
        return 0

    root = args.repo_root or lint.repo_root_for()

    if args.det_mutate is not None:
        from .determinism import run_mutant
        try:
            mutant, det, caught = run_mutant(root, args.det_mutate)
        except (ValueError, RuntimeError) as e:
            print(f"[analysis] {e}", file=sys.stderr)
            return 2
        for v in det.violations + det.warnings:
            print(v.render())
        verdict = "CAUGHT" if caught else "MISSED"
        print(f"[analysis] determinism mutant {mutant[0]}: {verdict} "
              f"(expected rule: {mutant[2]})")
        return 1 if caught else 0
    model_check = args.model_check or args.mutate is not None
    determinism = args.determinism or args.emit_manifest is not None
    audits_selected = (args.concurrency or args.contracts or model_check
                       or determinism)
    violations: List[lint.Violation] = []
    if not audits_selected:
        if not args.no_lint:
            violations.extend(lint.run_lint(root, paths=args.paths))
        if not args.no_jaxpr and args.paths is None:
            violations.extend(jaxpr_audit.run_audit())
    # Concurrency & contract audits: an explicit flag always wins
    # (scoped to --paths when given); otherwise they ride along on
    # full-tree default runs, and --paths runs stay lint-only.
    full_default = (not audits_selected and not args.no_lint
                    and args.paths is None)
    from .concurrency import UnsupportedScope
    try:
        if args.concurrency or full_default:
            from .concurrency import run_concurrency
            violations.extend(run_concurrency(root, paths=args.paths))
        if args.contracts or full_default:
            from .concurrency import run_contracts
            violations.extend(run_contracts(root, paths=args.paths))
    except UnsupportedScope as e:
        print(f"[analysis] {e}", file=sys.stderr)
        return 2
    det_audit = None
    if determinism or full_default:
        from .determinism import build_audit
        det_audit = build_audit(root, paths=args.paths)
        violations.extend(det_audit.violations)
        if args.emit_manifest:
            text = json.dumps(det_audit.manifest, indent=2) + "\n"
            if args.emit_manifest == "-":
                sys.stdout.write(text)
            else:
                with open(args.emit_manifest, "w") as f:
                    f.write(text)
    mc_result = None
    if model_check or full_default:
        from .protocol import run_conformance
        violations.extend(run_conformance(root))
    if model_check:
        mc_result, mc_violations = _model_check(args)
        violations.extend(mc_violations)

    baseline_path = args.baseline or os.path.join(
        root, "tools", "lint_baseline.json")
    if args.write_baseline:
        lint.write_baseline(baseline_path, violations)
        print(f"[analysis] baseline: accepted {len(violations)} "
              f"violation(s) into {baseline_path}")
        return 0

    baseline = lint.load_baseline(baseline_path)
    new = lint.filter_baselined(violations, baseline)

    from . import astcache
    if args.as_json:
        payload = {
            "total": len(violations),
            "baselined": len(violations) - len(new),
            "new": [vars(v) for v in new],
            "astcache": astcache.stats(),
        }
        if det_audit is not None:
            payload["determinism_warnings"] = [
                vars(v) for v in det_audit.warnings]
        if mc_result is not None:
            payload["model_check"] = {
                "config": mc_result.config.describe(),
                "mutation": mc_result.mutation,
                "strategy": mc_result.strategy,
                "states": mc_result.states,
                "transitions": mc_result.transitions,
                "elapsed_s": round(mc_result.elapsed_s, 3),
                "exhausted": mc_result.exhausted,
            }
        print(json.dumps(payload, indent=2))
    else:
        for v in new:
            print(v.render())
        if det_audit is not None:
            for v in det_audit.warnings:
                print(f"[warn] {v.render()}")
        n_base = len(violations) - len(new)
        tail = f" ({n_base} baselined)" if n_base else ""
        if mc_result is not None:
            state = ("exhausted" if mc_result.exhausted
                     else "PARTIAL (cap/depth hit)")
            mut = (f", mutation={mc_result.mutation}"
                   if mc_result.mutation else "")
            print(f"[analysis] model-check: {mc_result.config.describe()}"
                  f"{mut}: {mc_result.states} states / "
                  f"{mc_result.transitions} transitions in "
                  f"{mc_result.elapsed_s:.1f}s ({mc_result.strategy}, "
                  f"{state})")
        if new:
            print(f"[analysis] FAIL: {len(new)} violation(s){tail}")
        else:
            print(f"[analysis] OK: no new violations{tail}")
    if new:
        return 1
    if mc_result is not None and not mc_result.exhausted:
        # a clean verdict from a partial exploration proves nothing
        print("[analysis] model-check did not exhaust the bounded "
              "space; clean verdict is unsound (raise --mc-max-states "
              "or --mc-depth, or shrink the config)", file=sys.stderr)
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
