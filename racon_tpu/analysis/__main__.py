"""`python -m racon_tpu.analysis` — run the AST lint and the jaxpr
audit over the repo; exit non-zero on new (non-baselined) violations.

Wired into tier-1 via tests/test_analysis.py; run it locally before
sending a change that touches kernels, knobs, or the resilience layer.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List

from . import jaxpr_audit, lint


def _sanitize_report(path: str, as_json: bool) -> int:
    """Render the `sanitize` section of a run report; exit 1 on any
    recorded finding (the dynamic-analysis analogue of the lint gate)."""
    try:
        with open(path) as f:
            report = json.load(f)
    except (OSError, ValueError) as e:
        print(f"[analysis] cannot read run report {path}: {e}",
              file=sys.stderr)
        return 2
    section = report.get("sanitize")
    if not isinstance(section, dict):
        print(f"[analysis] {path}: no `sanitize` section (report predates "
              f"the runtime sanitizer?)", file=sys.stderr)
        return 2
    armed = bool(section.get("armed"))
    findings = section.get("findings") or []
    if as_json:
        print(json.dumps({"armed": armed, "findings": findings}, indent=2))
        return 1 if findings else 0
    for f in findings:
        times = f" x{f['count']}" if f.get("count", 1) > 1 else ""
        print(f"[sanitize] {f.get('kind', '?')} at {f.get('where', '?')}"
              f"{times}: {f.get('detail', '')}")
    if findings:
        print(f"[analysis] SANITIZE FAIL: {len(findings)} distinct "
              f"finding(s)")
        return 1
    state = "armed" if armed else "not armed (RACON_TPU_SANITIZE unset)"
    print(f"[analysis] SANITIZE OK: no findings; sanitizer {state}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m racon_tpu.analysis",
        description="racon_tpu static analysis: repo-specific AST lint "
                    "+ abstract jaxpr audit of the device kernel grid")
    p.add_argument("--repo-root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="suppression baseline JSON; violations whose "
                        "fingerprints it accepts are not reported "
                        "(default: <repo>/tools/lint_baseline.json if "
                        "present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept every current violation into the "
                        "baseline file and exit 0")
    p.add_argument("--paths", nargs="+", default=None, metavar="REL",
                   help="lint only these repo-relative files instead of "
                        "the whole tree (CI uses this to focus on the "
                        "modules a change touched); jaxpr audit is "
                        "skipped when --paths is given")
    p.add_argument("--no-jaxpr", action="store_true",
                   help="skip the jaxpr audit (AST lint only; fast)")
    p.add_argument("--no-lint", action="store_true",
                   help="skip the AST lint (jaxpr audit only)")
    p.add_argument("--concurrency", action="store_true",
                   help="run only the lock-discipline audit "
                        "(unguarded-mutation / lock-order-cycle); may be "
                        "combined with --contracts")
    p.add_argument("--contracts", action="store_true",
                   help="run only the contract cross-checks (lattice "
                        "drills/docs, fault-point drills/docs, wire-"
                        "protocol field agreement); may be combined "
                        "with --concurrency")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--list-rules", action="store_true",
                   help="print every rule id + summary and exit")
    p.add_argument("--sanitize-report", default=None, metavar="FILE",
                   help="render the runtime-sanitizer verdict from a run "
                        "report JSON (see RACON_TPU_REPORT / --report); "
                        "exit 1 when the run recorded sanitizer findings")
    args = p.parse_args(argv)

    if args.sanitize_report:
        return _sanitize_report(args.sanitize_report, args.as_json)

    if args.list_rules:
        from .rules import ALL_RULES
        for rule in ALL_RULES:
            print(f"{rule.id:18s} {rule.doc}")
        for rid, doc in (
            ("jaxpr-forbidden-primitive",
             "no host callbacks / infeed / implicit transfers in "
             "kernel jaxprs"),
            ("jaxpr-float64",
             "no float64 intermediates in kernel jaxprs"),
            ("recompile-budget",
             "distinct jit signatures across the kernel grid stay "
             "within the declared budget"),
            ("unguarded-mutation",
             "shared state mutated by >=2 thread roles without one "
             "lock held at every site"),
            ("lock-order-cycle",
             "lock-acquisition-order digraph must be acyclic"),
            ("lattice-drill",
             "every degradation-lattice edge needs a test drill"),
            ("lattice-docs",
             "every degradation-lattice edge needs a failure-modes "
             "docs row"),
            ("fault-drill",
             "every registered fault point needs a test drill"),
            ("fault-docs",
             "every registered fault point needs a docs table row"),
            ("protocol-mismatch",
             "wire-protocol producers/consumers must agree field-for-"
             "field with the declared spec"),
        ):
            print(f"{rid:18s} {doc}")
        return 0

    root = args.repo_root or lint.repo_root_for()
    audits_selected = args.concurrency or args.contracts
    violations: List[lint.Violation] = []
    if not audits_selected:
        if not args.no_lint:
            violations.extend(lint.run_lint(root, paths=args.paths))
        if not args.no_jaxpr and args.paths is None:
            violations.extend(jaxpr_audit.run_audit())
    # Concurrency & contract audits: run when selected explicitly, or as
    # part of a full-tree run (they are whole-repo analyses, so --paths
    # runs stay lint-only).
    if args.concurrency or (not audits_selected and not args.no_lint
                            and args.paths is None):
        from .concurrency import run_concurrency
        violations.extend(run_concurrency(root))
    if args.contracts or (not audits_selected and not args.no_lint
                          and args.paths is None):
        from .concurrency import run_contracts
        violations.extend(run_contracts(root))

    baseline_path = args.baseline or os.path.join(
        root, "tools", "lint_baseline.json")
    if args.write_baseline:
        lint.write_baseline(baseline_path, violations)
        print(f"[analysis] baseline: accepted {len(violations)} "
              f"violation(s) into {baseline_path}")
        return 0

    baseline = lint.load_baseline(baseline_path)
    new = lint.filter_baselined(violations, baseline)

    if args.as_json:
        print(json.dumps({
            "total": len(violations),
            "baselined": len(violations) - len(new),
            "new": [vars(v) for v in new],
        }, indent=2))
    else:
        for v in new:
            print(v.render())
        n_base = len(violations) - len(new)
        tail = f" ({n_base} baselined)" if n_base else ""
        if new:
            print(f"[analysis] FAIL: {len(new)} violation(s){tail}")
        else:
            print(f"[analysis] OK: no new violations{tail}")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
