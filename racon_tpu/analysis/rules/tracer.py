"""tracer-leak: no host syncs / host materialization inside traced code.

A jit- or Pallas-traced region runs against abstract tracers; calling
``float()`` / ``int()`` / ``bool()`` on a traced value, ``.item()``,
``np.asarray``/``np.array``, or branching with a Python ``if`` on a
traced expression forces a host sync (ConcretizationError at best, a
silent device->host transfer + recompile at worst).  The hot path must
stay free of both (AnySeq/GPU makes the same point for alignment
kernels; see PAPERS.md).

Detection: a function is a *traced region* when it

* is decorated with ``jit`` / ``jax.jit`` / ``functools.partial(jit, …)``, or
* is referenced by name inside a call to one of the trace entry points
  (``jit``, ``vmap``, ``pmap``, ``pallas_call``, ``shard_map``,
  ``scan``, ``while_loop``, ``fori_loop``, ``cond``, ``switch``,
  ``checkpoint``, ``remat``).

Inside a traced region (nested defs included) the rule flags:

* ``float(x)`` / ``int(x)`` / ``bool(x)`` where x mentions a parameter
  of the traced function or a jnp/lax call — a concretization;
* any ``.item()`` call — always a device sync;
* ``np.asarray`` / ``np.array`` / ``np.copy`` on anything — host
  materialization of a tracer;
* an ``if`` whose test mentions a parameter of the enclosing traced
  function or a jnp/lax call — data-dependent Python control flow
  (use ``jnp.where`` / ``lax.cond``).

Static py-level conditionals on closure config (e.g. ``if interpret:``)
do not fire: closure variables are not parameters of the traced region.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..lint import FileContext, Violation
from . import last_attr

TRACE_ENTRY_POINTS = {
    "jit", "vmap", "pmap", "pallas_call", "shard_map", "scan",
    "while_loop", "fori_loop", "cond", "switch", "checkpoint", "remat",
}

#: numpy-namespace calls that materialize tracers on the host
_HOST_MATERIALIZERS = {"np.asarray", "np.array", "np.copy",
                       "numpy.asarray", "numpy.array", "numpy.copy",
                       "onp.asarray", "onp.array"}

_CONCRETIZERS = {"float", "int", "bool"}


def _decorated_traced(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", ()):
        target = dec.func if isinstance(dec, ast.Call) else dec
        if last_attr(target) == "jit":
            return True
        # functools.partial(jax.jit, ...) / partial(jit, ...)
        if isinstance(dec, ast.Call) and last_attr(dec.func) == "partial":
            if any(last_attr(a) == "jit" for a in dec.args):
                return True
    return False


def _mentions_jax_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            name = last_attr(sub.func)
            dotted_first = ""
            f = sub.func
            while isinstance(f, ast.Attribute):
                f = f.value
            if isinstance(f, ast.Name):
                dotted_first = f.id
            if dotted_first in ("jnp", "lax") or (
                    dotted_first == "jax" and name):
                return True
    return False


def _traceable_names(arg: ast.AST) -> Set[str]:
    """Function names an entry-point argument hands over FOR TRACING: a
    bare reference, names inside a lambda body, or the callable args of
    ``functools.partial``.  Names inside other call expressions (e.g.
    ``mesh=device_mesh()``) are evaluated eagerly at build time, not
    traced, and must not mark that function as a traced region."""
    out: Set[str] = set()
    if isinstance(arg, ast.Name):
        out.add(arg.id)
    elif isinstance(arg, ast.Lambda):
        for sub in ast.walk(arg.body):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
    elif isinstance(arg, ast.Call) and last_attr(arg.func) == "partial":
        for a in arg.args:
            if isinstance(a, ast.Name):
                out.add(a.id)
    return out


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = [p.arg for p in
             list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return set(names)


def _mentions_any(node: ast.AST, names: Set[str]) -> bool:
    """A Name in `names` occurs outside a `len(...)` argument — len() of
    a traced array is its static leading dim, not a data-dependent
    read, so `if len(xs) % 2:` style structural branches stay legal."""
    def walk(n: ast.AST) -> bool:
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                and n.func.id == "len":
            return False
        if isinstance(n, ast.Name) and n.id in names:
            return True
        return any(walk(c) for c in ast.iter_child_nodes(n))
    return walk(node)


class TracerLeakRule:
    id = "tracer-leak"
    doc = ("no float()/int()/bool()/.item()/np.asarray or data-dependent "
           "`if` on traced values inside jit/Pallas regions")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        traced_fns = self._traced_functions(ctx)
        out: List[Violation] = []
        for fn in traced_fns:
            params = _param_names(fn)
            # include nested defs' params: their args are traced too
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and sub is not fn:
                    params |= _param_names(sub)
            for node in ast.walk(fn):
                out.extend(self._check_node(ctx, fn, node, params))
        # de-dup: nested traced fns are walked once per enclosing region
        seen = set()
        uniq = []
        for v in out:
            key = (v.line, v.message)
            if key not in seen:
                seen.add(key)
                uniq.append(v)
        return uniq

    # -- helpers -----------------------------------------------------------
    def _traced_functions(self, ctx: FileContext) -> List[ast.AST]:
        defs = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
        traced: List[ast.AST] = []
        names_referenced: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if last_attr(node.func) in TRACE_ENTRY_POINTS:
                for arg in list(node.args) + [kw.value for kw in
                                              node.keywords]:
                    names_referenced |= _traceable_names(arg)
        for name, fns in defs.items():
            for fn in fns:
                if _decorated_traced(fn) or name in names_referenced:
                    traced.append(fn)
        return traced

    def _check_node(self, ctx: FileContext, fn, node,
                    params: Set[str]) -> Iterable[Violation]:
        rel, rule = ctx.relpath, self.id
        if isinstance(node, ast.Call):
            name = last_attr(node.func)
            dotted = name
            f = node.func
            chain = []
            while isinstance(f, ast.Attribute):
                chain.append(f.attr)
                f = f.value
            if isinstance(f, ast.Name):
                dotted = ".".join([f.id] + list(reversed(chain)))
            # .item() on anything — including call results like
            # jnp.sum(x).item(), whose chain roots at a Call and so has
            # no dotted name
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item":
                yield Violation(rule, rel, node.lineno,
                                f".item() inside traced region "
                                f"'{fn.name}' forces a device sync")
                return
            if dotted in _HOST_MATERIALIZERS:
                yield Violation(rule, rel, node.lineno,
                                f"{dotted}() inside traced region "
                                f"'{fn.name}' materializes a tracer on "
                                f"the host")
                return
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _CONCRETIZERS and node.args):
                arg = node.args[0]
                if _mentions_any(arg, params) or _mentions_jax_call(arg):
                    yield Violation(
                        rule, rel, node.lineno,
                        f"{node.func.id}() on a traced value inside "
                        f"'{fn.name}' concretizes the tracer")
                return
        if isinstance(node, ast.If):
            if _mentions_any(node.test, params) or \
                    _mentions_jax_call(node.test):
                yield Violation(
                    rule, rel, node.lineno,
                    f"data-dependent `if` on a traced value inside "
                    f"'{fn.name}' (use jnp.where / lax.cond)")
