"""wall-clock: no wall-clock deadlines in the resilience layer.

``time.time()`` jumps when NTP steps the clock — a deadline, backoff, or
elapsed-time computation built on it can go negative or balloon by
minutes mid-run.  The resilience layer (watchdog timeouts, retry
backoff, run reports) and the hardware-session driver (per-step
budgets, lease renewal) are exactly the code that must survive such
steps, so they use ``time.monotonic()`` (or ``time.perf_counter`` for
fine-grained spans) exclusively.  The observability tracer is scoped for
the same reason: span durations computed from a stepped wall clock show
up as negative/garbage bars in Perfetto.  Wall-clock reads are fine
elsewhere —
log timestamps, unique directory names — hence the narrow scope.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..lint import FileContext, Violation
from . import dotted_name

#: Scope: the resilience package, the observability layer (trace spans
#: must be monotonic or Perfetto renders negative durations), and the
#: hw-session driver.
_SCOPED = (("resilience",), ("obs",))
_SCOPED_FILES = ("racon_tpu/tools/hw_session.py",)


class WallClockRule:
    id = "wall-clock"
    doc = ("no time.time() in racon_tpu/resilience/, racon_tpu/obs/, or "
           "tools/hw_session.py; deadlines, elapsed-time math, and trace "
           "spans use time.monotonic()")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        if not (any(ctx.in_package(*p) for p in _SCOPED)
                or ctx.relpath in _SCOPED_FILES):
            return
        # `from time import time` makes every bare time() call a
        # wall-clock read; track the local name it lands on.
        bare_names = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name == "time":
                        bare_names.add(alias.asname or alias.name)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "time.time" or name in bare_names:
                yield Violation(
                    self.id, ctx.relpath, node.lineno,
                    "time.time() jumps with NTP steps; use "
                    "time.monotonic() for deadlines/elapsed time "
                    "(wall-clock timestamps belong outside the "
                    "resilience layer)")
