"""Lint rule registry + tiny AST helpers shared by the rules.

Every rule is an object with:

* ``id``    — kebab-case identifier (used in suppressions and baselines)
* ``doc``   — one-line rationale (rendered by ``--list-rules`` and docs)
* ``check(ctx: FileContext) -> Iterable[Violation]``
* optionally ``check_project(project: ProjectContext)`` for cross-file
  invariants (run once per lint, after the per-file pass)
"""

from __future__ import annotations

import ast
from typing import Optional


def dotted_name(node: ast.AST) -> str:
    """'jax.lax.scan' for the func of a Call; '' when not a plain
    name/attribute chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def last_attr(node: ast.AST) -> str:
    """The final component of a call target ('scan' for jax.lax.scan)."""
    name = dotted_name(node)
    return name.rsplit(".", 1)[-1] if name else ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


from .tracer import TracerLeakRule            # noqa: E402
from .caching import KernelCacheKeyRule       # noqa: E402
from .knobs import EnvRegistryRule, KnobDocsRule  # noqa: E402
from .faultpoints import FaultPointRule       # noqa: E402
from .excepts import DeviceExceptRule         # noqa: E402
from .clock import WallClockRule              # noqa: E402
from .threads import ThreadsRule              # noqa: E402

#: All rules, in documentation order.
ALL_RULES = (
    TracerLeakRule(),
    KernelCacheKeyRule(),
    EnvRegistryRule(),
    KnobDocsRule(),
    FaultPointRule(),
    DeviceExceptRule(),
    WallClockRule(),
    ThreadsRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}
