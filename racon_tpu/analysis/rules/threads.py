"""thread-discipline: every thread carries a registered role; no
sleeping under a lock.

The concurrency auditor (``racon_tpu/analysis/concurrency``) reasons
about *roles* — which named thread reaches which mutation site — so an
anonymous thread is invisible to it.  Hence every
``threading.Thread(...)`` must pass ``daemon=`` explicitly (an
accidental non-daemon thread wedges interpreter shutdown, the
historical serve-daemon hang) and a ``name=`` matching a role pattern
registered in ``concurrency/roles.py``.

``time.sleep()`` lexically inside a ``with <lock>:`` block stalls every
other thread contending for that lock for the whole sleep; use
``Condition.wait(timeout)`` (which releases the lock) or sleep outside
the block.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..lint import FileContext, Violation
from . import dotted_name
from ..concurrency.roles import role_is_registered

#: with-item context expressions whose final name component matches one
#: of these fragments are treated as lock guards for the sleep check.
_LOCKISH = ("lock", "_cv", "cond", "mutex", "_sem", "_mu")


def _patternized_name(node) -> str:
    """Thread name with f-string interpolations collapsed to ``*``."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return "".join(parts)
    return ""


def _lockish(expr) -> bool:
    name = dotted_name(expr)
    if not name:
        return False
    last = name.rsplit(".", 1)[-1].lower()
    return any(frag in last for frag in _LOCKISH)


class ThreadsRule:
    id = "thread-discipline"
    doc = ("threading.Thread needs daemon= and a name= matching a "
           "registered role (concurrency/roles.py); no time.sleep() "
           "under a lock")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        lock_depth = 0
        bare_thread = any(
            isinstance(n, ast.ImportFrom) and n.module == "threading"
            and any(a.name == "Thread" and a.asname is None
                    for a in n.names)
            for n in ast.walk(ctx.tree))

        def visit(node):
            nonlocal lock_depth
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, node, lock_depth,
                                            bare_thread)
            holds = 0
            if isinstance(node, (ast.With, ast.AsyncWith)):
                holds = sum(1 for item in node.items
                            if _lockish(item.context_expr))
            lock_depth += holds
            for child in ast.iter_child_nodes(node):
                yield from visit(child)
            lock_depth -= holds

        yield from visit(ctx.tree)

    def _check_call(self, ctx: FileContext, node: ast.Call,
                    lock_depth: int,
                    bare_thread: bool) -> Iterable[Violation]:
        name = dotted_name(node.func)
        if name == "time.sleep" and lock_depth > 0:
            yield Violation(
                self.id, ctx.relpath, node.lineno,
                "time.sleep() under a lock stalls every contending "
                "thread for the whole sleep; use Condition.wait(timeout) "
                "or sleep outside the with block")
            return
        if name not in ("threading.Thread", "Thread"):
            return
        if name == "Thread" and not bare_thread:
            return
        kwargs = {kw.arg: kw.value for kw in node.keywords
                  if kw.arg is not None}
        if "daemon" not in kwargs:
            yield Violation(
                self.id, ctx.relpath, node.lineno,
                "threading.Thread without an explicit daemon= — an "
                "accidental non-daemon thread wedges interpreter "
                "shutdown; decide and say so")
        if "name" not in kwargs:
            yield Violation(
                self.id, ctx.relpath, node.lineno,
                "threading.Thread without a name= carrying a registered "
                "thread role (see concurrency/roles.py); anonymous "
                "threads are invisible to the lock-discipline audit")
            return
        thread_name = _patternized_name(kwargs["name"])
        if not thread_name or not role_is_registered(thread_name):
            shown = thread_name or "<non-literal>"
            yield Violation(
                self.id, ctx.relpath, node.lineno,
                f"thread name {shown!r} does not match any registered "
                f"role pattern in concurrency/roles.py; register the "
                f"role or reuse an existing one")
