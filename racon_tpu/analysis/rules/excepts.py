"""device-except: no silent broad excepts around device seams.

A bare ``except:`` (or an undocumented ``except Exception:``) around a
device call swallows the exact signal the degradation lattice needs to
retry / bisect / demote — work silently disappears instead of being
re-served by a lower tier.  The repo convention: every deliberate broad
catch at a lattice seam carries a ``# noqa: BLE001`` marker with a
one-phrase justification on the same line, making each seam searchable
and reviewed.

* bare ``except:`` — violation anywhere in the package;
* ``except Exception`` / ``except BaseException`` in the device layers
  (``racon_tpu/ops/``, ``racon_tpu/resilience/``, ``racon_tpu/parallel/``)
  without the ``noqa: BLE001`` marker — violation.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..lint import FileContext, Violation
from . import last_attr

_DEVICE_LAYERS = (("ops",), ("resilience",), ("parallel",))
_MARKER = "noqa: BLE001"


class DeviceExceptRule:
    id = "device-except"
    doc = ("no bare except; broad except in device layers must carry "
           "'# noqa: BLE001' documenting the lattice seam")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        device_layer = any(ctx.in_package(*p) for p in _DEVICE_LAYERS)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield Violation(
                    self.id, ctx.relpath, node.lineno,
                    "bare `except:` swallows the failure signal the "
                    "degradation lattice steps on; catch a type")
                continue
            if not device_layer:
                continue
            names = [last_attr(node.type)] if not isinstance(
                node.type, ast.Tuple) else [last_attr(e)
                                            for e in node.type.elts]
            if any(n in ("Exception", "BaseException") for n in names):
                line = ctx.lines[node.lineno - 1] \
                    if node.lineno <= len(ctx.lines) else ""
                if _MARKER not in line:
                    yield Violation(
                        self.id, ctx.relpath, node.lineno,
                        "broad except at a device seam without the "
                        "documented lattice-boundary marker "
                        "(# noqa: BLE001 — reason)")
