"""env-registry / knob-docs: RACON_TPU_* knobs are registered, read
through racon_tpu.config, and documented.

A scattered ``os.environ.get("RACON_TPU_…")`` read is invisible to the
stale-knob check, undocumentable by tooling, and untypo-checkable — the
round-5 serving-mix finding started exactly there.  `racon_tpu/config.py`
is the single sanctioned reader; this pair of rules enforces both
directions:

* **env-registry** (per file): any ``os.environ`` / ``os.getenv`` READ
  of a RACON_TPU name outside config.py is a violation (writes —
  assignment / ``setdefault`` with a value — stay allowed: tools pin
  knobs for subprocesses).  Literal knob names passed to ``config.get_*``
  must exist in the registry (catches typos at lint time, not at 3am).

* **knob-docs** (project): every registered knob appears in README.md's
  configuration section.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ..lint import FileContext, ProjectContext, Violation
from . import dotted_name, str_const

_PREFIX = "RACON_TPU_"
_READ_FUNCS = {"os.environ.get", "environ.get", "os.getenv", "getenv"}
_CONFIG_GETTERS = {"get_raw", "get_str", "get_int", "get_float",
                   "get_bool", "is_set"}


def _registry():
    from ... import config
    return config.KNOBS


class EnvRegistryRule:
    id = "env-registry"
    doc = ("RACON_TPU_* env reads must go through racon_tpu.config; "
           "literal knob names must be registered")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        if ctx.relpath == "racon_tpu/config.py":
            return
        knobs = _registry()
        for node in ast.walk(ctx.tree):
            # os.environ["RACON_TPU_X"] in Load context
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.ctx, ast.Load):
                base = dotted_name(node.value)
                key = str_const(node.slice)
                if base in ("os.environ", "environ") and key and \
                        key.startswith(_PREFIX):
                    yield Violation(
                        self.id, ctx.relpath, node.lineno,
                        f"direct os.environ read of {key}; use "
                        f"racon_tpu.config.get_*({key!r})")
                continue
            if not isinstance(node, ast.Call):
                continue
            func = dotted_name(node.func)
            if func in _READ_FUNCS and node.args:
                key = str_const(node.args[0])
                if key and key.startswith(_PREFIX):
                    yield Violation(
                        self.id, ctx.relpath, node.lineno,
                        f"direct os.environ read of {key}; use "
                        f"racon_tpu.config.get_*({key!r})")
            # config.get_*("RACON_TPU_TYPO") — typo'd literal knob name
            elif func.rsplit(".", 1)[-1] in _CONFIG_GETTERS and node.args:
                key = str_const(node.args[0])
                if key and key.startswith(_PREFIX) and key not in knobs:
                    yield Violation(
                        self.id, ctx.relpath, node.lineno,
                        f"config read of unregistered knob {key}; "
                        f"declare it in racon_tpu/config.py")


class KnobDocsRule:
    id = "knob-docs"
    doc = "every registered RACON_TPU_* knob is documented in README.md"

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        return ()

    def check_project(self, project: ProjectContext) -> List[Violation]:
        readme = project.read_text("README.md")
        if readme is None:
            return [Violation(self.id, "README.md", 0,
                              "README.md not found; knob table missing")]
        out = []
        for name in _registry():
            if name not in readme:
                out.append(Violation(
                    self.id, "racon_tpu/config.py", 0,
                    f"registered knob {name} is not documented in "
                    f"README.md"))
        return out
