"""fault-point: every `faults.check(...)` name exists in the resilience
registry.

The deterministic fault-injection contract (resilience/faults.py) is
only airtight if every seam the drivers guard is a *registered* point —
a `faults.check("poa.run.sl")` typo would assert at runtime only on the
exact code path that hits it, i.e. in production, not in CI.  This rule
resolves every literal (and f-string pattern) passed to
``faults.check`` against ``faults.KNOWN_POINTS`` at lint time.

f-strings are matched structurally: ``f"poa.run.{kind}"`` is accepted
iff at least one known point matches ``poa.run.*`` — a dynamic segment
can only range over registered names.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from ..lint import FileContext, Violation
from . import dotted_name, str_const


def _known_points():
    from ...resilience.faults import KNOWN_POINTS
    return KNOWN_POINTS


def _fstring_regex(node: ast.JoinedStr) -> Optional[str]:
    """'^poa\\.run\\..+$' for f"poa.run.{kind}"; None when the f-string
    has no literal anchor at all (matches anything — unverifiable)."""
    parts = []
    has_literal = False
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(re.escape(v.value))
            has_literal = True
        else:
            parts.append(".+")
    return "^" + "".join(parts) + "$" if has_literal else None


class FaultPointRule:
    id = "fault-point"
    doc = ("every faults.check(name) literal/pattern must resolve to a "
           "registered resilience injection point")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        known = _known_points()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = dotted_name(node.func)
            if not (func == "faults.check" or func.endswith(".faults.check")):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            lit = str_const(arg)
            if lit is not None:
                if lit not in known:
                    yield Violation(
                        self.id, ctx.relpath, node.lineno,
                        f"fault point {lit!r} is not registered in "
                        f"resilience.faults.KNOWN_POINTS")
                continue
            if isinstance(arg, ast.JoinedStr):
                pattern = _fstring_regex(arg)
                if pattern is None:
                    continue  # fully dynamic: runtime assert covers it
                if not any(re.match(pattern, p) for p in known):
                    yield Violation(
                        self.id, ctx.relpath, node.lineno,
                        f"fault-point pattern {pattern!r} matches no "
                        f"registered injection point")
