"""kernel-cache-key: kernel-builder caches must be keyed on device
topology.

A memoized kernel builder that ignores the device set serves a stale
sharded/interpreted kernel after the JAX backend is reconfigured — the
exact ADVICE.md round-5 finding (`_build_kernel_cached` originally keyed
only on geometry).  The sanctioned patterns are:

* decorate with ``ops.kernel_cache.device_keyed_cache`` (appends
  ``(len(jax.devices()), platform)`` to the key implicitly), or
* take explicit ``n_dev`` + ``platform`` parameters (the caller then
  owns the topology key, as ``_build_kernel_cached`` does), or
* be nested inside a function that satisfies one of the above (the
  closure is rebuilt per topology, so inner per-batch caches inherit
  the key).

The rule fires on any ``functools.lru_cache``-decorated function that
builds device kernels (name contains "kernel", or its body calls
``jit`` / ``pallas_call`` / ``shard_map`` / a ``shard_*`` mesh helper)
and satisfies none of the patterns above.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from ..lint import FileContext, Violation
from . import last_attr

_DEVICE_COUNT_PARAMS = {"n_dev", "ndev", "n_devices", "num_devices"}
_PLATFORM_PARAMS = {"platform"}
_KERNEL_BODY_CALLS = {"jit", "pallas_call", "shard_map"}


def _decorator_names(fn) -> Set[str]:
    names = set()
    for dec in fn.decorator_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        names.add(last_attr(target))
    return names


def _params(fn) -> Set[str]:
    a = fn.args
    return {p.arg for p in
            list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)}


def _builds_kernels(fn) -> bool:
    if "kernel" in fn.name.lower():
        return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = last_attr(node.func)
            if name in _KERNEL_BODY_CALLS or name.startswith("shard_"):
                return True
    return False


def _topology_keyed(fn) -> bool:
    if "device_keyed_cache" in _decorator_names(fn):
        return True
    p = _params(fn)
    return bool(p & _DEVICE_COUNT_PARAMS) and bool(p & _PLATFORM_PARAMS)


class KernelCacheKeyRule:
    id = "kernel-cache-key"
    doc = ("lru_cache'd kernel builders must key on device topology: use "
           "device_keyed_cache or explicit n_dev+platform params")

    def check(self, ctx: FileContext) -> Iterable[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "lru_cache" not in _decorator_names(node):
                continue
            if not _builds_kernels(node):
                continue
            if _topology_keyed(node):
                continue
            # nested inside a topology-keyed builder? then the closure is
            # per-topology and the inner cache inherits the key
            if any(isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and _topology_keyed(anc)
                   for anc in ctx.ancestors(node)):
                continue
            yield Violation(
                self.id, ctx.relpath, node.lineno,
                f"kernel builder '{node.name}' is lru_cache'd without a "
                f"device-topology key; use ops.kernel_cache."
                f"device_keyed_cache or take n_dev+platform params")
