"""Engine 4: the protocol model checker (``--model-check``).

An explicit-state checker over a formal, untimed model of the fleet
chunk lifecycle (model.py), an invariant library evaluated over every
reachable state (invariants.py), BFS/DFS exploration with minimal
counterexample traces (checker.py), a compiler from counterexamples to
replayable ``RACON_TPU_FAULT`` schedules (replay.py), and a
model<->implementation conformance pass (conformance.py) that keeps
the model from drifting away from the code it abstracts.

The conformance pass emits ``lint.Violation`` objects so the existing
baseline / suppression / CLI plumbing applies unchanged; the state
exploration has its own entry points below (it is deliberately not
part of default full-tree runs — exhausting the bounded space costs
tens of seconds, which the lint path must not pay).
"""

from __future__ import annotations

from typing import List, Optional

from ..lint import Violation, repo_root_for
from .checker import Result, check          # noqa: F401 (re-export)
from .model import (Config, MUTATIONS, TRANSITIONS,   # noqa: F401
                    mutation_names)


def run_conformance(repo_root: Optional[str] = None) -> List[Violation]:
    """Run the model<->code conformance pass over one repo tree."""
    from .conformance import audit
    root = repo_root or repo_root_for()
    return audit(root)
