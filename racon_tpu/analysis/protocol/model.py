"""Formal transition model of the fleet chunk lifecycle.

The model is an untimed abstraction of the protocol implemented across
``fleet/leases.py``, ``fleet/plane.py``, ``fleet/pool.py`` and
``serve/scheduler.py``.  A state is the cross product the checker
enumerates:

* **chunk lifecycle** — per chunk: pending/running/done, the accepted
  result count (the gather log), and the failure count (capped at
  ``retry + 1``, the point past which only the local floor applies);
* **lease ownership** — per chunk: the in-flight attempt set
  ``(worker, canonical, leased)``.  A TTL expiry drops ``leased`` but
  keeps the attempt in flight (the holder may still be computing — the
  straggler/speculation machinery exists exactly because of this);
* **journal ownership** — per chunk: ``jheld`` mirrors
  ``Chunk.journal_held`` (a possibly-live writer owns the canonical
  journal) and ``jowners`` is the set of live canonical writers, the
  quantity the one-canonical-owner invariant bounds;
* **pool membership** — per worker slot: absent / live / draining /
  exited(clean drain) / dead / hung;
* **budget reservations** — the serve scheduler's window-budget ledger:
  abstract submitters racing the atomic check-and-reserve of
  ``Scheduler._admission_lane``;
* **gather log** — which jobs have gathered, plus the per-chunk
  accepted counts that make exactly-once checkable.

Time is abstracted away: lease expiry and heartbeats are modeled as
nondeterministic events (an expiry can always happen — heartbeats only
make it *not mandatory*), and injected faults draw from a finite fault
budget so the space stays bounded.  Worker slots are recycled after a
clean exit or a reclaimed death, standing in for the real pool's
unbounded worker indices.

``TRANSITIONS`` is a pure literal so the conformance pass (and the
``fault-model`` contracts check) can read it from the AST without
importing this module; ``successors()`` must implement exactly the
events it declares — a unit test and the conformance pass keep the two
in sync with the real code.
"""

from __future__ import annotations

from collections import namedtuple
from typing import Dict, Iterator, List, Optional, Tuple

#: Every protocol event the model implements, as
#: ``(name, code_site_file, code_site_callable, fault_point_or_None)``.
#: The code site is where the real transition lives; the fault point is
#: the ``faults.KNOWN_POINTS`` entry that can perturb it.  PURE LITERAL
#: — the conformance pass reads it via ``ast.literal_eval``.
TRANSITIONS = (
    ("submit_reserve", "racon_tpu/serve/scheduler.py", "_admission_lane",
     None),
    ("release_budget", "racon_tpu/serve/scheduler.py", "_finish", None),
    ("scale_up", "racon_tpu/fleet/pool.py", "scale_up", "pool.scale_up"),
    ("spawn_fail", "racon_tpu/fleet/pool.py", "_spawn_one",
     "worker.spawn"),
    ("scale_down", "racon_tpu/fleet/pool.py", "scale_down",
     "pool.scale_down"),
    ("drain_exit", "racon_tpu/fleet/plane.py", "_fetch", None),
    ("dispatch", "racon_tpu/fleet/plane.py", "_assign", None),
    ("steal", "racon_tpu/fleet/plane.py", "_fetch", "pool.steal"),
    ("speculate", "racon_tpu/fleet/plane.py", "_straggler", None),
    ("heartbeat_loss", "racon_tpu/distrib/worker.py", "_heartbeat_loop",
     "worker.heartbeat"),
    ("ttl_expire", "racon_tpu/fleet/plane.py", "_expire_leases", None),
    ("worker_die", "racon_tpu/fleet/plane.py", "_worker_dead",
     "worker.result"),
    ("worker_hang", "racon_tpu/distrib/worker.py", "run_worker",
     "worker.result"),
    ("lease_reclaim", "racon_tpu/fleet/leases.py",
     "release_worker_leases", "lease.reclaim"),
    ("deliver_result", "racon_tpu/fleet/plane.py", "_result",
     "worker.result"),
    ("deliver_error", "racon_tpu/fleet/plane.py", "_chunk_error",
     "native.call"),
    ("local_floor", "racon_tpu/fleet/plane.py", "_run_local", None),
    ("controller_kill", "racon_tpu/resilience/faults.py", "check",
     "pool.scale_up"),
    ("recover", "racon_tpu/serve/scheduler.py", "recover", None),
    ("gather", "racon_tpu/fleet/plane.py", "_gather", None),
)

#: Seeded transition-guard mutations for the self-test mode
#: (``--mutate``): name -> (flipped guard, invariant expected to catch
#: it, config overrides that make the violation reachable).  PURE
#: LITERAL for the same reason as TRANSITIONS.
MUTATIONS = (
    ("expiry-releases-journal",
     "ttl_expire releases the canonical journal of a holder that may "
     "still be alive", "one-canonical-owner", {}),
    ("dispatch-double-canonical",
     "dispatch hands out a canonical journal even when a writer holds "
     "it", "one-canonical-owner", {}),
    ("reclaim-skips-requeue",
     "lease_reclaim forgets to re-queue the dead holder's chunk",
     "recovery-quiescence", {}),
    ("duplicate-accepted",
     "deliver_result accepts a result for an already-done chunk",
     "exactly-once-gather", {}),
    ("split-check-reserve",
     "submit's budget check and reserve are no longer one atomic step",
     "budget-capacity", {}),
    ("drain-exits-holding-lease",
     "a draining worker may exit while it still holds a lease",
     "no-orphan-lease-after-drain", {}),
    ("no-local-floor",
     "retry exhaustion no longer demotes the chunk to the local floor",
     "recovery-quiescence", {"retry": 0}),
    ("recover-marks-done",
     "recovery marks unfinished chunks done instead of re-queueing "
     "them", "exactly-once-gather", {}),
)

# -- state ------------------------------------------------------------------

#: One chunk: lifecycle state ("P"/"R"/"D"), accepted result count,
#: canonical-journal-held flag, live canonical writer set, in-flight
#: attempt set of (worker, canonical, leased), failure count.
Ch = namedtuple("Ch", "st acc jheld jowners att failures")

#: One model state.  workers: per-slot "A"bsent / "L"ive / "G"(draining)
#: / "X"(exited clean) / "D"ead / "H"ung.  submits: per-submitter
#: "idle" / "mid" (mutant only) / "res" / "set"(tled: released or
#: shed — the trace event keeps the distinction, the state does not).
#: The window reservation ledger is derived (`reserved()`): a
#: submitter in "res" holds exactly its estimate, which keeps the
#: state minimal.
S = namedtuple("S", "chunks workers affinity submits faults "
                    "controller gathered")


def reserved(cfg: "Config", s: "S") -> int:
    """The scheduler's window-budget ledger, derived from the
    admission states."""
    return sum(cfg.submit_ests[k] for k, st in enumerate(s.submits)
               if st == "res")


class Config:
    """One bounded configuration of the model."""

    def __init__(self, workers: int = 2, chunks: Tuple[str, ...] =
                 ("A", "A", "B"), retry: int = 1, faults: int = 1,
                 budget: int = 3, submit_ests: Tuple[int, ...] = (2, 2),
                 min_workers: int = 1, steal: bool = True,
                 speculate: bool = True):
        self.workers = workers          # pool slots (== max pool size)
        self.chunks = tuple(chunks)     # job label per chunk
        self.jobs = tuple(sorted(set(chunks)))
        self.retry = retry              # per-chunk retry budget
        self.faults = faults            # injected-fault budget
        self.budget = budget            # window-budget capacity
        self.submit_ests = tuple(submit_ests)
        self.min_workers = min_workers
        self.steal = steal
        self.speculate = speculate

    def describe(self) -> str:
        return (f"{self.workers} workers x {len(self.chunks)} chunks "
                f"({'+'.join(self.jobs)}) x {self.faults} fault(s), "
                f"retry={self.retry}, budget={self.budget}, "
                f"submits={list(self.submit_ests)}")


def initial(cfg: Config) -> S:
    chunks = tuple(Ch("P", 0, False, frozenset(), frozenset(), 0)
                   for _ in cfg.chunks)
    workers = tuple("L" if i < cfg.min_workers else "A"
                    for i in range(cfg.workers))
    return S(chunks=chunks, workers=workers,
             affinity=(None,) * cfg.workers,
             submits=("idle",) * len(cfg.submit_ests),
             faults=cfg.faults, controller="up", gathered=frozenset())


def mutation_names() -> List[str]:
    return [m[0] for m in MUTATIONS]


def mutation_entry(which) -> Tuple[str, str, str, dict]:
    """Resolve a --mutate selector (index or name) to its entry."""
    if isinstance(which, str) and which.isdigit():
        which = int(which)
    if isinstance(which, int):
        if not 0 <= which < len(MUTATIONS):
            raise ValueError(f"mutation index {which} out of range "
                             f"(0..{len(MUTATIONS) - 1})")
        return MUTATIONS[which]
    for m in MUTATIONS:
        if m[0] == which:
            return m
    raise ValueError(f"unknown mutation {which!r} "
                     f"(valid: {', '.join(mutation_names())})")


# -- helpers ----------------------------------------------------------------

def _busy(s: S, w: int) -> bool:
    """A worker runs one chunk at a time: busy while any attempt of its
    is in flight anywhere."""
    return any(a[0] == w for c in s.chunks for a in c.att)


def _upd_chunk(s: S, i: int, c: Ch) -> S:
    chunks = s.chunks[:i] + (c,) + s.chunks[i + 1:]
    return s._replace(chunks=chunks)


def _upd_worker(s: S, w: int, st: str,
                affinity: Optional[str] = "<keep>") -> S:
    workers = s.workers[:w] + (st,) + s.workers[w + 1:]
    s = s._replace(workers=workers)
    if affinity != "<keep>":
        aff = s.affinity[:w] + (affinity,) + s.affinity[w + 1:]
        s = s._replace(affinity=aff)
    return s


def _eligible(cfg: Config, s: S, i: int) -> bool:
    c = s.chunks[i]
    return c.st == "P" and c.failures <= cfg.retry


def _assign(cfg: Config, s: S, i: int, w: int, mutation: str) -> S:
    """The shared dispatch effect (plane._assign): lease + journal
    pick + affinity stamp."""
    c = s.chunks[i]
    canonical = (not c.jheld) or mutation == "dispatch-double-canonical"
    jowners = c.jowners | {w} if canonical else c.jowners
    c = c._replace(st="R", jheld=c.jheld or canonical, jowners=jowners,
                   att=c.att | {(w, canonical, True)})
    s = _upd_chunk(s, i, c)
    return _upd_worker(s, w, "L", affinity=cfg.chunks[i])


def _drop_lease(cfg: Config, s: S, i: int, w: int, mutation: str) -> S:
    """One lease of `w` on chunk i expires: leased -> False, the
    attempt stays in flight, _fail_chunk runs (failures += 1, re-queue
    when no lease remains).  The canonical journal is NOT released —
    unless the expiry-releases-journal mutation flips that guard."""
    c = s.chunks[i]
    att = frozenset((aw, can, False) if aw == w else (aw, can, leased)
                    for aw, can, leased in c.att)
    jheld = c.jheld
    if mutation == "expiry-releases-journal":
        jheld = False
    failures = min(c.failures + 1, cfg.retry + 1)
    st = c.st
    if not any(leased for _, _, leased in att) and st == "R":
        st = "P"
    return _upd_chunk(s, i, c._replace(st=st, att=att, jheld=jheld,
                                       failures=failures))


def _spawnable(s: S, w: int) -> bool:
    """Slot recycling: an absent slot, a cleanly-exited slot, or a dead
    slot whose leases were reclaimed stands in for the real pool's
    fresh worker indices."""
    st = s.workers[w]
    if st == "A":
        return True
    if st in ("X", "D"):
        return not _busy(s, w) and not any(w in c.jowners
                                           for c in s.chunks)
    return False


def _live(s: S) -> int:
    return sum(1 for st in s.workers if st in ("L", "G", "H"))


def _active(s: S) -> int:
    return sum(1 for st in s.workers if st == "L")


# -- successor generation ---------------------------------------------------

Event = Tuple[str, Tuple]          # (transition name, args)


def successors(cfg: Config, s: S,
               mutation: Optional[str] = None) -> Iterator[
                   Tuple[Event, S]]:
    """Every enabled protocol event from state `s` (the real guards, or
    one flipped by `mutation`)."""
    mut = mutation or ""
    if s.controller == "down":
        # the daemon is gone: the only transition is the restart
        yield from _recover(cfg, s, mut)
        return

    # Partial-order reduction, exact for this model: admission
    # transitions touch only `submits` and fleet transitions never read
    # it, so the two components compose with no synchronization.  Every
    # invariant is component-local (budget-capacity reads submits, the
    # rest read the fleet), hence exploring all admission interleavings
    # *first* — and only then the fleet — reaches the same verdicts as
    # the full product while shedding its multiplicative cost.
    settled = True
    for ev, ns in _admission(cfg, s, mut):
        settled = False
        yield ev, ns
    if not settled:
        return
    yield from _pool(cfg, s, mut)
    yield from _dispatching(cfg, s, mut)
    yield from _failures(cfg, s, mut)
    yield from _deliveries(cfg, s, mut)
    yield from _completion(cfg, s, mut)
    if s.faults > 0:
        yield (("controller_kill", ()),
               s._replace(controller="down", faults=s.faults - 1))


def _admission(cfg, s, mut):
    # scheduler.submit: atomic check-and-reserve under _cv -- or, under
    # the split-check-reserve mutation, two separately-interleavable
    # steps (the lost-update race the lock exists to prevent)
    ledger = reserved(cfg, s)
    for k, st in enumerate(s.submits):
        est = cfg.submit_ests[k]
        if st == "idle":
            if mut == "split-check-reserve":
                # the check passes, but the reserve is a later separate
                # step -- a "mid" submitter holds nothing yet, so a
                # racing submitter's check also passes (lost update)
                if ledger + est <= cfg.budget:
                    yield (("submit_reserve", (k, "check")),
                           s._replace(submits=_t(s.submits, k, "mid")))
                else:
                    yield (("submit_reserve", (k, "shed")),
                           s._replace(submits=_t(s.submits, k, "set")))
            elif ledger + est <= cfg.budget:
                yield (("submit_reserve", (k,)),
                       s._replace(submits=_t(s.submits, k, "res")))
            else:
                yield (("submit_reserve", (k, "shed")),
                       s._replace(submits=_t(s.submits, k, "set")))
        elif st == "mid":
            yield (("submit_reserve", (k, "reserve")),
                   s._replace(submits=_t(s.submits, k, "res")))
        elif st == "res":
            yield (("release_budget", (k,)),
                   s._replace(submits=_t(s.submits, k, "set")))


def _pool(cfg, s, mut):
    if _live(s) < cfg.workers:
        spawn_slots = [w for w in range(cfg.workers) if _spawnable(s, w)]
        if spawn_slots:
            w = spawn_slots[0]          # lowest slot: symmetry reduction
            yield (("scale_up", (w,)), _upd_worker(s, w, "L", None))
            if s.faults > 0:
                # worker.spawn / pool.scale_up raise: growth skipped
                yield (("spawn_fail", (w,)),
                       s._replace(faults=s.faults - 1))
    if _active(s) > cfg.min_workers:
        drain_slots = [w for w in range(cfg.workers)
                       if s.workers[w] == "L"]
        for w in drain_slots:
            yield (("scale_down", (w,)), _upd_worker(s, w, "G"))
        if drain_slots and s.faults > 0:
            # pool.scale_down raise: the drain is skipped, counted
            yield (("scale_down", (drain_slots[0], "fault")),
                   s._replace(faults=s.faults - 1))
    for w in range(cfg.workers):
        if s.workers[w] == "G" and (not _busy(s, w)
                                    or mut == "drain-exits-holding-lease"):
            # the drain answer at the worker's next fetch; graceful by
            # construction -- it holds no lease (unless mutated)
            yield (("drain_exit", (w,)), _upd_worker(s, w, "X", None))


def _dispatching(cfg, s, mut):
    idle = [w for w in range(cfg.workers)
            if s.workers[w] == "L" and not _busy(s, w)]
    for w in idle:
        aff = s.affinity[w]
        own = [i for i in range(len(cfg.chunks))
               if _eligible(cfg, s, i) and (aff is None
                                            or cfg.chunks[i] == aff)]
        other = [i for i in range(len(cfg.chunks))
                 if _eligible(cfg, s, i) and aff is not None
                 and cfg.chunks[i] != aff]
        for i in own:
            yield (("dispatch", (i, w)), _assign(cfg, s, i, w, mut))
        if not own and other and cfg.steal:
            for i in other:
                yield (("steal", (i, w)), _assign(cfg, s, i, w, mut))
            if s.faults > 0:
                # pool.steal raise: absorbed, the fetch waits
                yield (("steal", (other[0], w, "fault")),
                       s._replace(faults=s.faults - 1))
        if cfg.speculate:
            for i in range(len(cfg.chunks)):
                c = s.chunks[i]
                # the real guard counts *leases* (len(c.leases) >= 2
                # blocks; expired in-flight attempts don't count)
                leased = sum(1 for _, _, ls in c.att if ls)
                if (c.st == "R" and leased == 1
                        and not any(a[0] == w for a in c.att)):
                    yield (("speculate", (i, w)),
                           _assign(cfg, s, i, w, mut))


def _failures(cfg, s, mut):
    for i, c in enumerate(s.chunks):
        for (w, can, leased) in sorted(c.att):
            if leased:
                yield (("ttl_expire", (i, w)),
                       _drop_lease(cfg, s, i, w, mut))
    for w in range(cfg.workers):
        if s.workers[w] not in ("L", "G", "H"):
            continue
        if s.faults > 0 and s.workers[w] != "H":
            # worker.heartbeat raise: renewals stop silently; every
            # lease the worker holds expires
            held = [i for i, c in enumerate(s.chunks)
                    if any(a[0] == w and a[2] for a in c.att)]
            if held:
                hs = s._replace(faults=s.faults - 1)
                for i in held:
                    hs = _drop_lease(cfg, hs, i, w, mut)
                yield (("heartbeat_loss", (w,)), hs)
            # worker.result hang: the worker wedges mid-chunk forever
            # (the straggler limit case -- its attempts never deliver)
            if _busy(s, w) and s.workers[w] == "L":
                yield (("worker_hang", (w,)),
                       _upd_worker(s._replace(faults=s.faults - 1),
                                   w, "H"))
        if s.faults > 0:
            # worker.result kill / EOF: confirmed death
            ds = _upd_worker(s._replace(faults=s.faults - 1), w, "D",
                             None)
            # die step: the writer is gone from every live-writer set;
            # lease release is the separate lease_reclaim transition
            chunks = tuple(c._replace(jowners=c.jowners - {w})
                           for c in ds.chunks)
            yield (("worker_die", (w,)), ds._replace(chunks=chunks))
    for w in range(cfg.workers):
        if s.workers[w] == "D" and _busy(s, w):
            yield (("lease_reclaim", (w,)), _reclaim(cfg, s, w, mut))
            if s.faults > 0:
                # lease.reclaim raise: absorbed and counted, the
                # reclaim itself still proceeds
                rs = _reclaim(cfg, s, w, mut)
                yield (("lease_reclaim", (w, "fault")),
                       rs._replace(faults=rs.faults - 1))


def _reclaim(cfg, s, w, mut):
    """Confirmed death releases the holder's leases AND its canonical
    journal (the writer is known dead), then re-queues the chunk --
    release_worker_leases + _fail_chunk."""
    for i, c in enumerate(s.chunks):
        mine = {a for a in c.att if a[0] == w}
        if not mine:
            continue
        att = c.att - mine
        jheld = c.jheld
        if any(can and leased for _, can, leased in mine):
            jheld = False               # leased canonical: released
        if mut == "reclaim-skips-requeue":
            c = c._replace(att=att, jheld=c.jheld)
        else:
            failures = min(c.failures + 1, cfg.retry + 1)
            st = c.st
            if st == "R" and not any(ls for _, _, ls in att):
                st = "P"
            c = c._replace(st=st, att=att, jheld=jheld,
                           failures=failures)
        s = _upd_chunk(s, i, c)
    return s


def _deliveries(cfg, s, mut):
    for i, c in enumerate(s.chunks):
        for (w, can, leased) in sorted(c.att):
            if s.workers[w] not in ("L", "G"):
                continue                # hung/dead workers never deliver
            att = c.att - {(w, can, leased)}
            if c.st == "D":
                # duplicate: discarded and counted -- unless mutated
                acc = c.acc + 1 if mut == "duplicate-accepted" \
                    else c.acc
                nc = c._replace(att=att, acc=min(acc, 2),
                                jowners=c.jowners - {w})
                yield (("deliver_result", (i, w, "dup")),
                       _upd_chunk(s, i, nc))
            else:
                # first result wins, even when the lease expired
                nc = c._replace(st="D", acc=min(c.acc + 1, 2), att=att,
                                jowners=c.jowners - {w})
                yield (("deliver_result", (i, w)), _upd_chunk(s, i, nc))
            if s.faults > 0 and c.st != "D":
                # the worker survives but the polish failed (an
                # injected native fault): _chunk_error releases the
                # canonical journal only when the lease is still held
                jheld = c.jheld and not (can and leased)
                failures = min(c.failures + 1, cfg.retry + 1)
                st = c.st
                if st == "R" and not any(ls for _, _, ls in att):
                    st = "P"
                nc = c._replace(st=st, att=att, jheld=jheld,
                                jowners=c.jowners - {w},
                                failures=failures)
                yield (("deliver_error", (i, w)),
                       _upd_chunk(s, i, nc)._replace(
                           faults=s.faults - 1))


def _completion(cfg, s, mut):
    for i, c in enumerate(s.chunks):
        if (c.st == "P" and c.failures > cfg.retry
                and not any(ls for _, _, ls in c.att)
                and mut != "no-local-floor"):
            # retry budget exhausted: the fleet -> local lattice floor
            # (plane._run_local, byte-identical host oracle)
            yield (("local_floor", (i,)),
                   _upd_chunk(s, i, c._replace(st="D",
                                               acc=min(c.acc + 1, 2))))
    for j in cfg.jobs:
        if j in s.gathered:
            continue
        idx = [i for i, cj in enumerate(cfg.chunks) if cj == j]
        if all(s.chunks[i].st == "D" for i in idx):
            yield (("gather", (j,)),
                   s._replace(gathered=s.gathered | {j}))


def _recover(cfg, s, mut):
    """Daemon restart: scheduler.recover re-queues every unfinished
    job from its spec; leases and the in-memory journal ownership died
    with the plane, the chunk journals on disk turn re-runs into
    resumes.  Worker slots come back absent (the children died with
    the daemon)."""
    chunks = []
    for i, c in enumerate(s.chunks):
        if cfg.chunks[i] in s.gathered:
            chunks.append(c._replace(att=frozenset(),
                                     jowners=frozenset()))
        elif mut == "recover-marks-done":
            chunks.append(Ch("D", 0, False, frozenset(), frozenset(), 0))
        else:
            chunks.append(Ch("P", 0, False, frozenset(), frozenset(), 0))
    yield (("recover", ()),
           S(chunks=tuple(chunks), workers=("A",) * cfg.workers,
             affinity=(None,) * cfg.workers,
             submits=s.submits, faults=s.faults, controller="up",
             gathered=s.gathered))


def _t(tup: tuple, k: int, v) -> tuple:
    return tup[:k] + (v,) + tup[k + 1:]


# -- conformance anchors ----------------------------------------------------

def transition_names() -> List[str]:
    return [t[0] for t in TRANSITIONS]


def fault_points() -> Dict[str, List[str]]:
    """fault point -> transitions claiming it (the model side of the
    contracts `fault-model` coverage check)."""
    out: Dict[str, List[str]] = {}
    for name, _file, _fn, point in TRANSITIONS:
        if point is not None:
            out.setdefault(point, []).append(name)
    return out
