"""Model <-> implementation conformance (the drift guard).

The protocol model is only worth trusting while it matches the code it
claims to abstract, so every entry in ``model.TRANSITIONS`` is
cross-checked against the analyzed tree — in both directions:

* **model-site** — the transition's declared code site must exist: the
  file parses and defines the named callable.  A renamed or deleted
  handler breaks this before the model silently checks dead code.
* **model-fault** — the transition's declared fault point must be in
  ``faults.KNOWN_POINTS``; a point the runtime grammar does not know
  can never be injected, so its counterexamples would be unreplayable.
* **model-coverage** — the reverse direction: every literal
  ``faults.check("...")`` call site on a fleet-scoped point
  (``worker.*`` / ``pool.*`` / ``lease.*``) must be claimed by some
  model transition.  An injection point the model does not know about
  is an unchecked failure mode.

``TRANSITIONS`` is read from the analyzed tree's AST
(``ast.literal_eval``), never imported — fixture mini-trees can carry
deliberately-drifted models, and the pass always judges the tree it is
pointed at rather than the interpreter's copy.  A tree without a
protocol model (or without ``faults.py``) skips the respective checks,
contracts-style.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from .. import astcache
from ..concurrency import contracts
from ..lint import Violation, iter_source_files

MODEL_SITE = "model-site"
MODEL_FAULT = "model-fault"
MODEL_COVERAGE = "model-coverage"

MODEL_REL = "racon_tpu/analysis/protocol/model.py"

#: KNOWN_POINTS prefixes the fleet control plane owns; everything else
#: (align.*, poa.*, journal.*, ...) belongs to the polishing engines.
FLEET_PREFIXES = ("worker.", "pool.", "lease.")

#: (name, site_file, site_callable, fault_point_or_None, decl_line)
Entry = Tuple[str, str, str, Optional[str], int]


def _transitions(repo_root: str
                 ) -> Tuple[Optional[List[Entry]], List[Violation]]:
    """TRANSITIONS entries from the tree's model.py AST, with per-entry
    declaration lines.  (None, []) when the tree has no model."""
    parsed = astcache.load(repo_root, MODEL_REL)
    if parsed.tree is None:
        return None, []
    for node in parsed.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "TRANSITIONS"):
            break
    else:
        return None, [Violation(
            MODEL_SITE, MODEL_REL, 1,
            "protocol model defines no TRANSITIONS literal")]
    if not isinstance(node.value, (ast.Tuple, ast.List)):
        return None, [Violation(
            MODEL_SITE, MODEL_REL, node.lineno,
            "TRANSITIONS must be a pure tuple literal")]
    entries: List[Entry] = []
    out: List[Violation] = []
    for elt in node.value.elts:
        try:
            name, rel, fn, point = ast.literal_eval(elt)
        except (ValueError, SyntaxError, TypeError):
            out.append(Violation(
                MODEL_SITE, MODEL_REL, elt.lineno,
                "TRANSITIONS entry is not a pure "
                "(name, file, callable, fault_point) literal"))
            continue
        entries.append((name, rel, fn, point, elt.lineno))
    return entries, out


def _defined_callables(tree: ast.Module) -> set:
    return {n.name for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _site_checks(repo_root: str,
                 entries: List[Entry]) -> List[Violation]:
    out: List[Violation] = []
    for name, rel, fn, _point, line in entries:
        parsed = astcache.load(repo_root, rel)
        if parsed.tree is None:
            out.append(Violation(
                MODEL_SITE, MODEL_REL, line,
                f"transition {name}: code site {rel} is missing from "
                f"the analyzed tree"))
        elif fn not in _defined_callables(parsed.tree):
            out.append(Violation(
                MODEL_SITE, MODEL_REL, line,
                f"transition {name}: {rel} defines no callable "
                f"{fn!r} — the model points at dead code"))
    return out


def _fault_checks(repo_root: str, entries: List[Entry],
                  known: Dict[str, int]) -> List[Violation]:
    out: List[Violation] = []
    for name, _rel, _fn, point, line in entries:
        if point is not None and point not in known:
            out.append(Violation(
                MODEL_FAULT, MODEL_REL, line,
                f"transition {name}: fault point {point!r} is not in "
                f"faults.KNOWN_POINTS — its counterexamples cannot "
                f"be injected"))
    return out


def _coverage_checks(repo_root: str,
                     entries: List[Entry]) -> List[Violation]:
    claimed = {e[3] for e in entries if e[3] is not None}
    out: List[Violation] = []
    for rel in iter_source_files(repo_root):
        if rel == MODEL_REL:
            continue
        parsed = astcache.load(repo_root, rel)
        if parsed.tree is None:
            continue
        for node in ast.walk(parsed.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "check"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            point = node.args[0].value
            if (point.startswith(FLEET_PREFIXES)
                    and point not in claimed):
                out.append(Violation(
                    MODEL_COVERAGE, rel, node.lineno,
                    f"fleet fault point {point!r} is injected here "
                    f"but no protocol-model transition claims it — "
                    f"an unchecked failure mode"))
    return out


def audit(repo_root: str) -> List[Violation]:
    entries, out = _transitions(repo_root)
    if entries is None:
        return out          # tree carries no protocol model: skip
    out.extend(_site_checks(repo_root, entries))
    known = dict(contracts.fault_points(repo_root))
    if known:               # no faults.py in tree: skip fault checks
        out.extend(_fault_checks(repo_root, entries, known))
        out.extend(_coverage_checks(repo_root, entries))
    return sorted(out, key=lambda v: (v.path, v.line, v.rule, v.message))
