"""Compile model counterexamples into replayable fault schedules.

A counterexample trace is a sequence of model events.  Most of them —
dispatch, TTL expiry, steal, gather — happen on their own in a real
fleet given enough timing pressure; only the *fault-consuming* events
(the ones that decrement the model's fault budget) need help.  Each of
those maps onto a ``faults.KNOWN_POINTS`` injection, so a whole trace
compiles into one ``RACON_TPU_FAULT`` spec string (plus, when the
faults target a specific worker, a ``RACON_TPU_DISTRIB_FAULT_WORKER``
scope).  The compiled spec is validated against the *real* parser
(``faults.parse_spec``) before it is handed out — the bridge that keeps
a model counterexample honest: if the model invents a fault the runtime
grammar cannot express, compilation fails loudly.

``witness_trace`` runs the search in the other direction: it asks the
checker for a shortest *clean* run of the real model that still passes
through a chosen set of fault events (worker death + lease reclaim by
default) and ends quiescent — the schedule the e2e replay test drives
against a live two-worker daemon.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Dict, FrozenSet, List, Optional, Tuple

from .model import Config, Event, initial, successors

#: env var names, duplicated here so compiling a schedule does not
#: import the runtime fault machinery (validation does, lazily).
FAULT_ENV = "RACON_TPU_FAULT"
SCOPE_ENV = "RACON_TPU_DISTRIB_FAULT_WORKER"

#: Hang length for compiled worker_hang events: comfortably past the
#: lease TTL the replay tests run with, well short of any test timeout.
_HANG_S = 6

#: model event name -> (fault point, spec fields, scoped-to-worker).
#: Only fault-consuming events appear; everything else replays itself.
_COMPILE: Dict[str, Tuple[str, str, bool]] = {
    "worker_die": ("worker.result", "kill=1:count=1", True),
    "worker_hang": ("worker.result", f"hang={_HANG_S}:count=1", True),
    # heartbeat loss is permanent by design: renewals stop silently,
    # so the point stays broken (no count cap)
    "heartbeat_loss": ("worker.heartbeat", "raise=RuntimeError", True),
    "spawn_fail": ("worker.spawn", "raise=RuntimeError:count=1", False),
    "scale_down": ("pool.scale_down", "raise=RuntimeError:count=1",
                   False),
    "steal": ("pool.steal", "raise=RuntimeError:count=1", False),
    "lease_reclaim": ("lease.reclaim", "raise=RuntimeError:count=1",
                      False),
    "deliver_error": ("native.call", "raise=RuntimeError:count=1", True),
    "controller_kill": ("pool.scale_up", "kill=1:count=1", False),
}

#: events where the fault variant is marked by a trailing "fault" arg
#: (the unmarked form is the ordinary, injection-free transition).
_MARKED = {"scale_down", "steal", "lease_reclaim"}


class Unreplayable(ValueError):
    """The trace cannot be expressed as one RACON_TPU_FAULT schedule."""


@dataclass(frozen=True)
class Schedule:
    """One replayable fault schedule compiled from a trace."""

    spec: str                      # RACON_TPU_FAULT value
    worker: Optional[int]          # RACON_TPU_DISTRIB_FAULT_WORKER
    events: Tuple[str, ...]        # the injected events, in trace order

    def env(self) -> Dict[str, str]:
        out = {FAULT_ENV: self.spec} if self.spec else {}
        if self.worker is not None:
            out[SCOPE_ENV] = str(self.worker)
        return out

    def render(self) -> str:
        scope = (f" {SCOPE_ENV}={self.worker}"
                 if self.worker is not None else "")
        return f"{FAULT_ENV}={self.spec!r}{scope}"


def _injected(ev: Event) -> Optional[Tuple[str, Optional[int]]]:
    """(fault event name, scoped worker) when `ev` consumed a fault."""
    name, args = ev
    if name not in _COMPILE:
        return None
    if name in _MARKED and (not args or args[-1] != "fault"):
        return None                 # the ordinary, uninjected form
    _point, _fields, scoped = _COMPILE[name]
    w: Optional[int] = None
    if scoped:
        # the worker index is the last int argument (deliver_error and
        # worker_* events put it there)
        ints = [a for a in args if isinstance(a, int)]
        w = ints[-1] if ints else None
    return name, w


def compile_trace(trace: List[Event], validate: bool = True) -> Schedule:
    """Compile a counterexample trace into one fault schedule.

    Raises Unreplayable when the trace needs faults scoped to two
    different workers — the runtime has a single scope env var.
    """
    parts: List[str] = []
    names: List[str] = []
    scopes: List[int] = []
    for ev in trace:
        hit = _injected(ev)
        if hit is None:
            continue
        name, w = hit
        point, fields, scoped = _COMPILE[name]
        parts.append(f"{point}:{fields}" if fields else point)
        names.append(name)
        if scoped and w is not None:
            scopes.append(w)
    distinct = sorted(set(scopes))
    if len(distinct) > 1:
        raise Unreplayable(
            f"trace injects faults into workers {distinct}, but "
            f"{SCOPE_ENV} scopes a single worker")
    spec = ",".join(parts)
    sched = Schedule(spec=spec,
                     worker=distinct[0] if distinct else None,
                     events=tuple(names))
    if validate and spec:
        from racon_tpu.resilience import faults
        faults.parse_spec(spec)     # ValueError on grammar drift
    return sched


def witness_trace(cfg: Optional[Config] = None,
                  require: Tuple[str, ...] = ("worker_die",
                                              "lease_reclaim"),
                  max_states: int = 2_000_000,
                  ) -> Tuple[List[Event], Schedule]:
    """Shortest clean run of the *real* model that passes through every
    event in `require` and ends quiescent, plus its compiled schedule.

    BFS over (state, events-seen) so the progress through `require` is
    part of the search: the result is the minimal interleaving that a
    replay test can drive against a live fleet.
    """
    from . import invariants as inv

    cfg = cfg or Config(chunks=("A", "A", "A"), submit_ests=(2,))
    want = frozenset(require)
    init = initial(cfg)
    start = (init, frozenset())
    seen = {start: 0}
    nodes = [start]
    parent: List[Tuple[int, Optional[Event]]] = [(-1, None)]
    q = deque([0])
    while q:
        nid = q.popleft()
        s, got = nodes[nid]
        for ev, ns in successors(cfg, s, None):
            ngot: FrozenSet[str] = got | ({ev[0]} & want)
            key = (ns, ngot)
            if key in seen:
                continue
            if len(nodes) >= max_states:
                break
            seen[key] = len(nodes)
            nodes.append(key)
            parent.append((nid, ev))
            if ngot == want and inv.quiescent(cfg, ns):
                trace = _unwind(parent, len(nodes) - 1)
                return trace, compile_trace(trace)
            q.append(len(nodes) - 1)
    raise Unreplayable(
        f"no quiescent run through {sorted(want)} in "
        f"{cfg.describe()} (searched {len(nodes)} nodes)")


def _unwind(parent, nid: int) -> List[Event]:
    out: List[Event] = []
    while nid > 0:
        nid, ev = parent[nid]
        if ev is not None:
            out.append(ev)
    out.reverse()
    return out
