"""Explicit-state exploration of the protocol model.

BFS with state hashing is the default: states are interned to integer
ids, the frontier expands level by level, safety invariants run the
moment a state is discovered (so the first counterexample is a
*shortest* trace), and the forward edge list feeds the
recovery-quiescence check — a backward closure from the quiescent
states that every reachable state must fall inside.

The DFS fallback (``strategy="dfs"``) bounds depth instead of
exhausting the space: it exists for configurations too large to hold
in memory, trades minimal counterexamples for a bounded-depth sweep,
and reports ``exhausted=False`` whenever the bound clipped anything.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from . import invariants as inv
from .model import Config, Event, S, initial, mutation_entry, successors


@dataclass
class Violation:
    invariant: str
    detail: str
    trace: List[Event]          # minimal event path from the initial state

    def render(self) -> str:
        steps = " -> ".join(_fmt_event(e) for e in self.trace) or "<init>"
        return f"{self.invariant}: {self.detail}\n    trace: {steps}"


@dataclass
class Result:
    config: Config
    mutation: Optional[str]
    strategy: str
    states: int
    transitions: int
    elapsed_s: float
    exhausted: bool             # the bounded space was fully explored
    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations


def _fmt_event(ev: Event) -> str:
    name, args = ev
    return f"{name}({','.join(str(a) for a in args)})" if args else name


def check(cfg: Optional[Config] = None, mutation: Optional[str] = None,
          strategy: str = "bfs", max_states: int = 2_000_000,
          depth: int = 40, stop_on_first: bool = True) -> Result:
    """Explore the model; return the result with any counterexamples.

    mutation — a MUTATIONS name/index: its paired config overrides are
    applied on top of `cfg` (each mutation's violation is reachable
    under its documented bounded configuration).
    """
    mut_name = None
    if mutation is not None:
        name, _doc, _expected, overrides = mutation_entry(mutation)
        mut_name = name
        base = cfg or Config()
        if overrides:
            kw = dict(workers=base.workers, chunks=base.chunks,
                      retry=base.retry, faults=base.faults,
                      budget=base.budget, submit_ests=base.submit_ests,
                      min_workers=base.min_workers, steal=base.steal,
                      speculate=base.speculate)
            kw.update(overrides)
            cfg = Config(**kw)
        else:
            cfg = base
    cfg = cfg or Config()

    t0 = time.monotonic()
    if strategy == "dfs":
        res = _dfs(cfg, mut_name, max_states, depth, stop_on_first)
    else:
        res = _bfs(cfg, mut_name, max_states, stop_on_first)
    res.elapsed_s = time.monotonic() - t0
    return res


def _bfs(cfg: Config, mutation: Optional[str], max_states: int,
         stop_on_first: bool) -> Result:
    init = initial(cfg)
    ids: Dict[S, int] = {init: 0}
    states: List[S] = [init]
    parent: List[Tuple[int, Optional[Event]]] = [(-1, None)]
    edges: List[Tuple[int, int]] = []
    violations: List[Violation] = []

    v = _check_safety(cfg, init)
    if v is not None:
        violations.append(Violation(v[0], v[1], []))
        if stop_on_first:
            return Result(cfg, mutation, "bfs", 1, 0, 0.0, False,
                          violations)

    frontier = [0]
    exhausted = True
    while frontier and not (violations and stop_on_first):
        next_frontier: List[int] = []
        for sid in frontier:
            s = states[sid]
            for ev, ns in successors(cfg, s, mutation):
                nid = ids.get(ns)
                if nid is None:
                    if len(states) >= max_states:
                        exhausted = False
                        continue
                    nid = len(states)
                    ids[ns] = nid
                    states.append(ns)
                    parent.append((sid, ev))
                    next_frontier.append(nid)
                    v = _check_safety(cfg, ns)
                    if v is not None:
                        violations.append(Violation(
                            v[0], v[1], _trace(parent, nid)))
                        if stop_on_first:
                            return Result(cfg, mutation, "bfs",
                                          len(states), len(edges), 0.0,
                                          False, violations)
                edges.append((sid, nid))
        frontier = next_frontier

    if exhausted and not violations:
        violations.extend(_check_quiescence(cfg, states, edges, parent))
    return Result(cfg, mutation, "bfs", len(states), len(edges), 0.0,
                  exhausted, violations)


def _dfs(cfg: Config, mutation: Optional[str], max_states: int,
         depth: int, stop_on_first: bool) -> Result:
    """Depth-bounded DFS fallback: safety only (the quiescence check
    needs the exhausted graph), counterexamples not guaranteed
    minimal."""
    init = initial(cfg)
    ids: Dict[S, int] = {init: 0}
    states: List[S] = [init]
    parent: List[Tuple[int, Optional[Event]]] = [(-1, None)]
    violations: List[Violation] = []
    n_edges = 0
    exhausted = True

    v = _check_safety(cfg, init)
    if v is not None:
        violations.append(Violation(v[0], v[1], []))
        if stop_on_first:
            return Result(cfg, mutation, "dfs", 1, 0, 0.0, False,
                          violations)

    stack: List[Tuple[int, int]] = [(0, 0)]     # (state id, depth)
    while stack and not (violations and stop_on_first):
        sid, d = stack.pop()
        if d >= depth:
            exhausted = False
            continue
        for ev, ns in successors(cfg, states[sid], mutation):
            n_edges += 1
            nid = ids.get(ns)
            if nid is not None:
                continue
            if len(states) >= max_states:
                exhausted = False
                continue
            nid = len(states)
            ids[ns] = nid
            states.append(ns)
            parent.append((sid, ev))
            v = _check_safety(cfg, ns)
            if v is not None:
                violations.append(Violation(v[0], v[1],
                                            _trace(parent, nid)))
                if stop_on_first:
                    return Result(cfg, mutation, "dfs", len(states),
                                  n_edges, 0.0, False, violations)
            stack.append((nid, d + 1))
    return Result(cfg, mutation, "dfs", len(states), n_edges, 0.0,
                  exhausted, violations)


def _check_safety(cfg: Config, s: S) -> Optional[Tuple[str, str]]:
    for name, fn in inv.SAFETY.items():
        detail = fn(cfg, s)
        if detail is not None:
            return name, detail
    return None


def _check_quiescence(cfg: Config, states: List[S],
                      edges: List[Tuple[int, int]],
                      parent) -> List[Violation]:
    """Backward closure from the quiescent states; anything reachable
    but outside it is a stuck state — recovery cannot reach
    quiescence from there."""
    preds: List[List[int]] = [[] for _ in states]
    for src, dst in edges:
        preds[dst].append(src)
    good = [False] * len(states)
    work = [i for i, s in enumerate(states) if inv.quiescent(cfg, s)]
    for i in work:
        good[i] = True
    while work:
        dst = work.pop()
        for src in preds[dst]:
            if not good[src]:
                good[src] = True
                work.append(src)
    bad = [i for i, g in enumerate(good) if not g]
    if not bad:
        return []
    # ids are in BFS discovery order: the first bad id has the
    # shortest trace from the initial state
    sid = bad[0]
    s = states[sid]
    stuck = [f"chunk {i}={c.st}/f{c.failures}"
             for i, c in enumerate(s.chunks) if c.st != "D"]
    return [Violation(
        inv.QUIESCENCE,
        f"{len(bad)} reachable state(s) cannot reach quiescence "
        f"(first: {', '.join(stuck) or 'admission/controller stuck'})",
        _trace(parent, sid))]


def _trace(parent, sid: int) -> List[Event]:
    out: List[Event] = []
    while sid > 0:
        sid, ev = parent[sid]
        if ev is not None:
            out.append(ev)
    out.reverse()
    return out
