"""The invariant library the checker evaluates over every reachable
state.

Safety invariants are predicates on a single state (checked the moment
a state is discovered, so the BFS counterexample is minimal).  The one
graph invariant — recovery-reaches-quiescence — is evaluated over the
fully-explored space: from *every* reachable state a quiescent state
(all jobs gathered, every admission settled, controller up) must remain
reachable.  That formulation survives the pool's benign resize cycles,
which never deadlock but never stop either.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from .model import Config, S, reserved

ONE_CANONICAL = "one-canonical-owner"
BUDGET = "budget-capacity"
EXACTLY_ONCE = "exactly-once-gather"
NO_ORPHAN = "no-orphan-lease-after-drain"
QUIESCENCE = "recovery-quiescence"


def check_one_canonical(cfg: Config, s: S) -> Optional[str]:
    """At most one live writer may hold a chunk's canonical journal:
    a merely-expired holder keeps it, only a *confirmed dead* one
    releases it (leases.release_worker_leases)."""
    for i, c in enumerate(s.chunks):
        if len(c.jowners) > 1:
            return (f"chunk {i}: {len(c.jowners)} live canonical "
                    f"journal writers (workers "
                    f"{sorted(c.jowners)}) — resumed bytes would "
                    f"interleave")
        if c.jowners and not c.jheld:
            return (f"chunk {i}: journal marked free while worker "
                    f"{min(c.jowners)} still writes it")
    return None


def check_budget(cfg: Config, s: S) -> Optional[str]:
    """The serve window-budget ledger never oversubscribes capacity
    (scheduler._admission_lane's atomic check-and-reserve)."""
    ledger = reserved(cfg, s)
    if ledger > cfg.budget:
        return (f"reserved windows {ledger} > budget {cfg.budget} "
                f"(submits: {list(s.submits)})")
    return None


def check_exactly_once(cfg: Config, s: S) -> Optional[str]:
    """Each chunk's result is accepted at most once (duplicates from
    speculation/stealing are discarded), and a gathered job gathered
    every chunk exactly once."""
    for i, c in enumerate(s.chunks):
        if c.acc >= 2:
            return (f"chunk {i}: {c.acc} results accepted — a "
                    f"duplicate reached the gather log")
        if cfg.chunks[i] in s.gathered and c.acc != 1:
            return (f"job {cfg.chunks[i]} gathered with chunk {i} "
                    f"accepted {c.acc} times")
    return None


def check_no_orphan(cfg: Config, s: S) -> Optional[str]:
    """A cleanly-drained worker exited between chunks: it holds no
    lease, no in-flight attempt, and no canonical journal."""
    for w, st in enumerate(s.workers):
        if st != "X":
            continue
        for i, c in enumerate(s.chunks):
            if any(a[0] == w for a in c.att):
                return (f"drained worker {w} exited still holding an "
                        f"attempt on chunk {i}")
            if w in c.jowners:
                return (f"drained worker {w} exited still owning "
                        f"chunk {i}'s canonical journal")
    return None


def quiescent(cfg: Config, s: S) -> bool:
    """The terminal contract: every job gathered, every admission
    settled (released or shed), the controller up."""
    return (s.controller == "up"
            and all(j in s.gathered for j in cfg.jobs)
            and all(st == "set" for st in s.submits))


#: invariant name -> state predicate (None = graph-level, handled by
#: the checker itself).
SAFETY: Dict[str, Callable[[Config, S], Optional[str]]] = {
    ONE_CANONICAL: check_one_canonical,
    BUDGET: check_budget,
    EXACTLY_ONCE: check_exactly_once,
    NO_ORPHAN: check_no_orphan,
}

ALL = [ONE_CANONICAL, BUDGET, EXACTLY_ONCE, NO_ORPHAN, QUIESCENCE]


def invariant_names() -> List[str]:
    return list(ALL)
