"""Opt-in runtime sanitizer for the device drivers (dynamic analysis).

``RACON_TPU_SANITIZE=1`` arms three families of runtime checks — the
dynamic counterpart to this package's static lint + jaxpr audit:

* **kernel-output invariants** — every builder decorated with
  ``ops.kernel_cache.device_keyed_cache`` gets its built kernel wrapped
  in a checking proxy: float device outputs must be finite.  Checks are
  skipped while the proxied kernel is being re-traced (``shard_map`` /
  ``jit`` hand it tracers, not arrays); the concrete arrays are covered
  at the driver seams below.
* **driver-seam invariants** — the consensus install path
  (``poa_driver._install``) asserts in-range consensus codes/lengths,
  and on a sampled fraction of device-served windows
  (``RACON_TPU_SANITIZE_PARITY``, default every 8th) recomputes the
  window on the host and compares byte-for-byte *before* the device
  result is installed, so an armed run stays byte-identical to an
  unarmed one.  The aligner seam (``align.run_jobs``) asserts CIGAR op
  codes stay in the M/I/D range on served rows.
* **shared-state guards** — the drivers' stats dicts are wrapped so a
  mutation from any thread other than the owning driver thread is
  recorded as a ``racy-stats`` finding.

Violations never raise and never alter polish output: they are recorded
as structured findings, surfaced in ``RunReport.as_dict()["sanitize"]``
and rendered by ``python -m racon_tpu.analysis --sanitize-report``.

Fault hooks (the ``RACON_TPU_FAULT`` grammar, default ``raise=``):
``sanitize.nan`` poisons the checker's *copy* of one device buffer (the
installed consensus is untouched) and ``sanitize.stats`` performs one
real cross-thread stats mutation — both prove the detectors fire
end-to-end without corrupting a run.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Tuple

import numpy as np

from .. import config

KNOB = "RACON_TPU_SANITIZE"
PARITY_KNOB = "RACON_TPU_SANITIZE_PARITY"

#: Distinct (kind, where) findings kept; later hits only bump counters.
_MAX_FINDINGS = 100


@dataclass
class Finding:
    """One sanitizer violation class, aggregated across occurrences."""

    kind: str    # nonfinite | cigar-op-range | consensus-range |
                 # parity | racy-stats
    where: str   # kernel builder / driver seam that caught it
    detail: str  # first occurrence's specifics
    count: int = 1


_lock = threading.Lock()
_findings: Dict[Tuple[str, str], Finding] = {}


def enabled() -> bool:
    """Whether the runtime sanitizer is armed."""
    return config.get_bool(KNOB)


def reset() -> None:
    """Clear collected findings (per-run; polisher ctors call this)."""
    with _lock:
        _findings.clear()


def record(kind: str, where: str, detail: str) -> None:
    """Record one violation (thread-safe; capped, never raises)."""
    with _lock:
        f = _findings.get((kind, where))
        if f is not None:
            f.count += 1
        elif len(_findings) < _MAX_FINDINGS:
            _findings[(kind, where)] = Finding(kind, where, detail)


def findings() -> List[Finding]:
    with _lock:
        return list(_findings.values())


def as_dicts() -> List[dict]:
    """JSON-ready findings (the RunReport / --sanitize-report schema)."""
    return [{"kind": f.kind, "where": f.where, "detail": f.detail,
             "count": f.count} for f in findings()]


# --------------------------------------------------------------------------
# kernel-output proxy (hooked in by ops.kernel_cache.device_keyed_cache)
# --------------------------------------------------------------------------

def wrap_kernel(name: str, built):
    """Checking proxy around a built kernel (or kernel factory).

    Factories — builders whose return value is itself a callable that
    produces the kernel (the Pallas POA builders) — are wrapped
    transitively so the eventual kernel is proxied.  Outputs pass
    through unchanged; only a check rides along."""
    if not callable(built):
        return built

    def proxied(*args, **kwargs):
        out = built(*args, **kwargs)
        if callable(out):
            return wrap_kernel(name, out)
        check_kernel_outputs(name, out)
        return out

    return proxied


def check_kernel_outputs(name: str, out) -> None:
    """Generic invariant on concrete kernel outputs: float arrays are
    finite.  Tracers (a proxied kernel re-traced inside shard_map/jit)
    are skipped wholesale — the driver seams check the concrete side."""
    arrays = out if isinstance(out, (tuple, list)) else (out,)
    import jax

    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            return
    for k, a in enumerate(arrays):
        try:
            arr = np.asarray(a)
        except Exception:  # not array-like (config tuples, scalars…)
            continue
        if arr.dtype.kind == "f" and not np.isfinite(arr).all():
            record("nonfinite", f"{name}[out {k}]",
                   f"non-finite values in float output {k} "
                   f"(shape {arr.shape})")


# --------------------------------------------------------------------------
# driver-seam checks (called from ops/align.py and ops/poa_driver.py)
# --------------------------------------------------------------------------

def check_align_outputs(ops, cnt, ok, where: str) -> None:
    """Aligner outputs: op codes on a served (ok) row must stay in the
    M/I/D range 0..2 — code 3 is the kernel's out-of-band failure marker
    and is only legal on rows whose ok flag is already false."""
    ops = np.asarray(ops)
    cnt = np.asarray(cnt).reshape(-1)
    ok = np.asarray(ok).reshape(-1)
    for bi in range(ops.shape[0]):
        if bi >= len(ok) or not bool(ok[bi]):
            continue
        row = ops[bi, :int(cnt[bi])]
        if row.size and int(row.max()) > 2:
            record("cigar-op-range", where,
                   f"op code {int(row.max())} > 2 on served row {bi}")


def check_consensus_outputs(results, idxs, where: str) -> None:
    """Consensus chunk invariants at the install seam, where the arrays
    are concrete: cons_len within the padded capacity, base codes
    decodable (0..4) within each served length, failed flags boolean.

    The ``sanitize.nan`` fault poisons a float COPY for the checker only
    — the arrays the driver installs are never touched, so a
    fault-injected run still polishes byte-identically."""
    cons_base, _cons_cov, cons_len, failed = (np.asarray(x)
                                              for x in results)
    cons_len = cons_len.reshape(-1)
    failed = failed.reshape(-1)

    check_view = cons_base.astype(np.float32, copy=True)
    from ..resilience import faults
    try:
        faults.check("sanitize.nan", idxs)
    except faults.InjectedFault:
        if check_view.size:
            check_view.reshape(-1)[0] = np.nan
    if not np.isfinite(check_view).all():
        record("nonfinite", where,
               f"non-finite consensus values (chunk windows {idxs[:4]}…)")

    cap = cons_base.shape[1] if cons_base.ndim >= 2 else cons_base.size
    for bi in range(len(cons_len)):
        if int(failed[bi]) not in (0, 1):
            record("consensus-range", where,
                   f"failed flag {failed[bi]!r} not boolean (row {bi})")
        if int(failed[bi]):
            continue
        cl = int(cons_len[bi])
        if cl < 0 or cl > cap:
            record("consensus-range", where,
                   f"cons_len {cl} outside [0, {cap}] (row {bi})")
            continue
        row = cons_base[bi, :cl] if cons_base.ndim >= 2 else cons_base[:cl]
        if row.size and (int(row.min()) < 0 or int(row.max()) > 4):
            record("consensus-range", where,
                   f"base code outside 0..4 (row {bi}, "
                   f"min {int(row.min())}, max {int(row.max())})")


# --------------------------------------------------------------------------
# sampled host<->device parity
# --------------------------------------------------------------------------

def parity_stride() -> int:
    """Every Nth device-served window is host-recomputed and compared
    (0 = parity probe off)."""
    try:
        return max(0, config.get_int(PARITY_KNOB))
    except ValueError:
        return 0


def parity_due(n_installed: int) -> bool:
    s = parity_stride()
    return s > 0 and n_installed % s == 0


def check_parity(device_payload, host_payload, window: int,
                 where: str) -> None:
    """Byte-compare a device consensus against the host recompute of the
    same window (the caller recomputes BEFORE installing the device
    result, so the final pipeline state is untouched either way)."""
    d = (device_payload.encode() if isinstance(device_payload, str)
         else bytes(device_payload))
    h = (host_payload.encode() if isinstance(host_payload, str)
         else bytes(host_payload))
    if d != h:
        record("parity", where,
               f"window {window}: device consensus ({len(d)}b) != "
               f"host recompute ({len(h)}b)")


# --------------------------------------------------------------------------
# shared-state guard (driver stats dicts)
# --------------------------------------------------------------------------

class GuardedStats(dict):
    """Dict guard recording a ``racy-stats`` finding when any thread
    other than the creating (driver) thread mutates it.  The write still
    happens — the guard observes, it does not serialize."""

    def __init__(self, initial: dict, where: str):
        super().__init__(initial)
        self._owner = threading.get_ident()
        self._where = where

    def __setitem__(self, key, value):
        tid = threading.get_ident()
        if tid != self._owner:
            record("racy-stats", self._where,
                   f"key {key!r} written from thread {tid} "
                   f"(owner {self._owner})")
        super().__setitem__(key, value)


def guard_stats(stats: dict, where: str) -> dict:
    """Wrap a driver stats dict when the sanitizer is armed (passthrough
    otherwise).  The ``sanitize.stats`` fault performs one real
    cross-thread mutation through the guard — detector path exercised
    end-to-end, stats content left unchanged."""
    if not enabled():
        return stats
    g = GuardedStats(stats, where)
    from ..resilience import faults
    try:
        faults.check("sanitize.stats")
    except faults.InjectedFault:
        t = threading.Thread(target=g.__setitem__,
                             args=("_sanitize_stats_probe", 1),
                             name="sanitize-stats-probe", daemon=True)
        t.start()
        t.join()
        g.pop("_sanitize_stats_probe", None)
    return g
