"""Static jaxpr audit: trace the device kernels abstractly and enforce
TPU invariants that no unit test exercises.

Two properties are checked over the *whole* compile grid (every
(depth bucket, window class) the consensus driver can request, every
aligner bucket), using `jax.make_jaxpr` — abstract tracing only, no
device, no compilation:

* **forbidden primitives** — host callbacks (`pure_callback`,
  `io_callback`, ...), infeed/outfeed and explicit `device_put`
  transfers must never appear inside a kernel jaxpr: on TPU each one is
  a device->host round-trip that serializes the pipeline.  float64
  intermediates are likewise rejected (TPUs emulate f64 at ~1/10th
  throughput; the kernels are specified in i32/f32).

* **recompile budget** — the number of distinct jit input signatures
  across the audited grid must not exceed the budget declared next to
  the geometry (`POA_RECOMPILE_BUDGET`, `ALIGN_RECOMPILE_BUDGET`).
  Every signature is one XLA compile at serving time; a geometry change
  that silently splits signatures is the biggest TPU latency cliff this
  repo has hit (see docs/roadmap.md round-5 notes), so widening the
  grid must consciously raise the literal.

The audit traces through `jax.jit` wrappers (the pjit equation's inner
jaxpr is walked recursively), so it sees exactly what XLA would lower.
"""

from __future__ import annotations

import itertools
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from .lint import Violation

#: Primitive names that must never appear in a device kernel jaxpr.
#: Callbacks/infeed are host round-trips; device_put inside a jaxpr is
#: an implicit transfer the caller did not ask for.
FORBIDDEN_PRIMITIVES = {
    "pure_callback": "host callback",
    "io_callback": "host callback",
    "debug_callback": "host callback",
    "callback": "host callback",
    "infeed": "host infeed",
    "outfeed": "host outfeed",
    "device_put": "implicit transfer",
}

_POA_PATH = "racon_tpu/ops/poa.py"
_ALIGN_PATH = "racon_tpu/ops/align.py"


# --------------------------------------------------------------------------
# jaxpr walking (duck-typed: survives jax-internal module moves)
# --------------------------------------------------------------------------

def _as_jaxpr(obj):
    """Unwrap ClosedJaxpr-likes (have .jaxpr) to the raw Jaxpr-like
    (has .eqns); None when obj is neither."""
    if hasattr(obj, "eqns"):
        return obj
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def _sub_jaxprs(value) -> Iterable:
    """Jaxpr-likes reachable from one eqn.params value."""
    if isinstance(value, (list, tuple)):
        for v in value:
            yield from _sub_jaxprs(v)
        return
    j = _as_jaxpr(value)
    if j is not None:
        yield j


def iter_eqns(jaxpr, _seen: Optional[Set[int]] = None):
    """Every equation in `jaxpr` and (recursively) in any sub-jaxpr of
    its equations' params — scan/while/cond bodies, pjit inners, vmap'd
    closed jaxprs all included."""
    seen = _seen if _seen is not None else set()
    root = _as_jaxpr(jaxpr)
    if root is None or id(root) in seen:
        return
    seen.add(id(root))
    for eqn in root.eqns:
        yield eqn
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                yield from iter_eqns(sub, seen)


def _aval_dtypes(eqn) -> Iterable[str]:
    for var in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(var, "aval", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is not None:
            yield str(dtype)


def check_jaxpr(jaxpr, path: str, label: str) -> List[Violation]:
    """Forbidden-primitive + float64 scan of one traced kernel."""
    out: List[Violation] = []
    seen_prims: Set[str] = set()
    f64_hit = False
    for eqn in iter_eqns(jaxpr):
        name = getattr(eqn.primitive, "name", str(eqn.primitive))
        if name in FORBIDDEN_PRIMITIVES and name not in seen_prims:
            seen_prims.add(name)
            out.append(Violation(
                "jaxpr-forbidden-primitive", path, 0,
                f"{label}: primitive `{name}` "
                f"({FORBIDDEN_PRIMITIVES[name]}) in kernel jaxpr"))
        if not f64_hit and any("float64" in d for d in _aval_dtypes(eqn)):
            f64_hit = True
            out.append(Violation(
                "jaxpr-float64", path, 0,
                f"{label}: float64 intermediate in kernel jaxpr "
                f"(TPU-emulated; kernels are specified in i32/f32)"))
    return out


def _signature(avals) -> Tuple:
    """Hashable jit signature: the (shape, dtype) of every input aval —
    exactly what triggers an XLA recompile when it changes."""
    return tuple((tuple(a.shape), str(a.dtype)) for a in avals)


# --------------------------------------------------------------------------
# POA consensus kernel grid
# --------------------------------------------------------------------------

def audit_poa(window_lengths: Optional[Sequence[int]] = None,
              match: int = 3, mismatch: int = -5,
              gap: int = -4) -> List[Violation]:
    """Trace the XLA consensus kernel over the full bucket grid the
    driver can request and enforce POA_RECOMPILE_BUDGET."""
    import jax
    import numpy as np

    from ..ops import poa, poa_driver

    wls = tuple(window_lengths if window_lengths is not None
                else poa_driver.AUDIT_WINDOW_LENGTHS)
    classes = sorted({poa_driver.window_class(max(int(w), 1)) for w in wls})
    out: List[Violation] = []
    signatures: Set[Tuple] = set()
    for depth_bucket, wl_class in itertools.product(
            poa_driver.DEPTH_BUCKETS, classes):
        cfg = poa_driver.make_config(wl_class, depth_bucket,
                                     match, mismatch, gap)
        # Bypass the topology cache: the audit must not touch
        # jax.devices() (stays runnable with no backend configured) and
        # must not pollute the production cache with audit entries.
        kernel = poa.build_poa_kernel.__wrapped__(cfg)
        u8, i32 = np.uint8, np.int32
        args = [
            jax.ShapeDtypeStruct((1, cfg.max_backbone), u8),   # bb codes
            jax.ShapeDtypeStruct((1, cfg.max_backbone), i32),  # bb weights
            jax.ShapeDtypeStruct((1,), i32),                   # bb_len
            jax.ShapeDtypeStruct((1,), i32),                   # n_layers
            jax.ShapeDtypeStruct((1, cfg.depth, cfg.max_len), u8),
            jax.ShapeDtypeStruct((1, cfg.depth, cfg.max_len), i32),
            jax.ShapeDtypeStruct((1, cfg.depth), i32),         # lens
            jax.ShapeDtypeStruct((1, cfg.depth), i32),         # begins
            jax.ShapeDtypeStruct((1, cfg.depth), i32),         # ends
        ]
        label = f"poa d={depth_bucket} w={wl_class}"
        try:
            closed = jax.make_jaxpr(kernel)(*args)
        except Exception as e:  # noqa: BLE001 — audit reports, not raises
            out.append(Violation(
                "jaxpr-trace-error", _POA_PATH, 0,
                f"{label}: abstract trace failed: "
                f"{type(e).__name__}: {e}"))
            continue
        signatures.add(_signature(closed.in_avals))
        out.extend(check_jaxpr(closed, _POA_PATH, label))
    budget = poa_driver.POA_RECOMPILE_BUDGET
    if len(signatures) > budget:
        out.append(Violation(
            "recompile-budget", _POA_PATH, 0,
            f"POA grid compiles {len(signatures)} distinct jit "
            f"signatures over depths={tuple(poa_driver.DEPTH_BUCKETS)} "
            f"x windows={wls}, exceeding POA_RECOMPILE_BUDGET="
            f"{budget}; raise the declared budget only after sizing "
            f"the serving-latency cost"))
    return out


# --------------------------------------------------------------------------
# banded aligner bucket grid
# --------------------------------------------------------------------------

def audit_align(buckets: Optional[Sequence[Tuple[int, int]]] = None
                ) -> List[Violation]:
    """Trace the banded NW aligner over its (cap, band) buckets and
    enforce ALIGN_RECOMPILE_BUDGET."""
    import jax
    import numpy as np

    from ..ops import align

    grid = tuple(buckets if buckets is not None else align.BUCKETS)
    out: List[Violation] = []
    signatures: Set[Tuple] = set()
    for cap, band in grid:
        kernel = align.build_align_kernel.__wrapped__(cap, band)
        u8, i32 = np.uint8, np.int32
        args = [
            jax.ShapeDtypeStruct((1, cap), u8),   # query codes
            jax.ShapeDtypeStruct((1, cap), u8),   # target codes
            jax.ShapeDtypeStruct((1,), i32),      # query lengths
            jax.ShapeDtypeStruct((1,), i32),      # target lengths
        ]
        label = f"align cap={cap} band={band}"
        try:
            closed = jax.make_jaxpr(kernel)(*args)
        except Exception as e:  # noqa: BLE001 — audit reports, not raises
            out.append(Violation(
                "jaxpr-trace-error", _ALIGN_PATH, 0,
                f"{label}: abstract trace failed: "
                f"{type(e).__name__}: {e}"))
            continue
        signatures.add(_signature(closed.in_avals))
        out.extend(check_jaxpr(closed, _ALIGN_PATH, label))
    budget = align.ALIGN_RECOMPILE_BUDGET
    if len(signatures) > budget:
        out.append(Violation(
            "recompile-budget", _ALIGN_PATH, 0,
            f"aligner compiles {len(signatures)} distinct jit "
            f"signatures over buckets={grid}, exceeding "
            f"ALIGN_RECOMPILE_BUDGET={budget}; raise the declared "
            f"budget only after sizing the serving-latency cost"))
    return out


def run_audit() -> List[Violation]:
    """Full static jaxpr audit (POA grid + aligner buckets)."""
    return sorted(audit_poa() + audit_align(),
                  key=lambda v: (v.path, v.rule, v.message))
