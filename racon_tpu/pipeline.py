"""Python handle over the native polishing pipeline.

Wraps the C ABI in rt_capi.cpp. The pipeline object exposes the two
accelerator seams (overlap-alignment jobs and window-consensus jobs) as numpy
arrays ready for device batching; everything else (parsing, filtering,
windowing, stitching) runs natively.
"""

from __future__ import annotations

import ctypes
from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from . import native, obs
from .resilience import faults


@dataclass
class WindowExport:
    """One window's POA problem in packed form (layers sorted by begin)."""

    index: int
    rank: int
    target_id: int
    is_tgs: bool
    backbone: np.ndarray       # uint8 ASCII bases [L]
    backbone_weights: np.ndarray  # uint8 (PHRED-33, dummy backbone = 0) [L]
    lens: np.ndarray           # uint32 [K]
    begins: np.ndarray         # uint32 [K]
    ends: np.ndarray           # uint32 [K] (inclusive backbone positions)
    bases: np.ndarray          # uint8 concatenated layer bases
    weights: np.ndarray        # uint8 concatenated layer weights


class Pipeline:
    """One polishing run (sequences + overlaps + targets -> polished FASTA)."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 target_path: str, *, fragment_correction: bool = False,
                 window_length: int = 500, quality_threshold: float = 10.0,
                 error_threshold: float = 0.3, trim: bool = True,
                 match: int = 3, mismatch: int = -5, gap: int = -4,
                 num_threads: int = 1):
        self._lib = native.load()
        self._h = self._lib.rt_pipeline_create(
            sequences_path.encode(), overlaps_path.encode(),
            target_path.encode(), 1 if fragment_correction else 0,
            window_length, quality_threshold, error_threshold,
            1 if trim else 0, match, mismatch, gap, num_threads)
        if not self._h:
            native.check_error(self._lib)
            raise native.NativeError("pipeline creation failed")

    def __del__(self):
        if getattr(self, "_h", None):
            self._lib.rt_pipeline_destroy(self._h)
            self._h = None

    # -- phase 1 ----------------------------------------------------------
    # Coarse native calls carry `native.*` spans (racon_tpu/obs) so a
    # trace separates time inside the C++ engine from device batching;
    # per-window calls (export_window, consensus_cpu_one) are counted in
    # the drivers instead — a span per window would swamp the buffer.
    def prepare(self) -> None:
        with obs.span("native.prepare"):
            self._lib.rt_pipeline_prepare(self._h)
            native.check_error(self._lib)

    def num_align_jobs(self) -> int:
        return self._lib.rt_pipeline_num_align_jobs(self._h)

    def align_job(self, job: int) -> Tuple[np.ndarray, np.ndarray]:
        """Query/target byte arrays for alignment job `job`."""
        q = ctypes.c_char_p()
        t = ctypes.c_char_p()
        ql = ctypes.c_uint32()
        tl = ctypes.c_uint32()
        self._lib.rt_pipeline_align_job(
            self._h, job, ctypes.byref(q), ctypes.byref(ql), ctypes.byref(t),
            ctypes.byref(tl))
        qa = np.frombuffer(ctypes.string_at(q, ql.value), dtype=np.uint8)
        ta = np.frombuffer(ctypes.string_at(t, tl.value), dtype=np.uint8)
        return qa, ta

    def align_job_lengths(self) -> np.ndarray:
        """(q_len, t_len) per job without copying the bytes — one bulk
        ABI crossing (the per-job loop survives as `_align_job_lengths_loop`,
        the parity oracle)."""
        n = self.num_align_jobs()
        out = np.zeros((n, 2), dtype=np.uint32)
        if n:
            self._lib.rt_pipeline_align_job_lengths(
                self._h, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)))
            native.check_error(self._lib)
        return out

    def _align_job_lengths_loop(self) -> np.ndarray:
        """Per-job ctypes loop — the pre-bulk implementation, kept as the
        differential-test oracle for rt_pipeline_align_job_lengths."""
        n = self.num_align_jobs()
        out = np.zeros((n, 2), dtype=np.uint32)
        q = ctypes.c_char_p()
        t = ctypes.c_char_p()
        ql = ctypes.c_uint32()
        tl = ctypes.c_uint32()
        for i in range(n):
            self._lib.rt_pipeline_align_job(
                self._h, i, ctypes.byref(q), ctypes.byref(ql),
                ctypes.byref(t), ctypes.byref(tl))
            out[i, 0] = ql.value
            out[i, 1] = tl.value
        return out

    def set_job_cigar(self, job: int, cigar: str) -> None:
        self._lib.rt_pipeline_set_job_cigar(self._h, job, cigar.encode())

    def align_jobs_cpu(self) -> None:
        faults.check("native.call")
        with obs.span("native.align_jobs_cpu"):
            self._lib.rt_pipeline_align_jobs_cpu(self._h)
            native.check_error(self._lib)

    def build_windows(self) -> None:
        with obs.span("native.build_windows"):
            self._lib.rt_pipeline_build_windows(self._h)
            native.check_error(self._lib)

    def initialize(self) -> None:
        with obs.span("native.initialize"):
            self._lib.rt_pipeline_initialize(self._h)
            native.check_error(self._lib)

    # -- phase 2 ----------------------------------------------------------
    def num_windows(self) -> int:
        return self._lib.rt_pipeline_num_windows(self._h)

    def window_info(self, i: int) -> Tuple[int, int, int, bool, int, int]:
        out = (ctypes.c_uint64 * 6)()
        self._lib.rt_pipeline_window_info(self._h, i, out)
        return (int(out[0]), int(out[1]), int(out[2]), bool(out[3]),
                int(out[4]), int(out[5]))

    def export_window(self, i: int) -> WindowExport:
        faults.check("window.export", (i,))
        (n_seqs, bb_len, rank, is_tgs, layer_bytes,
         target_id) = self.window_info(i)
        k = n_seqs - 1
        bb = np.zeros(bb_len, dtype=np.uint8)
        bbw = np.zeros(bb_len, dtype=np.uint8)
        lens = np.zeros(k, dtype=np.uint32)
        begins = np.zeros(k, dtype=np.uint32)
        ends = np.zeros(k, dtype=np.uint32)
        bases = np.zeros(layer_bytes, dtype=np.uint8)
        weights = np.zeros(layer_bytes, dtype=np.uint8)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        self._lib.rt_pipeline_window_export(
            self._h, i,
            bb.ctypes.data_as(u8p), bbw.ctypes.data_as(u8p),
            lens.ctypes.data_as(u32p), begins.ctypes.data_as(u32p),
            ends.ctypes.data_as(u32p), bases.ctypes.data_as(u8p),
            weights.ctypes.data_as(u8p))
        return WindowExport(index=i, rank=rank, target_id=target_id,
                            is_tgs=is_tgs, backbone=bb, backbone_weights=bbw,
                            lens=lens, begins=begins, ends=ends, bases=bases,
                            weights=weights)

    def consensus_cpu_one(self, i: int) -> bool:
        faults.check("native.call", (i,))
        r = self._lib.rt_pipeline_consensus_cpu_one(self._h, i)
        if r < 0:
            native.check_error(self._lib)
            raise native.NativeError(f"consensus failed for window {i}")
        return bool(r)

    def consensus_cpu_all(self) -> None:
        faults.check("native.call")
        with obs.span("native.consensus_cpu_all"):
            self._lib.rt_pipeline_consensus_cpu_all(self._h)
            native.check_error(self._lib)

    def get_consensus(self, i: int) -> bytes:
        """Window i's stored consensus (host- or device-produced)."""
        ln = ctypes.c_uint64()
        p = self._lib.rt_pipeline_get_consensus(self._h, i, ctypes.byref(ln))
        return ctypes.string_at(p, ln.value)

    def set_consensus(self, i: int, consensus: bytes, polished: bool) -> None:
        self._lib.rt_pipeline_set_consensus(
            self._h, i, consensus, len(consensus), 1 if polished else 0)

    def stitch(self, drop_unpolished: bool = True) -> List[Tuple[str, str]]:
        with obs.span("native.stitch"):
            n = self._lib.rt_pipeline_stitch(
                self._h, 1 if drop_unpolished else 0)
            native.check_error(self._lib)
        out = []
        ln = ctypes.c_uint64()
        for i in range(n):
            p = self._lib.rt_pipeline_result_name(self._h, i, ctypes.byref(ln))
            name = ctypes.string_at(p, ln.value).decode()
            p = self._lib.rt_pipeline_result_data(self._h, i, ctypes.byref(ln))
            data = ctypes.string_at(p, ln.value).decode()
            out.append((name, data))
        return out
