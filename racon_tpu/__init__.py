"""racon-tpu: a TPU-native long-read consensus / assembly-polishing framework.

Feature-parity re-design of lbcb-sci/racon (v1.5.0): reads + overlaps
(MHAP/PAF/SAM) + draft targets in, polished contigs (or error-corrected
fragments) out. The host runtime (parsing, data model, filtering, windowing,
POA oracle, stitching) is native C++ (racon_tpu/native); the accelerated path
runs batched banded alignment and batched partial-order alignment as JAX/
Pallas kernels sharded over TPU meshes (racon_tpu/ops, racon_tpu/parallel).
"""

__version__ = "0.1.0"

import os as _os
import sys as _sys

# Persistent XLA/Mosaic compilation cache: the fused POA kernel takes tens
# of seconds to compile per geometry, and the axon TPU tunnel wedges for
# hours at a time — a cache that survives process restarts (and tunnel
# flaps) means each geometry is compiled once per machine, not once per
# run. Harmless on CPU. If jax was imported before us its config already
# captured the env, so set it through the config API instead.
if "JAX_COMPILATION_CACHE_DIR" not in _os.environ:  # "" = explicit opt-out
    # uid-suffixed: a world-shared fixed path breaks for the second user on
    # a machine (PermissionError -> jax silently skips the cache). Created
    # 0700 and ownership-checked so a pre-created dir by another user can
    # neither disable nor poison the cache.
    _cache = f"/tmp/racon_tpu_jax_cache_{_os.getuid()}"
    try:
        _os.makedirs(_cache, mode=0o700, exist_ok=True)
        _ok = _os.stat(_cache).st_uid == _os.getuid()
    except OSError:
        _ok = False
    if _ok:
        _os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache
        _os.environ.setdefault(
            "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
        if "jax" in _sys.modules:
            try:
                _sys.modules["jax"].config.update(
                    "jax_compilation_cache_dir", _cache)
                _sys.modules["jax"].config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1)
            except Exception:  # noqa: BLE001
                # a renamed/absent config knob on some jax version must
                # degrade to "no persistent cache", not break import
                pass

from .polisher import CpuPolisher, TpuPolisher, create_polisher  # noqa: F401
from .pipeline import Pipeline  # noqa: F401
