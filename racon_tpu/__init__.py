"""racon-tpu: a TPU-native long-read consensus / assembly-polishing framework.

Feature-parity re-design of lbcb-sci/racon (v1.5.0): reads + overlaps
(MHAP/PAF/SAM) + draft targets in, polished contigs (or error-corrected
fragments) out. The host runtime (parsing, data model, filtering, windowing,
POA oracle, stitching) is native C++ (racon_tpu/native); the accelerated path
runs batched banded alignment and batched partial-order alignment as JAX/
Pallas kernels sharded over TPU meshes (racon_tpu/ops, racon_tpu/parallel).
"""

__version__ = "0.1.0"

from .polisher import CpuPolisher, TpuPolisher, create_polisher  # noqa: F401
from .pipeline import Pipeline  # noqa: F401
