"""One registry for every cache/journal fingerprint composition.

Three seams in the tree key cached or resumable artifacts on an
identity fingerprint:

* the **journal** header (`resilience/journal.py`) — one polishing
  problem's identity, deciding whether a crash-resume may replay a
  previous run's records;
* the **kernel cache** (`ops/kernel_cache.device_keyed_cache`) — the
  implicit device-topology prefix every memoized kernel build is keyed
  under;
* the **serve job dir** (`serve/session.py` / `serve/scheduler.py`) —
  the per-job artifact namespace whose backend-keyed journal turns a
  re-submitted job into a resume.

They used to compose their keys ad hoc, one per module.  This module is
now the single authority: the helpers below build the actual keys, and
the ``SITES`` / ``OUTPUT_SOURCES`` literals describe *what the keys
cover* so the determinism taint auditor (``racon_tpu/analysis/
determinism``, Engine 5) can statically cross-check every composition
against the knob registry:

* an output-affecting input or knob missing from a ``complete`` site is
  a ``fingerprint-gap`` (a cache could serve stale bytes);
* a component covering only cost-only knobs is a
  ``fingerprint-overkey`` (spurious cache misses).

The ``--emit-manifest`` output of Engine 5 is derived from these
literals; ROADMAP open item 5 (the content-addressed window cache) is
expected to consume that manifest as its fingerprint schema instead of
inventing a fourth ad-hoc composition.

Only the stdlib is imported (config.py-style) so this module is
importable from anywhere, including before jax initializes.
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, Optional, Sequence, Tuple

#: Journal header schema version (the journal refuses to replay records
#: written under a different version).
JOURNAL_VERSION = 1

#: Polish parameters excluded from the journal fingerprint because they
#: provably cannot change output bytes (thread count only schedules
#: work).  Everything else passed to the polisher is hashed.
EXCLUDED_PARAMS = ("num_threads",)

#: Output-affecting sources every *complete* fingerprint composition
#: must cover.  ``input:*`` tokens are the polisher's problem inputs;
#: Engine 5 adds a ``knob:<NAME>`` token for every runtime knob whose
#: registry entry declares ``affects_output=True`` (racon_tpu/config.py)
#: and fails the build if a complete site misses one.
OUTPUT_SOURCES = (
    "input:sequences",
    "input:overlaps",
    "input:target",
    "input:params",
    "input:backend",
)

#: The fingerprint-site registry.  PURE LITERAL — Engine 5 parses this
#: dict out of the AST, so no computed values, spreads, or helpers.
#:
#: Per site: ``helper`` names the function below that builds the real
#: key; ``complete: True`` means the key must cover every output-
#: affecting source (journal-style identity keys); ``complete: False``
#: means the keyed artifact is a pure function of its explicit
#: arguments (kernel builds) and only the listed extras matter.
#: ``components`` maps each key component to the source tokens it
#: covers; ``site:<name>`` nests another site's coverage (the serve job
#: dir contains a journal, so it inherits the journal's coverage).
SITES = {
    "journal": {
        "helper": "journal_fingerprint",
        "description": "resilience/journal.py header: may a resume "
                       "replay this journal's records?",
        "complete": True,
        "components": {
            "schema": ("const:journal-version",),
            "backend": ("input:backend",),
            "params": ("input:params",),
            "input_bytes": ("input:sequences", "input:overlaps",
                            "input:target"),
        },
    },
    "kernel_cache": {
        "helper": "kernel_cache_key",
        "description": "ops/kernel_cache.device_keyed_cache implicit "
                       "prefix: a built kernel is a pure function of "
                       "its builder args plus the device topology",
        "complete": False,
        "components": {
            "n_devices": ("topology:n_devices",),
            "platform": ("topology:platform",),
            "builder_args": ("args:builder",),
        },
    },
    "serve_job_dir": {
        "helper": "serve_job_paths",
        "description": "serve/session.py per-job artifact namespace: "
                       "job id + backend key the journal a re-run "
                       "resumes",
        "complete": True,
        "components": {
            "job_id": ("input:job_id",),
            "backend": ("input:backend",),
            "journal": ("site:journal",),
        },
    },
}


# --------------------------------------------------------------------------
# the actual key builders (the helpers the SITES entries name)
# --------------------------------------------------------------------------

def journal_fingerprint(paths: Sequence[str], params: dict,
                        backend: str) -> str:
    """Identity of one polishing problem: input bytes + parameters +
    backend.  Streamed, so fingerprinting costs one read of the inputs
    (they are about to be parsed anyway).

    The serving environment (kernel tiers, batch size, pipeline depth,
    ...) is deliberately excluded — a resume may legally mix journaled
    device windows with recomputed ones, exactly like an uninterrupted
    run mixes tiers when the lattice degrades.  Engine 5 is the proof
    that the exclusion is sound: any knob with a dataflow path into
    output bytes is a ``determinism-leak`` finding.
    """
    h = hashlib.sha256()
    h.update(f"racon-tpu-journal-v{JOURNAL_VERSION}".encode())
    h.update(f"\0backend={backend}".encode())
    for k in sorted(params):
        if k in EXCLUDED_PARAMS:
            continue
        h.update(f"\0{k}={params[k]!r}".encode())
    for p in paths:
        h.update(b"\0file\0")
        with open(p, "rb") as f:
            for blk in iter(lambda: f.read(1 << 20), b""):
                h.update(blk)
    return h.hexdigest()


def kernel_cache_key(n_dev: int, platform: str) -> Tuple[int, str]:
    """The implicit key prefix ``device_keyed_cache`` prepends to every
    memoized kernel build (the builder's own args are the rest of the
    key — a built kernel is a pure function of both)."""
    return (int(n_dev), str(platform))


def serve_job_paths(workdir: str, job_id: str,
                    backend: Optional[str] = None) -> Dict[str, str]:
    """Every path the serve layer derives from a job id: the job
    directory plus (when ``backend`` is given) the artifact paths
    inside it.  The journal filename is backend-keyed so a job demoted
    from the device lane to the host lane never replays device-tier
    records into a cpu run."""
    jd = os.path.join(workdir, "jobs", job_id)
    out = {"dir": jd}
    if backend is not None:
        out.update(
            journal=os.path.join(jd, f"journal.{backend}.jsonl"),
            output=os.path.join(jd, "polished.fasta"),
            trace=os.path.join(jd, "trace.json"),
            report=os.path.join(jd, "report.json"),
        )
    return out
