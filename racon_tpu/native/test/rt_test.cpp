// Native unit tests for the host runtime (the C++ twin of the pytest
// layer — the reference keeps its unit tests native in test/racon_test.cpp;
// the end-to-end goldens live in tests/test_golden.py which exercises this
// same code through the C ABI).
//
// Plain CHECK macros instead of a vendored gtest: the framework must build
// with zero network access, and the assertions here are simple equality
// checks. Build + run:  make -C racon_tpu/native test
#include <zlib.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <memory>
#include <set>
#include <string>
#include <sys/stat.h>
#include <unistd.h>
#include <unordered_map>
#include <vector>

#include "../src/rt_align.hpp"
#include "../src/rt_error.hpp"
#include "../src/rt_overlap.hpp"
#include "../src/rt_parsers.hpp"
#include "../src/rt_pipeline.hpp"
#include "../src/rt_poa.hpp"
#include "../src/rt_sampler.hpp"
#include "../src/rt_sequence.hpp"
#include "../src/rt_threadpool.hpp"
#include "../src/rt_window.hpp"

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                       \
  do {                                                                    \
    ++g_checks;                                                           \
    if (!(cond)) {                                                        \
      ++g_failures;                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                     \
  } while (0)

#define CHECK_EQ(a, b)                                                    \
  do {                                                                    \
    ++g_checks;                                                           \
    auto va = (a);                                                        \
    auto vb = (b);                                                        \
    if (!(va == vb)) {                                                    \
      ++g_failures;                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s != %s\n", __FILE__, __LINE__,  \
                   #a, #b);                                               \
    }                                                                     \
  } while (0)

// ---- Sequence -------------------------------------------------------------

static void test_sequence() {
  // uppercasing (reference: src/sequence.cpp:24-27)
  rt::Sequence s("r", 1, "acgtn", 5);
  CHECK_EQ(s.data, std::string("ACGTN"));

  // informative quality is kept
  rt::Sequence q("r", 1, "ACGT", 4, "!!5!", 4);
  CHECK_EQ(q.quality, std::string("!!5!"));

  // all-'!' quality carries no information and is dropped
  // (reference: src/sequence.cpp:34-42)
  rt::Sequence z("r", 1, "ACGT", 4, "!!!!", 4);
  CHECK(z.quality.empty());

  // reverse complement + reversed quality, idempotent
  // (reference: src/sequence.cpp:49-84)
  q.create_reverse_complement();
  CHECK_EQ(q.reverse_complement, std::string("ACGT"));
  rt::Sequence r("r", 1, "AACG", 4, "!05!", 4);
  r.create_reverse_complement();
  CHECK_EQ(r.reverse_complement, std::string("CGTT"));
  CHECK_EQ(r.reverse_quality, std::string("!50!"));
  r.create_reverse_complement();
  CHECK_EQ(r.reverse_complement, std::string("CGTT"));
}

// ---- alignment kernels -----------------------------------------------------

static void test_align() {
  // pinned small distances
  CHECK_EQ(rt::edit_distance("kitten", 6, "sitting", 7), 3);
  CHECK_EQ(rt::edit_distance("", 0, "abc", 3), 3);
  CHECK_EQ(rt::edit_distance("ACGT", 4, "ACGT", 4), 0);
  // symmetry
  CHECK_EQ(rt::edit_distance("ACGTACGT", 8, "AGTACGGT", 8),
           rt::edit_distance("AGTACGGT", 8, "ACGTACGT", 8));

  // the CIGAR's edit count must equal the exact distance, and its spans
  // must cover both sequences
  const std::string qs = "ACGTTTACGGTACGT";
  const std::string ts = "ACGTACGGTACGTTT";
  std::string cig = rt::align_global_cigar(qs.data(), qs.size(), ts.data(),
                                           ts.size());
  int64_t q_span = 0, t_span = 0, edits = 0;
  uint32_t run = 0;
  for (char c : cig) {
    if (c >= '0' && c <= '9') {
      run = run * 10 + (c - '0');
      continue;
    }
    if (c == 'M' || c == '=') {
      q_span += run;
      t_span += run;
    } else if (c == 'X') {
      q_span += run;
      t_span += run;
      edits += run;
    } else if (c == 'I') {
      q_span += run;
      edits += run;
    } else if (c == 'D') {
      t_span += run;
      edits += run;
    }
    run = 0;
  }
  CHECK_EQ(q_span, (int64_t)qs.size());
  CHECK_EQ(t_span, (int64_t)ts.size());
  CHECK_EQ(edits, rt::edit_distance(qs.data(), qs.size(), ts.data(),
                                    ts.size()));
}

// ---- Overlap ---------------------------------------------------------------

static void test_overlap() {
  // PAF ctor + span-ratio error metric (reference: src/overlap.cpp:24-42)
  auto paf = rt::Overlap::from_paf("q", 100, 0, 80, '+', "t", 200, 10, 110);
  CHECK_EQ(paf->length, 100u);
  CHECK(paf->error > 0.19 && paf->error < 0.21);  // 1 - 80/100
  CHECK(!paf->strand);

  // MHAP ctor: 1-based ordinals, rc flags (reference: src/overlap.cpp:15-27)
  auto mhap = rt::Overlap::from_mhap(1, 2, 0.1, 10, 0, 0, 80, 100, 1, 10,
                                     110, 200);
  CHECK(mhap->strand);

  // SAM ctor scans the CIGAR for spans (reference: src/overlap.cpp:44-108)
  auto sam = rt::Overlap::from_sam("q", 0, "t", 11, "20M5I20M5D20M");
  CHECK_EQ(sam->q_begin, 0u);
  CHECK_EQ(sam->q_end, 65u);        // 20+5+20+20 query bases
  CHECK_EQ(sam->t_begin, 10u);      // pos is 1-based
  CHECK_EQ(sam->t_end, 10u + 65u);  // 20+20+5+20 target bases

  // transmute resolves names and validates lengths
  // (reference: src/overlap.cpp:129-177)
  std::vector<std::unique_ptr<rt::Sequence>> seqs;
  seqs.push_back(rt::createSequence("q", std::string(100, 'A')));
  seqs.push_back(rt::createSequence("t", std::string(200, 'C')));
  // keys carry a q/t suffix, the reference's disambiguation scheme for a
  // name that is both a read and a target (src/polisher.cpp:210-215)
  std::unordered_map<std::string, uint64_t> name_to_id{{"qq", 0}, {"tt", 1}};
  std::unordered_map<uint64_t, uint64_t> id_to_id;
  paf->transmute(seqs, name_to_id, id_to_id);
  CHECK(paf->is_transmuted);
  CHECK_EQ(paf->q_id, 0u);
  CHECK_EQ(paf->t_id, 1u);

  // breaking points from a pure-match CIGAR land on window boundaries
  // (reference: src/overlap.cpp:226-292)
  auto bp = rt::Overlap::from_sam("q", 0, "t", 1, "100M");
  bp->transmute(seqs, name_to_id, id_to_id);
  bp->find_breaking_points(seqs, 50);
  CHECK_EQ(bp->breaking_points.size(), 4u);  // two windows x (first, last)
  CHECK_EQ(bp->breaking_points[0].first, 0u);
  CHECK_EQ(bp->breaking_points[1].first, 50u);
  CHECK_EQ(bp->breaking_points[2].first, 50u);
  CHECK_EQ(bp->breaking_points[3].first, 100u);
}

// ---- POA graph -------------------------------------------------------------

static void test_poa() {
  // three identical layers over a backbone with one error: the consensus
  // recovers the majority base, coverage counts the paths through the
  // chosen nodes
  const std::string backbone = "ACGTACGT";
  const std::string truth = "ACGAACGT";  // backbone has T where truth has A
  rt::PoaGraph g;
  std::vector<uint32_t> w1(backbone.size(), 1);
  g.add_alignment({}, backbone.data(), backbone.size(), w1);
  rt::PoaAligner aligner(5, -4, -8);
  const double inf = 1e300;
  for (int i = 0; i < 3; ++i) {
    auto aln = aligner.align(truth.data(), truth.size(), g, -inf, inf);
    std::vector<uint32_t> w(truth.size(), 1);
    g.add_alignment(aln, truth.data(), truth.size(), w);
  }
  std::vector<uint32_t> cov;
  std::string cons = g.generate_consensus(&cov);
  CHECK_EQ(cons, truth);
  CHECK_EQ(cov.size(), cons.size());
  CHECK_EQ(cov[3], 3u);  // the corrected base: 3 supporting layers
  CHECK_EQ(cov[0], 4u);  // agreeing base: backbone + 3 layers
}

// ---- temp-file helpers -----------------------------------------------------

static std::string g_tmpdir;

static std::string write_file(const std::string& name,
                              const std::string& content) {
  const std::string path = g_tmpdir + "/" + name;
  std::ofstream f(path, std::ios::binary);
  f << content;
  return path;
}

static std::string write_gz(const std::string& name,
                            const std::string& content) {
  const std::string path = g_tmpdir + "/" + name;
  gzFile f = gzopen(path.c_str(), "wb");
  gzwrite(f, content.data(), static_cast<unsigned>(content.size()));
  gzclose(f);
  return path;
}

// ---- parsers ---------------------------------------------------------------
// Format coverage parity with the reference's vendored bioparser formats
// (reference factory: src/polisher.cpp:85-135).

static void test_parsers() {
  // extension sniffing: the reference's accepted extension sets
  rt::SeqFormat sf;
  rt::OvlFormat of;
  CHECK(rt::sniff_sequence_format("x.fasta", &sf) && sf == rt::SeqFormat::kFasta);
  CHECK(rt::sniff_sequence_format("x.fq.gz", &sf) && sf == rt::SeqFormat::kFastq);
  CHECK(!rt::sniff_sequence_format("x.txt", &sf));
  CHECK(rt::sniff_overlap_format("x.paf.gz", &of) && of == rt::OvlFormat::kPaf);
  CHECK(rt::sniff_overlap_format("x.mhap", &of) && of == rt::OvlFormat::kMhap);
  CHECK(rt::sniff_overlap_format("x.sam", &of) && of == rt::OvlFormat::kSam);
  CHECK(!rt::sniff_overlap_format("x.bam", &of));

  // multi-line FASTA, name ends at first whitespace
  const std::string fasta = ">r1 comment here\nACGT\nACGT\n>r2\nTTTT\n";
  rt::SequenceParser fp(write_file("t.fasta", fasta), rt::SeqFormat::kFasta);
  auto seqs = fp.parse(0);
  CHECK_EQ(seqs.size(), 2u);
  CHECK_EQ(seqs[0]->name, std::string("r1"));
  CHECK_EQ(seqs[0]->data, std::string("ACGTACGT"));
  CHECK_EQ(seqs[1]->data, std::string("TTTT"));

  // chunked parse: max_bytes=1 pulls one record per call; reset rewinds
  fp.reset();
  auto first = fp.parse(1);
  CHECK_EQ(first.size(), 1u);
  auto second = fp.parse(1);
  CHECK_EQ(second.size(), 1u);
  CHECK_EQ(second[0]->name, std::string("r2"));
  CHECK_EQ(fp.parse(1).size(), 0u);

  // FASTQ with informative quality
  const std::string fastq = "@q1\nACGT\n+\n!5!5\n";
  rt::SequenceParser qp(write_file("t.fastq", fastq), rt::SeqFormat::kFastq);
  auto qseqs = qp.parse(0);
  CHECK_EQ(qseqs.size(), 1u);
  CHECK_EQ(qseqs[0]->quality, std::string("!5!5"));

  // transparent gzip through the same parser (reference: bioparser + zlib)
  rt::SequenceParser gz(write_gz("t2.fasta.gz", fasta), rt::SeqFormat::kFasta);
  CHECK_EQ(gz.parse(0).size(), 2u);

  // PAF / SAM (headers skipped) / MHAP overlap records
  rt::OverlapParser pp(
      write_file("t.paf", "q\t100\t0\t80\t+\tt\t200\t10\t110\t70\t100\t60\n"),
      rt::OvlFormat::kPaf);
  auto povl = pp.parse(0);
  CHECK_EQ(povl.size(), 1u);
  CHECK_EQ(povl[0]->t_begin, 10u);

  rt::OverlapParser sp(
      write_file("t.sam",
                 "@HD\tVN:1.6\n@SQ\tSN:t\tLN:200\n"
                 "q\t0\tt\t11\t60\t20M5I20M5D20M\t*\t0\t0\t*\t*\n"),
      rt::OvlFormat::kSam);
  auto sovl = sp.parse(0);
  CHECK_EQ(sovl.size(), 1u);
  CHECK_EQ(sovl[0]->q_end, 65u);

  rt::OverlapParser mp(
      write_file("t.mhap", "1 2 0.1 10 0 0 80 100 1 10 110 200\n"),
      rt::OvlFormat::kMhap);
  auto movl = mp.parse(0);
  CHECK_EQ(movl.size(), 1u);
  CHECK(movl[0]->strand);

  // library error channel, not exit(): missing file and malformed records
  // throw rt::Error (the CLI catches at main, rt_main.cpp)
  bool threw = false;
  try {
    rt::GzReader bad(g_tmpdir + "/does_not_exist.fasta");
  } catch (const rt::Error& e) {
    threw = std::string(e.what()).find("unable to open") != std::string::npos;
  }
  CHECK(threw);

  threw = false;
  try {
    rt::SequenceParser mq(write_file("bad.fastq", "@q\nACGT\n+\n!!\n"),
                          rt::SeqFormat::kFastq);
    mq.parse(0);
  } catch (const rt::Error&) {
    threw = true;
  }
  CHECK(threw);
}

// ---- window semantics ------------------------------------------------------
// Reference: src/window.cpp — backbone passthrough (:68-71), layer position
// validation, TGS low-coverage end trim + chimera guard (:125-146).

static void test_window() {
  const std::string bb = "ACGTACGTACGTACGTACGT";  // 20 bp
  const std::string qual(bb.size(), '5');

  // <3 sequences: backbone passthrough, POA did not run
  auto w = rt::createWindow(7, 0, rt::WindowType::kTGS, bb.data(),
                            bb.size(), qual.data(), qual.size());
  rt::PoaAligner aligner(5, -4, -8);
  CHECK(!w->generate_consensus(aligner, true));
  CHECK_EQ(w->consensus, bb);

  // invalid layer positions throw through the library error channel
  bool threw = false;
  try {
    w->add_layer(bb.data(), 4, nullptr, 0, 10, 30);  // end > backbone
  } catch (const rt::Error&) {
    threw = true;
  }
  CHECK(threw);

  // zero-length / empty-span layers are silently ignored
  w->add_layer(bb.data(), 0, nullptr, 0, 0, 10);
  w->add_layer(bb.data(), 4, nullptr, 0, 5, 5);
  CHECK_EQ(w->sequences.size(), 1u);

  // TGS trim: 4 perfect layers covering only [5, 15) -> consensus trimmed
  // to the covered span (ends have backbone-only coverage 1 < avg 2)
  auto t = rt::createWindow(7, 1, rt::WindowType::kTGS, bb.data(),
                            bb.size(), qual.data(), qual.size());
  const std::string mid = bb.substr(5, 10);
  for (int i = 0; i < 4; ++i) {
    t->add_layer(mid.data(), mid.size(), nullptr, 0, 5, 14);
  }
  CHECK(t->generate_consensus(aligner, true));
  CHECK_EQ(t->consensus, mid);

  // same window untrimmed (NGS type or trim=false keeps full span)
  auto n = rt::createWindow(7, 2, rt::WindowType::kNGS, bb.data(),
                            bb.size(), qual.data(), qual.size());
  for (int i = 0; i < 4; ++i) {
    n->add_layer(mid.data(), mid.size(), nullptr, 0, 5, 14);
  }
  CHECK(n->generate_consensus(aligner, true));
  CHECK_EQ(n->consensus, bb);
}

// ---- thread pool -----------------------------------------------------------

static void test_threadpool() {
  rt::ThreadPool pool(4);
  CHECK_EQ(pool.num_threads(), 4u);
  // the calling (non-worker) thread gets the dedicated slot n
  CHECK_EQ(pool.this_thread_index(), 4u);

  std::atomic<uint32_t> sum{0};
  std::set<uint32_t> seen;
  std::mutex m;
  std::vector<std::future<void>> futs;
  for (int i = 0; i < 64; ++i) {
    futs.push_back(pool.submit([&] {
      sum.fetch_add(1);
      std::lock_guard<std::mutex> lock(m);
      seen.insert(pool.this_thread_index());
    }));
  }
  for (auto& f : futs) {
    f.get();
  }
  CHECK_EQ(sum.load(), 64u);
  // every observed worker index is a real worker slot (one aggregate
  // CHECK: how many distinct workers ran is scheduling-dependent, and a
  // per-element loop would make the total check count vary by build)
  uint32_t max_idx = 0;
  for (uint32_t idx : seen) {
    max_idx = idx > max_idx ? idx : max_idx;
  }
  CHECK(!seen.empty() && max_idx < 4u);
}

// ---- sampler (rampler parity) ----------------------------------------------

static void test_sampler() {
  std::string fasta;
  for (int i = 0; i < 4; ++i) {
    fasta += ">s" + std::to_string(i) + "\n" + std::string(100, 'A') + "\n";
  }
  const std::string path = write_file("sample.fasta", fasta);

  // split: record-granular ~200-byte chunks -> 2 files, all records kept
  auto chunks = rt::sampler_split(path, 200, g_tmpdir);
  CHECK_EQ(chunks.size(), 2u);
  size_t records = 0;
  for (const auto& c : chunks) {
    rt::SequenceParser p(c, rt::SeqFormat::kFasta);
    records += p.parse(0).size();
  }
  CHECK_EQ(records, 4u);

  // subsample to ref_length*coverage = 200 bases -> 2 whole reads
  const std::string sub = rt::sampler_subsample(path, 100, 2, g_tmpdir);
  rt::SequenceParser p(sub, rt::SeqFormat::kFasta);
  auto kept = p.parse(0);
  uint64_t bases = 0;
  for (const auto& s : kept) {
    bases += s->data.size();
  }
  CHECK_EQ(bases, 200u);
}

// ---- parser fuzz -----------------------------------------------------------
// Seeded random byte soup through every parser: malformed input must
// surface as rt::Error (or parse to something), never as a crash or
// sanitizer report — this block rides the ASan and TSan CI builds.

static void test_parser_fuzz() {
  uint64_t x = 0x2545F4914F6CDD1Dull;
  auto next = [&x]() {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    return x;
  };
  const char alphabet[] = ">@+ACGTacgt0123\t -\n\r!I~";
  for (int round = 0; round < 40; ++round) {
    std::string blob;
    const size_t len = next() % 2048;
    for (size_t i = 0; i < len; ++i) {
      // bias toward structural characters, sprinkle raw bytes
      blob += (next() % 8) ? alphabet[next() % (sizeof(alphabet) - 1)]
                           : static_cast<char>(next() & 0xFF);
    }
    const std::string p = write_file("fuzz.bin", blob);
    for (rt::SeqFormat f : {rt::SeqFormat::kFasta, rt::SeqFormat::kFastq}) {
      try {
        rt::SequenceParser sp(p, f);
        auto out = sp.parse(0);
        ++g_checks;  // parsed (possibly to zero records) without crashing
      } catch (const rt::Error&) {
        ++g_checks;  // clean library error is an acceptable outcome
      }
    }
    for (rt::OvlFormat f :
         {rt::OvlFormat::kMhap, rt::OvlFormat::kPaf, rt::OvlFormat::kSam}) {
      try {
        rt::OverlapParser op(p, f);
        auto out = op.parse(0);
        ++g_checks;
      } catch (const rt::Error&) {
        ++g_checks;
      }
    }
  }
}

// ---- pipeline end-to-end (pure native, no Python) --------------------------
// A miniature of the λ golden flow (reference: test/racon_test.cpp): perfect
// reads over a known truth must polish the draft back to the truth.

static void test_pipeline() {
  // deterministic pseudo-random truth
  std::string truth;
  uint64_t x = 0x9E3779B97F4A7C15ull;
  for (int i = 0; i < 600; ++i) {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    truth += "ACGT"[x & 3];
  }
  // draft: truth with a substitution every 100 bases
  std::string draft = truth;
  for (size_t i = 50; i < draft.size(); i += 100) {
    draft[i] = draft[i] == 'A' ? 'C' : 'A';
  }

  std::string reads, sam = "@HD\tVN:1.6\n@SQ\tSN:tgt\tLN:600\n";
  for (int i = 0; i < 5; ++i) {
    reads += ">r" + std::to_string(i) + "\n" + truth + "\n";
    sam += "r" + std::to_string(i) + "\t0\ttgt\t1\t60\t600M\t*\t0\t0\t" +
           truth + "\t*\n";
  }
  const std::string reads_p = write_file("e2e_reads.fasta", reads);
  const std::string sam_p = write_file("e2e_ovl.sam", sam);
  const std::string tgt_p = write_file("e2e_tgt.fasta", ">tgt\n" + draft + "\n");

  rt::PipelineParams params;
  params.window_length = 200;
  params.match = 5;
  params.mismatch = -4;
  params.gap = -8;
  params.num_threads = 4;  // pooled paths under the sanitizer builds
  rt::Pipeline pipe(reads_p, sam_p, tgt_p, params);
  pipe.initialize();
  CHECK_EQ(pipe.num_windows(), 3u);
  pipe.consensus_cpu_all();
  std::vector<std::pair<std::string, std::string>> out;
  pipe.stitch(true, &out);
  CHECK_EQ(out.size(), 1u);
  CHECK_EQ(out[0].second, truth);
  // provenance tags (reference: src/polisher.cpp:521-524)
  CHECK(out[0].first.find("LN:i:600") != std::string::npos);
  CHECK(out[0].first.find("RC:i:5") != std::string::npos);

  // bad extension: reference-compatible library error, not an exit
  bool threw = false;
  try {
    rt::Pipeline bad(g_tmpdir + "/x.txt", sam_p, tgt_p, params);
  } catch (const rt::Error&) {
    threw = true;
  }
  CHECK(threw);
}

int main() {
  g_tmpdir = "/tmp/rt_test_" + std::to_string(::getpid());
  ::mkdir(g_tmpdir.c_str(), 0755);
  test_sequence();
  test_align();
  test_overlap();
  test_poa();
  test_parsers();
  test_window();
  test_threadpool();
  test_sampler();
  test_parser_fuzz();
  test_pipeline();
  if (g_failures) {
    // keep g_tmpdir for post-mortem
    std::fprintf(stderr, "%d/%d checks FAILED (artifacts in %s)\n",
                 g_failures, g_checks, g_tmpdir.c_str());
    return 1;
  }
  std::system(("rm -rf '" + g_tmpdir + "'").c_str());
  std::printf("all %d checks passed\n", g_checks);
  return 0;
}
