// Native unit tests for the host runtime (the C++ twin of the pytest
// layer — the reference keeps its unit tests native in test/racon_test.cpp;
// the end-to-end goldens live in tests/test_golden.py which exercises this
// same code through the C ABI).
//
// Plain CHECK macros instead of a vendored gtest: the framework must build
// with zero network access, and the assertions here are simple equality
// checks. Build + run:  make -C racon_tpu/native test
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "../src/rt_align.hpp"
#include "../src/rt_overlap.hpp"
#include "../src/rt_poa.hpp"
#include "../src/rt_sequence.hpp"

static int g_failures = 0;
static int g_checks = 0;

#define CHECK(cond)                                                       \
  do {                                                                    \
    ++g_checks;                                                           \
    if (!(cond)) {                                                        \
      ++g_failures;                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                     \
  } while (0)

#define CHECK_EQ(a, b)                                                    \
  do {                                                                    \
    ++g_checks;                                                           \
    auto va = (a);                                                        \
    auto vb = (b);                                                        \
    if (!(va == vb)) {                                                    \
      ++g_failures;                                                       \
      std::fprintf(stderr, "FAIL %s:%d: %s != %s\n", __FILE__, __LINE__,  \
                   #a, #b);                                               \
    }                                                                     \
  } while (0)

// ---- Sequence -------------------------------------------------------------

static void test_sequence() {
  // uppercasing (reference: src/sequence.cpp:24-27)
  rt::Sequence s("r", 1, "acgtn", 5);
  CHECK_EQ(s.data, std::string("ACGTN"));

  // informative quality is kept
  rt::Sequence q("r", 1, "ACGT", 4, "!!5!", 4);
  CHECK_EQ(q.quality, std::string("!!5!"));

  // all-'!' quality carries no information and is dropped
  // (reference: src/sequence.cpp:34-42)
  rt::Sequence z("r", 1, "ACGT", 4, "!!!!", 4);
  CHECK(z.quality.empty());

  // reverse complement + reversed quality, idempotent
  // (reference: src/sequence.cpp:49-84)
  q.create_reverse_complement();
  CHECK_EQ(q.reverse_complement, std::string("ACGT"));
  rt::Sequence r("r", 1, "AACG", 4, "!05!", 4);
  r.create_reverse_complement();
  CHECK_EQ(r.reverse_complement, std::string("CGTT"));
  CHECK_EQ(r.reverse_quality, std::string("!50!"));
  r.create_reverse_complement();
  CHECK_EQ(r.reverse_complement, std::string("CGTT"));
}

// ---- alignment kernels -----------------------------------------------------

static void test_align() {
  // pinned small distances
  CHECK_EQ(rt::edit_distance("kitten", 6, "sitting", 7), 3);
  CHECK_EQ(rt::edit_distance("", 0, "abc", 3), 3);
  CHECK_EQ(rt::edit_distance("ACGT", 4, "ACGT", 4), 0);
  // symmetry
  CHECK_EQ(rt::edit_distance("ACGTACGT", 8, "AGTACGGT", 8),
           rt::edit_distance("AGTACGGT", 8, "ACGTACGT", 8));

  // the CIGAR's edit count must equal the exact distance, and its spans
  // must cover both sequences
  const std::string qs = "ACGTTTACGGTACGT";
  const std::string ts = "ACGTACGGTACGTTT";
  std::string cig = rt::align_global_cigar(qs.data(), qs.size(), ts.data(),
                                           ts.size());
  int64_t q_span = 0, t_span = 0, edits = 0;
  uint32_t run = 0;
  for (char c : cig) {
    if (c >= '0' && c <= '9') {
      run = run * 10 + (c - '0');
      continue;
    }
    if (c == 'M' || c == '=') {
      q_span += run;
      t_span += run;
    } else if (c == 'X') {
      q_span += run;
      t_span += run;
      edits += run;
    } else if (c == 'I') {
      q_span += run;
      edits += run;
    } else if (c == 'D') {
      t_span += run;
      edits += run;
    }
    run = 0;
  }
  CHECK_EQ(q_span, (int64_t)qs.size());
  CHECK_EQ(t_span, (int64_t)ts.size());
  CHECK_EQ(edits, rt::edit_distance(qs.data(), qs.size(), ts.data(),
                                    ts.size()));
}

// ---- Overlap ---------------------------------------------------------------

static void test_overlap() {
  // PAF ctor + span-ratio error metric (reference: src/overlap.cpp:24-42)
  auto paf = rt::Overlap::from_paf("q", 100, 0, 80, '+', "t", 200, 10, 110);
  CHECK_EQ(paf->length, 100u);
  CHECK(paf->error > 0.19 && paf->error < 0.21);  // 1 - 80/100
  CHECK(!paf->strand);

  // MHAP ctor: 1-based ordinals, rc flags (reference: src/overlap.cpp:15-27)
  auto mhap = rt::Overlap::from_mhap(1, 2, 0.1, 10, 0, 0, 80, 100, 1, 10,
                                     110, 200);
  CHECK(mhap->strand);

  // SAM ctor scans the CIGAR for spans (reference: src/overlap.cpp:44-108)
  auto sam = rt::Overlap::from_sam("q", 0, "t", 11, "20M5I20M5D20M");
  CHECK_EQ(sam->q_begin, 0u);
  CHECK_EQ(sam->q_end, 65u);        // 20+5+20+20 query bases
  CHECK_EQ(sam->t_begin, 10u);      // pos is 1-based
  CHECK_EQ(sam->t_end, 10u + 65u);  // 20+20+5+20 target bases

  // transmute resolves names and validates lengths
  // (reference: src/overlap.cpp:129-177)
  std::vector<std::unique_ptr<rt::Sequence>> seqs;
  seqs.push_back(rt::createSequence("q", std::string(100, 'A')));
  seqs.push_back(rt::createSequence("t", std::string(200, 'C')));
  // keys carry a q/t suffix, the reference's disambiguation scheme for a
  // name that is both a read and a target (src/polisher.cpp:210-215)
  std::unordered_map<std::string, uint64_t> name_to_id{{"qq", 0}, {"tt", 1}};
  std::unordered_map<uint64_t, uint64_t> id_to_id;
  paf->transmute(seqs, name_to_id, id_to_id);
  CHECK(paf->is_transmuted);
  CHECK_EQ(paf->q_id, 0u);
  CHECK_EQ(paf->t_id, 1u);

  // breaking points from a pure-match CIGAR land on window boundaries
  // (reference: src/overlap.cpp:226-292)
  auto bp = rt::Overlap::from_sam("q", 0, "t", 1, "100M");
  bp->transmute(seqs, name_to_id, id_to_id);
  bp->find_breaking_points(seqs, 50);
  CHECK_EQ(bp->breaking_points.size(), 4u);  // two windows x (first, last)
  CHECK_EQ(bp->breaking_points[0].first, 0u);
  CHECK_EQ(bp->breaking_points[1].first, 50u);
  CHECK_EQ(bp->breaking_points[2].first, 50u);
  CHECK_EQ(bp->breaking_points[3].first, 100u);
}

// ---- POA graph -------------------------------------------------------------

static void test_poa() {
  // three identical layers over a backbone with one error: the consensus
  // recovers the majority base, coverage counts the paths through the
  // chosen nodes
  const std::string backbone = "ACGTACGT";
  const std::string truth = "ACGAACGT";  // backbone has T where truth has A
  rt::PoaGraph g;
  std::vector<uint32_t> w1(backbone.size(), 1);
  g.add_alignment({}, backbone.data(), backbone.size(), w1);
  rt::PoaAligner aligner(5, -4, -8);
  const double inf = 1e300;
  for (int i = 0; i < 3; ++i) {
    auto aln = aligner.align(truth.data(), truth.size(), g, -inf, inf);
    std::vector<uint32_t> w(truth.size(), 1);
    g.add_alignment(aln, truth.data(), truth.size(), w);
  }
  std::vector<uint32_t> cov;
  std::string cons = g.generate_consensus(&cov);
  CHECK_EQ(cons, truth);
  CHECK_EQ(cov.size(), cons.size());
  CHECK_EQ(cov[3], 3u);  // the corrected base: 3 supporting layers
  CHECK_EQ(cov[0], 4u);  // agreeing base: backbone + 3 layers
}

int main() {
  test_sequence();
  test_align();
  test_overlap();
  test_poa();
  if (g_failures) {
    std::fprintf(stderr, "%d/%d checks FAILED\n", g_failures, g_checks);
    return 1;
  }
  std::printf("all %d checks passed\n", g_checks);
  return 0;
}
