// Concurrency stress harness for the native runtime, meant to run under
// the sanitizer builds (`make tsan|asan|ubsan`). Where rt_test.cpp checks
// functional behaviour, this file hammers the concurrent seams:
//
//   1. submit storm        — many producer threads racing submit()
//   2. shutdown w/ backlog — destructor drains a loaded queue
//   3. mid-flight cancel   — cancel_pending() vs running workers; dropped
//                            futures must break, not hang; pool reusable
//   4. pool churn          — rapid create/submit/destroy cycles
//   5. CIGAR install race  — concurrent set_job_cigar on disjoint jobs,
//                            then pooled host alignment for the rest
//                            (the device/host alignment hand-off)
//   6. consensus hand-off  — device-style set_consensus installs racing
//                            host consensus_cpu_one on disjoint windows
//                            (the device/host consensus hand-off; one
//                            external consensus caller only — that thread
//                            owns the shared aligner slot n)
//
// Build + run:  make -C racon_tpu/native stress   (or tsan/asan/ubsan)
#include <sys/stat.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <future>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "../src/rt_pipeline.hpp"
#include "../src/rt_threadpool.hpp"

// Atomic because CHECKs fire from racer threads too.
static std::atomic<int> g_failures{0};
static std::atomic<int> g_checks{0};

#define CHECK(cond)                                                        \
  do {                                                                     \
    ++g_checks;                                                            \
    if (!(cond)) {                                                         \
      ++g_failures;                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__, #cond); \
    }                                                                      \
  } while (0)

#define CHECK_EQ(a, b)                                                     \
  do {                                                                     \
    ++g_checks;                                                            \
    auto va = (a);                                                         \
    auto vb = (b);                                                         \
    if (!(va == vb)) {                                                     \
      ++g_failures;                                                        \
      std::fprintf(stderr, "FAIL %s:%d: %s != %s\n", __FILE__, __LINE__,   \
                   #a, #b);                                                \
    }                                                                      \
  } while (0)

static std::string g_tmpdir;

static std::string write_file(const std::string& name,
                              const std::string& content) {
  const std::string path = g_tmpdir + "/" + name;
  std::ofstream(path) << content;
  return path;
}

// ---- 1. submit storm -------------------------------------------------------
// Many producers race submit() against 4 workers; every future resolves and
// every job runs exactly once. Producers also probe this_thread_index()
// concurrently — non-pool callers must all map to the shared slot n.
static void stress_submit_storm() {
  constexpr int kProducers = 8;
  constexpr int kJobsPerProducer = 200;
  rt::ThreadPool pool(4);
  std::atomic<int> ran{0};
  std::vector<std::thread> producers;
  producers.reserve(kProducers);
  for (int p = 0; p < kProducers; ++p) {
    producers.emplace_back([&pool, &ran] {
      CHECK_EQ(pool.this_thread_index(), pool.num_threads());
      std::vector<std::future<void>> futs;
      futs.reserve(kJobsPerProducer);
      for (int i = 0; i < kJobsPerProducer; ++i) {
        futs.emplace_back(pool.submit([&ran] {
          ran.fetch_add(1, std::memory_order_relaxed);
        }));
      }
      for (auto& f : futs) {
        f.get();
      }
    });
  }
  for (auto& t : producers) {
    t.join();
  }
  CHECK_EQ(ran.load(), kProducers * kJobsPerProducer);
}

// ---- 2. shutdown with a loaded queue --------------------------------------
// The destructor must let workers drain everything already queued; no job
// is lost and no worker pops from a destructed queue.
static void stress_shutdown_backlog() {
  std::atomic<int> ran{0};
  constexpr int kJobs = 1000;
  {
    rt::ThreadPool pool(4);
    for (int i = 0; i < kJobs; ++i) {
      pool.submit([&ran] { ran.fetch_add(1, std::memory_order_relaxed); });
    }
    // destructor runs here with most of the queue still pending
  }
  CHECK_EQ(ran.load(), kJobs);
}

// ---- 3. mid-flight cancellation -------------------------------------------
// cancel_pending() from another thread while workers chew slow jobs: every
// submitted job either ran or its future throws broken_promise, the two
// counts add up, and the pool keeps working afterwards.
static void stress_cancellation() {
  rt::ThreadPool pool(2);
  std::atomic<int> ran{0};
  constexpr int kJobs = 64;
  std::vector<std::future<void>> futs;
  futs.reserve(kJobs);
  for (int i = 0; i < kJobs; ++i) {
    futs.emplace_back(pool.submit([&ran] {
      std::this_thread::sleep_for(std::chrono::milliseconds(1));
      ran.fetch_add(1, std::memory_order_relaxed);
    }));
  }
  std::size_t dropped = 0;
  std::thread canceller([&pool, &dropped] {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
    dropped = pool.cancel_pending();
  });
  canceller.join();
  int broken = 0;
  for (auto& f : futs) {
    try {
      f.get();
    } catch (const std::future_error&) {
      ++broken;
    }
  }
  CHECK_EQ(static_cast<std::size_t>(broken), dropped);
  CHECK_EQ(ran.load() + broken, kJobs);
  // the pool survives a cancellation and still serves new work
  std::atomic<int> again{0};
  std::vector<std::future<void>> futs2;
  for (int i = 0; i < 8; ++i) {
    futs2.emplace_back(pool.submit([&again] {
      again.fetch_add(1, std::memory_order_relaxed);
    }));
  }
  for (auto& f : futs2) {
    f.get();
  }
  CHECK_EQ(again.load(), 8);
}

// ---- 4. pool churn ---------------------------------------------------------
// Rapid create/submit/destroy cycles: constructor/worker-startup and
// destructor/worker-drain handshakes under repetition.
static void stress_pool_churn() {
  std::atomic<int> ran{0};
  constexpr int kCycles = 20;
  constexpr int kJobs = 50;
  for (int c = 0; c < kCycles; ++c) {
    rt::ThreadPool pool(4);
    for (int i = 0; i < kJobs; ++i) {
      pool.submit([&ran] { ran.fetch_add(1, std::memory_order_relaxed); });
    }
  }
  CHECK_EQ(ran.load(), kCycles * kJobs);
}

// ---- pipeline fixtures -----------------------------------------------------

// Deterministic pseudo-random truth (same generator as rt_test.cpp, longer
// so the pipeline has enough windows/jobs to race over).
static std::string make_truth(int length) {
  std::string truth;
  uint64_t x = 0x9E3779B97F4A7C15ull;
  for (int i = 0; i < length; ++i) {
    x ^= x << 13;
    x ^= x >> 7;
    x ^= x << 17;
    truth += "ACGT"[x & 3];
  }
  return truth;
}

static std::string make_draft(const std::string& truth) {
  std::string draft = truth;
  for (size_t i = 50; i < draft.size(); i += 100) {
    draft[i] = draft[i] == 'A' ? 'C' : 'A';
  }
  return draft;
}

// ---- 5. concurrent CIGAR installs -----------------------------------------
// PAF input (no CIGARs) so every overlap is an alignment job; several
// installer threads stamp device-style CIGARs onto disjoint jobs while the
// pool host-aligns the rest, mirroring the device/host alignment hand-off.
static void stress_cigar_install() {
  const int kLen = 6000;
  const int kReads = 8;
  const std::string truth = make_truth(kLen);
  const std::string draft = make_draft(truth);

  std::string reads, paf;
  for (int i = 0; i < kReads; ++i) {
    const std::string rn = "r" + std::to_string(i);
    reads += ">" + rn + "\n" + truth + "\n";
    paf += rn + "\t" + std::to_string(kLen) + "\t0\t" + std::to_string(kLen) +
           "\t+\ttgt\t" + std::to_string(kLen) + "\t0\t" +
           std::to_string(kLen) + "\t" + std::to_string(kLen - 60) + "\t" +
           std::to_string(kLen) + "\t60\n";
  }
  const std::string reads_p = write_file("cig_reads.fasta", reads);
  const std::string paf_p = write_file("cig_ovl.paf", paf);
  const std::string tgt_p = write_file("cig_tgt.fasta", ">tgt\n" + draft + "\n");

  rt::PipelineParams params;
  params.window_length = 500;
  params.num_threads = 4;
  rt::Pipeline pipe(reads_p, paf_p, tgt_p, params);
  pipe.prepare();
  const size_t n_jobs = pipe.num_align_jobs();
  CHECK_EQ(n_jobs, static_cast<size_t>(kReads));

  // Device installers: two threads stamp perfect-match CIGARs onto
  // disjoint halves of the even jobs; odd jobs are left for the host.
  const std::string cigar = std::to_string(kLen) + "M";
  std::vector<std::thread> installers;
  for (int half = 0; half < 2; ++half) {
    installers.emplace_back([&pipe, &cigar, half, n_jobs] {
      for (size_t j = half * 2; j < n_jobs; j += 4) {
        const char *q, *t;
        uint32_t q_len, t_len;
        pipe.align_job_views(j, &q, &q_len, &t, &t_len);
        CHECK(q_len > 0 && t_len > 0);
        pipe.set_job_cigar(j, cigar);
      }
    });
  }
  for (auto& t : installers) {
    t.join();
  }
  pipe.align_jobs_cpu();  // host finishes the odd jobs on the pool
  pipe.build_windows();
  CHECK(pipe.num_windows() > 0);
  pipe.consensus_cpu_all();
  std::vector<std::pair<std::string, std::string>> out;
  pipe.stitch(true, &out);
  CHECK_EQ(out.size(), 1u);
  CHECK_EQ(out[0].second, truth);
}

// ---- 6. consensus hand-off -------------------------------------------------
// Device-style installs (set_consensus from installer threads) racing host
// consensus (consensus_cpu_one from one external thread) on disjoint
// windows — the overlap-free interleaving the drivers rely on. Exactly one
// external consensus caller: that thread owns the shared aligner slot n.
static void stress_consensus_handoff() {
  const int kLen = 6000;
  const std::string truth = make_truth(kLen);
  const std::string draft = make_draft(truth);

  std::string reads, sam = "@HD\tVN:1.6\n@SQ\tSN:tgt\tLN:" +
                           std::to_string(kLen) + "\n";
  for (int i = 0; i < 5; ++i) {
    const std::string rn = "r" + std::to_string(i);
    reads += ">" + rn + "\n" + truth + "\n";
    sam += rn + "\t0\ttgt\t1\t60\t" + std::to_string(kLen) + "M\t*\t0\t0\t" +
           truth + "\t*\n";
  }
  const std::string reads_p = write_file("con_reads.fasta", reads);
  const std::string sam_p = write_file("con_ovl.sam", sam);
  const std::string tgt_p = write_file("con_tgt.fasta", ">tgt\n" + draft + "\n");

  rt::PipelineParams params;
  params.window_length = 200;
  params.match = 5;
  params.mismatch = -4;
  params.gap = -8;
  params.num_threads = 4;
  rt::Pipeline pipe(reads_p, sam_p, tgt_p, params);
  pipe.initialize();
  const size_t n = pipe.num_windows();
  CHECK_EQ(n, static_cast<size_t>(kLen / 200));

  // Installer threads serve even windows with the device result (here: the
  // truth slice the POA would converge to); one external host thread
  // serves the odd windows.
  std::vector<std::thread> racers;
  for (int half = 0; half < 2; ++half) {
    racers.emplace_back([&pipe, &truth, half, n] {
      for (size_t i = half * 2; i < n; i += 4) {
        pipe.set_consensus(i, truth.substr(i * 200, 200), true);
      }
    });
  }
  racers.emplace_back([&pipe, n] {
    for (size_t i = 1; i < n; i += 2) {
      CHECK(pipe.consensus_cpu_one(i));
    }
  });
  for (auto& t : racers) {
    t.join();
  }
  for (size_t i = 0; i < n; ++i) {
    CHECK(pipe.has_consensus(i));
  }
  std::vector<std::pair<std::string, std::string>> out;
  pipe.stitch(true, &out);
  CHECK_EQ(out.size(), 1u);
  CHECK_EQ(out[0].second, truth);
}

int main() {
  g_tmpdir = "/tmp/rt_stress_" + std::to_string(::getpid());
  ::mkdir(g_tmpdir.c_str(), 0755);
  stress_submit_storm();
  stress_shutdown_backlog();
  stress_cancellation();
  stress_pool_churn();
  stress_cigar_install();
  stress_consensus_handoff();
  if (g_failures.load()) {
    std::fprintf(stderr, "%d/%d stress checks FAILED (artifacts in %s)\n",
                 g_failures.load(), g_checks.load(), g_tmpdir.c_str());
    return 1;
  }
  std::system(("rm -rf '" + g_tmpdir + "'").c_str());
  std::printf("all %d stress checks passed\n", g_checks.load());
  return 0;
}
