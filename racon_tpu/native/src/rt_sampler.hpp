// Native sequence subsampler/splitter — the rampler-equivalent tool
// (reference: vendored rampler, invoked by scripts/racon_wrapper.py:63-64,
// 88-89 as `rampler -o DIR subsample <seqs> <ref_len> <cov>` and
// `rampler -o DIR split <seqs> <bytes>`). Exposed as subcommands of the
// racon_tpu binary; output naming matches the wrapper contract
// (<basename>_<cov>x.<ext> / <basename>_<i>.<ext>).
#pragma once

#include <string>
#include <vector>

namespace rt {

// Random whole-read subsample down to ref_length * coverage bases.
// Returns the output path. Atomic (tmp + rename).
std::string sampler_subsample(const std::string& path, uint64_t ref_length,
                              uint32_t coverage, const std::string& outdir,
                              uint64_t seed = 42);

// Split into chunks of ~chunk_size sequence bytes (record-granular).
// Returns the chunk paths.
std::vector<std::string> sampler_split(const std::string& path,
                                       uint64_t chunk_size,
                                       const std::string& outdir);

}  // namespace rt
