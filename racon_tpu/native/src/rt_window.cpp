#include "rt_error.hpp"
#include "rt_window.hpp"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <limits>
#include <numeric>

namespace rt {

std::shared_ptr<Window> createWindow(uint64_t id, uint32_t rank,
                                     WindowType type, const char* backbone,
                                     uint32_t backbone_length,
                                     const char* quality,
                                     uint32_t quality_length) {
  if (backbone_length == 0 || backbone_length != quality_length) {
    rt::fail("[racon_tpu::createWindow] error: "
                 "empty backbone sequence/unequal quality length!\n");
  }
  return std::make_shared<Window>(id, rank, type, backbone, backbone_length,
                                  quality, quality_length);
}

Window::Window(uint64_t id_, uint32_t rank_, WindowType type_,
               const char* backbone, uint32_t backbone_length,
               const char* quality, uint32_t quality_length)
    : id(id_), rank(rank_), type(type_) {
  sequences.emplace_back(backbone, backbone_length);
  qualities.emplace_back(quality, quality_length);
  positions.emplace_back(0, 0);
}

void Window::add_layer(const char* sequence, uint32_t sequence_length,
                       const char* quality, uint32_t quality_length,
                       uint32_t begin, uint32_t end) {
  if (sequence_length == 0 || begin == end) {
    return;
  }
  if (quality != nullptr && sequence_length != quality_length) {
    rt::fail("[racon_tpu::Window::add_layer] error: "
                 "unequal quality size!\n");
  }
  if (begin >= end || begin > sequences.front().second ||
      end > sequences.front().second) {
    rt::fail("[racon_tpu::Window::add_layer] error: "
                 "layer begin and end positions are invalid!\n");
  }
  sequences.emplace_back(sequence, sequence_length);
  qualities.emplace_back(quality, quality_length);
  positions.emplace_back(begin, end);
}

static std::vector<uint32_t> layer_weights(const char* quality, uint32_t len) {
  std::vector<uint32_t> w(len, 1);
  if (quality != nullptr) {
    for (uint32_t i = 0; i < len; ++i) {
      w[i] = static_cast<uint32_t>(static_cast<uint8_t>(quality[i]) -
                                   static_cast<uint8_t>('!'));
    }
  }
  return w;
}

bool Window::generate_consensus(PoaAligner& aligner, bool trim) {
  if (sequences.size() < 3) {
    consensus.assign(sequences.front().first, sequences.front().second);
    return false;
  }

  PoaGraph graph;
  graph.add_alignment(PoaAlignment(), sequences.front().first,
                      sequences.front().second,
                      layer_weights(qualities.front().first,
                                    qualities.front().second));

  // Layers sorted by begin position with std::sort, NOT stable_sort: the
  // reference sorts unstably (src/window.cpp:85-86), and with the many
  // equal begin keys of window-spanning reads the introsort permutation
  // (deterministic for a given input) decides the graph-growth order.
  // Measured: unstable order improves every golden scenario vs stable.
  std::vector<uint32_t> order(sequences.size());
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin() + 1, order.end(),
            [&](uint32_t a, uint32_t b) {
              return positions[a].first < positions[b].first;
            });

  const uint32_t backbone_len = sequences.front().second;
  const uint32_t offset = static_cast<uint32_t>(0.01 * backbone_len);
  const double inf = std::numeric_limits<double>::infinity();

  for (uint32_t idx = 1; idx < sequences.size(); ++idx) {
    const uint32_t i = order[idx];
    PoaAlignment alignment;
    if (positions[i].first < offset &&
        positions[i].second > backbone_len - offset) {
      alignment =
          aligner.align(sequences[i].first, sequences[i].second, graph, -inf, inf);
    } else {
      alignment = aligner.align(sequences[i].first, sequences[i].second, graph,
                                static_cast<double>(positions[i].first),
                                static_cast<double>(positions[i].second));
    }
    graph.add_alignment(alignment, sequences[i].first, sequences[i].second,
                        layer_weights(qualities[i].first, sequences[i].second));
  }

  std::vector<uint32_t> coverages;
  consensus = graph.generate_consensus(&coverages);

  if (type == WindowType::kTGS && trim) {
    const uint32_t average_coverage =
        (static_cast<uint32_t>(sequences.size()) - 1) / 2;

    int32_t begin = 0, end = static_cast<int32_t>(consensus.size()) - 1;
    for (; begin < static_cast<int32_t>(consensus.size()); ++begin) {
      if (coverages[begin] >= average_coverage) {
        break;
      }
    }
    for (; end >= 0; --end) {
      if (coverages[end] >= average_coverage) {
        break;
      }
    }

    if (begin >= end) {
      std::fprintf(stderr,
                   "[racon_tpu::Window::generate_consensus] warning: "
                   "contig %llu might be chimeric in window %u!\n",
                   static_cast<unsigned long long>(id), rank);
    } else {
      consensus = consensus.substr(begin, end - begin + 1);
    }
  }

  return true;
}

}  // namespace rt
