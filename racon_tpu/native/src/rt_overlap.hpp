// Overlap data model: one read<->target overlap from MHAP/PAF/SAM input,
// with id resolution against the loaded sequence set and computation of
// per-window "breaking points" (the (target_pos, query_pos) match anchors at
// window boundaries that later drive zero-copy window layer assignment).
//
// Capability parity with the reference overlap model
// (/root/reference/src/overlap.{hpp,cpp}): the three format constructors
// (MHAP src/overlap.cpp:15-27, PAF :29-42, SAM with full CIGAR scan :44-108),
// name/id -> internal id transmutation (:129-177) with the same hard
// length-consistency errors, the span-ratio error metric (:24-26), and the
// CIGAR walk emitting per-window first/last match pairs (:226-292).
//
// The alignment step for CIGAR-less overlaps is pluggable (host CPU aligner
// or the TPU batch aligner) instead of a hardwired edlib call — that is the
// seam the accelerator backend overrides (reference seam:
// src/overlap.cpp:179-203 + src/cuda/cudaaligner.cpp).
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "rt_sequence.hpp"

namespace rt {

struct Overlap {
  std::string q_name;
  uint64_t q_id = 0;
  uint32_t q_begin = 0, q_end = 0, q_length = 0;
  std::string t_name;
  uint64_t t_id = 0;
  uint32_t t_begin = 0, t_end = 0, t_length = 0;
  bool strand = false;  // true if query maps to the reverse strand
  uint32_t length = 0;  // max of the two spans
  double error = 0.0;   // 1 - min(span)/max(span)
  std::string cigar;    // SAM-provided or filled by the aligner
  bool is_valid = true;
  bool is_transmuted = false;
  // Flattened (t_pos, q_pos) pairs; even index = first match in a window,
  // odd index = one-past the last match.
  std::vector<std::pair<uint32_t, uint32_t>> breaking_points;

  Overlap() : is_transmuted(true) {}

  // MHAP record: ids are 1-based ordinals. Parity: src/overlap.cpp:15-27.
  static std::unique_ptr<Overlap> from_mhap(uint64_t a_id, uint64_t b_id,
                                            double err, uint32_t minmers,
                                            uint32_t a_rc, uint32_t a_begin,
                                            uint32_t a_end, uint32_t a_length,
                                            uint32_t b_rc, uint32_t b_begin,
                                            uint32_t b_end, uint32_t b_length);

  // PAF record. Parity: src/overlap.cpp:29-42.
  static std::unique_ptr<Overlap> from_paf(
      std::string q_name, uint32_t q_length, uint32_t q_begin, uint32_t q_end,
      char orientation, std::string t_name, uint32_t t_length,
      uint32_t t_begin, uint32_t t_end);

  // SAM record (single alignment line). Parity: src/overlap.cpp:44-108.
  static std::unique_ptr<Overlap> from_sam(std::string q_name, uint32_t flag,
                                           std::string t_name, uint32_t pos_1based,
                                           std::string cigar);

  // Resolve q/t to internal sequence ids and validate lengths.
  // Parity: src/overlap.cpp:129-177 (same hard exits on length mismatch).
  void transmute(const std::vector<std::unique_ptr<Sequence>>& sequences,
                 const std::unordered_map<std::string, uint64_t>& name_to_id,
                 const std::unordered_map<uint64_t, uint64_t>& id_to_id);

  // Compute breaking points; if no CIGAR is present the `aligned_cigar`
  // callback result (already computed global alignment) must be installed
  // into `cigar` beforehand, or pass nullptrs to use the built-in host
  // aligner. Parity: src/overlap.cpp:179-203.
  void find_breaking_points(
      const std::vector<std::unique_ptr<Sequence>>& sequences,
      uint32_t window_length);

  // Pointers into the strand-appropriate query/target subsequences that need
  // global alignment (used by both the host aligner and the TPU batch
  // aligner). Only meaningful when cigar is empty.
  void alignment_views(const std::vector<std::unique_ptr<Sequence>>& sequences,
                       const char** q, uint32_t* q_len, const char** t,
                       uint32_t* t_len) const;

  // CIGAR walk emitting per-window match anchors.
  // Parity: src/overlap.cpp:226-292.
  void find_breaking_points_from_cigar(uint32_t window_length);
};

}  // namespace rt
