// Native CLI binary for the pure-host path: racon-compatible flags
// (parity: /root/reference/src/main.cpp:18-38,166-229). The accelerated
// path lives behind the Python driver (python -m racon_tpu.cli --tpu),
// which shares this same native pipeline through the C ABI.
#include <getopt.h>
#include <sys/stat.h>

#include <exception>

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "rt_pipeline.hpp"
#include "rt_sampler.hpp"

#ifndef RT_VERSION
#define RT_VERSION "0.1.0"
#endif

namespace {

struct option long_options[] = {
    {"include-unpolished", no_argument, nullptr, 'u'},
    {"fragment-correction", no_argument, nullptr, 'f'},
    {"window-length", required_argument, nullptr, 'w'},
    {"quality-threshold", required_argument, nullptr, 'q'},
    {"error-threshold", required_argument, nullptr, 'e'},
    {"no-trimming", no_argument, nullptr, 'T'},
    {"match", required_argument, nullptr, 'm'},
    {"mismatch", required_argument, nullptr, 'x'},
    {"gap", required_argument, nullptr, 'g'},
    {"threads", required_argument, nullptr, 't'},
    {"version", no_argument, nullptr, 'v'},
    {"help", no_argument, nullptr, 'h'},
    {nullptr, 0, nullptr, 0}};

void help() {
  std::printf(
      "usage: racon_tpu [options ...] <sequences> <overlaps> <target "
      "sequences>\n"
      "\n"
      "    #default output is stdout\n"
      "    <sequences>    FASTA/FASTQ (may be gzipped) reads\n"
      "    <overlaps>     MHAP/PAF/SAM (may be gzipped) overlaps\n"
      "    <target sequences> FASTA/FASTQ (may be gzipped) draft targets\n"
      "\n"
      "    options:\n"
      "        -u, --include-unpolished  output unpolished target sequences\n"
      "        -f, --fragment-correction fragment correction mode\n"
      "        -w, --window-length <int>     default: 500\n"
      "        -q, --quality-threshold <float> default: 10.0\n"
      "        -e, --error-threshold <float>   default: 0.3\n"
      "        --no-trimming             disable consensus end trimming\n"
      "        -m, --match <int>             default: 3\n"
      "        -x, --mismatch <int>          default: -5\n"
      "        -g, --gap <int>               default: -4\n"
      "        -t, --threads <int>           default: 1\n"
      "        --version                 print version\n"
      "        -h, --help                print usage\n"
      "\n"
      "    TPU-accelerated path: python -m racon_tpu.cli --tpu ...\n");
}

}  // namespace

namespace {

// rampler-compatible subcommands:
//   racon_tpu [-o DIR] subsample <sequences> <ref_length> <coverage>
//   racon_tpu [-o DIR] split <sequences> <chunk_size>
int sampler_main(int argc, char** argv) {
  std::string outdir = ".";
  int i = 1;
  if (std::string(argv[i]) == "-o" || std::string(argv[i]) == "--out-directory") {
    if (i + 1 >= argc) {
      std::fprintf(stderr, "[racon_tpu::sampler] error: -o needs a value\n");
      return 1;
    }
    outdir = argv[i + 1];
    i += 2;
  }
  ::mkdir(outdir.c_str(), 0755);  // EEXIST is fine
  const std::string mode = argv[i];
  try {
    if (mode == "subsample") {
      if (i + 3 >= argc) {
        std::fprintf(stderr, "usage: racon_tpu [-o DIR] subsample "
                             "<sequences> <ref_length> <coverage>\n");
        return 1;
      }
      rt::sampler_subsample(argv[i + 1], std::strtoull(argv[i + 2], nullptr, 10),
                            static_cast<uint32_t>(std::atoi(argv[i + 3])),
                            outdir);
    } else {
      if (i + 2 >= argc) {
        std::fprintf(stderr, "usage: racon_tpu [-o DIR] split <sequences> "
                             "<chunk_size>\n");
        return 1;
      }
      rt::sampler_split(argv[i + 1],
                        std::strtoull(argv[i + 2], nullptr, 10), outdir);
    }
  } catch (const std::exception& e) {
    std::fprintf(stderr, "%s", e.what());
    return 1;
  }
  return 0;
}

bool is_sampler_invocation(int argc, char** argv) {
  // Subcommand must be argv[1], or argv[3] after a leading -o DIR.
  const auto is_mode = [](const char* a) {
    const std::string s = a;
    return s == "subsample" || s == "split";
  };
  if (argc > 1 && is_mode(argv[1])) {
    return true;
  }
  if (argc > 3 && (std::string(argv[1]) == "-o" ||
                   std::string(argv[1]) == "--out-directory")) {
    return is_mode(argv[3]);
  }
  return false;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc > 1 && is_sampler_invocation(argc, argv)) {
    return sampler_main(argc, argv);
  }

  rt::PipelineParams params;
  bool drop_unpolished = true;

  int arg;
  while ((arg = getopt_long(argc, argv, "ufw:q:e:m:x:g:t:h", long_options,
                            nullptr)) != -1) {
    switch (arg) {
      case 'u': drop_unpolished = false; break;
      case 'f': params.type = 1; break;
      case 'w': params.window_length = std::atoi(optarg); break;
      case 'q': params.quality_threshold = std::atof(optarg); break;
      case 'e': params.error_threshold = std::atof(optarg); break;
      case 'T': params.trim = false; break;
      case 'm': params.match = static_cast<int8_t>(std::atoi(optarg)); break;
      case 'x': params.mismatch = static_cast<int8_t>(std::atoi(optarg)); break;
      case 'g': params.gap = static_cast<int8_t>(std::atoi(optarg)); break;
      case 't': params.num_threads = std::atoi(optarg); break;
      case 'v': std::printf("%s\n", RT_VERSION); return 0;
      case 'h': help(); return 0;
      default: return 1;
    }
  }

  std::vector<std::string> inputs;
  for (int i = optind; i < argc; ++i) {
    inputs.emplace_back(argv[i]);
  }
  if (inputs.size() < 3) {
    std::fprintf(stderr, "[racon_tpu::] error: missing input file(s)!\n");
    help();
    return 1;
  }

  try {
    rt::Pipeline pipeline(inputs[0], inputs[1], inputs[2], params);
    pipeline.initialize();
    pipeline.consensus_cpu_all();

    std::vector<std::pair<std::string, std::string>> dst;
    pipeline.stitch(drop_unpolished, &dst);
    for (const auto& it : dst) {
      std::fprintf(stdout, ">%s\n%s\n", it.first.c_str(), it.second.c_str());
    }
  } catch (const std::exception& e) {
    std::fprintf(stderr, "%s", e.what());
    return 1;
  }
  return 0;
}
