#include "rt_sampler.hpp"

#include <algorithm>
#include <cstdio>
#include <numeric>
#include <random>

#include "rt_error.hpp"
#include "rt_parsers.hpp"

namespace rt {

namespace {

struct Fmt {
  SeqFormat fmt;
  const char* ext;
};

Fmt sniff(const std::string& path) {
  SeqFormat fmt;
  if (!sniff_sequence_format(path, &fmt)) {
    fail("[racon_tpu::sampler] error: unsupported extension in %s\n",
         path.c_str());
  }
  return {fmt, fmt == SeqFormat::kFasta ? ".fasta" : ".fastq"};
}

std::string base_name(const std::string& path) {
  const size_t slash = path.find_last_of('/');
  std::string base =
      slash == std::string::npos ? path : path.substr(slash + 1);
  const size_t dot = base.find('.');
  return dot == std::string::npos ? base : base.substr(0, dot);
}

void write_record(std::FILE* f, const Sequence& s, SeqFormat fmt) {
  if (fmt == SeqFormat::kFasta) {
    std::fprintf(f, ">%s\n%s\n", s.name.c_str(), s.data.c_str());
  } else {
    // Reads whose quality was dropped as uninformative still need a
    // placeholder line of the right length.
    const std::string qual =
        s.quality.empty() ? std::string(s.data.size(), '!') : s.quality;
    std::fprintf(f, "@%s\n%s\n+\n%s\n", s.name.c_str(), s.data.c_str(),
                 qual.c_str());
  }
}

std::FILE* open_or_fail(const std::string& path) {
  std::FILE* f = std::fopen(path.c_str(), "w");
  if (f == nullptr) {
    fail("[racon_tpu::sampler] error: unable to create %s\n", path.c_str());
  }
  return f;
}

}  // namespace

std::string sampler_subsample(const std::string& path, uint64_t ref_length,
                              uint32_t coverage, const std::string& outdir,
                              uint64_t seed) {
  const Fmt fmt = sniff(path);
  SequenceParser parser(path, fmt.fmt);
  auto records = parser.parse(0);

  const uint64_t target = ref_length * coverage;
  uint64_t total = 0;
  for (const auto& r : records) {
    total += r->data.size();
  }

  const std::string out_path =
      outdir + "/" + base_name(path) + "_" + std::to_string(coverage) + "x" +
      fmt.ext;
  const std::string tmp_path = out_path + ".tmp";
  std::FILE* f = open_or_fail(tmp_path);

  if (total <= target) {
    for (const auto& r : records) {
      write_record(f, *r, fmt.fmt);
    }
  } else {
    std::vector<size_t> order(records.size());
    std::iota(order.begin(), order.end(), 0);
    std::mt19937_64 rng(seed);
    std::shuffle(order.begin(), order.end(), rng);
    uint64_t picked = 0;
    std::vector<size_t> chosen;
    for (size_t i : order) {
      if (picked >= target) {
        break;
      }
      chosen.push_back(i);
      picked += records[i]->data.size();
    }
    std::sort(chosen.begin(), chosen.end());
    for (size_t i : chosen) {
      write_record(f, *records[i], fmt.fmt);
    }
  }
  std::fclose(f);
  if (std::rename(tmp_path.c_str(), out_path.c_str()) != 0) {
    fail("[racon_tpu::sampler] error: unable to finalize %s\n",
         out_path.c_str());
  }
  return out_path;
}

std::vector<std::string> sampler_split(const std::string& path,
                                       uint64_t chunk_size,
                                       const std::string& outdir) {
  const Fmt fmt = sniff(path);
  SequenceParser parser(path, fmt.fmt);

  std::vector<std::string> outputs;
  std::FILE* f = nullptr;
  uint64_t written = 0;
  uint32_t idx = 0;

  while (true) {
    auto batch = parser.parse(1ull << 26);
    if (batch.empty()) {
      break;
    }
    for (const auto& r : batch) {
      if (f == nullptr || (written >= chunk_size && written > 0)) {
        if (f != nullptr) {
          std::fclose(f);
        }
        const std::string out_path = outdir + "/" + base_name(path) + "_" +
                                     std::to_string(idx) + fmt.ext;
        outputs.push_back(out_path);
        f = open_or_fail(out_path);
        written = 0;
        ++idx;
      }
      write_record(f, *r, fmt.fmt);
      written += r->data.size();
    }
  }
  if (f != nullptr) {
    std::fclose(f);
  }
  return outputs;
}

}  // namespace rt
