#include "rt_error.hpp"
#include "rt_overlap.hpp"

#include <algorithm>
#include <cstdio>
#include <cstdlib>

#include "rt_align.hpp"

namespace rt {

static void span_metrics(uint32_t q_span, uint32_t t_span, uint32_t* length,
                         double* error) {
  *length = std::max(q_span, t_span);
  *error = 1.0 - std::min(q_span, t_span) / static_cast<double>(*length);
}

std::unique_ptr<Overlap> Overlap::from_mhap(uint64_t a_id, uint64_t b_id,
                                            double, uint32_t, uint32_t a_rc,
                                            uint32_t a_begin, uint32_t a_end,
                                            uint32_t a_length, uint32_t b_rc,
                                            uint32_t b_begin, uint32_t b_end,
                                            uint32_t b_length) {
  auto o = std::unique_ptr<Overlap>(new Overlap());
  o->is_transmuted = false;
  o->q_id = a_id - 1;  // MHAP ordinals are 1-based (parity: src/overlap.cpp:18)
  o->q_begin = a_begin;
  o->q_end = a_end;
  o->q_length = a_length;
  o->t_id = b_id - 1;
  o->t_begin = b_begin;
  o->t_end = b_end;
  o->t_length = b_length;
  o->strand = (a_rc ^ b_rc) != 0;
  span_metrics(a_end - a_begin, b_end - b_begin, &o->length, &o->error);
  return o;
}

std::unique_ptr<Overlap> Overlap::from_paf(std::string q_name,
                                           uint32_t q_length, uint32_t q_begin,
                                           uint32_t q_end, char orientation,
                                           std::string t_name,
                                           uint32_t t_length, uint32_t t_begin,
                                           uint32_t t_end) {
  auto o = std::unique_ptr<Overlap>(new Overlap());
  o->is_transmuted = false;
  o->q_name = std::move(q_name);
  o->q_begin = q_begin;
  o->q_end = q_end;
  o->q_length = q_length;
  o->t_name = std::move(t_name);
  o->t_begin = t_begin;
  o->t_end = t_end;
  o->t_length = t_length;
  o->strand = orientation == '-';
  span_metrics(q_end - q_begin, t_end - t_begin, &o->length, &o->error);
  return o;
}

std::unique_ptr<Overlap> Overlap::from_sam(std::string q_name, uint32_t flag,
                                           std::string t_name,
                                           uint32_t pos_1based,
                                           std::string cigar) {
  auto o = std::unique_ptr<Overlap>(new Overlap());
  o->is_transmuted = false;
  o->q_name = std::move(q_name);
  o->t_name = std::move(t_name);
  o->t_begin = pos_1based - 1;
  o->strand = (flag & 0x10) != 0;
  o->is_valid = !(flag & 0x4);
  o->cigar = std::move(cigar);

  // Unmapped records are dropped later; mapped records must carry a real
  // alignment (parity: src/overlap.cpp:55-59).
  if (o->cigar.size() < 2 && o->is_valid) {
    rt::fail("[racon_tpu::Overlap::from_sam] error: "
                 "missing alignment from SAM object!\n");
  }

  // Leading clip gives the query start; M/=/X/I/D/N tally the aligned and
  // clipped lengths (parity: src/overlap.cpp:60-107).
  const std::string& c = o->cigar;
  for (uint32_t i = 0; i < c.size(); ++i) {
    if (c[i] == 'S' || c[i] == 'H') {
      o->q_begin = static_cast<uint32_t>(std::atoi(c.c_str()));
      break;
    }
    if (c[i] == 'M' || c[i] == '=' || c[i] == 'I' || c[i] == 'D' ||
        c[i] == 'N' || c[i] == 'P' || c[i] == 'X') {
      break;
    }
  }

  uint32_t q_aln = 0, q_clip = 0, t_aln = 0;
  for (uint32_t i = 0, j = 0; i < c.size(); ++i) {
    char op = c[i];
    if (op == 'M' || op == '=' || op == 'X') {
      uint32_t n = static_cast<uint32_t>(std::atoi(c.c_str() + j));
      j = i + 1;
      q_aln += n;
      t_aln += n;
    } else if (op == 'I') {
      q_aln += static_cast<uint32_t>(std::atoi(c.c_str() + j));
      j = i + 1;
    } else if (op == 'D' || op == 'N') {
      t_aln += static_cast<uint32_t>(std::atoi(c.c_str() + j));
      j = i + 1;
    } else if (op == 'S' || op == 'H') {
      q_clip += static_cast<uint32_t>(std::atoi(c.c_str() + j));
      j = i + 1;
    } else if (op == 'P') {
      j = i + 1;
    }
  }

  o->q_end = o->q_begin + q_aln;
  o->q_length = q_clip + q_aln;
  if (o->strand) {
    uint32_t tmp = o->q_begin;
    o->q_begin = o->q_length - o->q_end;
    o->q_end = o->q_length - tmp;
  }
  o->t_end = o->t_begin + t_aln;
  span_metrics(q_aln, t_aln, &o->length, &o->error);
  return o;
}

template <typename K>
static bool lookup_id(const std::unordered_map<K, uint64_t>& map, const K& key,
                      uint64_t* id) {
  auto it = map.find(key);
  if (it == map.end()) {
    return false;
  }
  *id = it->second;
  return true;
}

void Overlap::transmute(
    const std::vector<std::unique_ptr<Sequence>>& sequences,
    const std::unordered_map<std::string, uint64_t>& name_to_id,
    const std::unordered_map<uint64_t, uint64_t>& id_to_id) {
  if (!is_valid || is_transmuted) {
    return;
  }

  if (!q_name.empty()) {
    if (!lookup_id(name_to_id, q_name + "q", &q_id)) {
      is_valid = false;
      return;
    }
    std::string().swap(q_name);
  } else if (!lookup_id(id_to_id, q_id << 1 | 0, &q_id)) {
    is_valid = false;
    return;
  }

  if (q_length != sequences[q_id]->data.size()) {
    rt::fail("[racon_tpu::Overlap::transmute] error: unequal lengths in "
                 "sequence and overlap file for sequence %s!\n",
                 sequences[q_id]->name.c_str());
  }

  if (!t_name.empty()) {
    if (!lookup_id(name_to_id, t_name + "t", &t_id)) {
      is_valid = false;
      return;
    }
    std::string().swap(t_name);
  } else if (!lookup_id(id_to_id, t_id << 1 | 1, &t_id)) {
    is_valid = false;
    return;
  }

  if (t_length != 0 && t_length != sequences[t_id]->data.size()) {
    rt::fail("[racon_tpu::Overlap::transmute] error: unequal lengths in "
                 "target and overlap file for target %s!\n",
                 sequences[t_id]->name.c_str());
  }
  t_length = sequences[t_id]->data.size();  // SAM carries no target length

  is_transmuted = true;
}

void Overlap::alignment_views(
    const std::vector<std::unique_ptr<Sequence>>& sequences, const char** q,
    uint32_t* q_len, const char** t, uint32_t* t_len) const {
  // Reverse-strand queries align their reverse complement over the mirrored
  // coordinate range (parity: src/overlap.cpp:192-197).
  if (!strand) {
    *q = sequences[q_id]->data.data() + q_begin;
  } else {
    *q = sequences[q_id]->reverse_complement.data() + (q_length - q_end);
  }
  *q_len = q_end - q_begin;
  *t = sequences[t_id]->data.data() + t_begin;
  *t_len = t_end - t_begin;
}

void Overlap::find_breaking_points(
    const std::vector<std::unique_ptr<Sequence>>& sequences,
    uint32_t window_length) {
  if (!is_transmuted) {
    rt::fail("[racon_tpu::Overlap::find_breaking_points] error: overlap "
                 "is not transmuted!\n");
  }
  if (!breaking_points.empty()) {
    return;
  }

  if (cigar.empty()) {
    const char *q, *t;
    uint32_t q_len, t_len;
    alignment_views(sequences, &q, &q_len, &t, &t_len);
    cigar = align_global_cigar(q, q_len, t, t_len);
  }

  find_breaking_points_from_cigar(window_length);
  std::string().swap(cigar);
}

void Overlap::find_breaking_points_from_cigar(uint32_t window_length) {
  // Window end positions on the target (inclusive), then the overlap end.
  // Parity: src/overlap.cpp:229-235.
  std::vector<int32_t> window_ends;
  for (uint32_t i = 0; i < t_end; i += window_length) {
    if (i > t_begin) {
      window_ends.emplace_back(static_cast<int32_t>(i) - 1);
    }
  }
  window_ends.emplace_back(static_cast<int32_t>(t_end) - 1);

  uint32_t w = 0;
  bool found_first = false;
  std::pair<uint32_t, uint32_t> first_match{0, 0}, last_match{0, 0};

  int32_t q_ptr = static_cast<int32_t>(strand ? (q_length - q_end) : q_begin) - 1;
  int32_t t_ptr = static_cast<int32_t>(t_begin) - 1;

  auto flush_window = [&]() {
    if (found_first) {
      breaking_points.emplace_back(first_match);
      breaking_points.emplace_back(last_match);
    }
    found_first = false;
    ++w;
  };

  for (uint32_t i = 0, j = 0; i < cigar.size(); ++i) {
    char op = cigar[i];
    if (op == 'M' || op == '=' || op == 'X') {
      uint32_t n = static_cast<uint32_t>(std::atoi(cigar.c_str() + j));
      j = i + 1;
      for (uint32_t k = 0; k < n; ++k) {
        ++q_ptr;
        ++t_ptr;
        if (!found_first) {
          found_first = true;
          first_match = {static_cast<uint32_t>(t_ptr),
                         static_cast<uint32_t>(q_ptr)};
        }
        last_match = {static_cast<uint32_t>(t_ptr) + 1,
                      static_cast<uint32_t>(q_ptr) + 1};
        if (t_ptr == window_ends[w]) {
          flush_window();
        }
      }
    } else if (op == 'I') {
      q_ptr += std::atoi(cigar.c_str() + j);
      j = i + 1;
    } else if (op == 'D' || op == 'N') {
      uint32_t n = static_cast<uint32_t>(std::atoi(cigar.c_str() + j));
      j = i + 1;
      for (uint32_t k = 0; k < n; ++k) {
        ++t_ptr;
        if (t_ptr == window_ends[w]) {
          flush_window();
        }
      }
    } else if (op == 'S' || op == 'H' || op == 'P') {
      j = i + 1;
    }
  }
}

}  // namespace rt
