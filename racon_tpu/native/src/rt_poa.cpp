#include "rt_poa.hpp"

#include <algorithm>
#include <cassert>
#include <cmath>
#include <limits>
#include <cstdint>
#include <cstdlib>
#include <numeric>

namespace rt {

namespace {
constexpr int32_t kNegInf = std::numeric_limits<int32_t>::min() / 4;
}

int32_t PoaGraph::new_column(double key) {
  col_keys_.push_back(key);
  col_members_.emplace_back();
  return static_cast<int32_t>(col_keys_.size()) - 1;
}

int32_t PoaGraph::new_node(char base, int32_t col) {
  PoaNode n;
  n.base = base;
  n.col = col;
  n.coverage = 0;
  nodes_.push_back(std::move(n));
  const int32_t id = static_cast<int32_t>(nodes_.size()) - 1;
  col_members_[col].push_back(id);
  return id;
}

void PoaGraph::add_or_bump_edge(int32_t src, int32_t dst, int64_t w) {
  for (int32_t e : nodes_[src].out_edges) {
    if (edges_[e].dst == dst) {
      edges_[e].weight += w;
      return;
    }
  }
  PoaEdge e{src, dst, w};
  edges_.push_back(e);
  const int32_t id = static_cast<int32_t>(edges_.size()) - 1;
  nodes_[src].out_edges.push_back(id);
  nodes_[dst].in_edges.push_back(id);
}

void PoaGraph::add_alignment(const PoaAlignment& alignment, const char* seq,
                             uint32_t len,
                             const std::vector<uint32_t>& weights) {
  if (len == 0) {
    return;
  }
  ++num_sequences_;

  if (alignment.empty()) {
    // Fresh source->sink chain (the window backbone). Integer column keys —
    // backbone column i gets key exactly i, which is what the key-range
    // subgraph filter relies on.
    double base_key = -1.0;
    for (double k : col_keys_) {
      base_key = std::max(base_key, k);
    }
    base_key = std::floor(base_key) + 1.0;
    int32_t prev = -1;
    for (uint32_t p = 0; p < len; ++p) {
      const int32_t node = new_node(seq[p], new_column(base_key + p));
      ++nodes_[node].coverage;
      if (prev != -1) {
        add_or_bump_edge(prev, node,
                         static_cast<int64_t>(weights[p - 1]) +
                             static_cast<int64_t>(weights[p]));
      }
      prev = node;
    }
    return;
  }

  // Seq position -> matched graph node (-1 = insertion, gets a new column).
  std::vector<int32_t> pos_node(len, -1);
  for (const auto& pr : alignment) {
    if (pr.second != -1 && pr.first != -1) {
      pos_node[pr.second] = pr.first;
    }
  }

  int32_t prev = -1;
  int32_t prev_pos = -1;
  uint32_t pos = 0;
  while (pos < len) {
    const char b = seq[pos];
    int32_t node;
    if (pos_node[pos] != -1) {
      const int32_t n = pos_node[pos];
      const int32_t col = nodes_[n].col;
      if (nodes_[n].base == b) {
        node = n;
      } else {
        node = -1;
        for (int32_t m : col_members_[col]) {
          if (nodes_[m].base == b) {
            node = m;
            break;
          }
        }
        if (node == -1) {
          node = new_node(b, col);  // column sibling == classic aligned ring
        }
      }
      ++pos;
    } else {
      // Insertion run [pos, run_end): fresh columns with keys strictly
      // between the previous path column and the next matched column.
      // `run_len` is the REMAINING run length (runs shrink as positions are
      // consumed one per loop iteration), so each new key subdivides the
      // residual interval and the run stays strictly increasing.
      uint32_t run_end = pos;
      while (run_end < len && pos_node[run_end] == -1) {
        ++run_end;
      }
      const uint32_t run_len = run_end - pos;
      double hi;
      if (run_end < len) {
        hi = col_keys_[nodes_[pos_node[run_end]].col];
      } else if (prev != -1) {
        hi = col_keys_[nodes_[prev].col] + 1.0;
      } else {
        double max_key = -1.0;
        for (double k : col_keys_) {
          max_key = std::max(max_key, k);
        }
        hi = max_key + static_cast<double>(run_len) + 1.0;
      }
      const double lo =
          prev != -1 ? col_keys_[nodes_[prev].col] : hi - run_len - 1.0;

      const double key = lo + (hi - lo) / (run_len + 1.0);
      node = new_node(b, new_column(key));
      ++pos;
    }

    ++nodes_[node].coverage;
    if (prev != -1) {
      add_or_bump_edge(prev, node,
                       static_cast<int64_t>(weights[prev_pos]) +
                           static_cast<int64_t>(weights[pos - 1]));
    }
    prev = node;
    prev_pos = static_cast<int32_t>(pos) - 1;
  }
}

std::vector<int32_t> PoaGraph::topo_order() const {
  std::vector<int32_t> order(nodes_.size());
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    const double ka = col_keys_[nodes_[a].col], kb = col_keys_[nodes_[b].col];
    if (ka != kb) {
      return ka < kb;
    }
    return a < b;
  });
  return order;
}

std::string PoaGraph::generate_consensus(
    std::vector<uint32_t>* coverages) const {
  std::string consensus;
  if (nodes_.empty()) {
    if (coverages) {
      coverages->clear();
    }
    return consensus;
  }

  const auto order = topo_order();
  std::vector<int64_t> score(nodes_.size(), 0);
  std::vector<int32_t> pred(nodes_.size(), -1);

  // Heaviest bundle: each node takes its best in-edge by
  // (edge weight, predecessor score).
  int32_t best_node = order[0];
  for (int32_t u : order) {
    int64_t best_w = -1, best_pred_score = -1;
    int32_t best_pred = -1;
    for (int32_t e : nodes_[u].in_edges) {
      const int64_t w = edges_[e].weight;
      const int64_t s = score[edges_[e].src];
      if (w > best_w || (w == best_w && s > best_pred_score)) {
        best_w = w;
        best_pred_score = s;
        best_pred = edges_[e].src;
      }
    }
    if (best_pred != -1) {
      score[u] = best_w + score[best_pred];
      pred[u] = best_pred;
    }
    if (score[u] > score[best_node]) {
      best_node = u;
    }
  }

  // Backward to a source along chosen predecessors, then forward from the
  // summit to a sink along the heaviest out-edges (branch completion
  // analogue: the consensus always spans source -> sink, so zero-weight
  // backbone-only stretches at window edges are retained for the trim stage
  // to judge; reference behavior: src/window.cpp:122-146).
  std::vector<int32_t> path;
  for (int32_t u = best_node; u != -1; u = pred[u]) {
    path.push_back(u);
  }
  std::reverse(path.begin(), path.end());

  int32_t u = best_node;
  while (!nodes_[u].out_edges.empty()) {
    int64_t best_w = -1, best_dst_score = -1;
    int32_t best_dst = -1;
    for (int32_t e : nodes_[u].out_edges) {
      const int64_t w = edges_[e].weight;
      const int64_t s = score[edges_[e].dst];
      if (w > best_w || (w == best_w && s > best_dst_score)) {
        best_w = w;
        best_dst_score = s;
        best_dst = edges_[e].dst;
      }
    }
    u = best_dst;
    path.push_back(u);
  }

  consensus.reserve(path.size());
  if (coverages) {
    coverages->clear();
    coverages->reserve(path.size());
  }
  for (int32_t v : path) {
    consensus += nodes_[v].base;
    if (coverages) {
      // Node coverage (paths through the chosen node itself) drives the
      // trim rule; measured better end-trimming than column-sum coverage
      // on every golden scenario.
      coverages->push_back(nodes_[v].coverage);
    }
  }
  return consensus;
}


namespace {

// DP + traceback core, templated on the score type (int16 when the score
// range allows, halving memory traffic). Returns the REVERSED alignment.
template <typename ScoreT>
PoaAlignment dp_and_traceback(const PoaGraph& graph, const char* seq,
                              uint32_t L, const std::vector<int32_t>& sub,
                              const std::vector<std::vector<int32_t>>& preds,
                              std::vector<ScoreT>& h, int8_t match_,
                              int8_t mismatch_, int8_t gap_) {
  const uint32_t S = static_cast<uint32_t>(sub.size());
  const size_t stride = L + 1;
  // No full-matrix fill: every subgraph row is written before any read (key
  // order == topological order); only the virtual start row needs values.
  h.resize(static_cast<size_t>(S + 1) * stride);

  for (uint32_t j = 0; j <= L; ++j) {
    h[j] = static_cast<ScoreT>(static_cast<int32_t>(j) * gap_);
  }

  for (uint32_t r = 1; r <= S; ++r) {
    const int32_t u = sub[r - 1];
    const char ub = graph.nodes()[u].base;
    ScoreT* __restrict row = h.data() + static_cast<size_t>(r) * stride;
    const auto& pr = preds[r - 1];

    // Diag/up pass over each predecessor row (vectorizable: row never
    // aliases a predecessor row — predecessors have strictly lower ranks),
    // then one sequential horizontal (gap-chain) pass.
    {
      const ScoreT* __restrict prow =
          pr.empty() ? h.data()
                     : h.data() + static_cast<size_t>(pr[0]) * stride;
      row[0] = static_cast<ScoreT>(prow[0] + gap_);
      for (uint32_t j = 1; j <= L; ++j) {
        const ScoreT diag = static_cast<ScoreT>(
            prow[j - 1] + (seq[j - 1] == ub ? match_ : mismatch_));
        const ScoreT up = static_cast<ScoreT>(prow[j] + gap_);
        row[j] = diag > up ? diag : up;
      }
    }
    for (size_t pi = 1; pi < pr.size(); ++pi) {
      const ScoreT* __restrict prow =
          h.data() + static_cast<size_t>(pr[pi]) * stride;
      if (static_cast<ScoreT>(prow[0] + gap_) > row[0]) {
        row[0] = static_cast<ScoreT>(prow[0] + gap_);
      }
      for (uint32_t j = 1; j <= L; ++j) {
        const ScoreT diag = static_cast<ScoreT>(
            prow[j - 1] + (seq[j - 1] == ub ? match_ : mismatch_));
        const ScoreT up = static_cast<ScoreT>(prow[j] + gap_);
        const ScoreT cand = diag > up ? diag : up;
        if (cand > row[j]) {
          row[j] = cand;
        }
      }
    }
    // Horizontal pass (inherently sequential gap chain).
    for (uint32_t j = 1; j <= L; ++j) {
      const ScoreT left = static_cast<ScoreT>(row[j - 1] + gap_);
      if (left > row[j]) {
        row[j] = left;
      }
    }
  }

  // End-node set: subgraph nodes without an out-edge inside the subgraph.
  // (An edge's dst is in the subgraph iff some preds entry references its
  // rank; recompute via a membership flag.)
  std::vector<uint8_t> in_sub(graph.num_nodes(), 0);
  for (int32_t u : sub) {
    in_sub[u] = 1;
  }
  std::vector<uint8_t> has_out(S, 0);
  for (uint32_t r = 0; r < S; ++r) {
    for (int32_t e : graph.nodes()[sub[r]].out_edges) {
      if (in_sub[graph.edges()[e].dst]) {
        has_out[r] = 1;
        break;
      }
    }
  }
  int32_t best_rank = -1;
  int64_t best_score = INT64_MIN;
  for (uint32_t r = 1; r <= S; ++r) {
    if (!has_out[r - 1]) {
      const int64_t sc = h[static_cast<size_t>(r) * stride + L];
      if (sc > best_score) {
        best_score = sc;
        best_rank = static_cast<int32_t>(r);
      }
    }
  }

  // Traceback by transition re-checking (H holds exact maxima, so any
  // satisfying transition lies on an optimal path). Priority: diag, up, left.
  int32_t r = best_rank;
  uint32_t j = L;
  PoaAlignment rev;
  while (r != 0 || j != 0) {
    if (r == 0) {
      rev.emplace_back(-1, static_cast<int32_t>(j) - 1);
      --j;
      continue;
    }
    const int32_t u = sub[r - 1];
    const char ub = graph.nodes()[u].base;
    const ScoreT* row = h.data() + static_cast<size_t>(r) * stride;
    const auto& pr = preds[r - 1];
    const int32_t cur = row[j];
    bool moved = false;

    const int32_t sc = j > 0 ? (seq[j - 1] == ub ? match_ : mismatch_) : 0;
    if (pr.empty()) {
      const ScoreT* prow = h.data();
      if (j > 0 && prow[j - 1] + sc == cur) {
        rev.emplace_back(u, static_cast<int32_t>(j) - 1);
        r = 0;
        --j;
        moved = true;
      } else if (prow[j] + gap_ == cur) {
        rev.emplace_back(u, -1);
        r = 0;
        moved = true;
      }
    } else {
      for (int32_t p : pr) {
        const ScoreT* prow = h.data() + static_cast<size_t>(p) * stride;
        if (j > 0 && prow[j - 1] + sc == cur) {
          rev.emplace_back(u, static_cast<int32_t>(j) - 1);
          r = p;
          --j;
          moved = true;
          break;
        }
      }
      if (!moved) {
        for (int32_t p : pr) {
          const ScoreT* prow = h.data() + static_cast<size_t>(p) * stride;
          if (prow[j] + gap_ == cur) {
            rev.emplace_back(u, -1);
            r = p;
            moved = true;
            break;
          }
        }
      }
    }
    if (!moved) {
      // Left move (insertion).
      rev.emplace_back(-1, static_cast<int32_t>(j) - 1);
      --j;
    }
  }
  return rev;
}

}  // namespace

PoaAlignment PoaAligner::align(const char* seq, uint32_t len,
                               const PoaGraph& graph, double key_lo,
                               double key_hi) {
  PoaAlignment result;
  if (len == 0 || graph.num_nodes() == 0) {
    return result;
  }

  // Subgraph: nodes whose column key lies in [key_lo, key_hi], topo order.
  sub_.clear();
  for (uint32_t i = 0; i < graph.num_nodes(); ++i) {
    const double k = graph.col_key(graph.nodes()[i].col);
    if (k >= key_lo && k <= key_hi) {
      sub_.push_back(static_cast<int32_t>(i));
    }
  }
  if (sub_.empty()) {
    return result;
  }
  std::sort(sub_.begin(), sub_.end(), [&](int32_t a, int32_t b) {
    const double ka = graph.col_key(graph.nodes()[a].col);
    const double kb = graph.col_key(graph.nodes()[b].col);
    if (ka != kb) {
      return ka < kb;
    }
    return a < b;
  });

  const uint32_t S = static_cast<uint32_t>(sub_.size());
  rank_of_.assign(graph.num_nodes(), 0);
  for (uint32_t r = 0; r < S; ++r) {
    rank_of_[sub_[r]] = static_cast<int32_t>(r) + 1;
  }

  // Predecessor ranks per subgraph node (edges from outside the key range
  // are cut, turning their targets into subgraph sources).
  std::vector<std::vector<int32_t>> preds(S);
  for (uint32_t r = 0; r < S; ++r) {
    for (int32_t e : graph.nodes()[sub_[r]].in_edges) {
      const int32_t pr = rank_of_[graph.edges()[e].src];
      if (pr > 0) {
        preds[r].push_back(pr);
      }
    }
  }

  const uint32_t L = len;
  // Score range bound: |score| <= (S + L + 2) * max |parameter|. When it
  // fits int16, the halved DP memory traffic nearly doubles throughput on
  // this bandwidth-bound loop.
  const int64_t max_param = std::max<int64_t>(
      {std::abs((int)match_), std::abs((int)mismatch_), std::abs((int)gap_)});
  const int64_t bound = static_cast<int64_t>(S + L + 2) * max_param;
  PoaAlignment rev;
  if (bound < 30000) {
    rev = dp_and_traceback<int16_t>(graph, seq, L, sub_, preds, h16_, match_,
                                    mismatch_, gap_);
  } else {
    rev = dp_and_traceback<int32_t>(graph, seq, L, sub_, preds, h_, match_,
                                    mismatch_, gap_);
  }
  result.assign(rev.rbegin(), rev.rend());
  return result;
}

}  // namespace rt
