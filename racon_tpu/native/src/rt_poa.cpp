#include "rt_poa.hpp"

#include <algorithm>
#include <atomic>
#include <cassert>
#include <cmath>
#include <cstdio>
#include <limits>
#include <cstdint>
#include <cstdlib>
#include <numeric>

#if defined(__AVX512BW__)
#include <immintrin.h>
#endif

namespace rt {

namespace {
constexpr int32_t kNegInf = std::numeric_limits<int32_t>::min() / 4;

// Env-gated (RT_POA_STATS=1) predecessor rank-distance telemetry. The
// device kernel keeps DP rows in a rank-keyed ring buffer, so a node whose
// predecessor lies more than RING_ROWS ranks back cannot run on the
// accelerator; this histogram, dumped at process exit, is how the ring
// size is chosen (and re-validated) against real workloads.
struct PredDistStats {
  std::atomic<uint64_t> edge_hist[16];   // per-edge log2 distance buckets
  std::atomic<uint64_t> align_hist[16];  // per-align(=layer) max distance
  std::atomic<uint64_t> edges{0}, aligns{0};
  std::atomic<int64_t> max_dist{0};
  std::atomic<int64_t> max_sub{0};  // largest subgraph (DP row count)
  const bool enabled = []() {
    const char* v = std::getenv("RT_POA_STATS");
    return v != nullptr && v[0] == '1';  // RT_POA_STATS=0 means off
  }();

  static int bucket(int64_t d) {
    int b = 0;
    while ((int64_t{1} << b) < d && b < 15) ++b;  // bucket b: d <= 2^b
    return b;
  }

  void record(int64_t d, std::atomic<uint64_t>* hist) {
    hist[bucket(d)].fetch_add(1, std::memory_order_relaxed);
  }

  ~PredDistStats() {
    if (!enabled || aligns.load() == 0) return;
    std::fprintf(stderr, "[rt_poa::stats] pred rank distance: edges=%llu "
                 "aligns=%llu max=%lld max_sub=%lld\n",
                 (unsigned long long)edges.load(),
                 (unsigned long long)aligns.load(),
                 (long long)max_dist.load(),
                 (long long)max_sub.load());
    for (int b = 0; b < 16; ++b) {
      const uint64_t e = edge_hist[b].load(), a = align_hist[b].load();
      if (e == 0 && a == 0) continue;
      std::fprintf(stderr, "[rt_poa::stats]   d<=%-6lld edges=%-10llu "
                   "align_max=%llu\n", (long long)(int64_t{1} << b),
                   (unsigned long long)e, (unsigned long long)a);
    }
  }
};
PredDistStats g_pred_stats;
}  // namespace

int32_t PoaGraph::new_column(double key) {
  col_keys_.push_back(key);
  col_members_.emplace_back();
  return static_cast<int32_t>(col_keys_.size()) - 1;
}

int32_t PoaGraph::new_node(char base, int32_t col) {
  PoaNode n;
  n.base = base;
  n.col = col;
  n.coverage = 0;
  nodes_.push_back(std::move(n));
  const int32_t id = static_cast<int32_t>(nodes_.size()) - 1;
  col_members_[col].push_back(id);
  return id;
}

void PoaGraph::add_or_bump_edge(int32_t src, int32_t dst, int64_t w) {
  for (int32_t e : nodes_[src].out_edges) {
    if (edges_[e].dst == dst) {
      edges_[e].weight += w;
      return;
    }
  }
  PoaEdge e{src, dst, w};
  edges_.push_back(e);
  const int32_t id = static_cast<int32_t>(edges_.size()) - 1;
  nodes_[src].out_edges.push_back(id);
  nodes_[dst].in_edges.push_back(id);
}

void PoaGraph::add_alignment(const PoaAlignment& alignment, const char* seq,
                             uint32_t len,
                             const std::vector<uint32_t>& weights) {
  if (len == 0) {
    return;
  }
  ++num_sequences_;

  if (alignment.empty()) {
    // Fresh source->sink chain (the window backbone). Integer column keys —
    // backbone column i gets key exactly i, which is what the key-range
    // subgraph filter relies on.
    double base_key = -1.0;
    for (double k : col_keys_) {
      base_key = std::max(base_key, k);
    }
    base_key = std::floor(base_key) + 1.0;
    int32_t prev = -1;
    for (uint32_t p = 0; p < len; ++p) {
      const int32_t node = new_node(seq[p], new_column(base_key + p));
      ++nodes_[node].coverage;
      if (prev != -1) {
        add_or_bump_edge(prev, node,
                         static_cast<int64_t>(weights[p - 1]) +
                             static_cast<int64_t>(weights[p]));
      }
      prev = node;
    }
    return;
  }

  // Seq position -> matched graph node (-1 = insertion, gets a new column).
  std::vector<int32_t> pos_node(len, -1);
  for (const auto& pr : alignment) {
    if (pr.second != -1 && pr.first != -1) {
      pos_node[pr.second] = pr.first;
    }
  }

  int32_t prev = -1;
  int32_t prev_pos = -1;
  uint32_t pos = 0;
  while (pos < len) {
    const char b = seq[pos];
    int32_t node;
    if (pos_node[pos] != -1) {
      const int32_t n = pos_node[pos];
      const int32_t col = nodes_[n].col;
      if (nodes_[n].base == b) {
        node = n;
      } else {
        node = -1;
        for (int32_t m : col_members_[col]) {
          if (nodes_[m].base == b) {
            node = m;
            break;
          }
        }
        if (node == -1) {
          node = new_node(b, col);  // column sibling == classic aligned ring
        }
      }
      ++pos;
    } else {
      // Insertion run [pos, run_end): fresh columns with keys strictly
      // between the previous path column and the next matched column.
      // `run_len` is the REMAINING run length (runs shrink as positions are
      // consumed one per loop iteration), so each new key subdivides the
      // residual interval and the run stays strictly increasing.
      uint32_t run_end = pos;
      while (run_end < len && pos_node[run_end] == -1) {
        ++run_end;
      }
      const uint32_t run_len = run_end - pos;
      double hi;
      if (run_end < len) {
        hi = col_keys_[nodes_[pos_node[run_end]].col];
      } else if (prev != -1) {
        hi = col_keys_[nodes_[prev].col] + 1.0;
      } else {
        double max_key = -1.0;
        for (double k : col_keys_) {
          max_key = std::max(max_key, k);
        }
        hi = max_key + static_cast<double>(run_len) + 1.0;
      }
      const double lo =
          prev != -1 ? col_keys_[nodes_[prev].col] : hi - run_len - 1.0;

      const double key = lo + (hi - lo) / (run_len + 1.0);
      node = new_node(b, new_column(key));
      ++pos;
    }

    ++nodes_[node].coverage;
    if (prev != -1) {
      add_or_bump_edge(prev, node,
                       static_cast<int64_t>(weights[prev_pos]) +
                           static_cast<int64_t>(weights[pos - 1]));
    }
    prev = node;
    prev_pos = static_cast<int32_t>(pos) - 1;
  }
}

std::vector<int32_t> PoaGraph::topo_order() const {
  std::vector<int32_t> order(nodes_.size());
  std::iota(order.begin(), order.end(), 0);
  std::sort(order.begin(), order.end(), [&](int32_t a, int32_t b) {
    const double ka = col_keys_[nodes_[a].col], kb = col_keys_[nodes_[b].col];
    if (ka != kb) {
      return ka < kb;
    }
    return a < b;
  });
  return order;
}

std::string PoaGraph::generate_consensus(
    std::vector<uint32_t>* coverages) const {
  std::string consensus;
  if (nodes_.empty()) {
    if (coverages) {
      coverages->clear();
    }
    return consensus;
  }

  const auto order = topo_order();
  std::vector<int64_t> score(nodes_.size(), 0);
  std::vector<int32_t> pred(nodes_.size(), -1);

  // Heaviest bundle: each node takes its best in-edge by
  // (edge weight, predecessor score).
  int32_t best_node = order[0];
  for (int32_t u : order) {
    int64_t best_w = -1, best_pred_score = -1;
    int32_t best_pred = -1;
    for (int32_t e : nodes_[u].in_edges) {
      const int64_t w = edges_[e].weight;
      const int64_t s = score[edges_[e].src];
      if (w > best_w || (w == best_w && s > best_pred_score)) {
        best_w = w;
        best_pred_score = s;
        best_pred = edges_[e].src;
      }
    }
    if (best_pred != -1) {
      score[u] = best_w + score[best_pred];
      pred[u] = best_pred;
    }
    if (score[u] > score[best_node]) {
      best_node = u;
    }
  }

  // Backward to a source along chosen predecessors, then forward from the
  // summit to a sink along the heaviest out-edges (branch completion
  // analogue: the consensus always spans source -> sink, so zero-weight
  // backbone-only stretches at window edges are retained for the trim stage
  // to judge; reference behavior: src/window.cpp:122-146).
  std::vector<int32_t> path;
  for (int32_t u = best_node; u != -1; u = pred[u]) {
    path.push_back(u);
  }
  std::reverse(path.begin(), path.end());

  int32_t u = best_node;
  while (!nodes_[u].out_edges.empty()) {
    int64_t best_w = -1, best_dst_score = -1;
    int32_t best_dst = -1;
    for (int32_t e : nodes_[u].out_edges) {
      const int64_t w = edges_[e].weight;
      const int64_t s = score[edges_[e].dst];
      if (w > best_w || (w == best_w && s > best_dst_score)) {
        best_w = w;
        best_dst_score = s;
        best_dst = edges_[e].dst;
      }
    }
    u = best_dst;
    path.push_back(u);
  }

  consensus.reserve(path.size());
  if (coverages) {
    coverages->clear();
    coverages->reserve(path.size());
  }
  for (int32_t v : path) {
    consensus += nodes_[v].base;
    if (coverages) {
      // Node coverage (paths through the chosen node itself) drives the
      // trim rule; measured better end-trimming than column-sum coverage
      // on every golden scenario.
      coverages->push_back(nodes_[v].coverage);
    }
  }
  return consensus;
}


namespace {

// Horizontal (gap-chain) pass of one DP row: row[j] = max over k<=j of
// row[k] + (j-k)*gap. In t-space (t[j] = row[j] - j*gap, ramp precomputed
// in jg) this is a prefix max. Generic version keeps the scalar chain.
template <typename ScoreT>
inline void horizontal_pass(ScoreT* __restrict row,
                            const ScoreT* __restrict /*jg*/, uint32_t L,
                            int8_t gap_) {
  for (uint32_t j = 1; j <= L; ++j) {
    const ScoreT left = static_cast<ScoreT>(row[j - 1] + gap_);
    if (left > row[j]) {
      row[j] = left;
    }
  }
}

#if defined(__AVX512BW__)
// int16 fast path: 32-lane blocks, prefix max inside the register via five
// shift-max steps (permutexvar word shifts), scalar carry across blocks.
inline void horizontal_pass(int16_t* __restrict row,
                            const int16_t* __restrict jg, uint32_t L,
                            int8_t gap_) {
  const uint32_t n = L + 1;
  const __m512i vneg = _mm512_set1_epi16(INT16_MIN);
  // shift-by-k index vectors: lane i reads lane i-k (masked to -inf below)
  __m512i idx[5];
  alignas(64) int16_t ibuf[32];
  for (int s = 0, k = 1; s < 5; ++s, k *= 2) {
    for (int i = 0; i < 32; ++i) {
      ibuf[i] = static_cast<int16_t>(i >= k ? i - k : 0);
    }
    idx[s] = _mm512_load_si512(ibuf);
  }
  const __mmask32 keep[5] = {
      static_cast<__mmask32>(~0x1u), static_cast<__mmask32>(~0x3u),
      static_cast<__mmask32>(~0xFu), static_cast<__mmask32>(~0xFFu),
      static_cast<__mmask32>(~0xFFFFu)};

  int16_t carry = INT16_MIN;
  uint32_t j = 0;
  for (; j + 32 <= n; j += 32) {
    __m512i t = _mm512_sub_epi16(_mm512_loadu_si512(row + j),
                                 _mm512_loadu_si512(jg + j));
    for (int s = 0; s < 5; ++s) {
      const __m512i sh = _mm512_mask_permutexvar_epi16(vneg, keep[s],
                                                       idx[s], t);
      t = _mm512_max_epi16(t, sh);
    }
    t = _mm512_max_epi16(t, _mm512_set1_epi16(carry));
    alignas(64) int16_t out[32];
    _mm512_store_si512(out, t);
    carry = out[31];
    _mm512_storeu_si512(
        row + j, _mm512_add_epi16(t, _mm512_loadu_si512(jg + j)));
  }
  // tail: scalar chain seeded with the carried prefix
  int16_t run = carry;
  for (; j < n; ++j) {
    const int16_t t = static_cast<int16_t>(row[j] - jg[j]);
    run = t > run ? t : run;
    row[j] = static_cast<int16_t>(run + jg[j]);
  }
  (void)gap_;
}
#endif

// DP + traceback core, templated on the score type (int16 when the score
// range allows, halving memory traffic). Returns the REVERSED alignment.
// preds come as CSR (poff/pdat) and scratch buffers are caller-owned so the
// hot path makes no allocations in steady state; per-letter match-profile
// rows turn the inner loop into pure ScoreT add/max streams (SPOA's SIMD
// engines use the same profile trick).
template <typename ScoreT>
PoaAlignment dp_and_traceback(const PoaGraph& graph, const char* seq,
                              uint32_t L, const std::vector<int32_t>& sub,
                              const int32_t* poff, const int32_t* pdat,
                              std::vector<ScoreT>& h,
                              std::vector<ScoreT>& prof,
                              std::vector<int32_t>& prof_of,
                              std::vector<uint8_t>& in_sub,
                              std::vector<uint8_t>& has_out, int8_t match_,
                              int8_t mismatch_, int8_t gap_) {
  const uint32_t S = static_cast<uint32_t>(sub.size());
  const size_t stride = L + 1;
  // No full-matrix fill: every subgraph row is written before any read (key
  // order == topological order); only the virtual start row needs values.
  // One extra row at the tail holds the j*gap ramp for the horizontal pass.
  h.resize(static_cast<size_t>(S + 2) * stride);
  ScoreT* __restrict jg = h.data() + static_cast<size_t>(S + 1) * stride;
  for (uint32_t j = 0; j <= L; ++j) {
    jg[j] = static_cast<ScoreT>(static_cast<int32_t>(j) * gap_);
  }

  for (uint32_t j = 0; j <= L; ++j) {
    h[j] = static_cast<ScoreT>(static_cast<int32_t>(j) * gap_);
  }

  // Match-profile rows, one per distinct letter in the subgraph.
  int32_t slot_of[256];
  std::fill(std::begin(slot_of), std::end(slot_of), -1);
  prof.clear();
  prof_of.resize(S);
  for (uint32_t r = 0; r < S; ++r) {
    const unsigned char ub =
        static_cast<unsigned char>(graph.nodes()[sub[r]].base);
    int32_t s = slot_of[ub];
    if (s < 0) {
      s = static_cast<int32_t>(prof.size() / stride);
      slot_of[ub] = s;
      prof.resize(prof.size() + stride);
      ScoreT* p = prof.data() + static_cast<size_t>(s) * stride;
      p[0] = 0;
      for (uint32_t j = 1; j <= L; ++j) {
        p[j] = static_cast<ScoreT>(
            seq[j - 1] == static_cast<char>(ub) ? match_ : mismatch_);
      }
    }
    prof_of[r] = s;
  }

  for (uint32_t r = 1; r <= S; ++r) {
    ScoreT* __restrict row = h.data() + static_cast<size_t>(r) * stride;
    const ScoreT* __restrict pf =
        prof.data() + static_cast<size_t>(prof_of[r - 1]) * stride;
    const int32_t pb = poff[r - 1];
    const int32_t pe = poff[r];

    // Diag/up pass over each predecessor row (vectorizable: row never
    // aliases a predecessor row — predecessors have strictly lower ranks),
    // then one sequential horizontal (gap-chain) pass.
    {
      const ScoreT* __restrict prow =
          pb == pe ? h.data()
                   : h.data() + static_cast<size_t>(pdat[pb]) * stride;
      row[0] = static_cast<ScoreT>(prow[0] + gap_);
      for (uint32_t j = 1; j <= L; ++j) {
        const ScoreT diag = static_cast<ScoreT>(prow[j - 1] + pf[j]);
        const ScoreT up = static_cast<ScoreT>(prow[j] + gap_);
        row[j] = diag > up ? diag : up;
      }
    }
    for (int32_t pi = pb + 1; pi < pe; ++pi) {
      const ScoreT* __restrict prow =
          h.data() + static_cast<size_t>(pdat[pi]) * stride;
      if (static_cast<ScoreT>(prow[0] + gap_) > row[0]) {
        row[0] = static_cast<ScoreT>(prow[0] + gap_);
      }
      for (uint32_t j = 1; j <= L; ++j) {
        const ScoreT diag = static_cast<ScoreT>(prow[j - 1] + pf[j]);
        const ScoreT up = static_cast<ScoreT>(prow[j] + gap_);
        const ScoreT cand = diag > up ? diag : up;
        if (cand > row[j]) {
          row[j] = cand;
        }
      }
    }
    // Horizontal pass. The gap chain row[j] = max(row[j], row[j-1]+g) is a
    // loop-carried dependency (~70% of DP time when scalar); in t-space
    // t[j] = row[j] - j*g it is a plain prefix max, computed per 32-lane
    // block with in-register shift-max steps plus a scalar carry.
    horizontal_pass(row, jg, L, gap_);
  }

  // End-node set: subgraph nodes without an out-edge inside the subgraph.
  // (An edge's dst is in the subgraph iff some preds entry references its
  // rank; recompute via a membership flag.)
  in_sub.assign(graph.num_nodes(), 0);
  for (int32_t u : sub) {
    in_sub[u] = 1;
  }
  has_out.assign(S, 0);
  for (uint32_t r = 0; r < S; ++r) {
    for (int32_t e : graph.nodes()[sub[r]].out_edges) {
      if (in_sub[graph.edges()[e].dst]) {
        has_out[r] = 1;
        break;
      }
    }
  }
  int32_t best_rank = -1;
  int64_t best_score = INT64_MIN;
  for (uint32_t r = 1; r <= S; ++r) {
    if (!has_out[r - 1]) {
      const int64_t sc = h[static_cast<size_t>(r) * stride + L];
      if (sc > best_score) {
        best_score = sc;
        best_rank = static_cast<int32_t>(r);
      }
    }
  }

  // Traceback by transition re-checking (H holds exact maxima, so any
  // satisfying transition lies on an optimal path). Priority: diag, up, left.
  int32_t r = best_rank;
  uint32_t j = L;
  PoaAlignment rev;
  while (r != 0 || j != 0) {
    if (r == 0) {
      rev.emplace_back(-1, static_cast<int32_t>(j) - 1);
      --j;
      continue;
    }
    const int32_t u = sub[r - 1];
    const char ub = graph.nodes()[u].base;
    const ScoreT* row = h.data() + static_cast<size_t>(r) * stride;
    const int32_t pb = poff[r - 1];
    const int32_t pe = poff[r];
    const int32_t cur = row[j];
    bool moved = false;

    const int32_t sc = j > 0 ? (seq[j - 1] == ub ? match_ : mismatch_) : 0;
    if (pb == pe) {
      const ScoreT* prow = h.data();
      if (j > 0 && prow[j - 1] + sc == cur) {
        rev.emplace_back(u, static_cast<int32_t>(j) - 1);
        r = 0;
        --j;
        moved = true;
      } else if (prow[j] + gap_ == cur) {
        rev.emplace_back(u, -1);
        r = 0;
        moved = true;
      }
    } else {
      for (int32_t pi = pb; pi < pe; ++pi) {
        const int32_t p = pdat[pi];
        const ScoreT* prow = h.data() + static_cast<size_t>(p) * stride;
        if (j > 0 && prow[j - 1] + sc == cur) {
          rev.emplace_back(u, static_cast<int32_t>(j) - 1);
          r = p;
          --j;
          moved = true;
          break;
        }
      }
      if (!moved) {
        for (int32_t pi = pb; pi < pe; ++pi) {
          const int32_t p = pdat[pi];
          const ScoreT* prow = h.data() + static_cast<size_t>(p) * stride;
          if (prow[j] + gap_ == cur) {
            rev.emplace_back(u, -1);
            r = p;
            moved = true;
            break;
          }
        }
      }
    }
    if (!moved) {
      // Left move (insertion).
      rev.emplace_back(-1, static_cast<int32_t>(j) - 1);
      --j;
    }
  }
  return rev;
}

}  // namespace

PoaAlignment PoaAligner::align(const char* seq, uint32_t len,
                               const PoaGraph& graph, double key_lo,
                               double key_hi) {
  PoaAlignment result;
  if (len == 0 || graph.num_nodes() == 0) {
    return result;
  }

  // Subgraph: nodes whose column key lies in [key_lo, key_hi], topo order.
  // Keys are cached in a flat array so the sort comparator is two loads,
  // not four indirections.
  keys_.resize(graph.num_nodes());
  sub_.clear();
  for (uint32_t i = 0; i < graph.num_nodes(); ++i) {
    const double k = graph.col_key(graph.nodes()[i].col);
    keys_[i] = k;
    if (k >= key_lo && k <= key_hi) {
      sub_.push_back(static_cast<int32_t>(i));
    }
  }
  if (sub_.empty()) {
    return result;
  }
  std::sort(sub_.begin(), sub_.end(), [&](int32_t a, int32_t b) {
    if (keys_[a] != keys_[b]) {
      return keys_[a] < keys_[b];
    }
    return a < b;
  });

  const uint32_t S = static_cast<uint32_t>(sub_.size());
  rank_of_.assign(graph.num_nodes(), 0);
  for (uint32_t r = 0; r < S; ++r) {
    rank_of_[sub_[r]] = static_cast<int32_t>(r) + 1;
  }

  // Predecessor ranks per subgraph node (edges from outside the key range
  // are cut, turning their targets into subgraph sources). CSR layout in
  // reused scratch — the nested-vector version spent more time in
  // allocator churn than in the DP at shallow depths.
  preds_off_.assign(S + 1, 0);
  for (uint32_t r = 0; r < S; ++r) {
    int32_t cnt = 0;
    for (int32_t e : graph.nodes()[sub_[r]].in_edges) {
      cnt += rank_of_[graph.edges()[e].src] > 0;
    }
    preds_off_[r + 1] = preds_off_[r] + cnt;
  }
  preds_dat_.resize(preds_off_[S]);
  for (uint32_t r = 0; r < S; ++r) {
    int32_t w = preds_off_[r];
    for (int32_t e : graph.nodes()[sub_[r]].in_edges) {
      const int32_t pr = rank_of_[graph.edges()[e].src];
      if (pr > 0) {
        preds_dat_[w++] = pr;
      }
    }
  }

  if (g_pred_stats.enabled) {
    int64_t amax = 0;
    for (uint32_t r = 0; r < S; ++r) {
      for (int32_t pi = preds_off_[r]; pi < preds_off_[r + 1]; ++pi) {
        const int64_t d = static_cast<int64_t>(r) + 1 - preds_dat_[pi];
        g_pred_stats.record(d, g_pred_stats.edge_hist);
        amax = std::max(amax, d);
      }
    }
    g_pred_stats.edges.fetch_add(preds_off_[S], std::memory_order_relaxed);
    g_pred_stats.aligns.fetch_add(1, std::memory_order_relaxed);
    g_pred_stats.record(amax, g_pred_stats.align_hist);
    int64_t cur = g_pred_stats.max_dist.load(std::memory_order_relaxed);
    while (amax > cur &&
           !g_pred_stats.max_dist.compare_exchange_weak(cur, amax)) {
    }
    int64_t cs = g_pred_stats.max_sub.load(std::memory_order_relaxed);
    while (S > cs &&
           !g_pred_stats.max_sub.compare_exchange_weak(cs, int64_t{S})) {
    }
  }

  const uint32_t L = len;
  // Score range bound: |score| <= (S + L + 2) * max |parameter|. When it
  // fits int16, the halved DP memory traffic nearly doubles throughput on
  // this bandwidth-bound loop.
  const int64_t max_param = std::max<int64_t>(
      {std::abs((int)match_), std::abs((int)mismatch_), std::abs((int)gap_)});
  const int64_t bound = static_cast<int64_t>(S + L + 2) * max_param;
  // t-space values in the horizontal prefix max reach bound + L*|gap|;
  // both must fit int16 for the fast path.
  const int64_t t_bound =
      bound + static_cast<int64_t>(L) * std::abs((int)gap_);
  PoaAlignment rev;
  if (t_bound < 32000) {
    rev = dp_and_traceback<int16_t>(graph, seq, L, sub_, preds_off_.data(),
                                    preds_dat_.data(), h16_, prof16_,
                                    prof_of_, in_sub_, has_out_, match_,
                                    mismatch_, gap_);
  } else {
    rev = dp_and_traceback<int32_t>(graph, seq, L, sub_, preds_off_.data(),
                                    preds_dat_.data(), h_, prof32_, prof_of_,
                                    in_sub_, has_out_, match_, mismatch_,
                                    gap_);
  }
  result.assign(rev.rbegin(), rev.rend());
  return result;
}

}  // namespace rt
