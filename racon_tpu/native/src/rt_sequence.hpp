// Sequence data model for racon-tpu's native host layer.
//
// Capability parity with the reference data model (see
// /root/reference/src/sequence.{hpp,cpp}): uppercased bases, optional PHRED
// quality (dropped when it is all-'!' i.e. carries no information,
// reference: src/sequence.cpp:34-42), lazy reverse complement + reversed
// quality (reference: src/sequence.cpp:49-84), and a field-freeing transmute
// used to keep peak RSS low on large datasets (reference:
// src/sequence.cpp:86-100).
//
// The implementation is new: it is a plain struct designed to hand out
// zero-copy views to the TPU batch packer rather than an OO class hierarchy.
#pragma once

#include <cstdint>
#include <memory>
#include <string>

namespace rt {

struct Sequence {
  std::string name;
  std::string data;                 // uppercased bases
  std::string quality;              // PHRED+33 chars, empty if absent/uninformative
  std::string reverse_complement;   // lazily built
  std::string reverse_quality;      // lazily built

  Sequence() = default;
  Sequence(const char* name_ptr, uint32_t name_len, const char* data_ptr,
           uint32_t data_len);
  Sequence(const char* name_ptr, uint32_t name_len, const char* data_ptr,
           uint32_t data_len, const char* qual_ptr, uint32_t qual_len);
  Sequence(std::string n, std::string d)
      : name(std::move(n)), data(std::move(d)) {}

  // Build reverse complement (A<->T, C<->G, other chars copied verbatim) and
  // reversed quality. Idempotent. Parity: src/sequence.cpp:49-84.
  void create_reverse_complement();

  // Free fields that later phases will never touch.
  // Parity: src/sequence.cpp:86-100.
  void transmute(bool keep_name, bool keep_data, bool need_reverse_data);
};

std::unique_ptr<Sequence> createSequence(const std::string& name,
                                         const std::string& data);

}  // namespace rt
