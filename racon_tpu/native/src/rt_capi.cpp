// C ABI for the racon-tpu native runtime, consumed by the Python driver via
// ctypes (no pybind11 dependency). Handles own all memory; strings returned
// to Python live inside the handle or in rt_free()-able buffers.
#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

#include "rt_align.hpp"
#include "rt_pipeline.hpp"
#include "rt_poa.hpp"
#include "rt_sequence.hpp"
#include "rt_window.hpp"

using rt::Pipeline;
using rt::PipelineParams;

namespace {

struct PipelineHandle {
  std::unique_ptr<Pipeline> pipeline;
  std::vector<std::pair<std::string, std::string>> results;
  bool stitched = false;
};

// Errors cross the C ABI as a thread-local message (the Python binding
// raises after every call that sets it); the CLI binary instead catches
// rt::Error at main() and exits 1 — the reference's observable behavior.
thread_local std::string g_error;

template <typename F>
auto guarded(F&& f, decltype(f()) fallback) -> decltype(f()) {
  g_error.clear();
  try {
    return f();
  } catch (const std::exception& e) {
    g_error = e.what();
    return fallback;
  }
}

template <typename F>
void guarded_void(F&& f) {
  guarded([&]() -> int { f(); return 0; }, 0);
}

}  // namespace

extern "C" {

const char* rt_last_error() {
  return g_error.empty() ? nullptr : g_error.c_str();
}

// ---------- standalone kernels -------------------------------------------

int64_t rt_edit_distance(const char* q, uint32_t q_len, const char* t,
                         uint32_t t_len) {
  return rt::edit_distance(q, q_len, t, t_len);
}

char* rt_align_cigar(const char* q, uint32_t q_len, const char* t,
                     uint32_t t_len) {
  return guarded([&]() -> char* {
    const std::string cigar = rt::align_global_cigar(q, q_len, t, t_len);
    char* out = static_cast<char*>(std::malloc(cigar.size() + 1));
    std::memcpy(out, cigar.c_str(), cigar.size() + 1);
    return out;
  }, nullptr);
}

void rt_free(void* p) { std::free(p); }

// One-shot window consensus (unit-test / differential-test hook).
// layers: concatenated bases; lens/begins/ends per layer; quals may be null
// (then pass has_qual = 0). Returns malloc'd consensus; *polished set to 1 if
// POA ran.
char* rt_window_consensus(const char* backbone, uint32_t backbone_len,
                          const char* backbone_qual, const char* layer_bases,
                          const char* layer_quals, const uint32_t* lens,
                          const uint32_t* begins, const uint32_t* ends,
                          uint32_t n_layers, int has_qual, int window_type,
                          int trim, int8_t match, int8_t mismatch, int8_t gap,
                          int* polished) {
  return guarded([&]() -> char* {
    std::string dummy(backbone_len, '!');
    auto window = rt::createWindow(
        0, 0, window_type == 0 ? rt::WindowType::kNGS : rt::WindowType::kTGS,
        backbone, backbone_len, backbone_qual ? backbone_qual : dummy.data(),
        backbone_len);
    uint64_t off = 0;
    for (uint32_t i = 0; i < n_layers; ++i) {
      window->add_layer(layer_bases + off, lens[i],
                        has_qual ? layer_quals + off : nullptr,
                        has_qual ? lens[i] : 0, begins[i], ends[i]);
      off += lens[i];
    }
    rt::PoaAligner aligner(match, mismatch, gap);
    const bool p = window->generate_consensus(aligner, trim != 0);
    if (polished) {
      *polished = p ? 1 : 0;
    }
    char* out = static_cast<char*>(std::malloc(window->consensus.size() + 1));
    std::memcpy(out, window->consensus.c_str(), window->consensus.size() + 1);
    return out;
  }, nullptr);
}

// ---------- pipeline ------------------------------------------------------

void* rt_pipeline_create(const char* sequences_path, const char* overlaps_path,
                         const char* target_path, int type,
                         uint32_t window_length, double quality_threshold,
                         double error_threshold, int trim, int8_t match,
                         int8_t mismatch, int8_t gap, uint32_t num_threads) {
  return guarded([&]() -> void* {
    PipelineParams params;
    params.type = type;
    params.window_length = window_length;
    params.quality_threshold = quality_threshold;
    params.error_threshold = error_threshold;
    params.trim = trim != 0;
    params.match = match;
    params.mismatch = mismatch;
    params.gap = gap;
    params.num_threads = num_threads;
    auto h = std::make_unique<PipelineHandle>();
    h->pipeline.reset(
        new Pipeline(sequences_path, overlaps_path, target_path, params));
    return h.release();
  }, nullptr);
}

void rt_pipeline_destroy(void* handle) {
  delete static_cast<PipelineHandle*>(handle);
}

void rt_pipeline_prepare(void* handle) {
  guarded_void(
      [&] { static_cast<PipelineHandle*>(handle)->pipeline->prepare(); });
}

uint64_t rt_pipeline_num_align_jobs(void* handle) {
  return static_cast<PipelineHandle*>(handle)->pipeline->num_align_jobs();
}

// Query/target views for alignment job k (zero-copy pointers + lengths).
void rt_pipeline_align_job(void* handle, uint64_t job, const char** q,
                           uint32_t* q_len, const char** t, uint32_t* t_len) {
  guarded_void([&] {
    static_cast<PipelineHandle*>(handle)->pipeline->align_job_views(
        job, q, q_len, t, t_len);
  });
}

// Bulk (q_len, t_len) export: out[2k] = q_len, out[2k+1] = t_len for every
// alignment job k.  One ABI crossing instead of num_align_jobs() of them —
// the Python driver re-reads the length table at each device-engine attempt.
void rt_pipeline_align_job_lengths(void* handle, uint32_t* out) {
  guarded_void([&] {
    auto* p = static_cast<PipelineHandle*>(handle)->pipeline.get();
    const uint64_t n = p->num_align_jobs();
    const char* q = nullptr;
    const char* t = nullptr;
    for (uint64_t k = 0; k < n; ++k) {
      p->align_job_views(k, &q, &out[2 * k], &t, &out[2 * k + 1]);
    }
  });
}

void rt_pipeline_set_job_cigar(void* handle, uint64_t job, const char* cigar) {
  guarded_void([&] {
    static_cast<PipelineHandle*>(handle)->pipeline->set_job_cigar(job, cigar);
  });
}

void rt_pipeline_align_jobs_cpu(void* handle) {
  guarded_void([&] {
    static_cast<PipelineHandle*>(handle)->pipeline->align_jobs_cpu();
  });
}

void rt_pipeline_build_windows(void* handle) {
  guarded_void([&] {
    static_cast<PipelineHandle*>(handle)->pipeline->build_windows();
  });
}

void rt_pipeline_initialize(void* handle) {
  guarded_void([&] {
    static_cast<PipelineHandle*>(handle)->pipeline->initialize();
  });
}

uint64_t rt_pipeline_num_windows(void* handle) {
  return static_cast<PipelineHandle*>(handle)->pipeline->num_windows();
}

// Window metadata: [n_total_seqs (incl. backbone), backbone_len, rank, type,
// total_layer_bytes, target_id]
void rt_pipeline_window_info(void* handle, uint64_t i, uint64_t* out6) {
  guarded_void([&] {
  const auto& w = static_cast<PipelineHandle*>(handle)->pipeline->window(i);
  out6[0] = w.sequences.size();
  out6[1] = w.sequences.front().second;
  out6[2] = w.rank;
  out6[3] = w.type == rt::WindowType::kTGS ? 1 : 0;
  uint64_t total = 0;
  for (size_t k = 1; k < w.sequences.size(); ++k) {
    total += w.sequences[k].second;
  }
  out6[4] = total;
  out6[5] = w.id;
  });
}

// Export a window's backbone and layers, layers sorted by begin
// position (the order the consensus phase consumes them in).
// weights are (PHRED - 33) when quality exists, 1 otherwise; backbone always
// has a quality view (dummy '!' when the target had none).
void rt_pipeline_window_export(void* handle, uint64_t i, uint8_t* bb_bases,
                               uint8_t* bb_weights, uint32_t* lens,
                               uint32_t* begins, uint32_t* ends,
                               uint8_t* bases_concat, uint8_t* weights_concat) {
  guarded_void([&] {
  const auto& w = static_cast<PipelineHandle*>(handle)->pipeline->window(i);
  const uint32_t bl = w.sequences.front().second;
  std::memcpy(bb_bases, w.sequences.front().first, bl);
  for (uint32_t k = 0; k < bl; ++k) {
    bb_weights[k] =
        static_cast<uint8_t>(w.qualities.front().first[k]) - uint8_t('!');
  }

  std::vector<uint32_t> order;
  for (uint32_t k = 1; k < w.sequences.size(); ++k) {
    order.push_back(k);
  }
  // Unstable sort, same comparator and element count as the host path's
  // layer ordering (rt_window.cpp) — introsort is deterministic for a
  // given input, so the device path sees layers in the identical order.
  std::sort(order.begin(), order.end(), [&](uint32_t a, uint32_t b) {
    return w.positions[a].first < w.positions[b].first;
  });

  uint64_t off = 0;
  for (size_t oi = 0; oi < order.size(); ++oi) {
    const uint32_t k = order[oi];
    const uint32_t len = w.sequences[k].second;
    lens[oi] = len;
    begins[oi] = w.positions[k].first;
    ends[oi] = w.positions[k].second;
    std::memcpy(bases_concat + off, w.sequences[k].first, len);
    if (w.qualities[k].first != nullptr) {
      for (uint32_t p = 0; p < len; ++p) {
        weights_concat[off + p] =
            static_cast<uint8_t>(w.qualities[k].first[p]) - uint8_t('!');
      }
    } else {
      std::memset(weights_concat + off, 1, len);
    }
    off += len;
  }
  });
}

int rt_pipeline_consensus_cpu_one(void* handle, uint64_t i) {
  return guarded(
      [&]() -> int {
        return static_cast<PipelineHandle*>(handle)
                       ->pipeline->consensus_cpu_one(i)
                   ? 1
                   : 0;
      },
      -1);
}

void rt_pipeline_consensus_cpu_all(void* handle) {
  guarded_void([&] {
    static_cast<PipelineHandle*>(handle)->pipeline->consensus_cpu_all();
  });
}

void rt_pipeline_set_consensus(void* handle, uint64_t i, const char* consensus,
                               uint32_t len, int polished) {
  guarded_void([&] {
    static_cast<PipelineHandle*>(handle)->pipeline->set_consensus(
        i, std::string(consensus, len), polished != 0);
  });
}

uint64_t rt_pipeline_stitch(void* handle, int drop_unpolished) {
  return guarded(
      [&]() -> uint64_t {
        auto* h = static_cast<PipelineHandle*>(handle);
        if (!h->stitched) {  // idempotent: repeats return cached results
          h->pipeline->stitch(drop_unpolished != 0, &h->results);
          h->stitched = true;
        }
        return h->results.size();
      },
      static_cast<uint64_t>(-1));
}

const char* rt_pipeline_result_name(void* handle, uint64_t i, uint64_t* len) {
  auto* h = static_cast<PipelineHandle*>(handle);
  *len = h->results[i].first.size();
  return h->results[i].first.c_str();
}

const char* rt_pipeline_result_data(void* handle, uint64_t i, uint64_t* len) {
  auto* h = static_cast<PipelineHandle*>(handle);
  *len = h->results[i].second.size();
  return h->results[i].second.c_str();
}

// Per-window consensus as currently stored (set by consensus_cpu_one or
// set_consensus); differential tests read the host result through this.
const char* rt_pipeline_get_consensus(void* handle, uint64_t i,
                                      uint64_t* len) {
  const auto& w = static_cast<PipelineHandle*>(handle)->pipeline->window(i);
  *len = w.consensus.size();
  return w.consensus.c_str();
}

int rt_pipeline_window_type(void* handle) {
  return static_cast<PipelineHandle*>(handle)->pipeline->window_type() ==
                 rt::WindowType::kTGS
             ? 1
             : 0;
}

}  // extern "C"
