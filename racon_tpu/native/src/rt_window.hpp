// Window: one POA consensus problem — a backbone slice of a target plus the
// read segments (layers) assigned to it, all as zero-copy views into the
// sequence store.
//
// Capability parity with the reference window
// (/root/reference/src/window.{hpp,cpp}): layer admission rules
// (src/window.cpp:42-63), the <3-sequences backbone shortcut (:68-71),
// layer ordering by begin position (:85-86), full-graph vs span-bounded
// alignment selection with the 1% offset rule (:88-107), quality-weighted
// graph updates (:110-119), and the TGS low-coverage end trim with the
// chimera warning (:125-146).
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "rt_poa.hpp"

namespace rt {

enum class WindowType { kNGS, kTGS };

struct Window {
  uint64_t id;     // target sequence id
  uint32_t rank;   // window ordinal within the target
  WindowType type;
  std::string consensus;

  // views (ptr, len); element 0 is the backbone
  std::vector<std::pair<const char*, uint32_t>> sequences;
  std::vector<std::pair<const char*, uint32_t>> qualities;  // ptr may be null
  std::vector<std::pair<uint32_t, uint32_t>> positions;     // begin, end (inclusive)

  Window(uint64_t id_, uint32_t rank_, WindowType type_, const char* backbone,
         uint32_t backbone_length, const char* quality,
         uint32_t quality_length);

  void add_layer(const char* sequence, uint32_t sequence_length,
                 const char* quality, uint32_t quality_length, uint32_t begin,
                 uint32_t end);

  // CPU oracle / fallback consensus via the host POA engine.
  // Returns true if POA actually ran (>= 2 layers), false when the backbone
  // was copied through unchanged.
  bool generate_consensus(PoaAligner& aligner, bool trim);
};

std::shared_ptr<Window> createWindow(uint64_t id, uint32_t rank,
                                     WindowType type, const char* backbone,
                                     uint32_t backbone_length,
                                     const char* quality,
                                     uint32_t quality_length);

}  // namespace rt
