#include "rt_sequence.hpp"

#include <cctype>

namespace rt {

Sequence::Sequence(const char* name_ptr, uint32_t name_len,
                   const char* data_ptr, uint32_t data_len)
    : name(name_ptr, name_len) {
  data.resize(data_len);
  for (uint32_t i = 0; i < data_len; ++i) {
    data[i] = static_cast<char>(std::toupper(static_cast<unsigned char>(data_ptr[i])));
  }
}

Sequence::Sequence(const char* name_ptr, uint32_t name_len,
                   const char* data_ptr, uint32_t data_len,
                   const char* qual_ptr, uint32_t qual_len)
    : Sequence(name_ptr, name_len, data_ptr, data_len) {
  // An all-'!' quality string carries zero information; treat it as absent
  // (parity: src/sequence.cpp:34-42).
  uint64_t quality_sum = 0;
  for (uint32_t i = 0; i < qual_len; ++i) {
    quality_sum += static_cast<uint8_t>(qual_ptr[i]) - static_cast<uint8_t>('!');
  }
  if (quality_sum > 0) {
    quality.assign(qual_ptr, qual_len);
  }
}

void Sequence::create_reverse_complement() {
  if (!reverse_complement.empty()) {
    return;
  }
  reverse_complement.reserve(data.size());
  for (auto it = data.rbegin(); it != data.rend(); ++it) {
    char c;
    switch (*it) {
      case 'A': c = 'T'; break;
      case 'T': c = 'A'; break;
      case 'C': c = 'G'; break;
      case 'G': c = 'C'; break;
      default: c = *it; break;
    }
    reverse_complement += c;
  }
  reverse_quality.assign(quality.rbegin(), quality.rend());
}

void Sequence::transmute(bool keep_name, bool keep_data,
                         bool need_reverse_data) {
  if (!keep_name) {
    std::string().swap(name);
  }
  if (need_reverse_data) {
    create_reverse_complement();
  }
  if (!keep_data) {
    std::string().swap(data);
    std::string().swap(quality);
  }
}

std::unique_ptr<Sequence> createSequence(const std::string& name,
                                         const std::string& data) {
  return std::unique_ptr<Sequence>(new Sequence(name, data));
}

}  // namespace rt
