#include "rt_error.hpp"
#include "rt_parsers.hpp"

#include <zlib.h>

#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace rt {

GzReader::GzReader(const std::string& path) : path_(path), buf_(1 << 20) {
  file_ = gzopen(path.c_str(), "rb");
  if (file_ == nullptr) {
    rt::fail("[racon_tpu::GzReader] error: unable to open file %s!\n",
                 path.c_str());
  }
  gzbuffer(static_cast<gzFile>(file_), 1 << 20);
}

GzReader::~GzReader() {
  if (file_ != nullptr) {
    gzclose(static_cast<gzFile>(file_));
  }
}

void GzReader::reset() {
  gzrewind(static_cast<gzFile>(file_));
  pos_ = len_ = 0;
  eof_ = false;
}

void GzReader::fill() {
  if (eof_) {
    return;
  }
  const int n =
      gzread(static_cast<gzFile>(file_), buf_.data(), static_cast<unsigned>(buf_.size()));
  if (n < 0) {
    rt::fail("[racon_tpu::GzReader] error: failed reading %s!\n",
                 path_.c_str());
  }
  pos_ = 0;
  len_ = static_cast<size_t>(n);
  if (n == 0) {
    eof_ = true;
  }
}

bool GzReader::getline(std::string& line) {
  line.clear();
  while (true) {
    if (pos_ >= len_) {
      fill();
      if (pos_ >= len_) {
        break;
      }
    }
    const char* start = buf_.data() + pos_;
    const char* nl =
        static_cast<const char*>(std::memchr(start, '\n', len_ - pos_));
    if (nl != nullptr) {
      line.append(start, nl - start);
      pos_ += (nl - start) + 1;
      if (!line.empty() && line.back() == '\r') {
        line.pop_back();
      }
      return true;
    }
    line.append(start, len_ - pos_);
    pos_ = len_;
  }
  if (!line.empty()) {
    if (line.back() == '\r') {
      line.pop_back();
    }
    return true;
  }
  return false;
}

static bool has_suffix(const std::string& src, const std::string& suffix) {
  return src.size() >= suffix.size() &&
         src.compare(src.size() - suffix.size(), suffix.size(), suffix) == 0;
}

bool sniff_sequence_format(const std::string& path, SeqFormat* fmt) {
  static const char* fasta_ext[] = {".fasta", ".fasta.gz", ".fna", ".fna.gz",
                                    ".fa", ".fa.gz"};
  static const char* fastq_ext[] = {".fastq", ".fastq.gz", ".fq", ".fq.gz"};
  for (const char* e : fasta_ext) {
    if (has_suffix(path, e)) {
      *fmt = SeqFormat::kFasta;
      return true;
    }
  }
  for (const char* e : fastq_ext) {
    if (has_suffix(path, e)) {
      *fmt = SeqFormat::kFastq;
      return true;
    }
  }
  return false;
}

bool sniff_overlap_format(const std::string& path, OvlFormat* fmt) {
  if (has_suffix(path, ".mhap") || has_suffix(path, ".mhap.gz")) {
    *fmt = OvlFormat::kMhap;
    return true;
  }
  if (has_suffix(path, ".paf") || has_suffix(path, ".paf.gz")) {
    *fmt = OvlFormat::kPaf;
    return true;
  }
  if (has_suffix(path, ".sam") || has_suffix(path, ".sam.gz")) {
    *fmt = OvlFormat::kSam;
    return true;
  }
  return false;
}

SequenceParser::SequenceParser(const std::string& path, SeqFormat fmt)
    : reader_(path), fmt_(fmt) {}

void SequenceParser::reset() {
  reader_.reset();
  pending_header_.clear();
}

bool SequenceParser::parse_one(std::vector<std::unique_ptr<Sequence>>& dst,
                               uint64_t* bytes) {
  std::string line;
  if (fmt_ == SeqFormat::kFasta) {
    std::string header;
    if (!pending_header_.empty()) {
      header.swap(pending_header_);
    } else {
      while (reader_.getline(line)) {
        if (!line.empty() && line[0] == '>') {
          header = line;
          break;
        }
      }
      if (header.empty()) {
        return false;
      }
    }
    std::string data;
    while (reader_.getline(line)) {
      if (!line.empty() && line[0] == '>') {
        pending_header_ = line;
        break;
      }
      data += line;
    }
    if (data.empty() && pending_header_.empty() && header.empty()) {
      return false;
    }
    // Name = first whitespace-delimited token after '>'.
    size_t name_end = header.find_first_of(" \t", 1);
    if (name_end == std::string::npos) {
      name_end = header.size();
    }
    dst.emplace_back(new Sequence(header.data() + 1,
                                  static_cast<uint32_t>(name_end - 1),
                                  data.data(), static_cast<uint32_t>(data.size())));
    *bytes += data.size();
    return true;
  }

  // FASTQ: strict 4-line records (multi-line FASTQ is handled by counting
  // sequence length against the '+' separator).
  std::string header;
  while (reader_.getline(line)) {
    if (!line.empty() && line[0] == '@') {
      header = line;
      break;
    }
  }
  if (header.empty()) {
    return false;
  }
  std::string data, qual;
  while (reader_.getline(line)) {
    if (!line.empty() && line[0] == '+') {
      break;
    }
    data += line;
  }
  while (qual.size() < data.size() && reader_.getline(line)) {
    qual += line;
  }
  if (qual.size() != data.size()) {
    rt::fail("[racon_tpu::SequenceParser] error: malformed FASTQ record "
                 "(quality length mismatch)!\n");
  }
  size_t name_end = header.find_first_of(" \t", 1);
  if (name_end == std::string::npos) {
    name_end = header.size();
  }
  dst.emplace_back(new Sequence(
      header.data() + 1, static_cast<uint32_t>(name_end - 1), data.data(),
      static_cast<uint32_t>(data.size()), qual.data(),
      static_cast<uint32_t>(qual.size())));
  *bytes += data.size() + qual.size();
  return true;
}

std::vector<std::unique_ptr<Sequence>> SequenceParser::parse(
    uint64_t max_bytes) {
  std::vector<std::unique_ptr<Sequence>> dst;
  uint64_t bytes = 0;
  while (parse_one(dst, &bytes)) {
    if (max_bytes != 0 && bytes >= max_bytes) {
      break;
    }
  }
  return dst;
}

OverlapParser::OverlapParser(const std::string& path, OvlFormat fmt)
    : reader_(path), fmt_(fmt) {}

void OverlapParser::reset() { reader_.reset(); }

static std::vector<std::string> split_tabs(const std::string& line) {
  std::vector<std::string> out;
  size_t start = 0;
  while (start <= line.size()) {
    size_t end = line.find('\t', start);
    if (end == std::string::npos) {
      out.emplace_back(line.substr(start));
      break;
    }
    out.emplace_back(line.substr(start, end - start));
    start = end + 1;
  }
  return out;
}

static std::vector<std::string> split_spaces(const std::string& line) {
  std::vector<std::string> out;
  size_t i = 0;
  while (i < line.size()) {
    while (i < line.size() && (line[i] == ' ' || line[i] == '\t')) {
      ++i;
    }
    size_t start = i;
    while (i < line.size() && line[i] != ' ' && line[i] != '\t') {
      ++i;
    }
    if (i > start) {
      out.emplace_back(line.substr(start, i - start));
    }
  }
  return out;
}

std::vector<std::unique_ptr<Overlap>> OverlapParser::parse(uint64_t max_bytes) {
  std::vector<std::unique_ptr<Overlap>> dst;
  uint64_t bytes = 0;
  std::string line;
  while ((max_bytes == 0 || bytes < max_bytes) && reader_.getline(line)) {
    bytes += line.size();
    if (line.empty()) {
      continue;
    }
    if (fmt_ == OvlFormat::kMhap) {
      // MHAP: A-id B-id jaccard shared-minmers A-rc A-begin A-end A-len
      //       B-rc B-begin B-end B-len (space or tab separated)
      auto f = split_spaces(line);
      if (f.size() < 12) {
        rt::fail("[racon_tpu::OverlapParser] error: malformed MHAP line!\n");
      }
      dst.push_back(Overlap::from_mhap(
          std::strtoull(f[0].c_str(), nullptr, 10),
          std::strtoull(f[1].c_str(), nullptr, 10), std::atof(f[2].c_str()),
          static_cast<uint32_t>(std::strtoul(f[3].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[4].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[5].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[6].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[7].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[8].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[9].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[10].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[11].c_str(), nullptr, 10))));
    } else if (fmt_ == OvlFormat::kPaf) {
      auto f = split_tabs(line);
      if (f.size() < 9) {
        rt::fail("[racon_tpu::OverlapParser] error: malformed PAF line!\n");
      }
      dst.push_back(Overlap::from_paf(
          f[0], static_cast<uint32_t>(std::strtoul(f[1].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[2].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[3].c_str(), nullptr, 10)),
          f[4][0], f[5],
          static_cast<uint32_t>(std::strtoul(f[6].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[7].c_str(), nullptr, 10)),
          static_cast<uint32_t>(std::strtoul(f[8].c_str(), nullptr, 10))));
    } else {
      if (line[0] == '@') {
        continue;  // header
      }
      auto f = split_tabs(line);
      if (f.size() < 11) {
        rt::fail("[racon_tpu::OverlapParser] error: malformed SAM line!\n");
      }
      dst.push_back(Overlap::from_sam(
          f[0], static_cast<uint32_t>(std::strtoul(f[1].c_str(), nullptr, 10)),
          f[2], static_cast<uint32_t>(std::strtoul(f[3].c_str(), nullptr, 10)),
          f[5]));
    }
  }
  return dst;
}

}  // namespace rt
