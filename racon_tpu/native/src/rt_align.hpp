// Host pairwise global alignment kernels.
//
// Capability parity with the reference's use of edlib
// (/root/reference/src/overlap.cpp:205-224: global NW alignment with a path,
// encoded as a standard CIGAR; /root/reference/test/racon_test.cpp:14-23:
// plain global edit distance as the accuracy metric).
//
// The implementation is new and self-contained:
//  * align_global_cigar — unit-cost banded Needleman-Wunsch with Ukkonen band
//    doubling and a 2-bit packed traceback, emitting standard "M/I/D" CIGAR
//    (I consumes query, D consumes target — SAM convention).
//  * edit_distance — Myers/Hyyro bit-parallel global Levenshtein distance
//    (distance only), used by tests and benchmarks.
#pragma once

#include <cstdint>
#include <string>

namespace rt {

// Global (NW) unit-cost alignment path as a standard CIGAR string.
// Handles empty inputs (pure I/D CIGARs).
std::string align_global_cigar(const char* q, uint32_t q_len, const char* t,
                               uint32_t t_len);

// Global (NW) Levenshtein distance, bit-parallel.
int64_t edit_distance(const char* q, uint32_t q_len, const char* t,
                      uint32_t t_len);

}  // namespace rt
