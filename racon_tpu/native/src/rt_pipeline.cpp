#include "rt_error.hpp"
#include "rt_pipeline.hpp"

#include "rt_align.hpp"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <future>
#include <unordered_map>

namespace rt {

namespace {
constexpr uint64_t kChunkSize = 1024ull * 1024 * 1024;  // 1 GiB
}

Pipeline::Pipeline(const std::string& sequences_path,
                   const std::string& overlaps_path,
                   const std::string& target_path,
                   const PipelineParams& params)
    : params_(params) {
  if (params_.type != 0 && params_.type != 1) {
    rt::fail("[racon_tpu::createPolisher] error: invalid polisher type!\n");
  }
  if (params_.window_length == 0) {
    rt::fail("[racon_tpu::createPolisher] error: invalid window length!\n");
  }

  SeqFormat sfmt, tfmt;
  OvlFormat ofmt;
  if (!sniff_sequence_format(sequences_path, &sfmt)) {
    rt::fail("[racon_tpu::createPolisher] error: file %s has unsupported "
                 "format extension (valid extensions: .fasta, .fasta.gz, "
                 ".fna, .fna.gz, .fa, .fa.gz, .fastq, .fastq.gz, .fq, "
                 ".fq.gz)!\n",
                 sequences_path.c_str());
  }
  if (!sniff_overlap_format(overlaps_path, &ofmt)) {
    rt::fail("[racon_tpu::createPolisher] error: file %s has unsupported "
                 "format extension (valid extensions: .mhap, .mhap.gz, .paf, "
                 ".paf.gz, .sam, .sam.gz)!\n",
                 overlaps_path.c_str());
  }
  if (!sniff_sequence_format(target_path, &tfmt)) {
    rt::fail("[racon_tpu::createPolisher] error: file %s has unsupported "
                 "format extension (valid extensions: .fasta, .fasta.gz, "
                 ".fna, .fna.gz, .fa, .fa.gz, .fastq, .fastq.gz, .fq, "
                 ".fq.gz)!\n",
                 target_path.c_str());
  }

  sparser_.reset(new SequenceParser(sequences_path, sfmt));
  tparser_.reset(new SequenceParser(target_path, tfmt));
  oparser_.reset(new OverlapParser(overlaps_path, ofmt));

  dummy_quality_.assign(params_.window_length, '!');
  pool_.reset(new ThreadPool(params_.num_threads));
  // One aligner per worker plus one for non-pool callers
  // (ThreadPool::this_thread_index maps them to slot n).
  for (uint32_t i = 0; i < pool_->num_threads() + 1; ++i) {
    aligners_.emplace_back(
        new PoaAligner(params_.match, params_.mismatch, params_.gap));
  }
}

void Pipeline::remove_invalid_overlaps(
    std::vector<std::unique_ptr<Overlap>>& overlaps, uint64_t begin,
    uint64_t end) {
  // Parity: src/polisher.cpp:285-309 — error threshold, self overlap, and
  // (kC) keep only the longest overlap per query group.
  for (uint64_t i = begin; i < end; ++i) {
    if (overlaps[i] == nullptr) {
      continue;
    }
    if (overlaps[i]->error > params_.error_threshold ||
        overlaps[i]->q_id == overlaps[i]->t_id) {
      overlaps[i].reset();
      continue;
    }
    if (params_.type == 0) {  // kC
      for (uint64_t j = i + 1; j < end; ++j) {
        if (overlaps[j] == nullptr) {
          continue;
        }
        if (overlaps[i]->length >= overlaps[j]->length) {
          overlaps[j].reset();
        } else {
          overlaps[i].reset();
          break;
        }
      }
    }
  }
}

void Pipeline::prepare() {
  if (!windows_.empty() || !sequences_.empty()) {
    // Benign (parity: src/polisher.cpp:192-196): repeat initialization is a
    // warning, not an error.
    std::fprintf(stderr,
                 "[racon_tpu::Pipeline::prepare] warning: already "
                 "initialized!\n");
    return;
  }

  // Targets, all at once (parity: src/polisher.cpp:200-208).
  sequences_ = tparser_->parse(0);
  targets_size_ = sequences_.size();
  if (targets_size_ == 0) {
    rt::fail(
        "[racon_tpu::Pipeline::initialize] error: empty target "
        "sequences set!\n");
  }

  std::unordered_map<std::string, uint64_t> name_to_id;
  std::unordered_map<uint64_t, uint64_t> id_to_id;
  for (uint64_t i = 0; i < targets_size_; ++i) {
    name_to_id[sequences_[i]->name + "t"] = i;
    id_to_id[i << 1 | 1] = i;
  }

  logger_.log("[racon_tpu::Pipeline::initialize] loaded target sequences");
  std::vector<bool> has_name(targets_size_, true);
  std::vector<bool> has_data(targets_size_, true);
  std::vector<bool> has_reverse_data(targets_size_, false);

  // Reads, chunked; reads that duplicate a target share its slot
  // (parity: src/polisher.cpp:226-265).
  uint64_t read_ordinal = 0, total_reads_length = 0;
  while (true) {
    auto reads = sparser_->parse(kChunkSize);
    if (reads.empty()) {
      break;
    }
    for (auto& read : reads) {
      total_reads_length += read->data.size();
      auto it = name_to_id.find(read->name + "t");
      if (it != name_to_id.end()) {
        if (read->data.size() != sequences_[it->second]->data.size() ||
            read->quality.size() != sequences_[it->second]->quality.size()) {
          rt::fail("[racon_tpu::Pipeline::initialize] error: duplicate "
                       "sequence %s with unequal data\n",
                       read->name.c_str());
        }
        name_to_id[read->name + "q"] = it->second;
        id_to_id[read_ordinal << 1 | 0] = it->second;
      } else {
        const uint64_t idx = sequences_.size();
        name_to_id[read->name + "q"] = idx;
        id_to_id[read_ordinal << 1 | 0] = idx;
        sequences_.push_back(std::move(read));
      }
      ++read_ordinal;
    }
  }
  if (read_ordinal == 0) {
    rt::fail("[racon_tpu::Pipeline::initialize] error: empty sequences "
                 "set!\n");
  }

  has_name.resize(sequences_.size(), false);
  has_data.resize(sequences_.size(), false);
  has_reverse_data.resize(sequences_.size(), false);

  logger_.log("[racon_tpu::Pipeline::initialize] loaded sequences");
  // Short reads get NGS windows (no trim), long reads TGS
  // (parity: src/polisher.cpp:277-278).
  window_type_ = static_cast<double>(total_reads_length) / read_ordinal <= 1000
                     ? WindowType::kNGS
                     : WindowType::kTGS;

  // Overlaps, chunked, with sequential per-query grouping
  // (parity: src/polisher.cpp:311-351).
  uint64_t group_begin = 0;
  while (true) {
    auto chunk = oparser_->parse(kChunkSize);
    if (chunk.empty()) {
      break;
    }
    for (auto& o : chunk) {
      o->transmute(sequences_, name_to_id, id_to_id);
      if (!o->is_valid) {
        continue;
      }
      // New query group boundary?
      if (!overlaps_.empty() && group_begin < overlaps_.size()) {
        // find first non-null in current group
        while (group_begin < overlaps_.size() &&
               overlaps_[group_begin] == nullptr) {
          ++group_begin;
        }
        if (group_begin < overlaps_.size() &&
            overlaps_[group_begin]->q_id != o->q_id) {
          remove_invalid_overlaps(overlaps_, group_begin, overlaps_.size());
          group_begin = overlaps_.size();
        }
      }
      overlaps_.push_back(std::move(o));
    }
  }
  remove_invalid_overlaps(overlaps_, group_begin, overlaps_.size());

  // Compact.
  {
    std::vector<std::unique_ptr<Overlap>> kept;
    kept.reserve(overlaps_.size());
    for (auto& o : overlaps_) {
      if (o != nullptr) {
        kept.push_back(std::move(o));
      }
    }
    overlaps_.swap(kept);
  }

  if (overlaps_.empty()) {
    rt::fail("[racon_tpu::Pipeline::initialize] error: empty overlap "
                 "set!\n");
  }

  for (const auto& o : overlaps_) {
    if (o->strand) {
      has_reverse_data[o->q_id] = true;
    } else {
      has_data[o->q_id] = true;
    }
  }

  // Per-sequence transmute (free unused fields, build reverse complements)
  // on the pool (parity: src/polisher.cpp:373-382).
  {
    std::vector<std::future<void>> futs;
    for (uint64_t i = 0; i < sequences_.size(); ++i) {
      futs.emplace_back(pool_->submit([this, &has_name, &has_data,
                                       &has_reverse_data, i] {
        sequences_[i]->transmute(has_name[i] || i < targets_size_,
                                 has_data[i] || i < targets_size_,
                                 has_reverse_data[i]);
      }));
    }
    for (auto& f : futs) {
      f.get();
    }
  }

  logger_.log("[racon_tpu::Pipeline::initialize] loaded overlaps");
  // Collect alignment jobs (overlaps without a CIGAR).
  for (size_t i = 0; i < overlaps_.size(); ++i) {
    if (overlaps_[i]->cigar.empty()) {
      align_jobs_.push_back(i);
    }
  }
}

void Pipeline::align_job_views(size_t job, const char** q, uint32_t* q_len,
                               const char** t, uint32_t* t_len) const {
  overlaps_[align_jobs_[job]]->alignment_views(sequences_, q, q_len, t, t_len);
}

void Pipeline::set_job_cigar(size_t job, std::string cigar) {
  overlaps_[align_jobs_[job]]->cigar = std::move(cigar);
}

void Pipeline::align_jobs_cpu() {
  std::vector<std::future<void>> futs;
  for (size_t job : align_jobs_) {
    Overlap* o = overlaps_[job].get();
    if (!o->cigar.empty()) {
      continue;  // device already served this one
    }
    futs.emplace_back(pool_->submit([this, o] {
      const char *q, *t;
      uint32_t q_len, t_len;
      o->alignment_views(sequences_, &q, &q_len, &t, &t_len);
      o->cigar = align_global_cigar(q, q_len, t, t_len);
    }));
  }
  // 20-bin progress bar over alignment jobs
  // (parity: src/polisher.cpp:476-487).
  const size_t step = futs.size() / 20;
  for (size_t i = 0; i < futs.size(); ++i) {
    futs[i].get();
    if (step != 0 && (i + 1) % step == 0 && (i + 1) / step < 20) {
      logger_.bar("[racon_tpu::Pipeline::initialize] aligning overlaps");
    }
  }
  if (step != 0) {
    logger_.bar("[racon_tpu::Pipeline::initialize] aligning overlaps");
  } else if (!futs.empty()) {
    logger_.log("[racon_tpu::Pipeline::initialize] aligned overlaps");
  }
}

void Pipeline::build_windows() {
  // Breaking-point walks on the pool (cheap CIGAR scans now that every
  // overlap has a CIGAR; parity: src/polisher.cpp:466-488).
  {
    std::vector<std::future<void>> futs;
    for (auto& o : overlaps_) {
      Overlap* op = o.get();
      futs.emplace_back(pool_->submit([this, op] {
        op->find_breaking_points(sequences_, params_.window_length);
      }));
    }
    for (auto& f : futs) {
      f.get();
    }
  }

  // Create windows per target (parity: src/polisher.cpp:388-403).
  std::vector<uint64_t> id_to_first_window_id(targets_size_ + 1, 0);
  for (uint64_t i = 0; i < targets_size_; ++i) {
    uint32_t k = 0;
    const auto& target = *sequences_[i];
    const uint32_t t_size = static_cast<uint32_t>(target.data.size());
    for (uint32_t j = 0; j < t_size; j += params_.window_length, ++k) {
      const uint32_t length = std::min(j + params_.window_length, t_size) - j;
      windows_.push_back(createWindow(
          i, k, window_type_, target.data.data() + j, length,
          target.quality.empty() ? dummy_quality_.data()
                                 : target.quality.data() + j,
          length));
    }
    id_to_first_window_id[i + 1] = id_to_first_window_id[i] + k;
  }

  targets_coverages_.assign(targets_size_, 0);

  // Distribute overlap pieces into windows (parity: src/polisher.cpp:407-461).
  for (auto& o : overlaps_) {
    ++targets_coverages_[o->t_id];
    const auto& sequence = sequences_[o->q_id];
    const auto& bp = o->breaking_points;

    for (size_t j = 0; j + 1 < bp.size(); j += 2) {
      if (bp[j + 1].second - bp[j].second <
          0.02 * params_.window_length) {
        continue;
      }

      if (!sequence->quality.empty() || !sequence->reverse_quality.empty()) {
        const auto& quality =
            o->strand ? sequence->reverse_quality : sequence->quality;
        double average_quality = 0;
        for (uint32_t k = bp[j].second; k < bp[j + 1].second; ++k) {
          average_quality += static_cast<uint32_t>(quality[k]) - 33;
        }
        average_quality /= bp[j + 1].second - bp[j].second;
        if (average_quality < params_.quality_threshold) {
          continue;
        }
      }

      const uint64_t window_id =
          id_to_first_window_id[o->t_id] + bp[j].first / params_.window_length;
      const uint32_t window_start =
          (bp[j].first / params_.window_length) * params_.window_length;

      const char* data = o->strand
                             ? sequence->reverse_complement.data() + bp[j].second
                             : sequence->data.data() + bp[j].second;
      const uint32_t data_length = bp[j + 1].second - bp[j].second;

      const char* quality =
          o->strand ? (sequence->reverse_quality.empty()
                           ? nullptr
                           : sequence->reverse_quality.data() + bp[j].second)
                    : (sequence->quality.empty()
                           ? nullptr
                           : sequence->quality.data() + bp[j].second);
      const uint32_t quality_length = quality == nullptr ? 0 : data_length;

      windows_[window_id]->add_layer(data, data_length, quality,
                                     quality_length,
                                     bp[j].first - window_start,
                                     bp[j + 1].first - window_start - 1);
    }
    o.reset();
  }
  overlaps_.clear();
  align_jobs_.clear();

  done_.assign(windows_.size(), 0);
  polished_.assign(windows_.size(), 0);

  logger_.log("[racon_tpu::Pipeline::initialize] transformed data into "
              "windows");
}

void Pipeline::initialize() {
  prepare();
  align_jobs_cpu();
  build_windows();
}

bool Pipeline::consensus_cpu_one(size_t i) {
  const bool polished = windows_[i]->generate_consensus(
      *aligners_[pool_->this_thread_index()], params_.trim);
  done_[i] = 1;
  polished_[i] = polished ? 1 : 0;
  return polished;
}

void Pipeline::consensus_cpu_all() {
  std::vector<std::future<void>> futs;
  for (size_t i = 0; i < windows_.size(); ++i) {
    if (done_[i]) {
      continue;
    }
    futs.emplace_back(pool_->submit([this, i] { consensus_cpu_one(i); }));
  }
  const size_t step = futs.size() / 20;
  for (size_t i = 0; i < futs.size(); ++i) {
    futs[i].get();
    if (step != 0 && (i + 1) % step == 0 && (i + 1) / step < 20) {
      logger_.bar("[racon_tpu::Pipeline::polish] generating consensus");
    }
  }
  if (step != 0) {
    logger_.bar("[racon_tpu::Pipeline::polish] generating consensus");
  } else if (!futs.empty()) {
    logger_.log("[racon_tpu::Pipeline::polish] generated consensus");
  }
}

void Pipeline::set_consensus(size_t i, std::string consensus, bool polished) {
  windows_[i]->consensus = std::move(consensus);
  done_[i] = 1;
  polished_[i] = polished ? 1 : 0;
}

void Pipeline::stitch(bool drop_unpolished_sequences,
                      std::vector<std::pair<std::string, std::string>>* dst) {
  if (stitched_) {
    rt::fail("[racon_tpu::Pipeline::stitch] error: windows already "
                 "consumed by a previous stitch!\n");
  }
  stitched_ = true;

  std::string polished_data;
  uint32_t num_polished_windows = 0;

  for (size_t i = 0; i < windows_.size(); ++i) {
    if (!done_[i]) {
      rt::fail("[racon_tpu::Pipeline::stitch] error: window %zu has no "
                   "consensus!\n",
                   i);
    }
    num_polished_windows += polished_[i] ? 1 : 0;
    polished_data += windows_[i]->consensus;

    if (i == windows_.size() - 1 || windows_[i + 1]->rank == 0) {
      const double polished_ratio =
          num_polished_windows / static_cast<double>(windows_[i]->rank + 1);

      if (!drop_unpolished_sequences || polished_ratio > 0) {
        std::string tags = params_.type == 1 ? "r" : "";
        tags += " LN:i:" + std::to_string(polished_data.size());
        tags += " RC:i:" + std::to_string(targets_coverages_[windows_[i]->id]);
        tags += " XC:f:" + std::to_string(polished_ratio);
        dst->emplace_back(sequences_[windows_[i]->id]->name + tags,
                          polished_data);
      }
      num_polished_windows = 0;
      polished_data.clear();
    }
    windows_[i].reset();
  }
}

}  // namespace rt
