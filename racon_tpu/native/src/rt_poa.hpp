// Partial-order alignment (POA) engine: sequence-to-graph global alignment,
// incremental graph construction, and heaviest-bundle consensus.
//
// Capability parity with the reference's use of vendored SPOA
// (spoa::AlignmentEngine::Create(kNW, m, x, g), Graph::AddAlignment with
// optional per-base quality weights, Graph::Subgraph + UpdateAlignment,
// Graph::GenerateConsensus(&coverages); call sites
// /root/reference/src/polisher.cpp:179-183 and
// /root/reference/src/window.cpp:65-149).
//
// The design is new and deliberately TPU-shaped: instead of SPOA's pointer
// graph with aligned-node rings, nodes live in *columns*. A column is an
// alignment slot holding at most one node per distinct base; the aligned-ring
// relation of classic POA is exactly column co-membership. Every column
// carries a strictly ordered fractional key; all edges point from lower to
// higher keys, so topological order is just a sort by key. This same
// column/key representation is what the JAX/Pallas batch POA kernel uses on
// device (racon_tpu/ops/poa.py), which keeps host fallback and device path
// semantically aligned.
//
// Subgraph extraction for span-bounded alignment (reference:
// src/window.cpp:98-107) becomes a key-range filter: backbone column i has
// key exactly i, so aligning a layer against backbone span [b, e] means
// aligning against all nodes whose column key lies in [b, e].
#pragma once

#include <cstdint>
#include <string>
#include <utility>
#include <vector>

namespace rt {

using PoaAlignment = std::vector<std::pair<int32_t, int32_t>>;  // (node, pos)

struct PoaNode {
  char base;
  int32_t col;          // column index
  uint32_t coverage;    // number of sequence paths through this node
  std::vector<int32_t> in_edges;   // edge ids
  std::vector<int32_t> out_edges;  // edge ids
};

struct PoaEdge {
  int32_t src, dst;
  int64_t weight;
};

class PoaGraph {
 public:
  PoaGraph() = default;

  // Incorporate `seq` along `alignment` (empty alignment = append the whole
  // sequence as a fresh source->sink chain, used for the backbone).
  // `weights` are per-base weights (PHRED quality - 33, or all 1 when the
  // sequence has no quality); an edge traversed between positions p-1 and p
  // gains w[p-1] + w[p].
  void add_alignment(const PoaAlignment& alignment, const char* seq,
                     uint32_t len, const std::vector<uint32_t>& weights);

  // Heaviest-bundle consensus. Every consensus base gets the chosen node's
  // own path coverage, consumed by the window trim rule (reference call
  // site: src/window.cpp:122-146). Deliberate deviation: spoa's summary
  // counts the whole aligned column; node-only coverage measured better
  // end-trimming on every golden scenario (docs/benchmarks.md), so the
  // trim threshold sees the support for the *chosen* base, not the column.
  std::string generate_consensus(std::vector<uint32_t>* coverages) const;

  uint32_t num_nodes() const { return static_cast<uint32_t>(nodes_.size()); }
  uint32_t num_sequences() const { return num_sequences_; }

  const std::vector<PoaNode>& nodes() const { return nodes_; }
  const std::vector<PoaEdge>& edges() const { return edges_; }
  double col_key(int32_t col) const { return col_keys_[col]; }
  const std::vector<std::vector<int32_t>>& col_members() const {
    return col_members_;
  }

  // Topologically sorted node ids (sorted by column key; nodes sharing a
  // column are mutually exclusive alternatives, so their relative order is
  // free).
  std::vector<int32_t> topo_order() const;

 private:
  friend class PoaAligner;
  int32_t new_column(double key);
  int32_t new_node(char base, int32_t col);
  void add_or_bump_edge(int32_t src, int32_t dst, int64_t w);

  std::vector<PoaNode> nodes_;
  std::vector<PoaEdge> edges_;
  std::vector<double> col_keys_;
  std::vector<std::vector<int32_t>> col_members_;
  uint32_t num_sequences_ = 0;
};

// Global (kNW) sequence-to-graph aligner with linear gap penalty.
// One instance per worker thread; DP buffers are reused across calls
// (reference analogue: per-thread spoa::AlignmentEngine,
// src/polisher.cpp:179-183).
class PoaAligner {
 public:
  PoaAligner(int8_t match, int8_t mismatch, int8_t gap)
      : match_(match), mismatch_(mismatch), gap_(gap) {}

  // Align seq against the subgraph of nodes whose column key lies in
  // [key_lo, key_hi]. Pass -inf/+inf bounds for a full-graph alignment.
  // Returned pairs reference full-graph node ids.
  PoaAlignment align(const char* seq, uint32_t len, const PoaGraph& graph,
                     double key_lo, double key_hi);

 private:
  int8_t match_, mismatch_, gap_;
  std::vector<int32_t> h_;       // (S+1) x (L+1) scores (wide-range fallback)
  std::vector<int16_t> h16_;     // narrow-range fast path
  std::vector<int32_t> sub_;     // subgraph node ids in topo order
  std::vector<int32_t> rank_of_; // node id -> rank (1-based), 0 = absent
  // Steady-state scratch (no per-call allocation on the hot path):
  std::vector<int32_t> preds_off_;  // CSR offsets into preds_dat_, size S+1
  std::vector<int32_t> preds_dat_;  // predecessor ranks, flat
  std::vector<int16_t> prof16_;     // per-letter match-profile rows
  std::vector<int32_t> prof32_;
  std::vector<int32_t> prof_of_;    // rank -> profile row index
  std::vector<double> keys_;        // node id -> column key (sort cache)
  std::vector<uint8_t> in_sub_, has_out_;
};

}  // namespace rt
