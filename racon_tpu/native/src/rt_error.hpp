// Error type for the native runtime. Library consumers (the ctypes ABI)
// surface these as Python exceptions; the CLI binary catches at main() and
// exits 1 with the message — preserving the reference's observable
// stderr/exit behavior (the reference exits inline:
// e.g. /root/reference/src/polisher.cpp:65-71, overlap.cpp:148-153).
#pragma once

#include <cstdarg>
#include <cstdio>
#include <stdexcept>
#include <string>

namespace rt {

class Error : public std::runtime_error {
 public:
  explicit Error(std::string msg) : std::runtime_error(std::move(msg)) {}
};

// printf-style constructor helper.
[[noreturn]] inline void fail(const char* fmt, ...) {
  char buf[1024];
  va_list args;
  va_start(args, fmt);
  std::vsnprintf(buf, sizeof(buf), fmt, args);
  va_end(args);
  throw Error(buf);
}

}  // namespace rt
