// Polishing pipeline orchestrator: the native runtime that parses inputs,
// filters overlaps, aligns them, slices targets into windows, distributes
// read segments, runs (or delegates) per-window POA consensus, and stitches
// polished contigs.
//
// Capability parity with the reference orchestrator
// (/root/reference/src/polisher.{hpp,cpp}): same two-phase
// initialize -> polish flow (src/polisher.cpp:190-464, 490-547), same overlap
// filtering rules (error threshold, self-overlaps, kC longest-per-query;
// :285-309), same window admission rules (2% span, average quality;
// :415-433), same provenance tags on output (:521-524).
//
// The accelerator seam is *phase-granular* instead of subclass-virtual: the
// two hot phases (overlap alignment, window consensus) are exposed as job
// exports + result imports so the TPU driver (Python/JAX) can claim batches
// and the host transparently finishes whatever the device rejected — the same
// graceful-degradation lattice the reference implements in
// src/cuda/cudapolisher.cpp:204-213,354-378.
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <vector>

#include "rt_logger.hpp"
#include "rt_overlap.hpp"
#include "rt_parsers.hpp"
#include "rt_poa.hpp"
#include "rt_sequence.hpp"
#include "rt_threadpool.hpp"
#include "rt_window.hpp"

namespace rt {

struct PipelineParams {
  int type = 0;  // 0 = kC (polish / keep-longest correction), 1 = kF
  uint32_t window_length = 500;
  double quality_threshold = 10.0;
  double error_threshold = 0.3;
  bool trim = true;
  int8_t match = 3;
  int8_t mismatch = -5;
  int8_t gap = -4;
  uint32_t num_threads = 1;
};

class Pipeline {
 public:
  // Exits with a reference-compatible message on unsupported extensions or
  // invalid parameters (parity: src/polisher.cpp:57-135).
  Pipeline(const std::string& sequences_path, const std::string& overlaps_path,
           const std::string& target_path, const PipelineParams& params);

  ~Pipeline() { logger_.total("[racon_tpu::Pipeline::] total ="); }

  // ---- phase 1: data preparation -----------------------------------------
  // Parse + dedup + transmute + filter; stops right before overlap
  // alignment. Parity: src/polisher.cpp:200-382.
  void prepare();

  // Overlaps still lacking a CIGAR (alignment jobs for the device).
  size_t num_align_jobs() const { return align_jobs_.size(); }
  void align_job_views(size_t job, const char** q, uint32_t* q_len,
                       const char** t, uint32_t* t_len) const;
  // Install a device-produced CIGAR for job k (marks it done).
  void set_job_cigar(size_t job, std::string cigar);
  // Host fallback: align every remaining CIGAR-less job on the thread pool.
  void align_jobs_cpu();

  // Breaking-point walks + window creation + layer distribution.
  // Parity: src/polisher.cpp:388-461. Frees overlaps.
  void build_windows();

  // prepare + align_jobs_cpu + build_windows (the pure-CPU initialize()).
  void initialize();

  // ---- phase 2: consensus -------------------------------------------------
  size_t num_windows() const { return windows_.size(); }
  const Window& window(size_t i) const { return *windows_[i]; }

  // Host POA for one window / all unfinished windows (thread pool).
  bool consensus_cpu_one(size_t i);
  void consensus_cpu_all();

  // Install a device-produced consensus for window i.
  void set_consensus(size_t i, std::string consensus, bool polished);
  bool has_consensus(size_t i) const { return done_[i] != 0; }

  // Ordered stitch into polished sequences with LN/RC/XC provenance tags.
  // Parity: src/polisher.cpp:505-537.
  void stitch(bool drop_unpolished_sequences,
              std::vector<std::pair<std::string, std::string>>* dst);

  const PipelineParams& params() const { return params_; }
  WindowType window_type() const { return window_type_; }

 private:
  void remove_invalid_overlaps(std::vector<std::unique_ptr<Overlap>>& overlaps,
                               uint64_t begin, uint64_t end);

  PipelineParams params_;
  std::unique_ptr<SequenceParser> sparser_, tparser_;
  std::unique_ptr<OverlapParser> oparser_;

  std::vector<std::unique_ptr<Sequence>> sequences_;
  uint64_t targets_size_ = 0;
  WindowType window_type_ = WindowType::kTGS;
  std::string dummy_quality_;

  std::vector<std::unique_ptr<Overlap>> overlaps_;
  std::vector<size_t> align_jobs_;  // overlap indices lacking a CIGAR

  std::vector<std::shared_ptr<Window>> windows_;
  bool stitched_ = false;
  std::vector<uint8_t> done_;      // consensus present
  std::vector<uint8_t> polished_;  // POA actually ran
  std::vector<uint64_t> targets_coverages_;

  std::vector<std::unique_ptr<PoaAligner>> aligners_;  // one per thread
  Logger logger_;
  // Declared last: destroyed first, so an exception-abandoned task queue
  // drains (and its tasks' member references stay valid) before any other
  // member is torn down.
  std::unique_ptr<ThreadPool> pool_;
};

}  // namespace rt
