#include "rt_error.hpp"
#include "rt_align.hpp"

#include <algorithm>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <vector>

namespace rt {

namespace {

constexpr int32_t kInf = std::numeric_limits<int32_t>::max() / 4;
constexpr uint64_t kHigh = 1ull << 63;

// Append `count` copies of `op` to a CIGAR under construction (run-length).
void push_op(std::string& cigar, char op, uint32_t count) {
  if (count == 0) {
    return;
  }
  cigar += std::to_string(count);
  cigar += op;
}

// Run-length encode reversed op characters into a forward CIGAR.
std::string cigar_from_reversed_ops(const std::string& rev_ops) {
  std::string cigar;
  uint32_t run = 0;
  char run_op = 0;
  for (auto it = rev_ops.rbegin(); it != rev_ops.rend(); ++it) {
    if (*it == run_op) {
      ++run;
    } else {
      push_op(cigar, run_op, run);
      run_op = *it;
      run = 1;
    }
  }
  push_op(cigar, run_op, run);
  return cigar;
}

// One Myers/Hyyro bit-parallel block step (64 rows of one DP column).
// Updates vp/vn in place; returns the horizontal delta out of the block's
// bottom row.
inline int myers_block_step(uint64_t eq, uint64_t& vp, uint64_t& vn,
                            int hin) {
  const uint64_t pv = vp, mv = vn;
  const uint64_t xv = eq | mv;
  if (hin < 0) {
    eq |= 1;
  }
  const uint64_t xh = (((eq & pv) + pv) ^ pv) | eq;
  uint64_t ph = mv | ~(xh | pv);
  uint64_t mh = pv & xh;
  int hout = 0;
  if (ph & kHigh) {
    hout = 1;
  } else if (mh & kHigh) {
    hout = -1;
  }
  ph <<= 1;
  mh <<= 1;
  if (hin < 0) {
    mh |= 1;
  } else if (hin > 0) {
    ph |= 1;
  }
  vp = mh | ~(xv | ph);
  vn = ph & xv;
  return hout;
}

}  // namespace

namespace {
std::string myers_banded_cigar(const char* q, uint32_t n, const char* t,
                               uint32_t m, int64_t dist);
std::string scalar_banded_cigar(const char* q, uint32_t q_len, const char* t,
                                uint32_t t_len, int64_t dist_exact);
}  // namespace

std::string align_global_cigar(const char* q, uint32_t q_len, const char* t,
                               uint32_t t_len) {
  if (q_len == 0 || t_len == 0) {
    std::string cigar;
    push_op(cigar, 'D', t_len);
    push_op(cigar, 'I', q_len);
    return cigar;
  }

  // One bit-parallel distance pass first: the exact distance gives an exact
  // band, so the path pass runs exactly once with no retries.
  const int64_t dist_exact = edit_distance(q, q_len, t, t_len);

  // Large problems: banded block-Myers with popcount traceback
  // (edlib-class throughput). Small problems: plain banded scalar DP.
  if (static_cast<uint64_t>(q_len) * t_len > (1ull << 22)) {
    std::string cigar = myers_banded_cigar(q, q_len, t, t_len, dist_exact);
    if (!cigar.empty()) {
      return cigar;
    }
    // verification failed (shouldn't happen): fall through to scalar DP
  }
  return scalar_banded_cigar(q, q_len, t, t_len, dist_exact);
}

namespace {

// Banded unit-cost NW over diagonals d = j - i, d in [dmin, dmax].
// Traceback moves: 0 = diag (M), 1 = left (D, consumes target),
// 2 = up (I, consumes query). Directions are packed 4-per-byte.
std::string scalar_banded_cigar(const char* q, uint32_t q_len, const char* t,
                                uint32_t t_len, int64_t dist_exact) {
  const int64_t diff = static_cast<int64_t>(t_len) - static_cast<int64_t>(q_len);
  int64_t k = std::max<int64_t>(1, dist_exact);
  const int64_t k_cap =
      static_cast<int64_t>(std::max(q_len, t_len)) + 1;

  std::vector<int32_t> prev_row, cur_row;
  std::vector<uint8_t> tb;

  while (true) {
    const int64_t dmin = std::min<int64_t>(0, diff) - k;
    const int64_t dmax = std::max<int64_t>(0, diff) + k;
    const int64_t width = dmax - dmin + 1;

    // Traceback storage: (q_len + 1) rows x width diagonals, 2 bits each.
    const size_t tb_bytes =
        (static_cast<size_t>(q_len + 1) * static_cast<size_t>(width) + 3) / 4;
    if (tb_bytes > (3ull << 30)) {
      rt::fail("[racon_tpu::align_global_cigar] error: alignment of "
                   "%u x %u exceeds memory budget!\n",
                   q_len, t_len);
    }
    tb.assign(tb_bytes, 0);
    prev_row.assign(width, kInf);
    cur_row.assign(width, kInf);

    auto set_tb = [&](uint32_t i, int64_t w, uint8_t move) {
      const size_t idx = static_cast<size_t>(i) * width + w;
      tb[idx >> 2] |= move << ((idx & 3) << 1);
    };

    // Row 0: D[0][j] = j for j in band.
    for (int64_t w = 0; w < width; ++w) {
      const int64_t j = dmin + w;  // i == 0
      if (j >= 0 && j <= static_cast<int64_t>(t_len)) {
        prev_row[w] = static_cast<int32_t>(j);
        if (j > 0) {
          set_tb(0, w, 1);
        }
      }
    }

    for (uint32_t i = 1; i <= q_len; ++i) {
      std::fill(cur_row.begin(), cur_row.end(), kInf);
      const int64_t j_lo = std::max<int64_t>(0, dmin + i);
      const int64_t j_hi = std::min<int64_t>(t_len, dmax + i);
      for (int64_t j = j_lo; j <= j_hi; ++j) {
        const int64_t w = j - i - dmin;
        int32_t best;
        uint8_t move;
        if (j == 0) {
          best = static_cast<int32_t>(i);
          move = 2;
        } else {
          // Diagonal (same w in previous row).
          const int32_t sub =
              prev_row[w] == kInf
                  ? kInf
                  : prev_row[w] + (q[i - 1] == t[j - 1] ? 0 : 1);
          best = sub;
          move = 0;
          // Left: consume target, w-1 in the same row.
          if (w > 0 && cur_row[w - 1] != kInf && cur_row[w - 1] + 1 < best) {
            best = cur_row[w - 1] + 1;
            move = 1;
          }
          // Up: consume query, w+1 in the previous row.
          if (w + 1 < width && prev_row[w + 1] != kInf &&
              prev_row[w + 1] + 1 < best) {
            best = prev_row[w + 1] + 1;
            move = 2;
          }
        }
        cur_row[w] = best;
        set_tb(i, w, move);
      }
      prev_row.swap(cur_row);
    }

    const int64_t w_final = diff - dmin;
    const int32_t dist =
        (w_final >= 0 && w_final < width) ? prev_row[w_final] : kInf;

    // Ukkonen criterion: a distance within the band radius is optimal.
    if (dist <= k || k >= k_cap) {
      std::string rev_ops;
      rev_ops.reserve(q_len + t_len);
      uint32_t i = q_len;
      int64_t j = t_len;
      while (i > 0 || j > 0) {
        const int64_t w = j - i - dmin;
        const size_t idx = static_cast<size_t>(i) * width + w;
        const uint8_t move = (tb[idx >> 2] >> ((idx & 3) << 1)) & 3;
        if (i > 0 && j > 0 && move == 0) {
          rev_ops += 'M';
          --i;
          --j;
        } else if (j > 0 && move == 1) {
          rev_ops += 'D';
          --j;
        } else {
          rev_ops += 'I';
          --i;
        }
      }

      return cigar_from_reversed_ops(rev_ops);
    }
    k *= 2;
  }
}

// Banded block-Myers (Hyyro) with per-column VP/VN snapshots and a
// popcount-based traceback. Band half-width k = dist + 65: the optimal path
// deviates at most `dist` diagonals from the endpoint diagonals, so it stays
// a full block away from the band edge, where the +1 boundary approximation
// (an overestimate, hence never winning a min) lives.
std::string myers_banded_cigar(const char* q, uint32_t n, const char* t,
                               uint32_t m, int64_t dist) {
  const int64_t k = dist + 65;
  const int64_t diff = static_cast<int64_t>(m) - static_cast<int64_t>(n);
  const int64_t dmin = std::min<int64_t>(0, diff) - k;
  const int64_t dmax = std::max<int64_t>(0, diff) + k;
  const uint32_t W = (n + 63) / 64;

  // Block range per column j (1-based): rows i in [max(1, j-dmax),
  // min(n, j-dmin)], bit r = i-1.
  auto blo = [&](int64_t j) -> int64_t {
    const int64_t top = std::max<int64_t>(1, j - dmax);
    return (top - 1) / 64;
  };
  auto bhi = [&](int64_t j) -> int64_t {
    const int64_t bot = std::min<int64_t>(n, j - dmin);
    return (bot - 1) / 64;
  };

  std::vector<uint64_t> peq(static_cast<size_t>(W) * 256, 0);
  for (uint32_t i = 0; i < n; ++i) {
    peq[static_cast<size_t>(i / 64) * 256 + static_cast<uint8_t>(q[i])] |=
        1ull << (i % 64);
  }

  std::vector<uint64_t> vp(W, ~0ull), vn(W, 0);

  // Per-column snapshot storage.
  std::vector<size_t> col_off(m + 1, 0);
  std::vector<int32_t> col_blo(m + 1, 0), col_bhi(m + 1, -1);
  std::vector<int64_t> col_bot(m + 1, 0);  // score at row (bhi+1)*64 (virtual)
  size_t total_blocks = 0;
  for (int64_t j = 1; j <= m; ++j) {
    total_blocks += static_cast<size_t>(bhi(j) - blo(j) + 1);
  }
  if (total_blocks * 16 > (3ull << 30)) {
    return std::string();  // too big; caller falls back
  }
  std::vector<uint64_t> svp(total_blocks), svn(total_blocks);

  // Column 0 snapshot is implicit: D[i][0] = i.
  int64_t bot_score = 64ll * (bhi(1) + 1);  // virtual bottom of col 0 band
  size_t off = 0;
  int64_t prev_bhi = bhi(1);
  // initialize bands below: vp preinitialized ~0 handles fresh blocks

  for (int64_t j = 1; j <= m; ++j) {
    const int64_t lo_b = blo(j), hi_b = bhi(j);
    // Entering new bottom blocks: extend the bottom anchor (fresh blocks are
    // all-VP, +1 per row).
    if (hi_b > prev_bhi) {
      bot_score += 64ll * (hi_b - prev_bhi);
      prev_bhi = hi_b;
    }

    const uint8_t c = static_cast<uint8_t>(t[j - 1]);
    int hin = 1;  // top boundary (row 0 or band top) advances +1 per column
    for (int64_t b = lo_b; b <= hi_b; ++b) {
      hin = myers_block_step(peq[static_cast<size_t>(b) * 256 + c], vp[b],
                             vn[b], hin);
    }
    bot_score += hin;

    col_off[j] = off;
    col_blo[j] = static_cast<int32_t>(lo_b);
    col_bhi[j] = static_cast<int32_t>(hi_b);
    col_bot[j] = bot_score;
    for (int64_t b = lo_b; b <= hi_b; ++b) {
      svp[off] = vp[b];
      svn[off] = vn[b];
      ++off;
    }
  }

  // D(i, j) from the column-j snapshot: walk up from the bottom anchor.
  auto cell = [&](int64_t i, int64_t j) -> int64_t {
    if (j == 0) {
      return i;
    }
    if (i == 0) {
      return j;
    }
    const int64_t lo_b = col_blo[j], hi_b = col_bhi[j];
    int64_t score = col_bot[j];
    // rows (r+1) for bits r; peel rows strictly above the anchor down to i.
    for (int64_t b = hi_b; b >= lo_b; --b) {
      const int64_t base = b * 64;  // bit r covers row r+1
      if (base + 1 > i) {
        // whole block rows are > i: peel all 64
        const uint64_t p = svp[col_off[j] + (b - lo_b)];
        const uint64_t mn = svn[col_off[j] + (b - lo_b)];
        score -= __builtin_popcountll(p);
        score += __builtin_popcountll(mn);
      } else {
        // partial: peel rows i+1 .. base+64 -> bits (i-base) .. 63
        const int shift = static_cast<int>(i - base);
        const uint64_t mask = shift >= 64 ? 0 : (~0ull << shift);
        const uint64_t p = svp[col_off[j] + (b - lo_b)] & mask;
        const uint64_t mn = svn[col_off[j] + (b - lo_b)] & mask;
        score -= __builtin_popcountll(p);
        score += __builtin_popcountll(mn);
        break;
      }
    }
    return score;
  };

  if (cell(n, m) != dist) {
    return std::string();  // boundary approximation violated; fall back
  }

  std::string rev_ops;
  rev_ops.reserve(n + m);
  int64_t i = n, j = m;
  int64_t cur = dist;  // cell(n, m), carried forward between steps
  while (i > 0 || j > 0) {
    int64_t next;
    if (i > 0 && j > 0 &&
        (next = cell(i - 1, j - 1)) + (q[i - 1] == t[j - 1] ? 0 : 1) == cur) {
      rev_ops += 'M';
      --i;
      --j;
    } else if (j > 0 && (next = cell(i, j - 1)) + 1 == cur) {
      rev_ops += 'D';
      --j;
    } else {
      next = cur - 1;  // vertical move always costs 1
      rev_ops += 'I';
      --i;
    }
    cur = next;
  }

  return cigar_from_reversed_ops(rev_ops);
}

}  // namespace

// Myers/Hyyro bit-parallel global edit distance over 64-row blocks.
namespace {

// One banded block-Myers scoring pass with half-width k. Returns the
// in-band distance D(n, m) — an overestimate of the true distance when
// the optimal path leaves the band, exact when the result is <= k (the
// Ukkonen criterion: every path of cost <= k stays within k diagonals of
// the endpoint diagonals, and the band-top boundary only overestimates).
int64_t banded_distance_pass(const std::vector<uint64_t>& peq, uint32_t n,
                             const char* t, uint32_t m, int64_t k,
                             std::vector<uint64_t>& vp,
                             std::vector<uint64_t>& vn) {
  const int64_t diff = static_cast<int64_t>(m) - static_cast<int64_t>(n);
  const int64_t dmin = std::min<int64_t>(0, diff) - k;
  const int64_t dmax = std::max<int64_t>(0, diff) + k;
  const uint32_t W = (n + 63) / 64;
  auto blo = [&](int64_t j) -> int64_t {
    return (std::max<int64_t>(1, j - dmax) - 1) / 64;
  };
  auto bhi = [&](int64_t j) -> int64_t {
    return (std::min<int64_t>(n, j - dmin) - 1) / 64;
  };

  vp.assign(W, ~0ull);
  vn.assign(W, 0);
  int64_t bot = 64ll * (bhi(1) + 1);  // score at the virtual band bottom
  int64_t prev_bhi = bhi(1);
  for (int64_t j = 1; j <= static_cast<int64_t>(m); ++j) {
    const int64_t lo_b = blo(j), hi_b = bhi(j);
    if (hi_b > prev_bhi) {  // fresh bottom blocks are all-VP (+1 per row)
      bot += 64ll * (hi_b - prev_bhi);
      prev_bhi = hi_b;
    }
    const uint8_t c = static_cast<uint8_t>(t[j - 1]);
    int hin = 1;  // +1 per column at row 0 / band top (overestimate)
    for (int64_t b = lo_b; b <= hi_b; ++b) {
      hin = myers_block_step(peq[static_cast<size_t>(b) * 256 + c], vp[b],
                             vn[b], hin);
    }
    bot += hin;
  }
  // Peel virtual rows below n off the final column.
  int64_t score = bot;
  for (int64_t r = 64ll * (bhi(m) + 1) - 1; r >= static_cast<int64_t>(n);
       --r) {
    const uint32_t b = static_cast<uint32_t>(r / 64);
    const uint64_t bit = 1ull << (r % 64);
    if (vp[b] & bit) {
      --score;
    } else if (vn[b] & bit) {
      ++score;
    }
  }
  return score;
}

}  // namespace

int64_t edit_distance(const char* q, uint32_t q_len, const char* t,
                      uint32_t t_len) {
  if (q_len == 0) {
    return t_len;
  }
  if (t_len == 0) {
    return q_len;
  }

  const uint32_t W = (q_len + 63) / 64;
  // Peq[block][symbol]: match mask for the 64 query rows of the block.
  std::vector<uint64_t> peq(static_cast<size_t>(W) * 256, 0);
  for (uint32_t i = 0; i < q_len; ++i) {
    const uint8_t c = static_cast<uint8_t>(q[i]);
    peq[static_cast<size_t>(i / 64) * 256 + c] |= 1ull << (i % 64);
  }

  std::vector<uint64_t> vp(W), vn(W);

  // Ukkonen doubling: banded passes cost O(n*k/64) instead of the full
  // O(n*m/64); a result <= k is exact. Typical long-read pairs resolve at
  // k ~ 2*distance, several times cheaper than the full pass. Seeding at
  // |m - n| skips passes that cannot possibly satisfy d <= k (distance is
  // always >= the length difference).
  const int64_t full = std::max(q_len, t_len);
  const int64_t diff = std::llabs(static_cast<int64_t>(t_len) -
                                  static_cast<int64_t>(q_len));
  for (int64_t k = std::max<int64_t>(256, diff); k < full; k *= 4) {
    const int64_t d = banded_distance_pass(peq, q_len, t, t_len, k, vp, vn);
    if (d <= k) {
      return d;
    }
  }

  // Band covering everything == the classic full pass.
  return banded_distance_pass(peq, q_len, t, t_len, full, vp, vn);
}

}  // namespace rt
