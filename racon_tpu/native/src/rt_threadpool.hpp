// Minimal work-stealing-free thread pool with futures and a per-thread index
// map (so each worker can own a reusable POA aligner, the way the reference
// gives each thread its own spoa engine — /root/reference/src/polisher.cpp:
// 176,179-183,497-503). New implementation, parity with the vendored
// thread_pool library's Submit/thread_map surface.
#pragma once

#include <condition_variable>
#include <cstddef>
#include <functional>
#include <future>
#include <mutex>
#include <queue>
#include <stdexcept>
#include <thread>
#include <unordered_map>
#include <vector>

namespace rt {

class ThreadPool {
 public:
  explicit ThreadPool(uint32_t num_threads) {
    num_threads = num_threads == 0 ? 1 : num_threads;
    for (uint32_t i = 0; i < num_threads; ++i) {
      workers_.emplace_back([this] { loop(); });
    }
    // thread_map_ is filled after the workers start, but workers only read
    // it from inside a job, and every job is handed over through mutex_:
    // the ctor's writes happen-before submit()'s lock release on the
    // submitting thread, which happens-before the worker's lock acquire.
    // After the ctor the map is never mutated, so lock-free reads in
    // this_thread_index() are safe.
    for (uint32_t i = 0; i < num_threads; ++i) {
      thread_map_[workers_[i].get_id()] = i;
    }
  }

  // Shutdown: the stop flag is set under the queue lock (a worker between
  // its predicate check and cv_.wait can never miss the notify), workers
  // drain whatever is still queued, then exit.
  ~ThreadPool() {
    {
      std::unique_lock<std::mutex> lock(mutex_);
      done_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) {
      w.join();
    }
  }

  template <typename F>
  std::future<void> submit(F&& f) {
    auto task = std::make_shared<std::packaged_task<void()>>(std::forward<F>(f));
    auto fut = task->get_future();
    {
      std::unique_lock<std::mutex> lock(mutex_);
      if (done_) {
        // A task enqueued after shutdown began would be destroyed unrun
        // while its future blocks forever; refuse loudly instead.
        throw std::runtime_error("rt::ThreadPool: submit after shutdown");
      }
      queue_.emplace([task] { (*task)(); });
    }
    cv_.notify_one();
    return fut;
  }

  // Mid-flight cancellation: drop every job no worker has picked up yet.
  // Returns the number dropped. The dropped packaged_tasks are destroyed
  // unrun outside the lock, so their futures throw std::future_error
  // (broken_promise) — callers awaiting cancelled work unblock with an
  // error instead of hanging. Jobs already running are unaffected and the
  // pool stays usable.
  std::size_t cancel_pending() {
    std::queue<std::function<void()>> dropped;
    {
      std::unique_lock<std::mutex> lock(mutex_);
      dropped.swap(queue_);
    }
    return dropped.size();
  }

  uint32_t num_threads() const { return static_cast<uint32_t>(workers_.size()); }

  // Index of the calling thread: workers get 0..n-1; any non-pool caller
  // (e.g. the Python driver finishing device-rejected work) gets the
  // dedicated slot n, so its scratch state never races a worker's.
  uint32_t this_thread_index() const {
    auto it = thread_map_.find(std::this_thread::get_id());
    return it == thread_map_.end() ? static_cast<uint32_t>(workers_.size())
                                   : it->second;
  }

 private:
  void loop() {
    while (true) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lock(mutex_);
        // Explicit wait loop: the stop flag and the queue are re-checked
        // under the lock after every wake-up, so a spurious wake, a
        // cancel_pending() draining the queue between notify and wake, or
        // a shutdown racing a submit can never pop from an empty queue or
        // miss the stop request.
        while (!done_ && queue_.empty()) {
          cv_.wait(lock);
        }
        if (queue_.empty()) {
          return;  // stop requested and no work left to drain
        }
        job = std::move(queue_.front());
        queue_.pop();
      }
      job();
    }
  }

  std::vector<std::thread> workers_;
  std::unordered_map<std::thread::id, uint32_t> thread_map_;
  std::queue<std::function<void()>> queue_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool done_ = false;
};

}  // namespace rt
