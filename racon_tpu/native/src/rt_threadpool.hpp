// Minimal work-stealing-free thread pool with futures and a per-thread index
// map (so each worker can own a reusable POA aligner, the way the reference
// gives each thread its own spoa engine — /root/reference/src/polisher.cpp:
// 176,179-183,497-503). New implementation, parity with the vendored
// thread_pool library's Submit/thread_map surface.
#pragma once

#include <condition_variable>
#include <functional>
#include <future>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace rt {

class ThreadPool {
 public:
  explicit ThreadPool(uint32_t num_threads) {
    num_threads = num_threads == 0 ? 1 : num_threads;
    for (uint32_t i = 0; i < num_threads; ++i) {
      workers_.emplace_back([this] { loop(); });
    }
    for (uint32_t i = 0; i < num_threads; ++i) {
      thread_map_[workers_[i].get_id()] = i;
    }
  }

  ~ThreadPool() {
    {
      std::unique_lock<std::mutex> lock(mutex_);
      done_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) {
      w.join();
    }
  }

  template <typename F>
  std::future<void> submit(F&& f) {
    auto task = std::make_shared<std::packaged_task<void()>>(std::forward<F>(f));
    auto fut = task->get_future();
    {
      std::unique_lock<std::mutex> lock(mutex_);
      queue_.emplace([task] { (*task)(); });
    }
    cv_.notify_one();
    return fut;
  }

  uint32_t num_threads() const { return static_cast<uint32_t>(workers_.size()); }

  // Index of the calling thread: workers get 0..n-1; any non-pool caller
  // (e.g. the Python driver finishing device-rejected work) gets the
  // dedicated slot n, so its scratch state never races a worker's.
  uint32_t this_thread_index() const {
    auto it = thread_map_.find(std::this_thread::get_id());
    return it == thread_map_.end() ? static_cast<uint32_t>(workers_.size())
                                   : it->second;
  }

 private:
  void loop() {
    while (true) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lock(mutex_);
        cv_.wait(lock, [this] { return done_ || !queue_.empty(); });
        if (done_ && queue_.empty()) {
          return;
        }
        job = std::move(queue_.front());
        queue_.pop();
      }
      job();
    }
  }

  std::vector<std::thread> workers_;
  std::unordered_map<std::thread::id, uint32_t> thread_map_;
  std::queue<std::function<void()>> queue_;
  std::mutex mutex_;
  std::condition_variable cv_;
  bool done_ = false;
};

}  // namespace rt
