// Phase timing + stderr progress bar.
//
// Capability parity with the reference logger
// (/root/reference/src/logger.{hpp,cpp}): wall-clock per-phase timings
// printed as "[...] phase = N.nnnnnn s", a 20-bin progress bar with
// percentage, and a total-runtime line on teardown.
#pragma once

#include <chrono>
#include <cstdio>
#include <string>

namespace rt {

class Logger {
 public:
  Logger()
      : start_(clock_t::now()), phase_(clock_t::now()), bar_state_(0) {}

  // Begin a new phase (silent).
  void log() { phase_ = clock_t::now(); }

  // Finish the current phase with a message.
  void log(const std::string& msg) {
    const double s = seconds_since(phase_);
    std::fprintf(stderr, "%s %.6f s\n", msg.c_str(), s);
    phase_ = clock_t::now();
  }

  // Advance a 20-bin progress bar; completes (prints elapsed + newline) on
  // the 20th tick.
  void bar(const std::string& msg) {
    ++bar_state_;
    const int bars = bar_state_;
    std::string b(bars, '=');
    if (bars < 20) {
      b += '>';
    }
    std::fprintf(stderr, "%s [%-20s] %3d%%\r", msg.c_str(), b.c_str(),
                 bars * 5);
    if (bars == 20) {
      const double s = seconds_since(phase_);
      std::fprintf(stderr, "\n%s %.6f s\n", msg.c_str(), s);
      bar_state_ = 0;
      phase_ = clock_t::now();
    }
  }

  void total(const std::string& msg) {
    std::fprintf(stderr, "%s %.6f s\n", msg.c_str(), seconds_since(start_));
  }

 private:
  using clock_t = std::chrono::steady_clock;
  static double seconds_since(clock_t::time_point t) {
    return std::chrono::duration<double>(clock_t::now() - t).count();
  }

  clock_t::time_point start_, phase_;
  int bar_state_;
};

}  // namespace rt
