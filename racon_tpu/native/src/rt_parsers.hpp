// Streaming parsers for the five input formats the framework accepts:
// FASTA / FASTQ (sequences, optionally gzipped) and MHAP / PAF / SAM
// (overlaps, optionally gzipped).
//
// Capability parity with the reference's vendored bioparser
// (bioparser::{Fasta,Fastq,Mhap,Paf,Sam}Parser, see
// /root/reference/src/polisher.cpp:20-24,85-135) — same format set, same
// transparent gzip handling, and a chunked Parse(max_bytes) pull interface so
// very large read sets can be consumed in bounded memory
// (reference: kChunkSize 1 GiB, src/polisher.cpp:30,226-265).
//
// The implementation is new: a single zlib-backed buffered reader with
// per-format record scanners.
#pragma once

#include <cstdint>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "rt_overlap.hpp"
#include "rt_sequence.hpp"

namespace rt {

// Buffered gzFile reader (zlib reads plain files transparently too).
class GzReader {
 public:
  explicit GzReader(const std::string& path);
  ~GzReader();
  GzReader(const GzReader&) = delete;
  GzReader& operator=(const GzReader&) = delete;

  // Read one line (without trailing \n / \r\n) into `line`.
  // Returns false at EOF with no data.
  bool getline(std::string& line);
  bool eof() const { return eof_ && pos_ >= len_; }
  void reset();

 private:
  void fill();
  void* file_ = nullptr;
  std::string path_;
  std::vector<char> buf_;
  size_t pos_ = 0, len_ = 0;
  bool eof_ = false;
};

enum class SeqFormat { kFasta, kFastq };
enum class OvlFormat { kMhap, kPaf, kSam };

// Extension sniffing, same accepted extension sets as the reference factory
// (src/polisher.cpp:85-135). Returns false if the extension is unsupported.
bool sniff_sequence_format(const std::string& path, SeqFormat* fmt);
bool sniff_overlap_format(const std::string& path, OvlFormat* fmt);

class SequenceParser {
 public:
  SequenceParser(const std::string& path, SeqFormat fmt);

  // Parse records until at least `max_bytes` of sequence payload has been
  // produced (or EOF). max_bytes == 0 means parse everything.
  std::vector<std::unique_ptr<Sequence>> parse(uint64_t max_bytes);
  void reset();

 private:
  bool parse_one(std::vector<std::unique_ptr<Sequence>>& dst, uint64_t* bytes);
  GzReader reader_;
  SeqFormat fmt_;
  std::string pending_header_;  // FASTA header lookahead
};

class OverlapParser {
 public:
  OverlapParser(const std::string& path, OvlFormat fmt);
  std::vector<std::unique_ptr<Overlap>> parse(uint64_t max_bytes);
  void reset();

 private:
  GzReader reader_;
  OvlFormat fmt_;
};

}  // namespace rt
