"""ctypes binding to the racon-tpu native host runtime (libracon_host.so).

The native library implements the host side of the framework: parsers for
FASTA/FASTQ/MHAP/PAF/SAM (+gzip), the sequence/overlap/window data model,
overlap filtering, the banded global aligner and POA consensus oracle, the
thread pool, and the stitching pipeline — the parity surface of the
reference's first-party C++ layer (/root/reference/src/) and its vendored
native dependencies (bioparser, spoa, edlib, thread_pool).

The Python side orchestrates the TPU phases and claims work through the job
export/import seam (see rt_pipeline.hpp).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "build", "libracon_host.so")

_lib: Optional[ctypes.CDLL] = None


def _newer_than_lib(path: str) -> bool:
    try:
        lib_mtime = os.path.getmtime(_LIB_PATH)
    except OSError:
        return True
    return os.path.getmtime(path) > lib_mtime


def ensure_built() -> str:
    """Build libracon_host.so if missing or stale. Returns its path."""
    src_dir = os.path.join(_DIR, "src")
    inputs = [os.path.join(src_dir, f) for f in os.listdir(src_dir)
              if f.endswith((".cpp", ".hpp"))]
    inputs.append(os.path.join(_DIR, "Makefile"))
    stale = not os.path.exists(_LIB_PATH) or any(
        _newer_than_lib(p) for p in inputs)
    if stale:
        proc = subprocess.run(
            ["make", "-j", str(os.cpu_count() or 4)],
            cwd=_DIR,
            capture_output=True,
            text=True,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed (make exited {proc.returncode}):\n"
                f"{proc.stdout}\n{proc.stderr}")
    return _LIB_PATH


def load() -> ctypes.CDLL:
    """Load (building if necessary) the native library, configured."""
    global _lib
    if _lib is not None:
        return _lib
    lib = ctypes.CDLL(ensure_built())

    u8p = ctypes.POINTER(ctypes.c_uint8)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)

    lib.rt_last_error.restype = ctypes.c_char_p
    lib.rt_last_error.argtypes = []

    lib.rt_edit_distance.restype = ctypes.c_int64
    lib.rt_edit_distance.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32]

    lib.rt_align_cigar.restype = ctypes.c_void_p
    lib.rt_align_cigar.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_uint32]

    lib.rt_free.restype = None
    lib.rt_free.argtypes = [ctypes.c_void_p]

    lib.rt_window_consensus.restype = ctypes.c_void_p
    lib.rt_window_consensus.argtypes = [
        ctypes.c_char_p, ctypes.c_uint32, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.c_char_p, u32p, u32p, u32p, ctypes.c_uint32, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int8, ctypes.c_int8,
        ctypes.c_int8, ctypes.POINTER(ctypes.c_int)]

    lib.rt_pipeline_create.restype = ctypes.c_void_p
    lib.rt_pipeline_create.argtypes = [
        ctypes.c_char_p, ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_uint32, ctypes.c_double, ctypes.c_double, ctypes.c_int,
        ctypes.c_int8, ctypes.c_int8, ctypes.c_int8, ctypes.c_uint32]

    lib.rt_pipeline_destroy.restype = None
    lib.rt_pipeline_destroy.argtypes = [ctypes.c_void_p]

    for name in ("rt_pipeline_prepare", "rt_pipeline_align_jobs_cpu",
                 "rt_pipeline_build_windows", "rt_pipeline_initialize",
                 "rt_pipeline_consensus_cpu_all"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p]

    lib.rt_pipeline_num_align_jobs.restype = ctypes.c_uint64
    lib.rt_pipeline_num_align_jobs.argtypes = [ctypes.c_void_p]

    lib.rt_pipeline_align_job.restype = None
    lib.rt_pipeline_align_job.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_char_p), u32p,
        ctypes.POINTER(ctypes.c_char_p), u32p]

    lib.rt_pipeline_align_job_lengths.restype = None
    lib.rt_pipeline_align_job_lengths.argtypes = [ctypes.c_void_p, u32p]

    lib.rt_pipeline_set_job_cigar.restype = None
    lib.rt_pipeline_set_job_cigar.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p]

    lib.rt_pipeline_num_windows.restype = ctypes.c_uint64
    lib.rt_pipeline_num_windows.argtypes = [ctypes.c_void_p]

    lib.rt_pipeline_window_info.restype = None
    lib.rt_pipeline_window_info.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u64p]

    lib.rt_pipeline_window_export.restype = None
    lib.rt_pipeline_window_export.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u8p, u8p, u32p, u32p, u32p, u8p, u8p]

    lib.rt_pipeline_consensus_cpu_one.restype = ctypes.c_int
    lib.rt_pipeline_consensus_cpu_one.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64]

    lib.rt_pipeline_set_consensus.restype = None
    lib.rt_pipeline_set_consensus.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint32,
        ctypes.c_int]

    lib.rt_pipeline_stitch.restype = ctypes.c_uint64
    lib.rt_pipeline_stitch.argtypes = [ctypes.c_void_p, ctypes.c_int]

    lib.rt_pipeline_result_name.restype = ctypes.c_void_p
    lib.rt_pipeline_result_name.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u64p]

    lib.rt_pipeline_result_data.restype = ctypes.c_void_p
    lib.rt_pipeline_result_data.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, u64p]

    lib.rt_pipeline_get_consensus.restype = ctypes.c_void_p
    lib.rt_pipeline_get_consensus.argtypes = [ctypes.c_void_p,
                                              ctypes.c_uint64, u64p]

    lib.rt_pipeline_window_type.restype = ctypes.c_int
    lib.rt_pipeline_window_type.argtypes = [ctypes.c_void_p]

    _lib = lib
    return lib


class NativeError(RuntimeError):
    """Raised when the native runtime reports an error (it never exits the
    process when used as a library; the CLI binary exits 1 instead)."""


def check_error(lib: ctypes.CDLL) -> None:
    msg = lib.rt_last_error()
    if msg:
        raise NativeError(msg.decode().strip())


def edit_distance(q: bytes, t: bytes) -> int:
    """Global (NW) edit distance — the accuracy metric of the test suite
    (reference analogue: test/racon_test.cpp:14-23)."""
    lib = load()
    return lib.rt_edit_distance(q, len(q), t, len(t))


def align_cigar(q: bytes, t: bytes) -> str:
    """Global alignment CIGAR (host banded NW)."""
    lib = load()
    ptr = lib.rt_align_cigar(q, len(q), t, len(t))
    if not ptr:
        check_error(lib)
        raise NativeError("alignment failed")
    try:
        return ctypes.string_at(ptr).decode()
    finally:
        lib.rt_free(ptr)


def window_consensus(backbone: bytes, layers, *, backbone_qual: bytes = None,
                     quals=None, begins=None, ends=None, tgs: bool = True,
                     trim: bool = True, match: int = 5, mismatch: int = -4,
                     gap: int = -8):
    """One-shot host POA window consensus (unit/differential test hook).

    layers: list of bytes. begins/ends: per-layer backbone positions
    (default: full span). quals: list of bytes or None.
    Returns (consensus: bytes, polished: bool).
    """
    lib = load()
    n = len(layers)
    bb_len = len(backbone)
    lens = (ctypes.c_uint32 * n)(*[len(s) for s in layers])
    begins_a = (ctypes.c_uint32 * n)(
        *(begins if begins is not None else [0] * n))
    ends_a = (ctypes.c_uint32 * n)(
        *(ends if ends is not None else [bb_len - 1] * n))
    bases = b"".join(layers)
    has_qual = quals is not None
    qual_cat = b"".join(quals) if has_qual else None
    polished = ctypes.c_int(0)
    ptr = lib.rt_window_consensus(
        backbone, bb_len, backbone_qual, bases, qual_cat, lens, begins_a,
        ends_a, n, 1 if has_qual else 0, 1 if tgs else 0, 1 if trim else 0,
        match, mismatch, gap, ctypes.byref(polished))
    if not ptr:
        check_error(lib)
        raise NativeError("window consensus failed")
    try:
        return ctypes.string_at(ptr), bool(polished.value)
    finally:
        lib.rt_free(ptr)
