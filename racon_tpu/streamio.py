"""Streaming per-chunk working sets for the chunked polisher.

The chunked polisher already splits the *target* FASTA into contiguous
contig chunks (``polisher._split_fasta``) — but every chunk's native
``Pipeline`` still parses the **full** reads and overlaps files, so peak
RSS is O(genome) no matter how small the chunks are.  This module makes
the working set O(chunk):

1. an **index pass** streams the overlaps file once, recording per-chunk
   byte ranges (and, per chunk, which read names its overlaps
   reference), then streams the reads file once, recording each needed
   read record's byte range;
2. at polish time each chunk **materializes** exactly its byte ranges
   into a small subset file pair which the native pipeline parses
   instead of the full inputs, and releases when the chunk is done.

Gzipped inputs are decompressed once into the run's work directory
(constant memory) so ranges are plain byte offsets.  Subsetting only
ever removes records the native parser would ignore for that chunk's
targets anyway — the chunked full-file path already proves that — so
output is byte-identical to the in-memory path.

Formats: PAF (column 6 = target name) and SAM (column 3 = RNAME, ``@``
headers copied to every chunk).  MHAP references reads by ordinal id,
which subsetting would renumber, so MHAP (and anything unrecognized)
raises :class:`StreamUnsupported` and the polisher falls back to the
in-memory path with a NOTE.

Torn input is survivable: a truncated or gzip-corrupt tail marks the
chunks whose ranges the tear could have fed as *torn*; the polisher
routes those chunks to the quarantine path (recorded in the RunReport)
and polishes them from the working set indexed before the tear, while
every other chunk — and the run — proceeds normally.  The in-memory
path, by contrast, hands the corrupt file straight to the native parser
and dies.
"""

from __future__ import annotations

import gzip
import os
import zlib
from typing import Dict, List, Optional, Tuple

from . import obs
from .resilience import budget

#: I/O block size for decompression and range gathering.
_BLOCK = 1 << 20

#: Errors a torn/corrupt input surfaces while streaming.
TORN_ERRORS = (OSError, EOFError, zlib.error, ValueError,
               UnicodeDecodeError)


class StreamUnsupported(Exception):
    """The inputs cannot be streamed (MHAP/unknown overlap format);
    the caller falls back to the in-memory path."""


def _plain_name(path: str) -> str:
    base = os.path.basename(path)
    return base[:-3] if base.endswith(".gz") else base


def _ensure_plain(path: str, workdir: str,
                  tag: str) -> Tuple[str, Optional[Exception]]:
    """A plain (uncompressed) copy of `path` with stable byte offsets.
    Non-gz inputs are used in place.  A corrupt gz tail yields the
    partial decompressed prefix plus the exception (torn input)."""
    if not path.endswith(".gz"):
        return path, None
    out = os.path.join(workdir, f"plain.{tag}.{_plain_name(path)}")
    torn: Optional[Exception] = None
    with open(out, "wb") as dst:
        try:
            with gzip.open(path, "rb") as src:
                while True:
                    # read1, not read: read(n) loops underlying reads to
                    # fill n and a corrupt tail raises mid-fill, throwing
                    # away the already-decompressed prefix; read1 does
                    # one decompression step, so every good block lands
                    # on disk before the tear raises
                    block = src.read1(_BLOCK)
                    if not block:
                        break
                    dst.write(block)
        except TORN_ERRORS as e:
            torn = e
    return out, torn


def chunk_contigs(chunk_paths: List[str]) -> List[List[bytes]]:
    """Per-chunk contig names, parsed from the split chunk FASTAs
    (the first whitespace-delimited token of each ``>`` header)."""
    out: List[List[bytes]] = []
    for cp in chunk_paths:
        names: List[bytes] = []
        with open(cp, "rb") as f:
            for line in f:
                if line.startswith(b">"):
                    names.append(line[1:].split()[0])
        out.append(names)
    return out


def _sniff_format(plain_ovls: str, original: str) -> str:
    """'paf' | 'sam'; raises StreamUnsupported otherwise."""
    base = _plain_name(original).lower()
    if base.endswith(".mhap"):
        raise StreamUnsupported(
            "MHAP overlaps reference reads by ordinal id; streaming "
            "subsets would renumber them")
    with open(plain_ovls, "rb") as f:
        first_data = b""
        for line in f:
            if not line.startswith(b"@"):
                first_data = line
                break
        cols = first_data.rstrip(b"\r\n").split(b"\t")
        if base.endswith(".paf") or (
                len(cols) >= 12 and cols[4] in (b"+", b"-")):
            return "paf"
        if base.endswith(".sam") or (
                len(cols) >= 11 and cols[1].isdigit()
                and cols[3].isdigit()):
            return "sam"
    raise StreamUnsupported(
        f"unrecognized overlap format in {original!r} "
        "(streaming supports PAF and SAM)")


def _add_range(ranges: List[List[int]], start: int, end: int) -> None:
    """Append [start, end), coalescing with a contiguous predecessor so
    contig-grouped files index to ~one range per chunk."""
    if ranges and ranges[-1][1] == start:
        ranges[-1][1] = end
    else:
        ranges.append([start, end])


class WorkingSet:
    """One chunk's materialized reads+overlaps subset.

    Lives in memory between materialization and realization; ``park``
    moves the buffers to a disk spill file under memory pressure
    (the soft-watermark backpressure), ``realize`` writes the subset
    files the native pipeline parses — reloading from the spill file
    first when parked."""

    def __init__(self, chunk_index: int, seqs: bytes, ovls: bytes,
                 seqs_name: str, ovls_name: str):
        self.chunk_index = chunk_index
        self._seqs: Optional[bytes] = seqs
        self._ovls: Optional[bytes] = ovls
        self.seqs_name = seqs_name
        self.ovls_name = ovls_name
        self._spill: Optional[str] = None

    def nbytes(self) -> int:
        if self._spill is not None:
            return 0
        return len(self._seqs or b"") + len(self._ovls or b"")

    def parked(self) -> bool:
        return self._spill is not None

    def park(self, dir_path: str) -> bool:
        """Spill the buffers to disk (no-op when already parked or the
        ``mem.spill`` fault/an I/O error aborts the park — the working
        set then simply stays in memory)."""
        if self._spill is not None or self._seqs is None:
            return False
        path = budget.park_bytes(
            [("seqs", self._seqs), ("ovls", self._ovls)],
            dir_path, f"chunk{self.chunk_index}")
        if path is None:
            return False
        self._spill = path
        self._seqs = None
        self._ovls = None
        return True

    def realize(self, outdir: str) -> Tuple[str, str]:
        """Write the subset files for the native pipeline and release
        the in-memory buffers.  Raises on a torn spill file."""
        if self._spill is not None:
            pairs = dict(budget.load_spill(self._spill))
            self._spill = None
            self._seqs = pairs["seqs"]
            self._ovls = pairs["ovls"]
        ci = self.chunk_index
        seqs_path = os.path.join(outdir, f"ws{ci}.{self.seqs_name}")
        ovls_path = os.path.join(outdir, f"ws{ci}.{self.ovls_name}")
        with open(seqs_path, "wb") as f:
            f.write(self._seqs or b"")
        with open(ovls_path, "wb") as f:
            f.write(self._ovls or b"")
        self._seqs = None
        self._ovls = None
        return seqs_path, ovls_path

    def release(self) -> None:
        self._seqs = None
        self._ovls = None
        if self._spill is not None:
            try:
                os.unlink(self._spill)
            except OSError:
                pass
            self._spill = None


class StreamIndex:
    """Byte-range index of the reads/overlaps files, per target chunk.

    Built by one streaming pass over each input (constant memory);
    ``materialize(ci)`` then loads chunk ci's working set — O(chunk),
    not O(genome).  ``torn(ci)`` reports chunks a truncated/corrupt
    input tail may have starved; the polisher quarantines those."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 chunk_paths: List[str], workdir: str):
        self.workdir = workdir
        self.seqs_name = _plain_name(sequences_path)
        self.ovls_name = _plain_name(overlaps_path)
        n = len(chunk_paths)
        self._ovl_ranges: List[List[List[int]]] = [[] for _ in range(n)]
        self._read_ranges: List[List[List[int]]] = [[] for _ in range(n)]
        self._headers: List[List[int]] = []
        self._torn: Dict[int, Exception] = {}

        contig_map: Dict[bytes, int] = {}
        for ci, names in enumerate(chunk_contigs(chunk_paths)):
            for name in names:
                contig_map[name] = ci

        self._plain_ovls, ovl_tear = _ensure_plain(
            overlaps_path, workdir, "ovls")
        self.fmt = _sniff_format(self._plain_ovls, overlaps_path)
        needed = self._index_overlaps(contig_map, ovl_tear)

        self._plain_seqs, seq_tear = _ensure_plain(
            sequences_path, workdir, "seqs")
        self._index_reads(needed, seq_tear)
        if self._torn:
            obs.event("stream.torn", chunks=sorted(self._torn))

    # -- index passes -----------------------------------------------------
    def _index_overlaps(self, contig_map: Dict[bytes, int],
                        tear: Optional[Exception]):
        """One pass over the (plain) overlaps file: per-chunk byte
        ranges plus the read names each chunk needs.  Returns
        {read_name: set(chunk ids)}."""
        tname_col = 5 if self.fmt == "paf" else 2
        needed: Dict[bytes, set] = {}
        seen_data = [False] * len(self._ovl_ranges)
        last_ci: Optional[int] = None
        offset = 0
        with open(self._plain_ovls, "rb") as f:
            for line in f:
                ln = len(line)
                if self.fmt == "sam" and line.startswith(b"@"):
                    _add_range(self._headers, offset, offset + ln)
                    offset += ln
                    continue
                complete = line.endswith(b"\n")
                cols = line.rstrip(b"\r\n").split(b"\t")
                ci = None
                if len(cols) > tname_col:
                    ci = contig_map.get(cols[tname_col])
                if not complete:
                    # truncated final record: its chunk (when still
                    # identifiable) ran out of data mid-stream
                    tear = tear or ValueError(
                        f"truncated overlap record at byte {offset} "
                        f"of {self.ovls_name}")
                    if ci is not None:
                        self._torn[ci] = tear
                    break
                if ci is not None:
                    _add_range(self._ovl_ranges[ci], offset, offset + ln)
                    needed.setdefault(cols[0], set()).add(ci)
                    seen_data[ci] = True
                    last_ci = ci
                offset += ln
        if tear is not None:
            # chunks the tear could have starved: the one mid-record at
            # the tear, and any chunk with no overlaps yet (their data,
            # if it existed, was beyond the tear — exact for the usual
            # contig-grouped layout, conservative otherwise)
            if last_ci is not None:
                self._torn.setdefault(last_ci, tear)
            for ci, seen in enumerate(seen_data):
                if not seen:
                    self._torn.setdefault(ci, tear)
        return needed

    def _index_reads(self, needed: Dict[bytes, set],
                     tear: Optional[Exception]) -> None:
        """One pass over the (plain) reads FASTA/FASTQ: the byte range
        of every record a chunk's overlaps reference."""
        found: Dict[bytes, List[int]] = {}
        offset = 0
        with open(self._plain_seqs, "rb") as f:
            first = f.read(1)
            f.seek(0)
            fastq = first == b"@"
            if fastq:
                while True:
                    rec = [f.readline() for _ in range(4)]
                    if not rec[0]:
                        break
                    ln = sum(len(x) for x in rec)
                    if not all(rec):  # file ended mid-record
                        tear = tear or ValueError(
                            f"truncated FASTQ record at byte {offset} "
                            f"of {self.seqs_name}")
                        break
                    name = rec[0][1:].split()[0] if len(rec[0]) > 1 else b""
                    found[name] = [offset, offset + ln]
                    offset += ln
            else:
                name = None
                start = 0
                for line in f:
                    if line.startswith(b">"):
                        if name is not None:
                            found[name] = [start, offset]
                        name = line[1:].split()[0] if len(line) > 1 else b""
                        start = offset
                    offset += len(line)
                if name is not None:
                    found[name] = [start, offset]
        for rname, chunks in needed.items():
            rng = found.get(rname)
            for ci in chunks:
                if rng is not None:
                    _add_range(self._read_ranges[ci], rng[0], rng[1])
                elif tear is not None:
                    # a referenced read the tear swallowed
                    self._torn.setdefault(ci, tear)

    # -- chunk access -----------------------------------------------------
    def torn(self, ci: int) -> Optional[Exception]:
        """The tear that starved chunk ci's working set, if any."""
        return self._torn.get(ci)

    def _gather(self, path: str, ranges: List[List[int]]) -> bytes:
        parts = []
        with open(path, "rb") as f:
            for start, end in ranges:
                f.seek(start)
                todo = end - start
                while todo > 0:
                    block = f.read(min(_BLOCK, todo))
                    if not block:
                        raise ValueError(
                            f"range [{start},{end}) past EOF in {path!r}")
                    parts.append(block)
                    todo -= len(block)
        return b"".join(parts)

    def materialize(self, ci: int) -> WorkingSet:
        """Load chunk ci's working set into memory (subset bytes of the
        reads and overlaps files; SAM headers included).  Raises
        OSError/ValueError on unreadable ranges — the caller routes
        that chunk to the quarantine path."""
        # ranges are deduplicated per chunk, so a read shared by two
        # chunks is loaded once into each chunk's subset
        seqs = self._gather(self._plain_seqs, self._read_ranges[ci])
        ovls = self._gather(
            self._plain_ovls, self._headers + self._ovl_ranges[ci])
        obs.count("stream.chunks_materialized")
        return WorkingSet(ci, seqs, ovls, self.seqs_name, self.ovls_name)
