"""Polisher front-ends: CPU oracle path and the TPU-backed path.

Mirrors the reference's factory seam (racon::createPolisher returning either
the base Polisher or the CUDA subclass, /root/reference/src/polisher.cpp:
137-163): `create_polisher(..., backend=...)` returns a polisher whose two hot
phases run either on the host oracle or on the TPU batch kernels with host
fallback for rejected work (the reference's graceful-degradation lattice,
src/cuda/cudapolisher.cpp:204-213,354-378).
"""

from __future__ import annotations

from typing import List, Tuple

from .pipeline import Pipeline
from .resilience import faults
from .resilience.report import RunReport


class CpuPolisher:
    """Pure-host polishing (the correctness oracle)."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 target_path: str, **kwargs):
        faults.reset()  # per-run firing schedule (deterministic)
        self._pipeline = Pipeline(sequences_path, overlaps_path, target_path,
                                  **kwargs)
        self.report = RunReport()

    def initialize(self) -> None:
        self._pipeline.initialize()

    def polish(self, drop_unpolished: bool = True) -> List[Tuple[str, str]]:
        self._pipeline.consensus_cpu_all()
        out = self._pipeline.stitch(drop_unpolished)
        self.report.finalize().write_env()
        return out


class TpuPolisher:
    """TPU-backed polishing: batched banded alignment + batched POA on
    device, host fallback for work outside device limits.

    After polish(), `self.report` (a resilience.report.RunReport) holds
    the per-phase serving/fallback accounting — who served what, why
    anything fell back, retries/bisections, quarantined windows, wall
    time per tier."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 target_path: str, **kwargs):
        faults.reset()  # per-run firing schedule (deterministic)
        self._kwargs = dict(kwargs)
        self._pipeline = Pipeline(sequences_path, overlaps_path, target_path,
                                  **kwargs)
        self.report = RunReport()

    def initialize(self) -> None:
        try:
            from .ops.align_driver import run_alignment_phase
        except ImportError as e:
            raise RuntimeError(
                "TPU backend unavailable (racon_tpu.ops failed to import); "
                "run without --tpu for the host path") from e

        self._pipeline.prepare()
        stats = run_alignment_phase(self._pipeline)  # device + host fallback
        self.report.attach(stats.get("report"))
        self._pipeline.build_windows()

    def polish(self, drop_unpolished: bool = True) -> List[Tuple[str, str]]:
        from .ops.poa_driver import run_consensus_phase

        stats = run_consensus_phase(self._pipeline,
                                    match=self._kwargs.get("match", 3),
                                    mismatch=self._kwargs.get("mismatch", -5),
                                    gap=self._kwargs.get("gap", -4),
                                    trim=self._kwargs.get("trim", True))
        self.report.attach(stats.get("report"))
        out = self._pipeline.stitch(drop_unpolished)
        self.report.finalize().write_env()
        return out


def create_polisher(sequences_path: str, overlaps_path: str, target_path: str,
                    backend: str = "cpu", **kwargs):
    """Factory. backend: 'cpu' (host oracle) or 'tpu' (device batched)."""
    if backend == "cpu":
        return CpuPolisher(sequences_path, overlaps_path, target_path,
                           **kwargs)
    if backend == "tpu":
        return TpuPolisher(sequences_path, overlaps_path, target_path,
                           **kwargs)
    raise ValueError(f"unknown backend: {backend!r}")
