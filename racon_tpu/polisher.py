"""Polisher front-ends: CPU oracle path and the TPU-backed path.

Mirrors the reference's factory seam (racon::createPolisher returning either
the base Polisher or the CUDA subclass, /root/reference/src/polisher.cpp:
137-163): `create_polisher(..., backend=...)` returns a polisher whose two hot
phases run either on the host oracle or on the TPU batch kernels with host
fallback for rejected work (the reference's graceful-degradation lattice,
src/cuda/cudapolisher.cpp:204-213,354-378).

Preemption tolerance: pass `journal_path` (CLI `--journal` /
`--resume-journal`, or the `RACON_TPU_JOURNAL` knob) and every served
window/CIGAR is appended to a crash-safe journal
(resilience/journal.py) as it is installed; a resumed run replays the
journal, recomputes only what is missing, and produces byte-identical
output.
"""

from __future__ import annotations

import os
import sys
import time
from typing import List, Optional, Tuple

from . import config, obs
from .pipeline import Pipeline
from .resilience import budget, faults, watchdog
from .resilience.journal import (Journal, input_fingerprint,
                                 replay_windows)
from .resilience.report import PhaseReport, RunReport

#: Handoff-queue sentinel: the alignment worker is done.
_DONE = object()


class _WorkerFailure:
    """An exception captured on the alignment worker thread, re-raised on
    the consumer so a pipelined polish fails exactly like a sequential
    one (instead of hanging on the queue)."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


def _split_fasta(target_path: str, n_chunks_hint: int, outdir: str):
    """Split a multi-contig FASTA into up to `n_chunks_hint` contiguous,
    roughly base-balanced chunk files (record text copied verbatim, so
    each chunk parses to byte-identical contigs).  Returns the chunk
    paths, or None when the target is not splittable (single contig,
    non-FASTA content) — the caller falls back to sequential phases.

    Two consumers depend on the contiguous/verbatim contract: the phase
    pipeline (below) overlaps alignment and consensus across chunks in
    one process, and the distrib coordinator (racon_tpu/distrib) farms
    chunks out to a worker fleet — both re-concatenate per-chunk output
    in chunk order and rely on it being byte-identical to the unchunked
    run."""
    import gzip
    import os

    opener = gzip.open if target_path.lower().endswith(".gz") else open
    records = []   # [bases, [raw lines]]
    cur = None
    try:
        with opener(target_path, "rt") as f:
            for line in f:
                if line.startswith(">"):
                    cur = [0, [line]]
                    records.append(cur)
                elif cur is None:
                    return None   # leading non-FASTA content
                else:
                    cur[0] += len(line.strip())
                    cur[1].append(line)
    except (OSError, UnicodeDecodeError):
        return None
    if len(records) < 2:
        return None
    k = min(len(records), max(2, n_chunks_hint))
    per_chunk = sum(r[0] for r in records) / k
    paths = []
    idx = 0
    for ci in range(k):
        must_leave = k - ci - 1   # later chunks each need >= 1 contig
        group = [records[idx]]
        acc = records[idx][0]
        idx += 1
        while (len(records) - idx > must_leave
               and (ci == k - 1 or acc + records[idx][0] <= per_chunk)):
            group.append(records[idx])
            acc += records[idx][0]
            idx += 1
        path = os.path.join(outdir, f"chunk{ci:03d}.fasta")
        with open(path, "w") as f:
            for _, lines in group:
                f.writelines(lines)
        paths.append(path)
    return paths


def reset_run_state(trace_path: Optional[str]) -> None:
    """Per-run reset of the module-global runtime state, shared by both
    polisher constructors: the deterministic fault schedule, watchdog
    wedge streaks, sanitizer findings, and obs arming all start fresh.

    This is the seam the serving layer leans on (racon_tpu/serve): a
    resident process runs many polishes, so every construction must
    re-arm per-request state — while everything deliberately *not* reset
    here (the topology-keyed kernel cache, the XLA compile cache) stays
    hot across jobs.  It also means in-process polishes cannot overlap;
    the serve scheduler serializes device-lane jobs for exactly this
    reason."""
    faults.reset()     # per-run firing schedule (deterministic)
    watchdog.reset()   # per-run wedge streaks
    budget.configure()  # fresh memory watermarks + RSS watchdog
    from .analysis import sanitize
    sanitize.reset()   # per-run sanitizer findings
    obs.reset()        # per-run trace/metrics (disarmed unless armed
    obs.configure(trace_path=trace_path)  # by --trace / the knobs)


def _open_journal(paths: Tuple[str, str, str], backend: str,
                  journal_path: Optional[str], resume: bool,
                  params: dict) -> Optional[Journal]:
    """Resolve this run's journal.  An explicit path (the CLI flags) wins
    and a fingerprint mismatch on explicit resume is an error; the
    `RACON_TPU_JOURNAL` knob auto-resumes and falls back to a fresh
    journal when the fingerprint says the inputs changed."""
    on_mismatch = "error"
    if journal_path is None:
        journal_path = config.get_str("RACON_TPU_JOURNAL") or None
        resume, on_mismatch = True, "fresh"
    if journal_path is None:
        return None
    fp = input_fingerprint(paths, params, backend)
    return Journal(journal_path, fp, resume=resume, on_mismatch=on_mismatch)


class CpuPolisher:
    """Pure-host polishing (the correctness oracle)."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 target_path: str, journal_path: Optional[str] = None,
                 resume_journal: bool = False,
                 trace_path: Optional[str] = None, **kwargs):
        reset_run_state(trace_path)
        self._journal = _open_journal(
            (sequences_path, overlaps_path, target_path), "cpu",
            journal_path, resume_journal, kwargs)
        self._pipeline = Pipeline(sequences_path, overlaps_path, target_path,
                                  **kwargs)
        self.report = RunReport()

    def initialize(self) -> None:
        # The native initialize fuses parse + host alignment + window
        # building in one ABI call (deliberately not decomposed: the
        # split Python calls carry extra fault-injection points that
        # would shift deterministic fault schedules); the host path's
        # phase attribution is therefore one span.
        with obs.span("phase.parse", fused="parse+align+window_assign"):
            self._pipeline.initialize()

    def polish(self, drop_unpolished: bool = True) -> List[Tuple[str, str]]:
        with obs.span("phase.poa", tier="host"):
            if self._journal is None:
                self._polish_unjournaled()
            else:
                self._polish_journaled(self._journal)
        with obs.span("phase.stitch"):
            out = self._pipeline.stitch(drop_unpolished)
        if self._journal is not None:
            self._journal.close()
        self.report.finalize().write_env()
        obs.write_trace()
        return out

    def _polish_unjournaled(self) -> None:
        pipeline = self._pipeline
        rep = PhaseReport("consensus", ("host",))
        rep.total = pipeline.num_windows()
        t0 = time.perf_counter()
        pipeline.consensus_cpu_all()
        rep.add_wall("host", time.perf_counter() - t0)
        rep.record_served("host", rep.total)
        self.report.attach(rep)

    def _polish_journaled(self, jr: Journal) -> None:
        # Window-at-a-time host consensus so every result is durable the
        # moment it exists (consensus_cpu_all's thread pool computes the
        # whole run before Python sees anything to journal); sequential
        # serving is the durability price on the host path.
        pipeline = self._pipeline
        n = pipeline.num_windows()
        rep = PhaseReport("consensus", ("journal", "host"))
        rep.total = n
        replayed = replay_windows(pipeline, jr, n, rep)
        t0 = time.perf_counter()
        for i in range(n):
            if i in replayed:
                continue
            polished = pipeline.consensus_cpu_one(i)
            _, _, rank, _, _, tid = pipeline.window_info(i)
            jr.append_window(i, tid, rank, "host",
                             pipeline.get_consensus(i), polished)
            rep.record_served("host")
        rep.add_wall("host", time.perf_counter() - t0)
        self.report.attach(rep)


class TpuPolisher:
    """TPU-backed polishing: batched banded alignment + batched POA on
    device, host fallback for work outside device limits.

    After polish(), `self.report` (a resilience.report.RunReport) holds
    the per-phase serving/fallback accounting — who served what, why
    anything fell back, retries/bisections, quarantined windows, wall
    time per tier, and (on a resumed run) how many units the journal
    replayed vs how many were served fresh."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 target_path: str, journal_path: Optional[str] = None,
                 resume_journal: bool = False,
                 trace_path: Optional[str] = None, **kwargs):
        reset_run_state(trace_path)
        self._kwargs = dict(kwargs)
        self._paths = (sequences_path, overlaps_path, target_path)
        self._journal = _open_journal(
            self._paths, "tpu", journal_path, resume_journal, kwargs)
        # Cross-phase pipelining (RACON_TPU_PIPELINE_PHASES=1): POA for
        # early target chunks runs while late alignment cohorts are still
        # in flight on a worker thread.  The journal records windows by
        # run-global index; a chunked run would journal chunk-local
        # indices, so journaled runs stay sequential.
        self._pipelined = config.get_bool("RACON_TPU_PIPELINE_PHASES")
        if self._pipelined and self._journal is not None:
            print("[racon_tpu::polisher] NOTE: RACON_TPU_PIPELINE_PHASES "
                  "ignored — the window journal needs run-global indices; "
                  "running the phases sequentially", file=sys.stderr)
            self._pipelined = False
        # Streaming input (RACON_TPU_STREAM_INPUT=1, auto-armed by a
        # memory budget): each target chunk's pipeline parses a
        # byte-range subset of the reads/overlaps files instead of the
        # whole inputs, so peak RSS is O(chunk) — see streamio.py.
        # Like pipelining, it chunks the target, so journaled runs
        # (run-global window indices) stay on the unchunked path.
        self._stream = (config.get_bool("RACON_TPU_STREAM_INPUT")
                        or budget.budget_mb() > 0)
        if self._stream and self._journal is not None:
            print("[racon_tpu::polisher] NOTE: streaming input ignored — "
                  "the window journal needs run-global indices; parsing "
                  "the full inputs", file=sys.stderr)
            self._stream = False
        # Chunked modes parse per target chunk; the full-target
        # Pipeline is only built when we end up sequential.
        self._pipeline = (None if (self._pipelined or self._stream) else
                          Pipeline(sequences_path, overlaps_path,
                                   target_path, **kwargs))
        self._queue = None
        self._worker = None
        self._warm = None
        self._tmpdir = None
        self._chunks = None
        self._stream_index = None
        self._collapsed = False
        # pressure/streaming accounting: torn-chunk quarantines and the
        # memory lattice edges land here, peak RSS is stamped in extra
        self._mem_rep = PhaseReport("memory", ())
        self.report = RunReport()

    def initialize(self) -> None:
        try:
            from .ops.align_driver import run_alignment_phase
        except ImportError as e:
            raise RuntimeError(
                "TPU backend unavailable (racon_tpu.ops failed to import); "
                "run without --tpu for the host path") from e

        obs.maybe_start_device_trace()
        if self._pipelined or self._stream:
            chunks = self._split_target()
            if chunks is not None:
                self._chunks = chunks
                if self._stream:
                    self._arm_streaming(chunks)
                if self._pipelined:
                    self._start_phase_pipeline(chunks, run_alignment_phase)
                # streaming without pipelining defers the per-chunk
                # polish loop to polish()
                return
            self._pipelined = False
            self._stream = False
        if self._pipeline is None:
            self._pipeline = Pipeline(*self._paths, **self._kwargs)
        with obs.span("phase.parse"):
            self._pipeline.prepare()
        with obs.span("phase.align") as sp:
            stats = run_alignment_phase(self._pipeline,
                                        journal=self._journal)
            sp.set(device=stats.get("device"), host=stats.get("host"))
        self.report.attach(stats.get("report"))
        with obs.span("phase.window_assign"):
            self._pipeline.build_windows()

    # -- phase pipelining --------------------------------------------------
    def _split_target(self):
        """Chunk the target FASTA for the phase pipeline / streaming
        loop; None (with a note) when the input is not splittable —
        sequential full-input fallback."""
        import tempfile

        target = self._paths[2]
        if not target.lower().endswith((".fa", ".fasta",
                                        ".fa.gz", ".fasta.gz")):
            print("[racon_tpu::polisher] NOTE: chunked polishing needs a "
                  "FASTA target; running the phases sequentially",
                  file=sys.stderr)
            return None
        depth = max(1, config.get_int("RACON_TPU_HANDOFF_DEPTH"))
        self._tmpdir = tempfile.mkdtemp(prefix="racon_tpu_chunks.")
        chunks = _split_fasta(target, depth + 2, self._tmpdir)
        if chunks is None:
            import shutil

            shutil.rmtree(self._tmpdir, ignore_errors=True)
            self._tmpdir = None
            print("[racon_tpu::polisher] NOTE: target has fewer than two "
                  "contigs; running the phases sequentially",
                  file=sys.stderr)
        return chunks

    # -- streaming working sets -------------------------------------------
    def _arm_streaming(self, chunks) -> None:
        """Build the per-chunk byte-range index (one streaming pass over
        each input).  Unsupported formats (MHAP's ordinal read ids) and
        unreadable inputs fall back to full-file chunk pipelines with a
        NOTE — never an error here; the native parser renders the final
        verdict on the full files."""
        from .streamio import TORN_ERRORS, StreamIndex, StreamUnsupported

        try:
            self._stream_index = StreamIndex(
                self._paths[0], self._paths[1], chunks, self._tmpdir)
        except StreamUnsupported as e:
            print(f"[racon_tpu::polisher] NOTE: streaming input disabled "
                  f"({e}); chunk pipelines parse the full inputs",
                  file=sys.stderr)
            self._stream_index = None
        except TORN_ERRORS as e:
            print(f"[racon_tpu::polisher] NOTE: streaming index failed "
                  f"({type(e).__name__}: {e}); chunk pipelines parse the "
                  f"full inputs", file=sys.stderr)
            self._stream_index = None

    def _chunk_inputs(self, ci: int):
        """(sequences, overlaps, subset_paths) for chunk ci's pipeline:
        the streamed working-set subset when streaming is armed, the
        full inputs otherwise.  This is the synchronous per-chunk
        budget poll (the deterministic ``mem.pressure`` seam); under
        soft-or-worse pressure the working set round-trips through the
        disk spill file before realization.  A torn chunk is
        quarantined — recorded in the RunReport, the run continues —
        and polishes from whatever working set the index recovered
        before the tear."""
        level = budget.poll()
        idx = self._stream_index
        if idx is None:
            return self._paths[0], self._paths[1], None
        torn = idx.torn(ci)
        try:
            ws = idx.materialize(ci)
            if budget.at_least(level, "soft"):
                ws.park(budget.spill_dir(self._tmpdir))
            paths = ws.realize(self._tmpdir)
        except Exception as e:  # noqa: BLE001 — degrade, never die
            self._quarantine_chunk(ci, torn or e)
            return self._paths[0], self._paths[1], None
        if torn is not None:
            self._quarantine_chunk(ci, torn)
        return paths[0], paths[1], paths

    def _quarantine_chunk(self, ci: int, exc: BaseException) -> None:
        print(f"[racon_tpu::polisher] WARNING: chunk {ci} working set "
              f"degraded ({type(exc).__name__}: {exc}); quarantining the "
              f"chunk", file=sys.stderr)
        self._mem_rep.record_quarantine(ci, exc)

    @staticmethod
    def _release_ws(ws_paths) -> None:
        """Delete a chunk's realized subset files (the native pipeline
        has fully parsed them by the end of prepare())."""
        if ws_paths:
            for p in ws_paths:
                try:
                    os.unlink(p)
                except OSError:
                    pass

    def _maybe_collapse(self) -> bool:
        """Hard-watermark latch for the pipelined path: once crossed,
        the alignment worker stops running ahead of POA (the phase
        pipeline collapses to sequential consumption) and the pressure
        lattice edge is recorded once."""
        if not budget.hard_latched():
            return False
        if not self._collapsed:
            self._collapsed = True
            self._mem_rep.record_degrade(
                "pipelined", "sequential",
                RuntimeError("hard memory watermark"))
        return True

    def _start_phase_pipeline(self, chunks, run_alignment_phase) -> None:
        """Arm the bounded handoff queue, the kernel prewarm thread (its
        compiles overlap the alignment phase instead of serializing
        before POA), and the single alignment worker.  One worker + FIFO
        queue = chunks arrive at POA in target order, so the stitched
        output is byte-identical to a sequential run."""
        import queue
        import threading

        from .ops import poa_driver

        kwargs = self._kwargs
        target = self._paths[2]

        def warm():
            try:
                w = int(kwargs.get("window_length", 500))
                lens = poa_driver.observed_window_lengths(target, w)
                poa_driver.warm_geometries(lens, kwargs.get("match", 3),
                                           kwargs.get("mismatch", -5),
                                           kwargs.get("gap", -4))
            except Exception as e:  # noqa: BLE001 — prewarm is best-effort
                print(f"[racon_tpu::polisher] WARNING: consensus prewarm "
                      f"failed ({type(e).__name__}: {e}); kernels compile "
                      f"on first use", file=sys.stderr)

        self._warm = threading.Thread(target=warm, name="poa-warm",
                                      daemon=True)
        self._warm.start()

        depth = max(1, config.get_int("RACON_TPU_HANDOFF_DEPTH"))
        self._queue = q = queue.Queue(maxsize=depth)

        def worker():
            try:
                for ci, chunk_path in enumerate(chunks):
                    # memory backpressure: under soft-or-worse pressure
                    # stop running ahead of POA until the consumer
                    # drains the handoff queue; a hard breach collapses
                    # the pipeline for the rest of the run
                    # (pipelined -> sequential, recorded once)
                    while ((self._maybe_collapse()
                            or budget.at_least(budget.level(), "soft"))
                           and not q.empty()):
                        time.sleep(0.02)
                    seqs_i, ovls_i, ws_paths = self._chunk_inputs(ci)
                    with obs.span("phase.parse", chunk=ci):
                        pl = Pipeline(seqs_i, ovls_i, chunk_path, **kwargs)
                        pl.prepare()
                    self._release_ws(ws_paths)
                    with obs.span("phase.align", chunk=ci) as sp:
                        stats = run_alignment_phase(pl, journal=None)
                        sp.set(device=stats.get("device"),
                               host=stats.get("host"))
                    with obs.span("phase.window_assign", chunk=ci):
                        pl.build_windows()
                    q.put((ci, pl, stats))
                q.put(_DONE)
            except BaseException as e:  # noqa: BLE001 — re-raised on main
                q.put(_WorkerFailure(e))

        self._worker = threading.Thread(target=worker, name="align-worker",
                                        daemon=True)
        self._worker.start()

    def _polish_pipelined(self, drop_unpolished: bool):
        from .ops.poa_driver import run_consensus_phase

        align_rep = None
        cons_rep = None
        out: List[Tuple[str, str]] = []
        try:
            # The prewarm compiles overlapped the alignment phase; POA
            # must not start until the geometries (and _WARM_DEAD) are
            # settled.
            if self._warm is not None:
                self._warm.join()
            while True:
                item = self._queue.get()
                if item is _DONE:
                    break
                if isinstance(item, _WorkerFailure):
                    raise item.exc
                ci, pl, stats = item
                rep = stats.get("report")
                if rep is not None:
                    if align_rep is None:
                        align_rep = rep
                    else:
                        align_rep.merge(rep)
                with obs.span("phase.poa", chunk=ci):
                    cstats = run_consensus_phase(
                        pl,
                        match=self._kwargs.get("match", 3),
                        mismatch=self._kwargs.get("mismatch", -5),
                        gap=self._kwargs.get("gap", -4),
                        trim=self._kwargs.get("trim", True),
                        journal=None)
                crep = cstats.get("report")
                if crep is not None:
                    if cons_rep is None:
                        cons_rep = crep
                    else:
                        cons_rep.merge(crep)
                with obs.span("phase.stitch", chunk=ci):
                    out.extend(pl.stitch(drop_unpolished))
        finally:
            if self._tmpdir is not None:
                import shutil

                shutil.rmtree(self._tmpdir, ignore_errors=True)
                self._tmpdir = None
        self.report.attach(align_rep)
        self.report.attach(cons_rep)
        return out

    def _polish_stream_sequential(self, drop_unpolished: bool):
        """Streaming without phase pipelining: one chunk at a time —
        materialize the working set, polish, release — so peak RSS is
        O(chunk), not O(genome)."""
        from .ops.align_driver import run_alignment_phase
        from .ops.poa_driver import run_consensus_phase

        align_rep = None
        cons_rep = None
        out: List[Tuple[str, str]] = []
        try:
            for ci, chunk_path in enumerate(self._chunks):
                seqs_i, ovls_i, ws_paths = self._chunk_inputs(ci)
                with obs.span("phase.parse", chunk=ci):
                    pl = Pipeline(seqs_i, ovls_i, chunk_path,
                                  **self._kwargs)
                    pl.prepare()
                self._release_ws(ws_paths)
                with obs.span("phase.align", chunk=ci) as sp:
                    stats = run_alignment_phase(pl, journal=None)
                    sp.set(device=stats.get("device"),
                           host=stats.get("host"))
                with obs.span("phase.window_assign", chunk=ci):
                    pl.build_windows()
                rep = stats.get("report")
                if rep is not None:
                    if align_rep is None:
                        align_rep = rep
                    else:
                        align_rep.merge(rep)
                with obs.span("phase.poa", chunk=ci):
                    cstats = run_consensus_phase(
                        pl,
                        match=self._kwargs.get("match", 3),
                        mismatch=self._kwargs.get("mismatch", -5),
                        gap=self._kwargs.get("gap", -4),
                        trim=self._kwargs.get("trim", True),
                        journal=None)
                crep = cstats.get("report")
                if crep is not None:
                    if cons_rep is None:
                        cons_rep = crep
                    else:
                        cons_rep.merge(crep)
                with obs.span("phase.stitch", chunk=ci):
                    out.extend(pl.stitch(drop_unpolished))
                del pl   # release the chunk's native working set
        finally:
            if self._tmpdir is not None:
                import shutil

                shutil.rmtree(self._tmpdir, ignore_errors=True)
                self._tmpdir = None
        self.report.attach(align_rep)
        self.report.attach(cons_rep)
        return out

    def _stamp_memory(self) -> None:
        """Attach the memory PhaseReport (peak RSS, budget, pressure
        verdicts) when a budget/streaming was armed or anything was
        recorded on it."""
        b = budget.active()
        armed = (b is not None and b.enabled) or self._stream
        if not (armed or self._mem_rep.degradations
                or self._mem_rep.quarantined):
            return
        self._mem_rep.extra.update({
            "peak_rss_mb": round(budget.peak_rss_mb(), 1),
            "budget_mb": b.budget_mb if b is not None else 0,
            "streamed": self._stream_index is not None,
            "pressure_level": b.level() if b is not None else "ok",
        })
        self.report.attach(self._mem_rep)

    def polish(self, drop_unpolished: bool = True) -> List[Tuple[str, str]]:
        from .ops.poa_driver import run_consensus_phase

        if self._pipelined:
            out = self._polish_pipelined(drop_unpolished)
        elif self._chunks is not None:
            out = self._polish_stream_sequential(drop_unpolished)
        else:
            with obs.span("phase.poa"):
                stats = run_consensus_phase(
                    self._pipeline,
                    match=self._kwargs.get("match", 3),
                    mismatch=self._kwargs.get("mismatch", -5),
                    gap=self._kwargs.get("gap", -4),
                    trim=self._kwargs.get("trim", True),
                    journal=self._journal)
            self.report.attach(stats.get("report"))
            with obs.span("phase.stitch"):
                out = self._pipeline.stitch(drop_unpolished)
        if self._journal is not None:
            self._journal.close()
        self._stamp_memory()
        self.report.finalize().write_env()
        obs.maybe_stop_device_trace()
        obs.write_trace()
        return out


def create_polisher(sequences_path: str, overlaps_path: str, target_path: str,
                    backend: str = "cpu", **kwargs):
    """Factory. backend: 'cpu' (host oracle) or 'tpu' (device batched).
    `journal_path=`/`resume_journal=` arm the crash-safe result journal
    (see resilience/journal.py); `trace_path=` arms the span tracer and
    writes a Chrome-trace JSON at the end of polish() (see
    racon_tpu/obs, CLI `--trace`, `RACON_TPU_TRACE`)."""
    if backend == "cpu":
        return CpuPolisher(sequences_path, overlaps_path, target_path,
                           **kwargs)
    if backend == "tpu":
        return TpuPolisher(sequences_path, overlaps_path, target_path,
                           **kwargs)
    raise ValueError(f"unknown backend: {backend!r}")
