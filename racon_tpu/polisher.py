"""Polisher front-ends: CPU oracle path and the TPU-backed path.

Mirrors the reference's factory seam (racon::createPolisher returning either
the base Polisher or the CUDA subclass, /root/reference/src/polisher.cpp:
137-163): `create_polisher(..., backend=...)` returns a polisher whose two hot
phases run either on the host oracle or on the TPU batch kernels with host
fallback for rejected work (the reference's graceful-degradation lattice,
src/cuda/cudapolisher.cpp:204-213,354-378).

Preemption tolerance: pass `journal_path` (CLI `--journal` /
`--resume-journal`, or the `RACON_TPU_JOURNAL` knob) and every served
window/CIGAR is appended to a crash-safe journal
(resilience/journal.py) as it is installed; a resumed run replays the
journal, recomputes only what is missing, and produces byte-identical
output.
"""

from __future__ import annotations

import time
from typing import List, Optional, Tuple

from . import config, obs
from .pipeline import Pipeline
from .resilience import faults, watchdog
from .resilience.journal import (Journal, input_fingerprint,
                                 replay_windows)
from .resilience.report import PhaseReport, RunReport


def _open_journal(paths: Tuple[str, str, str], backend: str,
                  journal_path: Optional[str], resume: bool,
                  params: dict) -> Optional[Journal]:
    """Resolve this run's journal.  An explicit path (the CLI flags) wins
    and a fingerprint mismatch on explicit resume is an error; the
    `RACON_TPU_JOURNAL` knob auto-resumes and falls back to a fresh
    journal when the fingerprint says the inputs changed."""
    on_mismatch = "error"
    if journal_path is None:
        journal_path = config.get_str("RACON_TPU_JOURNAL") or None
        resume, on_mismatch = True, "fresh"
    if journal_path is None:
        return None
    fp = input_fingerprint(paths, params, backend)
    return Journal(journal_path, fp, resume=resume, on_mismatch=on_mismatch)


class CpuPolisher:
    """Pure-host polishing (the correctness oracle)."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 target_path: str, journal_path: Optional[str] = None,
                 resume_journal: bool = False,
                 trace_path: Optional[str] = None, **kwargs):
        faults.reset()     # per-run firing schedule (deterministic)
        watchdog.reset()   # per-run wedge streaks
        from .analysis import sanitize
        sanitize.reset()   # per-run sanitizer findings
        obs.reset()        # per-run trace/metrics (disarmed unless armed
        obs.configure(trace_path=trace_path)  # by --trace / the knobs)
        self._journal = _open_journal(
            (sequences_path, overlaps_path, target_path), "cpu",
            journal_path, resume_journal, kwargs)
        self._pipeline = Pipeline(sequences_path, overlaps_path, target_path,
                                  **kwargs)
        self.report = RunReport()

    def initialize(self) -> None:
        # The native initialize fuses parse + host alignment + window
        # building in one ABI call (deliberately not decomposed: the
        # split Python calls carry extra fault-injection points that
        # would shift deterministic fault schedules); the host path's
        # phase attribution is therefore one span.
        with obs.span("phase.parse", fused="parse+align+window_assign"):
            self._pipeline.initialize()

    def polish(self, drop_unpolished: bool = True) -> List[Tuple[str, str]]:
        with obs.span("phase.poa", tier="host"):
            if self._journal is None:
                self._polish_unjournaled()
            else:
                self._polish_journaled(self._journal)
        with obs.span("phase.stitch"):
            out = self._pipeline.stitch(drop_unpolished)
        if self._journal is not None:
            self._journal.close()
        self.report.finalize().write_env()
        obs.write_trace()
        return out

    def _polish_unjournaled(self) -> None:
        pipeline = self._pipeline
        rep = PhaseReport("consensus", ("host",))
        rep.total = pipeline.num_windows()
        t0 = time.perf_counter()
        pipeline.consensus_cpu_all()
        rep.add_wall("host", time.perf_counter() - t0)
        rep.record_served("host", rep.total)
        self.report.attach(rep)

    def _polish_journaled(self, jr: Journal) -> None:
        # Window-at-a-time host consensus so every result is durable the
        # moment it exists (consensus_cpu_all's thread pool computes the
        # whole run before Python sees anything to journal); sequential
        # serving is the durability price on the host path.
        pipeline = self._pipeline
        n = pipeline.num_windows()
        rep = PhaseReport("consensus", ("journal", "host"))
        rep.total = n
        replayed = replay_windows(pipeline, jr, n, rep)
        t0 = time.perf_counter()
        for i in range(n):
            if i in replayed:
                continue
            polished = pipeline.consensus_cpu_one(i)
            _, _, rank, _, _, tid = pipeline.window_info(i)
            jr.append_window(i, tid, rank, "host",
                             pipeline.get_consensus(i), polished)
            rep.record_served("host")
        rep.add_wall("host", time.perf_counter() - t0)
        self.report.attach(rep)


class TpuPolisher:
    """TPU-backed polishing: batched banded alignment + batched POA on
    device, host fallback for work outside device limits.

    After polish(), `self.report` (a resilience.report.RunReport) holds
    the per-phase serving/fallback accounting — who served what, why
    anything fell back, retries/bisections, quarantined windows, wall
    time per tier, and (on a resumed run) how many units the journal
    replayed vs how many were served fresh."""

    def __init__(self, sequences_path: str, overlaps_path: str,
                 target_path: str, journal_path: Optional[str] = None,
                 resume_journal: bool = False,
                 trace_path: Optional[str] = None, **kwargs):
        faults.reset()     # per-run firing schedule (deterministic)
        watchdog.reset()   # per-run wedge streaks
        from .analysis import sanitize
        sanitize.reset()   # per-run sanitizer findings
        obs.reset()        # per-run trace/metrics (disarmed unless armed
        obs.configure(trace_path=trace_path)  # by --trace / the knobs)
        self._kwargs = dict(kwargs)
        self._journal = _open_journal(
            (sequences_path, overlaps_path, target_path), "tpu",
            journal_path, resume_journal, kwargs)
        self._pipeline = Pipeline(sequences_path, overlaps_path, target_path,
                                  **kwargs)
        self.report = RunReport()

    def initialize(self) -> None:
        try:
            from .ops.align_driver import run_alignment_phase
        except ImportError as e:
            raise RuntimeError(
                "TPU backend unavailable (racon_tpu.ops failed to import); "
                "run without --tpu for the host path") from e

        obs.maybe_start_device_trace()
        with obs.span("phase.parse"):
            self._pipeline.prepare()
        with obs.span("phase.align") as sp:
            stats = run_alignment_phase(self._pipeline,
                                        journal=self._journal)
            sp.set(device=stats.get("device"), host=stats.get("host"))
        self.report.attach(stats.get("report"))
        with obs.span("phase.window_assign"):
            self._pipeline.build_windows()

    def polish(self, drop_unpolished: bool = True) -> List[Tuple[str, str]]:
        from .ops.poa_driver import run_consensus_phase

        with obs.span("phase.poa"):
            stats = run_consensus_phase(
                self._pipeline,
                match=self._kwargs.get("match", 3),
                mismatch=self._kwargs.get("mismatch", -5),
                gap=self._kwargs.get("gap", -4),
                trim=self._kwargs.get("trim", True),
                journal=self._journal)
        self.report.attach(stats.get("report"))
        with obs.span("phase.stitch"):
            out = self._pipeline.stitch(drop_unpolished)
        if self._journal is not None:
            self._journal.close()
        self.report.finalize().write_env()
        obs.maybe_stop_device_trace()
        obs.write_trace()
        return out


def create_polisher(sequences_path: str, overlaps_path: str, target_path: str,
                    backend: str = "cpu", **kwargs):
    """Factory. backend: 'cpu' (host oracle) or 'tpu' (device batched).
    `journal_path=`/`resume_journal=` arm the crash-safe result journal
    (see resilience/journal.py); `trace_path=` arms the span tracer and
    writes a Chrome-trace JSON at the end of polish() (see
    racon_tpu/obs, CLI `--trace`, `RACON_TPU_TRACE`)."""
    if backend == "cpu":
        return CpuPolisher(sequences_path, overlaps_path, target_path,
                           **kwargs)
    if backend == "tpu":
        return TpuPolisher(sequences_path, overlaps_path, target_path,
                           **kwargs)
    raise ValueError(f"unknown backend: {backend!r}")
