"""Command-line interface, flag-compatible with the reference `racon` binary
(/root/reference/src/main.cpp:18-38,166-229) plus TPU backend flags in place
of the CUDA ones.

Usage: racon-tpu [options ...] <sequences> <overlaps> <target sequences>
       racon-tpu serve [options ...]   (resident polishing daemon)
       racon-tpu distrib [options ...] <sequences> <overlaps> <targets>
                                       (multi-process chunk-worker fleet)
"""

from __future__ import annotations

import argparse
import sys

from . import __version__
from .polisher import create_polisher


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="racon-tpu",
        description="TPU-native consensus module for raw de novo genome "
        "assembly of long uncorrected reads",
        epilog="subcommands: `racon-tpu serve` runs the resident "
        "polishing daemon (hot kernels, job queue, preemption-safe "
        "jobs — see `racon-tpu serve --help`); `racon-tpu distrib` "
        "polishes with a fault-tolerant multi-process chunk-worker "
        "fleet (leases, heartbeats, journal resume — see `racon-tpu "
        "distrib --help`).",
    )
    p.add_argument("sequences", help="FASTA/FASTQ file (optionally gzipped) "
                   "containing sequences used for correction")
    p.add_argument("overlaps", help="MHAP/PAF/SAM file (optionally gzipped) "
                   "containing overlaps between sequences and target "
                   "sequences")
    p.add_argument("targets", help="FASTA/FASTQ file (optionally gzipped) "
                   "containing sequences which will be corrected")
    p.add_argument("-u", "--include-unpolished", action="store_true",
                   help="output unpolished target sequences")
    p.add_argument("-f", "--fragment-correction", action="store_true",
                   help="perform fragment correction instead of contig "
                   "polishing (overlaps file should contain dual/self "
                   "overlaps!)")
    p.add_argument("-w", "--window-length", type=int, default=500,
                   help="size of window on which POA is performed (default "
                   "500)")
    p.add_argument("-q", "--quality-threshold", type=float, default=10.0,
                   help="threshold for average base quality of windows used "
                   "in POA (default 10.0)")
    p.add_argument("-e", "--error-threshold", type=float, default=0.3,
                   help="maximum allowed error rate used for filtering "
                   "overlaps (default 0.3)")
    p.add_argument("--no-trimming", action="store_true",
                   help="disables consensus trimming at window ends")
    p.add_argument("-m", "--match", type=int, default=3,
                   help="score for matching bases (default 3)")
    p.add_argument("-x", "--mismatch", type=int, default=-5,
                   help="score for mismatching bases (default -5)")
    p.add_argument("-g", "--gap", type=int, default=-4,
                   help="gap penalty, must be negative (default -4)")
    p.add_argument("-t", "--threads", type=int, default=1,
                   help="number of host threads (default 1)")
    p.add_argument("--tpu", action="store_true",
                   help="run the accelerated path (batched alignment + POA "
                   "on the JAX backend, host fallback for rejected work)")
    p.add_argument("--report", metavar="PATH", default=None,
                   help="write a machine-readable JSON run report (per-phase "
                   "serving tiers, fallback causes, retries, quarantined "
                   "windows, wall time per tier) to PATH")
    p.add_argument("--trace", metavar="PATH", default=None,
                   help="write a Chrome-trace/Perfetto JSON timeline of the "
                   "run (phase spans, per-bucket POA batches, lattice "
                   "events, kernel builds, embedded metrics snapshot) to "
                   "PATH; inspect with `python -m racon_tpu.obs PATH` or "
                   "ui.perfetto.dev (env: RACON_TPU_TRACE)")
    jr = p.add_mutually_exclusive_group()
    jr.add_argument("--journal", metavar="PATH", default=None,
                    help="append every served window/CIGAR to a crash-safe "
                    "journal at PATH (fsynced JSONL; overwrites an existing "
                    "file) so an interrupted run can be resumed")
    jr.add_argument("--resume-journal", metavar="PATH", default=None,
                    help="resume from the journal at PATH: replay every "
                    "already-served window, recompute only the rest, and "
                    "keep appending; output is byte-identical to an "
                    "uninterrupted run (errors out if the journal belongs "
                    "to different inputs/parameters; starts fresh if PATH "
                    "does not exist)")
    p.add_argument("--version", action="version", version=__version__)
    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # Subcommand seam (the reference binary's split/subsample pattern):
    # `racon-tpu serve` hands the rest of the argv to the daemon before
    # the polish-flags parser ever sees it.
    if argv and argv[0] == "serve":
        from .serve.__main__ import main as serve_main
        return serve_main(argv[1:])
    if argv and argv[0] == "distrib":
        from .distrib.__main__ import main as distrib_main
        return distrib_main(argv[1:])
    args = build_arg_parser().parse_args(argv)

    from .native import NativeError
    from .resilience import faults
    from .resilience.journal import JournalError

    # Validate the fault-injection spec up front (same contract as the
    # file-extension checks: single-line error, exit 1) — a malformed
    # RACON_TPU_FAULT must not surface as a mid-run traceback.
    try:
        faults.validate_env()
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1

    # Typo'd knobs must not be silently ignored: a RACON_TPU_* var the
    # registry doesn't know is almost always a misspelled real one.
    from . import config
    stale = config.unknown_env_knobs()
    if stale:
        print(f"[racon_tpu] WARNING: unknown RACON_TPU_* environment "
              f"variable(s) ignored: {', '.join(stale)} (known knobs: "
              f"see README.md)", file=sys.stderr)

    if args.tpu:
        # Validate device-path env config up front — a broad ValueError
        # catch around the whole run would also swallow real bugs'
        # tracebacks.
        from .ops.poa_driver import _kernel_kind
        try:
            _kernel_kind()
        except ValueError as e:
            print(e, file=sys.stderr)
            return 1

    try:
        polisher = create_polisher(
            args.sequences, args.overlaps, args.targets,
            backend="tpu" if args.tpu else "cpu",
            fragment_correction=args.fragment_correction,
            window_length=args.window_length,
            quality_threshold=args.quality_threshold,
            error_threshold=args.error_threshold,
            trim=not args.no_trimming,
            match=args.match, mismatch=args.mismatch, gap=args.gap,
            num_threads=args.threads,
            journal_path=args.resume_journal or args.journal,
            resume_journal=args.resume_journal is not None,
            trace_path=args.trace)
        polisher.initialize()
        for name, data in polisher.polish(not args.include_unpolished):
            sys.stdout.write(f">{name}\n{data}\n")
        if args.report:
            polisher.report.write(args.report)
    except JournalError as e:
        # same single-line contract as a malformed fault spec: resuming
        # against the wrong inputs must fail loudly before any compute
        print(e, file=sys.stderr)
        return 1
    except NativeError as e:
        # the reference binary surfaces runtime errors as the what() text
        # and a non-zero exit (src/main.cpp catches nothing); a Python
        # traceback is not that interface — and errors fire well past
        # construction (empty target set, duplicate sequences, ... in
        # rt_pipeline.cpp initialize/stitch)
        print(e, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
