"""ElasticPool: worker-process lifecycle for chunk fleets.

One abstraction for both controllers: the distrib coordinator runs it
at a fixed size (min == max, filled once by ``start()``), the fleet
plane grows and shrinks it from live signals.  The pool owns process
mechanics only — spawn, reap, drain, kill, and the pool-size timeline
stamped into bench entries; *when* to scale is the owner's policy.

Scale transitions are named control-plane seams with deterministic
fault points (resilience/faults.py):

* ``pool.scale_up``   — checked once per growth decision, before any
  process is spawned.  kill=1 crashes the controller mid-resize (the
  serve recover() interplay test is built on it); an injected raise is
  absorbed, counted in ``counters['scale_up_faults']``, and the growth
  step is skipped — the pool stays at its current size, which is the
  degraded-but-safe outcome.
* ``pool.scale_down`` — checked once per drain decision, same absorb
  semantics.  A skipped scale-down just keeps workers alive.
* ``worker.spawn``    — checked per process launched (inherited from
  the distrib coordinator; a spawn failure shrinks the fleet, never
  kills the run).

Scale-down is *graceful by construction*: a victim is only marked
draining here; the owner answers its next ``fetch`` with ``drain`` —
and a worker only fetches between chunks, so a draining worker never
holds a lease and a canonical journal can never be orphaned by a
resize.

Threading: every mutating entry point runs under the owner's condition
variable (the coordinator's / plane's ``_cv``), exactly like the
process dict this replaces.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional

from .. import obs
from ..resilience import faults


class ElasticPool:  # concurrency: every mutating entry point is called under the owner's _cv (documented contract, same as the coordinator's former _procs dict)
    def __init__(self, logs_dir: str, min_workers: int, max_workers: int,
                 env_fn: Optional[Callable[[int], dict]] = None,
                 port: int = 0,
                 on_spawn: Optional[Callable[[int, int], None]] = None,
                 on_spawn_failure: Optional[
                     Callable[[int, BaseException], None]] = None):
        self.logs_dir = logs_dir
        self.min_workers = max(0, int(min_workers))
        self.max_workers = max(self.min_workers, int(max_workers))
        self.port = port            # set by the owner before start()
        self._env_fn = env_fn
        self._on_spawn = on_spawn
        self._on_spawn_failure = on_spawn_failure
        self._procs: Dict[int, subprocess.Popen] = {}
        self._draining: set = set()
        self._reaped: set = set()
        self._next_index = 0
        self.counters: Dict[str, int] = {}
        self.size_timeline: List[list] = []   # [t_rel_s, live] samples
        self._t0 = time.monotonic()

    # -- introspection ------------------------------------------------------

    def live(self) -> int:
        """Processes still running (draining ones included — they hold
        no lease but still count against the ceiling until they exit)."""
        return sum(1 for p in self._procs.values() if p.poll() is None)

    def active(self) -> int:
        """Live workers that are not draining — the dispatch capacity."""
        return sum(1 for i, p in self._procs.items()
                   if p.poll() is None and i not in self._draining)

    def is_draining(self, worker: int) -> bool:
        return worker in self._draining

    def indices(self) -> List[int]:
        return sorted(self._procs)

    def alive_indices(self) -> List[int]:
        return sorted(i for i, p in self._procs.items()
                      if p.poll() is None)

    def _count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def _sample(self) -> None:
        self.size_timeline.append(
            [round(time.monotonic() - self._t0, 3), self.live()])

    # -- spawning -----------------------------------------------------------

    def _spawn_one(self) -> Optional[int]:
        """Launch one worker process; None on (injected or real) spawn
        failure — a failed spawn shrinks the fleet, it must not kill
        the run."""
        index = self._next_index
        self._next_index += 1
        try:
            faults.check("worker.spawn")
            os.makedirs(self.logs_dir, exist_ok=True)
            log = open(os.path.join(self.logs_dir,
                                    f"worker{index}.log"), "w")
            env = self._env_fn(index) if self._env_fn else None
            proc = subprocess.Popen(
                [sys.executable, "-m", "racon_tpu.distrib.worker",
                 "--port", str(self.port), "--worker", str(index)],
                env=env, stdout=log, stderr=log)
            log.close()
        except Exception as e:  # noqa: BLE001 — injected or real; the
            # owner records it and the run continues on fewer workers
            self._count("spawn_failures")
            if self._on_spawn_failure:
                self._on_spawn_failure(index, e)
            return None
        self._procs[index] = proc
        self._count("workers_spawned")
        if self._on_spawn:
            self._on_spawn(index, proc.pid)
        self._sample()
        return index

    def start(self) -> int:
        """Fill the pool to its floor (no scale event — the floor is
        the configured baseline, not a growth decision)."""
        spawned = 0
        for _ in range(self.min_workers):
            if self._spawn_one() is not None:
                spawned += 1
        return spawned

    def scale_up(self, n: int = 1, cause: str = "") -> int:
        """Grow by up to n workers (bounded by the ceiling); returns
        how many actually spawned.  One ``pool.scale_up`` check guards
        the whole decision."""
        room = self.max_workers - self.live()
        n = min(n, room)
        if n <= 0:
            return 0
        try:
            faults.check("pool.scale_up")
        except Exception:  # noqa: BLE001 — absorbed: a faulted resize
            # skips the growth step; staying small is the safe outcome
            self._count("scale_up_faults")
            return 0
        spawned = sum(1 for _ in range(n)
                      if self._spawn_one() is not None)
        if spawned:
            self._count("scale_ups")
            obs.count("fleet.scale_ups", spawned)
            obs.event("fleet.scale_up", added=spawned, live=self.live(),
                      cause=cause)
        return spawned

    # -- draining / reaping -------------------------------------------------

    def scale_down(self, n: int = 1, cause: str = "") -> List[int]:
        """Mark up to n workers draining (never below the floor);
        returns the victim indices.  The owner answers each victim's
        next fetch with ``drain`` — a worker only fetches between
        chunks, so no lease (and no canonical journal) is ever cut."""
        victims: List[int] = []
        headroom = self.active() - self.min_workers
        n = min(n, headroom)
        if n <= 0:
            return victims
        try:
            faults.check("pool.scale_down")
        except Exception:  # noqa: BLE001 — absorbed: a faulted drain
            # keeps the worker alive, which is the safe outcome
            self._count("scale_down_faults")
            return victims
        # newest first: oldest workers have the hottest kernel caches
        for index in sorted(self._procs, reverse=True):
            if len(victims) >= n:
                break
            if (self._procs[index].poll() is None
                    and index not in self._draining):
                self._draining.add(index)
                victims.append(index)
        if victims:
            self._count("scale_downs", len(victims))
            obs.count("fleet.scale_downs", len(victims))
            obs.event("fleet.scale_down", drained=victims,
                      live=self.live(), cause=cause)
            self._sample()
        return victims

    def reap(self) -> List[tuple]:
        """Newly-exited workers as (index, returncode, was_draining) —
        each reported exactly once.  The owner decides whether an exit
        is a death (lease reclaim) or a completed drain."""
        out = []
        for index, proc in self._procs.items():
            if proc.poll() is not None and index not in self._reaped:
                self._reaped.add(index)
                out.append((index, proc.returncode,
                            index in self._draining))
        if out:
            self._sample()
        return out

    # -- shutdown -----------------------------------------------------------

    def shutdown(self, timeout: float = 5.0) -> None:
        """Wait for workers to drain out, then kill any leftover — the
        zero-leaked-processes guarantee the chaos CI gates on."""
        t0 = time.monotonic()
        for p in self._procs.values():
            while p.poll() is None and time.monotonic() - t0 < timeout:
                time.sleep(0.05)
            if p.poll() is None:
                p.kill()
                p.wait()
        self._sample()
    # shutdown() runs after the owner's serving loop has stopped; the
    # wait/kill sweep deliberately happens outside any lock so a slow
    # worker exit cannot stall connection teardown elsewhere.
