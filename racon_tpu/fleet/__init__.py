"""Elastic multi-tenant polishing fleet: the shared control-plane core.

`racon-tpu serve` (whole-job scheduler, serve/scheduler.py) and
`racon-tpu distrib` (single-job chunk coordinator,
distrib/coordinator.py) grew the same machinery twice — queues with
round-robin fairness, worker processes, leases.  This package is the
refactor that gives both one core, plus the piece neither had: an
autoscaling chunk-level control plane.

* ``queues``  — per-tenant FIFOs with priority lanes served in
  round-robin rotation (the scheduler's fairness, generalized).
* ``leases``  — the TTL lease + chunk lifecycle shared by the distrib
  coordinator and the fleet plane (moved from coordinator.py).
* ``pool``    — ``ElasticPool``: worker-process lifecycle (spawn, reap,
  drain, kill) with deterministic ``pool.scale_up`` /
  ``pool.scale_down`` fault points.  The coordinator uses it at a fixed
  size (min == max); the plane scales it from live signals.
* ``plane``   — ``FleetPlane``: many jobs, one chunk queue, one elastic
  worker pool.  Work-stealing between jobs, per-tenant quotas and
  priorities, speculation and lease reclaim inherited from the distrib
  layer, graceful scale-down that drains leases, and a host-oracle
  floor so output stays byte-identical under any churn.
"""

from .. import config
from .queues import TenantQueues  # noqa: F401
from .leases import Chunk, Lease  # noqa: F401
from .pool import ElasticPool  # noqa: F401


#: Fleet knob accessors (registered in racon_tpu/config.py; README has
#: the docs rows).  Centralized here so the scheduler, the plane, and
#: the serve CLI share defaults.

def fleet_min_workers() -> int:
    return config.get_int("RACON_TPU_FLEET_MIN_WORKERS")


def fleet_max_workers() -> int:
    return config.get_int("RACON_TPU_FLEET_MAX_WORKERS")


def fleet_scale_p95_ms() -> float:
    return config.get_float("RACON_TPU_FLEET_SCALE_P95_MS")


def fleet_steal_enabled() -> bool:
    return config.get_bool("RACON_TPU_FLEET_STEAL")


def fleet_tenant_quota() -> int:
    return config.get_int("RACON_TPU_FLEET_TENANT_QUOTA")
