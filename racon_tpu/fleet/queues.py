"""Per-tenant FIFOs with priority lanes, served in round-robin rotation.

This is the serve scheduler's fairness structure (one FIFO per
submitter, submitters served in rotation so one flooding client cannot
starve the rest) extracted and generalized with priority lanes: within
a queue set, the highest priority present anywhere is served first, and
round-robin fairness applies among the tenants that have work at that
priority.  Priority orders service, fairness orders tenants — a
high-priority flood from one tenant still interleaves with other
tenants' high-priority work, and only outranks lower lanes.

Not internally locked: every caller (scheduler, fleet plane) already
serializes access under its own condition variable, exactly like the
dict-of-deques this replaces.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional


class TenantQueues:
    """Tenant -> priority -> FIFO, with a tenant rotation per pop."""

    def __init__(self) -> None:
        self._q: Dict[str, Dict[int, deque]] = {}
        self._rr: List[str] = []   # tenant rotation, front = next served

    def push(self, tenant: str, item, priority: int = 0) -> None:
        lanes = self._q.get(tenant)
        if lanes is None:
            lanes = self._q[tenant] = {}
            self._rr.append(tenant)
        q = lanes.get(priority)
        if q is None:
            q = lanes[priority] = deque()
        q.append(item)

    def pop(self):
        """Next item: the highest priority with queued work anywhere;
        among tenants holding that priority, the first in the rotation.
        The served tenant moves to the back of the rotation."""
        best: Optional[int] = None
        for lanes in self._q.values():
            for prio, q in lanes.items():
                if q and (best is None or prio > best):
                    best = prio
        if best is None:
            return None
        for i, tenant in enumerate(self._rr):
            q = self._q[tenant].get(best)
            if q:
                self._rr.append(self._rr.pop(i))
                return q.popleft()
        return None

    def remove(self, tenant: str, item) -> bool:
        """Remove a specific queued item (cancellation); True if found."""
        lanes = self._q.get(tenant)
        if not lanes:
            return False
        for q in lanes.values():
            if item in q:
                q.remove(item)
                return True
        return False

    def __len__(self) -> int:
        return sum(len(q) for lanes in self._q.values()
                   for q in lanes.values())

    def queued_for(self, tenant: str) -> int:
        return sum(len(q) for q in self._q.get(tenant, {}).values())

    def per_tenant(self) -> Dict[str, int]:
        """Queued-item counts by tenant (zero-count tenants included —
        they stay in the rotation once seen)."""
        return {t: self.queued_for(t) for t in self._rr}
