"""FleetPlane: the autoscaling, multi-job chunk-level control plane.

Where the serve scheduler multiplexes whole jobs onto one resident
session and the distrib coordinator farms chunks of a *single* job to a
*static* worker list, the plane does both at once: every admitted job
is split into contig chunks (``polisher._split_fasta`` — the same
base-balanced split the phase pipeline uses, so chunked output
concatenates byte-identically), all chunks share one dispatch queue,
and an ``ElasticPool`` of `racon_tpu.distrib.worker` processes grows
and shrinks from live signals.  Workers are completely agnostic: the
plane speaks the exact distrib wire protocol (serve/protocol.py), so
the same worker binary serves a fixed coordinator or an elastic plane.

Robustness model, layered on the shared lease core (fleet/leases.py):

* **Affinity + work-stealing.**  A worker prefers chunks of the job it
  last served (hot inputs, hot kernel geometries).  When its job has no
  eligible chunk but others do, it *steals* — tenant-fair rotation,
  highest job priority first — guarded by the deterministic
  ``pool.steal`` fault point and counted/traced (``fleet.steal``).
  ``RACON_TPU_FLEET_STEAL=0`` pins workers to their job instead.
* **Autoscaling.**  The monitor grows the pool one worker per tick when
  a backlog is pending and the recent chunk queueing p95 exceeds
  ``RACON_TPU_FLEET_SCALE_P95_MS`` (or the backlog dwarfs capacity, or
  no worker is active), and drains one worker per idle second above the
  floor.  Both transitions carry fault points (``pool.scale_up`` /
  ``pool.scale_down``); scale-down is drain-based, so a resize can
  never cut a lease or orphan a canonical journal.
* **Leases, speculation, reclaim.**  Exactly the distrib discipline:
  TTL leases with heartbeat renewal, EOF as the fast death signal,
  speculative duplicates for stragglers, exponential backoff on
  re-dispatch, and ``lease.reclaim``-guarded reclaim that releases a
  dead holder's canonical journals so the re-run resumes.
* **Host floor.**  A chunk that exhausts its retry budget — or every
  chunk, when the fleet collapses and cannot respawn — runs in the
  plane through the host-oracle CLI, recorded as a ``fleet -> local``
  degradation in the RunReport.  Output stays byte-identical on every
  path.

Tracing: when armed, dispatches emit ``distrib.dispatch`` events with
fresh child span ids and workers parent their ``distrib.chunk`` spans
under them, so ``python -m racon_tpu.obs fleet`` validates the merged
plane trace exactly like a coordinator trace — with ``fleet.scale_up``
/ ``fleet.scale_down`` / ``fleet.steal`` instant events interleaved.
"""

from __future__ import annotations

import os
import socket
import statistics
import subprocess
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import obs
from ..obs import context, flight, slo
from ..polisher import _split_fasta
from ..resilience.report import PhaseReport, RunReport
from ..serve.protocol import read_message, write_message
from ..distrib.common import (SCOPED_KNOBS, distrib_fault_worker,
                              distrib_heartbeat, distrib_lease_ttl,
                              distrib_max_retries, distrib_retry_base,
                              distrib_speculate)
from . import (fleet_max_workers, fleet_min_workers, fleet_scale_p95_ms,
               fleet_steal_enabled)
from .leases import (Chunk, Lease, fire_reclaim_fault,
                     release_worker_leases)
from .pool import ElasticPool

#: Lattice tiers of the plane phase (same naming as distrib: the fleet
#: is the device-analogue, local is the in-controller oracle floor).
TIERS = ("fleet", "local")

JOB_TERMINAL = ("done", "failed", "cancelled")


class FleetJob:
    """One admitted job: its inputs, its chunks, and its lifecycle
    (running -> done | failed | cancelled)."""

    def __init__(self, job_id: str, tenant: str, priority: int,
                 sequences: str, overlaps: str, target: str, args: dict,
                 include_unpolished: bool, backend: str, workdir: str,
                 on_done: Optional[Callable] = None):
        self.id = job_id
        self.tenant = tenant
        self.priority = priority
        self.sequences = sequences
        self.overlaps = overlaps
        self.target = target
        self.args = args
        self.include_unpolished = include_unpolished
        self.backend = backend
        self.workdir = workdir
        self.on_done = on_done     # (state, result, error) after terminal
        self.state = "running"
        self.error: Optional[str] = None
        self.result: Optional[dict] = None
        self.chunks: List[Chunk] = []
        self.done = threading.Event()
        self.t_submit = time.monotonic()
        self.t_end: Optional[float] = None
        # ledger stage_s fragment (obs/ledger.py): per-stage seconds
        # accumulated across this job's chunks — plane queue waits plus
        # the workers' report-derived compute stages.  Chunks run in
        # parallel, so these are resource-seconds, not wall slices.
        self.stage_s: Dict[str, float] = {}

    def add_stage(self, stage: str, seconds) -> None:
        # call with the plane's _cv held
        try:
            s = float(seconds)
        except (TypeError, ValueError):
            return
        if s >= 0:
            self.stage_s[stage] = self.stage_s.get(stage, 0.0) + s

    def unfinished(self) -> int:
        return sum(1 for c in self.chunks if c.state != "done")


class FleetPlane:
    def __init__(self, workdir: str,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 lease_ttl: Optional[float] = None,
                 max_retries: Optional[int] = None,
                 backend: str = "cpu",
                 trace_path: Optional[str] = None,
                 report_path: Optional[str] = None):
        self.workdir = workdir
        self.min_workers = (fleet_min_workers() if min_workers is None
                            else min_workers)
        self.max_workers = max(self.min_workers, 1 if max_workers is None
                               else max_workers)
        if max_workers is None:
            self.max_workers = max(self.min_workers, fleet_max_workers())
        self.lease_ttl = (distrib_lease_ttl() if lease_ttl is None
                          else lease_ttl)
        self.max_retries = (distrib_max_retries() if max_retries is None
                            else max_retries)
        self.backend = backend
        self.trace_path = trace_path
        self.report_path = report_path

        self.jobs: Dict[str, FleetJob] = {}
        self.chunks: List[Chunk] = []          # global chunk table
        self.counters: Dict[str, int] = {}
        self.completed_walls: List[float] = []
        self.queue_waits: List[float] = []     # eligible->dispatch, s
        self.worker_stats: Dict[int, dict] = {}
        self._staleness_max = 0.0
        self._affinity: Dict[int, str] = {}    # worker -> last job id
        self._tenant_rr: List[str] = []        # steal-order rotation
        self._ctx: Optional[dict] = None
        self._last_tick = 0.0
        self._last_scale = 0.0
        self._idle_ticks = 0
        self._respawn_failures = 0
        self._degraded = False
        self.report = RunReport()
        self.phase = PhaseReport("fleet", TIERS)
        self.report.attach(self.phase)
        self._cv = threading.Condition()
        self._stopping = False
        self._dead_workers = set()
        self._sock: Optional[socket.socket] = None
        self._monitor_thread: Optional[threading.Thread] = None
        self.port = 0
        self.pool = ElasticPool(
            logs_dir=os.path.join(workdir, "workers"),
            min_workers=self.min_workers, max_workers=self.max_workers,
            env_fn=self._worker_env,
            on_spawn=lambda i, pid: obs.event("fleet.spawn", worker=i,
                                              pid=pid),
            on_spawn_failure=self._on_spawn_failure)

    # -- counters -----------------------------------------------------------

    def _count(self, name: str, n: int = 1) -> None:
        # Condition wraps an RLock, so this is safe (and cheap) from
        # call sites that already hold self._cv.
        with self._cv:
            self.counters[name] = self.counters.get(name, 0) + n
        obs.count(f"fleet.{name}", n)

    def _on_spawn_failure(self, index: int, exc: BaseException) -> None:
        self.phase.record_failure("fleet", exc)  # concurrency: PhaseReport counters are guarded by the pool caller's _cv (monitor/start paths)
        obs.event("fleet.spawn_failed", worker=index,
                  error=f"{type(exc).__name__}: {exc}")

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Arm tracing/flight, bind the dispatch socket, fill the pool
        to its floor, start the monitor.  The plane owns the process
        tracer for its lifetime (with the plane on, device jobs run in
        workers, not in-process, so nothing else arms it)."""
        obs.reset()
        obs.set_role("fleet")
        context.activate(context.fresh())
        obs.configure(trace_path=self.trace_path)
        self._ctx = context.current() if obs.enabled() else None
        os.makedirs(self.workdir, exist_ok=True)
        flight.set_dir(self.workdir)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(("127.0.0.1", 0))
        self.port = self._sock.getsockname()[1]
        self._sock.listen(16)
        t = threading.Thread(target=self._accept_loop,
                             name="fleet-accept", daemon=True)
        t.start()
        with self._cv:
            self.pool.port = self.port
            self.pool.start()
        self._monitor_thread = threading.Thread(
            target=self._monitor, name="fleet-monitor", daemon=True)
        self._monitor_thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        """Graceful shutdown: stop dispatching (every fetch drains),
        wait the workers out, kill leftovers, write report + trace."""
        with self._cv:
            if self._stopping:
                return
            self._stopping = True
            self._cv.notify_all()
        if self._monitor_thread is not None:
            self._monitor_thread.join(timeout)
        self.pool.shutdown(timeout=max(1.0, timeout / 2))
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self.report.finalize()
        self.report.flight = flight.scan(self.workdir)
        if self.report.flight:
            self._count("flight_dumps", len(self.report.flight))
        with self._cv:
            self.phase.extra.update(self.counters)
            self.phase.extra.update(self.pool.counters)
        if self.report_path:
            self.report.write(self.report_path)
        obs.release(write=True)
        context.clear()

    def _worker_env(self, index: int) -> dict:
        env = dict(os.environ)
        for k in SCOPED_KNOBS:
            env.pop(k, None)
        # fault scoping: exactly one worker inherits RACON_TPU_FAULT, so
        # a chaos run kills a known worker instead of the whole fleet
        if "RACON_TPU_FAULT" in env and index != distrib_fault_worker():
            env.pop("RACON_TPU_FAULT", None)
        return env

    # -- submission ---------------------------------------------------------

    def submit_job(self, job_id: str, sequences: str, overlaps: str,
                   target: str, args: dict, include_unpolished: bool,
                   backend: str, workdir: str, tenant: str = "local",
                   priority: int = 0,
                   on_done: Optional[Callable] = None) -> FleetJob:
        """Admit one job: split it into chunks and make them eligible.
        Returns immediately; ``on_done(state, result, error)`` fires
        (off the submitter's thread) when the job is terminal."""
        chunks_dir = os.path.join(workdir, "chunks")
        os.makedirs(chunks_dir, exist_ok=True)
        # the split is deterministic in (target, hint): a restarted
        # daemon re-splits identically and chunk journals line up
        paths = _split_fasta(target, max(2, 2 * self.max_workers),
                             chunks_dir)
        if paths is None:
            paths = [target]
        job = FleetJob(job_id, tenant, priority, sequences, overlaps,
                       target, args, include_unpolished,
                       backend or self.backend, workdir, on_done)
        with self._cv:
            if self._stopping:
                raise RuntimeError("fleet plane is stopping")
            if job_id in self.jobs and \
                    self.jobs[job_id].state not in JOB_TERMINAL:
                raise RuntimeError(f"job {job_id!r} is already "
                                   f"{self.jobs[job_id].state}")
            base = len(self.chunks)
            for i, p in enumerate(paths):
                cd = os.path.join(chunks_dir, f"chunk{i:03d}")
                os.makedirs(cd, exist_ok=True)
                c = Chunk(base + i, p, cd)
                c.job = job           # backrefs for multi-job dispatch
                c.pos = i             # position inside the job's gather
                job.chunks.append(c)
                self.chunks.append(c)
            self.jobs[job_id] = job
            self.phase.total += len(job.chunks)
            if tenant not in self._tenant_rr:
                self._tenant_rr.append(tenant)
            self._count("jobs_admitted")
            self._cv.notify_all()
        return job

    def cancel_job(self, job_id: str) -> bool:
        """Cancel a job: pending chunks never dispatch again, running
        attempts are told to stop renewing on their next heartbeat and
        their late results are discarded.  True if the job was live."""
        with self._cv:
            job = self.jobs.get(job_id)
            if job is None or job.state in JOB_TERMINAL:
                return False
            job.state = "cancelled"
            job.error = "cancelled"
            job.t_end = time.monotonic()
            self._count("jobs_cancelled")
            self._cv.notify_all()
        self._finish_job(job, "cancelled", error="cancelled mid-run")
        return True

    # -- connection handling ------------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return   # socket closed during shutdown
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="fleet-conn", daemon=True)
            t.start()

    def _serve_conn(self, conn: socket.socket) -> None:
        worker = -1
        try:
            f = conn.makefile("rwb")
            while True:
                try:
                    req = read_message(f)
                    if req is None:
                        break
                    if "worker" in req:
                        worker = int(req["worker"])
                    resp = self._dispatch(req)
                except (ValueError, KeyError, TypeError) as e:
                    resp = {"ok": False, "error": f"{e}"}
                except Exception as e:  # noqa: BLE001 — one bad request
                    # must not take down the plane
                    resp = {"ok": False,
                            "error": f"{type(e).__name__}: {e}"}
                write_message(f, resp)
        except (OSError, BrokenPipeError, ConnectionResetError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
            # EOF on any of a worker's connections: a clean drain is a
            # completed scale-down; anything else is the fast death
            # signal and reclaims the worker's leases right now
            if worker >= 0:
                if self.pool.is_draining(worker):
                    self._count("workers_drained")
                else:
                    self._worker_dead(worker, "connection lost")

    def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        if op == "hello":
            return {"ok": True, "lease_ttl": self.lease_ttl,
                    "heartbeat": distrib_heartbeat(self.lease_ttl)}
        if op == "fetch":
            return self._fetch(int(req["worker"]))
        if op == "heartbeat":
            return self._heartbeat(int(req["worker"]), int(req["chunk"]),
                                   int(req["attempt"]))
        if op == "result":
            return self._result(req)
        if op == "error":
            return self._chunk_error(req)
        if op == "stats":
            return self._stats()
        raise ValueError(f"unknown op {op!r}")

    # -- assignment ---------------------------------------------------------

    def _eligible(self, now: float) -> List[Chunk]:
        """Dispatchable chunks (call with the lock held)."""
        return [c for c in self.chunks
                if c.state == "pending" and not c.local
                and c.next_eligible <= now
                and c.job.state == "running"]

    def _fetch(self, worker: int) -> dict:
        with self._cv:
            if self._stopping or self.pool.is_draining(worker):
                # a worker only fetches between chunks, so a drain
                # answer here is graceful by construction: it holds no
                # lease and owns no canonical journal
                return {"ok": True, "drain": True}
            now = time.monotonic()
            eligible = self._eligible(now)
            aff = self.jobs.get(self._affinity.get(worker, ""))
            if aff is not None and aff.state == "running":
                own = [c for c in eligible if c.job is aff]
                if own:
                    chunk = min(own, key=lambda c: (worker in c.tried,
                                                    c.index))
                    return self._assign(chunk, worker, speculative=False)
                if eligible:
                    # the worker's job is live but starved here: take a
                    # chunk from another job (tenant-fair, priority
                    # first) — the cross-job steal
                    if not fleet_steal_enabled():
                        return {"ok": True, "wait": True, "poll_s": 0.2}
                    try:
                        from ..resilience import faults
                        faults.check("pool.steal")
                    except Exception:  # noqa: BLE001 — absorbed: a
                        # faulted steal skips this fetch; the chunk
                        # stays eligible for the next one
                        self._count("steal_faults")
                        return {"ok": True, "wait": True, "poll_s": 0.2}
                    chunk = self._pick_fair(eligible, worker)
                    self._count("steals")
                    obs.event("fleet.steal", chunk=chunk.index,
                              worker=worker, job=chunk.job.id,
                              victim_tenant=chunk.job.tenant,
                              from_job=aff.id)
                    return self._assign(chunk, worker, speculative=False)
            elif eligible:
                chunk = self._pick_fair(eligible, worker)
                return self._assign(chunk, worker, speculative=False)
            chunk = self._straggler(worker, now)
            if chunk is not None:
                self._count("speculative")
                return self._assign(chunk, worker, speculative=True)
            return {"ok": True, "wait": True, "poll_s": 0.2}

    def _pick_fair(self, eligible: List[Chunk], worker: int) -> Chunk:
        """Tenant-fair pick: the first tenant in the rotation with an
        eligible chunk is served and rotates to the back; within a
        tenant, highest job priority first, then a chunk this worker
        has not tried, then global order (call with the lock held)."""
        by_tenant: Dict[str, List[Chunk]] = {}
        for c in eligible:
            by_tenant.setdefault(c.job.tenant, []).append(c)
        for t in by_tenant:
            if t not in self._tenant_rr:
                self._tenant_rr.append(t)
        for i, t in enumerate(self._tenant_rr):
            cs = by_tenant.get(t)
            if cs:
                self._tenant_rr.append(self._tenant_rr.pop(i))
                return min(cs, key=lambda c: (-c.job.priority,
                                              worker in c.tried, c.index))
        return min(eligible, key=lambda c: c.index)

    def _straggler(self, worker: int, now: float) -> Optional[Chunk]:
        """The longest-running chunk past the speculation threshold
        that `worker` could duplicate (call with the lock held)."""
        factor = distrib_speculate()
        if factor <= 0 or not self.completed_walls:
            return None
        median = statistics.median(self.completed_walls)
        best, best_elapsed = None, 0.0
        for c in self.chunks:
            if (c.state != "running" or c.local or worker in c.tried
                    or len(c.leases) >= 2 or not c.leases
                    or c.job.state != "running"):
                continue
            elapsed = now - min(ls.t_start for ls in c.leases.values())
            if elapsed > factor * median and elapsed > best_elapsed:
                best, best_elapsed = c, elapsed
        return best

    def _assign(self, c: Chunk, worker: int, speculative: bool) -> dict:  # concurrency: caller holds this plane's _cv; a Chunk is owned by exactly one plane, so the coordinator's _cv never guards the same instance
        c.attempts += 1
        attempt = c.attempts
        c.state = "running"
        c.tried.add(worker)
        canonical = not c.journal_held
        if canonical:
            c.journal_held = True
            journal = c.journal
        else:
            journal = os.path.join(c.dir, f"journal.a{attempt}.jsonl")
        c.leases[attempt] = Lease(worker, attempt, self.lease_ttl,
                                  canonical)
        self._affinity[worker] = c.job.id
        wait = max(0.0, time.monotonic() - max(c.t_pending,
                                               c.next_eligible))
        self.queue_waits.append(wait)
        # plane-side queueing rides the job ledger's dispatch stage:
        # with a plane attached the scheduler's own dispatch is instant
        # and the real wait happens here, per chunk
        c.job.add_stage("dispatch", wait)
        self._count("dispatches")
        if attempt > 1 and not speculative:
            self._count("redispatches")
        # same dispatch/span contract as the distrib coordinator: the
        # worker stamps this span id as its distrib.chunk parent, so
        # `obs fleet` parents the merged plane trace identically; the
        # job id lets `obs critpath` group chunk spans per job
        ctx = context.child(self._ctx)
        obs.event("distrib.dispatch", chunk=c.index, worker=worker,
                  attempt=attempt, speculative=speculative,
                  canonical_journal=canonical, job=c.job.id,
                  tenant=c.job.tenant,
                  trace_id=(ctx or {}).get("trace_id"),
                  span_id=(ctx or {}).get("parent"))
        return {"ok": True, "chunk": {
            "index": c.index, "attempt": attempt,
            "sequences": c.job.sequences, "overlaps": c.job.overlaps,
            "target": c.target, "args": c.job.args,
            "include_unpolished": c.job.include_unpolished,
            "backend": c.job.backend, "journal": journal,
            "output": os.path.join(c.dir, f"out.a{attempt}.fasta"),
            "trace": ctx,
        }}

    # -- worker messages ----------------------------------------------------

    def _heartbeat(self, worker: int, index: int, attempt: int) -> dict:
        with self._cv:
            c = self.chunks[index]
            lease = c.leases.get(attempt)
            if (lease is None or c.state == "done"
                    or c.job.state != "running"):
                return {"ok": True, "cancel": True}
            now = time.monotonic()
            self._staleness_max = max(self._staleness_max,
                                      now - lease.last_beat)
            lease.last_beat = now
            lease.deadline = now + self.lease_ttl
            self._count("heartbeats")
            return {"ok": True, "cancel": False}

    def _result(self, req: dict) -> dict:
        index = int(req["chunk"])
        attempt = int(req["attempt"])
        stats = req.get("stats") or {}
        finished: Optional[FleetJob] = None
        with self._cv:
            c = self.chunks[index]
            lease = c.leases.pop(attempt, None)
            if c.state == "done" or c.job.state != "running":
                self._count("duplicates")
                obs.event("fleet.duplicate", chunk=index,
                          worker=int(req["worker"]), attempt=attempt)
                return {"ok": True, "accepted": False}
            c.state = "done"
            c.served_by = "fleet"
            c.output = str(req["output"])
            c.stats = stats
            self.phase.record_served("fleet")
            if lease is not None:
                wall = time.monotonic() - lease.t_start
                self.completed_walls.append(wall)
                self.phase.add_wall("fleet", wall)
            replayed = int(stats.get("journal_replayed") or 0)
            if replayed:
                self._count("journal_replayed", replayed)
            self._count("chunks_fleet")
            # fold the worker's report-derived stage durations into the
            # job's ledger fragment (shipped onward in _gather)
            frag = stats.get("stage_s")
            if isinstance(frag, dict):
                for stage, s in frag.items():
                    if isinstance(stage, str):
                        c.job.add_stage(stage, s)
            ws = self.worker_stats.setdefault(
                int(req["worker"]),
                {"chunks": 0, "wall_s": 0.0, "kernel_wall_s": 0.0,
                 "rss_mb": 0.0})
            ws["chunks"] += 1
            ws["wall_s"] = round(
                ws["wall_s"] + float(stats.get("wall_s") or 0.0), 4)
            ws["kernel_wall_s"] = round(
                ws["kernel_wall_s"]
                + float(stats.get("kernel_wall_s") or 0.0), 4)
            # peak RSS per worker: the memory dimension of
            # fleet_telemetry() the admission ladder reads
            ws["rss_mb"] = max(ws.get("rss_mb", 0.0),
                               float(stats.get("rss_mb") or 0.0))
            obs.event("fleet.chunk_done", chunk=index, job=c.job.id,
                      worker=int(req["worker"]), attempt=attempt,
                      replayed=replayed)
            absorbed = obs.absorb(req.get("obs"))
            if absorbed:
                self._count("obs_events_absorbed", absorbed)
            if c.job.unfinished() == 0:
                finished = c.job
            self._cv.notify_all()
        if finished is not None:
            self._finish_job(finished, "done")
        return {"ok": True, "accepted": True}

    def _chunk_error(self, req: dict) -> dict:
        index = int(req["chunk"])
        attempt = int(req["attempt"])
        err = str(req.get("error", "worker error"))
        with self._cv:
            c = self.chunks[index]
            lease = c.leases.pop(attempt, None)
            if lease is not None and lease.canonical:
                # the worker survived to report, so its journal writer
                # is closed: the canonical journal is safe to hand on
                c.journal_held = False
            if c.state != "done" and c.job.state == "running":
                self._fail_chunk(c, RuntimeError(err))
            obs.event("fleet.chunk_error", chunk=index,
                      worker=int(req["worker"]), attempt=attempt,
                      error=err)
            return {"ok": True}

    def _stats(self) -> dict:
        with self._cv:
            now = time.monotonic()
            states = {"pending": 0, "running": 0, "done": 0}
            for c in self.chunks:
                states[c.state] = states.get(c.state, 0) + 1
            leases = sum(len(c.leases) for c in self.chunks)
            staleness = 0.0
            for c in self.chunks:
                for ls in c.leases.values():
                    staleness = max(staleness, now - ls.last_beat)
            self._staleness_max = max(self._staleness_max, staleness)
            return {"ok": True,
                    "chunks": states,
                    "leases": leases,
                    "workers": {"live": self.pool.live(),
                                "dead": len(self._dead_workers)},
                    "served": dict(self.phase.served),
                    "staleness_s": round(staleness, 3),
                    "counters": dict(self.counters),
                    "telemetry": obs.telemetry(last=8)}

    # -- failure paths (call with the lock held) ----------------------------

    def _fail_chunk(self, c: Chunk, exc: BaseException) -> None:  # concurrency: caller holds this plane's _cv; a Chunk is owned by exactly one plane
        c.failures += 1
        self.phase.record_failure("fleet", exc)
        self.phase.retries += 1
        if not c.leases and c.state != "done":
            c.state = "pending"
            backoff = distrib_retry_base() * (2 ** (c.failures - 1))
            c.next_eligible = time.monotonic() + backoff
            self._cv.notify_all()

    def _worker_dead(self, worker: int, why: str) -> None:
        with self._cv:
            if worker in self._dead_workers or self._stopping:
                return
            self._dead_workers.add(worker)
            self._count("workers_dead")
            obs.event("fleet.worker_dead", worker=worker, cause=why)
            # the reclaim transition is a named fault point: kill=1
            # crashes the controller mid-reclaim, a raise is absorbed
            # and counted — reclaim itself always proceeds
            if fire_reclaim_fault():
                self._count("reclaim_faults")
            for c in self.chunks:
                popped = release_worker_leases(c, worker)
                if popped:
                    self._count("lease_reclaimed", len(popped))
                    if c.state != "done" and c.job.state == "running":
                        self._fail_chunk(
                            c, RuntimeError(f"worker {worker} died "
                                            f"({why}) holding chunk "
                                            f"{c.index}"))

    def _expire_leases(self) -> None:
        now = time.monotonic()
        with self._cv:
            for c in self.chunks:
                expired = [a for a, ls in c.leases.items()
                           if ls.deadline < now]
                for a in expired:
                    lease = c.leases.pop(a)
                    # NOT releasing the canonical journal: an
                    # unresponsive-but-alive holder may still be writing
                    self._count("lease_expired")
                    obs.event("fleet.lease_expired", chunk=c.index,
                              worker=lease.worker, attempt=a)
                    if c.state != "done" and c.job.state == "running":
                        self._fail_chunk(
                            c, TimeoutError(
                                f"lease on chunk {c.index} expired "
                                f"(worker {lease.worker}, attempt {a})"))

    # -- autoscaling monitor ------------------------------------------------

    def _monitor(self) -> None:
        while True:
            with self._cv:
                if self._stopping:
                    return
            for index, rc, was_draining in self._reap():
                if not was_draining:
                    self._worker_dead(index, f"exited {rc}")
            self._expire_leases()
            now = time.monotonic()
            if now - self._last_scale >= 0.25:
                self._last_scale = now
                self._autoscale(now)
            if now - self._last_tick >= 1.0:
                self._last_tick = now
                self._telemetry_tick(now)
            local_work = []
            with self._cv:
                for c in self.chunks:
                    if (c.failures > self.max_retries and not c.leases
                            and c.state == "pending" and not c.local
                            and c.job.state == "running"):
                        c.local = True
                        self._degrade(f"chunk {c.index} exhausted its "
                                      f"retry budget ({c.failures} "
                                      f"failures > {self.max_retries})")
                local_work = [c for c in self.chunks
                              if c.local and c.state == "pending"
                              and c.job.state == "running"]
            for c in local_work:
                self._run_local(c)
            with self._cv:
                self._cv.wait(0.05)

    def _reap(self):
        with self._cv:
            return self.pool.reap()

    def _autoscale(self, now: float) -> None:
        """One scaling decision per call: grow when a backlog queues
        past the p95 trigger (or capacity is gone), drain when idle
        above the floor.  At most one worker per direction per tick, so
        the pool walks, never thrashes."""
        with self._cv:
            backlog = len(self._eligible(now))
            active = self.pool.active()
            live = self.pool.live()
            leases = sum(len(c.leases) for c in self.chunks)
            recent = self.queue_waits[-50:]
            p95_ms = 0.0
            if recent:
                waits = sorted(recent)
                p95_ms = 1000.0 * waits[min(len(waits) - 1,
                                            int(0.95 * len(waits)))]
            if backlog > 0:
                self._idle_ticks = 0
                # SLO burn is a first-class scale trigger: a multi-window
                # burn-rate alert grows the pool even before the queueing
                # p95 trips (obs/slo.py; the cause string makes the
                # slo-driven growth visible in counters and the trace)
                slo_burn = slo.engine().alerting("")
                if slo_burn:
                    self._count("slo_alert_ticks")
                if active == 0 or p95_ms > fleet_scale_p95_ms() \
                        or backlog >= 4 * active \
                        or (slo_burn and live < self.pool.max_workers):
                    cause = (f"backlog {backlog}, active {active}, "
                             f"queueing p95 {p95_ms:.0f}ms")
                    if slo_burn:
                        cause = f"slo_burn: {cause}"
                    spawned = self.pool.scale_up(1, cause=cause)
                    if slo_burn and spawned:
                        self._count("scale_up_slo")
                    if active == 0 and spawned == 0 and live == 0:
                        self._respawn_failures += 1
                        if self._respawn_failures >= 3:
                            # fleet collapse and the pool cannot come
                            # back: every eligible chunk falls to the
                            # local oracle floor
                            for c in self._eligible(now):
                                c.local = True
                            self._degrade("fleet collapse: no live "
                                          "workers and respawn failing")
                    else:
                        self._respawn_failures = 0
            elif leases == 0 and active > self.pool.min_workers:
                self._idle_ticks += 1
                if self._idle_ticks >= 4:
                    self._idle_ticks = 0
                    self.pool.scale_down(1, cause="idle above floor")
            else:
                self._idle_ticks = 0

    def _telemetry_tick(self, now: float) -> None:
        with self._cv:
            staleness = max(
                (now - ls.last_beat for c in self.chunks
                 for ls in c.leases.values()), default=0.0)
            self._staleness_max = max(self._staleness_max, staleness)
            obs.telemetry_tick(
                queue_depth=sum(1 for c in self.chunks
                                if c.state == "pending"
                                and c.job.state == "running"),
                leases=sum(len(c.leases) for c in self.chunks),
                workers_live=self.pool.live(),
                workers_active=self.pool.active(),
                jobs_running=sum(1 for j in self.jobs.values()
                                 if j.state == "running"),
                staleness_s=round(staleness, 3))

    def _degrade(self, cause: str) -> None:
        """Record the fleet→local lattice step (once per plane life)."""
        if not self._degraded:
            self._degraded = True
            self.phase.record_degrade("fleet", "local",
                                      RuntimeError(cause))

    # -- local (host-oracle) floor ------------------------------------------

    def _run_local(self, c: Chunk) -> None:  # concurrency: chunk-state writes happen under this plane's _cv; a Chunk is owned by exactly one plane
        """Execute one chunk in the plane through the host-oracle CLI —
        the same demotion target as the serve host lane, byte-identical
        output.  A free canonical journal (cpu fingerprint only) is
        resumed; otherwise a fresh local journal."""
        job = c.job
        with self._cv:
            if c.state == "done" or job.state != "running":
                return
            c.state = "running"
            resume = (not c.journal_held) and job.backend == "cpu"
        journal = c.journal if resume else os.path.join(
            c.dir, "journal.local.jsonl")
        out_path = os.path.join(c.dir, "out.local.fasta")
        part = out_path + ".part"
        a = job.args
        cmd = [sys.executable, "-m", "racon_tpu.cli",
               "-w", str(a["window_length"]),
               "-q", str(a["quality_threshold"]),
               "-e", str(a["error_threshold"]),
               "-m", str(a["match"]), "-x", str(a["mismatch"]),
               "-g", str(a["gap"]), "-t", str(a["num_threads"]),
               "--resume-journal", journal]
        if not a["trim"]:
            cmd.append("--no-trimming")
        if a["fragment_correction"]:
            cmd.append("-f")
        if job.include_unpolished:
            cmd.append("-u")
        cmd += [job.sequences, job.overlaps, c.target]
        env = dict(os.environ)
        for k in SCOPED_KNOBS:
            env.pop(k, None)
        t0 = time.monotonic()
        with open(part, "w") as out_f, \
                open(os.path.join(c.dir, "local.stderr.log"), "w") as err_f:
            rc = subprocess.call(cmd, stdout=out_f, stderr=err_f, env=env)
        finished: Optional[FleetJob] = None
        failed = False
        with self._cv:
            if c.state == "done" or job.state != "running":
                self._count("duplicates")   # a late fleet result won
                return
            if rc != 0:
                # the local rung is the floor: a failure here fails the
                # JOB (not the plane) — the scheduler's host lane is
                # the next rung up and re-runs the whole job there
                self.phase.record_failure(
                    "local", RuntimeError(f"local chunk {c.index} "
                                          f"exited {rc}"))
                failed = True
            else:
                os.replace(part, out_path)
                c.state = "done"
                c.served_by = "local"
                c.output = out_path
                self.phase.record_served("local")
                self.phase.add_wall("local", time.monotonic() - t0)
                self._count("chunks_local")
                obs.event("fleet.chunk_local", chunk=c.index, job=job.id)
                if job.unfinished() == 0:
                    finished = job
                self._cv.notify_all()
        if failed:
            with self._cv:
                if job.state == "running":
                    job.state = "failed"
                    job.error = (f"chunk {c.index} failed on the local "
                                 f"rung (exit {rc}; see "
                                 f"{c.dir}/local.stderr.log)")
                    job.t_end = time.monotonic()
                    self._count("jobs_failed")
            self._finish_job(job, "failed", error=job.error)
        elif finished is not None:
            self._finish_job(finished, "done")

    # -- job completion -----------------------------------------------------

    def _finish_job(self, job: FleetJob, state: str,
                    error: Optional[str] = None) -> None:
        """Gather (on done), mark terminal, fire the callback.  Runs
        outside the lock: the gather is file I/O and the callback
        re-enters the scheduler's own lock — holding ours across either
        would order fleet._cv before scheduler._cv."""
        result = None
        if state == "done":
            try:
                result = self._gather(job)
            except Exception as e:  # noqa: BLE001 — a torn gather fails
                # the job, not the plane
                state, error = "failed", f"gather: {type(e).__name__}: {e}"
        with self._cv:
            if job.state == "running" or job.state == "cancelled":
                job.state = state if job.state != "cancelled" \
                    else "cancelled"
            job.result = result
            if error and not job.error:
                job.error = error
            if job.t_end is None:
                job.t_end = time.monotonic()
            if state == "done":
                self._count("jobs_done")
            elif state == "failed":
                self._count("jobs_failed")
            obs.event("fleet.job_done", job=job.id, state=job.state,
                      chunks=len(job.chunks))
            job.done.set()
            self._cv.notify_all()
        if job.on_done is not None:
            job.on_done(job.state, result, job.error)

    def _gather(self, job: FleetJob) -> dict:
        """Ordered gather: chunk outputs concatenate in position order,
        so the polished FASTA is byte-identical to a single-process
        run."""
        out_path = os.path.join(job.workdir, "polished.fasta")
        part = out_path + ".part"
        with open(part, "wb") as out:
            for c in sorted(job.chunks, key=lambda c: c.pos):
                assert c.state == "done" and c.output, c.index
                with open(c.output, "rb") as f:
                    out.write(f.read())
        os.replace(part, out_path)
        records = polished_bp = 0
        with open(out_path) as f:
            for line in f:
                if line.startswith(">"):
                    records += 1
                else:
                    polished_bp += len(line.strip())
        replayed = sum(int(c.stats.get("journal_replayed") or 0)
                       for c in job.chunks)
        served: Dict[str, int] = {}
        for c in job.chunks:
            served[c.served_by or "?"] = served.get(c.served_by or "?",
                                                    0) + 1
        return {
            "job_id": job.id,
            "backend": job.backend,
            "cold": False,
            "wall_s": round(time.monotonic() - job.t_submit, 4),
            "records": records,
            "polished_bp": polished_bp,
            "kernel_builds": 0,
            "journal_replayed": replayed,
            "output": out_path,
            "report": None,
            "trace": None,
            "summary": None,
            "fleet": {"chunks": len(job.chunks), "served": served},
            "ledger": {"stage_s": {k: round(v, 6) for k, v in
                                   sorted(job.stage_s.items())}},
        }

    # -- telemetry ----------------------------------------------------------

    def _queueing_p95(self) -> Optional[float]:
        waits = sorted(self.queue_waits)
        if not waits:
            return None
        return round(waits[min(len(waits) - 1,
                               int(0.95 * len(waits)))], 4)

    def fleet_telemetry(self) -> dict:
        """The per-run fleet telemetry summary stamped into serve stats
        and bench entries."""
        with self._cv:
            return {
                "workers": {str(w): dict(s)
                            for w, s in sorted(self.worker_stats.items())},
                "queueing_p95_s": self._queueing_p95(),
                "staleness_max_s": round(self._staleness_max, 3),
            }

    def snapshot(self) -> dict:
        """Live control-plane snapshot for the serve ``stats`` verb and
        the load-test poller: pool size/limits, counters, timeline."""
        with self._cv:
            jobs: Dict[str, int] = {}
            for j in self.jobs.values():
                jobs[j.state] = jobs.get(j.state, 0) + 1
            counters = dict(self.counters)
            counters.update(self.pool.counters)
            return {
                "workers": {"live": self.pool.live(),
                            "active": self.pool.active(),
                            "dead": len(self._dead_workers)},
                "min_workers": self.pool.min_workers,
                "max_workers": self.pool.max_workers,
                "jobs": jobs,
                "chunks_pending": sum(1 for c in self.chunks
                                      if c.state == "pending"),
                "counters": counters,
                "queueing_p95_s": self._queueing_p95(),
                "staleness_max_s": round(self._staleness_max, 3),
                "timeline": [list(s) for s in
                             self.pool.size_timeline[-64:]],
            }
