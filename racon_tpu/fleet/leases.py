"""TTL leases and chunk lifecycle — the shared dispatch core.

Moved here from distrib/coordinator.py so the single-job coordinator
and the multi-job fleet plane run the same lease discipline:

* every assignment carries a TTL lease renewed by heartbeats;
* an expired lease re-queues the chunk with exponential backoff;
* a worker EOF reclaims all of its leases immediately (death at socket
  speed, not TTL speed);
* the canonical per-chunk journal has at most one live writer — a
  *known dead* holder releases it (the re-dispatch resumes the
  journaled prefix), a merely-unresponsive holder keeps it and the new
  attempt writes a side journal.

Reclaim is a named control-plane transition: ``fire_reclaim_fault``
checks the deterministic ``lease.reclaim`` injection point before a
dead holder's leases are released.  kill=1 there crashes the controller
mid-reclaim (the recover() path must absorb it); an injected raise is
absorbed at the seam and surfaced as a counter, because reclaim runs
inside connection-teardown paths that must never throw.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from ..resilience import faults


class Lease:
    __slots__ = ("worker", "attempt", "deadline", "t_start", "canonical",
                 "last_beat")

    def __init__(self, worker: int, attempt: int, ttl: float,
                 canonical: bool):
        self.worker = worker
        self.attempt = attempt
        self.t_start = time.monotonic()
        self.deadline = self.t_start + ttl
        self.canonical = canonical   # holds the chunk's primary journal
        self.last_beat = self.t_start   # heartbeat-staleness telemetry


class Chunk:
    """One contig chunk and its dispatch lifecycle."""

    def __init__(self, index: int, target: str, chunk_dir: str):
        self.index = index
        self.target = target
        self.dir = chunk_dir
        self.journal = os.path.join(chunk_dir, "journal.jsonl")
        self.state = "pending"        # pending | running | done
        self.local = False            # demoted to in-controller execution
        self.attempts = 0
        self.failures = 0
        self.next_eligible = 0.0
        self.leases: Dict[int, Lease] = {}
        self.tried = set()            # worker ids that have attempted
        self.journal_held = False     # a (possibly live) writer owns it
        self.output: Optional[str] = None
        self.stats: dict = {}
        self.served_by: Optional[str] = None
        self.t_pending = time.monotonic()   # queue-wait telemetry


def fire_reclaim_fault() -> bool:
    """Check the ``lease.reclaim`` injection point.  kill=1 never
    returns (the deterministic controller crash mid-reclaim); an
    injected raise is absorbed and reported as True so the caller can
    count it — the reclaim itself still proceeds.  False when nothing
    fired."""
    try:
        faults.check("lease.reclaim")
    except Exception:  # noqa: BLE001 — an injected reclaim fault is a
        # modeled hiccup, not a crash: reclaim runs in connection
        # teardown, which must never throw
        return True
    return False


def release_worker_leases(chunk: Chunk, worker: int) -> List[Lease]:  # concurrency: called with the owning control plane's _cv held (coordinator or fleet plane — one instance never spans both)
    """Pop every lease `worker` holds on `chunk`, releasing the
    canonical journal for any it held (the writer is known dead, so the
    re-dispatch may resume it).  Call with the owning lock held."""
    held = [a for a, ls in chunk.leases.items() if ls.worker == worker]
    popped = []
    for a in held:
        lease = chunk.leases.pop(a)
        if lease.canonical:
            chunk.journal_held = False
        popped.append(lease)
    return popped
