"""Shared bucketed-batch executor: the one feeder both device drivers run on.

Extracted from poa_driver.run_consensus_phase's chunk loop so the consensus
and alignment paths share a single serving seam:

* **single-copy packing** — the driver's `pack` hook copies each unit's
  bytes exactly once into preallocated padded buffers; lattice retries and
  bisection probes reuse the packed views instead of re-materializing;
* **depth-Q async dispatch** — for engines whose kernel call is a JAX
  async dispatch (`async_dispatch = True`), up to `depth` packed chunks
  stay in flight, so the host packs chunk N+1 while chunk N executes —
  the analogue of the reference's continuous batch fill running
  concurrently with kernel execution
  (/root/reference/src/cuda/cudapolisher.cpp:83-145);
* **one resilience seam** — the degradation lattice
  (resilience/lattice.py: bounded retry, batch bisection-quarantine,
  tier demotion down to the host floor), the journal taps, the runtime
  sanitizer hooks, and the obs span/counter emission all live in the
  driver-supplied hooks called from exactly one place, so every engine
  inherits identical failure semantics;
* **pack/kernel wall split** — `pack_ns` (host export+pack) vs
  `kernel_ns` (blocked inside the lattice serve) accumulate per executor
  and surface as `report.extra["pack_wall_s"/"kernel_wall_s"]` in the
  drivers, making VERDICT #7's "pack time < kernel time" criterion
  machine-checkable (bench.py stamps the split into its log entries).

The driver supplies an *ops* object (duck-typed; no registration):

    span_name: str            # per-chunk obs span name ("poa.chunk", …)
    async_dispatch: bool      # False = host-orchestrated engine: the
                              # chunk resolves inline through the lattice
                              # (watchdog-wrapped), nothing is queued
    live_tier(ctx, kind)      # best live tier at/below `kind` (None =
                              # the bucket's entry tier); may stash the
                              # kernel handle on ctx
    export(ctx, idxs)         # -> chunk items ([] = nothing to serve)
    pack(ctx, chunk)          # -> packed buffers (single-copy)
    dispatch(ctx, kind, packed, chunk)  # async kernel call -> futures;
                              # owns the pre-dispatch faults.check
    attempt(ctx, kind, sub)   # lattice retry/bisect probe over packed
                              # views; owns its faults.check
    unpack(ctx, kind, outs)   # block on dispatched futures -> results
    span_args(ctx, chunk, pipelined)   # extra span args (dict)
    install(ctx, kind, sub, results)   # journal/sanitize/report seam
    surrender(ctx, items, exported)    # route items to the host floor
    quarantine(ctx, item, exc)         # one poisoned item -> host
    demote(ctx, kind, cause)  # tier died: record + return next tier
    done(ctx, chunk)          # optional: chunk fully resolved — release
                              # any per-chunk packed state
    widen(ctx, kind)          # optional (banded DP): items of the chunk
                              # whose band verify failed and that should
                              # be re-attempted with widened params
                              # ([] = ladder drained).  The executor
                              # loops attempt+install over them reusing
                              # the packed batch — the verify-and-widen
                              # re-dispatch seam (ops/band.py)

Sharded dispatch (optional hooks; engines without them are untouched):

    shard_multiple(ctx, chunk)  # mesh batch-axis size this chunk will
                              # dispatch over (1 = single device).  When
                              # >1 the executor pads the packed buffers
                              # to that multiple HERE — the one place
                              # pad-to-multiple math runs — and counts
                              # the padding + per-device shard rows in
                              # obs (`shard.pad_rows`, `shard.rows.d<i>`)
    demote_shard(ctx, kind, cause)  # a sharded serve died: drop to
                              # single-device dispatch and return True to
                              # retry the SAME tier (the lattice's
                              # `sharded -> single-device` edge); False =
                              # not sharded, demote the tier as usual
"""

from __future__ import annotations

import time
from collections import deque

import numpy as np

from .. import config, obs
from ..resilience import budget
from ..resilience import lattice as rl


def pipeline_depth() -> int:
    """How many packed chunks may be in flight on the device at once."""
    return max(1, config.get_int("RACON_TPU_PIPELINE_DEPTH"))


def pad_to_multiple(packed, m):
    """Pad every packed array's leading dim up to a multiple of `m` by
    repeating the final row — valid rows recomputed and discarded, never
    sentinel garbage, so padded lanes can't poison a kernel.  Returns
    (padded tuple, rows added).  The round-UP replacement for the old
    round-DOWN `parallel.mesh.divisible_batch` remainder spill; every
    sharded engine pads through this one helper."""
    rows = int(np.asarray(packed[0]).shape[0])
    pad = (m - rows % m) % m
    if pad <= 0:
        return tuple(packed), 0
    out = []
    for a in packed:
        a = np.asarray(a)
        out.append(np.concatenate([a, np.repeat(a[-1:], pad, axis=0)],
                                  axis=0))
    return tuple(out), pad


def count_shard_rows(n_real, rows, m) -> int:
    """Shard-size observability for one sharded dispatch of `rows` rows
    (`n_real` of them real work) over `m` mesh shards: padded-row total
    plus one counter per device position, so shard balance ('within one
    batch per device') is checkable from any trace snapshot.  Returns
    the pad-row count.  Shared by the executor's pad seam and the
    host-orchestrated Hirschberg rounds (align_pallas), which pad their
    own pow2 batches."""
    pad = max(0, rows - n_real)
    if pad > 0:
        obs.count("shard.pad_rows", pad)
    obs.count("shard.chunks")
    per_dev = rows // m
    for i in range(m):
        obs.count(f"shard.rows.d{i}", per_dev)
    return pad


class BatchExecutor:
    """Depth-Q pipelined chunk server over a driver-supplied ops seam."""

    def __init__(self, ops, *, depth=None, report=None):
        self.ops = ops
        self.report = report
        self.depth = pipeline_depth() if depth is None else max(1, depth)
        # In-flight chunks: (ctx, chunk, outs, kind). JAX dispatch is
        # async, so with depth Q the host packs/exports chunks N+1..N+Q
        # while chunk N executes. Depth >= 2 keeps the device busy across
        # the host's pack gap even when pack time fluctuates; more mostly
        # adds host memory (Q packed batches).
        self._pending = deque()
        self.pack_ns = 0     # host wall: export + single-copy pack
        self.kernel_ns = 0   # host wall blocked inside the lattice serve
        self.shard_pad_rows = 0  # rows added padding batches to a
        #                          device multiple (sharded mode only)

    def _check_pressure(self) -> None:
        """Hard-watermark reaction at the pack seam: every queued packed
        chunk is host memory, so once the memory budget's hard watermark
        latches the executor stops queuing — depth drops to 1 and each
        pack resolves inline (batched -> stream-sequential, recorded
        once per executor).  Byte-identical: depth only changes when
        results are waited on, never what computes."""
        if self.depth <= 1 or not budget.hard_latched():
            return
        self.depth = 1
        if self.report is not None:
            self.report.record_degrade(
                "batched", "stream-sequential",
                RuntimeError("hard memory watermark"))
        obs.count("mem.depth_collapses")
        self.flush()

    # -- feeding -----------------------------------------------------------
    def submit(self, ctx, idxs) -> None:
        """Export, pack, and dispatch one chunk; drain at depth Q."""
        self._check_pressure()
        ops = self.ops
        kind = ops.live_tier(ctx, None)
        if kind == "host":
            ops.surrender(ctx, idxs, exported=False)
            return
        t0 = time.monotonic_ns()
        chunk = ops.export(ctx, idxs)
        if not chunk:
            self.pack_ns += time.monotonic_ns() - t0
            return
        packed = ops.pack(ctx, chunk)
        shard_m = getattr(ops, "shard_multiple", None)
        if packed is not None and shard_m is not None:
            m = shard_m(ctx, chunk)
            if m > 1:
                packed, _ = pad_to_multiple(packed, m)
                self._count_shard(len(chunk), packed, m)
        self.pack_ns += time.monotonic_ns() - t0
        if not getattr(ops, "async_dispatch", True):
            # host-orchestrated engine: the kernel call IS the blocking
            # compute, so it runs inside the lattice serve (bounded
            # retry + watchdog) rather than as a fire-and-forget dispatch
            self._resolve(ctx, chunk, None, kind)
            return
        try:
            outs = ops.dispatch(ctx, kind, packed, chunk)
        except Exception as e:  # noqa: BLE001 — lattice edge
            # synchronous dispatch failure: resolve this chunk through
            # the lattice right now (retry/bisect/demote)
            if self.report is not None:
                self.report.record_failure(kind, e)
                self.report.retries += 1
            self._resolve(ctx, chunk, None, kind)
            return
        self._pending.append((ctx, chunk, outs, kind))
        if len(self._pending) >= self.depth:
            self._resolve(*self._pending.popleft())

    def flush(self) -> None:
        """Block on every in-flight chunk and install its results."""
        while self._pending:
            self._resolve(*self._pending.popleft())

    # -- resolution --------------------------------------------------------
    def _resolve(self, ctx, chunk, outs, kind) -> None:
        """Fully serve one exported chunk through the lattice, starting at
        `kind` with optionally already-dispatched device futures `outs`.

        Per tier: bounded retry, then batch bisection (a poisoned item is
        quarantined to the host while the rest of the batch stays on the
        device); a batch-independent failure (TierDead) demotes one tier,
        down to the host floor.
        """
        ops = self.ops
        submitted_kind = kind
        while True:
            kind = ops.live_tier(ctx, kind)
            if kind == "host":
                ops.surrender(ctx, chunk, exported=True)
                self._done(ctx, chunk)
                return

            def attempt(sub, _kind=kind):
                return ops.attempt(ctx, _kind, sub)

            # the pipelined futures are only valid for the tier they were
            # dispatched on; a demotion in between invalidates them
            cached = None
            if outs is not None and kind == submitted_kind:
                cached = (lambda _o=outs, _k=kind: ops.unpack(ctx, _k, _o))
            t0 = time.monotonic_ns()
            try:
                with obs.span(ops.span_name, tier=kind,
                              **ops.span_args(ctx, chunk,
                                              cached is not None)):
                    pairs, quarantined = rl.serve_with_bisect(
                        chunk, attempt, tier=kind, report=self.report,
                        cached=cached)
            except rl.TierDead as td:
                self.kernel_ns += time.monotonic_ns() - t0
                outs = None
                # sharded -> single-device is a lattice edge ABOVE tier
                # demotion: a sharded compile failure / device loss drops
                # to single-device dispatch and retries the SAME tier
                # (byte-identical; sharding never changes what computes)
                demote_shard = getattr(ops, "demote_shard", None)
                if demote_shard is not None and demote_shard(ctx, kind,
                                                             td.cause):
                    continue
                kind = ops.demote(ctx, kind, td.cause)
                continue
            self.kernel_ns += time.monotonic_ns() - t0
            for sub, results in pairs:
                ops.install(ctx, kind, sub, results)
            for item, exc in quarantined:
                ops.quarantine(ctx, item, exc)
            self._widen(ctx, kind, attempt)
            self._done(ctx, chunk)
            return

    def _widen(self, ctx, kind, attempt) -> None:
        """Drain the ops' verify-and-widen ladder (banded DP): re-serve
        the chunk's band-hit items with widened params until the ladder
        is empty.  Re-dispatches reuse the packed batch views (install
        advanced each item's band state; attempt reads it), so a retry
        costs zero re-packing.  The ladder is bounded
        (RACON_TPU_BAND_MAX_WIDENINGS doublings, then the flat kernel),
        so this loop terminates."""
        ops = self.ops
        widen = getattr(ops, "widen", None)
        if widen is None:
            return
        while True:
            retry = widen(ctx, kind)
            if not retry:
                return
            t0 = time.monotonic_ns()
            try:
                with obs.span(ops.span_name, tier=kind,
                              band_retry=len(retry)):
                    pairs, quarantined = rl.serve_with_bisect(
                        retry, attempt, tier=kind, report=self.report,
                        cached=None)
            except rl.TierDead as td:
                self.kernel_ns += time.monotonic_ns() - t0
                # the tier died mid-ladder: surrender the pending
                # band retries to the host floor (the oracle) rather
                # than re-serving the already-installed chunk
                ops.demote(ctx, kind, td.cause)
                ops.surrender(ctx, retry, exported=True)
                return
            self.kernel_ns += time.monotonic_ns() - t0
            for sub, results in pairs:
                ops.install(ctx, kind, sub, results)
            for item, exc in quarantined:
                ops.quarantine(ctx, item, exc)

    def _done(self, ctx, chunk) -> None:
        done = getattr(self.ops, "done", None)
        if done is not None:
            done(ctx, chunk)

    def _count_shard(self, n_real, packed, m) -> None:
        rows = int(np.asarray(packed[0]).shape[0])
        self.shard_pad_rows += count_shard_rows(n_real, rows, m)

    # -- accounting --------------------------------------------------------
    def stamp_walls(self, report) -> None:
        """Fold the pack/kernel wall split into a PhaseReport's extras
        (accumulating: the alignment phase may run several engines)."""
        if report is None:
            return
        report.extra["pack_wall_s"] = round(
            report.extra.get("pack_wall_s", 0.0) + self.pack_ns / 1e9, 6)
        report.extra["kernel_wall_s"] = round(
            report.extra.get("kernel_wall_s", 0.0) + self.kernel_ns / 1e9, 6)
        if self.shard_pad_rows:
            report.extra["shard_pad_rows"] = (
                report.extra.get("shard_pad_rows", 0) + self.shard_pad_rows)
