"""Batched partial-order alignment (POA) on device.

TPU-native replacement for the reference's per-window SPOA consensus
(/root/reference/src/window.cpp:65-149) and its CUDA batch analogue
(/root/reference/src/cuda/cudabatch.cpp): one jitted program consumes a
padded batch of windows and emits consensus strings + per-node coverages.

Design (mirrors the host engine in racon_tpu/native/src/rt_poa.cpp, which is
the correctness oracle):

* The graph lives in fixed-size arrays per window. Every node belongs to a
  *column* identified by a strictly ordered fractional key (f32). Backbone
  column i has key exactly i; insertion columns take keys strictly between
  their neighbours. All edges increase the key, so topological order is a
  sort by key and the classic aligned-node ring is just "same key".
* Per layer (sequential, as POA fundamentally is): a global (kNW) sequence-
  to-graph DP over nodes in key order — the linear-gap horizontal pass is a
  cummax after the affine transform H[j] = j*g + cummax(V[j] - j*g) — then a
  device traceback (transition re-checking against exact maxima; no move
  matrix is stored), then a graph update scan that merges matched bases into
  columns, allocates insertion columns, and bumps edge weights by
  w[j-1]+w[j].
* Consensus: heaviest-bundle scoring over in-edges in key order, backward
  walk to a source, forward walk to a sink (branch completion), column
  coverage per consensus node.
* Any limit hit (node slots, in-edge slots, traceback budget) raises the
  window's `failed` flag -> the driver re-runs it on the host POA engine,
  reproducing the reference's accelerator->CPU fallback lattice
  (/root/reference/src/cuda/cudapolisher.cpp:354-378).

Shapes are static per (batch, depth, max_nodes, max_len) bucket; the driver
buckets windows to bound padding waste.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .kernel_cache import device_keyed_cache

NEG = jnp.int32(-(1 << 28))
KEY_INF = jnp.float32(jnp.inf)


class PoaConfig(NamedTuple):
    max_nodes: int = 1536     # node slots per window graph
    max_len: int = 768        # max layer sequence length
    max_backbone: int = 512   # max backbone (window) length
    max_edges: int = 12       # in-edge slots per node
    depth: int = 32           # layer slots (batch bucket)
    match: int = 5
    mismatch: int = -4
    gap: int = -8


class Graph(NamedTuple):
    base: jnp.ndarray    # i32 [N] code 0..4, -1 unused
    key: jnp.ndarray     # f32 [N] column key, +inf unused
    cov: jnp.ndarray     # i32 [N] paths through node
    in_src: jnp.ndarray  # i32 [N, E] source node id, -1 empty slot
    in_w: jnp.ndarray    # i32 [N, E] edge weight
    n: jnp.ndarray       # i32 [] node count
    failed: jnp.ndarray  # bool []


def _init_graph(cfg: PoaConfig, bb_codes, bb_w, bb_len):
    """Backbone chain: node i = column key i, edge i-1 -> i with weight
    w[i-1]+w[i] (host analogue: rt_poa.cpp add_alignment, empty-alignment
    branch)."""
    N, E = cfg.max_nodes, cfg.max_edges
    idx = jnp.arange(N, dtype=jnp.int32)
    used = idx < bb_len
    base = jnp.where(used, jnp.pad(bb_codes.astype(jnp.int32),
                                   (0, N - cfg.max_backbone)), -1)
    key = jnp.where(used, idx.astype(jnp.float32), KEY_INF)
    cov = jnp.where(used, 1, 0).astype(jnp.int32)
    in_src = jnp.full((N, E), -1, dtype=jnp.int32)
    in_w = jnp.zeros((N, E), dtype=jnp.int32)
    bbw = jnp.pad(bb_w.astype(jnp.int32), (0, N - cfg.max_backbone))
    chain = (idx > 0) & used
    in_src = in_src.at[:, 0].set(jnp.where(chain, idx - 1, -1))
    prev_w = jnp.roll(bbw, 1)
    in_w = in_w.at[:, 0].set(jnp.where(chain, prev_w + bbw, 0))
    return Graph(base, key, cov, in_src, in_w,
                 bb_len.astype(jnp.int32), jnp.bool_(False))


def _dp_matrix(cfg: PoaConfig, g: Graph, seq, sub_mask, order, n_sub):
    """H[node+1, j] for the subgraph; row 0 is the virtual start."""
    N, L = cfg.max_nodes, cfg.max_len
    gp = jnp.int32(cfg.gap)
    jj = jnp.arange(L + 1, dtype=jnp.int32)

    H0 = jnp.full((N + 1, L + 1), NEG, dtype=jnp.int32)
    H0 = H0.at[0].set(jj * gp)

    def cond(c):
        r, _ = c
        return r < n_sub

    def body(c):
        r, H = c
        u = order[r]
        ub = g.base[u]
        srcs = g.in_src[u]
        srcs_c = jnp.maximum(srcs, 0)
        valid = (srcs >= 0) & sub_mask[srcs_c]
        any_valid = valid.any()

        prows = jnp.where(valid[:, None], H[srcs_c + 1], NEG)   # [E, L+1]
        P = jnp.where(any_valid, prows.max(axis=0), H[0])       # [L+1]

        sc = jnp.where(seq == ub, jnp.int32(cfg.match),
                       jnp.int32(cfg.mismatch))                 # [L]
        diag = P[:-1] + sc
        up = P + gp
        V = up.at[1:].max(diag)

        # Linear-gap horizontal pass: H[j] = j*g + cummax(V[j] - j*g).
        tr = V - jj * gp
        row = jax.lax.cummax(tr) + jj * gp
        return (r + 1, H.at[u + 1].set(row))

    return jax.lax.while_loop(cond, body, (jnp.int32(0), H0))[1]


def _traceback(cfg: PoaConfig, g: Graph, H, seq, sub_mask, order, n_sub, L):
    """Walk optimal path from the best end node; returns pos_node[MAXL]
    (matched node per seq position, -1 = insertion) and an ok flag."""
    N, MAXL = cfg.max_nodes, cfg.max_len
    gp = jnp.int32(cfg.gap)

    # End nodes: subgraph nodes with no out-edge inside the subgraph.
    srcs_c = jnp.maximum(g.in_src, 0)
    edge_live = (g.in_src >= 0) & sub_mask[srcs_c] & sub_mask[:, None]
    has_out = jnp.zeros(N, dtype=jnp.bool_).at[srcs_c.reshape(-1)].max(
        edge_live.reshape(-1))
    end_mask = sub_mask & ~has_out

    colL = jnp.take(H, L, axis=1)                 # [N+1]
    end_score = colL[1:]                          # per node id
    # First best in key order (host picks first max in rank order).
    score_by_rank = jnp.where(end_mask[order], end_score[order], NEG)
    best_r = jnp.argmax(score_by_rank)
    start_u = order[best_r]

    def cond(c):
        u, j, _, steps, _ = c
        return ~((u == -1) & (j == 0)) & (steps < N + MAXL + 2)

    def body(c):
        u, j, pos_node, steps, ok = c
        at_virtual = u == -1
        u_c = jnp.maximum(u, 0)
        cur = H[u_c + 1, j]
        ub = g.base[u_c]
        srcs = g.in_src[u_c]
        srcs_c2 = jnp.maximum(srcs, 0)
        valid = (srcs >= 0) & sub_mask[srcs_c2]
        any_valid = valid.any()
        prow_jm1 = jnp.where(valid, H[srcs_c2 + 1, jnp.maximum(j - 1, 0)], NEG)
        prow_j = jnp.where(valid, H[srcs_c2 + 1, j], NEG)

        sc = jnp.where(seq[jnp.maximum(j - 1, 0)] == ub,
                       jnp.int32(cfg.match), jnp.int32(cfg.mismatch))

        diag_ok = valid & (j > 0) & (prow_jm1 + sc == cur)
        diag_virt = ~any_valid & (j > 0) & (
            H[0, jnp.maximum(j - 1, 0)] + sc == cur)
        any_diag = diag_ok.any() | diag_virt
        diag_slot = jnp.argmax(diag_ok)
        diag_pred = jnp.where(diag_ok.any(), srcs[diag_slot], -1)

        up_ok = valid & (prow_j + gp == cur)
        up_virt = ~any_valid & (H[0, j] + gp == cur)
        any_up = up_ok.any() | up_virt
        up_slot = jnp.argmax(up_ok)
        up_pred = jnp.where(up_ok.any(), srcs[up_slot], -1)

        # Priority: diag > up > left (host: rt_poa.cpp traceback order).
        take_diag = ~at_virtual & any_diag
        take_up = ~at_virtual & ~any_diag & any_up
        # left: insertion (also the only move from the virtual row)

        new_u = jnp.where(take_diag, diag_pred,
                          jnp.where(take_up, up_pred, u))
        new_j = jnp.where(take_diag | ~take_up, j - 1, j)
        new_j = jnp.where(take_up, j, new_j)
        wrote = take_diag
        pos_node = pos_node.at[jnp.maximum(j - 1, 0)].set(
            jnp.where(wrote, u, pos_node[jnp.maximum(j - 1, 0)]))
        return (new_u, new_j, pos_node, steps + 1, ok)

    pos_node0 = jnp.full(MAXL, -1, dtype=jnp.int32)
    u, j, pos_node, steps, _ = jax.lax.while_loop(
        cond, body, (start_u, L.astype(jnp.int32), pos_node0,
                     jnp.int32(0), jnp.bool_(True)))
    ok = (u == -1) & (j == 0)
    return pos_node, ok


def _update_graph(cfg: PoaConfig, g: Graph, pos_node, seq, w, L):
    """Thread the sequence through the graph along pos_node (host analogue:
    rt_poa.cpp add_alignment main loop)."""
    N, MAXL, E = cfg.max_nodes, cfg.max_len, cfg.max_edges
    jj = jnp.arange(MAXL, dtype=jnp.int32)
    active = jj < L
    matched = (pos_node >= 0) & active
    mkey = jnp.where(matched, g.key[jnp.maximum(pos_node, 0)], KEY_INF)

    # next matched column key at j' >= j, and remaining insertion-run length.
    def rev_scan(carry, x):
        nk, run = carry
        m, k = x
        nk = jnp.where(m, k, nk)
        run = jnp.where(m, 0, run + 1)
        return (nk, run), (nk, run)

    (_, _), (next_key, run_rem) = jax.lax.scan(
        rev_scan, (KEY_INF, jnp.int32(0)),
        (matched[::-1], mkey[::-1]))
    next_key = next_key[::-1]
    run_rem = run_rem[::-1]

    def body(carry):
        g, prev, prev_key, prev_w, j = carry
        act = active[j]
        b = seq[j].astype(jnp.int32)
        wj = w[j]

        k0 = mkey[j]
        is_match = matched[j]
        cand = (g.key == k0) & (g.base == b)
        has = cand.any() & is_match
        found = jnp.argmax(cand)

        hi = jnp.where(jnp.isfinite(next_key[j]), next_key[j], prev_key + 1.0)
        lo = jnp.where(prev >= 0, prev_key,
                       hi - run_rem[j].astype(jnp.float32) - 1.0)
        k_new = lo + (hi - lo) / (run_rem[j].astype(jnp.float32) + 1.0)
        key_val = jnp.where(is_match, k0, k_new)

        need_new = act & ~has
        overflow = need_new & (g.n >= N)
        do_new = need_new & ~overflow
        nid = jnp.where(has, found, jnp.minimum(g.n, N - 1))

        base = g.base.at[nid].set(jnp.where(do_new, b, g.base[nid]))
        key = g.key.at[nid].set(jnp.where(do_new, key_val, g.key[nid]))
        touch = act & ~overflow
        cov = g.cov.at[nid].add(jnp.where(touch, 1, 0))
        n = g.n + jnp.where(do_new, 1, 0)
        failed = g.failed | overflow

        # Edge prev -> nid with weight w[j-1] + w[j].
        has_prev = touch & (prev >= 0)
        slots = g.in_src[nid]
        same = slots == prev
        empty = slots == -1
        ew = prev_w + wj
        use_same = has_prev & same.any()
        use_empty = has_prev & ~same.any() & empty.any()
        slot = jnp.where(same.any(), jnp.argmax(same), jnp.argmax(empty))
        in_w = g.in_w.at[nid, slot].add(
            jnp.where(use_same, ew, 0))
        in_w = in_w.at[nid, slot].set(
            jnp.where(use_empty, ew, in_w[nid, slot]))
        in_src = g.in_src.at[nid, slot].set(
            jnp.where(use_empty, prev, g.in_src[nid, slot]))
        failed = failed | (has_prev & ~same.any() & ~empty.any())

        prev = jnp.where(act, nid, prev)
        prev_key = jnp.where(act, key[nid], prev_key)
        prev_w = jnp.where(act, wj, prev_w)
        g2 = Graph(base, key, cov, in_src, in_w, n, failed)
        return (g2, prev, prev_key, prev_w, j + 1)

    g = jax.lax.while_loop(
        lambda c: c[4] < L,
        body,
        (g, jnp.int32(-1), jnp.float32(-1.0), jnp.int32(0), jnp.int32(0)))[0]
    return g


def _add_layer(cfg: PoaConfig, g: Graph, seq, w, L, begin, end, bb_len):
    """Align one layer against the (sub)graph and merge it in
    (host analogue: rt_window.cpp generate_consensus loop body)."""
    offset = (0.01 * bb_len.astype(jnp.float32)).astype(jnp.int32)
    full = (begin < offset) & (end > bb_len - offset)
    lo = jnp.where(full, -jnp.inf, begin.astype(jnp.float32))
    hi = jnp.where(full, jnp.inf, end.astype(jnp.float32))

    sub_mask = (g.key >= lo) & (g.key <= hi)
    sort_keys = jnp.where(sub_mask, g.key, KEY_INF)
    order = jnp.argsort(sort_keys).astype(jnp.int32)
    n_sub = sub_mask.sum().astype(jnp.int32)

    H = _dp_matrix(cfg, g, seq, sub_mask, order, n_sub)
    pos_node, ok = _traceback(cfg, g, H, seq, sub_mask, order, n_sub, L)
    g = g._replace(failed=g.failed | ~ok)
    return _update_graph(cfg, g, pos_node, seq, w, L)


def _consensus(cfg: PoaConfig, g: Graph):
    """Heaviest bundle + branch completion + column coverage
    (host analogue: rt_poa.cpp generate_consensus)."""
    N = cfg.max_nodes
    order = jnp.argsort(g.key).astype(jnp.int32)

    def score_body(c):
        r, score, pred = c
        u = order[r]
        srcs = g.in_src[u]
        srcs_c = jnp.maximum(srcs, 0)
        valid = srcs >= 0
        w = jnp.where(valid, g.in_w[u], NEG)
        ps = jnp.where(valid, score[srcs_c], NEG)
        wmax = w.max()
        any_valid = valid.any()
        cand = valid & (w == wmax)
        slot = jnp.argmax(jnp.where(cand, ps, NEG))
        s = jnp.where(any_valid, wmax + ps[slot], 0)
        p = jnp.where(any_valid, srcs[slot], -1)
        return (r + 1, score.at[u].set(s), pred.at[u].set(p))

    score0 = jnp.zeros(N, dtype=jnp.int32)
    pred0 = jnp.full(N, -1, dtype=jnp.int32)
    _, score, pred = jax.lax.while_loop(
        lambda c: c[0] < g.n, score_body, (jnp.int32(0), score0, pred0))

    rr = jnp.arange(N, dtype=jnp.int32)
    score_by_rank = jnp.where(rr < g.n, score[order], NEG)
    summit = order[jnp.argmax(score_by_rank)]

    # Backward to a source.
    def bcond(c):
        u, _, cnt = c
        return (u != -1) & (cnt < N)

    def bbody(c):
        u, buf, cnt = c
        buf = buf.at[cnt].set(u)
        return (pred[u], buf, cnt + 1)

    buf0 = jnp.full(N, -1, dtype=jnp.int32)
    _, rev_buf, cnt_b = jax.lax.while_loop(
        bcond, bbody, (summit, buf0, jnp.int32(0)))

    flip_idx = jnp.clip(cnt_b - 1 - rr, 0, N - 1)
    path = jnp.where(rr < cnt_b, rev_buf[flip_idx], -1)

    # Forward from the summit along heaviest out-edges to a sink.
    def fcond(c):
        u, _, cnt, more = c
        return more & (cnt < N)

    def fbody(c):
        u, path, cnt, _ = c
        ew = jnp.where(g.in_src == u, g.in_w, NEG)    # [N, E]
        wv = ew.max(axis=1)                           # best edge u->v per v
        any_out = (wv > NEG).any()
        wmax = wv.max()
        cand = wv == wmax
        v = jnp.argmax(jnp.where(cand, score, NEG))
        path = path.at[cnt].set(jnp.where(any_out, v, -1))
        return (jnp.where(any_out, v, u).astype(jnp.int32),
                path, cnt + jnp.where(any_out, 1, 0), any_out)

    path, cnt = jax.lax.while_loop(
        fcond, fbody, (summit, path, cnt_b, jnp.bool_(True)))[1:3]

    # Node coverage per path node (trim-rule input; matches the host
    # oracle's semantics).
    path_c = jnp.maximum(path, 0)
    cons_base = jnp.where(path >= 0, g.base[path_c], -1)
    cons_cov = jnp.where(path >= 0, g.cov[path_c], 0)
    return cons_base, cons_cov, cnt


def _polish_window(cfg: PoaConfig, bb_codes, bb_w, bb_len, n_layers,
                   seqs, ws, lens, begins, ends):
    """Full per-window program: init graph, fold in layers, consensus."""
    g = _init_graph(cfg, bb_codes, bb_w, bb_len)

    def layer_body(c):
        g, li = c
        seq = seqs[li]
        w = ws[li]
        L = lens[li]
        use = (L > 0) & ~g.failed
        g = jax.lax.cond(
            use,
            lambda g: _add_layer(cfg, g, seq, w, L, begins[li], ends[li],
                                 bb_len),
            lambda g: g,
            g)
        return (g, li + 1)

    g = jax.lax.while_loop(
        lambda c: c[1] < n_layers, layer_body, (g, jnp.int32(0)))[0]

    cons_base, cons_cov, cons_len = _consensus(cfg, g)
    return cons_base, cons_cov, cons_len, g.failed, g.n


@device_keyed_cache(maxsize=32)
def build_poa_kernel(cfg: PoaConfig):
    """jit-compiled batch kernel: all inputs have a leading batch dim."""

    def batch_fn(bb_codes, bb_w, bb_len, n_layers, seqs, ws, lens, begins,
                 ends):
        return jax.vmap(
            lambda a, b, c, d, e, f, gg, h, i:
            _polish_window(cfg, a, b, c, d, e, f, gg, h, i)
        )(bb_codes, bb_w, bb_len, n_layers, seqs, ws, lens, begins, ends)

    return jax.jit(batch_fn)
