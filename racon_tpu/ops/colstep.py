"""Host-side reference for column-compressed POA stepping.

The device DP loop iterates graph nodes in topological rank order, which
is exactly column-key order (poa_pallas.py keeps `order` key-sorted
incrementally; poa_pallas_ls.py's rank space IS key order).  Two facts
make column compression sound:

* **Equal keys mean same column.**  A node key is either a backbone
  ordinal or `lo + (hi - lo) / (run + 1)` strictly between its
  neighbours' keys; two nodes share a key only when the graph update
  placed them as alternative bases of the same alignment column (the
  match rule `keys == k0` relies on this exact-equality invariant).
* **No intra-column edges.**  Every edge goes from a strictly smaller
  key to a strictly larger key (a predecessor is either the previous
  matched column or an inserted node keyed strictly below), so nodes of
  one column never feed each other and their predecessor scans are
  independent.

The v2 kernel therefore retires a same-column *pair* of adjacent ranks
per serial loop iteration (greedy adjacent pairing — the in-kernel
while_loop in poa_pallas.py mirrors `pair_schedule` below), driving the
trip count to ``n_column_steps(keys) <= n_ranks``.  The lockstep kernel
cannot pair by column (its 8 lanes hold unrelated windows) and instead
retires an unconditional rank pair per iteration — `ceil(n / 2)` steps.

This module is the numpy twin the unit tests pin the kernel loop shape
against; it is also what the cost model's POA_COLSTEP_PACK divisor
abstracts (obs/costmodel.py).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

#: Ranks retired per serial iteration when a same-column sibling is
#: adjacent (v2) or unconditionally (ls).  The kernels are pair-steppers,
#: not arbitrary-k steppers: a column of m nodes takes ceil(m / 2) steps.
PACK = 2


def pair_schedule(keys) -> List[Tuple[int, int]]:
    """Greedy adjacent pairing of equal keys in rank order.

    `keys` are the column keys of the live nodes in topological rank
    order (already key-sorted).  Returns ``[(rank, take), ...]`` with
    ``take`` in {1, 2}: the exact iteration schedule the v2 kernel's
    column-compressed while_loop executes over ranks [0, len(keys)).
    """
    k = np.asarray(keys)
    out: List[Tuple[int, int]] = []
    r, n = 0, len(k)
    while r < n:
        take = 2 if (r + 1 < n and k[r + 1] == k[r]) else 1
        out.append((r, take))
        r += take
    return out


def n_column_steps(keys) -> int:
    """Serial DP iterations the column-compressed v2 loop takes."""
    return len(pair_schedule(keys))


def compression(keys) -> float:
    """Ranks per serial step: len(keys) / n_column_steps (1.0..2.0)."""
    n = len(np.asarray(keys))
    return n / n_column_steps(keys) if n else 1.0
