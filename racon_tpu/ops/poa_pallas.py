"""Fused Pallas TPU kernel for batched POA window consensus.

Same semantics as the reference JAX implementation in poa.py (which mirrors
the host oracle rt_poa.cpp), but the entire per-window program — graph init,
per-layer sequence-to-graph DP, traceback, graph update, heaviest-bundle
consensus — runs as ONE kernel program per window (grid over the batch), with
the DP matrix and all graph state resident in VMEM.

Data layout (the v2 rework, after the first on-hardware measurements showed
~115 ms/window): every logical 1-D row is stored **sublane-blocked** as an
(8, W) tile with element i at (i // W, i % W) — so each vector op engages
all 8 VPU sublanes instead of 1-of-8 as a (1, N) row would:

  * DP/sequence rows (j in [0, L]):   (8, JW) — exactly one vreg at w=500
  * node/rank state  (u in [0, N)):   (8, NW) — two vregs at w=500
  * in-edge tables:                   (E, 8, NW), one dynamically indexed
    (8, NW) sublane-row per slot (the v1 layout mask-reduced the whole
    (E, N) array for every scalar edge read)

Layer sequences/weights stay in HBM (memory_space=ANY); each layer is DMA'd
into a double-buffered VMEM scratch slot while the previous layer's DP runs,
so VMEM residency is independent of the depth bucket (the v1 layout's
depth-200 bucket no longer threatens the ~16 MB core budget) and the copy
rides under compute.

Other deliberate choices, none semantic:
  * topological order is maintained incrementally (an O(N) vector
    shift-insert per new node) instead of argsort per layer; the subgraph is
    then a contiguous rank range [count(key < lo), min(count(key <= hi), n))
    — the min() clamp matters for full-graph layers, whose hi sentinel
    equals the unused-slot key sentinel and would otherwise sweep every
    node slot.
  * end-node detection reuses the DP's predecessor enumeration (any
    in-subgraph edge marks its source as "has out-edge").
  * the linear-gap cummax runs as lane-prefix + cross-sublane-prefix
    shift-max steps.
  * the DP rank loop steps per COLUMN, not per node (colstep=True,
    RACON_TPU_POA_COLSTEP): equal-key nodes are adjacent in rank order
    with no edges among themselves, so a same-column sibling is processed
    in the same iteration and the serial trip count is n_column_steps
    <= n_nodes (ops/colstep.py holds the host-side reference mapping).

VMEM budget (w=500 config: N=1536 -> NW=256, L=768 -> JW=128):
H and MV (1537, 8, 128) i32 ~6.3 MB each, node/edge state <0.3 MB, staged
layers 2 slots x 2 arrays x 4 KB — ~13 MB total for every depth bucket.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kernel_cache import device_keyed_cache
from .poa import PoaConfig

NEG = -(1 << 28)


def _round_up(x, m):
    return (x + m - 1) // m * m


def blocked_width(n: int) -> int:
    """Lane width of the (8, W) sublane-blocked tile covering n elements."""
    return _round_up((n + 7) // 8, 128)


@device_keyed_cache(maxsize=32)
def build_pallas_poa_kernel(cfg: PoaConfig, interpret: bool = False,
                            colstep: bool = True, band: bool = False):
    N = cfg.max_nodes
    L = cfg.max_len
    BB = cfg.max_backbone
    E = cfg.max_edges
    D = cfg.depth
    JW = blocked_width(L + 1)           # j-dimension lanes per sublane row
    NW = blocked_width(N)               # node/rank lanes per sublane row
    SJ = 8 * JW                         # padded j capacity
    SN = 8 * NW                         # padded node-slot capacity
    # plain Python scalars: captured jnp values would become kernel constants
    M = int(cfg.match)
    X = int(cfg.mismatch)
    G = int(cfg.gap)
    KEY_INF = 3.0e38

    VSLOT = 15  # pred-slot sentinel meaning "virtual start row"

    # The banded build (band=True, RACON_TPU_BAND) adds one SMEM input
    # (wband: the per-window half-band width) and one SMEM output
    # (band_hit: traceback touched the band boundary, or the terminal
    # score's deficit exceeded the gap-cost bound — ops/band.py owns the
    # verify-and-widen ladder that consumes it).  Every band operation
    # is gated on the Python-level `band` flag so the flat build traces
    # to an unchanged jaxpr, and on `wband > 0` at runtime so a widened-
    # to-flat window (wband == 0) runs exact flat semantics through the
    # same compiled kernel.
    def kernel(*refs):
        if band:
            (bb_len_ref, n_layers_ref, lens_ref, begins_ref, ends_ref,
             bb_ref, bbw_ref, seqs_hbm, ws_hbm, wband_ref,
             cons_base_ref, cons_cov_ref, cons_len_ref, failed_ref,
             n_nodes_ref, band_hit_ref,
             H, MV, base, key, cov, order, in_src, in_w, in_cnt,
             nkey, runrem, score, pred, revbuf, esc, rank_of,
             seq_scr, w_scr, dma_sem) = refs
            wb = wband_ref[0, 0, 0]
        else:
            (bb_len_ref, n_layers_ref, lens_ref, begins_ref, ends_ref,
             bb_ref, bbw_ref, seqs_hbm, ws_hbm,
             cons_base_ref, cons_cov_ref, cons_len_ref, failed_ref,
             n_nodes_ref,
             H, MV, base, key, cov, order, in_src, in_w, in_cnt,
             nkey, runrem, score, pred, revbuf, esc, rank_of,
             seq_scr, w_scr, dma_sem) = refs
        jlane = jax.lax.broadcasted_iota(jnp.int32, (8, JW), 1)
        jsub = jax.lax.broadcasted_iota(jnp.int32, (8, JW), 0)
        jj = jsub * JW + jlane                      # j index per element
        nlane = jax.lax.broadcasted_iota(jnp.int32, (8, NW), 1)
        nsub = jax.lax.broadcasted_iota(jnp.int32, (8, NW), 0)
        nn_i = nsub * NW + nlane                    # node/rank index
        gvec = jj * G

        # Mosaic cannot store scalars to VMEM; every scalar store becomes a
        # masked tile read-modify-write, and every dynamic-position scalar
        # load a masked reduction. On the blocked layout each costs 1-2
        # vregs of VPU work.
        def rmwj(ref, idx, val):
            ref[:] = jnp.where(jj == idx, val, ref[:])

        def rmwn(ref, idx, val):
            ref[:] = jnp.where(nn_i == idx, val, ref[:])

        def loadj(tile, idx):
            return jnp.sum(jnp.where(jj == idx, tile, jnp.zeros_like(tile)))

        def loadn(tile, idx):
            return jnp.sum(jnp.where(nn_i == idx, tile,
                                     jnp.zeros_like(tile)))

        # in-edge tables: one dynamically indexed sublane-row per slot
        def eload(ref, e, u):
            row = ref[pl.ds(e, 1)][0]
            return jnp.sum(jnp.where(nn_i == u, row, jnp.zeros_like(row)))

        def ermw(ref, e, u, val):
            row = ref[pl.ds(e, 1)][0]
            ref[pl.ds(e, 1)] = jnp.where(nn_i == u, val,
                                         row).reshape(1, 8, NW)

        # masked increments: no scalar read-back needed
        def rmwn_add(ref, idx, delta):
            ref[:] = jnp.where(nn_i == idx, ref[:] + delta, ref[:])

        def ermw_add(ref, e, u, delta):
            row = ref[pl.ds(e, 1)][0]
            ref[pl.ds(e, 1)] = jnp.where(nn_i == u, row + delta,
                                         row).reshape(1, 8, NW)

        def shift1(x, iota2, lane, fill):
            # blocked shift: new[i] = old[i-1]; new[0] = fill
            ln = pltpu.roll(x, 1, 1)
            carry = pltpu.roll(ln, 1, 0)            # sublane roll
            y = jnp.where(lane == 0, carry, ln)
            return jnp.where(iota2 == 0, fill, y)

        def tree_max(xs):
            # balanced pairwise reduction: log2 depth independent of any
            # compiler reassociation of integer max
            while len(xs) > 1:
                nxt = [jnp.maximum(a, b) for a, b in zip(xs[::2], xs[1::2])]
                if len(xs) % 2:
                    nxt.append(xs[-1])
                xs = nxt
            return xs[0]

        def cummaxj(x):
            # prefix max over the blocked j line: radix-4 lane prefix
            # within each sublane row, then a radix-8 exclusive
            # cross-sublane prefix of the row maxima. Radix-4/8 does the
            # same work as the binary scan in about half the
            # dependency-chain depth (the shifted copies within a round
            # are independent, and tree_max keeps the combine log-deep) —
            # this loop is latency-bound, not throughput-bound
            # (docs/benchmarks.md, dp_cost_probe).
            w = 1
            while w < JW:
                shs = [jnp.where(jlane >= k * w,
                                 pltpu.roll(x, k * w, 1), NEG)
                       for k in (1, 2, 3) if k * w < JW]
                x = tree_max([x] + shs)
                w *= 4
            tot = jnp.max(x, axis=1, keepdims=True)  # (8, 1) row maxima
            p = jnp.broadcast_to(tot, (8, JW))
            # row 0 ends up NEG by construction: every copy is masked by
            # jsub >= k with k >= 1
            excl = tree_max([jnp.where(jsub >= k, pltpu.roll(p, k, 0), NEG)
                             for k in range(1, 8)])
            return jnp.maximum(x, excl)

        bb_len = bb_len_ref[0, 0, 0]
        n_layers = n_layers_ref[0, 0, 0]
        b_prog = pl.program_id(0)

        def start_copy(li, slot):
            pltpu.make_async_copy(seqs_hbm.at[b_prog, li],
                                  seq_scr.at[slot],
                                  dma_sem.at[slot, 0]).start()
            pltpu.make_async_copy(ws_hbm.at[b_prog, li],
                                  w_scr.at[slot],
                                  dma_sem.at[slot, 1]).start()

        def wait_copy(li, slot):
            pltpu.make_async_copy(seqs_hbm.at[b_prog, li],
                                  seq_scr.at[slot],
                                  dma_sem.at[slot, 0]).wait()
            pltpu.make_async_copy(ws_hbm.at[b_prog, li],
                                  w_scr.at[slot],
                                  dma_sem.at[slot, 1]).wait()

        # ---- graph init from the backbone chain --------------------------
        bbblk = bb_ref[0]                           # (8, NW), node-blocked
        used0 = nn_i < bb_len
        base[:] = jnp.where(used0, bbblk, -1)
        key[:] = jnp.where(used0, nn_i.astype(jnp.float32), KEY_INF)
        cov[:] = jnp.where(used0, 1, 0)
        order[:] = nn_i
        bbw_blk = bbw_ref[0]
        chain = (nn_i > 0) & used0
        in_src[:] = jnp.full((E, 8, NW), -1, jnp.int32)
        in_src[0:1] = jnp.where(chain, nn_i - 1, -1).reshape(1, 8, NW)
        in_w[:] = jnp.zeros((E, 8, NW), jnp.int32)
        in_w[0:1] = jnp.where(
            chain, shift1(bbw_blk, nn_i, nlane, 0) + bbw_blk,
            0).reshape(1, 8, NW)
        # edge slots fill contiguously from 0, so in_cnt doubles as "first
        # empty slot" and bounds every per-node slot loop to the true degree
        in_cnt[:] = jnp.where(chain, 1, 0)
        H[0:1] = gvec.reshape(1, 8, JW)

        # ---- one layer ----------------------------------------------------
        def do_layer(li, slot, carry):
            if band:
                n, failed, hit = carry
            else:
                n, failed = carry
            Ln = lens_ref[0, 0, li]
            begin = begins_ref[0, 0, li]
            end = ends_ref[0, 0, li]

            # full-graph rule (reference: src/window.cpp:88-97)
            offset = (0.01 * bb_len.astype(jnp.float32)).astype(jnp.int32)
            full = (begin < offset) & (end > bb_len - offset)
            lo = jnp.where(full, jnp.float32(-3.0e38),
                           begin.astype(jnp.float32))
            hi = jnp.where(full, jnp.float32(3.0e38), end.astype(jnp.float32))

            seqv = seq_scr[pl.ds(slot, 1)][0]        # (8, JW)
            wv = w_scr[pl.ds(slot, 1)][0]

            keys = key[:]
            r_lo = jnp.sum(jnp.where(keys < lo, 1, 0)).astype(jnp.int32)
            # clamp to n: for full layers hi == the unused-slot sentinel
            r_hi = jnp.minimum(
                jnp.sum(jnp.where(keys <= hi, 1, 0)).astype(jnp.int32), n)

            seqm1 = shift1(seqv, jj, jlane, 255)
            virt_row = H[0:1][0]        # loop-invariant: hoist out of dp_body

            # End-node selection is fused into the DP sweep: each node's
            # score at column Ln lands in esc (indexed by RANK, so "first
            # max in rank order" is just "lowest index among maxima"), and
            # gaining an in-subgraph out-edge cancels the source's slot —
            # predecessors always precede successors in rank order, so the
            # cancel never races the write. rank_of maps node id -> rank
            # for the cancel. This removes the separate end_body sweep.
            esc[:] = jnp.full((8, NW), NEG, jnp.int32)

            # ---- DP over subgraph nodes in rank order ---------------------
            # Per-cell move records (2 bits move + pred slot, VSLOT =
            # virtual) land in MV so the traceback is one load per step.
            def dp_body(r, _):
                u = loadn(order[:], r)
                ub = loadn(base[:], u)
                rmwn(rank_of, u, r)

                def pred_scan(e, c):
                    P, Pslot, any_valid = c
                    src = eload(in_src, e, u)
                    ok = loadn(key[:], jnp.maximum(src, 0)) >= lo
                    prow = H[pl.ds(jnp.maximum(src, 0) + 1, 1)][0]
                    better = ok & (prow > P)  # strict: first max slot wins
                    P = jnp.where(better, prow, P)
                    Pslot = jnp.where(better, e, Pslot)

                    @pl.when(ok)
                    def _():
                        # src has an out-edge inside the subgraph: not an
                        # end node
                        rmwn(esc, loadn(rank_of[:], jnp.maximum(src, 0)),
                             NEG)
                    return (P, Pslot, any_valid | ok)

                P0 = jnp.full((8, JW), NEG, jnp.int32)
                S0 = jnp.full((8, JW), VSLOT, jnp.int32)
                P, Pslot, any_valid = jax.lax.fori_loop(
                    0, loadn(in_cnt[:], u), pred_scan,
                    (P0, S0, jnp.bool_(False)))
                P = jnp.where(any_valid, P, virt_row)
                Pslot = jnp.where(any_valid, Pslot, VSLOT)

                scvec = jnp.where(seqm1 == ub, M, X)
                Psh = shift1(P, jj, jlane, NEG)
                Ssh = shift1(Pslot, jj, jlane, VSLOT)
                diag = Psh + scvec
                up = P + G
                choose_diag = diag >= up  # host priority: diag before up
                V = jnp.where(choose_diag, diag, up)
                vmove = jnp.where(choose_diag, 4 * Ssh, 1 + 4 * Pslot)
                row = cummaxj(V - gvec) + gvec
                if band:
                    # diagonal band: node u's expected column is its
                    # backbone key minus the layer's begin; cells more
                    # than wband off that center are masked to NEG, so
                    # later rows, the end-score pick and the traceback
                    # all see banded values
                    cexp = (loadn(key[:], u) + 0.5).astype(jnp.int32) - begin
                    row = jnp.where((wb > 0) & (jnp.abs(jj - cexp) > wb),
                                    NEG, row)
                # left only if strictly better
                mv = jnp.where(row > V, 2, vmove)
                H[pl.ds(u + 1, 1)] = row.reshape(1, 8, JW)
                MV[pl.ds(u + 1, 1)] = mv.reshape(1, 8, JW)
                rmwn(esc, r, loadj(row, Ln))
                return 0

            if colstep:
                # Column-compressed stepping: equal-key ("same column")
                # nodes are adjacent in rank order and have no edges among
                # themselves (ops/colstep.py documents the invariant), so a
                # same-column sibling can ride in the same loop iteration —
                # the trip count drops from n_ranks to n_column_steps.
                # Both nodes still execute in rank order inside the body,
                # so the result is byte-identical to the serial loop even
                # for graphs that violate the invariant (e.g. after an
                # overflow-failed update): rank r's H row / rank_of / esc
                # writes land before rank r+1 reads them.
                def col_cond(c):
                    return c < r_hi

                def col_body(r):
                    ku = loadn(key[:], loadn(order[:], r))
                    dp_body(r, 0)
                    k2 = loadn(key[:], loadn(order[:], r + 1))
                    pair = (r + 1 < r_hi) & (k2 == ku)

                    @pl.when(pair)
                    def _():
                        dp_body(r + 1, 0)

                    return r + 1 + pair.astype(jnp.int32)

                jax.lax.while_loop(col_cond, col_body, r_lo)
            else:
                jax.lax.fori_loop(r_lo, r_hi, dp_body, 0)

            # ---- best end node (first max in rank order) ------------------
            escv = esc[:]
            in_range = (nn_i >= r_lo) & (nn_i < r_hi)
            best_s = jnp.max(jnp.where(in_range, escv, NEG))
            best_r = jnp.min(jnp.where(in_range & (escv == best_s), nn_i,
                                       SN)).astype(jnp.int32)
            best_u = jnp.where(best_s > NEG, loadn(order[:], best_r),
                               jnp.int32(-1))
            if band:
                # score-deficit verify: a terminal score this far below
                # the all-match ceiling means the off-band penalty bound
                # no longer certifies the banded optimum (host mirror:
                # band.poa_deficit_bound)
                hit = hit | ((wb > 0) & (M * Ln - best_s >
                                         2 * (-G) * jnp.maximum(wb // 2, 1)))

            # ---- traceback -------------------------------------------------
            # The walk visits j strictly downward, so the backward
            # next-matched-key / run-remaining pass rides along for free:
            # every j-decrement is either a match (diag: record key[u],
            # reset the run) or an insertion (left: extend the run), and
            # nkey/runrem are exactly what the graph update needs — the
            # old pos_node array and its separate backward sweep are gone.

            def tb_cond(c):
                u, j, steps = c[0], c[1], c[2]
                return (~((u == -1) & (j == 0))) & (steps < N + L + 2)

            def tb_body(c):
                u, j, steps, nk, run = c[:5]
                at_virtual = u == -1
                uc = jnp.maximum(u, 0)
                jm1 = jnp.maximum(j - 1, 0)
                mv_loaded = loadj(MV[pl.ds(uc + 1, 1)][0], j)
                mv = jnp.where(at_virtual, 2, mv_loaded)
                move = mv % 4
                slot = mv // 4
                slot_c = jnp.minimum(slot, E - 1)
                prd = jnp.where(slot == VSLOT, -1, eload(in_src, slot_c, uc))

                take_diag = ~at_virtual & (move == 0)
                take_up = ~at_virtual & (move == 1)
                descend = ~take_up                # j-1 gets its record now
                nk = jnp.where(take_diag, loadn(key[:], uc), nk)
                run = jnp.where(take_diag, 0,
                                jnp.where(descend, run + 1, run))

                @pl.when(descend)
                def _():
                    rmwj(nkey, jm1, nk)
                    rmwj(runrem, jm1, run)

                new_u = jnp.where(take_diag | take_up, prd, u)
                new_j = jnp.where(take_up, j, j - 1)
                out = (new_u, new_j, steps + 1, nk, run)
                if band:
                    # boundary touch: the optimal path came within one
                    # cell of the band edge — the true optimum may lie
                    # outside, so the window must be re-run wider
                    cu = (loadn(key[:], uc) + 0.5).astype(jnp.int32) - begin
                    near = (~at_virtual & (wb > 0) &
                            (jnp.abs(j - cu) >= wb - 1))
                    out = out + (c[5] | near,)
                return out

            if band:
                fu, fj, _, _, _, touch = jax.lax.while_loop(
                    tb_cond, tb_body,
                    (best_u, Ln, jnp.int32(0), jnp.float32(KEY_INF),
                     jnp.int32(0), jnp.bool_(False)))
                hit = hit | touch
            else:
                fu, fj, _, _, _ = jax.lax.while_loop(
                    tb_cond, tb_body,
                    (best_u, Ln, jnp.int32(0), jnp.float32(KEY_INF),
                     jnp.int32(0)))
            failed = failed | ~((fu == -1) & (fj == 0))

            # ---- graph update ----------------------------------------------
            def upd_body(j, c):
                n, failed, prev, prev_key, prev_w = c
                b = loadj(seqv, j)
                wj = loadj(wv, j)
                run_j = loadj(runrem[:], j)
                is_match = run_j == 0       # a zero run marks a match
                nk = loadj(nkey[:], j)
                # at a matched position, nkey[j] IS the matched node's
                # column key (the traceback wrote it) — no key[] reduction
                k0 = nk

                keys = key[:]
                cand = (keys == k0) & (base[:] == b)
                has = cand.any() & is_match
                found = jnp.min(jnp.where(cand, nn_i, SN)).astype(jnp.int32)

                run = run_j.astype(jnp.float32)
                hi2 = jnp.where(nk < KEY_INF, nk, prev_key + 1.0)
                lo2 = jnp.where(prev >= 0, prev_key, hi2 - run - 1.0)
                k_new = lo2 + (hi2 - lo2) / (run + 1.0)
                key_val = jnp.where(is_match, k0, k_new)

                need_new = ~has
                overflow = need_new & (n >= N)
                do_new = need_new & ~overflow
                nid = jnp.where(has, found, jnp.minimum(n, N - 1))

                @pl.when(do_new)
                def _():
                    # insert into sorted order: after all keys <= key_val
                    p = jnp.sum(jnp.where(keys <= key_val, 1, 0)).astype(
                        jnp.int32)
                    rmwn(base, nid, b)
                    rmwn(key, nid, key_val)
                    ordv = order[:]
                    shifted = shift1(ordv, nn_i, nlane, 0)
                    order[:] = jnp.where(
                        nn_i < p, ordv,
                        jnp.where(nn_i == p, nid, shifted))

                touch = ~overflow

                @pl.when(touch)
                def _():
                    rmwn_add(cov, nid, 1)

                n = n + jnp.where(do_new, 1, 0)
                failed = failed | overflow

                # edge prev -> nid, weight w[j-1] + w[j]
                has_prev = touch & (prev >= 0)

                def eslot_scan(e, c2):
                    same_slot = c2
                    src = eload(in_src, e, nid)
                    return jnp.where((src == prev) & (same_slot < 0), e,
                                     same_slot)

                cnt = loadn(in_cnt[:], nid)
                same_slot = jax.lax.fori_loop(
                    0, cnt, eslot_scan, jnp.int32(-1))
                empty_slot = jnp.where(cnt < E, cnt, -1)
                ew = prev_w + wj

                @pl.when(has_prev & (same_slot >= 0))
                def _():
                    ermw_add(in_w, jnp.maximum(same_slot, 0), nid, ew)

                @pl.when(has_prev & (same_slot < 0) & (empty_slot >= 0))
                def _():
                    ermw(in_src, empty_slot, nid, prev)
                    ermw(in_w, empty_slot, nid, ew)
                    rmwn(in_cnt, nid, cnt + 1)

                failed = failed | (has_prev & (same_slot < 0) &
                                   (empty_slot < 0))
                # key[nid] == key_val in every non-overflow case (matched:
                # key_val = k0 = key[found]; new: just written), and under
                # overflow the window is already failed — saves a reduction
                return (n, failed, nid, key_val, wj)

            n, failed, _, _, _ = jax.lax.fori_loop(
                0, Ln, upd_body,
                (n, failed, jnp.int32(-1), jnp.float32(-1.0), jnp.int32(0)))
            return (n, failed, hit) if band else (n, failed)

        @pl.when(n_layers > 0)
        def _():
            start_copy(0, 0)

        def layer_loop(li, carry):
            failed = carry[1]
            slot = jax.lax.rem(li, 2)
            wait_copy(li, slot)

            @pl.when(li + 1 < n_layers)
            def _():
                # prefetch the next layer while this one's DP runs
                start_copy(li + 1, jax.lax.rem(li + 1, 2))

            run = (lens_ref[0, 0, li] > 0) & ~failed
            return jax.lax.cond(run, lambda c: do_layer(li, slot, c),
                                lambda c: c, carry)

        if band:
            n, failed, hit = jax.lax.fori_loop(
                0, n_layers, layer_loop,
                (bb_len, jnp.bool_(False), jnp.bool_(False)))
        else:
            n, failed = jax.lax.fori_loop(
                0, n_layers, layer_loop, (bb_len, jnp.bool_(False)))

        # ---- consensus -----------------------------------------------------
        def score_body(r, c):
            best_u, best_s = c
            u = loadn(order[:], r)

            def slot_scan(e, c2):
                bw, bs, bp = c2
                src = eload(in_src, e, u)
                w = eload(in_w, e, u)
                s = loadn(score[:], jnp.maximum(src, 0))
                better = (w > bw) | ((w == bw) & (s > bs))
                return (jnp.where(better, w, bw), jnp.where(better, s, bs),
                        jnp.where(better, src, bp))

            bw, bs, bp = jax.lax.fori_loop(
                0, loadn(in_cnt[:], u), slot_scan,
                (jnp.int32(NEG), jnp.int32(NEG), jnp.int32(-1)))
            s = jnp.where(bp >= 0, bw + bs, 0)
            rmwn(score, u, s)
            rmwn(pred, u, bp)
            better = s > best_s
            return (jnp.where(better, u, best_u), jnp.maximum(s, best_s))

        summit, _ = jax.lax.fori_loop(0, n, score_body,
                                      (jnp.int32(0), jnp.int32(NEG)))

        # backward walk to a source
        def bcond(c):
            u, cnt = c
            return (u != -1) & (cnt < N)

        def bbody(c):
            u, cnt = c
            rmwn(revbuf, cnt, u)
            return (loadn(pred[:], u), cnt + 1)

        _, cnt_b = jax.lax.while_loop(bcond, bbody, (summit, jnp.int32(0)))

        cons_base_ref[0] = jnp.full((8, NW), -1, jnp.int32)
        cons_cov_ref[0] = jnp.zeros((8, NW), jnp.int32)

        def emit(i, u):
            cons_base_ref[0] = jnp.where(nn_i == i, loadn(base[:], u),
                                         cons_base_ref[0])
            cons_cov_ref[0] = jnp.where(nn_i == i, loadn(cov[:], u),
                                        cons_cov_ref[0])

        def flip_body(i, _):
            emit(i, loadn(revbuf[:], cnt_b - 1 - i))
            return 0

        jax.lax.fori_loop(0, cnt_b, flip_body, 0)

        # forward walk to a sink along heaviest out-edges
        def fcond(c):
            u, cnt, more = c
            return more & (cnt < N)

        def fbody(c):
            u, cnt, _ = c
            ew = jnp.where(in_src[:] == u, in_w[:], NEG)      # (E, 8, NW)
            wv2 = jnp.max(ew, axis=0)                         # (8, NW)
            any_out = jnp.max(wv2) > NEG
            wmax = jnp.max(wv2)
            scorev = score[:]
            cand_s = jnp.where(wv2 == wmax, scorev, NEG)
            smax = jnp.max(cand_s)
            v = jnp.min(jnp.where(cand_s == smax, nn_i, SN)).astype(
                jnp.int32)

            @pl.when(any_out)
            def _():
                emit(cnt, v)

            return (jnp.where(any_out, v, u), cnt + jnp.where(any_out, 1, 0),
                    any_out)

        _, cnt, _ = jax.lax.while_loop(
            fcond, fbody, (summit, cnt_b, jnp.bool_(True)))

        cons_len_ref[0, 0, 0] = cnt
        failed_ref[0, 0, 0] = failed.astype(jnp.int32)
        n_nodes_ref[0, 0, 0] = n
        if band:
            band_hit_ref[0, 0, 0] = hit.astype(jnp.int32)

    def make(batch: int):
        # Mosaic block rules: last two block dims must tile (8,128) or equal
        # the array dims; the blocked tiles satisfy this natively. SMEM
        # residency stays O(D), not O(B*D); layer arrays live in HBM (ANY)
        # and are DMA'd per layer.
        smem3 = lambda w: pl.BlockSpec((1, 1, w), lambda b: (b, 0, 0),
                                       memory_space=pltpu.SMEM)
        vblk = pl.BlockSpec((1, 8, NW), lambda b: (b, 0, 0),
                            memory_space=pltpu.VMEM)
        hbm = pl.BlockSpec(memory_space=pl.ANY)

        scal = jax.ShapeDtypeStruct((batch, 1, 1), jnp.int32)
        return pl.pallas_call(
            kernel,
            grid=(batch,),
            in_specs=[smem3(1), smem3(1), smem3(D), smem3(D), smem3(D),
                      vblk, vblk, hbm, hbm] +
                     ([smem3(1)] if band else []),
            out_specs=[vblk, vblk, smem3(1), smem3(1), smem3(1)] +
                      ([smem3(1)] if band else []),
            out_shape=[
                jax.ShapeDtypeStruct((batch, 8, NW), jnp.int32),
                jax.ShapeDtypeStruct((batch, 8, NW), jnp.int32),
                scal, scal, scal,
            ] + ([scal] if band else []),
            scratch_shapes=[
                pltpu.VMEM((N + 1, 8, JW), jnp.int32),  # H
                pltpu.VMEM((N + 1, 8, JW), jnp.int32),  # MV (move records)
                pltpu.VMEM((8, NW), jnp.int32),         # base
                pltpu.VMEM((8, NW), jnp.float32),       # key
                pltpu.VMEM((8, NW), jnp.int32),         # cov
                pltpu.VMEM((8, NW), jnp.int32),         # order
                pltpu.VMEM((E, 8, NW), jnp.int32),      # in_src
                pltpu.VMEM((E, 8, NW), jnp.int32),      # in_w
                pltpu.VMEM((8, NW), jnp.int32),         # in_cnt
                pltpu.VMEM((8, JW), jnp.float32),       # nkey
                pltpu.VMEM((8, JW), jnp.int32),         # runrem
                pltpu.VMEM((8, NW), jnp.int32),         # score
                pltpu.VMEM((8, NW), jnp.int32),         # pred
                pltpu.VMEM((8, NW), jnp.int32),         # revbuf
                pltpu.VMEM((8, NW), jnp.int32),         # esc (end scores)
                pltpu.VMEM((8, NW), jnp.int32),         # rank_of
                pltpu.VMEM((2, 8, JW), jnp.int32),      # seq_scr (2 slots)
                pltpu.VMEM((2, 8, JW), jnp.int32),      # w_scr
                pltpu.SemaphoreType.DMA((2, 2)),        # per (slot, array)
            ],
            interpret=interpret,
        )

    @functools.lru_cache(maxsize=8)
    def jitted(batch: int):
        call = make(batch)

        def fn(bb_len, n_layers, lens, begins, ends, bb, bbw, seqs, ws,
               *extra):
            # host-shaped inputs -> sublane-blocked tiles (XLA relayouts
            # on device; the pallas kernel sees native (8, W) tiles)
            bbB = jnp.pad(bb.reshape(batch, BB),
                          ((0, 0), (0, SN - BB))).reshape(batch, 8, NW)
            bbwB = jnp.pad(bbw.reshape(batch, BB),
                           ((0, 0), (0, SN - BB))).reshape(batch, 8, NW)
            seqsB = jnp.pad(seqs, ((0, 0), (0, 0), (0, SJ - L)),
                            constant_values=255).reshape(batch, D, 8, JW)
            wsB = jnp.pad(ws, ((0, 0), (0, 0), (0, SJ - L))
                          ).reshape(batch, D, 8, JW)
            args = [bb_len.reshape(batch, 1, 1),
                    n_layers.reshape(batch, 1, 1),
                    lens.reshape(batch, 1, D), begins.reshape(batch, 1, D),
                    ends.reshape(batch, 1, D), bbB, bbwB, seqsB, wsB]
            if band:
                args.append(extra[0].reshape(batch, 1, 1))
            outs = call(*args)
            cb, cc, cl, fl, nn = outs[:5]
            res = (cb.reshape(batch, SN)[:, :N],
                   cc.reshape(batch, SN)[:, :N],
                   cl.reshape(batch, 1), fl.reshape(batch, 1),
                   nn.reshape(batch, 1))
            if band:
                res = res + (outs[5].reshape(batch, 1),)
            return res

        return jax.jit(fn)

    return jitted
