"""Fused Pallas TPU kernel for batched POA window consensus.

Same semantics as the reference JAX implementation in poa.py (which mirrors
the host oracle rt_poa.cpp), but the entire per-window program — graph init,
per-layer sequence-to-graph DP, traceback, graph update, heaviest-bundle
consensus — runs as ONE kernel program per window (grid over the batch), with
the DP matrix and all graph state resident in VMEM. This removes the
per-step XLA while-loop overhead that dominates the pure-JAX version
(~160us/step there; in-kernel loop iterations are orders of magnitude
cheaper).

Key differences from poa.py, none semantic:
  * topological order is maintained incrementally (an O(N) vector
    shift-insert per new node) instead of argsort per layer; the subgraph is
    then a contiguous rank range [count(key < lo), count(key <= hi)).
  * end-node detection reuses the DP's predecessor enumeration (any
    in-subgraph edge marks its source as "has out-edge").
  * the linear-gap cummax runs as log2(width) shift-max steps.

VMEM budget (w=500 config: N=1536, L=768): H (1537x896 i32) ~5.5 MB, layer
inputs ~1.2 MB, graph arrays <1 MB — comfortably under the ~16 MB/core VMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .poa import PoaConfig

NEG = -(1 << 28)


def _round_up(x, m):
    return (x + m - 1) // m * m


@functools.lru_cache(maxsize=32)
def build_pallas_poa_kernel(cfg: PoaConfig, interpret: bool = False):
    N = cfg.max_nodes
    L = cfg.max_len
    BB = cfg.max_backbone
    E = cfg.max_edges
    D = cfg.depth
    LP = _round_up(L + 1, 128)          # H row width (lanes)
    # plain Python scalars: captured jnp values would become kernel constants
    M = int(cfg.match)
    X = int(cfg.mismatch)
    G = int(cfg.gap)
    KEY_INF = 3.0e38

    VSLOT = 15  # pred-slot sentinel meaning "virtual start row"

    def kernel(bb_len_ref, n_layers_ref, lens_ref, begins_ref, ends_ref,
               bb_ref, bbw_ref, seqs_ref, ws_ref,
               cons_base_ref, cons_cov_ref, cons_len_ref, failed_ref,
               n_nodes_ref,
               H, MV, base, key, cov, order, in_src, in_w, in_cnt,
               pos_node, nkey, runrem, score, pred, revbuf, has_out,
               seq_scr, w_scr):
        lane_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        lane_lp = jax.lax.broadcasted_iota(jnp.int32, (1, LP), 1)
        gvec = lane_lp * G

        bb_len = bb_len_ref[0, 0]
        n_layers = n_layers_ref[0, 0]

        # ---- graph init from the backbone chain --------------------------
        bbrow = bb_ref[:]                                   # (1, BB)
        bbpad = jnp.full((1, N), -1, jnp.int32).at[:, :BB].set(bbrow)
        used0 = lane_n < bb_len
        base[:] = jnp.where(used0, bbpad, -1)
        key[:] = jnp.where(used0, lane_n.astype(jnp.float32), KEY_INF)
        cov[:] = jnp.where(used0, 1, 0)
        order[:] = lane_n
        bbw_row = bbw_ref[:]
        bbw_pad = jnp.zeros((1, N), jnp.int32).at[:, :BB].set(bbw_row)
        chain = (lane_n > 0) & used0
        in_src[:] = jnp.full((E, N), -1, jnp.int32)
        in_src[0:1, :] = jnp.where(chain, lane_n - 1, -1)
        in_w[:] = jnp.zeros((E, N), jnp.int32)
        in_w[0:1, :] = jnp.where(chain,
                                 pltpu.roll(bbw_pad, 1, 1) + bbw_pad, 0)
        # edge slots fill contiguously from 0, so in_cnt doubles as "first
        # empty slot" and bounds every per-node slot loop to the true degree
        in_cnt[:] = jnp.where(chain, 1, 0)
        H[0:1, :] = gvec

        def cummax_lanes(x):
            k = 1
            while k < LP:
                sh = jnp.where(lane_lp >= k, pltpu.roll(x, k, 1), NEG)
                x = jnp.maximum(x, sh)
                k *= 2
            return x

        # ---- one layer ----------------------------------------------------
        def do_layer(li, carry):
            n, failed = carry
            Ln = lens_ref[0, li]
            begin = begins_ref[0, li]
            end = ends_ref[0, li]

            # full-graph rule (reference: src/window.cpp:88-97)
            offset = (0.01 * bb_len.astype(jnp.float32)).astype(jnp.int32)
            full = (begin < offset) & (end > bb_len - offset)
            lo = jnp.where(full, jnp.float32(-3.0e38), begin.astype(jnp.float32))
            hi = jnp.where(full, jnp.float32(3.0e38), end.astype(jnp.float32))

            # stage the layer into scratch
            seq_scr[:] = jnp.full((1, LP), 255, jnp.int32).at[:, :L].set(
                seqs_ref[0, pl.ds(li, 1), :])
            w_scr[:] = jnp.zeros((1, LP), jnp.int32).at[:, :L].set(
                ws_ref[0, pl.ds(li, 1), :])

            keys = key[:]
            r_lo = jnp.sum(jnp.where(keys < lo, 1, 0)).astype(jnp.int32)
            r_hi = jnp.sum(jnp.where(keys <= hi, 1, 0)).astype(jnp.int32)

            has_out[:] = jnp.zeros((1, N), jnp.int32)

            seqv = seq_scr[:]
            seqm1 = pltpu.roll(seqv, 1, 1)

            # ---- DP over subgraph nodes in rank order ---------------------
            # Per-cell move records (2 bits move + pred slot, VSLOT =
            # virtual) land in MV so the traceback is one load per step.
            def dp_body(r, _):
                u = order[0, r]
                ub = base[0, u]

                def pred_scan(e, c):
                    P, Pslot, any_valid = c
                    src = in_src[e, u]
                    ok = key[0, jnp.maximum(src, 0)] >= lo
                    prow = H[pl.ds(jnp.maximum(src, 0) + 1, 1), :]
                    better = ok & (prow > P)  # strict: first max slot wins
                    P = jnp.where(better, prow, P)
                    Pslot = jnp.where(better, e, Pslot)

                    @pl.when(ok)
                    def _():
                        has_out[0, jnp.maximum(src, 0)] = 1
                    return (P, Pslot, any_valid | ok)

                P0 = jnp.full((1, LP), NEG, jnp.int32)
                S0 = jnp.full((1, LP), VSLOT, jnp.int32)
                P, Pslot, any_valid = jax.lax.fori_loop(
                    0, in_cnt[0, u], pred_scan, (P0, S0, jnp.bool_(False)))
                P = jnp.where(any_valid, P, H[pl.ds(0, 1), :])
                Pslot = jnp.where(any_valid, Pslot, VSLOT)

                scvec = jnp.where(seqm1 == ub, M, X)
                Psh = jnp.where(lane_lp >= 1, pltpu.roll(P, 1, 1), NEG)
                Ssh = jnp.where(lane_lp >= 1, pltpu.roll(Pslot, 1, 1), VSLOT)
                diag = Psh + scvec
                up = P + G
                choose_diag = diag >= up  # host priority: diag before up
                V = jnp.where(choose_diag, diag, up)
                vmove = jnp.where(choose_diag, 4 * Ssh, 1 + 4 * Pslot)
                row = cummax_lanes(V - gvec) + gvec
                mv = jnp.where(row > V, 2, vmove)  # left only if strictly better
                H[pl.ds(u + 1, 1), :] = row
                MV[pl.ds(u + 1, 1), :] = mv.astype(jnp.int8)
                return 0

            jax.lax.fori_loop(r_lo, r_hi, dp_body, 0)

            # ---- best end node (first max in rank order) ------------------
            def end_body(r, c):
                best_u, best_s = c
                u = order[0, r]
                is_end = has_out[0, u] == 0
                s = H[u + 1, Ln]
                better = is_end & (s > best_s)
                return (jnp.where(better, u, best_u),
                        jnp.where(better, s, best_s))

            best_u, _ = jax.lax.fori_loop(
                r_lo, r_hi, end_body,
                (jnp.int32(-1), jnp.int32(NEG)))

            # ---- traceback -------------------------------------------------
            pos_node[:] = jnp.full((1, L), -1, jnp.int32)

            def tb_cond(c):
                u, j, steps, ok = c
                return (~((u == -1) & (j == 0))) & (steps < N + L + 2)

            def tb_body(c):
                u, j, steps, ok = c
                at_virtual = u == -1
                uc = jnp.maximum(u, 0)
                jm1 = jnp.maximum(j - 1, 0)
                mv = jnp.where(at_virtual, 2,
                               MV[uc + 1, j].astype(jnp.int32))
                move = mv % 4
                slot = mv // 4
                slot_c = jnp.minimum(slot, E - 1)
                prd = jnp.where(slot == VSLOT, -1, in_src[slot_c, uc])

                take_diag = ~at_virtual & (move == 0)
                take_up = ~at_virtual & (move == 1)

                @pl.when(take_diag)
                def _():
                    pos_node[0, jm1] = u

                new_u = jnp.where(take_diag | take_up, prd, u)
                new_j = jnp.where(take_up, j, j - 1)
                return (new_u, new_j, steps + 1, ok)

            fu, fj, _, _ = jax.lax.while_loop(
                tb_cond, tb_body,
                (best_u, Ln, jnp.int32(0), jnp.bool_(True)))
            failed = failed | ~((fu == -1) & (fj == 0))

            # ---- next-matched-key / run-remaining (backward) ---------------
            def back_body(i, c):
                nk, run = c
                j = Ln - 1 - i
                pn = pos_node[0, j]
                m = pn >= 0
                nk = jnp.where(m, key[0, jnp.maximum(pn, 0)], nk)
                run = jnp.where(m, 0, run + 1)
                nkey[0, j] = nk
                runrem[0, j] = run
                return (nk, run)

            jax.lax.fori_loop(0, Ln, back_body,
                              (jnp.float32(KEY_INF), jnp.int32(0)))

            # ---- graph update ----------------------------------------------
            def upd_body(j, c):
                n, failed, prev, prev_key, prev_w = c
                b = seq_scr[0, j]
                wj = w_scr[0, j]
                pn = pos_node[0, j]
                is_match = pn >= 0
                k0 = key[0, jnp.maximum(pn, 0)]

                keys = key[:]
                cand = (keys == k0) & (base[:] == b)
                has = cand.any() & is_match
                found = jnp.min(jnp.where(cand, lane_n, N)).astype(jnp.int32)

                nk = nkey[0, j]
                run = runrem[0, j].astype(jnp.float32)
                hi2 = jnp.where(nk < KEY_INF, nk, prev_key + 1.0)
                lo2 = jnp.where(prev >= 0, prev_key, hi2 - run - 1.0)
                k_new = lo2 + (hi2 - lo2) / (run + 1.0)
                key_val = jnp.where(is_match, k0, k_new)

                need_new = ~has
                overflow = need_new & (n >= N)
                do_new = need_new & ~overflow
                nid = jnp.where(has, found, jnp.minimum(n, N - 1))

                @pl.when(do_new)
                def _():
                    # insert into sorted order: after all keys <= key_val
                    p = jnp.sum(jnp.where(keys <= key_val, 1, 0)).astype(
                        jnp.int32)
                    base[0, nid] = b
                    key[0, nid] = key_val
                    ordv = order[:]
                    shifted = pltpu.roll(ordv, 1, 1)
                    order[:] = jnp.where(
                        lane_n < p, ordv,
                        jnp.where(lane_n == p, nid, shifted))

                touch = ~overflow

                @pl.when(touch)
                def _():
                    cov[0, nid] = cov[0, nid] + 1

                n = n + jnp.where(do_new, 1, 0)
                failed = failed | overflow

                # edge prev -> nid, weight w[j-1] + w[j]
                has_prev = touch & (prev >= 0)

                def eslot_scan(e, c2):
                    same_slot = c2
                    src = in_src[e, nid]
                    return jnp.where((src == prev) & (same_slot < 0), e,
                                     same_slot)

                cnt = in_cnt[0, nid]
                same_slot = jax.lax.fori_loop(
                    0, cnt, eslot_scan, jnp.int32(-1))
                empty_slot = jnp.where(cnt < E, cnt, -1)
                ew = prev_w + wj

                @pl.when(has_prev & (same_slot >= 0))
                def _():
                    in_w[same_slot, nid] = in_w[same_slot, nid] + ew

                @pl.when(has_prev & (same_slot < 0) & (empty_slot >= 0))
                def _():
                    in_src[empty_slot, nid] = prev
                    in_w[empty_slot, nid] = ew
                    in_cnt[0, nid] = cnt + 1

                failed = failed | (has_prev & (same_slot < 0) &
                                   (empty_slot < 0))
                return (n, failed, nid, key[0, nid], wj)

            n, failed, _, _, _ = jax.lax.fori_loop(
                0, Ln, upd_body,
                (n, failed, jnp.int32(-1), jnp.float32(-1.0), jnp.int32(0)))
            return (n, failed)

        def layer_loop(li, carry):
            n, failed = carry
            run = (lens_ref[0, li] > 0) & ~failed
            return jax.lax.cond(run, lambda c: do_layer(li, c),
                                lambda c: c, (n, failed))

        n, failed = jax.lax.fori_loop(
            0, n_layers, layer_loop, (bb_len, jnp.bool_(False)))

        # ---- consensus -----------------------------------------------------
        def score_body(r, c):
            best_u, best_s = c
            u = order[0, r]

            def slot_scan(e, c2):
                bw, bs, bp = c2
                src = in_src[e, u]
                w = in_w[e, u]
                s = score[0, jnp.maximum(src, 0)]
                better = (w > bw) | ((w == bw) & (s > bs))
                return (jnp.where(better, w, bw), jnp.where(better, s, bs),
                        jnp.where(better, src, bp))

            bw, bs, bp = jax.lax.fori_loop(
                0, in_cnt[0, u], slot_scan, (jnp.int32(NEG), jnp.int32(NEG),
                                             jnp.int32(-1)))
            s = jnp.where(bp >= 0, bw + bs, 0)
            score[0, u] = s
            pred[0, u] = bp
            better = s > best_s
            return (jnp.where(better, u, best_u), jnp.maximum(s, best_s))

        summit, _ = jax.lax.fori_loop(0, n, score_body,
                                      (jnp.int32(0), jnp.int32(NEG)))

        # backward walk to a source
        def bcond(c):
            u, cnt = c
            return (u != -1) & (cnt < N)

        def bbody(c):
            u, cnt = c
            revbuf[0, cnt] = u
            return (pred[0, u], cnt + 1)

        _, cnt_b = jax.lax.while_loop(bcond, bbody, (summit, jnp.int32(0)))

        cons_base_ref[:] = jnp.full((1, N), -1, jnp.int32)
        cons_cov_ref[:] = jnp.zeros((1, N), jnp.int32)

        def emit(i, u):
            cons_base_ref[0, i] = base[0, u]
            cons_cov_ref[0, i] = cov[0, u]

        def flip_body(i, _):
            emit(i, revbuf[0, cnt_b - 1 - i])
            return 0

        jax.lax.fori_loop(0, cnt_b, flip_body, 0)

        # forward walk to a sink along heaviest out-edges
        def fcond(c):
            u, cnt, more = c
            return more & (cnt < N)

        def fbody(c):
            u, cnt, _ = c
            ew = jnp.where(in_src[:] == u, in_w[:], NEG)      # (E, N)
            wv = jnp.max(ew, axis=0, keepdims=True)           # (1, N)
            any_out = jnp.max(wv) > NEG
            wmax = jnp.max(wv)
            scorev = score[:]
            cand_s = jnp.where(wv == wmax, scorev, NEG)
            smax = jnp.max(cand_s)
            v = jnp.min(jnp.where(cand_s == smax, lane_n, N)).astype(
                jnp.int32)

            @pl.when(any_out)
            def _():
                emit(cnt, v)

            return (jnp.where(any_out, v, u), cnt + jnp.where(any_out, 1, 0),
                    any_out)

        _, cnt, _ = jax.lax.while_loop(
            fcond, fbody, (summit, cnt_b, jnp.bool_(True)))

        cons_len_ref[0, 0] = cnt
        failed_ref[0, 0] = failed.astype(jnp.int32)
        n_nodes_ref[0, 0] = n

    def make(batch: int):
        smem1 = lambda: pl.BlockSpec((1, 1), lambda b: (b, 0),
                                     memory_space=pltpu.SMEM)
        smemD = lambda: pl.BlockSpec((1, D), lambda b: (b, 0),
                                     memory_space=pltpu.SMEM)
        vmem2 = lambda w: pl.BlockSpec((1, w), lambda b: (b, 0),
                                       memory_space=pltpu.VMEM)
        vmem3 = lambda: pl.BlockSpec((1, D, L), lambda b: (b, 0, 0),
                                     memory_space=pltpu.VMEM)

        return pl.pallas_call(
            kernel,
            grid=(batch,),
            in_specs=[smem1(), smem1(), smemD(), smemD(), smemD(),
                      vmem2(BB), vmem2(BB), vmem3(), vmem3()],
            out_specs=[vmem2(N), vmem2(N), smem1(), smem1(), smem1()],
            out_shape=[
                jax.ShapeDtypeStruct((batch, N), jnp.int32),
                jax.ShapeDtypeStruct((batch, N), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((N + 1, LP), jnp.int32),    # H
                pltpu.VMEM((N + 1, LP), jnp.int8),     # MV (move records)
                pltpu.VMEM((1, N), jnp.int32),         # base
                pltpu.VMEM((1, N), jnp.float32),       # key
                pltpu.VMEM((1, N), jnp.int32),         # cov
                pltpu.VMEM((1, N), jnp.int32),         # order
                pltpu.VMEM((E, N), jnp.int32),         # in_src
                pltpu.VMEM((E, N), jnp.int32),         # in_w
                pltpu.VMEM((1, N), jnp.int32),         # in_cnt
                pltpu.VMEM((1, L), jnp.int32),         # pos_node
                pltpu.VMEM((1, L), jnp.float32),       # nkey
                pltpu.VMEM((1, L), jnp.int32),         # runrem
                pltpu.VMEM((1, N), jnp.int32),         # score
                pltpu.VMEM((1, N), jnp.int32),         # pred
                pltpu.VMEM((1, N), jnp.int32),         # revbuf
                pltpu.VMEM((1, N), jnp.int32),         # has_out
                pltpu.VMEM((1, LP), jnp.int32),        # seq_scr
                pltpu.VMEM((1, LP), jnp.int32),        # w_scr
            ],
            interpret=interpret,
        )

    @functools.lru_cache(maxsize=8)
    def jitted(batch: int):
        call = make(batch)

        def fn(bb_len, n_layers, lens, begins, ends, bb, bbw, seqs, ws):
            return call(bb_len, n_layers, lens, begins, ends, bb, bbw, seqs,
                        ws)

        return jax.jit(fn)

    return jitted
