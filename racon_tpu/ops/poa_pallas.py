"""Fused Pallas TPU kernel for batched POA window consensus.

Same semantics as the reference JAX implementation in poa.py (which mirrors
the host oracle rt_poa.cpp), but the entire per-window program — graph init,
per-layer sequence-to-graph DP, traceback, graph update, heaviest-bundle
consensus — runs as ONE kernel program per window (grid over the batch), with
the DP matrix and all graph state resident in VMEM. This removes the
per-step XLA while-loop overhead that dominates the pure-JAX version
(~160us/step there; in-kernel loop iterations are orders of magnitude
cheaper).

Key differences from poa.py, none semantic:
  * topological order is maintained incrementally (an O(N) vector
    shift-insert per new node) instead of argsort per layer; the subgraph is
    then a contiguous rank range [count(key < lo), count(key <= hi)).
  * end-node detection reuses the DP's predecessor enumeration (any
    in-subgraph edge marks its source as "has out-edge").
  * the linear-gap cummax runs as log2(width) shift-max steps.

VMEM budget (w=500 config: N=1536, L=768): H (1537x896 i32) ~5.5 MB, layer
inputs ~1.2 MB, graph arrays <1 MB — comfortably under the ~16 MB/core VMEM.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .poa import PoaConfig

NEG = -(1 << 28)


def _round_up(x, m):
    return (x + m - 1) // m * m


@functools.lru_cache(maxsize=32)
def build_pallas_poa_kernel(cfg: PoaConfig, interpret: bool = False):
    N = cfg.max_nodes
    L = cfg.max_len
    BB = cfg.max_backbone
    E = cfg.max_edges
    D = cfg.depth
    LP = _round_up(L + 1, 128)          # H row width (lanes)
    # plain Python scalars: captured jnp values would become kernel constants
    M = int(cfg.match)
    X = int(cfg.mismatch)
    G = int(cfg.gap)
    KEY_INF = 3.0e38

    VSLOT = 15  # pred-slot sentinel meaning "virtual start row"

    def kernel(bb_len_ref, n_layers_ref, lens_ref, begins_ref, ends_ref,
               bb_ref, bbw_ref, seqs_ref, ws_ref,
               cons_base_ref, cons_cov_ref, cons_len_ref, failed_ref,
               n_nodes_ref,
               H, MV, base, key, cov, order, in_src, in_w, in_cnt,
               pos_node, nkey, runrem, score, pred, revbuf, has_out,
               seq_scr, w_scr):
        lane_n = jax.lax.broadcasted_iota(jnp.int32, (1, N), 1)
        lane_lp = jax.lax.broadcasted_iota(jnp.int32, (1, LP), 1)
        lane_l = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)
        en_rows = jax.lax.broadcasted_iota(jnp.int32, (E, N), 0)
        en_cols = jax.lax.broadcasted_iota(jnp.int32, (E, N), 1)
        gvec = lane_lp * G

        # Mosaic cannot store scalars to VMEM; every scalar store becomes a
        # masked full-row read-modify-write (the rows are a handful of
        # vregs, so this costs a few VPU ops per store).
        def rmw1(ref, iota, idx, val):
            ref[:] = jnp.where(iota == idx, val, ref[:])

        def rmw2(ref, row, col, val):
            ref[:] = jnp.where((en_rows == row) & (en_cols == col), val,
                               ref[:])

        # ... and every dynamic-lane scalar load becomes a masked reduction
        # (dynamic lane offsets must be 128-aligned on Mosaic; dynamic
        # sublane offsets are fine, which the H/MV row accesses rely on).
        def load1(ref, iota, idx):
            row = ref[:]
            return jnp.sum(jnp.where(iota == idx, row,
                                     jnp.zeros_like(row)))

        def load2(ref, row, col):
            v = ref[:]
            return jnp.sum(jnp.where((en_rows == row) & (en_cols == col), v,
                                     jnp.zeros_like(v)))

        def load_lane(rowvec, iota, idx):
            return jnp.sum(jnp.where(iota == idx, rowvec,
                                     jnp.zeros_like(rowvec)))

        bb_len = bb_len_ref[0, 0, 0]
        n_layers = n_layers_ref[0, 0, 0]

        def padcat(row, width, fill):
            # static right-pad to `width` lanes (Mosaic has no scatter;
            # concatenate lowers cleanly)
            w = row.shape[1]
            if w == width:
                return row
            return jnp.concatenate(
                [row, jnp.full((1, width - w), fill, row.dtype)], axis=1)

        # ---- graph init from the backbone chain --------------------------
        bbrow = bb_ref[0]                                   # (1, BB)
        bbpad = padcat(bbrow, N, -1)
        used0 = lane_n < bb_len
        base[:] = jnp.where(used0, bbpad, -1)
        key[:] = jnp.where(used0, lane_n.astype(jnp.float32), KEY_INF)
        cov[:] = jnp.where(used0, 1, 0)
        order[:] = lane_n
        bbw_row = bbw_ref[0]
        bbw_pad = padcat(bbw_row, N, 0)
        chain = (lane_n > 0) & used0
        in_src[:] = jnp.full((E, N), -1, jnp.int32)
        in_src[0:1, :] = jnp.where(chain, lane_n - 1, -1)
        in_w[:] = jnp.zeros((E, N), jnp.int32)
        in_w[0:1, :] = jnp.where(chain,
                                 pltpu.roll(bbw_pad, 1, 1) + bbw_pad, 0)
        # edge slots fill contiguously from 0, so in_cnt doubles as "first
        # empty slot" and bounds every per-node slot loop to the true degree
        in_cnt[:] = jnp.where(chain, 1, 0)
        H[0:1, :] = gvec

        def cummax_lanes(x):
            k = 1
            while k < LP:
                sh = jnp.where(lane_lp >= k, pltpu.roll(x, k, 1), NEG)
                x = jnp.maximum(x, sh)
                k *= 2
            return x

        # ---- one layer ----------------------------------------------------
        def do_layer(li, carry):
            n, failed = carry
            Ln = lens_ref[0, 0, li]
            begin = begins_ref[0, 0, li]
            end = ends_ref[0, 0, li]

            # full-graph rule (reference: src/window.cpp:88-97)
            offset = (0.01 * bb_len.astype(jnp.float32)).astype(jnp.int32)
            full = (begin < offset) & (end > bb_len - offset)
            lo = jnp.where(full, jnp.float32(-3.0e38), begin.astype(jnp.float32))
            hi = jnp.where(full, jnp.float32(3.0e38), end.astype(jnp.float32))

            # stage the layer into scratch
            seq_scr[:] = padcat(seqs_ref[0, pl.ds(li, 1), :], LP, 255)
            w_scr[:] = padcat(ws_ref[0, pl.ds(li, 1), :], LP, 0)

            keys = key[:]
            r_lo = jnp.sum(jnp.where(keys < lo, 1, 0)).astype(jnp.int32)
            r_hi = jnp.sum(jnp.where(keys <= hi, 1, 0)).astype(jnp.int32)

            has_out[:] = jnp.zeros((1, N), jnp.int32)

            seqv = seq_scr[:]
            seqm1 = pltpu.roll(seqv, 1, 1)

            # ---- DP over subgraph nodes in rank order ---------------------
            # Per-cell move records (2 bits move + pred slot, VSLOT =
            # virtual) land in MV so the traceback is one load per step.
            def dp_body(r, _):
                u = load1(order, lane_n, r)
                ub = load1(base, lane_n, u)

                def pred_scan(e, c):
                    P, Pslot, any_valid = c
                    src = load2(in_src, e, u)
                    ok = load1(key, lane_n, jnp.maximum(src, 0)) >= lo
                    prow = H[pl.ds(jnp.maximum(src, 0) + 1, 1), :]
                    better = ok & (prow > P)  # strict: first max slot wins
                    P = jnp.where(better, prow, P)
                    Pslot = jnp.where(better, e, Pslot)

                    @pl.when(ok)
                    def _():
                        rmw1(has_out, lane_n, jnp.maximum(src, 0), 1)
                    return (P, Pslot, any_valid | ok)

                P0 = jnp.full((1, LP), NEG, jnp.int32)
                S0 = jnp.full((1, LP), VSLOT, jnp.int32)
                P, Pslot, any_valid = jax.lax.fori_loop(
                    0, load1(in_cnt, lane_n, u), pred_scan,
                    (P0, S0, jnp.bool_(False)))
                P = jnp.where(any_valid, P, H[pl.ds(0, 1), :])
                Pslot = jnp.where(any_valid, Pslot, VSLOT)

                scvec = jnp.where(seqm1 == ub, M, X)
                Psh = jnp.where(lane_lp >= 1, pltpu.roll(P, 1, 1), NEG)
                Ssh = jnp.where(lane_lp >= 1, pltpu.roll(Pslot, 1, 1), VSLOT)
                diag = Psh + scvec
                up = P + G
                choose_diag = diag >= up  # host priority: diag before up
                V = jnp.where(choose_diag, diag, up)
                vmove = jnp.where(choose_diag, 4 * Ssh, 1 + 4 * Pslot)
                row = cummax_lanes(V - gvec) + gvec
                mv = jnp.where(row > V, 2, vmove)  # left only if strictly better
                H[pl.ds(u + 1, 1), :] = row
                MV[pl.ds(u + 1, 1), :] = mv
                return 0

            jax.lax.fori_loop(r_lo, r_hi, dp_body, 0)

            # ---- best end node (first max in rank order) ------------------
            def end_body(r, c):
                best_u, best_s = c
                u = load1(order, lane_n, r)
                is_end = load1(has_out, lane_n, u) == 0
                s = load_lane(H[pl.ds(u + 1, 1), :], lane_lp, Ln)
                better = is_end & (s > best_s)
                return (jnp.where(better, u, best_u),
                        jnp.where(better, s, best_s))

            best_u, _ = jax.lax.fori_loop(
                r_lo, r_hi, end_body,
                (jnp.int32(-1), jnp.int32(NEG)))

            # ---- traceback -------------------------------------------------
            pos_node[:] = jnp.full((1, L), -1, jnp.int32)

            def tb_cond(c):
                u, j, steps, ok = c
                return (~((u == -1) & (j == 0))) & (steps < N + L + 2)

            def tb_body(c):
                u, j, steps, ok = c
                at_virtual = u == -1
                uc = jnp.maximum(u, 0)
                jm1 = jnp.maximum(j - 1, 0)
                mv_loaded = load_lane(MV[pl.ds(uc + 1, 1), :], lane_lp, j)
                mv = jnp.where(at_virtual, 2, mv_loaded)
                move = mv % 4
                slot = mv // 4
                slot_c = jnp.minimum(slot, E - 1)
                prd = jnp.where(slot == VSLOT, -1, load2(in_src, slot_c, uc))

                take_diag = ~at_virtual & (move == 0)
                take_up = ~at_virtual & (move == 1)

                @pl.when(take_diag)
                def _():
                    rmw1(pos_node, lane_l, jm1, u)

                new_u = jnp.where(take_diag | take_up, prd, u)
                new_j = jnp.where(take_up, j, j - 1)
                return (new_u, new_j, steps + 1, ok)

            fu, fj, _, _ = jax.lax.while_loop(
                tb_cond, tb_body,
                (best_u, Ln, jnp.int32(0), jnp.bool_(True)))
            failed = failed | ~((fu == -1) & (fj == 0))

            # ---- next-matched-key / run-remaining (backward) ---------------
            def back_body(i, c):
                nk, run = c
                j = Ln - 1 - i
                pn = load1(pos_node, lane_l, j)
                m = pn >= 0
                nk = jnp.where(m, load1(key, lane_n, jnp.maximum(pn, 0)), nk)
                run = jnp.where(m, 0, run + 1)
                rmw1(nkey, lane_l, j, nk)
                rmw1(runrem, lane_l, j, run)
                return (nk, run)

            jax.lax.fori_loop(0, Ln, back_body,
                              (jnp.float32(KEY_INF), jnp.int32(0)))

            # ---- graph update ----------------------------------------------
            def upd_body(j, c):
                n, failed, prev, prev_key, prev_w = c
                b = load1(seq_scr, lane_lp, j)
                wj = load1(w_scr, lane_lp, j)
                pn = load1(pos_node, lane_l, j)
                is_match = pn >= 0
                k0 = load1(key, lane_n, jnp.maximum(pn, 0))

                keys = key[:]
                cand = (keys == k0) & (base[:] == b)
                has = cand.any() & is_match
                found = jnp.min(jnp.where(cand, lane_n, N)).astype(jnp.int32)

                nk = load1(nkey, lane_l, j)
                run = load1(runrem, lane_l, j).astype(jnp.float32)
                hi2 = jnp.where(nk < KEY_INF, nk, prev_key + 1.0)
                lo2 = jnp.where(prev >= 0, prev_key, hi2 - run - 1.0)
                k_new = lo2 + (hi2 - lo2) / (run + 1.0)
                key_val = jnp.where(is_match, k0, k_new)

                need_new = ~has
                overflow = need_new & (n >= N)
                do_new = need_new & ~overflow
                nid = jnp.where(has, found, jnp.minimum(n, N - 1))

                @pl.when(do_new)
                def _():
                    # insert into sorted order: after all keys <= key_val
                    p = jnp.sum(jnp.where(keys <= key_val, 1, 0)).astype(
                        jnp.int32)
                    rmw1(base, lane_n, nid, b)
                    rmw1(key, lane_n, nid, key_val)
                    ordv = order[:]
                    shifted = pltpu.roll(ordv, 1, 1)
                    order[:] = jnp.where(
                        lane_n < p, ordv,
                        jnp.where(lane_n == p, nid, shifted))

                touch = ~overflow

                @pl.when(touch)
                def _():
                    rmw1(cov, lane_n, nid, load1(cov, lane_n, nid) + 1)

                n = n + jnp.where(do_new, 1, 0)
                failed = failed | overflow

                # edge prev -> nid, weight w[j-1] + w[j]
                has_prev = touch & (prev >= 0)

                def eslot_scan(e, c2):
                    same_slot = c2
                    src = load2(in_src, e, nid)
                    return jnp.where((src == prev) & (same_slot < 0), e,
                                     same_slot)

                cnt = load1(in_cnt, lane_n, nid)
                same_slot = jax.lax.fori_loop(
                    0, cnt, eslot_scan, jnp.int32(-1))
                empty_slot = jnp.where(cnt < E, cnt, -1)
                ew = prev_w + wj

                @pl.when(has_prev & (same_slot >= 0))
                def _():
                    rmw2(in_w, same_slot, nid,
                         load2(in_w, same_slot, nid) + ew)

                @pl.when(has_prev & (same_slot < 0) & (empty_slot >= 0))
                def _():
                    rmw2(in_src, empty_slot, nid, prev)
                    rmw2(in_w, empty_slot, nid, ew)
                    rmw1(in_cnt, lane_n, nid, cnt + 1)

                failed = failed | (has_prev & (same_slot < 0) &
                                   (empty_slot < 0))
                return (n, failed, nid, load1(key, lane_n, nid), wj)

            n, failed, _, _, _ = jax.lax.fori_loop(
                0, Ln, upd_body,
                (n, failed, jnp.int32(-1), jnp.float32(-1.0), jnp.int32(0)))
            return (n, failed)

        def layer_loop(li, carry):
            n, failed = carry
            run = (lens_ref[0, 0, li] > 0) & ~failed
            return jax.lax.cond(run, lambda c: do_layer(li, c),
                                lambda c: c, (n, failed))

        n, failed = jax.lax.fori_loop(
            0, n_layers, layer_loop, (bb_len, jnp.bool_(False)))

        # ---- consensus -----------------------------------------------------
        def score_body(r, c):
            best_u, best_s = c
            u = load1(order, lane_n, r)

            def slot_scan(e, c2):
                bw, bs, bp = c2
                src = load2(in_src, e, u)
                w = load2(in_w, e, u)
                s = load1(score, lane_n, jnp.maximum(src, 0))
                better = (w > bw) | ((w == bw) & (s > bs))
                return (jnp.where(better, w, bw), jnp.where(better, s, bs),
                        jnp.where(better, src, bp))

            bw, bs, bp = jax.lax.fori_loop(
                0, load1(in_cnt, lane_n, u), slot_scan,
                (jnp.int32(NEG), jnp.int32(NEG), jnp.int32(-1)))
            s = jnp.where(bp >= 0, bw + bs, 0)
            rmw1(score, lane_n, u, s)
            rmw1(pred, lane_n, u, bp)
            better = s > best_s
            return (jnp.where(better, u, best_u), jnp.maximum(s, best_s))

        summit, _ = jax.lax.fori_loop(0, n, score_body,
                                      (jnp.int32(0), jnp.int32(NEG)))

        # backward walk to a source
        def bcond(c):
            u, cnt = c
            return (u != -1) & (cnt < N)

        def bbody(c):
            u, cnt = c
            rmw1(revbuf, lane_n, cnt, u)
            return (load1(pred, lane_n, u), cnt + 1)

        _, cnt_b = jax.lax.while_loop(bcond, bbody, (summit, jnp.int32(0)))

        cons_base_ref[0] = jnp.full((1, N), -1, jnp.int32)
        cons_cov_ref[0] = jnp.zeros((1, N), jnp.int32)

        def emit(i, u):
            cons_base_ref[0] = jnp.where(lane_n == i, load1(base, lane_n, u),
                                         cons_base_ref[0])
            cons_cov_ref[0] = jnp.where(lane_n == i, load1(cov, lane_n, u),
                                        cons_cov_ref[0])

        def flip_body(i, _):
            emit(i, load1(revbuf, lane_n, cnt_b - 1 - i))
            return 0

        jax.lax.fori_loop(0, cnt_b, flip_body, 0)

        # forward walk to a sink along heaviest out-edges
        def fcond(c):
            u, cnt, more = c
            return more & (cnt < N)

        def fbody(c):
            u, cnt, _ = c
            ew = jnp.where(in_src[:] == u, in_w[:], NEG)      # (E, N)
            wv = jnp.max(ew, axis=0, keepdims=True)           # (1, N)
            any_out = jnp.max(wv) > NEG
            wmax = jnp.max(wv)
            scorev = score[:]
            cand_s = jnp.where(wv == wmax, scorev, NEG)
            smax = jnp.max(cand_s)
            v = jnp.min(jnp.where(cand_s == smax, lane_n, N)).astype(
                jnp.int32)

            @pl.when(any_out)
            def _():
                emit(cnt, v)

            return (jnp.where(any_out, v, u), cnt + jnp.where(any_out, 1, 0),
                    any_out)

        _, cnt, _ = jax.lax.while_loop(
            fcond, fbody, (summit, cnt_b, jnp.bool_(True)))

        cons_len_ref[0, 0, 0] = cnt
        failed_ref[0, 0, 0] = failed.astype(jnp.int32)
        n_nodes_ref[0, 0, 0] = n

    def make(batch: int):
        # Mosaic block rules: last two block dims must tile (8,128) or equal
        # the array dims. A leading singleton makes the grid dim the only
        # blocked dim, so per-program blocks satisfy the rule in both SMEM
        # (scalars) and VMEM (rows); SMEM residency stays O(D), not O(B*D).
        smem3 = lambda w: pl.BlockSpec((1, 1, w), lambda b: (b, 0, 0),
                                       memory_space=pltpu.SMEM)
        vmem3w = lambda w: pl.BlockSpec((1, 1, w), lambda b: (b, 0, 0),
                                        memory_space=pltpu.VMEM)
        vmem3 = lambda: pl.BlockSpec((1, D, L), lambda b: (b, 0, 0),
                                     memory_space=pltpu.VMEM)

        return pl.pallas_call(
            kernel,
            grid=(batch,),
            in_specs=[smem3(1), smem3(1), smem3(D), smem3(D), smem3(D),
                      vmem3w(BB), vmem3w(BB), vmem3(), vmem3()],
            out_specs=[vmem3w(N), vmem3w(N), smem3(1), smem3(1), smem3(1)],
            out_shape=[
                jax.ShapeDtypeStruct((batch, 1, N), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1, N), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1, 1), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((N + 1, LP), jnp.int32),    # H
                # i32, not i8: packed i8 sublanes can't be dynamically
                # row-indexed on Mosaic (offset must be a multiple of 4)
                pltpu.VMEM((N + 1, LP), jnp.int32),    # MV (move records)
                pltpu.VMEM((1, N), jnp.int32),         # base
                pltpu.VMEM((1, N), jnp.float32),       # key
                pltpu.VMEM((1, N), jnp.int32),         # cov
                pltpu.VMEM((1, N), jnp.int32),         # order
                pltpu.VMEM((E, N), jnp.int32),         # in_src
                pltpu.VMEM((E, N), jnp.int32),         # in_w
                pltpu.VMEM((1, N), jnp.int32),         # in_cnt
                pltpu.VMEM((1, L), jnp.int32),         # pos_node
                pltpu.VMEM((1, L), jnp.float32),       # nkey
                pltpu.VMEM((1, L), jnp.int32),         # runrem
                pltpu.VMEM((1, N), jnp.int32),         # score
                pltpu.VMEM((1, N), jnp.int32),         # pred
                pltpu.VMEM((1, N), jnp.int32),         # revbuf
                pltpu.VMEM((1, N), jnp.int32),         # has_out
                pltpu.VMEM((1, LP), jnp.int32),        # seq_scr
                pltpu.VMEM((1, LP), jnp.int32),        # w_scr
            ],
            interpret=interpret,
        )

    @functools.lru_cache(maxsize=8)
    def jitted(batch: int):
        call = make(batch)

        def fn(bb_len, n_layers, lens, begins, ends, bb, bbw, seqs, ws):
            cb, cc, cl, fl, nn = call(
                bb_len.reshape(batch, 1, 1), n_layers.reshape(batch, 1, 1),
                lens.reshape(batch, 1, D), begins.reshape(batch, 1, D),
                ends.reshape(batch, 1, D),
                bb.reshape(batch, 1, BB), bbw.reshape(batch, 1, BB),
                seqs, ws)
            return (cb.reshape(batch, N), cc.reshape(batch, N),
                    cl.reshape(batch, 1), fl.reshape(batch, 1),
                    nn.reshape(batch, 1))

        return jax.jit(fn)

    return jitted
