"""Lane-lockstep fused Pallas POA kernel (v3).

Same window-consensus semantics as the host oracle (rt_poa.cpp) and the v2
kernel (poa_pallas.py), re-laid for VPU throughput. The v2 kernel runs ONE
window per grid step; its DP inner loop is a serial dependency chain of
~50 single-vreg ops at ~40 cycles/op of latency (measured: dp_cost_probe,
docs/benchmarks.md), so the VPU idles most of the time. This kernel runs
EIGHT windows per grid step in lock-step, one per sublane:

  * j-rows: (JC, 8, 128) — window g in sublane g, DP column j at
    [j // 128, g, j % 128]. Every row op serves all 8 windows at once,
    and lane-only prefix scans replace the v2 layout's cross-sublane
    carries.
  * The graph lives in RANK SPACE: arrays (NC, 8, 128) keyed by
    topological rank (= column-key order), with in-edges stored as rank
    DISTANCES (rk_delta). Node insertion is a lane shift; there are no
    node ids at all. Rank distance is bounded in practice: measured max
    34 on the lambda dataset and 16 on the synthetic ONT bench over ~12M
    edges (RT_POA_STATS histograms), so distances are capped at DMAX=64
    and a window with a longer in-subgraph edge fails to the host path
    (the same degradation lattice as every other device limit).
  * H rows live in a 128-row rank-keyed VMEM ring (the distance cap makes
    older rows dead); completed 64-row chunks are DMA'd to an HBM spill
    buffer under the compute.
  * No move matrix. The traceback re-derives moves from H values exactly
    like the pure-JAX twin (poa.py _traceback, differentially verified
    against the host), walking rank blocks top-down with the spill buffer
    streamed back through the same ring; insertion runs are applied as
    one masked vector op per run instead of one step per base.

Reference parity: the per-window program mirrors rt_poa.cpp /
src/window.cpp (see poa.py's docstring for the layer-by-layer map); the
batch orchestration mirrors the reference's cudapoa batch
(/root/reference/src/cuda/cudabatch.cpp).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .kernel_cache import device_keyed_cache
from .poa import PoaConfig

NEG = -(1 << 28)
G = 8            # windows per kernel program (the sublane dimension)
RING = 128       # H ring rows (must be 2 * BLK)
BLK = 64         # HBM spill chunk = traceback block
DMAX = 64        # max predecessor rank distance the device accepts
KEY_INF = 3.0e38
BIG = 1 << 20    # "no slot" sentinel inside packed slot*256+delta minima
WNONE = BIG * 512


def _round_up(x, m):
    return (x + m - 1) // m * m


@device_keyed_cache(maxsize=32)
def build_lockstep_poa_kernel(cfg: PoaConfig, interpret: bool = False,
                              colstep: bool = True, band: bool = False):
    N = cfg.max_nodes
    L = cfg.max_len
    BB = cfg.max_backbone
    E = cfg.max_edges
    D = cfg.depth
    assert N % 128 == 0 and BB <= N
    NC = N // 128                       # node/rank lane-chunks
    JL = _round_up(L + 1, 128)
    JC = JL // 128                      # j lane-chunks
    M = int(cfg.match)
    X = int(cfg.mismatch)
    GP = int(cfg.gap)

    # The banded build (band=True, RACON_TPU_BAND) adds one SMEM input
    # (wband: per-window half-band width, 0 = flat semantics through the
    # same compiled kernel) and one SMEM output (band_hit: the composite
    # verify signal — see poa_pallas.py / ops/band.py).  Every band op
    # is gated on the Python-level `band` flag so the flat build's jaxpr
    # is unchanged.
    def kernel(*refs):
        if band:
            (bb_len_s, n_layers_s, lens_s, begins_s, ends_s,
             bb_ref, bbw_ref, seqs_hbm, ws_hbm, wband_s,
             cons_base_ref, cons_cov_ref, cl_s, fl_s, nn_s, bh_s, hbm_H,
             Hring, H0, rk_base, rk_key, rk_cov, rk_cnt, rk_delta, rk_ew,
             rk_dmax, esc, score, spred, revbuf, nkey, runrem,
             seq_scr, w_scr, dma_sem, flush_sem, tb_sem) = refs
        else:
            (bb_len_s, n_layers_s, lens_s, begins_s, ends_s,
             bb_ref, bbw_ref, seqs_hbm, ws_hbm,
             cons_base_ref, cons_cov_ref, cl_s, fl_s, nn_s, hbm_H,
             Hring, H0, rk_base, rk_key, rk_cov, rk_cnt, rk_delta, rk_ew,
             rk_dmax, esc, score, spred, revbuf, nkey, runrem,
             seq_scr, w_scr, dma_sem, flush_sem, tb_sem) = refs
        b_prog = pl.program_id(0)

        lane_n = jax.lax.broadcasted_iota(jnp.int32, (NC, G, 128), 2)
        chunk_n = jax.lax.broadcasted_iota(jnp.int32, (NC, G, 128), 0)
        rr = chunk_n * 128 + lane_n                    # global rank index
        lane_j = jax.lax.broadcasted_iota(jnp.int32, (JC, G, 128), 2)
        chunk_j = jax.lax.broadcasted_iota(jnp.int32, (JC, G, 128), 0)
        jj = chunk_j * 128 + lane_j                    # global j index
        lane1 = jax.lax.broadcasted_iota(jnp.int32, (G, 128), 1)
        giota = jax.lax.broadcasted_iota(jnp.int32, (1, G, 1), 1)
        gvec = jj * GP

        # ---- helpers ----------------------------------------------------
        # (1, G, 1) per-window scalar-vectors are the working currency;
        # extracts are masked sums (zero elsewhere), so indices must be in
        # range — callers clamp.

        def glob(x):
            return rr if x.shape[-3] == NC else jj

        def lanes_of(x):
            return lane_n if x.shape[-3] == NC else lane_j

        def _lane_extract(c, idx):
            """(G,128) row -> (1,G,1) value at lane idx (masked sum)."""
            m = lane1 == (idx % 128)
            return jnp.sum(jnp.where(m, c, jnp.zeros_like(c)), axis=-1,
                           keepdims=True)[None]

        def exr(ref, r):
            """ref (C,G,128) at global index r (shared scalar) -> (1,G,1).

            Reads THROUGH the ref with pl.ds — dynamic_slice on a loaded
            value does not lower to Mosaic (caught by the jax.export
            cross-lowering check; interpret mode accepts it silently).
            One (1,G,128) VMEM load + a lane mask, not an O(N) masked
            reduction over every chunk."""
            return _lane_extract(ref[pl.ds(r // 128, 1)][0], r)

        def exs(ref, slot, j):
            """(2,JC,G,128) double-buffer ref at (slot, global j)."""
            return _lane_extract(
                ref[pl.ds(slot, 1), pl.ds(j // 128, 1)][0, 0], j)

        def ex_v(val, rv):
            """val (C,G,128) at per-window indices rv (1,G,1)."""
            m = glob(val) == rv
            return jnp.sum(jnp.where(m, val, jnp.zeros_like(val)),
                           axis=(0, 2), keepdims=True)[:, :, 0:1]

        def rmw(ref, r, v, active):
            """ref value at shared scalar index r <- v where active."""
            c = ref[pl.ds(r // 128, 1)]
            m = (lane1 == (r % 128))[None] & active
            ref[pl.ds(r // 128, 1)] = jnp.where(m, v, c)

        def rmw_v(ref, rv, v, active):
            """masked write at per-window global indices rv (1,G,1)."""
            ref[...] = jnp.where((glob(ref[...]) == rv) & active, v,
                                 ref[...])

        def shift_right(x, fill):
            """lane shift: out[i] = x[i-1], out[0] = fill (global index)."""
            ln = pltpu.roll(x, 1, 2)
            carry = pltpu.roll(ln, 1, 0)
            y = jnp.where(lanes_of(x) == 0, carry, ln)
            return jnp.where(glob(x) == 0, fill, y)

        def shift_left_dyn(x, d, fill):
            """out[i] = x[i + d] (dynamic scalar d >= 0), fill past the
            end; crosses lane chunks."""
            dl = d % 128
            dc = d // 128
            xs = pltpu.roll(x, -dl, 2)
            xc = pltpu.roll(xs, -dc, 0)
            xc2 = pltpu.roll(xs, -(dc + 1), 0)
            y = jnp.where(lanes_of(x) < 128 - dl, xc, xc2)
            top = x.shape[-3] * 128
            return jnp.where(glob(x) + d < top, y, fill)

        def cummaxj(x):
            """prefix max over the global j index of a (JC,G,128) array:
            radix-4 within lanes, then an exclusive chunk prefix."""
            w = 1
            while w < 128:
                for k in (1, 2, 3):
                    if k * w < 128:
                        x = jnp.maximum(
                            x, jnp.where(lane_j >= k * w,
                                         pltpu.roll(x, k * w, 2), NEG))
                w *= 4
            tot = jnp.max(x, axis=2, keepdims=True)
            p = jnp.broadcast_to(tot, (JC, G, 128))
            acc = jnp.full((JC, G, 128), NEG, jnp.int32)
            for k in range(1, JC):
                acc = jnp.maximum(
                    acc, jnp.where(chunk_j >= k, pltpu.roll(p, k, 0), NEG))
            return jnp.maximum(x, acc)

        def scalar_of(v, g):
            return jnp.sum(jnp.where(giota == g, v, jnp.zeros_like(v)))

        def svec(read):
            """(1,G,1) vector from G SMEM scalars (SMEM is scalar-only)."""
            v = jnp.zeros((1, G, 1), jnp.int32)
            for g in range(G):
                v = jnp.where(giota == g, read(g), v)
            return v

        bb_len = svec(lambda g: bb_len_s[0, g])
        n_layers = svec(lambda g: n_layers_s[0, g])
        max_layers = jnp.max(n_layers)
        if band:
            wbv = svec(lambda g: wband_s[0, g])       # (1,G,1) half-band

        # ---- graph init from the backbone chain ------------------------
        # (parity: rt_poa.cpp add_alignment, empty-alignment branch)
        used0 = rr < bb_len
        rk_base[...] = jnp.where(used0, bb_ref[0], -1)
        rk_key[...] = jnp.where(used0, rr.astype(jnp.float32), KEY_INF)
        rk_cov[...] = jnp.where(used0, 1, 0)
        chain = (rr > 0) & used0
        rk_cnt[...] = jnp.where(chain, 1, 0)
        rk_delta[...] = jnp.zeros((E, NC, G, 128), jnp.int32)
        rk_delta[0:1] = jnp.where(chain, 1, 0)[None]
        bbw = bbw_ref[0]
        rk_ew[...] = jnp.zeros((E, NC, G, 128), jnp.int32)
        rk_ew[0:1] = jnp.where(chain, shift_right(bbw, 0) + bbw, 0)[None]
        H0[...] = gvec

        def start_copy(li, slot):
            pltpu.make_async_copy(seqs_hbm.at[b_prog, li],
                                  seq_scr.at[slot],
                                  dma_sem.at[slot, 0]).start()
            pltpu.make_async_copy(ws_hbm.at[b_prog, li],
                                  w_scr.at[slot],
                                  dma_sem.at[slot, 1]).start()

        def wait_copy(li, slot):
            pltpu.make_async_copy(seqs_hbm.at[b_prog, li],
                                  seq_scr.at[slot],
                                  dma_sem.at[slot, 0]).wait()
            pltpu.make_async_copy(ws_hbm.at[b_prog, li],
                                  w_scr.at[slot],
                                  dma_sem.at[slot, 1]).wait()

        def flush_chunk(c):
            pltpu.make_async_copy(
                Hring.at[pl.ds((c * BLK) % RING, BLK)],
                hbm_H.at[b_prog, pl.ds(c * BLK, BLK)],
                flush_sem.at[c % 2]).start()

        def flush_wait(c):
            pltpu.make_async_copy(
                Hring.at[pl.ds((c * BLK) % RING, BLK)],
                hbm_H.at[b_prog, pl.ds(c * BLK, BLK)],
                flush_sem.at[c % 2]).wait()

        # ================= one layer =====================================
        def do_layer(li, slot, carry):
            if band:
                n, failed, hit = carry                 # (1,G,1) i32
            else:
                n, failed = carry                      # (1,G,1) i32
            Ln = svec(lambda g: lens_s[0, g, li])
            begin = svec(lambda g: begins_s[0, g, li])
            end = svec(lambda g: ends_s[0, g, li])
            lact = (li < n_layers) & (Ln > 0) & (failed == 0)

            # full-graph rule (reference: src/window.cpp:88-97)
            offset = (0.01 * bb_len.astype(jnp.float32)).astype(jnp.int32)
            full = (begin < offset) & (end > bb_len - offset)
            lo = jnp.where(full, jnp.float32(-KEY_INF),
                           begin.astype(jnp.float32))
            hi = jnp.where(full, jnp.float32(KEY_INF),
                           end.astype(jnp.float32))

            keys = rk_key[...]
            r_lo = jnp.sum(jnp.where(keys < lo, 1, 0), axis=(0, 2),
                           keepdims=True)[:, :, 0:1]
            r_hi = jnp.minimum(
                jnp.sum(jnp.where(keys <= hi, 1, 0), axis=(0, 2),
                        keepdims=True)[:, :, 0:1], n)
            r_start = jnp.min(jnp.where(lact, r_lo, N))
            r_end = jnp.max(jnp.where(lact, r_hi, 0))

            seqv = seq_scr[pl.ds(slot, 1)][0]          # (JC, G, 128)
            seqm1 = shift_right(seqv, 255)             # lane j: seq[j-1]
            rk_dmax[...] = jnp.max(rk_delta[...], axis=0)

            # layer-invariant snapshots (the graph does not change during
            # DP + traceback; Mosaic keeps these as VMEM-backed values)
            dmax_v = rk_dmax[...]
            delta_v = [rk_delta[e] for e in range(E)]
            H0v = H0[...]

            # distance cap: an IN-SUBGRAPH edge beyond DMAX fails the
            # window (its H row is evicted from the ring; the host path
            # takes over — the rank-distance histograms say this is rare)
            in_sub = (rr >= r_lo) & (rr < r_hi)
            far = jnp.zeros((1, G, 1), jnp.int32)
            for e in range(E):
                bad = ((delta_v[e] > DMAX) & in_sub &
                       ((rr - delta_v[e]) >= r_lo))
                far = far | jnp.any(bad, axis=(0, 2),
                                    keepdims=True)[:, :, 0:1].astype(
                    jnp.int32)
            failed = failed | jnp.where(lact & (far > 0), 1, 0)

            esc[...] = jnp.full((NC, G, 128), NEG, jnp.int32)

            # ---- DP over ranks in lock-step -----------------------------
            def dp_body(r, _):
                act = lact & (r >= r_lo) & (r < r_hi)
                dmax_r = jnp.minimum(jnp.max(exr(rk_dmax, r)), DMAX)
                dmax_r = jnp.minimum(dmax_r, r)
                ds = []
                for e in range(E):
                    d_e = exr(rk_delta.at[e], r)
                    valid = ((d_e > 0) & (d_e <= DMAX) &
                             (r - d_e >= r_lo) & act)
                    ds.append(jnp.where(valid, d_e, 0))
                any_valid = ds[0] > 0
                for e in range(1, E):
                    any_valid = any_valid | (ds[e] > 0)

                def delta_scan(d, P):
                    prow = Hring[pl.ds((r - d) % RING, 1)][0]
                    has = ds[0] == d
                    for e in range(1, E):
                        has = has | (ds[e] == d)
                    return jnp.where(has, jnp.maximum(P, prow), P)

                P0 = jnp.full((JC, G, 128), NEG, jnp.int32)
                P = jax.lax.fori_loop(1, dmax_r + 1, delta_scan, P0)
                P = jnp.where(any_valid, P, H0v)

                ub = exr(rk_base, r)
                scvec = jnp.where(seqm1 == ub, M, X)
                diag = shift_right(P, NEG) + scvec
                up = P + GP
                V = jnp.maximum(diag, up)
                row = cummaxj(V - gvec) + gvec
                if band:
                    # diagonal band around the rank's backbone offset:
                    # cells past the per-window half-band are masked to
                    # NEG before the ring write, so later ranks, the end
                    # score and the traceback all see banded values
                    cr = (exr(rk_key, r) + 0.5).astype(jnp.int32) - begin
                    row = jnp.where((wbv > 0) & (jnp.abs(jj - cr) > wbv),
                                    NEG, row)
                Hring[pl.ds(r % RING, 1)] = row[None]
                rmw(esc, r, ex_v(row, Ln), act)

                @pl.when((r + 1) % BLK == 0)
                def _():
                    flush_chunk((r + 1) // BLK - 1)
                    # the chunk whose ring slots ranks [r+1, r+1+BLK)
                    # will overwrite must have landed in HBM
                    @pl.when(r + 1 >= RING)
                    def _():
                        flush_wait((r + 1 - RING) // BLK)
                return 0

            rs64 = (r_start // BLK) * BLK
            if colstep:
                # Rank-pair stepping (the lockstep variant of column
                # compression, RACON_TPU_POA_COLSTEP): the 8 lanes hold
                # unrelated windows so per-column pairing cannot line up
                # across the sublane dimension — instead every serial
                # iteration retires TWO consecutive ranks, halving the
                # trip count. Ranks still execute strictly in order
                # inside the body (rank r's ring row is written before
                # rank r+1's delta scan reads it at d == 1), so the
                # result is byte-identical to the serial loop. The flush
                # schedule is untouched: rs64 and BLK are even, so the
                # (r+1) % BLK == 0 trigger only ever fires on the second
                # rank of a pair.
                def pair_body(p, _):
                    r = rs64 + 2 * p
                    dp_body(r, 0)

                    @pl.when(r + 1 < r_end)
                    def _():
                        dp_body(r + 1, 0)

                    return 0

                jax.lax.fori_loop(0, (r_end - rs64 + 1) // 2, pair_body, 0)
            else:
                jax.lax.fori_loop(rs64, r_end, dp_body, 0)

            @pl.when(r_end % BLK != 0)
            def _():
                flush_chunk(r_end // BLK)

            n_chunks = (r_end + BLK - 1) // BLK - rs64 // BLK

            @pl.when(n_chunks >= 1)
            def _():
                flush_wait(rs64 // BLK + n_chunks - 1)

            @pl.when(n_chunks >= 2)
            def _():
                flush_wait(rs64 // BLK + n_chunks - 2)

            # ---- end-node selection -------------------------------------
            # rank r is an end node iff no in-subgraph node has an edge
            # from it (v2 fused this into the DP; here one masked dynamic
            # shift per distance serves every rank at once)
            dmax_all = jnp.minimum(
                jnp.max(jnp.where(in_sub, dmax_v, 0)), DMAX)

            def out_body(d, hm):
                has_d = delta_v[0] == d
                for e in range(1, E):
                    has_d = has_d | (delta_v[e] == d)
                src_ok = has_d & in_sub & ((rr - d) >= r_lo)
                return hm | shift_left_dyn(src_ok.astype(jnp.int32), d, 0)

            has_out = jax.lax.fori_loop(
                1, dmax_all + 1, out_body,
                jnp.zeros((NC, G, 128), jnp.int32))
            endok = in_sub & (has_out == 0)

            escv = jnp.where(endok, esc[...], NEG)
            best_s = jnp.max(escv, axis=(0, 2), keepdims=True)[:, :, 0:1]
            best_r = jnp.min(jnp.where((escv == best_s) & endok, rr, N),
                             axis=(0, 2), keepdims=True)[:, :, 0:1]
            has_end = best_s > NEG
            failed = failed | jnp.where(lact & ~has_end, 1, 0)
            if band:
                # score-deficit verify (host mirror: band.poa_deficit_bound)
                deficit_bad = (M * Ln - best_s >
                               2 * (-GP) * jnp.maximum(wbv // 2, 1))
                hit = hit | jnp.where(lact & (wbv > 0) & deficit_bad, 1, 0)

            # ---- traceback: block-descending re-derivation --------------
            walking = lact & has_end & (failed == 0)
            cur = jnp.where(walking, best_r, -1)
            jcur = jnp.where(walking, Ln, 0)
            nk0 = jnp.full((1, G, 1), KEY_INF, jnp.float32)
            run0 = jnp.zeros((1, G, 1), jnp.int32)
            done0 = ~walking
            b_top = jnp.max(jnp.where(done0, 0, cur)) // BLK

            def tb_load(b, half):
                pltpu.make_async_copy(
                    hbm_H.at[b_prog, pl.ds(b * BLK, BLK)],
                    Hring.at[pl.ds(half * BLK, BLK)],
                    tb_sem.at[half]).start()

            def tb_wait(b, half):
                pltpu.make_async_copy(
                    hbm_H.at[b_prog, pl.ds(b * BLK, BLK)],
                    Hring.at[pl.ds(half * BLK, BLK)],
                    tb_sem.at[half]).wait()

            def ring_row(p):
                """resident spill row for rank p (blocks b and b-1)."""
                return Hring[pl.ds(((p // BLK) % 2) * BLK + p % BLK, 1)][0]

            tb_load(b_top, b_top % 2)
            tb_wait(b_top, b_top % 2)

            @pl.when(b_top >= 1)
            def _():
                tb_load(b_top - 1, (b_top - 1) % 2)

            def tb_rank_work(r, c):
                cur, jcur, nk, run, done, failed = c[:6]
                here = ~done & (cur == r)
                row = ring_row(r)
                ub = exr(rk_base, r)
                scv = jnp.where(seqm1 == ub, M, X)
                ds = []
                for e in range(E):
                    d_e = exr(rk_delta.at[e], r)
                    valid = (d_e > 0) & (d_e <= DMAX) & (r - d_e >= r_lo)
                    ds.append(jnp.where(valid, d_e, 0))
                any_v = ds[0] > 0
                for e in range(1, E):
                    any_v = any_v | (ds[e] > 0)
                dmax_r = jnp.minimum(jnp.max(exr(rk_dmax, r)), DMAX)
                dmax_r = jnp.minimum(dmax_r, r)

                # min over (slot, delta) packed as slot*256+delta: the
                # winning predecessor is the FIRST slot whose row explains
                # the H value (host tie-break: edge insertion order)
                def mscan(d, c2):
                    wdiag, wup = c2
                    prow = ring_row(r - d)
                    s_of_d = jnp.full((1, G, 1), BIG, jnp.int32)
                    for e in range(E - 1, -1, -1):
                        s_of_d = jnp.where(ds[e] == d, e, s_of_d)
                    has = s_of_d < BIG
                    pk = s_of_d * 256 + d
                    dm = has & (shift_right(prow, NEG) + scv == row)
                    um = has & (prow + GP == row)
                    wdiag = jnp.minimum(wdiag, jnp.where(dm, pk, WNONE))
                    wup = jnp.minimum(wup, jnp.where(um, pk, WNONE))
                    return (wdiag, wup)

                W0 = jnp.full((JC, G, 128), WNONE, jnp.int32)
                wdiag, wup = jax.lax.fori_loop(1, dmax_r + 1, mscan,
                                               (W0, W0))
                vdiag = ~any_v & (shift_right(H0v, NEG) + scv == row)
                vup = ~any_v & (H0v + GP == row)
                diag_ok = (wdiag < WNONE) | vdiag
                ok = diag_ok | (wup < WNONE) | vup

                # insertion run: walk left to the nearest explained cell
                okm = ok & (jj <= jcur) & here
                j_stop = jnp.max(jnp.where(okm, jj, -1), axis=(0, 2),
                                 keepdims=True)[:, :, 0:1]
                stuck = here & (j_stop < 0)
                failed = failed | jnp.where(stuck, 1, 0)
                done = done | stuck
                act = here & ~stuck
                j_stop = jnp.maximum(j_stop, 0)
                if band:
                    # boundary touch: a column visited at this rank came
                    # within one cell of the band edge (the run's extreme
                    # columns are j_stop and the entry jcur)
                    cr_tb = (exr(rk_key, r) + 0.5).astype(jnp.int32) - begin
                    near = act & (wbv > 0) & (
                        (jnp.abs(j_stop - cr_tb) >= wbv - 1) |
                        (jnp.abs(jcur - cr_tb) >= wbv - 1))
                    hit_tb = c[6] | jnp.where(near, 1, 0)

                lanes = (jj >= j_stop) & (jj < jcur) & act
                runrem[...] = jnp.where(lanes, run + (jcur - jj),
                                        runrem[...])
                nkey[...] = jnp.where(lanes, nk, nkey[...])
                run = jnp.where(act, run + (jcur - j_stop), run)

                # the descending move at j_stop (diag > up priority)
                take_diag = act & (ex_v(
                    jnp.where(diag_ok, 1, 0), j_stop) == 1)
                wd = ex_v(jnp.where(wdiag == WNONE, 0, wdiag), j_stop)
                wd_virt = ex_v(jnp.where(wdiag == WNONE, 1, 0),
                               j_stop) == 1
                wu = ex_v(jnp.where(wup == WNONE, 0, wup), j_stop)
                wu_virt = ex_v(jnp.where(wup == WNONE, 1, 0), j_stop) == 1
                take_up = act & ~take_diag

                kr = exr(rk_key, r)
                nk = jnp.where(take_diag, kr, nk)
                mlane = (jj == j_stop - 1) & take_diag
                runrem[...] = jnp.where(mlane, 0, runrem[...])
                nkey[...] = jnp.where(mlane, kr, nkey[...])
                run = jnp.where(take_diag, 0, run)
                jcur = jnp.where(take_diag, j_stop - 1,
                                 jnp.where(take_up, j_stop, jcur))

                new_cur = jnp.where(
                    take_diag,
                    jnp.where(wd_virt, -1, r - wd % 256),
                    jnp.where(wu_virt, -1, r - wu % 256))
                cur = jnp.where(act, new_cur, cur)

                # a window that reached the virtual row finishes its
                # remaining insertions in one masked write
                at_virt = act & (cur == -1)
                vl = (jj < jcur) & at_virt
                runrem[...] = jnp.where(vl, run + (jcur - jj), runrem[...])
                nkey[...] = jnp.where(vl, nk, nkey[...])
                done = done | at_virt
                out = (cur, jcur, nk, run, done, failed)
                if band:
                    out = out + (hit_tb,)
                return out

            def tb_rank(i, c):
                b = c[0]
                r = b * BLK + (BLK - 1 - i)
                cc = c[1:]
                here_any = jnp.any(~cc[4] & (cc[0] == r))
                cc2 = jax.lax.cond(here_any,
                                   lambda cc: tb_rank_work(r, cc),
                                   lambda cc: cc, cc)
                return (b,) + cc2

            def tb_block(i, c):
                b = b_top - i

                @pl.when(b >= 1)
                def _():
                    tb_wait(b - 1, (b - 1) % 2)

                c2 = jax.lax.fori_loop(0, BLK, tb_rank, (b,) + c)[1:]

                @pl.when(b >= 2)
                def _():
                    tb_load(b - 2, b % 2)
                return c2

            if band:
                cur, jcur, nk, run, done, failed, hit = jax.lax.fori_loop(
                    0, b_top + 1, tb_block,
                    (cur, jcur, nk0, run0, done0, failed, hit))
            else:
                cur, jcur, nk, run, done, failed = jax.lax.fori_loop(
                    0, b_top + 1, tb_block,
                    (cur, jcur, nk0, run0, done0, failed))
            failed = failed | jnp.where(~done & lact, 1, 0)

            # ---- graph update (parity: rt_poa.cpp add_alignment) --------
            maxL = jnp.max(jnp.where(lact & (failed == 0), Ln, 0))

            def upd_body(j, c):
                n, failed, prev_r, prev_key, prev_w = c
                act = lact & (j < Ln) & (failed == 0)
                b = exs(seq_scr, slot, j)
                wj = exs(w_scr, slot, j)
                run_j = exr(runrem, j)
                nk_j = exr(nkey, j)
                is_match = (run_j == 0) & act
                k0 = nk_j

                keys = rk_key[...]
                basev = rk_base[...]
                cand = (keys == k0) & (basev == b)
                has = jnp.any(cand, axis=(0, 2),
                              keepdims=True)[:, :, 0:1] & is_match
                found = jnp.min(jnp.where(cand, rr, N), axis=(0, 2),
                                keepdims=True)[:, :, 0:1]

                runf = run_j.astype(jnp.float32)
                hi2 = jnp.where(nk_j < KEY_INF, nk_j, prev_key + 1.0)
                lo2 = jnp.where(prev_r >= 0, prev_key, hi2 - runf - 1.0)
                k_new = lo2 + (hi2 - lo2) / (runf + 1.0)
                key_val = jnp.where(is_match, k0, k_new)

                need_new = act & ~has
                overflow = need_new & (n >= N)
                do_new = need_new & ~overflow
                p_ins = jnp.sum(jnp.where(keys <= key_val, 1, 0),
                                axis=(0, 2), keepdims=True)[:, :, 0:1]
                nid = jnp.where(has, found, jnp.minimum(p_ins, N - 1))

                @pl.when(jnp.any(do_new))
                def _():
                    sh = (rr >= p_ins) & do_new
                    v = rk_base[...]
                    rk_base[...] = jnp.where(sh, shift_right(v, -1), v)
                    v = rk_cov[...]
                    rk_cov[...] = jnp.where(sh, shift_right(v, 0), v)
                    v = rk_cnt[...]
                    rk_cnt[...] = jnp.where(sh, shift_right(v, 0), v)
                    vk = rk_key[...]
                    rk_key[...] = jnp.where(sh, shift_right(vk, KEY_INF),
                                            vk)
                    for e in range(E):
                        vd = rk_delta[e]
                        sd = shift_right(vd, 0)
                        # an edge whose source sits below the insertion
                        # point now spans it: distance grows by one
                        sd = sd + jnp.where(
                            (sd > 0) & (rr - 1 - sd < p_ins), 1, 0)
                        rk_delta[e] = jnp.where(sh, sd, vd)
                        vw = rk_ew[e]
                        rk_ew[e] = jnp.where(sh, shift_right(vw, 0), vw)
                    rmw_v(rk_base, p_ins, b, do_new)
                    rmw_v(rk_key, p_ins, key_val, do_new)
                    rmw_v(rk_cov, p_ins, 0, do_new)
                    rmw_v(rk_cnt, p_ins, 0, do_new)
                    # zero the inserted row's edge slots through the ref
                    # (a loaded slice is immutable; write like eslot_write)
                    new_row = (rr == p_ins) & do_new
                    for e in range(E):
                        vd2 = rk_delta[pl.ds(e, 1)][0]
                        rk_delta[pl.ds(e, 1)] = jnp.where(
                            new_row, 0, vd2)[None]
                        vw2 = rk_ew[pl.ds(e, 1)][0]
                        rk_ew[pl.ds(e, 1)] = jnp.where(
                            new_row, 0, vw2)[None]

                touch = act & ~overflow
                rmw_v(rk_cov, nid, ex_v(rk_cov[...], nid) + 1, touch)
                n = n + jnp.where(do_new, 1, 0)
                failed = failed | jnp.where(overflow, 1, 0)

                # edge prev -> nid with weight w[j-1] + w[j]
                prev_r = prev_r + jnp.where(do_new & (prev_r >= p_ins),
                                            1, 0)
                has_prev = touch & (prev_r >= 0)
                d_tgt = nid - prev_r
                cntv = ex_v(rk_cnt[...], nid)
                cnt_max = jnp.max(jnp.where(has_prev, cntv, 0))

                def same_scan(e, s):
                    de = ex_v(rk_delta[pl.ds(e, 1)][0], nid)
                    return jnp.where((s < 0) & (e < cntv) & (de == d_tgt),
                                     e, s)

                same = jax.lax.fori_loop(
                    0, cnt_max, same_scan,
                    jnp.full((1, G, 1), -1, jnp.int32))
                ew = prev_w + wj
                add_new = has_prev & (same < 0) & (cntv < E)

                def eslot_write(e, _):
                    m_same = has_prev & (same == e)
                    m_new = add_new & (cntv == e)
                    roww = rk_ew[pl.ds(e, 1)][0]
                    rk_ew[pl.ds(e, 1)] = jnp.where(
                        (rr == nid) & (m_same | m_new),
                        jnp.where(m_same, roww + ew, ew), roww)[None]
                    rowd = rk_delta[pl.ds(e, 1)][0]
                    rk_delta[pl.ds(e, 1)] = jnp.where(
                        (rr == nid) & m_new, d_tgt, rowd)[None]
                    return 0

                slot_hi = jnp.maximum(
                    cnt_max, jnp.max(jnp.where(add_new, cntv + 1, 0)))
                jax.lax.fori_loop(0, slot_hi, eslot_write, 0)
                rmw_v(rk_cnt, nid, cntv + 1, add_new)
                failed = failed | jnp.where(
                    has_prev & (same < 0) & (cntv >= E), 1, 0)

                prev_r = jnp.where(act, nid, prev_r)
                prev_key = jnp.where(act, key_val, prev_key)
                prev_w = jnp.where(act, wj, prev_w)
                return (n, failed, prev_r, prev_key, prev_w)

            n, failed, _, _, _ = jax.lax.fori_loop(
                0, maxL, upd_body,
                (n, failed,
                 jnp.full((1, G, 1), -1, jnp.int32),
                 jnp.full((1, G, 1), -1.0, jnp.float32),
                 jnp.zeros((1, G, 1), jnp.int32)))
            return (n, failed, hit) if band else (n, failed)

        @pl.when(max_layers > 0)
        def _():
            start_copy(0, 0)

        def layer_loop(li, carry):
            slot = jax.lax.rem(li, 2)
            wait_copy(li, slot)

            @pl.when(li + 1 < max_layers)
            def _():
                start_copy(li + 1, jax.lax.rem(li + 1, 2))

            return do_layer(li, slot, carry)

        if band:
            n, failed, hit = jax.lax.fori_loop(
                0, max_layers, layer_loop,
                (bb_len, jnp.zeros((1, G, 1), jnp.int32),
                 jnp.zeros((1, G, 1), jnp.int32)))
        else:
            n, failed = jax.lax.fori_loop(
                0, max_layers, layer_loop,
                (bb_len, jnp.zeros((1, G, 1), jnp.int32)))

        # ================= consensus =====================================
        # (parity: rt_poa.cpp generate_consensus — heaviest bundle)
        score[...] = jnp.zeros((NC, G, 128), jnp.int32)
        spred[...] = jnp.full((NC, G, 128), -1, jnp.int32)
        n_max = jnp.max(n)
        delta_f = [rk_delta[e] for e in range(E)]
        ew_f = [rk_ew[e] for e in range(E)]

        def score_body(r, c):
            best_r, best_s = c
            act = r < n
            cnt_r = exr(rk_cnt, r)
            bw = jnp.full((1, G, 1), NEG, jnp.int32)
            bs = jnp.full((1, G, 1), NEG, jnp.int32)
            bp = jnp.full((1, G, 1), -1, jnp.int32)
            for e in range(E):
                d_e = exr(rk_delta.at[e], r)
                w_e = exr(rk_ew.at[e], r)
                valid = (d_e > 0) & (e < cnt_r)
                s_e = ex_v(score[...], jnp.clip(r - d_e, 0, N - 1))
                better = valid & ((w_e > bw) | ((w_e == bw) & (s_e > bs)))
                bw = jnp.where(better, w_e, bw)
                bs = jnp.where(better, s_e, bs)
                bp = jnp.where(better, r - d_e, bp)
            s = jnp.where(bp >= 0, bw + bs, 0)
            rmw(score, r, s, act)
            rmw(spred, r, bp, act)
            better = act & (s > best_s)
            return (jnp.where(better, r, best_r),
                    jnp.where(better, s, best_s))

        summit, _ = jax.lax.fori_loop(
            0, n_max, score_body,
            (jnp.zeros((1, G, 1), jnp.int32),
             jnp.full((1, G, 1), NEG, jnp.int32)))

        # backward walk to a source (ranks into revbuf)
        def bcond(c):
            u, cnt = c
            return jnp.any((u >= 0) & (cnt < N))

        def bbody(c):
            u, cnt = c
            act = (u >= 0) & (cnt < N)
            rmw_v(revbuf, cnt, u, act)
            pu = ex_v(spred[...], jnp.maximum(u, 0))
            return (jnp.where(act, pu, u),
                    cnt + jnp.where(act, 1, 0))

        _, cnt_b = jax.lax.while_loop(
            bcond, bbody, (summit, jnp.zeros((1, G, 1), jnp.int32)))

        cons_base_ref[0] = jnp.full((NC, G, 128), -1, jnp.int32)
        cons_cov_ref[0] = jnp.zeros((NC, G, 128), jnp.int32)
        base_f = rk_base[...]
        cov_f = rk_cov[...]

        def emit(i, u, act):
            bv = ex_v(base_f, u)
            cv = ex_v(cov_f, u)
            m = (rr == i) & act
            cons_base_ref[0] = jnp.where(m, bv, cons_base_ref[0])
            cons_cov_ref[0] = jnp.where(m, cv, cons_cov_ref[0])

        def flip_body(i, _):
            act = i < cnt_b
            u = ex_v(revbuf[...], jnp.clip(cnt_b - 1 - i, 0, N - 1))
            emit(i, jnp.clip(u, 0, N - 1), act)
            return 0

        jax.lax.fori_loop(0, jnp.max(cnt_b), flip_body, 0)

        # forward walk to a sink along heaviest out-edges
        def fcond(c):
            u, cnt, more = c
            return jnp.any(more)

        def fbody(c):
            u, cnt, more = c
            ew = jnp.full((NC, G, 128), NEG, jnp.int32)
            for e in range(E):
                m = ((delta_f[e] > 0) & (delta_f[e] == rr - u) &
                     (rr < n))
                ew = jnp.maximum(ew, jnp.where(m, ew_f[e], NEG))
            wmax = jnp.max(ew, axis=(0, 2), keepdims=True)[:, :, 0:1]
            any_out = more & (wmax > NEG)
            cand_s = jnp.where(ew == wmax, score[...], NEG)
            smax = jnp.max(cand_s, axis=(0, 2), keepdims=True)[:, :, 0:1]
            v = jnp.min(jnp.where(cand_s == smax, rr, N), axis=(0, 2),
                        keepdims=True)[:, :, 0:1]
            emit(cnt, jnp.clip(v, 0, N - 1), any_out)
            return (jnp.where(any_out, v, u),
                    cnt + jnp.where(any_out, 1, 0), any_out)

        _, cnt_f, _ = jax.lax.while_loop(
            fcond, fbody,
            (summit, cnt_b,
             jnp.broadcast_to(jnp.bool_(True), (1, G, 1))))

        for g in range(G):
            cl_s[0, g] = scalar_of(cnt_f, g)
            fl_s[0, g] = jnp.where(scalar_of(failed, g) > 0, 1, 0)
            nn_s[0, g] = scalar_of(n, g)
            if band:
                bh_s[0, g] = jnp.where(scalar_of(hit, g) > 0, 1, 0)

    def make(batch: int):
        assert batch % G == 0
        nb = batch // G
        smem2 = pl.BlockSpec((1, G), lambda b: (b, 0),
                             memory_space=pltpu.SMEM)
        smem3 = pl.BlockSpec((1, G, D), lambda b: (b, 0, 0),
                             memory_space=pltpu.SMEM)
        vblk = pl.BlockSpec((1, NC, G, 128), lambda b: (b, 0, 0, 0),
                            memory_space=pltpu.VMEM)
        hbm = pl.BlockSpec(memory_space=pl.ANY)

        gshape = jax.ShapeDtypeStruct((nb, G), jnp.int32)
        return pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[smem2, smem2, smem3, smem3, smem3, vblk, vblk,
                      hbm, hbm] + ([smem2] if band else []),
            out_specs=[vblk, vblk, smem2, smem2, smem2] +
                      ([smem2] if band else []) + [hbm],
            out_shape=[
                jax.ShapeDtypeStruct((nb, NC, G, 128), jnp.int32),
                jax.ShapeDtypeStruct((nb, NC, G, 128), jnp.int32),
                gshape, gshape, gshape,
            ] + ([gshape] if band else []) + [
                jax.ShapeDtypeStruct((nb, N, JC, G, 128), jnp.int32),
            ],
            scratch_shapes=[
                pltpu.VMEM((RING, JC, G, 128), jnp.int32),   # Hring
                pltpu.VMEM((JC, G, 128), jnp.int32),         # H0
                pltpu.VMEM((NC, G, 128), jnp.int32),         # rk_base
                pltpu.VMEM((NC, G, 128), jnp.float32),       # rk_key
                pltpu.VMEM((NC, G, 128), jnp.int32),         # rk_cov
                pltpu.VMEM((NC, G, 128), jnp.int32),         # rk_cnt
                pltpu.VMEM((E, NC, G, 128), jnp.int32),      # rk_delta
                pltpu.VMEM((E, NC, G, 128), jnp.int32),      # rk_ew
                pltpu.VMEM((NC, G, 128), jnp.int32),         # rk_dmax
                pltpu.VMEM((NC, G, 128), jnp.int32),         # esc
                pltpu.VMEM((NC, G, 128), jnp.int32),         # score
                pltpu.VMEM((NC, G, 128), jnp.int32),         # spred
                pltpu.VMEM((NC, G, 128), jnp.int32),         # revbuf
                pltpu.VMEM((JC, G, 128), jnp.float32),       # nkey
                pltpu.VMEM((JC, G, 128), jnp.int32),         # runrem
                pltpu.VMEM((2, JC, G, 128), jnp.int32),      # seq_scr
                pltpu.VMEM((2, JC, G, 128), jnp.int32),      # w_scr
                pltpu.SemaphoreType.DMA((2, 2)),             # layer DMA
                pltpu.SemaphoreType.DMA((2,)),               # flush
                pltpu.SemaphoreType.DMA((2,)),               # tb load
            ],
            interpret=interpret,
        )

    @functools.lru_cache(maxsize=8)
    def jitted(batch: int):
        call = make(batch)
        nb = batch // G

        def fn(bb_len, n_layers, lens, begins, ends, bb, bbw, seqs, ws,
               *extra):
            def to_n(x):
                x = jnp.pad(x.reshape(batch, BB), ((0, 0), (0, N - BB)))
                return x.reshape(nb, G, NC, 128).transpose(0, 2, 1, 3)

            seqsJ = jnp.pad(seqs, ((0, 0), (0, 0), (0, JL - L)),
                            constant_values=255)
            wsJ = jnp.pad(ws, ((0, 0), (0, 0), (0, JL - L)))
            seqsJ = seqsJ.reshape(nb, G, D, JC, 128).transpose(
                0, 2, 3, 1, 4)
            wsJ = wsJ.reshape(nb, G, D, JC, 128).transpose(0, 2, 3, 1, 4)

            args = [bb_len.reshape(nb, G), n_layers.reshape(nb, G),
                    lens.reshape(nb, G, D), begins.reshape(nb, G, D),
                    ends.reshape(nb, G, D), to_n(bb), to_n(bbw),
                    seqsJ, wsJ]
            if band:
                args.append(extra[0].reshape(nb, G))
            outs = call(*args)
            cb, cc, cl, fl, nn = outs[:5]
            cb = cb.transpose(0, 2, 1, 3).reshape(batch, N)
            cc = cc.transpose(0, 2, 1, 3).reshape(batch, N)
            res = (cb, cc, cl.reshape(batch, 1), fl.reshape(batch, 1),
                   nn.reshape(batch, 1))
            if band:
                res = res + (outs[5].reshape(batch, 1),)
            return res

        return jax.jit(fn)

    return jitted
