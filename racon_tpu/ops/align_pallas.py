"""Pallas banded global aligner: Hirschberg splitting over distance-only
kernels.

TPU-native replacement for the edlib seam (reference:
/root/reference/src/overlap.cpp:205-224) built for FULL-LENGTH reads. The
moves-matrix design (ops/align.py) needs O(rows x band) memory per pair,
which caps device-eligible pairs far below ONT read lengths; this engine
keeps only O(band) state per kernel program — the classic
divide-and-conquer (Hirschberg) trick:

  * forward kernel: banded unit-cost DP over a row range, returning ONLY
    the final score row (O(band) VMEM);
  * backward kernel: the mirrored recurrence from the bottom edge;
  * the host picks the optimal crossing column at the midpoint row from
    F + B and splits the problem in two — numpy bookkeeping, batched
    kernel launches, ~log2(n/base) rounds;
  * base-case kernel: subproblems of <= BASE_ROWS rows run the full
    moves-matrix DP in VMEM with in-kernel traceback, emitting op codes.

Mosaic constraints honored throughout (no scalar VMEM stores — masked row
RMW; no dynamic-lane scalar loads — masked reductions; 3-D per-program
blocks; i32 everywhere).

Costs are unit (edit distance), matching the reference's edlib NW config.
In-band-only contract as the reference's banded CUDA aligner; pairs whose
optimal path escapes the band are detected (INF at a midpoint) and left to
the host engine.

Multi-device: kernel batches whose size divides the mesh shard over the
1-D `windows` axis (shard_map, leading batch dim, zero collectives) —
the same batch striping as the consensus path and the analogue of the
reference's per-GPU aligner batches
(/root/reference/src/cuda/cudapolisher.cpp:96-114).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import config
from . import band as _band
from .encoding import encode, pack_bases
from .kernel_cache import device_keyed_cache

INF = 1 << 28
BASE_ROWS = 256          # subproblems at or below this row count run the
                         # full traceback kernel
ROW_BUCKETS = (512, 1024, 2048, 4096, 8192, 16384, 32768, 49152)
BANDS = (256, 512, 1024, 2048)


def band_for(n: int, m: int, band_hint: int = 0) -> int:
    """Band bucket: 10% of the larger side (reference auto-band rule,
    src/cuda/cudapolisher.cpp:159-163) plus the diagonal drift."""
    need = max(band_hint, abs(m - n) + max(n, m) // 10 + 2)
    for b in BANDS:
        if need <= b:
            return b
    return 0  # host


def _round_up(x, m):
    return (x + m - 1) // m * m


def _shard_over_mesh(build_local, batch, n_in, n_out):
    """Batch-stripe a kernel build over the partitioner's mesh (same
    no-collective striping as the consensus path; reference analogue:
    per-GPU aligner batches,
    /root/reference/src/cuda/cudapolisher.cpp:96-114).  The partitioner
    owns the gate: RACON_TPU_SHARD, the min-batch floor, sticky
    sharded->single-device demotion state, and divisibility.  None =
    don't shard; caller uses the single-device jit."""
    from ..parallel.partitioner import get_partitioner

    part = get_partitioner()
    if not part.will_shard(batch):
        return None
    return part.shard_build(build_local, batch, n_in, n_out)


def _dispatch_shards(batch: int) -> int:
    """Mesh shards a `batch`-row kernel launch dispatches over — mirrors
    _shard_over_mesh's gate so the shard-size accounting matches what
    the (batch-keyed, topology-keyed) jitted kernel actually does."""
    from ..parallel.partitioner import get_partitioner

    part = get_partitioner()
    m = part.batch_axis_size
    return m if (m > 1 and batch % m == 0
                 and part.will_shard(batch)) else 1


# ---------------------------------------------------------------------------
# distance-only kernels
# ---------------------------------------------------------------------------

def _pack_factor() -> int:
    """Row-pack factor for the Hirschberg kernels: PACK (4) query bases
    per 32-bit word and per serial loop iteration (RACON_TPU_ALIGN_PACK,
    default on), 1 = the one-row-per-step kernels."""
    from .encoding import PACK

    return PACK if config.get_bool("RACON_TPU_ALIGN_PACK") else 1


@device_keyed_cache(maxsize=64)
def _build_edge_kernel(rcap: int, K: int, backward: bool,
                       interpret: bool = False, pack: int = 1):
    """Batched banded DP over up to `rcap` rows; returns the last row.

    Per task (one grid program): query slice q (rcap), target slice t
    (rcap + K), scalars R (rows), S (target span), dmin (local band
    offset). Lane o of a row holds cell (i, j = i + dmin + o); the
    backward kernel mirrors the recurrence (B[i][o] from B[i+1][o],
    B[i+1][o-1]... expressed with opposite shifts).

    pack > 1: the query arrives packed `pack` codes per int32 word
    (encoding.pack_bases; REVERSED for the backward kernel so the word
    index ascends with the loop) and each serial iteration retires
    `pack` DP rows off one scalar word read — the fori_loop trip count
    drops from R to ceil(R / pack).  Rows past R carry the row value
    through unchanged, so the result is byte-identical to pack == 1.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    TCAP = rcap + K
    QIN = rcap if pack == 1 else max(128, _round_up(rcap // pack, 128))

    def kernel(scal_ref, q_ref, t_ref, out_ref, row_scr, tq_scr):
        lane_k = jax.lax.broadcasted_iota(jnp.int32, (1, K), 1)
        R = scal_ref[0, 0, 0]
        S = scal_ref[0, 0, 1]
        dmin = scal_ref[0, 0, 2]

        QW = q_ref.shape[-1]

        def lroll(x, amt, width):
            # left-rotate by a (possibly negative) traced amount;
            # pltpu.roll only accepts non-negative shifts
            return pltpu.roll(x, jnp.mod(width - amt, width), 1)

        def qchar(i):
            # q char at index i: rotate the lane row and read lane 0
            # (static extracts are allowed; dynamic-lane loads are not)
            return lroll(q_ref[0], i, QW)[0, 0]

        def cummin_fwd(x):
            # prefix min along lanes (left-to-right)
            k = 1
            while k < K:
                sh = jnp.where(lane_k >= k, pltpu.roll(x, k, 1), INF)
                x = jnp.minimum(x, sh)
                k *= 2
            return x

        def cummin_bwd(x):
            # suffix min along lanes (right-to-left)
            k = 1
            while k < K:
                sh = jnp.where(lane_k < K - k, pltpu.roll(x, K - k, 1),
                               INF)
                x = jnp.minimum(x, sh)
                k *= 2
            return x

        def fwd_step(i, qc, row):
            # i = 1..R ; j' = i + dmin + o
            jv = i + dmin + lane_k
            # target chars at j'-1 per lane: t[(i-1) + dmin + o],
            # staged via a dynamic lane rotation of the target row
            tc = lroll(tq_scr[:], i - 1 + dmin, TCAP)[:, :K]
            sub = row + jnp.where(tc == qc, 0, 1)
            up = jnp.where(lane_k < K - 1, pltpu.roll(row, K - 1, 1),
                           INF) + 1
            V = jnp.minimum(sub, up)
            V = jnp.where(jv == 0, i, V)
            V = jnp.where((jv < 0) | (jv > S), INF, V)
            gv = lane_k
            nrow = cummin_fwd(V - gv) + gv
            nrow = jnp.minimum(nrow, INF)
            nrow = jnp.where((jv < 0) | (jv > S), INF, nrow)
            return nrow

        def bwd_step(i, qc, row):
            jv = i + dmin + lane_k
            tc = lroll(tq_scr[:], i + dmin, TCAP)[:, :K]  # t[j']
            # B[i][o]: diag = B[i+1][o] + sub(q[i], t[j']);
            # down (consume query) = B[i+1][o-1] + 1;
            # right (consume target) = B[i][o+1] + 1 (suffix chain)
            sub = row + jnp.where(tc == qc, 0, 1)
            down = jnp.where(lane_k >= 1, pltpu.roll(row, 1, 1),
                             INF) + 1
            V = jnp.minimum(sub, down)
            V = jnp.where(jv == S, R - i, V)
            V = jnp.where((jv < 0) | (jv > S), INF, V)
            gv = K - 1 - lane_k
            nrow = cummin_bwd(V - gv) + gv
            nrow = jnp.minimum(nrow, INF)
            nrow = jnp.where((jv < 0) | (jv > S), INF, nrow)
            return nrow

        if not backward:
            # row 0: F[0][j'] = j' for j' in [0, S]
            j0 = dmin + lane_k
            row = jnp.where((j0 >= 0) & (j0 <= S), j0, INF)
            tq_scr[:] = t_ref[0]
            if pack == 1:
                row = jax.lax.fori_loop(
                    1, R + 1, lambda i, row: fwd_step(i, qchar(i - 1), row),
                    row)
            else:
                # one packed-word scalar read feeds `pack` rows; rows
                # past R carry `row` through unchanged (byte-identity)
                def body(it, row):
                    qword = lroll(q_ref[0], it, QW)[0, 0]
                    for p in range(pack):
                        i = it * pack + 1 + p
                        qc = (qword >> (8 * p)) & 0xFF
                        row = jnp.where(i <= R, fwd_step(i, qc, row), row)
                    return row

                row = jax.lax.fori_loop(0, (R + pack - 1) // pack, body,
                                        row)
        else:
            # row R: B[R][j'] = S - j'
            jR = R + dmin + lane_k
            row = jnp.where((jR >= 0) & (jR <= S), S - jR, INF)
            tq_scr[:] = t_ref[0]
            if pack == 1:
                def body1(k, row):
                    i = R - 1 - k          # i = R-1 .. 0
                    return bwd_step(i, qchar(i), row)

                row = jax.lax.fori_loop(0, R, body1, row)
            else:
                # the host packed the REVERSED query slice, so word it /
                # byte p holds q[R - 1 - (it*pack + p)] — the word index
                # ascends with the serial loop
                def body(it, row):
                    qword = lroll(q_ref[0], it, QW)[0, 0]
                    for p in range(pack):
                        k = it * pack + p
                        i = R - 1 - k
                        qc = (qword >> (8 * p)) & 0xFF
                        row = jnp.where(k < R, bwd_step(i, qc, row), row)
                    return row

                row = jax.lax.fori_loop(0, (R + pack - 1) // pack, body,
                                        row)

        out_ref[0] = row

    def make(batch):
        smem3 = pl.BlockSpec((1, 1, 4), lambda b: (b, 0, 0),
                             memory_space=pltpu.SMEM)
        vrow = lambda w: pl.BlockSpec((1, 1, w), lambda b: (b, 0, 0),
                                      memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kernel,
            grid=(batch,),
            in_specs=[smem3, vrow(QIN), vrow(TCAP)],
            out_specs=vrow(K),
            out_shape=jax.ShapeDtypeStruct((batch, 1, K), jnp.int32),
            scratch_shapes=[pltpu.VMEM((1, K), jnp.int32),
                            pltpu.VMEM((1, TCAP), jnp.int32)],
            interpret=interpret,
        )

    def plain(b):
        call = make(b)

        def fn(scal, q, t):
            out = call(scal.reshape(b, 1, 4),
                       q.reshape(b, 1, QIN),
                       t.reshape(b, 1, TCAP))
            return out.reshape(b, K)

        return fn

    @functools.lru_cache(maxsize=8)
    def jitted(batch):
        sharded = _shard_over_mesh(plain, batch, 3, 1)
        return sharded if sharded is not None else jax.jit(plain(batch))

    return jitted


# ---------------------------------------------------------------------------
# base-case kernel: full moves + in-kernel traceback
# ---------------------------------------------------------------------------

@device_keyed_cache(maxsize=32)
def _build_base_kernel(K: int, interpret: bool = False, pack: int = 1):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    RB = BASE_ROWS
    TCAP = RB + K
    OPS = _round_up(RB + K + 2, 128)
    # pack > 1: packed query words (encoding.pack_bases), `pack` DP rows
    # per serial iteration — same contract as _build_edge_kernel
    QCAP = _round_up(RB, 128) if pack == 1 else \
        max(128, _round_up(RB // pack, 128))

    def kernel(scal_ref, q_ref, t_ref, ops_ref, cnt_ref, ok_ref,
               dist_ref, MVS, tq_scr):
        lane_k = jax.lax.broadcasted_iota(jnp.int32, (1, K), 1)
        lane_ops = jax.lax.broadcasted_iota(jnp.int32, (1, OPS), 1)
        R = scal_ref[0, 0, 0]
        S = scal_ref[0, 0, 1]
        dmin = scal_ref[0, 0, 2]

        def load_lane(rowvec, iota, idx):
            return jnp.sum(jnp.where(iota == idx, rowvec,
                                     jnp.zeros_like(rowvec)))

        def cummin_fwd(x):
            k = 1
            while k < K:
                sh = jnp.where(lane_k >= k, pltpu.roll(x, k, 1), INF)
                x = jnp.minimum(x, sh)
                k *= 2
            return x

        tq_scr[:] = t_ref[0]
        j0 = dmin + lane_k
        row0 = jnp.where((j0 >= 0) & (j0 <= S), j0, INF)

        def dp_row(i, qc, row):
            jv = i + dmin + lane_k
            tc = pltpu.roll(tq_scr[:], jnp.mod(TCAP - (i - 1 + dmin), TCAP),
                            1)[:, :K]
            sub = row + jnp.where(tc == qc, 0, 1)
            up = jnp.where(lane_k < K - 1, pltpu.roll(row, K - 1, 1),
                           INF) + 1
            V = jnp.minimum(sub, up)
            mv = jnp.where(V == sub, 0, 1)
            V = jnp.where(jv == 0, i, V)
            mv = jnp.where(jv == 0, 1, mv)
            V = jnp.where((jv < 0) | (jv > S), INF, V)
            nrow = cummin_fwd(V - lane_k) + lane_k
            mv = jnp.where(nrow < V, 2, mv)
            nrow = jnp.where((jv < 0) | (jv > S), INF, nrow)
            return nrow, mv

        QW = q_ref.shape[-1]
        if pack == 1:
            def body(i, row):
                qc = pltpu.roll(q_ref[0], jnp.mod(QW - (i - 1), QW),
                                1)[0, 0]
                nrow, mv = dp_row(i, qc, row)
                MVS[pl.ds(i - 1, 1), :] = mv
                return nrow

            row_fin = jax.lax.fori_loop(1, R + 1, body, row0)
        else:
            def body(it, row):
                qword = pltpu.roll(q_ref[0], jnp.mod(QW - it, QW),
                                   1)[0, 0]
                for p in range(pack):
                    i = it * pack + 1 + p
                    qc = (qword >> (8 * p)) & 0xFF
                    nrow, mv = dp_row(i, qc, row)

                    @pl.when(i <= R)
                    def _():
                        MVS[pl.ds(i - 1, 1), :] = mv

                    row = jnp.where(i <= R, nrow, row)
                return row

            row_fin = jax.lax.fori_loop(0, (R + pack - 1) // pack, body,
                                        row0)

        # terminal distance D = DP[R][S]: lane o with R + dmin + o == S
        # (INF when the terminal cell is out of band).  Free with the
        # final row already live — it is the banded mode's exact
        # Ukkonen-verify input (ops/band.py) for base-case-only pairs.
        o_fin = S - R - dmin
        d_at = load_lane(row_fin, lane_k, jnp.clip(o_fin, 0, K - 1))
        dist_ref[0, 0, 0] = jnp.where((o_fin >= 0) & (o_fin < K),
                                      d_at, INF)

        # traceback from (R, S) to (0, 0); ops: 0=M 1=I(query) 2=D(target)
        def cond(c):
            i, j, cnt, ok = c
            return ((i > 0) | (j > 0)) & (cnt < OPS) & ok

        def bodytb(c):
            i, j, cnt, ok = c
            o = j - i - dmin
            in_band = (o >= 0) & (o < K)
            mvrow = MVS[pl.ds(jnp.maximum(i - 1, 0), 1), :]
            mv_at = load_lane(mvrow, lane_k, jnp.clip(o, 0, K - 1))
            mv = jnp.where(i > 0, jnp.where(in_band, mv_at, 3), 2)
            ok = ok & (mv != 3)
            ops_ref[0] = jnp.where(lane_ops == cnt, mv, ops_ref[0])
            i = jnp.where(mv == 2, i, i - 1)
            j = jnp.where(mv == 1, j, j - 1)
            return (i, j, cnt + 1, ok)

        ops_ref[0] = jnp.zeros((1, OPS), jnp.int32)
        i, j, cnt, ok = jax.lax.while_loop(
            cond, bodytb, (R, S, jnp.int32(0), jnp.bool_(True)))
        ok = ok & (i == 0) & (j == 0)
        cnt_ref[0, 0, 0] = cnt
        ok_ref[0, 0, 0] = ok.astype(jnp.int32)

    def make(batch):
        smem3 = pl.BlockSpec((1, 1, 4), lambda b: (b, 0, 0),
                             memory_space=pltpu.SMEM)
        smem1 = pl.BlockSpec((1, 1, 1), lambda b: (b, 0, 0),
                             memory_space=pltpu.SMEM)
        vrow = lambda w: pl.BlockSpec((1, 1, w), lambda b: (b, 0, 0),
                                      memory_space=pltpu.VMEM)
        return pl.pallas_call(
            kernel,
            grid=(batch,),
            in_specs=[smem3, vrow(QCAP), vrow(TCAP)],
            out_specs=[vrow(OPS), smem1, smem1, smem1],
            out_shape=[
                jax.ShapeDtypeStruct((batch, 1, OPS), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1, 1), jnp.int32),
                jax.ShapeDtypeStruct((batch, 1, 1), jnp.int32),
            ],
            scratch_shapes=[pltpu.VMEM((RB, K), jnp.int32),
                            pltpu.VMEM((1, TCAP), jnp.int32)],
            interpret=interpret,
        )

    def plain(b):
        call = make(b)

        def fn(scal, q, t):
            ops, cnt, ok, dist = call(scal.reshape(b, 1, 4),
                                      q.reshape(b, 1, QCAP),
                                      t.reshape(b, 1, TCAP))
            return (ops.reshape(b, OPS), cnt.reshape(b), ok.reshape(b),
                    dist.reshape(b))

        return fn

    @functools.lru_cache(maxsize=8)
    def jitted(batch):
        sharded = _shard_over_mesh(plain, batch, 3, 4)
        return sharded if sharded is not None else jax.jit(plain(batch))

    return jitted, OPS, QCAP, TCAP


# ---------------------------------------------------------------------------
# host orchestrator
# ---------------------------------------------------------------------------

class _Task:
    __slots__ = ("pair", "ia", "ib", "ja", "jb")

    def __init__(self, pair, ia, ib, ja, jb):
        self.pair, self.ia, self.ib, self.ja, self.jb = pair, ia, ib, ja, jb


def _interpret() -> bool:
    import jax as _jax
    return _jax.devices()[0].platform != "tpu"


def align_pairs(pairs, *, interpret=None, band_overrides=None, hits=None):
    """pairs: [(q_codes int32 np, t_codes int32 np)] -> [ops np | None].

    ops are forward-ordered codes (0=M, 1=I, 2=D); None = host fallback
    (band escape / oversize).

    band_overrides: {pair index: K} runs those pairs under the given
    band (narrower than the flat ``band_for`` bucket) with the exact
    Ukkonen in-band verify (ops/band.py): the terminal distance must
    certify that every optimal AND co-optimal path lies strictly inside
    the band — then midpoints, tie-breaks and traceback coincide with
    the flat kernel's and the result is byte-identical.  A pair whose
    certificate fails is aborted at its first round (no wasted
    recursion), gets result None, and its index is added to `hits` for
    the caller's verify-and-widen ladder.
    """
    if interpret is None:
        interpret = _interpret()
    results = [None] * len(pairs)
    segments = {}   # pair index -> list of (ia, ops array)
    bands = {}
    verify = {}     # pair index -> (n, m, K, gdmin) for banded pairs
    active = []
    for idx, (q, t) in enumerate(pairs):
        n, m = len(q), len(t)
        K = band_for(n, m)
        if K == 0 or n == 0 or m == 0 or (n + 1) // 2 > ROW_BUCKETS[-1]:
            continue
        kb = band_overrides.get(idx) if band_overrides else None
        if kb is not None and kb < K:
            K = int(kb)
        else:
            kb = None
        gdmin = int(np.minimum(0, m - n) - (K - 1 - abs(m - n)) // 2)
        bands[idx] = (K, gdmin)
        if kb is not None:
            verify[idx] = (n, m, K, gdmin)
        segments[idx] = []
        active.append(_Task(idx, 0, n, 0, m))

    failed = set()
    while True:
        big = [t for t in active if (t.ib - t.ia) > BASE_ROWS
               and t.pair not in failed]
        if not big:
            break
        active = [t for t in active if (t.ib - t.ia) <= BASE_ROWS]
        new_tasks = _split_round(pairs, big, bands, failed, interpret,
                                 verify)
        active.extend(new_tasks)

    # base cases
    base = [t for t in active if t.pair not in failed]
    _solve_base(pairs, base, bands, segments, failed, interpret, verify)

    for idx, segs in segments.items():
        if idx in failed:
            continue
        segs.sort(key=lambda s: s[0])
        results[idx] = np.concatenate([s[1] for s in segs]) if segs else \
            np.zeros(0, np.int32)
    if hits is not None and verify:
        # any banded-pair failure is a band hit: a verified-clean banded
        # pair cannot fail mid-recursion (certificate covers co-optima)
        hits.update(idx for idx in failed if idx in verify)
    return results


def _pow2(n):
    b = 1
    while b < n:
        b *= 2
    return b


def _task_arrays(pairs, tasks, bands, rcap, K, backward, pack=1):
    """Pack tasks into kernel arrays. The staged target window is clipped
    to the half's band-reachable columns (j <= ib + gdmin + K going
    forward, j >= ia + gdmin going backward) so it fits rcap + K — the
    full task span can be up to 2*rcap + K.

    pack > 1: queries go out as packed words (the backward kernel's
    query slice reversed first, so its word index ascends with the
    serial loop)."""
    B = len(tasks)
    TCAP = rcap + K
    scal = np.zeros((B, 4), np.int32)
    qs = np.zeros((B, rcap), np.int32)
    ts = np.full((B, TCAP), 255, np.int32)
    for bi, t in enumerate(tasks):
        q, tt = pairs[t.pair]
        _, gdmin = bands[t.pair]
        R = t.ib - t.ia
        if backward:
            j_lo = max(t.ja, t.ia + gdmin)
            j_hi = t.jb
        else:
            j_lo = t.ja
            j_hi = min(t.jb, t.ib + gdmin + K)
        S = j_hi - j_lo
        assert 0 <= S <= TCAP, (S, TCAP)
        scal[bi] = (R, S, gdmin + t.ia - j_lo, 0)
        qrow = q[t.ia:t.ib]
        qs[bi, :R] = qrow[::-1] if (pack > 1 and backward) else qrow
        ts[bi, :S] = tt[j_lo:j_hi]
    if pack > 1:
        qs = pack_bases(qs, width=max(128, _round_up(rcap // pack, 128)))
    return scal, qs, ts


def _split_round(pairs, tasks, bands, failed, interpret, verify=None):
    """One Hirschberg round: split every oversized task at its midpoint."""
    out = []
    by_bucket = {}
    for t in tasks:
        K = bands[t.pair][0]
        R = t.ib - t.ia
        half = (R + 1) // 2
        rcap = next(rb for rb in ROW_BUCKETS if half <= rb)
        by_bucket.setdefault((rcap, K), []).append(t)

    pk = _pack_factor()
    for (rcap, K), group in sorted(by_bucket.items()):
        fwd = _build_edge_kernel(rcap, K, False, interpret, pk)
        bwd = _build_edge_kernel(rcap, K, True, interpret, pk)
        # forward over [ia, imid], backward over [imid, ib]
        f_tasks, b_tasks = [], []
        for t in group:
            imid = (t.ia + t.ib) // 2
            f_tasks.append(_Task(t.pair, t.ia, imid, t.ja, t.jb))
            b_tasks.append(_Task(t.pair, imid, t.ib, t.ja, t.jb))
        fs, fq, ft = _task_arrays(pairs, f_tasks, bands, rcap, K, False, pk)
        bs, bq, bt = _task_arrays(pairs, b_tasks, bands, rcap, K, True, pk)
        # pad the batch dim to a power of two so each (rcap, K) bucket
        # compiles a handful of kernel variants, not one per group size
        B = _pow2(len(group))
        m = _dispatch_shards(B)
        if m > 1:
            from .batch_exec import count_shard_rows

            count_shard_rows(len(group), B, m)  # forward launch
            count_shard_rows(len(group), B, m)  # backward launch
        pad = lambda a: np.concatenate(
            [a, np.repeat(a[-1:], B - len(group), axis=0)]) \
            if B > len(group) else a
        F = np.asarray(fwd(B)(pad(fs), pad(fq), pad(ft)))[:len(group)]
        Bv = np.asarray(bwd(B)(pad(bs), pad(bq), pad(bt)))[:len(group)]
        for gi, t in enumerate(group):
            imid = (t.ia + t.ib) // 2
            K_, gdmin = bands[t.pair]
            # Both midpoint rows map lane o to absolute column
            # j = imid + gdmin + o (independent of each frame's clipped
            # origin); overlay onto the task's column range rel. ja.
            jmid = imid + gdmin - t.ja + np.arange(K_)
            span = t.jb - t.ja
            fv = np.full(span + 1, INF, np.int64)
            bv = np.full(span + 1, INF, np.int64)
            m = (jmid >= 0) & (jmid <= span)
            fv[jmid[m]] = F[gi][m]
            bv[jmid[m]] = Bv[gi][m]
            tot = fv + bv
            jstar = int(np.argmin(tot))
            if tot[jstar] >= INF:
                failed.add(t.pair)
                continue
            v = verify.get(t.pair) if verify else None
            if (v is not None and t.ia == 0 and t.ib == v[0]
                    and t.ja == 0 and t.jb == v[1]):
                # root task of a banded pair: tot[jstar] IS the global
                # edit distance (every path crosses the midpoint row),
                # so check the exact Ukkonen certificate here and abort
                # the whole pair before recursing on an unproven band
                if not _band.ukkonen_ok(v[0], v[1], v[2], v[3],
                                        int(tot[jstar])):
                    failed.add(t.pair)
                    continue
            jabs = t.ja + jstar
            out.append(_Task(t.pair, t.ia, imid, t.ja, jabs))
            out.append(_Task(t.pair, imid, t.ib, jabs, t.jb))
    return out


def _solve_base(pairs, tasks, bands, segments, failed, interpret,
                verify=None):
    by_bucket = {}
    for t in tasks:
        K = bands[t.pair][0]
        by_bucket.setdefault(K, []).append(t)
    pk = _pack_factor()
    for K, group in sorted(by_bucket.items()):
        kern, OPS, QCAP, TCAP = _build_base_kernel(K, interpret, pk)
        for off in range(0, len(group), 64):
            chunk = group[off:off + 64]
            B = _pow2(len(chunk))
            m = _dispatch_shards(B)
            if m > 1:
                from .batch_exec import count_shard_rows

                count_shard_rows(len(chunk), B, m)
            scal = np.zeros((B, 4), np.int32)
            qraw = np.zeros((B, BASE_ROWS), np.int32)
            ts = np.full((B, TCAP), 255, np.int32)
            for bi, t in enumerate(chunk):
                q, tt = pairs[t.pair]
                _, gdmin = bands[t.pair]
                R, S = t.ib - t.ia, t.jb - t.ja
                scal[bi] = (R, S, gdmin + t.ia - t.ja, 0)
                qraw[bi, :R] = q[t.ia:t.ib]
                ts[bi, :S] = tt[t.ja:t.jb]
            scal[len(chunk):, 0] = 1  # pad tasks: 1 empty-target row
            if pk > 1:
                qs = pack_bases(qraw, width=QCAP)
            else:
                # QCAP == _round_up(BASE_ROWS, 128) == BASE_ROWS here
                qs = qraw
            ops, cnt, ok, dist = (np.asarray(x)
                                  for x in kern(B)(scal, qs, ts))
            for bi, t in enumerate(chunk):
                v = verify.get(t.pair) if verify else None
                if (v is not None and t.ia == 0 and t.ib == v[0]
                        and t.ja == 0 and t.jb == v[1]):
                    # base-case-only banded pair: the kernel's terminal
                    # distance carries the exact Ukkonen certificate
                    if (not ok[bi]
                            or not _band.ukkonen_ok(v[0], v[1], v[2],
                                                    v[3], int(dist[bi]))):
                        failed.add(t.pair)
                        continue
                if not ok[bi]:
                    failed.add(t.pair)
                    continue
                seg = ops[bi, :cnt[bi]][::-1].astype(np.int32)
                segments[t.pair].append((t.ia, seg))


from .align import ops_to_cigar  # same 0=M/1=I/2=D convention


def cohort_size(default: int = 64) -> int:
    """Jobs materialized per device cohort (RACON_TPU_ALIGN_COHORT)."""
    env = config.get_raw("RACON_TPU_ALIGN_COHORT")
    return max(1, int(env if env is not None else default))


class _HirschbergOps:
    """Executor hooks (ops/batch_exec.py) for the Hirschberg engine.

    The engine is host-orchestrated (align_pairs launches rounds of
    kernel batches itself), so there is nothing to async-dispatch: each
    cohort resolves inline through the lattice (`async_dispatch = False`)
    — bounded retry, bisection-quarantine of a poisoned job, and tier
    death to host all behave exactly as the pre-executor loop did.

    Single-copy packing: `pack` encodes each job once into two
    preallocated padded row buffers; the per-job views are what lattice
    retries and bisection probes reuse (the old loop re-materialized
    every pair per attempt with a per-job Python loop)."""

    span_name = "align.cohort"
    async_dispatch = False

    def __init__(self, pipeline, dims, report, stats, state):
        self.pipeline = pipeline
        self.dims = dims          # job -> (n, m) from the bulk lengths
        self.report = report
        self.stats = stats
        self.state = state        # {"served": int}
        self.pairs = {}           # job -> (q_view, t_view), packed once
        self.band = {}            # job -> band.BandState (banded jobs)
        self.dead = False

    def live_tier(self, ctx, kind):
        return "host" if self.dead else "hirschberg"

    def export(self, ctx, group):
        return list(group)

    def pack(self, ctx, chunk):
        qcap = max(1, max(self.dims[j][0] for j in chunk))
        tcap = max(1, max(self.dims[j][1] for j in chunk))
        qbuf = np.zeros((len(chunk), qcap), dtype=np.int32)
        tbuf = np.zeros((len(chunk), tcap), dtype=np.int32)
        for bi, job in enumerate(chunk):
            qa, ta = self.pipeline.align_job(job)
            if len(qa) <= qcap and len(ta) <= tcap:
                qbuf[bi, :len(qa)] = encode(qa)
                tbuf[bi, :len(ta)] = encode(ta)
                self.pairs[job] = (qbuf[bi, :len(qa)], tbuf[bi, :len(ta)])
            else:
                # lengths-table mismatch (duck-typed pipeline): fall back
                # to a standalone copy for just this job
                self.pairs[job] = (encode(qa).astype(np.int32),
                                   encode(ta).astype(np.int32))
        return None

    def attempt(self, ctx, kind, sub):
        from ..resilience import faults

        faults.check("align.run", sub)
        plist = [self.pairs[j] for j in sub]
        overrides = {}
        for bi, j in enumerate(sub):
            st = self.band.get(j)
            if st is not None and st.k is not None:
                overrides[bi] = st.k
        if not overrides:
            return align_pairs(plist)
        forced = False
        try:
            # the deterministic widening-exhaustion drill: an armed
            # band.hit fault turns every banded job of this attempt
            # into a hit, driving the ladder to its flat floor
            faults.check("band.hit", sub)
        except faults.InjectedFault:
            forced = True
        hits = set()
        res = align_pairs(plist, band_overrides=overrides, hits=hits)
        if forced:
            hits.update(overrides)
        # attempt stays pure (lattice retries/bisection re-call it);
        # hit classification and ladder advance happen in install()
        return [_band.HIT if bi in hits else res[bi]
                for bi in range(len(sub))]

    def span_args(self, ctx, chunk, pipelined):
        return {"jobs": len(chunk)}

    def install(self, ctx, kind, sub, results):
        from ..resilience import faults

        for job, ops in zip(sub, results):
            if isinstance(ops, _band.Hit):
                # banded verify failed: advance this job's widening
                # ladder; the executor's widen() loop re-attempts it
                st = self.band.get(job)
                if st is not None:
                    n, m = self.dims[job]
                    st.widen(n, m, band_for(n, m), self.report,
                             tier=kind or "hirschberg",
                             cells_counter="align.cells.banded")
                continue
            if ops is None:
                continue  # band escape: host aligns it
            st = self.band.get(job)
            if st is not None:
                st.pending = False
            faults.check("align.install", (job,))
            self.pipeline.set_job_cigar(job, ops_to_cigar(ops))
            self.state["served"] += 1
            if self.stats is not None:
                self.stats["device"] = self.stats.get("device", 0) + 1
            if self.report is not None:
                self.report.record_served("hirschberg")

    def surrender(self, ctx, items, exported):
        pass  # CIGAR-less jobs fall to the native host pass

    def quarantine(self, ctx, job, exc):
        if self.report is not None:
            self.report.record_quarantine(job, exc)

    def demote(self, ctx, kind, cause):
        import sys

        self.dead = True
        print(f"[racon_tpu::align] WARNING: hirschberg engine failed "
              f"({type(cause).__name__}: {cause}); remaining jobs fall "
              f"back to the host aligner", file=sys.stderr)
        if self.report is not None:
            self.report.record_degrade("hirschberg", "host", cause)
        return "host"

    def widen(self, ctx, kind):
        """Band-hit jobs of the current chunk awaiting a widened
        re-attempt (executor verify-and-widen seam).  Clearing `pending`
        here makes the ladder drain: a re-attempt either installs (flat
        floor included — exhausted jobs re-run with no override) or hits
        again, re-arming `pending` one rung higher."""
        retry = [j for j in self.pairs
                 if (st := self.band.get(j)) is not None and st.pending]
        for j in retry:
            self.band[j].pending = False
        return retry

    def done(self, ctx, chunk):
        # keep host memory O(cohort): packed views die with the chunk
        for job in chunk:
            self.pairs.pop(job, None)
            self.band.pop(job, None)

    # -- sharded dispatch (optional executor hook) -------------------------
    def demote_shard(self, ctx, kind, cause):
        # A cohort died while its round kernels could have been sharded:
        # drop the partitioner to single-device, flush the builder
        # caches (the batch-keyed jitted closures baked in shard_map
        # wraps), and retry the SAME tier locally before any tier
        # demotion — the sharded -> single-device lattice edge.
        from ..parallel.partitioner import get_partitioner
        from ..resilience import lattice as rl

        part = get_partitioner()
        if (part.disabled is not None or part.batch_axis_size <= 1
                or config.get_raw("RACON_TPU_SHARD") == "0"):
            return False
        if part.demote(f"{type(cause).__name__}: {cause}"):
            rl.record_shard_demotion(self.report, kind, cause)
        _build_edge_kernel.cache_clear()
        _build_base_kernel.cache_clear()
        return True


def run_jobs(pipeline, jobs, cohort: int = None, report=None,
             stats=None, lengths=None) -> int:
    """Align pipeline jobs with the Hirschberg engine; install CIGARs.
    Returns how many the device served (band escapes fall to host).
    Jobs are packed per cohort (single copy into padded buffers) so host
    memory stays O(cohort), not O(total bases).

    Cohorts are length-bucketed by (band, first-round row bucket) so a
    cohort launches geometry-homogeneous kernel batches — one long pair
    no longer drags a cohort of short pairs through its row splits.

    `lengths` is the bulk job-lengths array (the driver fetches it once
    and threads it through); without it, one bulk FFI fetch happens here.

    Each cohort runs through the degradation lattice via the shared
    executor: bounded retry, then bisection (a poisoned job is
    quarantined to the host while the rest of the cohort stays on the
    device).  A cohort-independent failure stops the engine and leaves
    the remaining jobs CIGAR-less for the host — the served count stays
    accurate for the cohorts already installed, whatever point the
    engine died at.  ``stats['device']`` (when the driver passes its
    accounting dict) is incremented per install, so even an exception
    escaping this function cannot erase already-installed work from the
    driver's device count."""
    import sys

    from ..resilience import lattice as rl
    from .. import obs
    from .batch_exec import BatchExecutor

    if cohort is None:
        cohort = cohort_size()
    if lengths is None and hasattr(pipeline, "align_job_lengths"):
        lengths = pipeline.align_job_lengths()
    if lengths is not None:
        dims = {j: (int(lengths[j, 0]), int(lengths[j, 1])) for j in jobs}
    else:  # duck-typed pipelines without the lengths table
        dims = {}
        for job in jobs:
            qa, ta = pipeline.align_job(job)
            dims[job] = (len(qa), len(ta))

    # Length buckets: band x the first split round's row bucket — the
    # geometry key align_pairs' rounds compile under.  With banded DP on
    # (RACON_TPU_BAND), a job whose Ukkonen band plan beats its flat
    # bucket starts on the narrow band instead (verify-and-widen makes
    # that safe), and the bucket key uses the banded K so cohorts stay
    # geometry-homogeneous.
    banded_on = _band.enabled()
    band_states = {}
    buckets = {}
    for job in jobs:
        n, m = dims[job]
        K = band_for(n, m)
        kb = _band.plan_align_band(n, m, K) if banded_on and K else None
        if kb is not None:
            band_states[job] = _band.BandState(kb)
        half = (max(n, 1) + 1) // 2
        rcap = next((rb for rb in ROW_BUCKETS if half <= rb), 0)
        buckets.setdefault((kb if kb is not None else K, rcap),
                           []).append(job)
    if band_states:
        obs.count("band.jobs", len(band_states))

    state = {"served": 0}
    ops_obj = _HirschbergOps(pipeline, dims, report, stats, state)
    ops_obj.band = band_states
    executor = BatchExecutor(ops_obj, report=report)
    try:
        for (K, rcap), items in sorted(buckets.items()):
            for off in range(0, len(items), cohort):
                group = items[off:off + cohort]
                if obs.enabled():
                    # Measured-cell counter for the cost model
                    # (obs/costmodel.py): forward+backward distance
                    # passes over the recursion tree ~ 2x the base
                    # max(n,m) x band DP.  align.cells.hirschberg stays
                    # the flat-band count; align.cells.banded is what
                    # the banded plan actually iterates, so the ratio of
                    # the two is the measured cell cut.
                    obs.count("align.cells.hirschberg", sum(
                        2 * max(dims[j][0], dims[j][1])
                        * band_for(dims[j][0], dims[j][1])
                        for j in group))
                    bj = [j for j in group if j in band_states]
                    if bj:
                        obs.count("align.cells.banded", sum(
                            2 * max(dims[j][0], dims[j][1])
                            * band_states[j].k for j in bj))
                executor.submit(None, group)
        executor.flush()
    except Exception as e:  # noqa: BLE001 — lattice boundary
        cause = e.cause if isinstance(e, rl.TierDead) else e
        print(f"[racon_tpu::align] WARNING: hirschberg engine failed "
              f"({type(cause).__name__}: {cause}); remaining jobs fall "
              f"back to the host aligner", file=sys.stderr)
        if report is not None:
            report.record_degrade("hirschberg", "host", cause)
    if report is not None:
        executor.stamp_walls(report)
    return state["served"]
