"""Topology-keyed memoization for kernel builders.

A plain ``functools.lru_cache`` on a kernel builder is a latent bug: the
built object bakes in the device set (sharding meshes, interpret-mode
decisions), so reconfiguring JAX devices after a first build would serve
a stale sharded/interpreted kernel (the round-5 ADVICE finding on
``_build_kernel_cached``).  ``device_keyed_cache`` is the sanctioned
replacement: it appends ``(len(jax.devices()), platform)`` to the cache
key implicitly, keeping builder signatures unchanged.

The ``kernel-cache-key`` lint rule (racon_tpu/analysis) enforces that
every cached kernel builder either uses this decorator or takes explicit
``n_dev`` + ``platform`` parameters.
"""

from __future__ import annotations

import functools
import time

from .. import fingerprint, obs


def device_keyed_cache(maxsize: int = 64):
    """`functools.lru_cache` whose key implicitly includes the device
    topology (device count + platform) at call time.

    Exposes ``cache_clear`` / ``cache_info`` like lru_cache.  jax is
    imported lazily at first call so decorated builders stay importable
    before any backend configuration (e.g. the test suite's forced CPU
    mesh)."""
    def deco(build):
        @functools.lru_cache(maxsize=maxsize)
        def cached(_n_dev, _platform, *args, **kwargs):
            return build(*args, **kwargs)

        @functools.wraps(build)
        def wrapper(*args, **kwargs):
            import jax

            devs = jax.devices()
            # Kernel-(re)build observability: a cache miss here is the
            # builder running (tracing + staging; the XLA compile proper
            # lands in the first submit span).  The miss is only known
            # after the call, so the span is stamped retroactively from
            # monotonic stamps taken around it.
            misses0 = cached.cache_info().misses
            t0 = time.monotonic_ns()
            # the implicit topology prefix is the `kernel_cache` site of
            # the unified fingerprint registry (racon_tpu/fingerprint.py)
            topo = fingerprint.kernel_cache_key(len(devs),
                                                devs[0].platform)
            built = cached(*topo, *args, **kwargs)
            if cached.cache_info().misses != misses0:
                # shape/cost extraction for the analytic cost model:
                # the predicted per-unit bill rides in the same span as
                # the measured build wall (obs/costmodel.py)
                from . import cost_hooks

                pred = cost_hooks.record_build(build.__name__, args,
                                               kwargs)
                obs.add_complete("kernel.build", t0, time.monotonic_ns(),
                                 builder=build.__name__,
                                 platform=devs[0].platform, **pred)
                obs.count(f"kernel.builds.{build.__name__}")
            # Opt-in runtime sanitizer (RACON_TPU_SANITIZE=1): hand the
            # built kernel back wrapped in a checking proxy. Imported
            # lazily at call time — by the first kernel build the
            # analysis package is safe to import, while a module-level
            # import here would run analysis/__init__ during ops import.
            from ..analysis import sanitize

            if sanitize.enabled():
                return sanitize.wrap_kernel(build.__name__, built)
            return built

        wrapper.cache_clear = cached.cache_clear
        wrapper.cache_info = cached.cache_info
        wrapper.__wrapped__ = build
        return wrapper
    return deco
