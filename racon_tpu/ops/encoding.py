"""Base encoding shared by the device kernels.

ASCII bases map to codes A=0, C=1, G=2, T=3; every other character
(N, IUPAC ambiguity codes, '-') collapses to 4. Divergence from the host
path: the host compares raw characters, so two distinct ambiguity codes
mismatch there but compare equal (4==4) on device — irrelevant for ACGT data
and pinned separately in the golden tests, the same way the reference pins
its CUDA deltas (/root/reference/test/racon_test.cpp:297-507).
"""

import numpy as np

_LUT = np.full(256, 4, dtype=np.uint8)
for i, c in enumerate(b"ACGT"):
    _LUT[c] = i
# lowercase never reaches the kernels (Sequence uppercases on parse), but be
# safe for direct-API users
for i, c in enumerate(b"acgt"):
    _LUT[c] = i

_DECODE = np.frombuffer(b"ACGTN", dtype=np.uint8)


def encode(ascii_bases: np.ndarray) -> np.ndarray:
    """uint8 ASCII -> uint8 codes 0..4."""
    return _LUT[ascii_bases]


def decode(codes: np.ndarray) -> bytes:
    """uint8/int codes 0..4 -> ASCII bytes."""
    return _DECODE[np.asarray(codes, dtype=np.int64).clip(0, 4)].tobytes()


#: Codes packed per int32 word by pack_bases (one byte per code).  A 2-bit
#: packing (4 codes per BYTE) would be denser but cannot represent code 4
#: (N / ambiguity) without collapsing it into a real base — which would
#: break byte-identity against the host on non-ACGT input — so the packed
#: DP kernels trade density for losslessness: 4 codes per 32-bit word,
#: one byte each, little-endian byte order.
PACK = 4


def pack_bases(codes: np.ndarray, width: int = 0) -> np.ndarray:
    """Pack codes 0..4 along the last axis, PACK per int32 word.

    Word w holds codes [PACK*w, PACK*w + PACK); code p sits in byte p
    (value << 8*p).  The tail word is zero-padded.  `width` pads the
    packed axis out to a fixed lane count (0 = minimal).  Round-trips
    exactly through unpack_bases for any values 0..255.
    """
    a = np.asarray(codes, dtype=np.int64)
    n = a.shape[-1]
    nw = (n + PACK - 1) // PACK
    w = max(width, nw)
    padded = np.zeros(a.shape[:-1] + (w * PACK,), dtype=np.int64)
    padded[..., :n] = a
    parts = padded.reshape(a.shape[:-1] + (w, PACK))
    shifts = (np.arange(PACK, dtype=np.int64) * 8)
    return np.sum(parts << shifts, axis=-1).astype(np.int32)


def unpack_bases(words: np.ndarray, n: int) -> np.ndarray:
    """Inverse of pack_bases: the first n codes along the last axis."""
    w = np.asarray(words, dtype=np.int64)
    shifts = (np.arange(PACK, dtype=np.int64) * 8)
    codes = (w[..., None] >> shifts) & 0xFF
    return codes.reshape(w.shape[:-1] + (-1,))[..., :n].astype(np.int32)
