"""Base encoding shared by the device kernels.

ASCII bases map to codes A=0, C=1, G=2, T=3; every other character
(N, IUPAC ambiguity codes, '-') collapses to 4. Divergence from the host
path: the host compares raw characters, so two distinct ambiguity codes
mismatch there but compare equal (4==4) on device — irrelevant for ACGT data
and pinned separately in the golden tests, the same way the reference pins
its CUDA deltas (/root/reference/test/racon_test.cpp:297-507).
"""

import numpy as np

_LUT = np.full(256, 4, dtype=np.uint8)
for i, c in enumerate(b"ACGT"):
    _LUT[c] = i
# lowercase never reaches the kernels (Sequence uppercases on parse), but be
# safe for direct-API users
for i, c in enumerate(b"acgt"):
    _LUT[c] = i

_DECODE = np.frombuffer(b"ACGTN", dtype=np.uint8)


def encode(ascii_bases: np.ndarray) -> np.ndarray:
    """uint8 ASCII -> uint8 codes 0..4."""
    return _LUT[ascii_bases]


def decode(codes: np.ndarray) -> bytes:
    """uint8/int codes 0..4 -> ASCII bytes."""
    return _DECODE[np.asarray(codes, dtype=np.int64).clip(0, 4)].tobytes()
